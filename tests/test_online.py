"""Continuous learning (ISSUE 7): delta codec, publish protocol,
train-while-serve.

The acceptance bar: the model served after the cut at step T is
BIT-exact with an offline ``sgd_fit_outofcore`` over all WAL windows
<= T, and steady-state delta publishes trigger zero new XLA lowerings
(the publish is a device-resident buffer swap into already-compiled
bucketed executors — no reload, no warm-up).  The crashy half of the
story lives in tests/test_faults.py.
"""

import os

import numpy as np
import pytest

from flink_ml_tpu import Table
from flink_ml_tpu.data.wal import WindowBatchReader, WindowLog
from flink_ml_tpu.iteration import (
    CheckpointConfig,
    IterationBodyResult,
    IterationConfig,
    iterate,
)
from flink_ml_tpu.models.classification.logisticregression import (
    LogisticRegression,
)
from flink_ml_tpu.models.common.losses import logistic_loss
from flink_ml_tpu.models.common.sgd import SGDConfig, sgd_fit_outofcore
from flink_ml_tpu.online import (
    ContinuousLearner,
    DeltaBaseMismatch,
    DeltaCorrupt,
    DeltaEncoder,
    DeltaPublisher,
    DeltaShapeChanged,
    DeterminismViolation,
    FullUpdate,
    ParamDelta,
    PublishingListener,
    StalenessPolicy,
    apply_delta,
    diff_params,
    flatten_params,
    params_of_model,
    tree_digest,
)
from flink_ml_tpu.serving import ModelRegistry, ServingEndpoint, serve_model


# -- delta codec -------------------------------------------------------------

def test_delta_sparse_roundtrip_bitexact():
    base = {"w": np.arange(64, dtype=np.float32), "b": np.float32(0.5)}
    new = {"w": base["w"].copy(), "b": np.float32(0.5)}
    new["w"][3] = 7.5
    new["w"][41] = -2.0
    d = diff_params(base, new, step=5)
    assert d.changed_leaves == ["w"]
    assert d.leaves["w"].idx is not None          # sparse encode
    assert d.payload_bytes == 2 * (8 + 4)         # int64 idx + f32 val
    out = apply_delta(base, d)
    flat_new = flatten_params(new)
    assert all(out[k].tobytes() == flat_new[k].tobytes() for k in flat_new)


def test_delta_dense_leaf_ships_full_buffer():
    base = {"w": np.zeros(32, np.float32)}
    new = {"w": np.ones(32, np.float32)}           # 100% changed
    d = diff_params(base, new)
    assert d.leaves["w"].idx is None
    assert d.payload_bytes == 32 * 4
    out = apply_delta(base, d)
    assert out["w"].tobytes() == new["w"].tobytes()


def test_delta_bitexact_nan_and_signed_zero():
    """Raw-byte change detection: NaN payloads round-trip (a value
    compare would mark them changed forever), and +0.0 -> -0.0 is a
    REAL change the codec must carry."""
    base = {"w": np.array([0.0, 1.0, np.nan, 3.0], np.float32)}
    new = {"w": np.array([-0.0, 1.0, np.nan, 3.0], np.float32)}
    d = diff_params(base, new)
    assert d.leaves["w"].idx.tolist() == [0]      # only the zero flip
    out = apply_delta(base, d)
    assert out["w"].tobytes() == new["w"].tobytes()
    # identical trees (NaN included) encode as the empty delta
    d2 = diff_params(new, {"w": new["w"].copy()})
    assert d2.changed_leaves == []


def test_delta_nested_pytree_and_scalar_shapes():
    base = {"mlp": [{"w": np.ones((4, 2), np.float32),
                     "b": np.zeros(2, np.float32)}],
            "bias": np.float32(1.0)}
    new = {"mlp": [{"w": base["mlp"][0]["w"] * 2,
                    "b": base["mlp"][0]["b"]}],
           "bias": np.float32(2.0)}
    out = apply_delta(base, diff_params(base, new))
    assert out["bias"].shape == ()                # 0-d preserved
    assert out["mlp/0/w"].shape == (4, 2)
    from flink_ml_tpu.online import unflatten_params

    tree = unflatten_params(base, out)
    assert np.asarray(tree["mlp"][0]["w"]).tobytes() \
        == new["mlp"][0]["w"].tobytes()


def test_delta_base_mismatch_and_corrupt_detected():
    base = {"w": np.zeros(8, np.float32)}
    new = {"w": np.ones(8, np.float32)}
    d = diff_params(base, new)
    with pytest.raises(DeltaBaseMismatch):
        apply_delta({"w": np.full(8, 2.0, np.float32)}, d)
    torn = ParamDelta(step=d.step, base_digest=d.base_digest,
                      new_digest=d.new_digest ^ 1, leaves=d.leaves)
    with pytest.raises(DeltaCorrupt):
        apply_delta(base, torn)


def test_delta_shape_change_raises():
    base = {"w": np.zeros(8, np.float32)}
    with pytest.raises(DeltaShapeChanged):
        diff_params(base, {"w": np.zeros(9, np.float32)})
    with pytest.raises(DeltaShapeChanged):
        diff_params(base, {"w": np.zeros(8, np.float64)})
    with pytest.raises(DeltaShapeChanged):
        diff_params(base, {"v": np.zeros(8, np.float32)})


# -- serving-side publish protocol -------------------------------------------

def _lr_table(n=64, d=8, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d))
    y = (X[:, 0] + 0.3 * rng.normal(size=n) > 0).astype(np.int64)
    return Table({"features": X, "label": y})


def _served_w(endpoint, name="default"):
    model = endpoint.registry.current(name).servable.model
    return np.asarray(model._state.coefficients, np.float32)


def _publish_chain(endpoint, steps):
    """Publish a chain of nudged params; returns the final params."""
    pub = endpoint.delta_publisher()
    enc = DeltaEncoder()
    p = params_of_model(endpoint.registry.current("default").servable.model)
    for step in steps:
        p = {"w": p["w"].copy(), "b": p["b"]}
        p["w"][step % p["w"].size] += np.float32(0.125)
        pub.apply(enc.encode(step, p, pub.stats))
        enc.ack()
    return pub, enc, p


def test_publish_swaps_generation_and_serves_published_bits():
    model = LogisticRegression().set_max_iter(3).fit(_lr_table())
    feats = _lr_table(seed=5).drop("label")
    endpoint = serve_model(model, feats.take(2), max_batch_rows=32,
                           max_wait_ms=0.5)
    try:
        gen0 = endpoint.registry.current("default").generation
        pub, enc, p = _publish_chain(endpoint, [1, 2, 3])
        assert endpoint.registry.current("default").generation == gen0 + 3
        assert _served_w(endpoint).tobytes() == p["w"].tobytes()
        out = endpoint.predict(feats.take(4))
        assert "prediction" in out.column_names
        # second publish was an incremental delta (one slot changed)
        assert pub.stats.deltas >= 1
    finally:
        endpoint.close()


def test_publish_zero_new_lowerings_steady_state():
    """THE tentpole property: after warm-up, a publish+serve cycle
    compiles NOTHING — same-shape generations hit the already-compiled
    bucketed executors (params are runtime args in the serving jit
    cache), so the swap is a device-resident buffer move."""
    from jax._src import test_util as jtu

    model = LogisticRegression().set_max_iter(3).fit(_lr_table())
    feats = _lr_table(seed=5).drop("label")
    endpoint = serve_model(model, feats.take(2), max_batch_rows=64,
                           max_wait_ms=0.5)
    try:
        pub = endpoint.delta_publisher()
        enc = DeltaEncoder()
        p = params_of_model(model)
        pub.apply(enc.encode(1, p, pub.stats))
        enc.ack()
        for n in (1, 2, 64):
            endpoint.predict(feats.take(n))       # settle wave
        with jtu.count_jit_and_pmap_lowerings() as count:
            for step in range(2, 12):
                p = {"w": p["w"] + np.float32(0.01), "b": p["b"]}
                pub.apply(enc.encode(step, p, pub.stats))
                enc.ack()
                endpoint.predict(feats.take(1 + step % 32))
        assert count[0] == 0, (
            f"{count[0]} new XLA lowerings across 10 publish+serve "
            "cycles — a delta publish recompiled something")
        assert endpoint.registry.current("default").generation >= 11
    finally:
        endpoint.close()


def test_publish_replay_is_idempotent_and_stale_steps_skip():
    model = LogisticRegression().set_max_iter(3).fit(_lr_table())
    endpoint = serve_model(model, _lr_table(seed=5).drop("label").take(2),
                           max_batch_rows=32, max_wait_ms=0.5)
    try:
        pub, enc, p = _publish_chain(endpoint, [4, 8])
        gen = endpoint.registry.current("default").generation
        # replayed cut at the SAME step with the same bits: no-op
        same = DeltaEncoder()
        r = pub.apply(same.encode(8, p, pub.stats))
        assert r.mode == "noop"
        assert endpoint.registry.current("default").generation == gen
        # an OLDER step (restore fell back a cut): serving never moves
        # backward
        older = {"w": np.zeros_like(p["w"]), "b": p["b"]}
        r = pub.apply(DeltaEncoder().encode(4, older, pub.stats))
        assert r.mode == "noop"
        assert _served_w(endpoint).tobytes() == p["w"].tobytes()
    finally:
        endpoint.close()


def test_publish_replay_with_different_bits_is_determinism_violation():
    model = LogisticRegression().set_max_iter(3).fit(_lr_table())
    endpoint = serve_model(model, _lr_table(seed=5).drop("label").take(2),
                           max_batch_rows=32, max_wait_ms=0.5)
    try:
        pub, enc, p = _publish_chain(endpoint, [4, 8])
        diverged = {"w": p["w"] + np.float32(1.0), "b": p["b"]}
        with pytest.raises(DeterminismViolation):
            pub.apply(DeltaEncoder().encode(8, diverged, pub.stats))
    finally:
        endpoint.close()


def test_stale_encoder_base_heals_with_full_reanchor():
    """A crash between publish and ack leaves the encoder one
    generation behind: its next delta base-mismatches, and
    encode_and_publish re-anchors with a full update."""
    from flink_ml_tpu.online import encode_and_publish

    model = LogisticRegression().set_max_iter(3).fit(_lr_table())
    endpoint = serve_model(model, _lr_table(seed=5).drop("label").take(2),
                           max_batch_rows=32, max_wait_ms=0.5)
    try:
        pub = endpoint.delta_publisher()
        enc = DeltaEncoder()
        p0 = params_of_model(model)
        encode_and_publish(enc, pub, 1, p0)
        p1 = {"w": p0["w"] + np.float32(0.5), "b": p0["b"]}
        pub.apply(enc.encode(2, p1, pub.stats))    # landed, NOT acked
        p2 = {"w": p1["w"] + np.float32(0.5), "b": p1["b"]}
        enc._pending = None                        # simulate crashed ack
        r = encode_and_publish(enc, pub, 3, p2)
        assert r.mode == "full"                    # healed by re-anchor
        assert _served_w(endpoint).tobytes() == p2["w"].tobytes()
    finally:
        endpoint.close()


def test_full_publish_with_changed_shape_refused_serving_unharmed():
    """A delta is shape-guarded by its base digest; a FULL update must
    be guarded explicitly — a shape-incompatible publish riding the
    rebind fast path (which skips warm-up) would break every later
    request.  The publisher refuses, and the live generation keeps
    answering."""
    model = LogisticRegression().set_max_iter(3).fit(_lr_table(d=8))
    feats = _lr_table(seed=5, d=8).drop("label")
    endpoint = serve_model(model, feats.take(2), max_batch_rows=32,
                           max_wait_ms=0.5)
    try:
        pub = endpoint.delta_publisher()
        wrong = DeltaEncoder().encode(     # 16-wide params on an 8-wide
            1, {"w": np.zeros(16, np.float32),   # generation
                "b": np.float32(0.0)}, pub.stats)
        gen = endpoint.registry.current("default").generation
        with pytest.raises(DeltaShapeChanged, match="registry.deploy"):
            pub.apply(wrong)
        assert endpoint.registry.current("default").generation == gen
        out = endpoint.predict(feats.take(3))
        assert out.num_rows == 3
    finally:
        endpoint.close()


def test_external_hot_swap_invalidates_publisher_base():
    """An operator hot_swap between trainer publishes moves the live
    generation: the publisher must re-anchor on what actually serves —
    a pending delta heals with a full re-anchor (never applies against
    the stale lineage), and a shape-incompatible trainer update is
    refused against the LIVE shapes, not the cached ones."""
    from flink_ml_tpu.online import encode_and_publish

    model = LogisticRegression().set_max_iter(3).fit(_lr_table(d=8))
    feats = _lr_table(seed=5, d=8).drop("label")
    endpoint = serve_model(model, feats.take(2), max_batch_rows=32,
                           max_wait_ms=0.5)
    try:
        pub = endpoint.delta_publisher()
        enc = DeltaEncoder()
        p = params_of_model(model)
        encode_and_publish(enc, pub, 1, p)
        # operator deploys a DIFFERENT model into the same entry
        other = LogisticRegression().set_max_iter(5).fit(_lr_table(seed=9))
        endpoint.hot_swap(other)
        # trainer's next delta: heals via full re-anchor onto its own
        # lineage (the publish protocol owns the entry again)
        p2 = {"w": p["w"] + np.float32(0.25), "b": p["b"]}
        r = encode_and_publish(enc, pub, 2, p2)
        assert r.mode == "full"
        assert _served_w(endpoint).tobytes() == p2["w"].tobytes()
    finally:
        endpoint.close()


def test_publish_compare_and_swap_refuses_stale_generation():
    """publish_servable is a compare-and-swap: a publish validated
    against a generation that a concurrent deploy has since replaced is
    refused (GenerationConflict), never silently clobbering the newer
    model; DeltaPublisher.apply retries through re-validation."""
    from flink_ml_tpu.serving.registry import GenerationConflict

    model = LogisticRegression().set_max_iter(3).fit(_lr_table())
    endpoint = serve_model(model, _lr_table(seed=5).drop("label").take(2),
                           max_batch_rows=32, max_wait_ms=0.5)
    try:
        live = endpoint.registry.current("default")
        rebound = live.servable.rebind(live.servable.model)
        endpoint.hot_swap(LogisticRegression().set_max_iter(5)
                          .fit(_lr_table(seed=9)))   # generation moves
        with pytest.raises(GenerationConflict):
            endpoint.registry.publish_servable(
                "default", rebound, expected_generation=live.generation)
        # unconditional publish (no expectation) still works
        endpoint.registry.publish_servable("default", rebound)
    finally:
        endpoint.close()


def test_learner_publish_cadence_skips_cuts(tmp_path):
    """StalenessPolicy(publish_every=2) thins the publish cadence to
    every other cut; skipped cuts are counted and never fetched."""
    windows = list(_windows(0, 16))
    boot = LogisticRegression().set_max_iter(1).fit(windows[0])
    endpoint = serve_model(boot, windows[0].drop("label").take(2),
                           max_batch_rows=32, max_wait_ms=0.5)
    try:
        learner = ContinuousLearner(
            loss_fn=logistic_loss, num_features=4,
            source=iter(windows), wal_dir=str(tmp_path / "wal"),
            endpoint=endpoint, batch_rows=16,
            checkpoint=CheckpointConfig(str(tmp_path / "ck")),
            publish_every_steps=4,
            policy=StalenessPolicy(publish_every=2))
        learner.run(max_windows=16)
        steps = [r.step for r in learner.publish_log]
        assert steps == [8, 16]                   # cuts 4 and 12 skipped
        assert learner.publisher.stats.skips >= 2
        w_off, _ = _offline_fit(windows, 16, every=4)
        assert _served_w(endpoint).tobytes() == w_off.tobytes()
    finally:
        endpoint.close()


def test_generic_servable_refuses_rebind():
    from flink_ml_tpu.serving.executor import ServableModel

    model = LogisticRegression().set_max_iter(2).fit(_lr_table())
    servable = ServableModel(model, _lr_table().drop("label").take(1))
    assert not servable.rebind_safe
    with pytest.raises(TypeError, match="not rebind-safe"):
        servable.rebind(model)


def test_staleness_metrics_and_policy_decisions():
    model = LogisticRegression().set_max_iter(3).fit(_lr_table())
    feats = _lr_table(seed=5).drop("label")
    endpoint = serve_model(model, feats.take(2), max_batch_rows=32,
                           max_wait_ms=0.5)
    try:
        pub, enc, p = _publish_chain(endpoint, [1, 2, 3])
        endpoint.predict(feats.take(2))
        snap = endpoint.metrics.snapshot()
        assert snap["publishes_full"] >= 1
        assert snap["publishes_delta"] >= 1
        assert snap["model_staleness_seconds"] >= 0.0
        assert "publishes_per_sec" in snap and "last_publish_bytes" in snap
    finally:
        endpoint.close()
    from flink_ml_tpu.online import PublishStats

    policy = StalenessPolicy(publish_every=2, full_every=3)
    stats = PublishStats(publishes=1)
    assert policy.due(0, stats) and not policy.due(1, stats)
    # payload parity forces full (re-anchor is free at equal bytes)
    assert policy.choose(95, 100, stats) == "full"
    assert policy.choose(10, 100, stats) == "delta"
    # cadence re-anchor: every full_every-th publish ships full
    assert policy.choose(10, 100, PublishStats(publishes=3)) == "full"


# -- WAL window reader -------------------------------------------------------

def _windows(start, stop, rows=16, d=4):
    for i in range(start, stop):
        rng = np.random.default_rng(1000 + i)
        X = rng.normal(size=(rows, d)).astype(np.float32)
        yield Table({"features": X,
                     "label": (X[:, 0] > 0).astype(np.float32)})


def test_window_batch_reader_ragged_window_raises(tmp_path):
    log = WindowLog(iter([Table({"features": np.zeros((16, 4)),
                                 "label": np.zeros(16)}),
                          Table({"features": np.zeros((7, 4)),
                                 "label": np.zeros(7)})]),
                    str(tmp_path / "wal"))
    reader = WindowBatchReader(log, 16)
    it = iter(reader)
    next(it)
    with pytest.raises(ValueError, match="fixed window grid"):
        next(it)


def test_window_batch_reader_seek_rides_wal_cursor(tmp_path):
    d = str(tmp_path / "wal")
    for _ in WindowLog(_windows(0, 6), d):
        pass                                       # log 6 windows
    log = WindowLog(iter(()), d)
    reader = WindowBatchReader(log, 16)
    with pytest.raises(ValueError, match="window boundaries"):
        reader.seek(17)
    reader.seek(4 * 16)
    batches = list(reader)
    assert len(batches) == 2                       # replayed 4, 5
    oracle = list(_windows(4, 6))
    np.testing.assert_array_equal(batches[0]["features"],
                                  np.asarray(oracle[0]["features"]))


# -- the acceptance bar ------------------------------------------------------

class _SpyPublisher(DeltaPublisher):
    """Records the full published params at every landed publish."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.history = []

    def apply(self, update):
        result = super().apply(update)
        if result.mode != "noop":
            self.history.append(
                (result.step, {k: v.copy()
                               for k, v in self._base.items()}))
        return result


def _offline_fit(windows, upto, every):
    def make_reader():
        for w in windows[:upto]:
            yield w.to_dict()

    state, _ = sgd_fit_outofcore(
        logistic_loss, make_reader, num_features=4,
        config=SGDConfig(max_epochs=1, tol=0.0), steps_per_dispatch=every)
    return np.asarray(state.coefficients, np.float32), \
        np.float32(state.intercept)


def test_train_while_serve_served_bits_match_offline_fit(tmp_path):
    """ROADMAP item 1 acceptance (crash-free half): at EVERY publish
    step T, the published params are bit-exact with an offline
    single-pass fit over WAL windows <= T, and the final served model is
    bit-exact with the offline fit over all of them."""
    windows = list(_windows(0, 20))
    boot = LogisticRegression().set_max_iter(1).fit(windows[0])
    endpoint = serve_model(boot, windows[0].drop("label").take(2),
                           max_batch_rows=32, max_wait_ms=0.5)
    try:
        learner = ContinuousLearner(
            loss_fn=logistic_loss, num_features=4,
            source=iter(windows), wal_dir=str(tmp_path / "wal"),
            endpoint=endpoint, batch_rows=16,
            checkpoint=CheckpointConfig(str(tmp_path / "ck")),
            publish_every_steps=4)
        spy = _SpyPublisher(endpoint.registry, "default",
                            metrics=endpoint.metrics)
        learner.publisher = spy
        state, loss_log = learner.run(max_windows=20)
        assert len(loss_log) == 1                  # single unbounded pass
        steps = [s for s, _ in spy.history]
        assert steps == [4, 8, 12, 16, 20]
        for step, flat in spy.history:
            w_off, b_off = _offline_fit(windows, step, every=4)
            assert flat["w"].tobytes() == w_off.tobytes(), \
                f"published params at step {step} != offline fit"
            assert flat["b"].tobytes() == np.asarray(b_off).tobytes()
        w_final, _ = _offline_fit(windows, 20, every=4)
        assert _served_w(endpoint).tobytes() == w_final.tobytes()
        # serving answered on the continuously-published generations
        out = endpoint.predict(windows[3].drop("label"))
        assert out.num_rows == 16
    finally:
        endpoint.close()


def test_hosted_iterate_listener_publishes_at_checkpoints(tmp_path):
    """The hosted-``iterate`` flavor (FTRL/online-KMeans-style bodies):
    a PublishingListener on the checkpoint hook pushes every durable
    cut's state into the live generation."""
    import jax.numpy as jnp

    windows = list(_windows(0, 12))
    boot = LogisticRegression().set_max_iter(1).fit(windows[0])
    endpoint = serve_model(boot, windows[0].drop("label").take(2),
                           max_batch_rows=32, max_wait_ms=0.5)
    try:
        listener = PublishingListener(
            endpoint.delta_publisher(),
            params_of=lambda s: {"w": s["w"], "b": s["b"]})

        def body(state, epoch, data):
            X, y = data
            margin = X @ state["w"] + state["b"]
            p = 1.0 / (1.0 + jnp.exp(-margin))
            g = X.T @ (p - y) / X.shape[0]
            return IterationBodyResult({
                "w": state["w"] - 0.5 * g,
                "b": state["b"] - 0.5 * jnp.mean(p - y)})

        state0 = {"w": jnp.zeros(4, jnp.float32),
                  "b": jnp.asarray(0.0, jnp.float32)}
        payloads = ((np.asarray(w["features"], np.float32),
                     np.asarray(w["label"], np.float32))
                    for w in windows)
        result = iterate(
            body, state0, payloads,
            config=IterationConfig(mode="hosted", jit=True),
            listeners=[listener],
            checkpoint=CheckpointConfig(str(tmp_path / "ck"), interval=4))
        assert [r.step for r in listener.publish_log] == [4, 8, 12]
        final_w = np.asarray(result.state["w"], np.float32)
        assert _served_w(endpoint).tobytes() == final_w.tobytes()
    finally:
        endpoint.close()
