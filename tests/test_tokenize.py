"""Tokenizer / RegexTokenizer / NGram / StopWordsRemover /
CountVectorizer."""

import numpy as np
import pytest

from flink_ml_tpu import Table
from flink_ml_tpu.models.feature import (
    CountVectorizer,
    CountVectorizerModel,
    NGram,
    RegexTokenizer,
    StopWordsRemover,
    Tokenizer,
)


def _text_table(*docs):
    return Table({"features": np.asarray(docs, dtype=object)})


def _tokens_table(*rows):
    col = np.empty((len(rows),), object)
    for i, r in enumerate(rows):
        col[i] = list(r)
    return Table({"features": col})


def test_tokenizer_lowercases_and_splits():
    out = Tokenizer().transform(
        _text_table("Hello  World", "One TWO three", "tail  "))[0]
    toks = out["output"]
    # Java split("\\s") semantics: interior empties kept, trailing dropped
    assert toks[0] == ["hello", "", "world"]
    assert toks[1] == ["one", "two", "three"]
    assert toks[2] == ["tail"]


def test_regex_tokenizer_gaps_and_matches():
    t = _text_table("a-b-c d")
    gaps = (RegexTokenizer().set_pattern(r"[-\s]+")
            .transform(t)[0]["output"])
    assert gaps[0] == ["a", "b", "c", "d"]

    words = (RegexTokenizer().set_pattern(r"\w+").set_gaps(False)
             .transform(_text_table("Foo, bar!  baz?"))[0]["output"])
    assert words[0] == ["foo", "bar", "baz"]


def test_regex_tokenizer_min_length_and_case():
    out = (RegexTokenizer().set_min_token_length(3).set_to_lowercase(False)
           .transform(_text_table("An Owl ate my Sandwich"))[0]["output"])
    assert out[0] == ["Owl", "ate", "Sandwich"]


def test_ngram_basic_and_short_rows():
    out = (NGram().set_n(2)
           .transform(_tokens_table(["a", "b", "c"], ["x"]))[0]["output"])
    assert out[0] == ["a b", "b c"]
    assert out[1] == []


def test_stop_words_remover_default_english():
    out = (StopWordsRemover()
           .transform(_tokens_table(["The", "red", "balloon", "and", "a",
                                     "dog"]))[0]["output"])
    assert out[0] == ["red", "balloon", "dog"]


def test_stop_words_remover_case_sensitive_custom():
    r = (StopWordsRemover().set_stop_words("The", "a")
         .set_case_sensitive(True))
    out = r.transform(_tokens_table(["The", "the", "a", "A"]))[0]["output"]
    assert out[0] == ["the", "A"]


def test_stop_words_remover_unknown_language():
    with pytest.raises(ValueError, match="language"):
        StopWordsRemover.load_default_stop_words("klingon")


def _corpus():
    return _tokens_table(
        ["a", "b", "c"],
        ["a", "b", "b", "c", "a"],
        ["a"],
    )


def test_count_vectorizer_vocab_order_and_counts():
    model = CountVectorizer().fit(_corpus())
    # corpus term freq: a=4, b=3, c=2 -> vocabulary in that order
    assert model.vocabulary == ["a", "b", "c"]
    out = np.asarray(model.transform(_corpus())[0]["output"])
    np.testing.assert_array_equal(out, [[1, 1, 1], [2, 2, 1], [1, 0, 0]])


def test_count_vectorizer_vocab_size_and_min_df():
    model = (CountVectorizer().set_vocabulary_size(2).fit(_corpus()))
    assert model.vocabulary == ["a", "b"]

    # c appears in 2/3 docs; min_df as a count of 3 excludes it and b (2 docs)
    model = CountVectorizer().set_min_df(3.0).fit(_corpus())
    assert model.vocabulary == ["a"]

    # fractional max_df: drop terms in > 90% of docs (a is in all 3)
    model = CountVectorizer().set_max_df(0.9).fit(_corpus())
    assert model.vocabulary == ["b", "c"]


def test_count_vectorizer_min_tf_and_binary():
    model = CountVectorizer().fit(_corpus())
    # min_tf count 2: only terms appearing >= 2x in the doc survive
    out = np.asarray(
        model.set_min_tf(2.0).transform(_corpus())[0]["output"])
    np.testing.assert_array_equal(out[1], [2, 2, 0])
    np.testing.assert_array_equal(out[0], [0, 0, 0])

    binary = CountVectorizer().set_binary(True).fit(_corpus())
    bout = np.asarray(binary.transform(_corpus())[0]["output"])
    assert set(np.unique(bout)) <= {0.0, 1.0}


def test_count_vectorizer_unseen_tokens_ignored():
    model = CountVectorizer().fit(_corpus())
    out = np.asarray(
        model.transform(_tokens_table(["z", "a"]))[0]["output"])
    np.testing.assert_array_equal(out, [[1, 0, 0]])


def test_count_vectorizer_save_load(tmp_path):
    model = CountVectorizer().set_vocabulary_size(2).fit(_corpus())
    path = str(tmp_path / "cv")
    model.save(path)
    loaded = CountVectorizerModel.load(path)
    assert loaded.vocabulary == ["a", "b"]
    out = np.asarray(loaded.transform(_corpus())[0]["output"])
    np.testing.assert_array_equal(out[:, 0], [1, 2, 1])


def test_tokenize_pipeline_chains_into_hashing_idf():
    """Tokenizer -> StopWordsRemover -> NGram chained through one Table."""
    t = _text_table("the quick brown fox", "the lazy dog sleeps")
    toks = Tokenizer().set_output_col("tokens").transform(t)[0]
    kept = (StopWordsRemover().set_features_col("tokens")
            .set_output_col("kept").transform(toks)[0])
    grams = (NGram().set_features_col("kept").set_output_col("grams")
             .transform(kept)[0])
    assert grams["grams"][0] == ["quick brown", "brown fox"]
    assert grams["grams"][1] == ["lazy dog", "dog sleeps"]
