"""Iteration runtime tests.

Mirrors the reference ITCase matrix (SURVEY §4): bounded all-round
iteration with exact per-round sums, termination by criteria vs max-round,
per-round lifecycle, listener callbacks, and stream-end termination.
The 4x1000 exact-sum anchor comes from
``BoundedAllRoundStreamIterationITCase.java:96-101`` (sum = 1,998,000).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flink_ml_tpu.iteration import (
    EpochContext,
    FnListener,
    IterationBodyResult,
    IterationConfig,
    IterationListener,
    OperatorLifeCycle,
    iterate,
)
from flink_ml_tpu.parallel import data_sharding, device_mesh, shard_batch


def test_simple_carried_state():
    # x_{e+1} = x_e + 1 for 5 epochs
    res = iterate(lambda x, e: x + 1, jnp.asarray(0.0), max_epochs=5)
    assert float(res.state) == 5.0
    assert res.num_epochs == 5


def test_reduce_sum_anchor():
    # The reference's 4 parallel sources x records 0..999, reduced per round:
    # every round must see the exact sum 1,998,000.
    records = np.concatenate([np.arange(1000)] * 4).astype(np.float64)
    data = jnp.asarray(records)

    def body(state, epoch, d):
        round_sum = jnp.sum(d)
        return IterationBodyResult(feedback=state + 1, outputs=round_sum)

    res = iterate(body, jnp.asarray(0, jnp.int32), data, max_epochs=5,
                  config=IterationConfig(mode="hosted"))
    assert res.num_epochs == 5
    assert [float(o) for o in res.outputs] == [1998000.0] * 5

    # fused mode gives identical per-round sums (scan-stacked)
    res_f = iterate(body, jnp.asarray(0, jnp.int32), data, max_epochs=5,
                    config=IterationConfig(mode="fused"))
    np.testing.assert_array_equal(np.asarray(res_f.outputs), [1998000.0] * 5)


def test_termination_criteria():
    # RoundBasedTerminationCriteria analog: continue while epoch < 3.
    def body(x, epoch):
        return IterationBodyResult(feedback=x * 2, outputs=x,
                                   termination=epoch < 3)

    res = iterate(body, jnp.asarray(1.0), max_epochs=100,
                  config=IterationConfig(mode="hosted"))
    # epochs 0,1,2 vote continue; epoch 3 votes stop -> 4 body invocations
    assert res.num_epochs == 4
    assert float(res.state) == 16.0
    assert res.side["termination_reason"] == "criteria"


def test_termination_criteria_fused_matches_hosted():
    def body(x, epoch):
        return IterationBodyResult(feedback=x * 2, outputs=x,
                                   termination=epoch < 3)

    hosted = iterate(body, jnp.asarray(1.0), max_epochs=100,
                     config=IterationConfig(mode="hosted"))
    # fused + outputs + criteria: the documented keeps-last-epoch warning
    # must fire (the IterationListener-era evidence, VERDICT row 18)
    with pytest.warns(UserWarning, match="LAST epoch's outputs"):
        fused = iterate(body, jnp.asarray(1.0), max_epochs=100,
                        config=IterationConfig(mode="fused"))
    assert float(fused.state) == float(hosted.state)
    assert fused.num_epochs == hosted.num_epochs


def test_zero_feedback_terminates_immediately():
    # Termination vote false on the first epoch: 1-round case
    # (BoundedAllRoundStreamIterationITCase.java:116-142 criteria-from-
    # constants analog).
    res = iterate(
        lambda x, e: IterationBodyResult(x, None, jnp.asarray(False)),
        jnp.asarray(7.0), max_epochs=10, config=IterationConfig(mode="hosted"))
    assert res.num_epochs == 1
    assert float(res.state) == 7.0


def test_listeners_fire_per_epoch():
    seen = []
    terminated = []

    class Recorder(IterationListener):
        def on_epoch_watermark_incremented(self, epoch, ctx):
            seen.append((epoch, float(ctx.state)))

        def on_iteration_terminated(self, ctx):
            terminated.append(ctx.epoch)

    res = iterate(lambda x, e: x + 1, jnp.asarray(0.0), max_epochs=3,
                  listeners=[Recorder()])
    assert seen == [(0, 1.0), (1, 2.0), (2, 3.0)]
    assert terminated == [3]
    assert res.num_epochs == 3


def test_fn_listener_side_outputs():
    def on_epoch(epoch, ctx: EpochContext):
        ctx.output("epochs", epoch)

    res = iterate(lambda x, e: x + 1, jnp.asarray(0.0), max_epochs=3,
                  listeners=[FnListener(on_epoch=on_epoch)])
    assert res.side["epochs"] == [0, 1, 2]


def test_per_round_lifecycle():
    # PER_ROUND: body-local state re-initialised every epoch (the analog of
    # per-round operator instances, BoundedPerRoundStreamIterationITCase).
    calls = []

    def body(state, epoch):
        calls.append(float(jax.device_get(state)))
        return IterationBodyResult(state + 10, outputs=None)

    res = iterate(body, jnp.asarray(0.0), max_epochs=3,
                  config=IterationConfig(lifecycle=OperatorLifeCycle.PER_ROUND,
                                         mode="hosted", jit=False))
    # every epoch starts from the re-initialised state 0
    assert calls == [0.0, 0.0, 0.0]
    assert float(res.state) == 10.0


def test_stream_end_terminates():
    # Iterator data source: epoch = one window; exhaustion ends the iteration
    # (the bounded end of iterateUnboundedStreams).
    batches = iter([jnp.ones(4), jnp.ones(4) * 2, jnp.ones(4) * 3])

    def body(acc, epoch, d):
        return IterationBodyResult(acc + jnp.sum(d), outputs=None)

    res = iterate(body, jnp.asarray(0.0), batches, max_epochs=100,
                  config=IterationConfig(mode="hosted"))
    assert res.num_epochs == 3
    assert float(res.state) == 4 + 8 + 12
    assert res.side["termination_reason"] == "stream_end"


def test_epoch_passed_as_device_scalar():
    # epoch enters the jitted step as a traced scalar -> one compilation
    compilations = []

    def body(x, e):
        compilations.append(1)  # traced once per compile
        return x + e

    res = iterate(body, jnp.asarray(0, jnp.int32), max_epochs=5,
                  config=IterationConfig(mode="hosted"))
    assert sum(compilations) == 1  # no per-epoch recompile
    assert int(res.state) == 0 + 1 + 2 + 3 + 4


def test_sharded_state_iteration():
    # SPMD epoch step over an 8-device mesh: data batch-sharded, state
    # replicated; aggregation = jnp.sum (XLA inserts the psum over ICI).
    mesh = device_mesh()
    data = shard_batch(np.arange(64, dtype=np.float32), mesh)
    assert len(data.sharding.device_set) == 8

    def body(w, epoch, d):
        return IterationBodyResult(w + jnp.sum(d), outputs=None)

    res = iterate(body, jnp.asarray(0.0, jnp.float32), data, max_epochs=4)
    assert float(res.state) == 4 * np.arange(64).sum()


def test_invalid_mode_rejected():
    with pytest.raises(ValueError):
        IterationConfig(mode="warp")


def test_fused_requires_static_data():
    with pytest.raises(ValueError):
        iterate(lambda x, e, d: x, jnp.asarray(0.0), iter([1, 2]),
                max_epochs=2, config=IterationConfig(mode="fused"))


def test_donation_preserves_caller_state():
    # Donation must consume a private copy — the caller's initial_state
    # buffers stay alive and reusable across multiple iterate() calls.
    init = jnp.arange(4, dtype=jnp.float32)
    r1 = iterate(lambda x, e: x + 1, init, max_epochs=3,
                 config=IterationConfig(mode="hosted"))
    r2 = iterate(lambda x, e: x + 1, init, max_epochs=3,
                 config=IterationConfig(mode="fused"))
    np.testing.assert_array_equal(np.asarray(init), [0, 1, 2, 3])
    np.testing.assert_array_equal(np.asarray(r1.state), np.asarray(r2.state))


def test_auto_mode_with_criteria_keeps_all_outputs():
    # auto must not pick fused (last-output-only) when a vote exists
    def body(x, epoch):
        return IterationBodyResult(x + 1, outputs=x, termination=epoch < 3)

    res = iterate(body, jnp.asarray(0.0), max_epochs=10)
    assert len(res.outputs) == 4  # full per-epoch log, not just the last


def test_tuple_state_never_unpacked():
    # A bare tuple return is the state itself, not (feedback, outputs)
    res = iterate(lambda s, e: (s[0] + 1, s[1] * 2),
                  (jnp.asarray(0.0), jnp.asarray(1.0)), max_epochs=3,
                  config=IterationConfig(mode="hosted"))
    assert float(res.state[0]) == 3.0
    assert float(res.state[1]) == 8.0


def test_mixed_replayed_and_per_epoch_inputs():
    # ReplayableDataStreamList analog: replayed device data + a live stream,
    # mixed in one dict (SURVEY §2.2).
    from flink_ml_tpu.iteration import PerEpoch, Replayed

    replayed = jnp.arange(8, dtype=jnp.float32)   # same every epoch
    stream = iter([jnp.asarray(1.0), jnp.asarray(2.0), jnp.asarray(3.0)])

    seen = []

    def body(acc, epoch, data):
        seen.append((float(jnp.sum(data["train"])), float(data["delta"])))
        return IterationBodyResult(acc + jnp.sum(data["train"]) * data["delta"])

    res = iterate(body, jnp.asarray(0.0),
                  {"train": Replayed(replayed), "delta": PerEpoch(stream)},
                  max_epochs=100, config=IterationConfig(mode="hosted",
                                                         jit=False))
    assert res.num_epochs == 3
    assert res.side["termination_reason"] == "stream_end"
    assert seen == [(28.0, 1.0), (28.0, 2.0), (28.0, 3.0)]
    assert float(res.state) == 28.0 * 6


def test_per_epoch_callable_marker():
    from flink_ml_tpu.iteration import PerEpoch

    res = iterate(
        lambda acc, e, d: IterationBodyResult(acc + d["x"]),
        jnp.asarray(0.0),
        {"x": PerEpoch(lambda epoch: jnp.asarray(float(epoch)))},
        max_epochs=4, config=IterationConfig(mode="hosted"))
    assert float(res.state) == 0 + 1 + 2 + 3


def test_replayed_marker_is_fusible():
    from flink_ml_tpu.iteration import Replayed

    data = {"x": Replayed(jnp.arange(4, dtype=jnp.float32))}
    res = iterate(lambda s, e, d: IterationBodyResult(s + jnp.sum(d["x"])),
                  jnp.asarray(0.0), data, max_epochs=3,
                  config=IterationConfig(mode="fused"))
    assert float(res.state) == 18.0


# ----------------------------------------------- mixed lifecycle (forEachRound)


def test_mixed_lifecycle_per_round_subtree():
    """Part of the state is per-round (re-initialised each epoch), part is
    carried — the ``IterationBody.forEachRound`` analog, semantics mirroring
    ``BoundedMixedLifeCycleStreamIterationITCase.java``: an all-round
    running reduce feeds a per-round accumulator that must start fresh every
    round."""
    data = jnp.arange(4.0)

    def body(state, epoch, d):
        # per-round scratch starts at 0 every epoch; if it carried, round_sum
        # would accumulate across rounds and the asserts below would fail
        round_sum = state["scratch"] + jnp.sum(d) + state["carried"]
        return IterationBodyResult(
            {"carried": state["carried"] + 1.0, "scratch": round_sum},
            outputs=round_sum)

    init = {"carried": jnp.asarray(0.0), "scratch": jnp.asarray(0.0)}
    result = iterate(body, init, data, max_epochs=4, per_round=("scratch",),
                     config=IterationConfig(mode="hosted"))
    # round e: scratch re-enters at 0, carried enters at e -> output 6 + e
    assert [float(o) for o in result.outputs] == [6.0, 7.0, 8.0, 9.0]
    assert float(result.state["carried"]) == 4.0
    # final state keeps the LAST round's per-round value (forEachRound output)
    assert float(result.state["scratch"]) == 9.0


def test_mixed_lifecycle_fused_matches_hosted():
    data = jnp.arange(3.0)

    def body(state, epoch, d):
        s = state["tmp"] + jnp.sum(d)
        return IterationBodyResult({"acc": state["acc"] + s, "tmp": s})

    init = {"acc": jnp.asarray(0.0), "tmp": jnp.asarray(0.0)}
    hosted = iterate(body, init, data, max_epochs=5, per_round=("tmp",),
                     config=IterationConfig(mode="hosted"))
    fused = iterate(body, init, data, max_epochs=5, per_round=("tmp",),
                    config=IterationConfig(mode="fused"))
    assert float(hosted.state["acc"]) == float(fused.state["acc"]) == 15.0
    assert float(fused.state["tmp"]) == 3.0


def test_mixed_lifecycle_validates_keys():
    with pytest.raises(KeyError, match="nope"):
        iterate(lambda s, e: s, {"a": jnp.asarray(0.0)}, max_epochs=1,
                per_round=("nope",))
    with pytest.raises(TypeError, match="dict"):
        iterate(lambda s, e: s, jnp.asarray(0.0), max_epochs=1,
                per_round=("a",))


# -- workset iterations (ISSUE 9) --------------------------------------------

def _counter_workset_body(state, ws, epoch, data):
    """Toy workset: per-element counters run up to per-element targets;
    an element leaves the workset once its target is reached."""
    from flink_ml_tpu.iteration import Workset

    new = state + ws.mask
    return IterationBodyResult(
        (new, Workset((new < data).astype(jnp.float32), ws.bounds)))


def test_workset_drains_and_exits_before_max_epochs():
    from flink_ml_tpu.iteration import Workset

    targets = jnp.asarray([2.0, 5.0, 3.0, 7.0])
    ws0 = Workset(jnp.ones(4, jnp.float32), {"aux": jnp.zeros(4)})
    res = iterate(_counter_workset_body, jnp.zeros(4), targets,
                  max_epochs=50, workset=ws0)
    np.testing.assert_array_equal(np.asarray(res.state), [2, 5, 3, 7])
    assert res.num_epochs == 7 < 50          # convergence-driven exit
    assert np.all(np.asarray(res.workset.mask) == 0)
    # bounds pytree rides untouched
    np.testing.assert_array_equal(np.asarray(res.workset.bounds["aux"]),
                                  np.zeros(4))


def test_workset_fused_matches_hosted_including_trace():
    from flink_ml_tpu.iteration import Workset

    targets = jnp.asarray([2.0, 5.0, 3.0, 7.0])
    ws0 = Workset(jnp.ones(4, jnp.float32))
    fused = iterate(_counter_workset_body, jnp.zeros(4), targets,
                    max_epochs=50, workset=ws0,
                    config=IterationConfig(mode="fused"))
    hosted = iterate(_counter_workset_body, jnp.zeros(4), targets,
                     max_epochs=50, workset=ws0,
                     config=IterationConfig(mode="hosted"))
    np.testing.assert_array_equal(np.asarray(fused.state),
                                  np.asarray(hosted.state))
    assert fused.num_epochs == hosted.num_epochs
    for key in ("active_fraction", "termination"):
        np.testing.assert_allclose(fused.side["epoch_trace"][key],
                                   hosted.side["epoch_trace"][key])


def test_workset_epoch_trace_records_decay_curve():
    from flink_ml_tpu.iteration import Workset

    targets = jnp.asarray([1.0, 2.0, 3.0, 4.0])
    res = iterate(_counter_workset_body, jnp.zeros(4), targets,
                  max_epochs=32, workset=Workset(jnp.ones(4, jnp.float32)))
    trace = res.side["epoch_trace"]
    # one entry per epoch actually run; the NaN prefill never leaks out
    assert trace["active_fraction"].shape == (res.num_epochs,)
    assert not np.any(np.isnan(trace["active_fraction"]))
    np.testing.assert_allclose(trace["active_fraction"],
                               [0.75, 0.5, 0.25, 0.0])
    # the final epoch votes stop (fraction hit zero)
    assert trace["termination"][-1] == 0.0
    assert np.all(trace["termination"][:-1] == 1.0)


def test_criteria_while_loop_emits_termination_trace_without_workset():
    # ISSUE 9 satellite: convergence curves survive the fused while_loop
    # even for plain criteria-driven bodies — active_fraction is NaN
    # (no workset), termination carries the per-epoch vote.
    def body(x, epoch):
        return IterationBodyResult(feedback=x * 2, termination=epoch < 3)

    res = iterate(body, jnp.asarray(1.0), max_epochs=100,
                  config=IterationConfig(mode="fused"))
    trace = res.side["epoch_trace"]
    assert res.num_epochs == 4
    assert np.all(np.isnan(trace["active_fraction"]))
    np.testing.assert_array_equal(trace["termination"], [1, 1, 1, 0])


def test_workset_body_vote_ands_with_active_fraction():
    from flink_ml_tpu.iteration import Workset

    # elements never drain, but the body votes stop at epoch 3
    def body(state, ws, epoch, data):
        return IterationBodyResult((state + 1, ws), termination=epoch < 3)

    res = iterate(body, jnp.zeros(4), jnp.ones(4), max_epochs=50,
                  workset=Workset(jnp.ones(4, jnp.float32)))
    assert res.num_epochs == 4
    assert float(np.asarray(res.workset.mask).sum()) == 4.0


def test_workset_tol_exits_at_nonzero_fraction():
    from flink_ml_tpu.iteration import Workset

    targets = jnp.asarray([2.0, 5.0, 3.0, 20.0])
    res = iterate(_counter_workset_body, jnp.zeros(4), targets,
                  max_epochs=50, workset=Workset(jnp.ones(4, jnp.float32)),
                  workset_tol=0.3)   # exit once <= 30% remain active
    # after epoch 5 only the target-20 element is active (25% <= 30%)
    assert res.num_epochs == 5
    assert float(np.asarray(res.workset.mask).sum()) == 1.0


def test_workset_rejects_per_round_and_wrong_type():
    from flink_ml_tpu.iteration import Workset

    with pytest.raises(TypeError, match="Workset"):
        iterate(_counter_workset_body, jnp.zeros(2), jnp.ones(2),
                max_epochs=3, workset=jnp.ones(2))
    with pytest.raises(ValueError, match="per-round"):
        iterate(_counter_workset_body, {"a": jnp.zeros(2)}, jnp.ones(2),
                max_epochs=3, workset=Workset(jnp.ones(2, jnp.float32)),
                per_round=["a"])


def test_workset_active_fraction_spans_mask_pytree():
    from flink_ml_tpu.iteration import Workset, active_fraction

    ws = Workset({"users": jnp.asarray([1.0, 0.0, 1.0]),
                  "items": jnp.asarray([0.0])})
    assert float(active_fraction(ws)) == 0.5
