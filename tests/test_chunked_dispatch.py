"""Chunked-scan out-of-core execution: W prefetched batches stack into
one device chunk and run as ONE jitted lax.scan with a donated carry, so
W optimizer steps cost one host dispatch (the fused-loop dispatch
amortization applied to the streaming paths).  These tests pin the two
contracts the layer rides on:

- BIT-EXACTNESS: any two ``steps_per_dispatch`` values produce identical
  results on the same batch stream, including a padded (masked) final
  chunk — the dead steps freeze the carry exactly.
- PIPELINE HEALTH: the prefetch reassembly keeps put concurrency under
  backpressure (puts happen outside ``flush_lock``), and an in-stream
  error stops further ``device_put`` work.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flink_ml_tpu.data.datacache import DataCacheReader, DataCacheWriter
from flink_ml_tpu.data.prefetch import PrefetchStats, prefetch_to_device
from flink_ml_tpu.models.common.losses import logistic_loss
from flink_ml_tpu.models.common.sgd import SGDConfig, sgd_fit_outofcore


def _lr_cache(tmp_path, name="chunk_cache", n=4096, d=16, seed=0):
    rng = np.random.default_rng(seed)
    true_w = rng.normal(size=(d,))
    cache = str(tmp_path / name)
    writer = DataCacheWriter(cache, segment_rows=1024)
    for start in range(0, n, 512):
        X = rng.normal(size=(512, d)).astype(np.float32)
        writer.append({"features": X,
                       "label": (X @ true_w > 0).astype(np.float32)})
    writer.finish()
    return cache


# ------------------------------------------------------ sgd streaming


def test_sgd_streaming_chunked_bitexact_w_sweep(tmp_path):
    """W in (1, 3, 8) on an 11-batch epoch: W=3 and W=8 both pad the
    final chunk (11 % 3 != 0, 11 % 8 != 0), and every W lands on
    BIT-identical parameters and loss logs."""
    cache = _lr_cache(tmp_path)
    cfg = SGDConfig(learning_rate=0.5, max_epochs=3, tol=0.0)

    results = {}
    for W in (1, 3, 8):
        info = {}
        state, log = sgd_fit_outofcore(
            logistic_loss,
            # 4096 / 384 -> 11 batches (final one partial-row as well)
            lambda: DataCacheReader(cache, batch_rows=384),
            num_features=16, config=cfg, steps_per_dispatch=W,
            stream_info=info)
        assert info["steps_per_dispatch"] == W
        assert info["dispatches_per_epoch"] == [-(-11 // W)] * 3
        results[W] = (state, log)

    ref_state, ref_log = results[1]
    for W in (3, 8):
        state, log = results[W]
        np.testing.assert_array_equal(state.coefficients,
                                      ref_state.coefficients)
        assert state.intercept == ref_state.intercept
        np.testing.assert_array_equal(log, ref_log)


def test_sgd_chunked_smoke_w2(tmp_path):
    """Tier-1-safe smoke: tiny rows, W=2, padded final chunk — the
    chunked path runs in every CI pass."""
    cache = _lr_cache(tmp_path, "smoke", n=1280, d=8, seed=1)
    info = {}
    state, log = sgd_fit_outofcore(
        logistic_loss, lambda: DataCacheReader(cache, batch_rows=256),
        num_features=8,
        config=SGDConfig(learning_rate=0.5, max_epochs=2, tol=0.0),
        steps_per_dispatch=2, stream_info=info)
    # 5 batches -> 3 dispatches (last chunk padded+masked)
    assert info["steps_per_dispatch"] == 2
    assert info["dispatches_per_epoch"] == [3, 3]
    assert np.all(np.isfinite(state.coefficients))
    assert log[-1] < log[0]


def test_sgd_chunked_checkpoint_cuts_at_chunk_boundaries(tmp_path):
    """Mid-epoch checkpoint cuts land at chunk boundaries and resume
    bit-exactly (chunk-granular exactly-once)."""
    from flink_ml_tpu.iteration.checkpoint import CheckpointConfig

    cache = _lr_cache(tmp_path, "ckpt", n=2048, d=8, seed=2)
    cfg = SGDConfig(learning_rate=0.4, max_epochs=3, tol=0.0)

    def reader():
        return DataCacheReader(cache, batch_rows=256)   # 8 batches/epoch

    ref_state, ref_log = sgd_fit_outofcore(
        logistic_loss, reader, num_features=8, config=cfg,
        steps_per_dispatch=3)

    calls = [0]

    def failing_reader():
        def gen():
            for b in DataCacheReader(cache, batch_rows=256):
                calls[0] += 1
                if calls[0] > 12:
                    raise RuntimeError("injected mid-epoch failure")
                yield b
        return gen()

    ckpt = CheckpointConfig(str(tmp_path / "ck"), max_to_keep=4)
    with pytest.raises(RuntimeError, match="injected"):
        sgd_fit_outofcore(
            logistic_loss, failing_reader, num_features=8, config=cfg,
            steps_per_dispatch=3, cache_decoded=False,
            checkpoint=ckpt, checkpoint_every_steps=2)

    resumed_state, resumed_log = sgd_fit_outofcore(
        logistic_loss, reader, num_features=8, config=cfg,
        steps_per_dispatch=3, checkpoint=ckpt, checkpoint_every_steps=2,
        resume=True)
    np.testing.assert_array_equal(resumed_state.coefficients,
                                  ref_state.coefficients)
    np.testing.assert_array_equal(resumed_log, ref_log)


def test_sgd_chunked_streaming_ell_matches_w1(tmp_path, monkeypatch):
    """The mixed ELL streaming path chunk-scans its layout-stack batches
    the same way: W=4 == W=1 bitwise through the sharded ELL update."""
    from flink_ml_tpu.models.common import sgd as sgd_mod

    rng = np.random.default_rng(7)
    n, nd, nc, d = 2000, 3, 4, 128 * 128
    dense = rng.normal(size=(n, nd)).astype(np.float32)
    cat = rng.integers(0, d, size=(n, nc)).astype(np.int32)
    y = rng.integers(0, 2, size=n).astype(np.float32)
    cache = str(tmp_path / "ell")
    w = DataCacheWriter(cache, segment_rows=1024)
    w.append({"d": dense, "c": cat, "label": y})
    w.finish()

    monkeypatch.setattr(sgd_mod, "plan_mixed_impl", lambda *a, **k: "ell")
    cfg = SGDConfig(learning_rate=0.4, max_epochs=2, tol=0)

    def fit(W):
        return sgd_mod.sgd_fit_outofcore(
            logistic_loss, lambda: DataCacheReader(cache, batch_rows=640),
            num_features=d, config=cfg, dense_key="d", indices_key="c",
            steps_per_dispatch=W)

    s1, log1 = fit(1)
    s4, log4 = fit(4)
    assert s1.planned_impl == "ell-stream"
    np.testing.assert_array_equal(s4.coefficients, s1.coefficients)
    np.testing.assert_array_equal(log4, log1)


# ------------------------------------------------------------ widedeep


def _wd_cache(tmp_path, n=500):
    rng = np.random.default_rng(5)
    dense = rng.normal(size=(n, 3)).astype(np.float32)
    cat = np.stack([rng.integers(0, 10, n),
                    rng.integers(0, 7, n)], axis=1).astype(np.int32)
    logits = dense[:, 0] + 0.3 * (cat[:, 0] % 3) - 0.5
    y = (logits > 0).astype(np.float32)
    cache = str(tmp_path / "wd")
    w = DataCacheWriter(cache, segment_rows=256)
    w.append({"denseFeatures": dense, "catFeatures": cat, "label": y})
    w.finish()
    return cache


@pytest.mark.parametrize("lazy", [False, True])
def test_widedeep_fit_outofcore_chunked_bitexact(tmp_path, lazy):
    """W in (1, 3, 8) on a 4-batch widedeep epoch (padded final chunk at
    both W=3 and W=8): params and loss logs are bit-identical — the
    masked scan freezes params AND optimizer state on dead steps."""
    from flink_ml_tpu.models.recommendation.widedeep import WideDeep

    cache = _wd_cache(tmp_path)   # 500 rows / 128 -> 4 batches

    def fit(W):
        est = (WideDeep().set_vocab_sizes([10, 7]).set_max_iter(4)
               .set_seed(0).set(WideDeep.LAZY_EMB_OPT, lazy))
        return est.fit_outofcore(
            lambda: DataCacheReader(cache, batch_rows=128),
            steps_per_dispatch=W)

    ref = fit(1)
    ref_leaves = jax.tree_util.tree_leaves(ref._params)
    for W in (3, 8):
        model = fit(W)
        leaves = jax.tree_util.tree_leaves(model._params)
        for a, b in zip(leaves, ref_leaves):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        np.testing.assert_array_equal(model._loss_log, ref._loss_log)


# ----------------------------------------------------------------- gbt


def test_gbt_outofcore_chunked_matches_w1(tmp_path):
    """Chunked GBT passes (histogram/leaf/margin) are bit-exact vs W=1
    — padding batches carry zero grad/hess and are inert in every
    additive pass."""
    from flink_ml_tpu.models.common.gbt import (GBTConfig,
                                                train_forest_outofcore)

    rng = np.random.default_rng(3)
    n, d = 3000, 6
    X = rng.normal(size=(n, d))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)

    def grad_hess(y, m):
        p = 1 / (1 + np.exp(-m))
        return p - y, np.maximum(p * (1 - p), 1e-12)

    def make_reader():
        def gen():
            for s in range(0, n, 640):
                yield {"features": X[s:s + 640], "label": y[s:s + 640]}
        return gen()

    forests = {}
    # batch_device_rows=256 -> 12 batches: W=8 runs 2 chunks (the second
    # ragged+padded) and W=3 runs 4, so the CROSS-chunk histogram
    # accumulation — the only place chunked math could diverge from
    # W=1 — is actually exercised, not just the single-chunk case
    for W in (1, 3, 8):
        cfg = GBTConfig(num_trees=3, max_depth=3, max_bins=16,
                        steps_per_dispatch=W)
        forests[W] = train_forest_outofcore(
            make_reader, grad_hess, 0.0, cfg,
            work_dir=str(tmp_path / f"gbt{W}"), batch_device_rows=256)
    for W in (3, 8):
        np.testing.assert_array_equal(forests[W].feature,
                                      forests[1].feature)
        np.testing.assert_array_equal(forests[W].threshold,
                                      forests[1].threshold)
        np.testing.assert_array_equal(forests[W].value, forests[1].value)


# ------------------------------------------------------- iterate() knob


def test_iterate_steps_per_dispatch_equivalence():
    """Hosted iterate with a termination vote + per-epoch outputs: any
    steps_per_dispatch lands on the same state, epoch count, and output
    log (the voting epoch's feedback is kept, exactly like W=1)."""
    from flink_ml_tpu.iteration import (IterationBodyResult,
                                        IterationConfig, iterate)

    def body(state, epoch, data):
        s = state + data
        return IterationBodyResult(feedback=s, outputs=s * 2,
                                   termination=s < 10)

    ref = None
    for W in (1, 3, 8):
        r = iterate(body, jnp.asarray(0.0), jnp.asarray(1.5),
                    config=IterationConfig(mode="hosted"), max_epochs=20,
                    steps_per_dispatch=W)
        got = (float(r.state), r.num_epochs,
               [float(o) for o in r.outputs],
               r.side["termination_reason"])
        if ref is None:
            ref = got
        assert got == ref, (W, got, ref)
    assert ref[1] == 7 and ref[3] == "criteria"


def test_iterate_chunked_listeners_fire_at_chunk_boundaries():
    from flink_ml_tpu.iteration import IterationConfig, iterate
    from flink_ml_tpu.iteration.body import FnListener

    seen = []
    r = iterate(lambda s, e: s + 1, jnp.asarray(0),
                config=IterationConfig(mode="hosted"), max_epochs=10,
                steps_per_dispatch=4,
                listeners=[FnListener(on_epoch=lambda e, c: seen.append(e))])
    assert int(r.state) == 10 and r.num_epochs == 10
    # chunk boundaries: epochs 0-3, 4-7, 8-9 -> last epoch of each chunk
    assert seen == [3, 7, 9]


# ------------------------------------------- prefetch pipeline health


def test_prefetch_puts_stay_concurrent_under_backpressure():
    """With the output queue full and one putter blocked emitting, the
    OTHER putters must keep completing device_puts (the flush no longer
    holds ``flush_lock`` across blocking queue puts): put count grows
    well past what a lock-serialized flush would allow while the
    consumer holds off."""
    n_batches = 12
    put_count = [0]
    lock = threading.Lock()

    def counting_put(batch, _sharding):
        with lock:
            put_count[0] += 1
        return jax.device_put(batch)

    it = prefetch_to_device(
        (np.full((2,), i, np.float32) for i in range(n_batches)),
        depth=1, workers=2, put_workers=2, put_fn=counting_put)
    first = next(it)    # consume one, then stall the consumer
    assert int(np.asarray(first)[0]) == 0
    # old behavior: the drainer blocks ON flush_lock with q full, the
    # second putter finishes ONE put then parks on the lock -> count
    # stalls around 4.  New behavior: putters keep registering and
    # pulling work; everything in fq range completes.
    deadline = time.time() + 10.0
    while put_count[0] < 6 and time.time() < deadline:
        time.sleep(0.01)
    assert put_count[0] >= 6, put_count[0]
    # stream still correct after the stall
    rest = [int(np.asarray(b)[0]) for b in it]
    assert rest == list(range(1, n_batches))


def test_prefetch_no_device_put_after_error():
    """Once an in-stream error entry is flushed, no further device_put
    is issued — the consumer will raise at that seq, so every later
    transfer would be wasted work."""
    put_seqs = []
    lock = threading.Lock()

    def counting_put(batch, _sharding):
        with lock:
            put_seqs.append(int(batch[0]))
        return jax.device_put(batch)

    def transform(i):
        if i == 0:
            raise ValueError("decode exploded at 0")
        time.sleep(0.3)   # later decodes finish AFTER the error flushes
        return np.full((2,), i, np.float32)

    with pytest.raises(ValueError, match="decode exploded"):
        list(prefetch_to_device(range(8), transform=transform,
                                workers=2, put_workers=2, depth=2,
                                put_fn=counting_put))
    # the error (seq 0) flushed before any slow decode completed, so the
    # putters saw the failed latch and skipped every transfer
    assert put_seqs == [], put_seqs


def test_prefetch_chunks_stack_pad_and_stats():
    """chunks=W yields (chunk, mask, n_valid) triples: stacked leaves,
    padded+masked final chunk, batch/chunk accounting in stats."""
    stats = PrefetchStats()
    batches = [np.full((4,), i, np.float32) for i in range(11)]
    out = list(prefetch_to_device(iter(batches), chunks=4, workers=2,
                                  put_workers=2, stats=stats))
    assert [o[2] for o in out] == [4, 4, 3]
    chunk, mask, n_valid = out[2]
    assert chunk.shape == (4, 4)
    np.testing.assert_array_equal(np.asarray(mask), [1, 1, 1, 0])
    # pad slot repeats the last real batch; masked consumers ignore it
    np.testing.assert_array_equal(np.asarray(chunk)[:3],
                                  np.stack(batches[8:]))
    assert stats.batches == 11 and stats.chunks == 3
    d = stats.as_dict()
    assert d["chunks"] == 3 and "chunk_assemble_s" in d


def test_prefetch_chunks_reject_put_fn():
    with pytest.raises(ValueError, match="chunks"):
        list(prefetch_to_device(iter([np.ones(2)]), chunks=2,
                                put_fn=lambda b, s: b))


# ------------------------------------------------- slow: chunk sweep


@pytest.mark.slow
def test_chunk_sweep_amortization(tmp_path):
    """The INGEST_SCALING.md amortization table's generator: epoch time
    and dispatch count over W in (1, 2, 4, 8, 16) on the CPU smoke
    shape.  Asserts the >= 4x dispatch-count reduction at W=8 the bench
    acceptance requires, and bit-exactness across the whole sweep."""
    cache = _lr_cache(tmp_path, "sweep", n=1 << 14, d=16, seed=9)
    cfg = SGDConfig(learning_rate=0.5, max_epochs=3, tol=0.0)
    n_batches = (1 << 14) // 512    # 32

    rows = []
    ref = None
    for W in (1, 2, 4, 8, 16):
        info = {}
        t0 = time.perf_counter()
        state, _ = sgd_fit_outofcore(
            logistic_loss, lambda: DataCacheReader(cache, batch_rows=512),
            num_features=16, config=cfg, steps_per_dispatch=W,
            cache_decoded=False, stream_info=info)
        epoch_ms = (time.perf_counter() - t0) / cfg.max_epochs * 1000
        dispatches = info["dispatches_per_epoch"][-1]
        rows.append((W, dispatches, round(epoch_ms, 1)))
        if ref is None:
            ref = state.coefficients
        else:
            np.testing.assert_array_equal(state.coefficients, ref)
    print("\nW  dispatches/epoch  epoch_ms")
    for W, disp, ms in rows:
        print(f"{W:<3}{disp:<18}{ms}")
    by_w = {w: d for w, d, _ in rows}
    assert by_w[1] == n_batches
    assert by_w[1] / by_w[8] >= 4.0
