"""Save/load sweep over (nearly) every shipped stage.

The reference's core persistence contract (``Stage.save`` + static
``load``, ``StageTest``/``PipelineTest``) applied wholesale: every
transformer must round-trip through disk with identical transform output,
and every estimator's fitted model must too.  A stage added without
working persistence fails here instead of at a user's checkpoint.

Deliberately out of scope: WideDeep (its fitted state is an optimizer-
coupled pytree exercised by tests/test_widedeep.py's own save/load) and
the pure-function parallel primitives (no Stage surface).
"""

import glob
import os
import shutil

import numpy as np
import pytest

from flink_ml_tpu import Table
from flink_ml_tpu.models import classification as C
from flink_ml_tpu.models import clustering as CL
from flink_ml_tpu.models import feature as F
from flink_ml_tpu.models import recommendation as REC
from flink_ml_tpu.models import evaluation as E
from flink_ml_tpu.models import regression as R
from flink_ml_tpu.models import stats as S

# Every factory seeds its own generator: test data is identical whether a
# case runs in the full sweep, in isolation, or on an xdist worker.

def _num_table():
    rng = np.random.default_rng(1)
    X = rng.normal(size=(48, 4))
    return Table({
        "features": X,
        "a": X[:, 0], "b": X[:, 1],
        "label": (X[:, 0] + X[:, 1] > 0).astype(np.float64),
        "multilabel": rng.integers(0, 3, size=48).astype(np.float64),
    })


def _pos_table():
    rng = np.random.default_rng(2)
    return Table({"features": np.abs(rng.normal(size=(32, 3))) + 0.5})


def _nb_table():
    rng = np.random.default_rng(3)
    return Table({
        "features": np.abs(rng.normal(size=(32, 3))),
        "multilabel": rng.integers(0, 2, size=32).astype(np.float64)})


def _tok_table():
    rng = np.random.default_rng(4)
    col = np.empty(6, object)
    vocab = ["apple", "banana", "cherry", "date"]
    for i in range(6):
        col[i] = list(rng.choice(vocab, size=4))
    return Table({"features": col})


def _text_table():
    return Table({"features": np.asarray(
        ["the quick brown fox", "lazy dogs sleep all day",
         "brown dogs eat"], dtype=object)})


def _str_table():
    return Table({"color": np.asarray(["red", "blue", "red", "green"],
                                      dtype=object),
                  "size": np.asarray(["s", "m", "l", "m"], dtype=object)})


def _binary_table():
    rng = np.random.default_rng(5)
    X = (rng.random((24, 16)) < 0.4).astype(np.float64)
    X[X.sum(1) == 0, 0] = 1.0
    return Table({"features": X})


def _rating_table():
    rng = np.random.default_rng(6)
    return Table({
        "user": np.repeat(np.arange(6), 4),
        "item": np.tile(np.arange(4), 6),
        "rating": rng.uniform(1, 5, size=24),
    })


def _tf_table():
    rng = np.random.default_rng(7)
    return Table({"features": (rng.random((12, 8)) < 0.5)
                  .astype(np.float64) * rng.integers(1, 4, (12, 8))})


def _idx_table():
    return Table({"features": np.asarray([0.0, 1.0, 2.0, 1.0])})


# (stage factory, input-table factory) — transformers: save/load the STAGE
# and compare transform output before/after.
TRANSFORMER_CASES = [
    ("Binarizer", lambda: F.Binarizer().set_threshold(0.1), _num_table),
    ("Bucketizer", lambda: (F.Bucketizer().set_splits(-10.0, 0.0, 10.0)
                            .set_handle_invalid("clip")), _num_table),
    ("Normalizer", lambda: F.Normalizer().set_p(2.0), _num_table),
    ("PolynomialExpansion", lambda: F.PolynomialExpansion().set_degree(2),
     _num_table),
    ("DCT", lambda: F.DCT(), _num_table),
    ("ElementwiseProduct",
     lambda: F.ElementwiseProduct().set_scaling_vec(1.0, 2.0, 3.0, 4.0),
     _num_table),
    ("VectorSlicer", lambda: F.VectorSlicer().set_indices(2, 0), _num_table),
    ("Interaction", lambda: F.Interaction().set_input_cols("a", "b"),
     _num_table),
    ("VectorAssembler",
     lambda: F.VectorAssembler().set_input_cols("a", "b")
     .set_features_col("out"), _num_table),
    ("HashingTF", lambda: F.HashingTF().set_num_features(32), _tok_table),
    ("Tokenizer", lambda: F.Tokenizer(), _text_table),
    ("RegexTokenizer", lambda: F.RegexTokenizer().set_pattern(r"\s+"),
     _text_table),
    ("NGram", lambda: F.NGram().set_n(2), _tok_table),
    ("StopWordsRemover", lambda: F.StopWordsRemover(), _tok_table),
    ("FeatureHasher",
     lambda: F.FeatureHasher().set_input_cols("color", "size")
     .set_num_features(64), _str_table),
    ("SQLTransformer",
     lambda: F.SQLTransformer().set_statement(
         "SELECT a + b AS s FROM __THIS__"), _num_table),
    ("IndexToString",
     lambda: F.IndexToString().set_labels(["red", "green", "blue"]),
     _idx_table),
    # AlgoOperators persist params-only; their transform must survive too
    ("AgglomerativeClustering",
     lambda: CL.AgglomerativeClustering().set_num_clusters(2), _num_table),
    ("Swing",
     lambda: REC.Swing().set_min_user_behavior(1).set_k(2), _rating_table),
]

# (estimator factory, input-table factory, model class) — fit, save/load
# the MODEL, compare transform output.
ESTIMATOR_CASES = [
    ("Imputer", lambda: F.Imputer(), _num_table, F.ImputerModel),
    ("PCA", lambda: F.PCA().set_k(2), _num_table, F.PCAModel),
    ("KBinsDiscretizer", lambda: F.KBinsDiscretizer().set_num_bins(3),
     _num_table, F.KBinsDiscretizerModel),
    ("VectorIndexer", lambda: F.VectorIndexer().set_max_categories(50),
     _num_table, F.VectorIndexerModel),
    ("StandardScaler", lambda: F.StandardScaler().set_output_col("o"),
     _num_table, F.StandardScalerModel),
    ("MinMaxScaler", lambda: F.MinMaxScaler().set_output_col("o"),
     _num_table, F.MinMaxScalerModel),
    ("MaxAbsScaler", lambda: F.MaxAbsScaler().set_output_col("o"),
     _num_table, F.MaxAbsScalerModel),
    ("RobustScaler", lambda: F.RobustScaler().set_output_col("o"),
     _num_table, F.RobustScalerModel),
    ("StringIndexer",
     lambda: F.StringIndexer().set_input_cols("color")
     .set_output_cols("color_idx"), _str_table, F.StringIndexerModel),
    ("CountVectorizer", lambda: F.CountVectorizer(), _tok_table,
     F.CountVectorizerModel),
    ("VarianceThresholdSelector", lambda: F.VarianceThresholdSelector(),
     _num_table, F.VarianceThresholdSelectorModel),
    ("UnivariateFeatureSelector",
     lambda: (F.UnivariateFeatureSelector().set_feature_type("continuous")
              .set_label_type("categorical").set_selection_threshold(2)),
     _num_table, F.UnivariateFeatureSelectorModel),
    ("MinHashLSH", lambda: F.MinHashLSH().set_num_hash_tables(2),
     _binary_table, F.MinHashLSHModel),
    ("LogisticRegression",
     lambda: C.LogisticRegression().set_max_iter(3), _num_table,
     C.LogisticRegressionModel),
    ("LinearSVC", lambda: C.LinearSVC().set_max_iter(3), _num_table,
     C.LinearSVCModel),
    ("LinearRegression", lambda: R.LinearRegression().set_max_iter(3),
     _num_table, R.LinearRegressionModel),
    ("SoftmaxRegression",
     lambda: C.SoftmaxRegression().set_max_iter(3)
     .set_label_col("multilabel"), _num_table, C.SoftmaxRegressionModel),
    ("NaiveBayes", lambda: C.NaiveBayes().set_label_col("multilabel"),
     _nb_table, C.NaiveBayesModel),
    ("KNNClassifier", lambda: C.KNNClassifier().set_k(3), _num_table,
     C.KNNClassifierModel),
    ("GBTClassifier",
     lambda: C.GBTClassifier().set_max_iter(3).set_max_depth(2),
     _num_table, C.GBTClassifierModel),
    ("GBTRegressor",
     lambda: R.GBTRegressor().set_max_iter(3).set_max_depth(2),
     _num_table, R.GBTRegressorModel),
    ("KMeans", lambda: CL.KMeans().set_k(2).set_max_iter(3), _num_table,
     CL.KMeansModel),
    ("ALS", lambda: REC.ALS().set_rank(2).set_max_iter(2), _rating_table,
     REC.ALSModel),
    ("IDF", lambda: F.IDF().set_output_col("o"), _tf_table, F.IDFModel),
    ("OneHotEncoder",
     lambda: F.OneHotEncoder().set_input_cols("features")
     .set_output_cols("onehot"), _idx_table, F.OneHotEncoderModel),
    ("OnlineStandardScaler",
     lambda: F.OnlineStandardScaler().set_output_col("o"), _num_table,
     F.OnlineStandardScalerModel),
    ("OnlineKMeans",
     lambda: CL.OnlineKMeans().set_k(2), _num_table, CL.OnlineKMeansModel),
    ("OnlineLogisticRegression",
     lambda: C.OnlineLogisticRegression().set_global_batch_size(16),
     _num_table, C.OnlineLogisticRegressionModel),
]


def _tables_equal(t1: Table, t2: Table):
    assert t1.column_names == t2.column_names
    for name in t1.column_names:
        c1, c2 = t1[name], t2[name]
        if c1.dtype == object:
            assert [list(np.ravel(np.asarray(r, dtype=object)))
                    for r in c1] == \
                   [list(np.ravel(np.asarray(r, dtype=object)))
                    for r in c2], name
        elif np.issubdtype(c1.dtype, np.number):
            np.testing.assert_allclose(
                c1.astype(np.float64), c2.astype(np.float64),
                atol=1e-6, err_msg=name, equal_nan=True)
        else:
            np.testing.assert_array_equal(c1, c2, err_msg=name)


@pytest.mark.parametrize("name,factory,table_fn", TRANSFORMER_CASES,
                         ids=[c[0] for c in TRANSFORMER_CASES])
def test_transformer_save_load_roundtrip(name, factory, table_fn, tmp_path):
    stage = factory()
    table = table_fn()
    before = stage.transform(table)[0]
    path = str(tmp_path / name)
    stage.save(path)
    loaded = type(stage).load(path)
    after = loaded.transform(table)[0]
    _tables_equal(before, after)


@pytest.mark.parametrize("name,factory,table_fn,model_cls",
                         ESTIMATOR_CASES,
                         ids=[c[0] for c in ESTIMATOR_CASES])
def test_estimator_model_save_load_roundtrip(name, factory, table_fn,
                                             model_cls, tmp_path):
    est = factory()
    table = table_fn()
    model = est.fit(table)
    before = model.transform(table)[0]
    path = str(tmp_path / name)
    model.save(path)
    loaded = model_cls.load(path)
    after = loaded.transform(table)[0]
    _tables_equal(before, after)

    # the estimator itself round-trips its params (NaN-safe comparison:
    # Imputer's default missingValue is NaN)
    est_path = str(tmp_path / f"{name}_est")
    est.save(est_path)
    reloaded = type(est).load(est_path)
    orig = {p.name: v for p, v in est.param_items()}
    back = {p.name: v for p, v in reloaded.param_items()}
    assert orig.keys() == back.keys()
    for key, v1 in orig.items():
        v2 = back[key]
        if isinstance(v1, float) and isinstance(v2, float) \
                and np.isnan(v1) and np.isnan(v2):
            continue
        assert v1 == v2, (key, v1, v2)


# -- AlgoOperators with analytic outputs: save/load the stage and the
#    transform result must be identical (params-only persistence)

def _labeled_table():
    rng = np.random.default_rng(8)
    X = rng.normal(size=(40, 3))
    return Table({"features": X,
                  "label": rng.integers(0, 2, size=40).astype(np.float64),
                  "clabel": (X[:, 0] + rng.normal(size=40))})


def _ranked_table():
    p = np.empty(3, object)
    r = np.empty(3, object)
    for i in range(3):
        p[i] = ["a", "b", "c"]
        r[i] = ["a", "x"]
    return Table({"prediction": p, "label": r})


def _cat_table():
    rng = np.random.default_rng(9)
    return Table({"features": rng.integers(0, 3, size=(40, 2))
                  .astype(np.float64),
                  "label": rng.integers(0, 2, size=40)})


def _scored_table():
    rng = np.random.default_rng(11)
    y = rng.integers(0, 3, size=40).astype(np.float64)
    return Table({
        "features": rng.normal(size=(40, 2)),
        "label": y,
        "prediction": np.where(rng.random(40) < 0.8, y,
                               (y + 1) % 3).astype(np.float64),
        "rawPrediction": rng.random(40),
    })


ALGO_CASES = [
    ("ChiSqTest", lambda: S.ChiSqTest(), _cat_table),
    ("ANOVATest", lambda: S.ANOVATest(), _labeled_table),
    ("FValueTest", lambda: S.FValueTest().set_label_col("clabel"),
     _labeled_table),
    ("RankingEvaluator", lambda: E.RankingEvaluator().set_k(2),
     _ranked_table),
    ("BinaryClassificationEvaluator",
     lambda: E.BinaryClassificationEvaluator().set_metrics(
         "areaUnderROC", "accuracy"),
     lambda: Table({"label": (np.random.default_rng(12)
                              .random(40) < 0.5).astype(np.float64),
                    "rawPrediction": np.random.default_rng(13)
                    .random(40)})),
    ("MulticlassClassificationEvaluator",
     lambda: E.MulticlassClassificationEvaluator(), _scored_table),
    ("RegressionEvaluator",
     lambda: E.RegressionEvaluator(), _scored_table),
    ("ClusteringEvaluator",
     lambda: E.ClusteringEvaluator(), _scored_table),
]


@pytest.mark.parametrize("name,factory,table_fn", ALGO_CASES,
                         ids=[c[0] for c in ALGO_CASES])
def test_algo_operator_save_load_roundtrip(name, factory, table_fn,
                                           tmp_path):
    op = factory()
    table = table_fn()
    before = op.transform(table)[0]
    path = str(tmp_path / name)
    op.save(path)
    loaded = type(op).load(path)
    _tables_equal(before, loaded.transform(table)[0])


# -- corruption sweep (robustness PR): a damaged save must raise a
#    DIAGNOSABLE IOError naming the file — never silently-wrong params.
#    One representative estimator per stage family (the data layouts all
#    funnel through persist.load_model_arrays/load_metadata, so one case
#    per family covers the family's load path).

_CORRUPTION_FAMILIES = [
    c for c in ESTIMATOR_CASES
    if c[0] in ("LogisticRegression",   # linear family
                "KMeans",               # clustering
                "GBTClassifier",        # tree ensembles
                "StandardScaler",       # feature/scaler
                "StringIndexer",        # string-domain
                "PCA",                  # decomposition
                "ALS")                  # recommendation
]

_CORRUPTIONS = ["truncate_npz", "flip_npz", "missing_metadata",
                "truncated_metadata"]

_fitted_saves = {}   # name -> pristine saved dir (fit once per family)


def _pristine_save(name, factory, table_fn, tmp_path_factory):
    if name not in _fitted_saves:
        base = tmp_path_factory.mktemp(f"corrupt_{name}")
        model = factory().fit(table_fn())
        path = str(base / "model")
        model.save(path)
        _fitted_saves[name] = path
    return _fitted_saves[name]


def _apply_corruption(path, mode):
    from flink_ml_tpu.robustness import corrupt_file

    if mode in ("truncate_npz", "flip_npz"):
        npzs = sorted(glob.glob(os.path.join(path, "data", "*.npz")))
        assert npzs, f"{path} has no model data to corrupt"
        corrupt_file(npzs[0],
                     mode="torn" if mode == "truncate_npz" else "flip")
    elif mode == "missing_metadata":
        os.unlink(os.path.join(path, "metadata"))
    elif mode == "truncated_metadata":
        meta = os.path.join(path, "metadata")
        data = open(meta, "rb").read()
        open(meta, "wb").write(data[:len(data) // 2])
    else:  # pragma: no cover
        raise AssertionError(mode)


@pytest.mark.parametrize("mode", _CORRUPTIONS)
@pytest.mark.parametrize("name,factory,table_fn,model_cls",
                         _CORRUPTION_FAMILIES,
                         ids=[c[0] for c in _CORRUPTION_FAMILIES])
def test_corrupted_save_raises_diagnosable_ioerror(
        name, factory, table_fn, model_cls, mode, tmp_path,
        tmp_path_factory):
    pristine = _pristine_save(name, factory, table_fn, tmp_path_factory)
    path = str(tmp_path / "model")
    shutil.copytree(pristine, path)
    _apply_corruption(path, mode)
    with pytest.raises(IOError) as ei:
        model_cls.load(path)
    # diagnosable: the error names the offending path (or file inside it)
    assert path.split(os.sep)[-2] in str(ei.value) or path in str(ei.value)
