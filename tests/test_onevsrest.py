"""OneVsRest meta-classifier: K binary fits -> argmax prediction,
original label values preserved, persistence round-trip, error probes."""

import numpy as np
import pytest

from flink_ml_tpu import Table
from flink_ml_tpu.models.classification import (
    LinearSVC,
    LogisticRegression,
    OneVsRest,
    OneVsRestModel,
)


def _data(n=600, seed=0):
    rng = np.random.default_rng(seed)
    centers = np.array([[2.0, 0.0], [-2.0, 1.0], [0.0, -2.5]])
    y = rng.integers(0, 3, size=n)
    X = centers[y] + 0.4 * rng.normal(size=(n, 2))
    # non-contiguous label VALUES to prove inventory mapping
    labels = np.array([10.0, 20.0, 30.0])[y]
    return Table({"features": X, "label": labels}), labels


def _base_lr():
    return (LogisticRegression().set_max_iter(30).set_learning_rate(0.5)
            .set_global_batch_size(128)
            .set_raw_prediction_col("rawPrediction"))


def test_three_class_accuracy_and_label_values():
    t, labels = _data()
    model = OneVsRest(_base_lr()).fit(t)
    out = model.transform(t)[0]
    pred = np.asarray(out[model.get_prediction_col()]).ravel()
    assert set(np.unique(pred)) <= {10.0, 20.0, 30.0}
    assert (pred == labels).mean() > 0.93
    raw = np.asarray(out[model.get_raw_prediction_col()])
    assert raw.shape == (len(labels), 3)


def test_works_with_linearsvc_base():
    t, labels = _data(seed=1)
    base = (LinearSVC().set_max_iter(30).set_learning_rate(0.3)
            .set_global_batch_size(128)
            .set_raw_prediction_col("rawPrediction"))
    model = OneVsRest(base).fit(t)
    pred = np.asarray(model.transform(t)[0]
                      [model.get_prediction_col()]).ravel()
    assert (pred == labels).mean() > 0.9


def test_save_load_round_trip(tmp_path):
    t, _ = _data(n=300)
    model = OneVsRest(_base_lr()).fit(t)
    path = str(tmp_path / "ovr")
    model.save(path)
    loaded = OneVsRestModel.load(path)
    np.testing.assert_array_equal(
        np.asarray(loaded.transform(t)[0][model.get_prediction_col()]),
        np.asarray(model.transform(t)[0][model.get_prediction_col()]))


def test_requires_classifier_and_raw_col():
    t, _ = _data(n=60)
    with pytest.raises(ValueError, match="set_classifier"):
        OneVsRest().fit(t)
    base = LogisticRegression().set_raw_prediction_col("")
    with pytest.raises(ValueError, match="rawPredictionCol"):
        OneVsRest(base).fit(t)


def test_single_class_rejected():
    t = Table({"features": np.zeros((10, 2)), "label": np.ones(10)})
    with pytest.raises(ValueError, match=">= 2 label values"):
        OneVsRest(_base_lr()).fit(t)


def test_estimator_save_load_keeps_classifier(tmp_path):
    t, labels = _data(n=200)
    est = OneVsRest(_base_lr())
    path = str(tmp_path / "est")
    est.save(path)
    reloaded = OneVsRest.load(path)
    model = reloaded.fit(t)
    pred = np.asarray(model.transform(t)[0]
                      [model.get_prediction_col()]).ravel()
    assert (pred == labels).mean() > 0.9


def test_multiclass_base_rejected_cleanly():
    from flink_ml_tpu.models.classification import SoftmaxRegression

    t, _ = _data(n=90)
    base = (SoftmaxRegression().set_max_iter(2)
            .set_raw_prediction_col("rawPrediction"))
    with pytest.raises(ValueError, match="ONE score per row"):
        OneVsRest(base).fit(t).transform(t)
