"""Examples must keep running — they are the migration surface a reference
user reads first, and nothing else executes them.

Each example is a self-contained script (it inserts the repo root into
``sys.path`` itself) run here as a subprocess on the virtual 8-device CPU
mesh.  The fast ones run in the default suite; the slow ones (real
training work, covered functionally by unit tests of the same surfaces)
run only with ``FLINK_ML_TPU_RUN_SLOW_EXAMPLES=1``.
"""

import os
import subprocess
import sys

import pytest

_EXAMPLES_DIR = os.path.join(os.path.dirname(__file__), "..", "examples")

# measured on the 1-core bench host (CPU mesh): fast <= ~12s each
_FAST = [
    "kmeans_example.py",
    "pipeline_example.py",
    "pod_sharded_lr_example.py",
    "streaming_ftrl_example.py",
    "text_pipeline_example.py",
    "criteo_e2e_pipeline_example.py",
]
_SLOW = [
    "als_example.py",
    "criteo_mixed_lr_example.py",
    "distributed_example.py",
    "graph_example.py",
    "iteration_example.py",
    "model_selection_example.py",
    "recommender_example.py",
    "widedeep_ctr_example.py",     # ~20s: 12 streamed epochs
]

_RUN_SLOW = os.environ.get("FLINK_ML_TPU_RUN_SLOW_EXAMPLES") == "1"


def _run(name: str) -> None:
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        flags = (flags + " --xla_force_host_platform_device_count=8").strip()
    env = dict(os.environ, JAX_PLATFORMS="cpu", XLA_FLAGS=flags)
    # Launch through a live-config CPU override, not the env var alone:
    # this environment's sitecustomize consumes JAX_PLATFORMS at
    # interpreter startup, and with the TPU relay down the axon
    # backend's first device use blocks for minutes (the r3 outage
    # failure mode) — the config update runs before any device use, so
    # the example tier stays green in any relay weather.
    boot = ("import jax; jax.config.update('jax_platforms', 'cpu'); "
            "import runpy, sys; sys.argv = sys.argv[1:]; "
            "runpy.run_path(sys.argv[0], run_name='__main__')")
    proc = subprocess.run(
        [sys.executable, "-c", boot, os.path.join(_EXAMPLES_DIR, name)],
        env=env, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, (
        f"{name} failed (rc={proc.returncode}):\n"
        f"--- stdout ---\n{proc.stdout[-2000:]}\n"
        f"--- stderr ---\n{proc.stderr[-2000:]}")


def test_example_inventory_complete():
    """Every example on disk is classified — a new example that is not
    added to _FAST or _SLOW fails here instead of silently rotting."""
    on_disk = sorted(f for f in os.listdir(_EXAMPLES_DIR)
                     if f.endswith(".py"))
    assert on_disk == sorted(_FAST + _SLOW)


@pytest.mark.parametrize("name", _FAST)
def test_fast_example(name):
    _run(name)


@pytest.mark.parametrize("name", _SLOW)
@pytest.mark.skipif(not _RUN_SLOW,
                    reason="slow example; set "
                           "FLINK_ML_TPU_RUN_SLOW_EXAMPLES=1")
def test_slow_example(name):
    _run(name)
