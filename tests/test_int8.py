"""Int8 serving path tests (ISSUE 18): quantized-precision plumbing
through ``make_servable`` / the scheduler, the accuracy envelope on
served bits, per-generation bit-stability, the embedding-row cache's
int8 pools (codes + per-row scales — half the bytes, twice the resident
rows at the same device budget), warm-up / admission precision
attribution, and the compilation-free admission contract for int8
tenants (zero new lowerings for tenant N+1 of a served int8 schema).

Contract under test (ARCHITECTURE.md "Int8 serving"): calibration is
captured at publish/bind time from the published params themselves,
re-derived on every rebind; within a generation repeat predicts are
bit-identical; agreement with f32 is gated at the decision/rank
envelope, never bitwise."""

import numpy as np
import pytest

from flink_ml_tpu import Table
from flink_ml_tpu.serving import (
    SLO_BULK,
    SLO_INTERACTIVE,
    SLO_STANDARD,
    EmbeddingRowCache,
    SharedScheduler,
    make_servable,
)

ENVELOPE = 0.99


# -- fixtures ----------------------------------------------------------------

def _lr_table(n=64, d=8, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d))
    y = (X[:, 0] + 0.3 * rng.normal(size=n) > 0).astype(np.int64)
    return Table({"features": X, "label": y})


def _fit_lr(seed=0):
    from flink_ml_tpu.models.classification.logisticregression import (
        LogisticRegression)

    return LogisticRegression().set_max_iter(3).fit(_lr_table(seed=seed))


def _feats(n=256, seed=1):
    return _lr_table(n=n, seed=seed).drop("label")


def _widedeep(seed=6, vocab=(50, 30), n=128):
    from flink_ml_tpu.models.recommendation.widedeep import WideDeep

    rng = np.random.default_rng(seed)
    dense = rng.normal(size=(n, 4)).astype(np.float32)
    cat = np.stack([rng.integers(0, v, size=n) for v in vocab],
                   axis=1).astype(np.int32)
    label = (cat[:, 0] > vocab[0] // 2).astype(np.int64)
    t = Table({"denseFeatures": dense, "catFeatures": cat, "label": label})
    return WideDeep().set_vocab_sizes(list(vocab)).set_max_iter(2).fit(t), t


def _agreement(a, b):
    a, b = np.asarray(a).ravel(), np.asarray(b).ravel()
    return float(np.mean(a == b))


# -- servable precision plumbing ---------------------------------------------

def test_int8_linear_servable_envelope_and_bitstable():
    model = _fit_lr()
    feats = _feats(n=256)
    sv8 = make_servable(model, feats.take(2), max_batch_rows=64,
                        precision="int8").warm_up()
    svf = make_servable(model, feats.take(2), max_batch_rows=64).warm_up()
    assert sv8.precision == "int8" and svf.precision == "f32"
    out8 = sv8.predict(feats)
    outf = svf.predict(feats)
    # decisions agree to the envelope, never required bitwise
    assert _agreement(out8["prediction"], outf["prediction"]) >= ENVELOPE
    # within a generation the quantized program is bit-stable
    again = sv8.predict(feats)
    np.testing.assert_array_equal(
        np.asarray(again["rawPrediction"]),
        np.asarray(out8["rawPrediction"]))


def test_int8_kmeans_servable_envelope():
    from flink_ml_tpu.models.clustering.kmeans import KMeans

    rng = np.random.default_rng(4)
    centers = rng.normal(scale=6.0, size=(5, 6))
    X = np.concatenate(
        [c + rng.normal(size=(40, 6)) for c in centers])
    t = Table({"features": X})
    model = KMeans().set_k(5).set_max_iter(5).set_seed(1).fit(t)
    sv8 = make_servable(model, t.take(2), max_batch_rows=64,
                        precision="int8").warm_up()
    svf = make_servable(model, t.take(2), max_batch_rows=64).warm_up()
    assert _agreement(sv8.predict(t)["prediction"],
                      svf.predict(t)["prediction"]) >= ENVELOPE


def test_int8_widedeep_servable_envelope():
    model, t = _widedeep()
    feats = t.drop("label")
    sv8 = make_servable(model, feats.take(2), max_batch_rows=64,
                        precision="int8").warm_up()
    svf = make_servable(model, feats.take(2), max_batch_rows=64).warm_up()
    assert _agreement(sv8.predict(feats)["prediction"],
                      svf.predict(feats)["prediction"]) >= ENVELOPE


def test_precision_refused_without_a_quantized_seam():
    """Families with no int8 backend refuse loudly at construction —
    silently serving f32 under precision='int8' would fake the
    models-per-chip ledger."""
    from flink_ml_tpu.models.classification.gbtclassifier import (
        GBTClassifier)

    t = _lr_table(n=96, seed=4)
    gbt = (GBTClassifier().set_max_iter(2).set_max_depth(2)
           .set_max_bins(16).fit(t))
    with pytest.raises(TypeError, match="precision"):
        make_servable(gbt, t.drop("label").take(2), precision="int8")
    with pytest.raises(TypeError, match="precision"):
        make_servable(_fit_lr(), _feats().take(2), precision="fp8")


def test_int8_requires_the_registry_dispatched_plan():
    """A linear config whose transform_kernel is unported (returns
    None) cannot serve int8 — the quantized path exists only through
    the registry's "int8" backends, never a silent f32 fallback."""
    model = _fit_lr()
    model.transform_kernel = lambda schema: None
    with pytest.raises(TypeError, match="int8"):
        make_servable(model, _feats().take(2), precision="int8")


def test_warmup_report_attributes_precision_per_bucket():
    model = _fit_lr()
    sv8 = make_servable(model, _feats().take(2), max_batch_rows=32,
                        precision="int8").warm_up()
    rep = sv8.warmup_report
    assert rep["precision"] == "int8"
    assert rep["buckets"]
    assert all(b["precision"] == "int8" for b in rep["buckets"].values())
    svf = make_servable(model, _feats().take(2), max_batch_rows=32)
    repf = svf.warm_up().warmup_report
    assert repf["precision"] == "f32"
    assert all(b["precision"] == "f32" for b in repf["buckets"].values())


# -- embedding-row cache int8 pools ------------------------------------------

def test_embcache_int8_pools_double_resident_rows_at_equal_bytes():
    """THE footprint dividend: codes+scales pools cost about half the
    f32 pool bytes per block, so the same device budget holds ~2x the
    resident rows."""
    rng = np.random.default_rng(5)
    V, E, B = 256, 16, 8
    emb = rng.normal(size=(V, E)).astype(np.float32)
    cache_f = EmbeddingRowCache({"emb": emb}, block_rows=B,
                                capacity_blocks=8)
    cache_q = EmbeddingRowCache({"emb": emb}, block_rows=B,
                                capacity_blocks=8, precision="int8")
    assert cache_q.snapshot()["precision"] == "int8"
    budget = cache_f.pool_bytes
    per_block_q = cache_q.pool_bytes // 8
    assert cache_q.pool_bytes * 2 <= budget + 8 * B * 4  # ~half + scales
    cap_q = budget // per_block_q
    assert cap_q >= 2 * 8, (
        f"int8 pools hold {cap_q} blocks in the f32 budget of 8 — "
        "expected at least 2x resident rows at equal pool bytes")
    cache_q2 = EmbeddingRowCache({"emb": emb}, block_rows=B,
                                 capacity_blocks=int(cap_q),
                                 precision="int8")
    assert cache_q2.pool_bytes <= budget
    assert cache_q2.capacity_blocks * B >= 2 * 8 * B


def test_embcache_int8_cached_and_bypass_paths_agree_bitwise():
    """Gather-then-dequantize on device and host-side dequantize in the
    bypass path are the same f32 multiply — one quantized truth, bit
    equal either way."""
    rng = np.random.default_rng(6)
    V, E = 64, 6
    emb = rng.normal(size=(V, E)).astype(np.float32)
    wc = rng.normal(size=(V,)).astype(np.float32)
    cache = EmbeddingRowCache({"emb": emb, "wide_cat": wc}, block_rows=8,
                              capacity_blocks=2, precision="int8")
    ids = np.array([[0, 9], [1, 8]])
    cached = np.asarray(cache.lookup(ids)["emb"])
    big = np.array([[0, 9], [1, 8], [16, 24], [32, 40], [48, 56]])
    out = cache.lookup(big)                      # exceeds capacity
    assert cache.bypasses == 1
    np.testing.assert_array_equal(np.asarray(out["emb"])[:2], cached)
    # 1-d scalar-row tables never quantize: wide_cat rows stay exact
    np.testing.assert_array_equal(np.asarray(out["wide_cat"]), wc[big])


def test_cached_widedeep_int8_envelope_and_bitstable():
    model, t = _widedeep(seed=9)
    feats = t.drop("label")
    sv8 = make_servable(model, feats.take(2), emb_cache=True,
                        cache_block_rows=8, cache_capacity_blocks=6,
                        max_batch_rows=64, precision="int8").warm_up()
    assert sv8.precision == "int8"
    assert sv8.cache.snapshot()["precision"] == "int8"
    offline = model.transform(feats)[0]
    served = sv8.predict(feats)
    assert _agreement(served["prediction"],
                      offline["prediction"]) >= ENVELOPE
    again = sv8.predict(feats)
    np.testing.assert_array_equal(np.asarray(again["rawPrediction"]),
                                  np.asarray(served["rawPrediction"]))


# -- scheduler: precision attribution + admission ----------------------------

def test_scheduler_precision_gauges_and_shared_servable_inheritance():
    s = SharedScheduler(max_batch_rows=64, max_wait_ms=0.5,
                        queue_capacity=1024)
    feats = _feats(seed=3)
    try:
        s.add_tenant("quant", _fit_lr(seed=1), feats.take(2),
                     slo=SLO_INTERACTIVE, precision="int8")
        s.add_tenant("plain", _fit_lr(seed=2), feats.take(2),
                     slo=SLO_STANDARD)
        s.add_tenant("shadow", servable_of="quant", slo=SLO_BULK)
        assert s.tenant("quant").precision == "int8"
        assert s.tenant("plain").precision == "f32"
        # a shared-servable tenant inherits the sharing tenant's
        # precision — same program, same codes
        assert s.tenant("shadow").precision == "int8"
        for name, want in (("quant", "int8"), ("plain", "f32"),
                           ("shadow", "int8")):
            gauge = s.tenant(name).metrics.group.gauge("precision")
            assert gauge.value == want
        rep = s.tenant("quant").admission_report
        assert rep is not None and rep["precision"] == "int8"
        assert all(b["precision"] == "int8"
                   for b in rep["buckets"].values())
        s._refresh_gauges()
        assert s._int8_tenants.value == 2
    finally:
        s.close()


def test_second_int8_tenant_admits_with_zero_new_lowerings():
    """The registry dividend survives quantization: tenant N+1 of an
    already-served int8 schema warms entirely out of the shared caches
    — zero new XLA lowerings, and the admission report says so at
    precision int8."""
    from jax._src import test_util as jtu

    feats = _feats(seed=7)
    s = SharedScheduler(max_batch_rows=64, max_wait_ms=0.5,
                        queue_capacity=1024)
    s.add_tenant("q1", _fit_lr(seed=1), feats.take(2),
                 slo=SLO_INTERACTIVE, precision="int8")
    s.start()
    try:
        for n in (1, 2, 64):            # settle wave, as in the f32 test
            s.predict("q1", feats.take(n))
        model2 = _fit_lr(seed=2)
        with jtu.count_jit_and_pmap_lowerings() as count:
            tenant = s.add_tenant("q2", model2, feats.take(2),
                                  slo=SLO_BULK, precision="int8")
            out = s.predict("q2", feats.take(5))
        assert count[0] == 0, (
            f"{count[0]} new lowerings admitting a same-schema int8 "
            "tenant — quantized admission must be placement only")
        report = tenant.admission_report
        assert report is not None and report["compiled"] == 0
        assert report["precision"] == "int8"
        # the decisions still come from the quantized program
        sv = make_servable(model2, feats.take(2), max_batch_rows=64,
                           precision="int8").warm_up()
        np.testing.assert_array_equal(
            np.asarray(out["rawPrediction"]),
            np.asarray(sv.predict(feats.take(5))["rawPrediction"]))
    finally:
        s.close()
