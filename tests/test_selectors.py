"""ANOVATest / VarianceThresholdSelector / UnivariateFeatureSelector."""

import numpy as np
import pytest

from flink_ml_tpu import Table
from flink_ml_tpu.models.feature import (
    UnivariateFeatureSelector,
    UnivariateFeatureSelectorModel,
    VarianceThresholdSelector,
    VarianceThresholdSelectorModel,
)
from flink_ml_tpu.models.stats import ANOVATest


def _t(X, y=None):
    cols = {"features": np.asarray(X, np.float64)}
    if y is not None:
        cols["label"] = np.asarray(y)
    return Table(cols)


def test_anova_hand_computed_two_groups():
    # groups {1,2,3} vs {5,6,7}: SSB = 24, SSW = 4, F = 24 / (4/4) = 24
    X = np.array([[1.0], [2.0], [3.0], [5.0], [6.0], [7.0]])
    y = np.array([0, 0, 0, 1, 1, 1])
    out = ANOVATest().transform(_t(X, y))[0]
    np.testing.assert_allclose(np.asarray(out["fValue"])[0], 24.0,
                               rtol=1e-5)
    assert np.asarray(out["degreesOfFreedom"])[0] == 5  # (k-1)+(n-k) = 1+4
    # p-value for F(1,4)=24: 1 - CDF = 0.0080499 (F survival function)
    np.testing.assert_allclose(np.asarray(out["pValue"])[0], 0.0080499,
                               rtol=1e-4)


def test_anova_unrelated_feature_high_p():
    rng = np.random.default_rng(0)
    X = np.column_stack([rng.normal(size=300),
                         rng.normal(size=300)])
    y = np.repeat([0, 1, 2], 100)
    X[:, 0] += y * 3.0        # strongly separated
    out = ANOVATest().transform(_t(X, y))[0]
    p = np.asarray(out["pValue"])
    assert p[0] < 1e-10 and p[1] > 0.01


def test_variance_threshold_selector(tmp_path):
    X = np.array([[1.0, 5.0, 0.1], [2.0, 5.0, 0.2], [3.0, 5.0, 0.1],
                  [4.0, 5.0, 0.2]])
    model = VarianceThresholdSelector().set_variance_threshold(0.05).fit(_t(X))
    out = model.transform(_t(X))[0]
    # col1 variance 0 and col2 variance ~0.0033 both drop; col0 stays
    np.testing.assert_array_equal(np.asarray(out["output"]), X[:, :1])

    path = str(tmp_path / "vts")
    model.save(path)
    loaded = VarianceThresholdSelectorModel.load(path)
    np.testing.assert_array_equal(
        np.asarray(loaded.transform(_t(X))[0]["output"]), X[:, :1])


def test_variance_threshold_default_keeps_nonconstant():
    X = np.array([[1.0, 7.0], [2.0, 7.0]])
    model = VarianceThresholdSelector().fit(_t(X))
    out = model.transform(_t(X))[0]
    np.testing.assert_array_equal(np.asarray(out["output"]), X[:, :1])


def _make_classif_data():
    rng = np.random.default_rng(1)
    n = 400
    y = rng.integers(0, 2, size=n)
    X = rng.normal(size=(n, 6))
    X[:, 1] += y * 2.0          # informative
    X[:, 4] += y * 1.5          # informative
    return X, y


def test_univariate_anova_top_k():
    X, y = _make_classif_data()
    sel = (UnivariateFeatureSelector()
           .set_feature_type("continuous").set_label_type("categorical")
           .set_selection_mode("numTopFeatures").set_selection_threshold(2))
    model = sel.fit(_t(X, y))
    np.testing.assert_array_equal(model._indices, [1, 4])
    out = model.transform(_t(X))[0]
    np.testing.assert_array_equal(np.asarray(out["output"]), X[:, [1, 4]])


def test_univariate_fpr_fwe_fdr_modes():
    X, y = _make_classif_data()
    base = (UnivariateFeatureSelector()
            .set_feature_type("continuous").set_label_type("categorical"))
    for mode in ["fpr", "fdr", "fwe"]:
        model = (base.set_selection_mode(mode)
                 .set_selection_threshold(0.01).fit(_t(X, y)))
        np.testing.assert_array_equal(model._indices, [1, 4]), mode


def test_univariate_percentile_mode():
    X, y = _make_classif_data()
    model = (UnivariateFeatureSelector()
             .set_feature_type("continuous").set_label_type("categorical")
             .set_selection_mode("percentile").set_selection_threshold(0.34)
             .fit(_t(X, y)))
    np.testing.assert_array_equal(model._indices, [1, 4])  # 6*0.34 -> top 2


def test_univariate_chi2_categorical():
    rng = np.random.default_rng(2)
    n = 600
    y = rng.integers(0, 2, size=n)
    X = np.column_stack([
        y ^ (rng.random(n) < 0.05),      # nearly determines label
        rng.integers(0, 3, size=n),      # noise
    ]).astype(np.float64)
    model = (UnivariateFeatureSelector()
             .set_feature_type("categorical").set_label_type("categorical")
             .set_selection_mode("numTopFeatures").set_selection_threshold(1)
             .fit(_t(X, y)))
    np.testing.assert_array_equal(model._indices, [0])


def test_univariate_f_regression_continuous():
    rng = np.random.default_rng(3)
    n = 500
    X = rng.normal(size=(n, 4))
    y = 3.0 * X[:, 2] + rng.normal(scale=0.5, size=n)
    model = (UnivariateFeatureSelector()
             .set_feature_type("continuous").set_label_type("continuous")
             .set_selection_mode("numTopFeatures").set_selection_threshold(1)
             .fit(_t(X, y)))
    np.testing.assert_array_equal(model._indices, [2])


def test_univariate_unsupported_combination():
    with pytest.raises(ValueError, match="not supported"):
        (UnivariateFeatureSelector()
         .set_feature_type("categorical").set_label_type("continuous")
         .fit(_t(np.zeros((4, 2)), np.zeros(4))))


def test_univariate_requires_types():
    with pytest.raises(ValueError, match="not be null"):
        UnivariateFeatureSelector().fit(_t(np.zeros((4, 2)), np.zeros(4)))


def test_univariate_save_load(tmp_path):
    X, y = _make_classif_data()
    model = (UnivariateFeatureSelector()
             .set_feature_type("continuous").set_label_type("categorical")
             .set_selection_mode("numTopFeatures").set_selection_threshold(2)
             .fit(_t(X, y)))
    path = str(tmp_path / "ufs")
    model.save(path)
    loaded = UnivariateFeatureSelectorModel.load(path)
    np.testing.assert_array_equal(loaded._indices, [1, 4])
    assert loaded.get_selection_mode() == "numTopFeatures"


def test_fvaluetest_hand_computed():
    """y = 2x exactly: r = 1 -> F = inf -> p = 0 for the correlated column;
    noise column gets a large p."""
    from flink_ml_tpu.models.stats import FValueTest

    rng = np.random.default_rng(4)
    n = 200
    x = rng.normal(size=n)
    X = np.column_stack([x, rng.normal(size=n)])
    y = 2.0 * x
    out = FValueTest().transform(
        Table({"features": X, "label": y}))[0]
    p = np.asarray(out["pValue"])
    assert p[0] < 1e-12 and p[1] > 0.01
    # the reference family reports numSamples - 2 (denominator dof)
    assert np.asarray(out["degreesOfFreedom"])[0] == n - 2
    assert np.asarray(out["fValue"])[0] > 1e6  # finite even at r = +-1


def test_fvaluetest_known_f_value():
    # fixed tiny fixture: x = [1..6], y = x + alternating noise
    X = np.arange(1.0, 7.0)[:, None]
    y = X[:, 0] + np.asarray([0.1, -0.1, 0.1, -0.1, 0.1, -0.1])
    from flink_ml_tpu.models.stats import FValueTest

    out = FValueTest().transform(Table({"features": X, "label": y}))[0]
    # r computed by hand via numpy.corrcoef in float64 (f32 device pass
    # keeps ~6 digits)
    r = np.corrcoef(X[:, 0], y)[0, 1]
    expected_f = r * r / (1 - r * r) * 4
    np.testing.assert_allclose(np.asarray(out["fValue"])[0], expected_f,
                               rtol=1e-3)
