"""Graph / GraphBuilder / GraphModel — DAG composition tests."""

import numpy as np
import pytest

from flink_ml_tpu import Graph, GraphBuilder, GraphModel, Table, TableId
from flink_ml_tpu.models.classification import SoftmaxRegression
from flink_ml_tpu.models.clustering.kmeans import KMeans
from flink_ml_tpu.models.feature import (
    Normalizer,
    StandardScaler,
    VectorAssembler,
)


def _blobs(n_per=40, seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.normal(scale=5.0, size=(2, 4))
    X = np.concatenate([centers[i] + rng.normal(size=(n_per, 4))
                        for i in range(2)]).astype(np.float64)
    y = np.repeat([0, 1], n_per)
    return Table({"features": X, "label": y}), X, y


def test_linear_graph_equals_pipeline():
    table, X, y = _blobs()
    b = GraphBuilder()
    src = b.source()
    scaled = b.add_stage(
        StandardScaler().set_output_col("features"), [src])[0]
    pred = b.add_stage(SoftmaxRegression().set_max_iter(30), [scaled])[0]
    graph = b.build(inputs=[src], outputs=[pred])
    model = graph.fit(table)
    out = model.transform(table)[0]
    assert (np.asarray(out["prediction"]) == y).mean() > 0.95

    from flink_ml_tpu import Pipeline
    pipe_out = Pipeline([
        StandardScaler().set_output_col("features"),
        SoftmaxRegression().set_max_iter(30),
    ]).fit(table).transform(table)[0]
    np.testing.assert_array_equal(np.asarray(out["prediction"]),
                                  np.asarray(pipe_out["prediction"]))


def test_diamond_graph_two_branches():
    table, X, y = _blobs()
    b = GraphBuilder()
    src = b.source()
    # branch 1: standardize; branch 2: row-normalize; merge via assembler
    s1 = b.add_stage(StandardScaler().set_output_col("std"), [src])[0]
    s2 = b.add_stage(
        Normalizer().set_output_col("unit").set_features_col("features"),
        [s1])[0]
    merged = b.add_stage(
        VectorAssembler().set_input_cols("std", "unit")
        .set_features_col("both"), [s2])[0]
    pred = b.add_stage(
        SoftmaxRegression().set_features_col("both").set_max_iter(30),
        [merged])[0]
    graph = b.build(inputs=[src], outputs=[pred])
    model = graph.fit(table)
    out = model.transform(table)[0]
    assert np.asarray(out["both"]).shape == (len(y), 8)
    assert (np.asarray(out["prediction"]) == y).mean() > 0.95


def test_multi_output_graph():
    table, X, y = _blobs()
    b = GraphBuilder()
    src = b.source()
    scaled = b.add_stage(StandardScaler().set_output_col("features"),
                         [src])[0]
    clustered = b.add_stage(KMeans().set_max_iter(5), [scaled])[0]
    graph = b.build(inputs=[src], outputs=[scaled, clustered])
    model = graph.fit(table)
    scaled_t, clustered_t = model.transform(table)
    assert "prediction" in clustered_t
    assert abs(float(np.asarray(scaled_t["features"]).mean())) < 1e-6


def test_graph_save_load(tmp_path):
    table, X, y = _blobs()
    b = GraphBuilder()
    src = b.source()
    scaled = b.add_stage(StandardScaler().set_output_col("features"),
                         [src])[0]
    pred = b.add_stage(SoftmaxRegression().set_max_iter(20), [scaled])[0]
    graph = b.build([src], [pred])

    graph.save(str(tmp_path / "g"))
    re_graph = Graph.load(str(tmp_path / "g"))
    model = re_graph.fit(table)
    p1 = np.asarray(model.transform(table)[0]["prediction"])

    model.save(str(tmp_path / "gm"))
    re_model = GraphModel.load(str(tmp_path / "gm"))
    p2 = np.asarray(re_model.transform(table)[0]["prediction"])
    np.testing.assert_array_equal(p1, p2)


def test_unknown_input_rejected():
    b = GraphBuilder()
    b.source()
    with pytest.raises(ValueError, match="Unknown input"):
        b.add_stage(StandardScaler(), [TableId(999)])


def test_unproduced_output_rejected():
    b = GraphBuilder()
    src = b.source()
    with pytest.raises(ValueError, match="produced by no node"):
        b.build([src], [TableId(7)])


def test_wrong_input_arity_rejected():
    table, _, _ = _blobs()
    b = GraphBuilder()
    src = b.source()
    out = b.add_stage(StandardScaler().set_output_col("features"), [src])[0]
    graph = b.build([src], [out])
    with pytest.raises(ValueError, match="Expected 1 input"):
        graph.fit(table, table)


def test_non_stage_rejected():
    b = GraphBuilder()
    b.source()
    with pytest.raises(TypeError):
        b.add_stage(object(), [])


def test_passthrough_output():
    # a graph output that is directly one of its inputs
    table, _, _ = _blobs()
    b = GraphBuilder()
    src = b.source()
    out = b.add_stage(StandardScaler().set_output_col("s"), [src])[0]
    graph = b.build([src], [src, out])
    model = graph.fit(table)
    raw, scaled = model.transform(table)
    np.testing.assert_array_equal(np.asarray(raw["features"]),
                                  np.asarray(table["features"]))


class _JoinColumns(
        __import__("flink_ml_tpu").AlgoOperator):
    """Two-input test stage: attaches table B's 'extra' column to table A
    (order-sensitive, so it catches input-resolution regressions)."""

    def transform(self, *inputs):
        a, b = inputs
        return [a.with_column("extra", np.asarray(b["extra"]) * 10.0)]


def test_multi_input_node_fan_in_and_order():
    rng = np.random.default_rng(0)
    t_a = Table({"features": rng.normal(size=(5, 2))})
    t_b = Table({"extra": np.arange(5, dtype=np.float64)})

    b = GraphBuilder()
    src_a, src_b = b.source(), b.source()
    joined = b.add_stage(_JoinColumns(), [src_a, src_b])[0]
    graph = b.build([src_a, src_b], [joined])
    model = graph.fit(t_a, t_b)
    out = model.transform(t_a, t_b)[0]
    np.testing.assert_allclose(np.asarray(out["extra"]),
                               np.arange(5) * 10.0)
    # swapped wiring resolves the other way round (both tables carry
    # 'extra', with different values, so order is observable)
    t_a2 = Table({"extra": np.full(5, 7.0)})
    b2 = GraphBuilder()
    sa, sb = b2.source(), b2.source()
    j2 = b2.add_stage(_JoinColumns(), [sb, sa])[0]
    g2 = b2.build([sa, sb], [j2])
    out2 = g2.fit(t_a2, t_b).transform(t_a2, t_b)[0]
    # first input was t_b (base), second t_a2 -> extra = 7*10
    np.testing.assert_allclose(np.asarray(out2["extra"]), np.full(5, 70.0))


def test_forgotten_source_fails_at_build():
    b = GraphBuilder()
    s0, s1 = b.source(), b.source()
    out = b.add_stage(_JoinColumns(), [s0, s1])[0]
    with pytest.raises(ValueError, match="forget to\n?.*list a source|neither a build"):
        b.build([s0], [out])
