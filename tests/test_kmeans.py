"""KMeans tests — mirror of ``KMeansTest.java`` (259 LoC): param defaults,
fit+transform on the 6-point/2-cluster fixture with exact cluster membership
(BASELINE.md anchor), save/load round-trip, pipeline integration."""

import numpy as np
import pytest

from flink_ml_tpu import Pipeline, Table
from flink_ml_tpu.models.clustering.kmeans import (
    KMeans,
    KMeansModel,
    select_random_centroids,
)
from flink_ml_tpu.utils import persist

# The exact fixture from KMeansTest.java:58-66
DATA = np.array([
    [0.0, 0.0],
    [0.0, 0.3],
    [0.3, 0.0],
    [9.0, 0.0],
    [9.0, 0.6],
    [9.6, 0.0],
], dtype=np.float64)


def _table():
    return Table({"features": DATA})


def _clusters(table, pred_col="prediction"):
    """Group feature rows by predicted cluster -> set of frozensets."""
    groups = {}
    for row, c in zip(table["features"], table[pred_col]):
        groups.setdefault(int(c), set()).add(tuple(row.tolist()))
    return set(frozenset(v) for v in groups.values())

EXPECTED = {
    frozenset({(0.0, 0.0), (0.0, 0.3), (0.3, 0.0)}),
    frozenset({(9.0, 0.0), (9.0, 0.6), (9.6, 0.0)}),
}


def test_param_defaults():
    # KMeansTest.testParam analog
    kmeans = KMeans()
    assert kmeans.get_k() == 2
    assert kmeans.get_max_iter() == 20
    assert kmeans.get_features_col() == "features"
    assert kmeans.get_prediction_col() == "prediction"
    assert kmeans.get_distance_measure() == "euclidean"

    kmeans.set_k(9).set_max_iter(3).set_features_col("f")
    assert kmeans.get_k() == 9 and kmeans.get_max_iter() == 3

    with pytest.raises(Exception):
        KMeans().set_k(1)  # gtEq(2)


def test_fit_and_transform_exact_clusters():
    # KMeansTest.testFitAndPredict analog: exact cluster membership
    model = KMeans().set_max_iter(10).set_seed(3).fit(_table())
    out = model.transform(_table())[0]
    assert out.column_names == ["features", "prediction"]
    assert _clusters(out) == EXPECTED


def test_different_seeds_converge_same_clusters():
    for seed in range(5):
        model = KMeans().set_seed(seed).set_max_iter(20).fit(_table())
        assert _clusters(model.transform(_table())[0]) == EXPECTED


def test_prediction_col_rename():
    model = KMeans().set_prediction_col("cluster").fit(_table())
    out = model.transform(_table())[0]
    assert "cluster" in out.column_names
    assert _clusters(out, "cluster") == EXPECTED


def test_model_data_round_trip():
    model = KMeans().set_max_iter(5).fit(_table())
    (data,) = model.get_model_data()
    centroids = data["centroids"][0]
    assert centroids.shape == (2, 2)
    fresh = KMeansModel().set_model_data(Table({"centroids": centroids[None]}))
    assert _clusters(fresh.transform(_table())[0]) == EXPECTED


def test_save_load_estimator_and_model(tmp_path):
    # KMeansTest.testSaveLoad analog
    est_path, model_path = str(tmp_path / "est"), str(tmp_path / "model")
    kmeans = KMeans().set_k(2).set_max_iter(7).set_seed(1)
    kmeans.save(est_path)
    loaded_est = KMeans.load(est_path)
    assert loaded_est.get_max_iter() == 7

    model = loaded_est.fit(_table())
    model.save(model_path)
    loaded_model = KMeansModel.load(model_path)
    assert _clusters(loaded_model.transform(_table())[0]) == EXPECTED
    # reflective load too
    assert isinstance(persist.load_stage(model_path), KMeansModel)


def test_in_pipeline(tmp_path):
    pipeline = Pipeline([KMeans().set_max_iter(10)])
    pmodel = pipeline.fit(_table())
    assert _clusters(pmodel.transform(_table())[0]) == EXPECTED
    path = str(tmp_path / "pm")
    pmodel.save(path)
    from flink_ml_tpu import PipelineModel
    assert _clusters(PipelineModel.load(path).transform(_table())[0]) == EXPECTED


def test_select_random_centroids_semantics():
    pts = np.arange(20, dtype=np.float64).reshape(10, 2)
    c1 = select_random_centroids(pts, 3, seed=5)
    c2 = select_random_centroids(pts, 3, seed=5)
    np.testing.assert_array_equal(c1, c2)  # deterministic under seed
    assert len({tuple(r) for r in c1}) == 3  # distinct points
    with pytest.raises(ValueError):
        select_random_centroids(pts[:2], 3, seed=0)


def test_transform_without_model_data_errors():
    with pytest.raises(RuntimeError):
        KMeansModel().transform(_table())


def test_unpadded_vs_padded_identical():
    # 6 rows on an 8-device mesh forces padding; result must equal a
    # single-device (no padding needed) run via masking.
    m1 = KMeans().set_seed(0).set_max_iter(10).fit(_table())
    big = Table({"features": np.tile(DATA, (4, 1))})  # 24 rows: divisible by 8
    m2 = KMeans().set_seed(0).set_max_iter(10).fit(big)
    assert _clusters(m1.transform(_table())[0]) == EXPECTED
    assert _clusters(m2.transform(_table())[0]) == EXPECTED


def test_manhattan_distance_measure():
    model = (KMeans().set_distance_measure("manhattan").set_max_iter(10)
             .fit(_table()))
    assert _clusters(model.transform(_table())[0]) == EXPECTED
