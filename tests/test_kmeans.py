"""KMeans tests — mirror of ``KMeansTest.java`` (259 LoC): param defaults,
fit+transform on the 6-point/2-cluster fixture with exact cluster membership
(BASELINE.md anchor), save/load round-trip, pipeline integration."""

import numpy as np
import pytest

from flink_ml_tpu import Pipeline, Table
from flink_ml_tpu.models.clustering.kmeans import (
    KMeans,
    KMeansModel,
    select_random_centroids,
)
from flink_ml_tpu.utils import persist

# The exact fixture from KMeansTest.java:58-66
DATA = np.array([
    [0.0, 0.0],
    [0.0, 0.3],
    [0.3, 0.0],
    [9.0, 0.0],
    [9.0, 0.6],
    [9.6, 0.0],
], dtype=np.float64)


def _table():
    return Table({"features": DATA})


def _clusters(table, pred_col="prediction"):
    """Group feature rows by predicted cluster -> set of frozensets."""
    groups = {}
    for row, c in zip(table["features"], table[pred_col]):
        groups.setdefault(int(c), set()).add(tuple(row.tolist()))
    return set(frozenset(v) for v in groups.values())

EXPECTED = {
    frozenset({(0.0, 0.0), (0.0, 0.3), (0.3, 0.0)}),
    frozenset({(9.0, 0.0), (9.0, 0.6), (9.6, 0.0)}),
}


def test_param_defaults():
    # KMeansTest.testParam analog
    kmeans = KMeans()
    assert kmeans.get_k() == 2
    assert kmeans.get_max_iter() == 20
    assert kmeans.get_features_col() == "features"
    assert kmeans.get_prediction_col() == "prediction"
    assert kmeans.get_distance_measure() == "euclidean"

    kmeans.set_k(9).set_max_iter(3).set_features_col("f")
    assert kmeans.get_k() == 9 and kmeans.get_max_iter() == 3

    with pytest.raises(Exception):
        KMeans().set_k(1)  # gtEq(2)


def test_fit_and_transform_exact_clusters():
    # KMeansTest.testFitAndPredict analog: exact cluster membership
    model = KMeans().set_max_iter(10).set_seed(3).fit(_table())
    out = model.transform(_table())[0]
    assert out.column_names == ["features", "prediction"]
    assert _clusters(out) == EXPECTED


def test_different_seeds_converge_same_clusters():
    for seed in range(5):
        model = KMeans().set_seed(seed).set_max_iter(20).fit(_table())
        assert _clusters(model.transform(_table())[0]) == EXPECTED


def test_prediction_col_rename():
    model = KMeans().set_prediction_col("cluster").fit(_table())
    out = model.transform(_table())[0]
    assert "cluster" in out.column_names
    assert _clusters(out, "cluster") == EXPECTED


def test_model_data_round_trip():
    model = KMeans().set_max_iter(5).fit(_table())
    (data,) = model.get_model_data()
    centroids = data["centroids"][0]
    assert centroids.shape == (2, 2)
    fresh = KMeansModel().set_model_data(Table({"centroids": centroids[None]}))
    assert _clusters(fresh.transform(_table())[0]) == EXPECTED


def test_save_load_estimator_and_model(tmp_path):
    # KMeansTest.testSaveLoad analog
    est_path, model_path = str(tmp_path / "est"), str(tmp_path / "model")
    kmeans = KMeans().set_k(2).set_max_iter(7).set_seed(1)
    kmeans.save(est_path)
    loaded_est = KMeans.load(est_path)
    assert loaded_est.get_max_iter() == 7

    model = loaded_est.fit(_table())
    model.save(model_path)
    loaded_model = KMeansModel.load(model_path)
    assert _clusters(loaded_model.transform(_table())[0]) == EXPECTED
    # reflective load too
    assert isinstance(persist.load_stage(model_path), KMeansModel)


def test_in_pipeline(tmp_path):
    pipeline = Pipeline([KMeans().set_max_iter(10)])
    pmodel = pipeline.fit(_table())
    assert _clusters(pmodel.transform(_table())[0]) == EXPECTED
    path = str(tmp_path / "pm")
    pmodel.save(path)
    from flink_ml_tpu import PipelineModel
    assert _clusters(PipelineModel.load(path).transform(_table())[0]) == EXPECTED


def test_select_random_centroids_semantics():
    pts = np.arange(20, dtype=np.float64).reshape(10, 2)
    c1 = select_random_centroids(pts, 3, seed=5)
    c2 = select_random_centroids(pts, 3, seed=5)
    np.testing.assert_array_equal(c1, c2)  # deterministic under seed
    assert len({tuple(r) for r in c1}) == 3  # distinct points
    with pytest.raises(ValueError):
        select_random_centroids(pts[:2], 3, seed=0)


def test_transform_without_model_data_errors():
    with pytest.raises(RuntimeError):
        KMeansModel().transform(_table())


def test_unpadded_vs_padded_identical():
    # 6 rows on an 8-device mesh forces padding; result must equal a
    # single-device (no padding needed) run via masking.
    m1 = KMeans().set_seed(0).set_max_iter(10).fit(_table())
    big = Table({"features": np.tile(DATA, (4, 1))})  # 24 rows: divisible by 8
    m2 = KMeans().set_seed(0).set_max_iter(10).fit(big)
    assert _clusters(m1.transform(_table())[0]) == EXPECTED
    assert _clusters(m2.transform(_table())[0]) == EXPECTED


def test_manhattan_distance_measure():
    model = (KMeans().set_distance_measure("manhattan").set_max_iter(10)
             .fit(_table()))
    assert _clusters(model.transform(_table())[0]) == EXPECTED


def test_pallas_epoch_step_matches_xla_step():
    # The fused-kernel body (interpret mode) must reproduce the XLA body on
    # zero-padded data, for both tie policies.
    import jax.numpy as jnp

    from flink_ml_tpu.distance import DistanceMeasure
    from flink_ml_tpu.models.clustering.kmeans import (
        kmeans_epoch_step,
        kmeans_epoch_step_pallas,
    )

    rng = np.random.default_rng(3)
    pts = rng.normal(size=(256 - 11, 4)).astype(np.float32)
    padded = np.concatenate(
        [pts, np.zeros((11, 4), np.float32)]).astype(np.float32)
    mask = np.concatenate([np.ones(len(pts)), np.zeros(11)]).astype(np.float32)
    cents = pts[:5].copy()
    data = (jnp.asarray(padded), jnp.asarray(mask))

    xla_body = kmeans_epoch_step(DistanceMeasure.get_instance("euclidean"), 5)
    expected = np.asarray(xla_body(jnp.asarray(cents), 0, data).feedback)
    for tie_policy in ("first", "fast", "split"):
        body = kmeans_epoch_step_pallas(5, block_n=128, tie_policy=tie_policy,
                                        interpret=True)
        got = np.asarray(body(jnp.asarray(cents), 0, data).feedback)
        np.testing.assert_allclose(got, expected, atol=1e-4)


def test_pallas_epoch_step_sharded_matches(cpu_mesh_8):
    import jax
    import jax.numpy as jnp

    from flink_ml_tpu.models.clustering.kmeans import (
        _prepare_points,
        kmeans_epoch_step_pallas,
    )
    from flink_ml_tpu.parallel.mesh import replicate

    rng = np.random.default_rng(4)
    pts = rng.normal(size=(1000, 4)).astype(np.float32)
    points, mask = _prepare_points(pts, cpu_mesh_8, row_multiple=128,
                                   fill="zero")
    assert points.shape[0] == 1024
    cents = replicate(pts[:5].copy(), cpu_mesh_8)

    single = kmeans_epoch_step_pallas(5, block_n=128, interpret=True)
    sharded = kmeans_epoch_step_pallas(5, cpu_mesh_8, block_n=128,
                                       interpret=True)
    expected = np.asarray(single(jnp.asarray(pts[:5].copy()), 0,
                                 (jnp.asarray(np.asarray(points)),
                                  jnp.asarray(np.asarray(mask)))).feedback)
    got = np.asarray(
        jax.jit(lambda c, d: sharded(c, 0, d).feedback)(cents, (points, mask)))
    np.testing.assert_allclose(got, expected, atol=1e-4)


def test_plan_fit_impl_gates():
    import jax

    from flink_ml_tpu.distance import DistanceMeasure
    from flink_ml_tpu.models.clustering import kmeans as km
    from flink_ml_tpu.parallel.mesh import default_mesh

    mesh = default_mesh()
    euclid = DistanceMeasure.get_instance("euclidean")
    cosine = DistanceMeasure.get_instance("cosine")
    if jax.default_backend() == "tpu":  # pragma: no cover - CPU suite
        assert km._plan_fit_impl(1 << 20, 64, 256, euclid, mesh)[0] == "pallas"
    # CPU backend always plans XLA
    else:
        assert km._plan_fit_impl(1 << 20, 64, 256, euclid, mesh)[0] == "xla"
    # small n / non-euclidean never plan pallas regardless of backend
    assert km._plan_fit_impl(100, 64, 256, euclid, mesh)[0] == "xla"
    assert km._plan_fit_impl(1 << 20, 64, 256, cosine, mesh)[0] == "xla"


def test_pallas_step_fractional_split_counts_divide_exactly():
    # A cluster whose total "split" count is fractional (< 1) must divide by
    # the fractional count, not a clamp-to-1 (regression: centroid scaled by
    # its count).
    import jax.numpy as jnp

    from flink_ml_tpu.models.clustering.kmeans import kmeans_epoch_step_pallas

    p = np.zeros((128, 4), np.float32)
    p[0] = [2.0, 0.0, 0.0, 0.0]
    p[1:] = [40.0, 0.0, 0.0, 0.0]  # rest land on the far centroid
    dup = np.array([[2.0, 0.0, 0.0, 1.0], [2.0, 0.0, 0.0, -1.0]], np.float32)
    cents = jnp.asarray(np.concatenate([dup, [[40.0, 0, 0, 0]]]))
    mask = jnp.asarray(np.ones(128, np.float32))
    body = kmeans_epoch_step_pallas(3, block_n=128, tie_policy="split",
                                    interpret=True)
    new = np.asarray(body(cents, 0, (jnp.asarray(p), mask)).feedback)
    # p[0] ties between the duplicate pair -> each gets count 0.5, sum 0.5*p0;
    # the mean must still be exactly p0.
    np.testing.assert_allclose(new[0], p[0], atol=1e-5)
    np.testing.assert_allclose(new[1], p[0], atol=1e-5)


# ---------------------------------------------------------------------------
# out-of-core fit (replay-per-epoch, the ReplayOperator analog at scale)
# ---------------------------------------------------------------------------

def _ooc_batches(pts, batch):
    def gen():
        for s in range(0, len(pts), batch):
            yield {"features": pts[s:s + batch]}
    return gen


def test_kmeans_outofcore_matches_incore_math():
    """Per-batch accumulation must reproduce the full-batch Lloyd's update
    exactly (same init): streaming is a layout change, not a math change."""
    import jax.numpy as jnp

    from flink_ml_tpu.distance import DistanceMeasure
    from flink_ml_tpu.models.clustering.kmeans import (
        kmeans_epoch_step,
        kmeans_fit_outofcore,
        select_random_centroids,
    )

    rng = np.random.default_rng(0)
    pts = rng.normal(size=(257, 5)).astype(np.float32)  # odd row count
    k, iters, batch = 4, 6, 64

    got = kmeans_fit_outofcore(_ooc_batches(pts, batch), k,
                               max_iter=iters, seed=3)

    body = kmeans_epoch_step(DistanceMeasure.get_instance("euclidean"), k)
    c = jnp.asarray(select_random_centroids(pts[:batch], k, 3))
    mask = jnp.ones((len(pts),), jnp.float32)
    for _ in range(iters):
        c = body(c, 0, (jnp.asarray(pts), mask)).feedback
    np.testing.assert_allclose(got, np.asarray(c), atol=1e-5)


def test_kmeans_outofcore_estimator_clusters(tmp_path):
    from flink_ml_tpu.data.datacache import DataCacheReader, DataCacheWriter

    rng = np.random.default_rng(1)
    centers = np.asarray([[6.0, 6.0], [-6.0, -6.0]], np.float32)
    pts = np.concatenate([c + rng.normal(scale=0.3, size=(150, 2))
                          for c in centers]).astype(np.float32)
    pts = pts[rng.permutation(len(pts))]

    cache = str(tmp_path / "cache")
    writer = DataCacheWriter(cache, segment_rows=128)
    for s in range(0, len(pts), 64):
        writer.append({"features": pts[s:s + 64]})
    writer.finish()

    model = (KMeans().set_k(2).set_max_iter(10)
             .fit_outofcore(lambda: DataCacheReader(cache, batch_rows=64)))
    got = np.sort(np.asarray(model.get_model_data()[0]["centroids"][0]),
                  axis=0)
    np.testing.assert_allclose(got, np.sort(centers, axis=0), atol=0.2)

    pred = np.asarray(
        model.transform(Table({"features": pts}))[0]["prediction"])
    assert len(np.unique(pred)) == 2


def test_kmeans_outofcore_empty_reader_raises():
    from flink_ml_tpu.models.clustering.kmeans import kmeans_fit_outofcore

    with pytest.raises(ValueError, match="empty"):
        kmeans_fit_outofcore(lambda: iter(()), 2, max_iter=2)


class TestKMeansPlusPlus:
    def test_seeding_picks_distinct_dataset_points(self):
        from flink_ml_tpu.models.clustering.kmeans import (
            select_kmeanspp_centroids)

        rng = np.random.default_rng(0)
        pts = rng.normal(size=(500, 3)).astype(np.float32)
        init = select_kmeanspp_centroids(pts, 8, seed=1)
        assert init.shape == (8, 3)
        # every chosen centroid IS a dataset point, all distinct
        matches = (np.abs(pts[None, :, :] - init[:, None, :])
                   .sum(-1) < 1e-7).any(axis=1)
        assert matches.all()
        assert len(np.unique(init.round(5), axis=0)) == 8
        # deterministic per seed
        np.testing.assert_array_equal(
            init, select_kmeanspp_centroids(pts, 8, seed=1))

    def test_covers_separated_clusters(self):
        from flink_ml_tpu.models.clustering.kmeans import (
            select_kmeanspp_centroids)

        rng = np.random.default_rng(2)
        centers = np.array([[0.0, 0.0], [50.0, 0.0], [0.0, 50.0]])
        pts = np.concatenate([c + 0.5 * rng.normal(size=(200, 2))
                              for c in centers]).astype(np.float32)
        init = select_kmeanspp_centroids(pts, 3, seed=0)
        # one seed per cluster: nearest true center of each pick is unique
        owner = np.argmin(((init[:, None, :] - centers[None])**2).sum(-1),
                          axis=1)
        assert set(owner) == {0, 1, 2}

    def test_estimator_init_mode_param(self):
        rng = np.random.default_rng(3)
        centers = np.array([[0.0, 0.0], [30.0, 0.0]])
        pts = np.concatenate([c + rng.normal(size=(100, 2))
                              for c in centers])
        t = Table({"features": pts})
        model = (KMeans().set_k(2).set_max_iter(10)
                 .set_init_mode("k-means++").fit(t))
        assign = np.asarray(model.transform(t)[0]["prediction"])
        assert len(set(assign[:100])) == 1 and len(set(assign[100:])) == 1
        assert assign[0] != assign[100]
        with pytest.raises(Exception):
            KMeans().set_init_mode("banana")


def test_tie_policy_first_matches_argmin_under_real_ties():
    """'first' (the r4 default) must reproduce numpy first-index argmin
    EXACTLY on discrete data with real ties — where 'fast' double-counts
    and 'split' fractions.  This is the reference's Lloyd's semantics
    (KMeans.java:238-315 assigns each point to exactly one centroid)."""
    import jax.numpy as jnp

    from flink_ml_tpu.ops.kmeans_pallas import kmeans_update_stats

    rng = np.random.default_rng(0)
    pts = rng.integers(0, 3, size=(1024, 8)).astype(np.float32)
    cents = np.stack([
        pts[0], pts[1],
        pts[0] + np.eye(8, dtype=np.float32)[0],
        pts[0] - np.eye(8, dtype=np.float32)[0]])
    d2 = ((pts[:, None, :] - cents[None, :, :]) ** 2).sum(-1)
    assert int(((d2 == d2.min(1, keepdims=True)).sum(1) > 1).sum()) > 0

    sums, counts = kmeans_update_stats(
        jnp.asarray(pts), jnp.asarray(cents), block_n=1024,
        tie_policy="first", interpret=True)
    assign = d2.argmin(1)
    want_counts = np.bincount(assign, minlength=4).astype(np.float64)
    want_sums = np.zeros((4, 8))
    np.add.at(want_sums, assign, pts)
    np.testing.assert_allclose(np.asarray(counts, np.float64), want_counts,
                               atol=1e-3)
    np.testing.assert_allclose(np.asarray(sums, np.float64), want_sums,
                               rtol=1e-5, atol=1e-3)
    # total mass is exactly n ('fast' would double-count ties)
    assert float(np.asarray(counts).sum()) == len(pts)


def test_kmeans_outofcore_epoch_aware_shuffled_reader(tmp_path):
    """An epoch-aware ShuffledCacheReader factory (the sgd streaming
    protocol) drives out-of-core Lloyd's: each iteration receives its
    epoch number, the permuted stream carries the same row multiset, and
    the fit recovers the true generating centers (init draws from epoch
    0's first shuffled batch, so the whole run is deterministic in the
    pinned seeds)."""
    from flink_ml_tpu.data.datacache import (
        DataCacheReader,
        DataCacheWriter,
        ShuffledCacheReader,
    )
    from flink_ml_tpu.models.clustering.kmeans import kmeans_fit_outofcore

    rng = np.random.default_rng(4)
    centers = np.array([[0.0, 0.0], [8.0, 8.0], [-8.0, 8.0]], np.float32)
    pts = np.concatenate([
        centers[i] + rng.normal(scale=0.3, size=(200, 2)).astype(np.float32)
        for i in range(3)])
    rng.shuffle(pts)
    cache = str(tmp_path / "kmshuf")
    w = DataCacheWriter(cache, segment_rows=256)
    w.append({"features": pts})
    w.finish()

    # seed pinned to a converging random init (random Lloyd init can
    # collapse two centroids onto a midpoint regardless of the reader)
    got = kmeans_fit_outofcore(
        lambda epoch: ShuffledCacheReader(cache, batch_rows=128,
                                          seed=3, epoch=epoch),
        k=3, max_iter=8, seed=1)
    # every true center recovered within the cluster noise scale
    d = np.linalg.norm(got[:, None, :] - centers[None, :, :], axis=-1)
    assert d.min(axis=0).max() < 0.5


# -- workset (delta-iteration) fit, ISSUE 9 ----------------------------------

def _blob_table(n, d=16, k=5, seed=0, spread=8.0, noise=0.4):
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(k, d)) * spread
    lab = rng.integers(0, k, n)
    X = centers[lab] + rng.normal(size=(n, d)) * noise
    return Table({"features": X.astype(np.float32)})


@pytest.mark.parametrize("tie", ["first", "fast", "split"])
@pytest.mark.parametrize("n", [4096, 4003])   # exact multiple + padded tail
def test_workset_kmeans_bitexact_vs_bsp(tie, n):
    """Acceptance: on the virtual 8-device mesh the bound-filtered fit's
    final centroids are BIT-identical to BSP across tie policies and
    padded tails, the while_loop exits strictly before maxIter, and the
    points scored per round decay below 20% of n before convergence."""
    k, max_iter = 5, 60
    table = _blob_table(n, k=k, seed=3)
    bsp = (KMeans().set_k(k).set_max_iter(max_iter).set_seed(7)
           .set_tie_policy(tie).fit(table))
    est = (KMeans().set_k(k).set_max_iter(max_iter).set_seed(7)
           .set_tie_policy(tie).set_workset(True))
    wk = est.fit(table)

    c_bsp = bsp.get_model_data()[0]["centroids"][0]
    c_wk = wk.get_model_data()[0]["centroids"][0]
    np.testing.assert_array_equal(c_bsp, c_wk)

    rep = est.last_workset_report
    assert rep["rounds"] < max_iter            # convergence-driven exit
    assert rep["rounds"] == len(rep["active_fraction"])
    assert rep["n_points"] == n
    # bound filter bites: some pre-convergence round scores < 20% of n
    scored = rep["points_scored"]
    assert scored[0] == n                      # round 0 = BSP full rescore
    assert scored[:-1].min() < 0.2 * n
    # the workset drains exactly at the exit round
    assert rep["active_fraction"][-1] == 0.0


def test_workset_kmeans_report_absent_on_bsp_fit():
    est = KMeans().set_k(2).set_max_iter(5)
    est.fit(_table())
    assert getattr(est, "last_workset_report", None) is None


def test_workset_param_default_off_and_roundtrips(tmp_path):
    est = KMeans().set_k(3).set_workset(True)
    assert KMeans().get_workset() is False
    est.save(str(tmp_path / "est"))
    assert KMeans.load(str(tmp_path / "est")).get_workset() is True


def test_workset_requires_euclidean():
    from flink_ml_tpu.distance import DistanceMeasure
    from flink_ml_tpu.models.clustering.kmeans import (
        kmeans_workset_epoch_step)

    with pytest.raises(ValueError, match="euclidean"):
        kmeans_workset_epoch_step(
            DistanceMeasure.get_instance("manhattan"), 3)


def test_fit_plan_workset_initializer_settles_padding():
    """Satellite: the shared FitPlan bound-state initializer — padding
    rows are born settled (never active, never scored), real rows start
    with the vacuous full-rescore bounds."""
    import jax.numpy as jnp

    from flink_ml_tpu.distance import DistanceMeasure
    from flink_ml_tpu.models.clustering.kmeans import _fit_plan
    from flink_ml_tpu.parallel.mesh import default_mesh

    euclid = DistanceMeasure.get_instance("euclidean")
    plan = _fit_plan(100, 4, 3, euclid, default_mesh(), workset=True)
    assert plan.impl == "xla" and plan.row_multiple == 1
    pad_mask = jnp.asarray([1.0, 1.0, 1.0, 0.0, 0.0])
    ws = plan.init_workset(pad_mask)
    np.testing.assert_array_equal(np.asarray(ws.mask), [1, 1, 1, 0, 0])
    assert np.all(np.isinf(np.asarray(ws.bounds["upper"])))
    assert np.all(np.asarray(ws.bounds["lower"]) == -np.inf)
    np.testing.assert_array_equal(np.asarray(ws.bounds["assign"]),
                                  np.zeros(5))
