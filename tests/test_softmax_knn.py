"""SoftmaxRegression (multinomial LR) and KNNClassifier tests."""

import numpy as np
import pytest

from flink_ml_tpu import Table
from flink_ml_tpu.models.classification import (
    KNNClassifier,
    KNNClassifierModel,
    SoftmaxRegression,
    SoftmaxRegressionModel,
)


def _three_blobs(n_per=60, d=5, seed=0, scale=4.0):
    rng = np.random.default_rng(seed)
    centers = rng.normal(scale=scale, size=(3, d))
    X = np.concatenate([centers[i] + rng.normal(size=(n_per, d))
                        for i in range(3)]).astype(np.float32)
    y = np.repeat([10, 20, 30], n_per)  # non-contiguous label values
    return Table({"features": X, "label": y}), X, y


# ---------------------------------------------------------------- softmax --

def test_softmax_learns_three_classes():
    table, X, y = _three_blobs()
    model = (SoftmaxRegression().set_max_iter(60).set_learning_rate(0.3)
             .set_global_batch_size(64).set_seed(0).fit(table))
    out = model.transform(table)[0]
    pred = np.asarray(out["prediction"])
    assert (pred == y).mean() > 0.95
    probs = np.asarray(out["rawPrediction"])
    assert probs.shape == (len(y), 3)
    np.testing.assert_allclose(probs.sum(1), 1.0, atol=1e-5)
    # prediction = argmax of the raw probabilities, mapped to label values
    np.testing.assert_array_equal(np.array([10, 20, 30])[probs.argmax(1)],
                                  pred)


def test_softmax_single_class_rejected():
    table = Table({"features": np.zeros((4, 2), np.float32),
                   "label": np.ones(4)})
    with pytest.raises(ValueError, match="distinct label"):
        SoftmaxRegression().fit(table)


def test_softmax_save_load_round_trip(tmp_path):
    table, X, y = _three_blobs(n_per=30)
    model = SoftmaxRegression().set_max_iter(20).fit(table)
    p1 = np.asarray(model.transform(table)[0]["prediction"])
    model.save(str(tmp_path / "m"))
    re = SoftmaxRegressionModel.load(str(tmp_path / "m"))
    p2 = np.asarray(re.transform(table)[0]["prediction"])
    np.testing.assert_array_equal(p1, p2)


def test_softmax_sample_weights_shift_boundary():
    # all weight on class 10 rows -> model heavily favors class 10
    table, X, y = _three_blobs(n_per=20, scale=0.5, seed=3)
    w = np.where(y == 10, 100.0, 0.01)
    weighted = Table({"features": X, "label": y, "w": w})
    model = (SoftmaxRegression().set_weight_col("w").set_max_iter(40)
             .set_learning_rate(0.5).fit(weighted))
    pred = np.asarray(model.transform(weighted)[0]["prediction"])
    assert (pred == 10).mean() > 0.8


def test_softmax_binary_agrees_with_logistic_family_shape():
    table, X, y = _three_blobs()
    two = Table({"features": X[y != 30], "label": y[y != 30]})
    model = SoftmaxRegression().set_max_iter(40).fit(two)
    pred = np.asarray(model.transform(two)[0]["prediction"])
    assert set(np.unique(pred)) <= {10, 20}
    assert (pred == np.asarray(two["label"])).mean() > 0.95


# -------------------------------------------------------------------- knn --

def test_knn_classifies_blobs():
    table, X, y = _three_blobs()
    model = KNNClassifier().set_k(5).fit(table)
    pred = np.asarray(model.transform(table)[0]["prediction"])
    assert (pred == y).mean() > 0.95


def test_knn_k1_memorizes_training_set():
    table, X, y = _three_blobs(n_per=25)
    model = KNNClassifier().set_k(1).fit(table)
    pred = np.asarray(model.transform(table)[0]["prediction"])
    np.testing.assert_array_equal(pred, y)


def test_knn_k_larger_than_train_clamped():
    table = Table({"features": np.asarray([[0.0], [1.0], [1.1]], np.float32),
                   "label": np.asarray([0, 1, 1])})
    model = KNNClassifier().set_k(100).fit(table)
    pred = np.asarray(model.transform(Table(
        {"features": np.asarray([[0.9]], np.float32)}))[0]["prediction"])
    assert pred[0] == 1  # majority of the whole (clamped) train set


def test_knn_chunking_boundary():
    # query count not a multiple of the chunk: padded rows must be dropped
    from flink_ml_tpu.models.classification import knn as knn_mod
    old = knn_mod._QUERY_CHUNK
    knn_mod._QUERY_CHUNK = 8
    try:
        table, X, y = _three_blobs(n_per=7)  # 21 rows: 2 chunks + remainder
        model = KNNClassifier().set_k(3).fit(table)
        pred = np.asarray(model.transform(table)[0]["prediction"])
        assert len(pred) == 21
        assert (pred == y).mean() > 0.9
    finally:
        knn_mod._QUERY_CHUNK = old


def test_knn_save_load_round_trip(tmp_path):
    table, X, y = _three_blobs(n_per=10)
    model = KNNClassifier().set_k(3).fit(table)
    p1 = np.asarray(model.transform(table)[0]["prediction"])
    model.save(str(tmp_path / "m"))
    re = KNNClassifierModel.load(str(tmp_path / "m"))
    p2 = np.asarray(re.transform(table)[0]["prediction"])
    np.testing.assert_array_equal(p1, p2)
    assert re.get_k() == 3


def test_knn_model_data_round_trip():
    table, X, y = _three_blobs(n_per=5)
    model = KNNClassifier().set_k(3).fit(table)
    rebuilt = KNNClassifierModel().set_model_data(*model.get_model_data())
    rebuilt.copy_params_from(model)
    p1 = np.asarray(model.transform(table)[0]["prediction"])
    p2 = np.asarray(rebuilt.transform(table)[0]["prediction"])
    np.testing.assert_array_equal(p1, p2)


def test_knn_manhattan_metric():
    table, X, y = _three_blobs()
    model = (KNNClassifier().set_distance_measure("manhattan").set_k(5)
             .fit(table))
    pred = np.asarray(model.transform(table)[0]["prediction"])
    assert (pred == y).mean() > 0.9


def test_knn_empty_train_rejected():
    table = Table({"features": np.zeros((0, 2), np.float32),
                   "label": np.zeros((0,))})
    with pytest.raises(ValueError):
        KNNClassifier().fit(table)
