"""Streaming substrate (data/stream.py): count/event-time windows, watermark
close, snapshot/restore cursors, and the shared online-model ingest."""

import numpy as np
import pytest

from flink_ml_tpu import Table
from flink_ml_tpu.data.stream import CountWindows, EventTimeWindows, windows_of


def _t(n, start=0):
    return Table({"x": np.arange(start, start + n, dtype=np.float64)})


# ------------------------------------------------------------ CountWindows


def test_count_windows_over_table_flushes_tail():
    windows = list(CountWindows(_t(10), 4))
    assert [w.num_rows for w in windows] == [4, 4, 2]
    np.testing.assert_array_equal(np.asarray(windows[2]["x"]), [8.0, 9.0])


def test_count_windows_rechunks_live_feed_across_table_boundaries():
    feed = [_t(3, 0), _t(5, 3), _t(2, 8)]  # 10 rows in ragged tables
    windows = list(CountWindows(iter(feed), 4))
    assert [w.num_rows for w in windows] == [4, 4, 2]
    np.testing.assert_array_equal(np.asarray(windows[0]["x"]), [0, 1, 2, 3])
    np.testing.assert_array_equal(np.asarray(windows[1]["x"]), [4, 5, 6, 7])


def test_count_windows_table_cursor_snapshot_restore():
    src = CountWindows(_t(10), 4)
    it = iter(src)
    next(it)
    snap = src.snapshot()
    assert snap == {"cursor": 4}
    fresh = CountWindows(_t(10), 4)
    fresh.restore(snap)
    remaining = list(fresh)
    assert [w.num_rows for w in remaining] == [4, 2]
    np.testing.assert_array_equal(np.asarray(remaining[0]["x"]),
                                  [4, 5, 6, 7])


def test_count_windows_feed_restore_skips_windows():
    def feed():
        yield _t(4, 0)
        yield _t(4, 4)
        yield _t(4, 8)

    src = CountWindows(feed(), 4)
    it = iter(src)
    next(it), next(it)
    snap = src.snapshot()
    fresh = CountWindows(feed(), 4)
    fresh.restore(snap)
    remaining = list(fresh)
    assert len(remaining) == 1
    np.testing.assert_array_equal(np.asarray(remaining[0]["x"]),
                                  [8, 9, 10, 11])


def test_count_windows_validates_size():
    with pytest.raises(ValueError, match="positive"):
        CountWindows(_t(4), 0)


# -------------------------------------------------------- EventTimeWindows


def _timed(ts, vals=None):
    ts = np.asarray(ts, np.float64)
    return Table({"ts": ts,
                  "v": np.asarray(vals if vals is not None else ts)})


def test_event_time_tumbling_windows_close_on_watermark():
    # windows of size 10; rows arrive slightly out of order within windows
    stream = [_timed([1, 5, 3]), _timed([12, 8]), _timed([25])]
    out = list(EventTimeWindows(stream, "ts", 10.0))
    # window [0,10) closes when watermark (max ts) reaches 10 -> after t=12
    # window [10,20) closes when ts=25 arrives; [20,30) flushes at stream end
    assert len(out) == 3
    np.testing.assert_array_equal(sorted(np.asarray(out[0]["ts"])),
                                  [1, 3, 5, 8])
    np.testing.assert_array_equal(np.asarray(out[1]["ts"]), [12])
    np.testing.assert_array_equal(np.asarray(out[2]["ts"]), [25])


def test_event_time_late_rows_dropped():
    # ts=2 arrives after the watermark passed 10 -> dropped
    stream = [_timed([1, 11]), _timed([2, 13])]
    out = list(EventTimeWindows(stream, "ts", 10.0))
    all_ts = np.concatenate([np.asarray(w["ts"]) for w in out])
    assert 2.0 not in all_ts
    assert {1.0, 11.0, 13.0} <= set(all_ts)


def test_event_time_allowed_lateness_keeps_late_rows():
    stream = [_timed([1, 11]), _timed([2, 13])]
    out = list(EventTimeWindows(stream, "ts", 10.0, allowed_lateness=20.0))
    all_ts = np.concatenate([np.asarray(w["ts"]) for w in out])
    assert 2.0 in all_ts


def test_event_time_snapshot_restore_skips_emitted():
    stream = lambda: [_timed([1, 5]), _timed([12]), _timed([25])]  # noqa: E731
    src = EventTimeWindows(stream(), "ts", 10.0)
    it = iter(src)
    first = next(it)
    snap = src.snapshot()
    fresh = EventTimeWindows(stream(), "ts", 10.0)
    fresh.restore(snap)
    remaining = list(fresh)
    assert len(remaining) == 2
    assert float(np.asarray(first["ts"]).max()) < float(
        np.asarray(remaining[0]["ts"]).min())


# -------------------------------------------------------------- windows_of


def test_windows_of_table_and_feed_and_windows():
    assert [w.num_rows for w in windows_of(_t(5), 2)] == [2, 2, 1]
    # live feeds pass through unchanged (the feed's framing is the windowing)
    feed = [_t(3), _t(5)]
    assert [w.num_rows for w in windows_of(iter(feed), 2)] == [3, 5]
    # an explicit windowing object is consumed as-is
    assert [w.num_rows
            for w in windows_of(CountWindows(_t(5), 4), 999)] == [4, 1]


def test_online_models_consume_event_time_windows(rng):
    """A time-windowed stream feeds an online estimator directly — the
    shared substrate replaces per-model windowing."""
    from flink_ml_tpu.models.feature import OnlineStandardScaler

    X = rng.normal(size=(300, 3)) * 2.0 + 5.0
    ts = np.arange(300, dtype=np.float64)
    stream = EventTimeWindows(
        [Table({"features": X[i:i + 50], "ts": ts[i:i + 50]})
         for i in range(0, 300, 50)], "ts", 100.0)
    model = OnlineStandardScaler().fit(stream)
    got_mean = np.asarray(model.get_model_data()[0]["mean"][0])
    np.testing.assert_allclose(got_mean, X.mean(axis=0), atol=1e-9)
    assert model.model_version == 3  # three closed [0,100) windows


def test_event_time_out_of_order_rows_join_open_windows():
    # ts=12 arrives after ts=15 advanced the watermark; window [10,20) is
    # still open, so 12 must join it (only CLOSED windows reject rows)
    stream = [_timed([1, 15]), _timed([12])]
    out = list(EventTimeWindows(stream, "ts", 10.0))
    assert len(out) == 2
    np.testing.assert_array_equal(sorted(np.asarray(out[1]["ts"])), [12, 15])
