"""Pipeline composition + persistence — mirror of ``PipelineTest.java``."""

import numpy as np

from flink_ml_tpu import Pipeline, PipelineModel, Table
from flink_ml_tpu.utils import persist

from example_stages import PlusOne, SumEstimator, SumModel


def _table(values):
    return Table({"x": np.asarray(values, dtype=np.int64)})


def test_pipeline_fit_transform():
    # [PlusOne, SumEstimator, PlusOne]: fit transforms inputs up to the last
    # estimator only (Pipeline.java:74-103 semantics).
    pipeline = Pipeline([PlusOne(), SumEstimator(), PlusOne()])
    model = pipeline.fit(_table([1, 2, 3]))
    assert isinstance(model, PipelineModel)
    # SumEstimator sees [2,3,4] -> delta 9; transform: +1, +9, +1
    out = model.transform(_table([10]))[0]
    np.testing.assert_array_equal(out["x"], [21])


def test_pipeline_with_trailing_estimator():
    pipeline = Pipeline([SumEstimator()])
    model = pipeline.fit(_table([1, 2, 3]))
    out = model.transform(_table([0, 1]))[0]
    np.testing.assert_array_equal(out["x"], [6, 7])


def test_pipeline_model_chaining():
    m1, m2 = SumModel().set("delta", 1), SumModel().set("delta", 10)
    chained = PipelineModel([m1, m2])
    out = chained.transform(_table([5]))[0]
    np.testing.assert_array_equal(out["x"], [16])


def test_pipeline_save_load(tmp_path):
    path = str(tmp_path / "pipeline")
    pipeline = Pipeline([PlusOne(), SumEstimator()])
    pipeline.save(path)
    loaded = Pipeline.load(path)
    assert len(loaded.stages) == 2
    assert isinstance(loaded.stages[0], PlusOne)
    assert isinstance(loaded.stages[1], SumEstimator)
    model = loaded.fit(_table([1, 2, 3]))
    out = model.transform(_table([0]))[0]
    np.testing.assert_array_equal(out["x"], [10])


def test_pipeline_model_save_load(tmp_path):
    path = str(tmp_path / "pm")
    model = Pipeline([PlusOne(), SumEstimator()]).fit(_table([1, 2, 3]))
    model.transform(_table([0]))  # exercise before save
    model.save(path)
    loaded = PipelineModel.load(path)
    out = loaded.transform(_table([0]))[0]
    np.testing.assert_array_equal(out["x"], [10])
    # generic reflective load resolves PipelineModel from metadata
    loaded2 = persist.load_stage(path)
    assert isinstance(loaded2, PipelineModel)


def test_sum_model_data_round_trip():
    model = SumModel()
    model.set_model_data(Table({"delta": np.array([7])}))
    (data,) = model.get_model_data()
    assert int(data["delta"][0]) == 7
