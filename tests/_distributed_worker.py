"""Worker process for the two-process jax.distributed test tier (the
MiniCluster analog — see tests/test_distributed_multiprocess.py).

Run as: python tests/_distributed_worker.py <coordinator> <nprocs> <pid> <outdir>

Exercises the real multi-process branches of parallel/distributed.py
(initialize, global_mesh, host_local_to_global, barrier,
broadcast_from_host0, global_to_host_local) plus a data-parallel iterate fit
with the multi-host checkpoint path (process-0 writes + cross-host barrier),
then writes a result JSON the parent compares across processes.
"""

import json
import os
import sys


def main() -> None:
    coord, nprocs, pid, outdir = (sys.argv[1], int(sys.argv[2]),
                                  int(sys.argv[3]), sys.argv[4])
    import jax

    # The environment's sitecustomize imports jax and initializes the axon
    # backend at interpreter startup — before this script runs.  Tear the
    # live backend down and pin a 2-device CPU platform so the distributed
    # runtime owns backend creation (the same dance as
    # __graft_entry__.dryrun_multichip).
    from jax.extend.backend import clear_backends

    clear_backends()
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 2)

    from flink_ml_tpu.parallel import distributed as dist

    dist.initialize(coordinator_address=coord, num_processes=nprocs,
                    process_id=pid)
    info = dist.process_info()
    assert info.process_count == nprocs, info
    assert info.global_device_count == 2 * nprocs, info  # 2 cpu devs/host
    assert info.is_coordinator == (pid == 0)

    import jax.numpy as jnp
    import numpy as np

    mesh = dist.global_mesh()
    assert int(mesh.shape["data"]) == 2 * nprocs

    # host-local -> global: host p contributes rows [4p, 4p+4)
    local = np.arange(pid * 4, pid * 4 + 4, dtype=np.float32)
    global_arr = dist.host_local_to_global(local, mesh, axis="data")
    assert not global_arr.is_fully_addressable
    total = float(np.asarray(jax.jit(jnp.sum)(global_arr)))
    assert total == sum(range(4 * nprocs)), total

    # global -> host-local round trip returns this host's own rows
    back = dist.global_to_host_local(global_arr, mesh, axis="data")
    np.testing.assert_array_equal(np.asarray(back), local)

    dist.barrier("after-ingest")
    v = dist.broadcast_from_host0(np.asarray([123.0 + pid]))
    assert float(np.asarray(v)[0]) == 123.0, v  # host 0's value everywhere

    # data-parallel iterate + the multi-host checkpoint path: every epoch
    # all processes enter save_pytree (collective assembly + barrier),
    # process 0 writes, everyone restores the same bytes on resume
    from flink_ml_tpu.iteration import (
        IterationBodyResult,
        IterationConfig,
        iterate,
    )
    from flink_ml_tpu.iteration.checkpoint import CheckpointConfig

    def body(w, epoch, d):
        return IterationBodyResult(w + jnp.sum(d))

    ck = os.path.join(outdir, "ck")  # same dir: the shared-filesystem setup
    res = iterate(body, jnp.asarray(0.0, jnp.float32), global_arr,
                  max_epochs=3, config=IterationConfig(mode="hosted"),
                  checkpoint=CheckpointConfig(ck))
    resumed = iterate(body, jnp.asarray(0.0, jnp.float32), global_arr,
                      max_epochs=5, config=IterationConfig(mode="hosted"),
                      checkpoint=CheckpointConfig(ck), resume=True)

    out = {
        "pid": pid,
        "global_devices": info.global_device_count,
        "total": total,
        "final": float(np.asarray(jax.device_get(res.state))),
        "resumed": float(np.asarray(jax.device_get(resumed.state))),
    }
    with open(os.path.join(outdir, f"result_{pid}.json"), "w") as f:
        json.dump(out, f)
    dist.barrier("done")


if __name__ == "__main__":
    main()
