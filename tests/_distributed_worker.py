"""Worker process for the two-process jax.distributed test tier (the
MiniCluster analog — see tests/test_distributed_multiprocess.py).

Run as: python tests/_distributed_worker.py <coordinator> <nprocs> <pid> <outdir>

Exercises the real multi-process branches of parallel/distributed.py
(initialize, global_mesh, host_local_to_global, barrier,
broadcast_from_host0, global_to_host_local) plus a data-parallel iterate fit
with the multi-host checkpoint path (process-0 writes + cross-host barrier),
then writes a result JSON the parent compares across processes.
"""

import json
import os
import sys


def main() -> None:
    coord, nprocs, pid, outdir = (sys.argv[1], int(sys.argv[2]),
                                  int(sys.argv[3]), sys.argv[4])
    # The environment's sitecustomize may import jax and register/initialize
    # the axon backend at interpreter startup — before this script runs.
    # Pin a 2-device CPU platform so the distributed runtime owns backend
    # creation (shared helper: handles the teardown-before-config ordering
    # and never touches a possibly-dead TPU relay).
    from flink_ml_tpu.utils.backend import force_virtual_cpu

    force_virtual_cpu(2, verify=False)  # jax.distributed owns backend init

    import jax

    from flink_ml_tpu.parallel import distributed as dist

    dist.initialize(coordinator_address=coord, num_processes=nprocs,
                    process_id=pid)
    info = dist.process_info()
    assert info.process_count == nprocs, info
    assert info.global_device_count == 2 * nprocs, info  # 2 cpu devs/host
    assert info.is_coordinator == (pid == 0)

    import jax.numpy as jnp
    import numpy as np

    mesh = dist.global_mesh()
    assert int(mesh.shape["data"]) == 2 * nprocs

    # host-local -> global: host p contributes rows [4p, 4p+4)
    local = np.arange(pid * 4, pid * 4 + 4, dtype=np.float32)
    global_arr = dist.host_local_to_global(local, mesh, axis="data")
    assert not global_arr.is_fully_addressable
    total = float(np.asarray(jax.jit(jnp.sum)(global_arr)))
    assert total == sum(range(4 * nprocs)), total

    # global -> host-local round trip returns this host's own rows
    back = dist.global_to_host_local(global_arr, mesh, axis="data")
    np.testing.assert_array_equal(np.asarray(back), local)

    dist.barrier("after-ingest")
    v = dist.broadcast_from_host0(np.asarray([123.0 + pid]))
    assert float(np.asarray(v)[0]) == 123.0, v  # host 0's value everywhere

    # data-parallel iterate + the multi-host checkpoint path: every epoch
    # all processes enter save_pytree (collective assembly + barrier),
    # process 0 writes, everyone restores the same bytes on resume
    from flink_ml_tpu.iteration import (
        IterationBodyResult,
        IterationConfig,
        iterate,
    )
    from flink_ml_tpu.iteration.checkpoint import CheckpointConfig

    def body(w, epoch, d):
        return IterationBodyResult(w + jnp.sum(d))

    ck = os.path.join(outdir, "ck")  # same dir: the shared-filesystem setup
    res = iterate(body, jnp.asarray(0.0, jnp.float32), global_arr,
                  max_epochs=3, config=IterationConfig(mode="hosted"),
                  checkpoint=CheckpointConfig(ck))
    resumed = iterate(body, jnp.asarray(0.0, jnp.float32), global_arr,
                      max_epochs=5, config=IterationConfig(mode="hosted"),
                      checkpoint=CheckpointConfig(ck), resume=True)

    # multi-host trainer: sgd_fit_mixed over the process-spanning mesh.
    # Every process passes ITS shard; the result must equal a manual
    # single-program update loop over the concatenated global batches
    # (both shards are deterministic functions of pid, so every process
    # can compute the oracle locally).
    from flink_ml_tpu.models.common.losses import LOSSES
    from flink_ml_tpu.models.common.sgd import (
        SGDConfig,
        _mixed_update,
        sgd_fit_mixed,
    )

    def shard(p):
        srng = np.random.default_rng(100 + p)
        nloc, nd, nc, dim = 64, 3, 2, 256
        dense = srng.normal(size=(nloc, nd)).astype(np.float32)
        cat = srng.integers(nd, dim, size=(nloc, nc)).astype(np.int32)
        y = (dense[:, 0] > 0).astype(np.float64)
        return dense, cat, y

    cfg = SGDConfig(learning_rate=0.3, max_epochs=3, tol=0, seed=0,
                    global_batch_size=16)
    dense_l, cat_l, y_l = shard(pid)
    state, log = sgd_fit_mixed(LOSSES["logistic"], dense_l, cat_l, y_l,
                               None, 256, cfg, mesh=mesh)

    # tol > 0 works across hosts: the termination vote is a replicated
    # scalar inside the fused while_loop and num_epochs reads back from
    # the local replica (no cross-host round-trip per epoch)
    state_t, log_t = sgd_fit_mixed(
        LOSSES["logistic"], dense_l, cat_l, y_l, None, 256,
        SGDConfig(learning_rate=0.3, max_epochs=4, tol=1e-6,
                  global_batch_size=16), mesh=mesh)
    assert 1 <= len(log_t) <= 4
    assert np.isfinite(state_t.coefficients).all()

    # oracle: global batch = [proc0 local batch | proc1 local batch] per
    # step, each locally shuffled by the same seed (the layout
    # _plan_epoch_layout_for_mesh produces)
    from flink_ml_tpu.models.common.sgd import prepare_epoch_tensor

    local_batch = 16 // nprocs
    steps = 64 // local_batch
    parts = []
    for p in range(nprocs):
        dp, cp, yp = shard(p)
        perm = np.random.default_rng(cfg.seed).permutation(64)
        parts.append((
            prepare_epoch_tensor(dp, perm, steps, local_batch),
            prepare_epoch_tensor(cp, perm, steps, local_batch),
            prepare_epoch_tensor(yp.astype(np.float32), perm, steps,
                                 local_batch)))
    g_dense = np.concatenate([q[0] for q in parts], axis=1)
    g_cat = np.concatenate([q[1] for q in parts], axis=1)
    g_y = np.concatenate([q[2] for q in parts], axis=1)

    update = jax.jit(_mixed_update(LOSSES["logistic"], cfg))
    params = {"w": jnp.zeros((256,), jnp.float32),
              "b": jnp.zeros((), jnp.float32)}
    ones = np.ones((16,), np.float32)
    oracle_log = []
    for _ in range(cfg.max_epochs):
        losses = []
        for s in range(steps):
            params, value = update(params, g_dense[s], g_cat[s], g_y[s],
                                   ones)
            losses.append(float(value))
        oracle_log.append(float(np.mean(losses)))
    np.testing.assert_allclose(state.coefficients,
                               np.asarray(params["w"], np.float64),
                               atol=1e-5)
    np.testing.assert_allclose(log, oracle_log, atol=1e-5)
    assert log[-1] < log[0]

    # multi-host STREAMING fit (r4): each process feeds its own reader
    # (its own data shard, the parallelism-P source posture); the global
    # batch is the per-step concatenation over processes, assembled by
    # make_array_from_process_local_data inside the prefetch pipeline.
    # Must equal a manual single-program loop over the concatenated
    # batches (deterministic shards => every process computes the oracle).
    from flink_ml_tpu.models.common.sgd import sgd_fit_outofcore

    def stream_shard(p):
        srng = np.random.default_rng(300 + p)
        nloc, nd2, nc2 = 96, 3, 2
        return (srng.normal(size=(nloc, nd2)).astype(np.float32),
                srng.integers(nd2, 256, size=(nloc, nc2)).astype(np.int32),
                (srng.normal(size=nloc) > 0).astype(np.float32))

    def make_stream_reader():
        d_l, c_l, y_loc = stream_shard(pid)
        return iter([{"fd": d_l[i:i + 32], "fi": c_l[i:i + 32],
                      "label": y_loc[i:i + 32]} for i in range(0, 96, 32)])

    scfg = SGDConfig(learning_rate=0.4, max_epochs=2, tol=0)
    st_state, st_log = sgd_fit_outofcore(
        LOSSES["logistic"], make_stream_reader, num_features=256,
        config=scfg, mesh=mesh, dense_key="fd", indices_key="fi")
    assert st_state.planned_impl == "xla-stream"

    # cross-process SHARDED ELL streaming (r4): per-host decode builds
    # its own devices' layout stacks; forced plan (the planner is
    # TPU-gated) runs the kernel's XLA twin through the full multi-host
    # wiring.  Must equal the xla-stream fit above... on the same data
    # but the ELL layout needs an ELL-supported hash space, so rerun
    # both paths at d=128*128 and compare against each other.
    from flink_ml_tpu.models.common import sgd as S

    d_ell = 128 * 128

    def make_stream_reader_ell():
        d_l, c_l, y_loc = stream_shard(pid)
        c_big = (c_l.astype(np.int64) * 131) % (d_ell - 3) + 3
        return iter([{"fd": d_l[i:i + 32],
                      "fi": c_big[i:i + 32].astype(np.int32),
                      "label": y_loc[i:i + 32]} for i in range(0, 96, 32)])

    real_plan = S.plan_mixed_impl
    S.plan_mixed_impl = lambda *a, **k: "ell"
    try:
        ell_state, ell_log = sgd_fit_outofcore(
            LOSSES["logistic"], make_stream_reader_ell, num_features=d_ell,
            config=scfg, mesh=mesh, dense_key="fd", indices_key="fi")
    finally:
        S.plan_mixed_impl = real_plan
    assert ell_state.planned_impl == "ell-stream"
    xla_state, xla_log = sgd_fit_outofcore(
        LOSSES["logistic"], make_stream_reader_ell, num_features=d_ell,
        config=scfg, mesh=mesh, dense_key="fd", indices_key="fi")
    np.testing.assert_allclose(ell_state.coefficients,
                               xla_state.coefficients, atol=1e-5)
    np.testing.assert_allclose(ell_log, xla_log, atol=1e-6)

    st_update = jax.jit(_mixed_update(LOSSES["logistic"], scfg))
    sp = {"w": jnp.zeros((256,), jnp.float32),
          "b": jnp.zeros((), jnp.float32)}
    shards = [stream_shard(p) for p in range(nprocs)]
    s_log = []
    for _ in range(scfg.max_epochs):
        losses = []
        for i in range(0, 96, 32):
            gd = np.concatenate([sh[0][i:i + 32] for sh in shards])
            gc = np.concatenate([sh[1][i:i + 32] for sh in shards])
            gy = np.concatenate([sh[2][i:i + 32] for sh in shards])
            sp, v = st_update(sp, gd, gc, gy,
                              np.ones(len(gy), np.float32))
            losses.append(float(v))
        s_log.append(float(np.mean(losses)))
    np.testing.assert_allclose(st_state.coefficients,
                               np.asarray(sp["w"], np.float64), atol=1e-5)
    np.testing.assert_allclose(st_log, s_log, atol=1e-5)

    # dp x model over 2 OS processes (VERDICT r3 task 5): the weight
    # itself sharded over the 'model' axis with the same shards living on
    # BOTH hosts' devices — the final fetch is a cross-process allgather
    # of the model axis (mesh.fetch_replicated).  Must equal the
    # data-parallel fit above exactly.
    from flink_ml_tpu.parallel.mesh import device_mesh

    dpmp_mesh = device_mesh({"data": nprocs, "model": 2},
                            devices=jax.devices())
    state_mp, log_mp = sgd_fit_mixed(LOSSES["logistic"], dense_l, cat_l,
                                     y_l, None, 256, cfg, mesh=dpmp_mesh)
    assert state_mp.planned_impl == "sharded"
    np.testing.assert_allclose(state_mp.coefficients, state.coefficients,
                               atol=1e-5)
    np.testing.assert_allclose(log_mp, log, atol=1e-5)

    # multi-host STREAMING Wide&Deep (r4): each process streams its own
    # shard through fit_outofcore over the process-spanning mesh; the
    # fitted params must equal a manual single-program Adam loop over
    # the concatenated per-step batches with the same init.
    from flink_ml_tpu.models.recommendation.widedeep import (
        WideDeep,
        _make_train_ops,
        _validate_cat_ids,
        init_params,
    )

    wd_vocab = [9, 5]

    def wd_shard(p):
        srng = np.random.default_rng(500 + p)
        nloc = 64
        return (srng.normal(size=(nloc, 3)).astype(np.float32),
                np.stack([srng.integers(0, v, size=nloc)
                          for v in wd_vocab], 1).astype(np.int32),
                srng.integers(0, 2, size=nloc).astype(np.float32))

    def wd_reader():
        wdn, wcn, wyn = wd_shard(pid)
        return iter([{"denseFeatures": wdn[i:i + 16],
                      "catFeatures": wcn[i:i + 16],
                      "label": wyn[i:i + 16]} for i in range(0, 64, 16)])

    wd_est = (WideDeep().set_vocab_sizes(wd_vocab).set_max_iter(2)
              .set_seed(0))
    wd_model = wd_est.fit_outofcore(wd_reader, mesh=mesh)

    wd_oracle = init_params(np.random.default_rng(1), 3, wd_vocab, 8,
                            (64, 32))
    wd_step, wd_opt = _make_train_ops(wd_oracle, 1e-2, False)
    wd_step = jax.jit(wd_step)
    wd_shards = [wd_shard(p) for p in range(nprocs)]
    import jax.numpy as _jnp
    wd_oracle = jax.tree_util.tree_map(_jnp.asarray, wd_oracle)
    for _ in range(2):
        for i in range(0, 64, 16):
            gdn = np.concatenate([s[0][i:i + 16] for s in wd_shards])
            gcn = np.concatenate(
                [_validate_cat_ids(s[1][i:i + 16], wd_vocab)
                 for s in wd_shards])
            gyn = np.concatenate([s[2][i:i + 16] for s in wd_shards])
            wd_oracle, wd_opt, _ = wd_step(
                wd_oracle, wd_opt, gdn, gcn, gyn,
                np.ones(len(gyn), np.float32))
    for a, b in zip(jax.tree_util.tree_leaves(wd_model._params),
                    jax.tree_util.tree_leaves(jax.device_get(wd_oracle))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)

    # multi-host KMeans: each host holds a different half of 4 separated
    # clusters; the replicated centroids must recover all 4 means on BOTH
    # hosts (host 0's local selection seeds the global init).
    from flink_ml_tpu import Table
    from flink_ml_tpu.models.clustering import KMeans
    from flink_ml_tpu.parallel.mesh import use_mesh

    # The distributed-correctness assert: multi-host KMeans must equal a
    # manual single-program Lloyd's run on the concatenated shards with
    # the same init (clustering QUALITY is a property of Lloyd's, not of
    # the distribution — only exact equivalence catches sharding bugs).
    centers = np.asarray([[10.0, 0.0], [-10.0, 0.0],
                          [0.0, 10.0], [0.0, -10.0]], np.float32)

    def kshard(p):
        srng = np.random.default_rng(7 + p)
        return np.concatenate([
            c + srng.normal(scale=0.3, size=(16, 2)).astype(np.float32)
            for c in centers])

    pts = kshard(pid)
    with use_mesh(mesh):
        km_model = (KMeans().set_k(4).set_max_iter(20).set_seed(3)
                    .fit(Table({"features": pts})))
    got = np.asarray(km_model.get_model_data()[0]["centroids"][0])

    from flink_ml_tpu.distance import DistanceMeasure
    from flink_ml_tpu.models.clustering.kmeans import (
        kmeans_epoch_step,
        select_random_centroids,
    )

    all_pts = np.concatenate([kshard(p) for p in range(nprocs)])
    oracle_c = jnp.asarray(select_random_centroids(kshard(0), 4, 3))
    body = kmeans_epoch_step(DistanceMeasure.get_instance("euclidean"), 4)
    omask = jnp.ones((len(all_pts),), jnp.float32)
    opts = jnp.asarray(all_pts)
    for _ in range(20):
        oracle_c = body(oracle_c, 0, (opts, omask)).feedback
    np.testing.assert_allclose(got, np.asarray(oracle_c), atol=1e-4)

    # hybrid dcn x data mesh over the 2 REAL processes: hierarchical
    # gradient reduction — exact reduce_scatter over each host's local
    # 'data' axis, the (compressed) all-reduce over the cross-host 'dcn'
    # axis, gather back — asserted against the single-program oracle
    # (inputs are deterministic in pid, so every process computes it).
    from jax.sharding import PartitionSpec as P

    from flink_ml_tpu.parallel import grad_reduce as GR
    from flink_ml_tpu.parallel.collectives import shard_map_fn
    from flink_ml_tpu.parallel.grad_reduce import GradReduceConfig
    from flink_ml_tpu.parallel.mesh import fetch_replicated, put_sharded

    hmesh = dist.hybrid_mesh({"data": 2})     # (dcn=2 hosts, data=2 devs)
    assert dict(hmesh.shape) == {"dcn": 2, "data": 2}
    d_red, n_red = 32, 4
    g_all = np.random.default_rng(900).normal(
        size=(n_red, d_red)).astype(np.float32)
    dev_spec = P(("dcn", "data"), None)
    g_stack = put_sharded(g_all[pid * 2:(pid + 1) * 2], hmesh, dev_spec)

    def run_reduce(cfg_gr):
        state = GR.init_state(cfg_gr, {"g": np.zeros((d_red,), np.float32)},
                              n_red)
        state = jax.tree_util.tree_map(
            lambda a: put_sharded(np.asarray(a)[pid * 2:(pid + 1) * 2],
                                  hmesh, dev_spec), state)

        def body(g, st):
            red, new_st = GR.reduce_gradients(
                {"g": g[0]}, GR.squeeze_state(st), cfg_gr)
            return red["g"][None], GR.unsqueeze_state(new_st)

        fn = shard_map_fn(body, hmesh, in_specs=(dev_spec, dev_spec),
                          out_specs=(dev_spec, dev_spec))
        red, _ = jax.jit(fn)(g_stack, state)
        red = fetch_replicated(red)          # (n_red, d) — rows identical
        np.testing.assert_array_equal(red, np.broadcast_to(red[:1],
                                                           red.shape))
        return red[0]

    # exact hierarchical == plain global sum (up to f32 order)
    np.testing.assert_allclose(
        run_reduce(GradReduceConfig(mode="exact", axis="data",
                                    dcn_axis="dcn")),
        g_all.sum(0), atol=1e-5)

    # topk hierarchical == the shard-domain EF oracle: each dcn member
    # reduces its host's 2-device group exactly, then sends its per-shard
    # top-k over the dcn hop
    density = 0.25
    shard_len = d_red // 2
    k = max(1, int(shard_len * density))
    expected = np.zeros((d_red,), np.float32)
    for m in range(2):                        # dcn members
        ici_sum = g_all[m * 2:(m + 1) * 2].sum(0)
        for i in range(2):                    # data positions -> shards
            sl = slice(i * shard_len, (i + 1) * shard_len)
            acc = ici_sum[sl]
            order = np.argsort(-np.abs(acc), kind="stable")[:k]
            sent = np.zeros_like(acc)
            sent[order] = acc[order]
            expected[sl] += sent
    np.testing.assert_allclose(
        run_reduce(GradReduceConfig(mode="topk", density=density,
                                    axis="data", dcn_axis="dcn")),
        expected, atol=1e-5)

    out = {
        "pid": pid,
        "grad_reduce_dcn_ok": True,
        "global_devices": info.global_device_count,
        "total": total,
        "final": float(np.asarray(jax.device_get(res.state))),
        "resumed": float(np.asarray(jax.device_get(resumed.state))),
        "mixed_lr_final_loss": float(log[-1]),
        "mixed_lr_w0": float(state.coefficients[0]),
        "kmeans_c00": float(got[0, 0]),
    }
    with open(os.path.join(outdir, f"result_{pid}.json"), "w") as f:
        json.dump(out, f)
    dist.barrier("done")


if __name__ == "__main__":
    main()
