"""HashingTF / IDF / FeatureHasher / IndexToString (models/feature/text.py)."""

import numpy as np
import pytest

from flink_ml_tpu import Table
from flink_ml_tpu.models.feature import (
    FeatureHasher,
    HashingTF,
    IDF,
    IDFModel,
    IndexToString,
)
from flink_ml_tpu.models.feature.text import _fnv1a


def test_fnv1a_deterministic_and_no_overflow_warning():
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("error")  # any RuntimeWarning fails the test
        a = _fnv1a("some token")
        b = _fnv1a("some token")
    assert a == b
    assert 0 <= a < (1 << 64)
    assert _fnv1a("x") != _fnv1a("y")


def _docs_table():
    docs = np.empty((3,), object)
    docs[0] = ["a", "b", "a"]
    docs[1] = ["b"]
    docs[2] = ["c", "c", "c"]
    return Table({"features": docs})


def test_hashingtf_counts_and_binary():
    tf = (HashingTF().set_num_features(32)
          .set_features_col("features").set_output_col("tf"))
    out = tf.transform(_docs_table())[0]
    mat = np.asarray(out["tf"])
    assert mat.shape == (3, 32)
    # row sums = token counts; "a" hashed twice in doc 0
    np.testing.assert_array_equal(mat.sum(axis=1), [3, 1, 3])
    slot_a = _fnv1a("a") % 32
    assert mat[0, slot_a] == 2.0

    binary = tf.set_binary(True).transform(_docs_table())[0]
    bmat = np.asarray(binary["tf"])
    assert set(np.unique(bmat)) <= {0.0, 1.0}


def test_idf_fit_transform_roundtrip(tmp_path):
    tf = np.asarray([[1.0, 0.0], [1.0, 2.0], [1.0, 0.0]])
    table = Table({"features": tf})
    idf = IDF().set_features_col("features").set_output_col("scaled")
    model = idf.fit(table)
    # df = [3, 1]; idf = log((3+1)/(df+1))
    expected_idf = np.log([4.0 / 4.0, 4.0 / 2.0])
    got_idf = np.asarray(model.get_model_data()[0]["idf"][0])
    np.testing.assert_allclose(got_idf, expected_idf, atol=1e-6)
    out = model.transform(table)[0]
    np.testing.assert_allclose(np.asarray(out["scaled"]),
                               tf * expected_idf[None, :], atol=1e-5)

    model.save(str(tmp_path / "idf"))
    re = IDFModel.load(str(tmp_path / "idf"))
    np.testing.assert_allclose(
        np.asarray(re.transform(table)[0]["scaled"]),
        np.asarray(out["scaled"]), atol=1e-6)


def test_idf_min_doc_freq_zeroes_rare_terms():
    tf = np.asarray([[1.0, 0.0], [1.0, 2.0], [1.0, 0.0]])
    model = (IDF().set_min_doc_freq(2).set_features_col("features")
             .set_output_col("o").fit(Table({"features": tf})))
    got = np.asarray(model.get_model_data()[0]["idf"][0])
    assert got[1] == 0.0  # df=1 < 2


def test_feature_hasher_numeric_and_categorical():
    t = Table({"age": np.asarray([30.0, 40.0]),
               "city": np.asarray(["sf", "nyc"])})
    fh = (FeatureHasher().set_input_cols("age", "city")
          .set_num_features(64).set_output_col("hashed"))
    out = fh.transform(t)[0]
    mat = np.asarray(out["hashed"])
    assert mat.shape == (2, 64)
    # numeric column lands its value at hash(colName)
    assert mat[0, _fnv1a("age") % 64] == 30.0
    # categorical column adds 1 at hash(col=value)
    assert mat[0, _fnv1a("city=sf") % 64] == 1.0
    assert mat[1, _fnv1a("city=nyc") % 64] == 1.0


def test_feature_hasher_requires_input_cols():
    with pytest.raises(ValueError, match="inputCols"):
        (FeatureHasher().set_output_col("h")
         .transform(Table({"x": np.asarray([1.0])})))


def test_index_to_string_roundtrip(tmp_path):
    its = (IndexToString().set_labels(["red", "green", "blue"])
           .set_features_col("idx").set_output_col("color"))
    out = its.transform(Table({"idx": np.asarray([2, 0, 1])}))[0]
    np.testing.assert_array_equal(np.asarray(out["color"]),
                                  ["blue", "red", "green"])
    with pytest.raises(ValueError, match="out of range"):
        its.transform(Table({"idx": np.asarray([3])}))

    its.save(str(tmp_path / "its"))
    re = IndexToString.load(str(tmp_path / "its"))
    out2 = re.transform(Table({"idx": np.asarray([1])}))[0]
    np.testing.assert_array_equal(np.asarray(out2["color"]), ["green"])
