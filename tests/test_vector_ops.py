"""VectorSlicer / ElementwiseProduct / Interaction / DCT /
KBinsDiscretizer / VectorIndexer."""

import numpy as np
import pytest

from flink_ml_tpu import Table
from flink_ml_tpu.models.feature import (
    DCT,
    ElementwiseProduct,
    Interaction,
    KBinsDiscretizer,
    KBinsDiscretizerModel,
    VectorIndexer,
    VectorIndexerModel,
    VectorSlicer,
)


def _t(X):
    return Table({"features": np.asarray(X, np.float64)})


def test_vector_slicer_selects_and_reorders():
    out = (VectorSlicer().set_indices(2, 0, 2)
           .transform(_t([[1.0, 2.0, 3.0], [4.0, 5.0, 6.0]]))[0])
    np.testing.assert_array_equal(np.asarray(out["output"]),
                                  [[3.0, 1.0, 3.0], [6.0, 4.0, 6.0]])


def test_vector_slicer_rejects_out_of_range():
    with pytest.raises(ValueError, match="out of range"):
        VectorSlicer().set_indices(3).transform(_t([[1.0, 2.0]]))


def test_elementwise_product():
    out = (ElementwiseProduct().set_scaling_vec(2.0, 0.5)
           .transform(_t([[1.0, 4.0], [3.0, 8.0]]))[0])
    np.testing.assert_array_equal(np.asarray(out["output"]),
                                  [[2.0, 2.0], [6.0, 4.0]])
    with pytest.raises(ValueError, match="dim"):
        (ElementwiseProduct().set_scaling_vec(1.0)
         .transform(_t([[1.0, 2.0]])))


def test_interaction_matches_nested_loop_order():
    t = Table({
        "a": np.array([2.0, 3.0]),                       # scalar column
        "b": np.array([[1.0, 2.0], [3.0, 4.0]]),
        "c": np.array([[5.0, 6.0], [7.0, 8.0]]),
    })
    out = (Interaction().set_input_cols("a", "b", "c")
           .transform(t)[0])
    got = np.asarray(out["output"])
    # row 0: 2 * [1,2] (x) [5,6] -> [2*1*5, 2*1*6, 2*2*5, 2*2*6]
    np.testing.assert_allclose(got[0], [10.0, 12.0, 20.0, 24.0])
    np.testing.assert_allclose(got[1], [3 * 3 * 7, 3 * 3 * 8,
                                        3 * 4 * 7, 3 * 4 * 8])


def test_interaction_needs_two_columns():
    with pytest.raises(ValueError, match=">= 2"):
        Interaction().set_input_cols("a").transform(
            Table({"a": np.array([1.0])}))


def test_dct_roundtrip_and_orthonormality():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(4, 8))
    fwd = DCT().transform(_t(X))[0]
    Y = np.asarray(fwd["output"])
    # Parseval: orthonormal transform preserves row norms
    np.testing.assert_allclose(np.linalg.norm(Y, axis=1),
                               np.linalg.norm(X, axis=1), rtol=1e-5)
    back = (DCT().set_inverse(True)
            .transform(Table({"features": Y}))[0])
    np.testing.assert_allclose(np.asarray(back["output"]), X,
                               rtol=1e-4, atol=1e-5)


def test_dct_constant_row_concentrates_in_dc():
    out = DCT().transform(_t([[1.0, 1.0, 1.0, 1.0]]))[0]
    got = np.asarray(out["output"])[0]
    np.testing.assert_allclose(got, [2.0, 0.0, 0.0, 0.0], atol=1e-6)


def test_kbins_uniform():
    X = np.array([[0.0], [2.5], [4.9], [5.0], [10.0]])
    model = (KBinsDiscretizer().set_num_bins(2).set_strategy("uniform")
             .fit(_t(X)))
    out = model.transform(_t(X))[0]
    np.testing.assert_array_equal(np.asarray(out["output"]).ravel(),
                                  [0, 0, 0, 1, 1])


def test_kbins_quantile_balances_counts():
    rng = np.random.default_rng(1)
    X = rng.normal(size=(1000, 1))
    model = (KBinsDiscretizer().set_num_bins(4).set_strategy("quantile")
             .fit(_t(X)))
    out = np.asarray(model.transform(_t(X))[0]["output"]).ravel()
    counts = np.bincount(out.astype(int), minlength=4)
    assert counts.min() > 200      # ~250 each for quantile bins


def test_kbins_quantile_collapses_duplicate_edges():
    # skewed: 90% zeros -> duplicate quantile edges collapse
    X = np.concatenate([np.zeros(90), np.arange(1, 11)])[:, None]
    model = (KBinsDiscretizer().set_num_bins(5).set_strategy("quantile")
             .fit(_t(X)))
    out = np.asarray(model.transform(_t(X))[0]["output"]).ravel()
    assert out.max() < 5 and out.min() == 0


def test_kbins_constant_column_single_bin_all_strategies():
    # ADVICE r2: uniform on a constant column used to emit k+1 identical
    # edges, bucketing every value into bin k-1 while quantile gave bin 0;
    # all strategies now agree on the single-bin degenerate layout
    X = np.concatenate([np.full((20, 1), 3.0), np.arange(20)[:, None]],
                       axis=1)
    for strategy in ("uniform", "quantile", "kmeans"):
        model = (KBinsDiscretizer().set_num_bins(4).set_strategy(strategy)
                 .fit(_t(X)))
        out = np.asarray(model.transform(_t(X))[0]["output"])
        assert np.all(out[:, 0] == 0), strategy
        assert out[:, 1].max() > 0, strategy  # varying column still bins


def test_kbins_seed_param_controls_subsample():
    rng = np.random.default_rng(5)
    X = rng.normal(size=(5000, 1))
    fits = [(KBinsDiscretizer().set_num_bins(4).set_sub_samples(100)
             .set_seed(s).fit(_t(X)))._edges for s in (1, 1, 2)]
    np.testing.assert_array_equal(fits[0], fits[1])   # reproducible
    assert not np.array_equal(fits[0], fits[2])       # seed-sensitive


def test_kbins_kmeans_separated_clusters():
    X = np.concatenate([np.full(10, 0.0), np.full(10, 5.0),
                        np.full(10, 10.0)])[:, None]
    model = (KBinsDiscretizer().set_num_bins(3).set_strategy("kmeans")
             .fit(_t(X)))
    out = np.asarray(model.transform(_t(X))[0]["output"]).ravel()
    np.testing.assert_array_equal(out, [0] * 10 + [1] * 10 + [2] * 10)


def test_kbins_clamps_out_of_range_and_roundtrips(tmp_path):
    X = np.linspace(0, 10, 50)[:, None]
    model = (KBinsDiscretizer().set_num_bins(5).set_strategy("uniform")
             .fit(_t(X)))
    out = np.asarray(
        model.transform(_t([[-100.0], [100.0]]))[0]["output"]).ravel()
    np.testing.assert_array_equal(out, [0, 4])

    path = str(tmp_path / "kbins")
    model.save(path)
    loaded = KBinsDiscretizerModel.load(path)
    out2 = np.asarray(
        loaded.transform(_t([[-100.0], [100.0]]))[0]["output"]).ravel()
    np.testing.assert_array_equal(out2, [0, 4])


def test_vector_indexer_maps_ascending_and_passes_continuous():
    X = np.array([[1.0, 0.1], [5.0, 0.2], [1.0, 0.3], [9.0, 0.4],
                  [5.0, 0.5], [9.0, 0.6], [1.0, 0.7], [5.0, 0.8],
                  [9.0, 0.9], [1.0, 1.0], [5.0, 1.1], [9.0, 1.2],
                  [1.0, 1.3], [5.0, 1.4], [9.0, 1.5], [1.0, 1.6],
                  [5.0, 1.7], [9.0, 1.8], [1.0, 1.9], [5.0, 2.0],
                  [9.0, 2.1]])
    model = VectorIndexer().set_max_categories(5).fit(_t(X))
    out = np.asarray(model.transform(_t(X))[0]["output"])
    # col 0: {1,5,9} -> {0,1,2}; col 1: 21 distinct > 5 -> continuous
    np.testing.assert_array_equal(out[:3, 0], [0, 1, 0])
    np.testing.assert_array_equal(out[:, 1], X[:, 1])


def test_vector_indexer_handle_invalid():
    X = np.array([[1.0], [2.0], [3.0]])
    model = VectorIndexer().set_max_categories(5).fit(_t(X))
    with pytest.raises(ValueError, match="unseen"):
        model.transform(_t([[7.0]]))

    keep = model.set_handle_invalid("keep").transform(_t([[7.0], [2.0]]))[0]
    np.testing.assert_array_equal(np.asarray(keep["output"]).ravel(), [3, 1])

    skip = model.set_handle_invalid("skip").transform(_t([[7.0], [2.0]]))[0]
    np.testing.assert_array_equal(np.asarray(skip["output"]).ravel(), [1])


def test_vector_indexer_save_load(tmp_path):
    X = np.array([[1.0], [2.0], [3.0]])
    model = VectorIndexer().set_max_categories(5).fit(_t(X))
    path = str(tmp_path / "vidx")
    model.save(path)
    loaded = VectorIndexerModel.load(path)
    out = np.asarray(loaded.transform(_t([[3.0], [1.0]]))[0]["output"])
    np.testing.assert_array_equal(out.ravel(), [2, 0])
