"""Linear family tests: LogisticRegression / LinearRegression / LinearSVC.

Coverage shape follows KMeansTest: defaults, fit+predict accuracy on a
separable fixture, save/load, weight column, regularization behavior."""

import numpy as np
import pytest

from flink_ml_tpu import Table
from flink_ml_tpu.models.classification import (
    LinearSVC,
    LinearSVCModel,
    LogisticRegression,
    LogisticRegressionModel,
)
from flink_ml_tpu.models.regression import (
    LinearRegression,
    LinearRegressionModel,
)


def _binary_table(n=256, d=4, seed=0, margin=2.0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d))
    w_true = rng.normal(size=(d,))
    y = (X @ w_true + 0.1 * rng.normal(size=n) > 0).astype(np.int64)
    X = X + margin * 0.1 * (2 * y[:, None] - 1) * np.sign(w_true)[None, :]
    return Table({"features": X, "label": y}), w_true


def _regression_table(n=256, d=3, seed=1):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d))
    w_true = np.array([1.5, -2.0, 0.5])
    y = X @ w_true + 3.0
    return Table({"features": X, "label": y}), w_true


def test_logreg_defaults():
    lr = LogisticRegression()
    assert lr.get_max_iter() == 20
    assert lr.get_learning_rate() == 0.1
    assert lr.get_reg() == 0.0
    # None = auto batch sizing (layout-aware for hashed fits, r4)
    assert lr.get_global_batch_size() is None
    assert lr.get_label_col() == "label"
    assert lr.get_raw_prediction_col() == "rawPrediction"


def test_logreg_fit_predict():
    table, _ = _binary_table()
    model = (LogisticRegression().set_max_iter(30).set_learning_rate(0.5)
             .fit(table))
    out = model.transform(table)[0]
    acc = np.mean(out["prediction"] == table["label"])
    assert acc > 0.95
    probs = out["rawPrediction"]
    assert np.all((probs >= 0) & (probs <= 1))
    # prediction is prob > 0.5
    np.testing.assert_array_equal(out["prediction"], (probs > 0.5))


def test_logreg_save_load(tmp_path):
    table, _ = _binary_table()
    model = LogisticRegression().set_max_iter(10).fit(table)
    path = str(tmp_path / "lr")
    model.save(path)
    loaded = LogisticRegressionModel.load(path)
    np.testing.assert_array_equal(
        loaded.transform(table)[0]["prediction"],
        model.transform(table)[0]["prediction"])


def test_logreg_model_data_round_trip():
    table, _ = _binary_table()
    model = LogisticRegression().set_max_iter(10).fit(table)
    (data,) = model.get_model_data()
    fresh = LogisticRegressionModel().set_model_data(data)
    fresh.copy_params_from(model)
    np.testing.assert_array_equal(
        fresh.transform(table)[0]["prediction"],
        model.transform(table)[0]["prediction"])


def test_linear_regression_recovers_coefficients():
    table, w_true = _regression_table()
    model = (LinearRegression().set_max_iter(200).set_learning_rate(0.1)
             .set_global_batch_size(64).set_tol(0.0).fit(table))
    out = model.transform(table)[0]
    resid = np.abs(out["prediction"] - table["label"])
    assert resid.mean() < 0.05
    np.testing.assert_allclose(model._state.coefficients, w_true, atol=0.05)
    assert abs(model._state.intercept - 3.0) < 0.05


def test_linearsvc_fit_predict():
    table, _ = _binary_table(margin=4.0)
    model = LinearSVC().set_max_iter(50).set_learning_rate(0.2).fit(table)
    out = model.transform(table)[0]
    acc = np.mean(out["prediction"] == table["label"])
    assert acc > 0.95


def test_linearsvc_threshold():
    table, _ = _binary_table()
    model = LinearSVC().set_max_iter(20).fit(table)
    high = model.set_threshold(1e9).transform(table)[0]
    assert np.all(high["prediction"] == 0)
    low = model.set_threshold(-1e9).transform(table)[0]
    assert np.all(low["prediction"] == 1)


def test_weight_column_influences_fit():
    # All weight on class-1 rows pushes predictions toward 1
    rng = np.random.default_rng(3)
    X = rng.normal(size=(128, 3))
    y = (rng.uniform(size=128) > 0.5).astype(np.int64)
    w = np.where(y == 1, 1000.0, 0.001)
    t = Table({"features": X, "label": y, "w": w})
    model = (LogisticRegression().set_weight_col("w").set_max_iter(30)
             .set_learning_rate(0.5).fit(t))
    preds = model.transform(t)[0]["prediction"]
    assert preds.mean() > 0.9


def test_l2_regularization_shrinks_weights():
    table, _ = _binary_table()
    free = LogisticRegression().set_max_iter(30).fit(table)
    ridge = LogisticRegression().set_max_iter(30).set_reg(1.0).fit(table)
    assert (np.linalg.norm(ridge._state.coefficients)
            < np.linalg.norm(free._state.coefficients))


def test_l1_regularization_sparsifies():
    rng = np.random.default_rng(5)
    X = rng.normal(size=(256, 10))
    y = (X[:, 0] > 0).astype(np.int64)  # only feature 0 matters
    t = Table({"features": X, "label": y})
    lasso = (LogisticRegression().set_reg(0.2).set_elastic_net(1.0)
             .set_max_iter(50).set_learning_rate(0.5).fit(t))
    coef = lasso._state.coefficients
    assert np.sum(np.abs(coef[1:]) < 1e-3) >= 7  # most noise features zeroed
    assert abs(coef[0]) > 0.01


def test_loss_log_decreases():
    table, _ = _binary_table()
    model = (LogisticRegression().set_max_iter(20).set_tol(0.0)
             .set_learning_rate(0.3).fit(table))
    log = model._loss_log
    assert len(log) == 20
    assert log[-1] < log[0]


def test_untrained_model_errors():
    with pytest.raises(RuntimeError):
        LogisticRegressionModel().transform(Table({"features": np.ones((2, 2))}))
