"""Criteo TSV ingest: native parser vs Python twin, reader batching, and
the end-to-end out-of-core mixed LR fit from a raw TSV file."""

import numpy as np
import pytest

from flink_ml_tpu.data import criteo
from flink_ml_tpu.data.criteo import CriteoTSVReader, parse_chunk
from flink_ml_tpu.models.feature.text import _fnv1a


def _line(label, ints, cats):
    return "\t".join([str(label)]
                     + [("" if v is None else str(v)) for v in ints]
                     + list(cats)).encode() + b"\n"


def _make_tsv(path, rows, rng, hash_tokens=("aa11bb22", "cc33dd44")):
    # dense ints stay small: raw Criteo counts get log-transformed before
    # training; here the signal lives in C1 and the dense slots are noise
    lines = []
    labels = []
    for _ in range(rows):
        y = int(rng.random() < 0.5)
        ints = [int(v) for v in rng.integers(-2, 4, size=13)]
        cats = [hash_tokens[y]] + [f"{rng.integers(0, 1 << 32):08x}"
                                   for _ in range(25)]
        lines.append(_line(y, ints, cats))
        labels.append(y)
    path.write_bytes(b"".join(lines))
    return labels


def test_parse_basic_line_semantics():
    data = _line(1, [5, None, -3] + [0] * 10, ["deadbeef"] * 26)
    dense, cat, label, consumed = parse_chunk(data, 10, hash_space=1000)
    assert consumed == len(data)
    assert label.tolist() == [1.0]
    assert dense[0, 0] == 5.0 and dense[0, 1] == 0.0 and dense[0, 2] == -3.0
    # hash convention: FNV-1a("C{field}={token}") % space + n_reserved
    for f in range(26):
        expected = 13 + _fnv1a(f"C{f + 1}=deadbeef") % 1000
        assert cat[0, f] == expected
    # distinct fields get distinct salts -> (almost surely) distinct slots
    assert len(set(cat[0].tolist())) > 20


def test_parse_empty_categorical_hashes_missing_slot():
    data = _line(0, list(range(13)), [""] * 26)
    dense, cat, label, _ = parse_chunk(data, 10, hash_space=997)
    assert label.tolist() == [0.0]
    assert cat[0, 3] == 13 + _fnv1a("C4=") % 997


def test_parse_skips_malformed_and_partial_lines():
    good = _line(1, [1] * 13, ["ab"] * 26)
    bad = b"not\ta\tvalid\tline\n"
    partial = b"0\t1\t2"      # no newline: must stay unconsumed
    data = good + bad + good + partial
    dense, cat, label, consumed = parse_chunk(data, 10, hash_space=100)
    assert len(label) == 2
    assert consumed == len(good) * 2 + len(bad)


def test_native_matches_python_twin():
    if criteo._native_lib() is None:
        pytest.skip("native toolchain unavailable")
    rng = np.random.default_rng(0)
    lines = []
    for i in range(50):
        ints = [None if i % 7 == 0 else int(v)
                for v in rng.integers(-5, 50, size=13)]
        cats = ["" if (i + f) % 11 == 0 else f"{rng.integers(0, 1 << 32):08x}"
                for f in range(26)]
        lines.append(_line(i % 2, ints, cats))
    data = b"".join(lines)
    native = parse_chunk(data, 100, hash_space=12345)
    python = criteo._py_parse_chunk(data, 100, hash_space=12345,
                                    n_reserved=13)
    np.testing.assert_array_equal(native[0], python[0])
    np.testing.assert_array_equal(native[1], python[1])
    np.testing.assert_array_equal(native[2], python[2])
    assert native[3] == python[3] == len(data)


def test_reader_batches_across_chunk_boundaries(tmp_path):
    rng = np.random.default_rng(1)
    path = tmp_path / "day0.tsv"
    _make_tsv(path, 103, rng)
    # tiny chunk size forces many partial-line carries
    reader = CriteoTSVReader(str(path), batch_rows=16, hash_space=1 << 10,
                             chunk_bytes=1 << 12)
    batches = list(reader)
    rows = sum(len(b["label"]) for b in batches)
    assert rows == 103
    assert all(len(b["label"]) == 16 for b in batches[:-1])
    assert batches[0]["features_dense"].shape == (16, 13)
    assert batches[0]["features_indices"].shape == (16, 26)
    # two passes are identical (fresh-iterator protocol)
    again = list(CriteoTSVReader(str(path), batch_rows=16,
                                 hash_space=1 << 10, chunk_bytes=1 << 12))
    np.testing.assert_array_equal(batches[3]["features_indices"],
                                  again[3]["features_indices"])


def test_reader_handles_missing_trailing_newline(tmp_path):
    path = tmp_path / "notrail.tsv"
    content = _line(1, [1] * 13, ["ab"] * 26) + \
        _line(0, [2] * 13, ["cd"] * 26)
    path.write_bytes(content[:-1])    # strip final newline
    rows = sum(len(b["label"]) for b in
               CriteoTSVReader(str(path), batch_rows=8, hash_space=64))
    assert rows == 2


def test_outofcore_mixed_lr_from_tsv(tmp_path):
    """The north-star pipeline end-to-end: raw TSV -> CriteoTSVReader ->
    fit_outofcore(mixed=True); the C1 token encodes the label, so the fit
    must learn it."""
    from flink_ml_tpu.models.classification import LogisticRegression

    rng = np.random.default_rng(2)
    path = tmp_path / "train.tsv"
    labels = _make_tsv(path, 512, rng)
    hash_space = 1 << 14

    lr = (LogisticRegression().set_max_iter(6).set_learning_rate(0.5)
          .set_tol(0))
    model = lr.fit_outofcore(
        lambda: CriteoTSVReader(str(path), batch_rows=64,
                                hash_space=hash_space),
        num_features=13 + hash_space, mixed=True)
    log = model.loss_log
    assert log[-1] < log[0] * 0.6, log

    # score the same file through one reader pass
    batch = next(iter(CriteoTSVReader(str(path), batch_rows=512,
                                      hash_space=hash_space)))
    from flink_ml_tpu import Table

    out = model.transform(Table(batch))[0]
    acc = np.mean(np.asarray(out["prediction"]) == np.asarray(labels))
    assert acc > 0.95, acc


def test_parse_strict_int_rules_and_field_count():
    """'+5', ' 5', 19+ digits, and 41-field lines behave identically on
    the native and Python paths (the divergence classes a permissive
    int() would hide)."""
    tricky = [
        _line(1, ["+5", " 7", "9" * 19, "12", "-0"] + [3] * 8,
              ["ab"] * 26),
        # 41 fields: must be SKIPPED by both parsers
        _line(0, [1] * 13, ["cd"] * 26)[:-1] + b"\textra\n",
        _line(0, [2] * 13, ["ef"] * 26),
    ]
    data = b"".join(tricky)
    results = [criteo._py_parse_chunk(data, 10, 500, 13)]
    if criteo._native_lib() is not None:
        results.append(parse_chunk(data, 10, hash_space=500))
    for dense, cat, label, consumed in results:
        assert len(label) == 2            # 41-field line skipped
        assert consumed == len(data)
        # +5 / ' 7' / 19-digit all parse as 0; '12' stays; '-0' is +0.0
        np.testing.assert_array_equal(dense[0, :5], [0, 0, 0, 12, 0])
        np.testing.assert_array_equal(dense[1], [2.0] * 13)
    if len(results) == 2:
        for a, b in zip(results[0][:3], results[1][:3]):
            np.testing.assert_array_equal(a, b)


def test_parse_non_utf8_token_hashes_raw_bytes():
    raw = b"1\t" + b"\t".join(b"1" for _ in range(13)) + b"\t" + \
        b"\t".join(b"\x80\xffab" for _ in range(26)) + b"\n"
    dense, cat, label, consumed = criteo._py_parse_chunk(raw, 5, 997, 13)
    assert len(label) == 1 and consumed == len(raw)
    expected = 13 + criteo._fnv1a_bytes(b"C1=\x80\xffab") % 997
    assert cat[0, 0] == expected
    if criteo._native_lib() is not None:
        n_dense, n_cat_arr, n_label, _ = parse_chunk(raw, 5, hash_space=997)
        np.testing.assert_array_equal(n_cat_arr, cat)


def test_parse_rejects_oversized_hash_space():
    with pytest.raises(ValueError, match="int32"):
        parse_chunk(b"", 1, hash_space=1 << 31)


def test_tsv_to_datacache_to_outofcore_replay(tmp_path):
    """The full documented ingest pipeline: TSV -> CriteoTSVReader ->
    DataCacheWriter (persisted once) -> DataCacheReader replay per epoch
    -> fit_outofcore(mixed=True).  Caching must not change the fit: the
    coefficients match streaming the TSV directly with the same batch
    order."""
    from flink_ml_tpu.data.datacache import DataCacheReader, DataCacheWriter
    from flink_ml_tpu.models.classification import LogisticRegression

    rng = np.random.default_rng(5)
    path = tmp_path / "day.tsv"
    _make_tsv(path, 256, rng)
    hash_space = 1 << 12
    batch = 64

    cache = str(tmp_path / "cache")
    writer = DataCacheWriter(cache, segment_rows=128)
    for b in CriteoTSVReader(str(path), batch_rows=batch,
                             hash_space=hash_space):
        writer.append(b)
    writer.finish()

    def fit(make_reader):
        lr = (LogisticRegression().set_max_iter(3).set_learning_rate(0.5)
              .set_tol(0))
        return lr.fit_outofcore(make_reader,
                                num_features=13 + hash_space, mixed=True)

    cached = fit(lambda: DataCacheReader(cache, batch_rows=batch))
    direct = fit(lambda: CriteoTSVReader(str(path), batch_rows=batch,
                                         hash_space=hash_space))
    np.testing.assert_allclose(cached._state.coefficients,
                               direct._state.coefficients, atol=1e-6)
    assert cached.loss_log[-1] < cached.loss_log[0]


def test_reader_streams_multiple_files(tmp_path):
    """The Criteo-1TB layout is day_0..day_N files; a path list streams
    them back-to-back with batches crossing file boundaries."""
    rng = np.random.default_rng(7)
    p1, p2 = tmp_path / "day_0.tsv", tmp_path / "day_1.tsv"
    _make_tsv(p1, 20, rng)
    _make_tsv(p2, 13, rng)

    multi = list(CriteoTSVReader([str(p1), str(p2)], batch_rows=8,
                                 hash_space=64))
    assert sum(len(b["label"]) for b in multi) == 33
    # batch 2 straddles the file boundary (rows 16..23 span 20-row file 1)
    straddle = multi[2]
    assert len(straddle["label"]) == 8

    # concatenating per-file reads gives the identical stream
    single = list(CriteoTSVReader(str(p1), batch_rows=8, hash_space=64)) + \
        list(CriteoTSVReader(str(p2), batch_rows=8, hash_space=64))
    cat_multi = np.concatenate([b["features_indices"] for b in multi])
    cat_single = np.concatenate([b["features_indices"] for b in single])
    np.testing.assert_array_equal(cat_multi, cat_single)

    with pytest.raises(ValueError, match="at least one"):
        CriteoTSVReader([], batch_rows=8, hash_space=64)


def test_parallel_reader_matches_serial_exactly(tmp_path, monkeypatch):
    """workers>1 range-shards the files; output must be byte-identical to
    the serial reader in ORDER too (deterministic resume depends on it).
    Tiny ranges force every boundary case: range starting mid-line, range
    ending exactly on a line boundary, range inside one line, multi-file
    crossing, trailing line without newline."""
    rng = np.random.default_rng(7)
    p1, p2 = tmp_path / "day0.tsv", tmp_path / "day1.tsv"
    _make_tsv(p1, 57, rng)
    _make_tsv(p2, 41, rng)
    # strip p2's final newline to exercise the EOF tail
    p2.write_bytes(p2.read_bytes()[:-1])

    def collect(reader):
        d, c, y = [], [], []
        for b in reader:
            d.append(b["features_dense"])
            c.append(b["features_indices"])
            y.append(b["label"])
        return (np.concatenate(d), np.concatenate(c), np.concatenate(y))

    serial = collect(CriteoTSVReader([str(p1), str(p2)], batch_rows=16,
                                     hash_space=1 << 10, workers=1))
    for range_bytes in (64, 200, 1 << 20):
        par = CriteoTSVReader([str(p1), str(p2)], batch_rows=16,
                              hash_space=1 << 10, workers=3)
        monkeypatch.setattr(
            par, "_range_tasks",
            lambda rb=range_bytes, r=par:
            CriteoTSVReader._range_tasks(r, range_bytes=rb))
        got = collect(par)
        for a, b in zip(serial, got):
            np.testing.assert_array_equal(a, b)


def test_parallel_reader_auto_workers_single_core():
    r = CriteoTSVReader("x.tsv", batch_rows=4, hash_space=8, workers=0)
    assert r.workers >= 1


def test_parallel_reader_with_malformed_short_lines(tmp_path):
    """Malformed (<40-field) lines near range boundaries must not drop or
    duplicate neighboring valid rows (code-review r3 finding)."""
    rng = np.random.default_rng(9)
    path = tmp_path / "dirty.tsv"
    _make_tsv(path, 30, rng)
    content = path.read_bytes().split(b"\n")
    # splice short garbage lines between every few valid lines
    dirty = []
    for i, line in enumerate(content):
        dirty.append(line)
        if i % 3 == 1:
            dirty.append(b"x")
            dirty.append(b"bad\tline")
    path.write_bytes(b"\n".join(dirty))

    def labels(reader):
        return np.concatenate([b["label"] for b in reader])

    serial = labels(CriteoTSVReader(str(path), batch_rows=8,
                                    hash_space=256, workers=1))
    assert len(serial) == 30
    for rb in (48, 100, 256):
        par = CriteoTSVReader(str(path), batch_rows=8, hash_space=256,
                              workers=3)
        par._range_tasks = (
            lambda rb=rb, r=par:
            CriteoTSVReader._range_tasks(r, range_bytes=rb))
        np.testing.assert_array_equal(serial, labels(par))
