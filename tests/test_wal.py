"""Write-ahead window log (`data/wal.py`) — exactly-once ingest for live
feeds (the Checkpoints.java analog at window granularity, VERDICT r2
missing #1).

The decisive test is the last one: an online-model fit killed mid-stream
on a NON-replayable iterator must converge identically to the
uninterrupted run — the crashed run's unacknowledged windows come back
from the log, not from the source."""

import os

import numpy as np
import pytest

import jax.numpy as jnp

from flink_ml_tpu.data.table import Table
from flink_ml_tpu.data.wal import WindowLog
from flink_ml_tpu.iteration import (CheckpointConfig, IterationBodyResult,
                                    IterationConfig, iterate)


def _windows(lo, hi, rows=4):
    """Deterministic windows lo..hi-1; window i carries value i rows."""
    for i in range(lo, hi):
        yield Table({"x": np.full((rows,), float(i), np.float32),
                     "i": np.full((rows,), i, np.int64)})


class OneShotFeed:
    """A genuinely non-replayable source: iterating consumes it forever,
    and a second iteration continues where the first stopped (a socket)."""

    def __init__(self, lo, hi):
        self._it = _windows(lo, hi)

    def __iter__(self):
        return self._it


class TestWindowLog:
    def test_tee_then_replay_after_crash(self, tmp_path):
        d = str(tmp_path / "wal")
        feed = OneShotFeed(0, 10)
        log = WindowLog(feed, d)
        it = iter(log)
        seen = [int(next(it)["i"][0]) for _ in range(6)]
        assert seen == list(range(6))
        snap = log.snapshot()          # checkpoint cut at 6
        assert snap == {"consumed": 6}
        # two more windows consumed after the cut, then "crash"
        assert int(next(it)["i"][0]) == 6
        assert int(next(it)["i"][0]) == 7

        # restart: the source lost windows 0..7 forever (socket moved on)
        resumed = WindowLog(OneShotFeed(8, 10), d)
        resumed.restore(snap)
        replayed = [int(t["i"][0]) for t in resumed]
        # 6,7 come from the LOG; 8,9 from the live source
        assert replayed == [6, 7, 8, 9]

    def test_crash_before_any_checkpoint_replays_everything(self, tmp_path):
        d = str(tmp_path / "wal")
        it = iter(WindowLog(OneShotFeed(0, 5), d))
        for _ in range(3):
            next(it)
        # crash with no snapshot: a fresh log replays all logged windows
        resumed = WindowLog(OneShotFeed(3, 5), d)
        assert [int(t["i"][0]) for t in resumed] == [0, 1, 2, 3, 4]

    def test_truncation_keeps_recent_snapshots(self, tmp_path):
        d = str(tmp_path / "wal")
        log = WindowLog(OneShotFeed(0, 8), d, keep_snapshots=2)
        it = iter(log)
        for k in (2, 4, 6):
            while log._consumed < k:
                next(it)
            log.snapshot()
        files = sorted(os.listdir(d))
        # horizon = second-most-recent snapshot (4): win-0..3 truncated
        assert files == [f"win-{i:08d}.npz" for i in (4, 5)]
        # restoring to the oldest RETAINED cut works...
        ok = WindowLog(OneShotFeed(6, 8), d)
        ok.restore({"consumed": 4})
        assert [int(t["i"][0]) for t in ok] == [4, 5, 6, 7]
        # ...restoring past the horizon errors loudly
        bad = WindowLog(OneShotFeed(6, 8), d)
        bad.restore({"consumed": 2})
        with pytest.raises(ValueError, match="truncation horizon"):
            next(iter(bad))

    def test_kill_and_resume_fit_matches_uninterrupted(self, tmp_path):
        """The r2 'done' criterion: online fit + WindowLog + checkpoint,
        killed mid-stream on a non-replayable feed, resumes to EXACTLY the
        uninterrupted run's state."""

        def body(state, epoch, window):
            x = jnp.asarray(np.asarray(window["x"], np.float32))
            # order-sensitive update: any lost/duplicated/reordered window
            # changes the result
            return IterationBodyResult(state * 0.9 + jnp.sum(x) * (epoch + 1))

        # uninterrupted oracle (same windows, no crash)
        oracle = iterate(
            body, jnp.asarray(0.0),
            WindowLog(OneShotFeed(0, 12), str(tmp_path / "wal-oracle")),
            config=IterationConfig(mode="hosted", jit=False))
        assert oracle.num_epochs == 12

        class Killed(RuntimeError):
            pass

        class KillingFeed:
            """Non-replayable feed that dies after handing out 7 windows."""

            def __init__(self, lo, hi, die_after):
                self._it = _windows(lo, hi)
                self._left = die_after

            def __iter__(self):
                return self

            def __next__(self):
                if self._left == 0:
                    raise Killed()
                self._left -= 1
                return next(self._it)

        wal_dir = str(tmp_path / "wal-crash")
        ckpt = str(tmp_path / "ckpt")
        with pytest.raises(Killed):
            iterate(body, jnp.asarray(0.0),
                    WindowLog(KillingFeed(0, 12, die_after=7), wal_dir),
                    config=IterationConfig(mode="hosted", jit=False),
                    checkpoint=CheckpointConfig(ckpt, interval=4))

        # the feed itself lost windows 0..6 (already consumed); only 7..11
        # remain live.  The WAL brings back 4..6 (consumed after the cut).
        resumed = iterate(
            body, jnp.asarray(0.0),
            WindowLog(OneShotFeed(7, 12), wal_dir),
            config=IterationConfig(mode="hosted", jit=False),
            checkpoint=CheckpointConfig(ckpt, interval=4), resume=True)
        assert float(resumed.state) == pytest.approx(float(oracle.state),
                                                     rel=1e-6)
        assert resumed.num_epochs == 12