"""Tier-1 wiring for the unified observability layer (ISSUE 13).

Four blocks:

1. **Tracer mechanics** — ring wraparound, disabled-path no-op,
   retroactive spans, Chrome-trace/JSONL export round trips.
2. **One metrics tree** — every surface merges into one snapshot, the
   Prometheus exposition parses line by line, the never-published
   staleness gauge exports ABSENT (the ``-1`` sentinel regression),
   the background sampler's JSONL survives a torn tail.
3. **StepProbe** — device-side recording under jit/scan, one-transfer
   fetch, masked-freeze parity (probe on/off bit-exact through the real
   chunked fit), ServingMetrics edge cases.
4. **THE acceptance** — one enabled tracer follows a correlation chain
   from WAL ingest through checkpoint cut and delta publish to a served
   request, in the exported trace; serving with tracing on adds ZERO
   new XLA lowerings after warm-up.
"""

import json
import math
import os
import re

import numpy as np
import pytest

from flink_ml_tpu import Table
from flink_ml_tpu.obs import (
    MetricsTree,
    ObsSampler,
    SpanTracer,
    StepProbe,
    default_tree,
    prometheus_text,
    read_samples,
)
from flink_ml_tpu.obs import trace as trace_mod
from flink_ml_tpu.serving.metrics import ServingMetrics


@pytest.fixture(autouse=True)
def _quiet_global_tracer():
    """Every test leaves the process-wide tracer disabled and empty."""
    yield
    trace_mod.tracer.disable()
    trace_mod.tracer.clear()


# ---------------------------------------------------------------------------
# 1. tracer mechanics
# ---------------------------------------------------------------------------

def test_tracer_disabled_records_nothing():
    t = SpanTracer(capacity=8)
    assert t.span("a") is t.span("b")          # one shared no-op object
    with t.span("a", op="x"):
        pass
    t.instant("b")
    t.add("c", 0.0, 1.0)
    assert t.spans() == [] and t.count == 0


def test_tracer_ring_wraparound_keeps_newest():
    t = SpanTracer(capacity=4).enable()
    for i in range(6):
        t.add(f"s{i}", 0.0, 0.1, step=i)
    assert t.count == 6 and t.dropped == 2
    assert [s.name for s in t.spans()] == ["s2", "s3", "s4", "s5"]


def test_tracer_span_note_and_find():
    t = SpanTracer(capacity=8).enable()
    with t.span("serve", generation=1) as span:
        span.note(request_id=7)
    found = list(t.find("serve", request_id=7))
    assert len(found) == 1 and found[0].ids["generation"] == 1
    assert list(t.find("serve", request_id=99)) == []


def test_chrome_export_round_trips(tmp_path):
    t = SpanTracer(capacity=16).enable()
    with t.span("outer", cat="serving", request_id=3):
        t.instant("mark", window=5)
    path = str(tmp_path / "trace.json")
    n = t.export_chrome(path)
    assert n == 2
    loaded = json.load(open(path))
    events = loaded["traceEvents"]
    by_name = {e["name"]: e for e in events}
    outer, mark = by_name["outer"], by_name["mark"]
    # the Chrome-trace contract Perfetto loads: X events carry ts+dur,
    # instants carry a scope, args hold the correlation ids
    assert outer["ph"] == "X" and outer["dur"] >= 0
    assert mark["ph"] == "i" and mark["s"] == "t"
    assert outer["args"]["request_id"] == 3
    assert mark["args"]["window"] == 5
    assert all(e["ts"] >= 0 and e["pid"] == os.getpid() for e in events)
    # the instant falls INSIDE the enclosing span's interval
    assert outer["ts"] <= mark["ts"] <= outer["ts"] + outer["dur"]


def test_jsonl_export_round_trips(tmp_path):
    t = SpanTracer(capacity=8).enable()
    t.add("a", 1.0, 2.0, step=4)
    path = str(tmp_path / "trace.jsonl")
    assert t.export_jsonl(path) == 1
    lines = [json.loads(line) for line in open(path)]
    assert lines[0]["name"] == "a" and lines[0]["step"] == 4
    assert lines[0]["dur_s"] == pytest.approx(1.0)


# ---------------------------------------------------------------------------
# 2. the metrics tree
# ---------------------------------------------------------------------------

def _publish_once(m: ServingMetrics, generation=1, t0=1000.0):
    m.on_publish(generation, mode="delta", payload_bytes=64, now=t0)


def test_metrics_tree_merges_every_surface():
    from flink_ml_tpu.kernels.registry import kernel_stats
    from flink_ml_tpu.robustness.supervisor import RecoveryReport

    m = ServingMetrics()
    m.on_batch(n_requests=2, rows=3, bucket=8, latencies_s=[0.01, 0.02],
               queue_depth=0, generation=1)
    report = RecoveryReport(restarts=1, recovered=True)
    stream_info = {"impl": "dense-stream",
                   "step_trace": {"loss": np.asarray([1.0, 0.5])}}
    tree = default_tree(serving=m, recovery=report,
                        stream_info=stream_info, tracer=trace_mod.tracer)
    snap = tree.snapshot()
    assert snap["serving"]["requests"] == 2
    assert snap["recovery"]["restarts"] == 1
    assert snap["training"]["step_trace"]["loss"] == [1.0, 0.5]
    assert snap["trace"]["enabled"] is False
    assert snap["kernels"]["dispatches"] == kernel_stats.dispatches
    assert "aot" in snap["kernels"] and "tuned_ops" in snap["kernels"]
    json.dumps(snap)        # JSON-clean end to end (numpy normalized)


def test_default_tree_registers_autoscale_provider():
    """ISSUE 17: the autoscale controller's self-view hangs off the same
    tree it reads — counters, the live placement, and the decision
    latency (NaN before the first tick: absent in prometheus, the
    never-faked stance) round-trip snapshot -> exposition."""
    from flink_ml_tpu.autoscale import (AutoscaleController,
                                        AutoscalePolicy, PlacementStore,
                                        PolicyConfig, SignalSource)

    store = PlacementStore(4)
    store.publish({"svc": [0, 1]}, 2)
    inner = MetricsTree()
    controller = AutoscaleController(
        store=store,
        policy=AutoscalePolicy(PolicyConfig(p99_target_ms=50.0,
                                            total_chips=4)),
        signals=SignalSource(inner))
    tree = default_tree(autoscale=controller)
    snap = tree.snapshot()
    assert snap["autoscale"]["ticks"] == 0
    assert snap["autoscale"]["placement_generation"] == 1
    assert snap["autoscale"]["placement_learner_workers"] == 2
    assert math.isnan(snap["autoscale"]["decision_latency_s"])
    text = prometheus_text(snap)
    assert "flink_ml_tpu_autoscale_placement_generation 1" in text
    assert "decision_latency_s" not in text      # NaN = absent
    json.dumps(snap)
    controller.tick()
    snap = tree.snapshot()
    assert snap["autoscale"]["ticks"] == 1
    assert snap["autoscale"]["decision_latency_s"] >= 0.0
    assert "decision_latency_s" in prometheus_text(snap)


def test_metrics_tree_provider_kinds_and_none():
    tree = MetricsTree()
    tree.register("fn", lambda: {"a": 1})
    tree.register("ref", {"b": np.int64(2)})
    tree.register("absent", lambda: None)
    snap = tree.snapshot()
    assert snap == {"fn": {"a": 1}, "ref": {"b": 2}}
    with pytest.raises(TypeError, match="unsnapshotable"):
        tree.register("bad", 42)


_PROM_LINE = re.compile(
    r"^(?:# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* gauge"
    r"|[a-zA-Z_:][a-zA-Z0-9_:]* -?[0-9.eE+-]+(?:\.[0-9]+)?)$")


def test_prometheus_exposition_parses():
    """Every emitted line is either a TYPE comment or `name value` with
    a legal metric name — the strict-parse half of the acceptance."""
    m = ServingMetrics()
    m.on_batch(n_requests=1, rows=1, bucket=8, latencies_s=[0.005],
               queue_depth=0, generation=2)
    text = prometheus_text(default_tree(serving=m).snapshot())
    lines = text.strip().split("\n")
    assert len(lines) >= 10
    for line in lines:
        assert _PROM_LINE.match(line), f"unparseable line: {line!r}"
    # dotted MetricGroup keys flatten into legal names with values
    assert re.search(
        r"^flink_ml_tpu_serving_requests 1(\.0)?$", text, re.M)
    # strings (health) are skipped, not mangled into bad samples
    assert "SERVING" not in text


def test_staleness_sentinel_never_exports_negative():
    """ISSUE 13 satellite regression: never-published staleness is NaN
    on the gauge and ABSENT from the exposition — not a fake ``-1``
    age.  After a publish it exports as a real non-negative number."""
    m = ServingMetrics()
    m.touch_staleness()
    assert math.isnan(m.staleness_seconds)
    snap = m.snapshot()
    assert math.isnan(snap["model_staleness_seconds"])
    text = prometheus_text({"serving": m.group.snapshot()})
    assert "model_staleness_seconds" not in text
    assert "-1" not in text.split()
    _publish_once(m, t0=1000.0)
    m.touch_staleness(now=1002.5)
    assert m.staleness_seconds == pytest.approx(2.5)
    text = prometheus_text({"serving": m.group.snapshot()})
    assert re.search(
        r"^flink_ml_tpu_serving_model_staleness_seconds 2\.5$", text, re.M)


def test_sampler_appends_and_survives_torn_tail(tmp_path):
    path = str(tmp_path / "series.jsonl")
    tree = MetricsTree().register("x", lambda: {"v": 1})
    clock = iter([10.0, 11.0]).__next__
    sampler = ObsSampler(tree, path, interval_s=60.0, clock=clock)
    sampler.sample()
    sampler.sample()
    # crash mid-append: a torn final line is dropped by the reader
    with open(path, "a") as f:
        f.write('{"t": 12.0, "x": {"v"')
    samples = read_samples(path)
    assert [s["t"] for s in samples] == [10.0, 11.0]
    assert samples[0]["x"] == {"v": 1}
    assert sampler.samples_written == 2


def test_sampler_mid_series_corruption_raises(tmp_path):
    path = str(tmp_path / "series.jsonl")
    with open(path, "w") as f:
        f.write('{"t": 1}\nGARBAGE\n{"t": 2}\n')
    with pytest.raises(ValueError, match="not the tail"):
        read_samples(path)


def test_sampler_background_thread_ticks(tmp_path):
    import time as _time

    path = str(tmp_path / "bg.jsonl")
    tree = MetricsTree().register("x", lambda: {"v": 2})
    sampler = ObsSampler(tree, path, interval_s=0.01).start()
    deadline = _time.time() + 5.0
    while sampler.samples_written < 2 and _time.time() < deadline:
        _time.sleep(0.01)
    sampler.stop()
    assert len(read_samples(path)) >= 2


# ---------------------------------------------------------------------------
# 3a. ServingMetrics edge cases (ISSUE 13 satellite)
# ---------------------------------------------------------------------------

def test_publishes_per_sec_ewma_first_publish():
    """The FIRST publish has no predecessor interval: the rate gauge
    must stay unset (no fake spike from a zero interval); the second
    publish seeds the EWMA with the true instantaneous rate."""
    m = ServingMetrics()
    _publish_once(m, generation=1, t0=1000.0)
    assert m.snapshot()["publishes_per_sec"] is None
    m.on_publish(2, mode="delta", now=1002.0)
    assert m.snapshot()["publishes_per_sec"] == pytest.approx(0.5)
    m.on_publish(3, mode="delta", now=1004.0)        # EWMA stays put
    assert m.snapshot()["publishes_per_sec"] == pytest.approx(0.5)


def test_latency_ring_quantiles_at_wraparound():
    """Past the window the ring holds exactly the newest ``window``
    samples (write order irrelevant to quantiles): quantiles must match
    numpy over that set, not over a stale prefix."""
    from flink_ml_tpu.serving.metrics import LatencyTracker

    tracker = LatencyTracker(window=8)
    for v in range(1, 13):                 # 12 records, window 8
        tracker.record(float(v))
    assert tracker.count == 12
    newest = np.asarray([5.0, 6, 7, 8, 9, 10, 11, 12])
    p50, p99 = tracker.quantiles((0.5, 0.99))
    assert p50 == pytest.approx(float(np.quantile(newest, 0.5)))
    assert p99 == pytest.approx(float(np.quantile(newest, 0.99)))


def test_kernel_gauges_republish_skips_if_unchanged():
    """The kernels.* re-export refreshes only when the dispatch counter
    moved — an idle endpoint's metric tick must not re-walk the
    registry snapshot."""
    from flink_ml_tpu.api.chain import StageKernel, run_kernel
    from flink_ml_tpu.kernels.registry import kernel_stats

    m = ServingMetrics()
    m.publish()
    sentinel = object()
    gauge = m.group.add_group("kernels").gauge("dispatches")
    gauge.set(sentinel)
    m.publish()                            # counter unchanged -> skipped
    assert gauge.value is sentinel
    kernel = StageKernel(
        fn=_double_fn, static=(), params=None,
        consumes=("obs_col",), produces=("obs_out",))
    run_kernel(kernel, Table({"obs_col": np.ones((4,), np.float32)}),
               op="_obs_gauge_op")
    m.publish()                            # counter moved -> refreshed
    assert gauge.value == kernel_stats.dispatches


def _double_fn(static, params, cols):
    return {"obs_out": cols["obs_col"] * 2.0}


# ---------------------------------------------------------------------------
# 3b. StepProbe
# ---------------------------------------------------------------------------

def test_probe_records_under_scan_and_fetches_once():
    import jax
    import jax.numpy as jnp

    probe = StepProbe.create(("loss", "grad_norm"), 4)

    @jax.jit
    def run(probe, xs):
        def step(p, x):
            return p.record(loss=x, grad_norm=x * 2), None

        p, _ = jax.lax.scan(step, probe, xs)
        return p

    out = run(probe, jnp.arange(3, dtype=jnp.float32))
    got = out.fetch()
    np.testing.assert_array_equal(got["loss"], [0.0, 1.0, 2.0])
    np.testing.assert_array_equal(got["grad_norm"], [0.0, 2.0, 4.0])
    fresh = out.reset().fetch()
    assert fresh["loss"].shape == (0,)


def test_probe_partial_channels_and_validation():
    probe = StepProbe.create(("a", "b"), 2)
    got = probe.record(a=1.0).fetch()
    assert got["a"][0] == 1.0 and math.isnan(got["b"][0])
    with pytest.raises(ValueError, match="unknown probe channel"):
        probe.record(c=1.0)
    with pytest.raises(ValueError, match="duplicate"):
        StepProbe.create(("a", "a"), 2)
    # past-capacity records drop instead of corrupting the buffer
    full = probe.record(a=1.0).record(a=2.0).record(a=3.0)
    np.testing.assert_array_equal(full.fetch()["a"], [1.0, 2.0])


def test_probe_rides_pytree_boundaries():
    import jax

    probe = StepProbe.create(("loss",), 3).record(loss=7.0)
    leaves, treedef = jax.tree_util.tree_flatten(probe)
    rebuilt = jax.tree_util.tree_unflatten(treedef, leaves)
    assert rebuilt.names == ("loss",) and rebuilt.capacity == 3
    np.testing.assert_array_equal(rebuilt.fetch()["loss"], [7.0])


def test_chunked_fit_step_probe_bitexact_and_traced():
    """sgd_fit_outofcore(step_probe=True): the probe changes NOTHING
    about the result (bit-exact params + loss log vs probe-off on the
    same stream) and stream_info carries the full per-step loss series
    across chunk boundaries, padded tail excluded."""
    from flink_ml_tpu.models.common.losses import squared_loss
    from flink_ml_tpu.models.common.sgd import SGDConfig, sgd_fit_outofcore

    def mk():
        rng = np.random.default_rng(7)

        def make_reader():
            for _ in range(10):           # 10 batches, W=4 -> padded tail
                X = rng.normal(size=(16, 4)).astype(np.float32)
                yield {"features": X,
                       "label": (X @ np.arange(1, 5)).astype(np.float32)}

        return make_reader

    cfg = SGDConfig(max_epochs=2, tol=0.0)
    info: dict = {}
    s1, log1 = sgd_fit_outofcore(squared_loss, mk(), num_features=4,
                                 config=cfg, steps_per_dispatch=4,
                                 stream_info=info, step_probe=True)
    s2, log2 = sgd_fit_outofcore(squared_loss, mk(), num_features=4,
                                 config=cfg, steps_per_dispatch=4)
    assert s1.coefficients.tobytes() == s2.coefficients.tobytes()
    assert log1 == log2
    trace = info["step_trace"]["loss"]
    assert trace.shape == (20,)           # 10 steps x 2 epochs, no pad
    assert np.all(np.isfinite(trace))
    # the per-step series is consistent with the epoch aggregate
    assert np.mean(trace[:10]) == pytest.approx(log1[0], rel=1e-5)


def test_chunked_fit_probe_lowerings_do_not_scale_with_chunks():
    """The probe rides the ONE chunk-scan program and its per-chunk
    fetch/reset are transfers + cached tiny ops, not new programs: a
    warmed probed fit lowers the same count at 1 epoch and at 4 (12
    chunk dispatches) — zero per-chunk/per-epoch retraces with the
    probe attached."""
    from jax._src import test_util as jtu

    from flink_ml_tpu.models.common.losses import squared_loss
    from flink_ml_tpu.models.common.sgd import SGDConfig, sgd_fit_outofcore

    def mk():
        rng = np.random.default_rng(5)

        def make_reader():
            for _ in range(8):
                X = rng.normal(size=(16, 4)).astype(np.float32)
                yield {"features": X,
                       "label": (X @ np.arange(1, 5)).astype(np.float32)}

        return make_reader

    def lowerings(epochs: int) -> int:
        cfg = SGDConfig(max_epochs=epochs, tol=0.0)
        with jtu.count_jit_and_pmap_lowerings() as count:
            sgd_fit_outofcore(squared_loss, mk(), num_features=4,
                              config=cfg, steps_per_dispatch=4,
                              cache_decoded=False, step_probe=True)
        return count[0]

    lowerings(1)                          # one-time compiles warm here
    assert lowerings(1) == lowerings(4)


def test_step_probe_refused_off_the_chunked_path():
    from flink_ml_tpu.models.common.losses import squared_loss
    from flink_ml_tpu.models.common.sgd import SGDConfig, sgd_fit_outofcore
    from flink_ml_tpu.parallel.mesh import default_mesh

    mesh = default_mesh()
    if int(np.prod(list(mesh.shape.values()))) == mesh.devices.size \
            and len(set(d.process_index for d in mesh.devices.flat)) == 1:
        pytest.skip("single-process mesh: the chunked path engages")
    with pytest.raises(ValueError, match="chunked single-process"):
        sgd_fit_outofcore(squared_loss, lambda: iter(()), num_features=4,
                          config=SGDConfig(max_epochs=1), mesh=mesh,
                          step_probe=True)


def test_fused_iterate_epoch_trace_still_reports():
    """The PR 9 epoch-trace surface survived the StepProbe port: fused
    workset iterations still surface trimmed active-fraction /
    termination curves."""
    import jax.numpy as jnp

    from flink_ml_tpu.iteration import (
        IterationBodyResult,
        IterationConfig,
        Workset,
        iterate,
    )

    def body(state, ws, epoch, data):
        new = state + ws.mask
        return IterationBodyResult(
            (new, Workset((new < data).astype(jnp.float32), ws.bounds)))

    res = iterate(body, jnp.zeros(4), jnp.asarray([1.0, 2.0, 3.0, 2.0]),
                  max_epochs=8, workset=Workset(jnp.ones(4, jnp.float32)),
                  config=IterationConfig(mode="fused"))
    trace = res.side["epoch_trace"]
    assert trace["active_fraction"].shape == (res.num_epochs,)
    assert np.all(np.isfinite(trace["active_fraction"]))
    assert res.num_epochs < 8             # drained before max_epochs


# ---------------------------------------------------------------------------
# 4. THE acceptance: end-to-end correlation + zero new lowerings
# ---------------------------------------------------------------------------

def _windows(start, stop, rows=16, d=4):
    for i in range(start, stop):
        rng = np.random.default_rng(1000 + i)
        X = rng.normal(size=(rows, d)).astype(np.float32)
        yield Table({"features": X,
                     "label": (X[:, 0] > 0).astype(np.float32)})


def test_trace_correlates_wal_cut_publish_and_request(tmp_path):
    """One enabled tracer, one correlation chain: WAL window N ->
    checkpoint cut T -> delta publish (step T, generation G) ->
    generation G served request R — all present and joinable in the
    exported Chrome trace."""
    from flink_ml_tpu.iteration.checkpoint import CheckpointConfig
    from flink_ml_tpu.models.classification.logisticregression import (
        LogisticRegression,
    )
    from flink_ml_tpu.models.common.losses import logistic_loss
    from flink_ml_tpu.online import ContinuousLearner
    from flink_ml_tpu.serving import serve_model

    windows = list(_windows(0, 8))
    boot = LogisticRegression().set_max_iter(1).fit(windows[0])
    endpoint = serve_model(boot, windows[0].drop("label").take(2),
                           max_batch_rows=32, max_wait_ms=0.5)
    tracer = trace_mod.tracer
    try:
        tracer.enable()
        learner = ContinuousLearner(
            loss_fn=logistic_loss, num_features=4,
            source=iter(windows), wal_dir=str(tmp_path / "wal"),
            endpoint=endpoint, batch_rows=16,
            checkpoint=CheckpointConfig(str(tmp_path / "ck")),
            publish_every_steps=4)
        learner.run(max_windows=8)
        out = endpoint.predict(windows[3].drop("label"))
        assert out.num_rows == 16
        tracer.disable()

        # -- the chain, link by link -----------------------------------
        wal = sorted(s.ids["window"] for s in tracer.find("wal_append"))
        assert wal == list(range(8))
        cuts = {s.ids["step"] for s in tracer.find("checkpoint_write")}
        assert {4, 8} <= cuts
        publishes = list(tracer.find("delta_publish"))
        pub_by_step = {s.ids["step"]: s for s in publishes}
        assert {4, 8} <= set(pub_by_step)
        # every publish's cut step has a checkpoint span (never serve
        # ahead of durable) and the WAL holds exactly the windows the
        # cut covers (one window = one step on the fixed grid)
        for step, span in pub_by_step.items():
            assert step in cuts
            assert {w for w in wal if w < step} == set(range(step))
            assert "generation" in span.ids
        live_gen = pub_by_step[8].ids["generation"]
        served = [s for s in tracer.find("request")
                  if s.ids.get("generation") == live_gen]
        assert served, "no request span on the published generation"
        assert all("request_id" in s.ids for s in served)
        # supporting spans of the request path showed up too
        assert any(tracer.find("queue_wait"))
        assert any(tracer.find("serve_batch"))
        assert any(tracer.find("train_chunk"))
        assert any(tracer.find("train_epoch"))

        # -- export round trip -----------------------------------------
        path = str(tmp_path / "trace.json")
        n = tracer.export_chrome(path)
        events = json.load(open(path))["traceEvents"]
        assert len(events) == n
        names = {e["name"] for e in events}
        assert {"wal_append", "checkpoint_write", "delta_publish",
                "request"} <= names
        pub_ev = [e for e in events if e["name"] == "delta_publish"
                  and e["args"].get("step") == 8]
        assert pub_ev and pub_ev[0]["args"]["generation"] == live_gen
    finally:
        tracer.disable()
        tracer.clear()
        endpoint.close()


def test_serving_with_tracing_adds_zero_lowerings():
    """Tracing is pure host bookkeeping: enabling it on a warmed
    endpoint compiles NOTHING (lowering-counter asserted) while the
    request-path spans all appear."""
    from jax._src import test_util as jtu

    from flink_ml_tpu.models.classification.logisticregression import (
        LogisticRegression,
    )
    from flink_ml_tpu.serving import serve_model

    rng = np.random.default_rng(3)
    X = rng.normal(size=(48, 6)).astype(np.float32)
    train = Table({"features": X, "label": (X[:, 0] > 0).astype(np.float64)})
    model = LogisticRegression().set_max_iter(2).fit(train)
    feats = Table({"features": X})
    endpoint = serve_model(model, feats.take(2), max_batch_rows=64,
                           max_wait_ms=0.5)
    tracer = trace_mod.tracer
    try:
        endpoint.predict(feats.take(5))           # tracing off, warm
        tracer.enable()
        with jtu.count_jit_and_pmap_lowerings() as count:
            endpoint.predict(feats.take(5))
        assert count[0] == 0, (
            f"{count[0]} new lowerings with tracing enabled — the "
            "tracer leaked into a traced program")
        for name in ("queue_wait", "serve_batch", "request",
                     "registry_dispatch", "device_execute", "bucket_pad"):
            assert any(tracer.find(name)), f"missing span {name!r}"
    finally:
        tracer.disable()
        tracer.clear()
        endpoint.close()
