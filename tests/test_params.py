"""Param system tests — mirror of ``StageTest.java:51-150`` (a synthetic
stage with every param type; validators, defaults, json, save/load)."""

import numpy as np
import pytest

from flink_ml_tpu import (
    BoolParam,
    DoubleArrayParam,
    FloatParam,
    IntArrayParam,
    IntParam,
    InvalidParamError,
    ParamValidators,
    StringArrayParam,
    StringParam,
    VectorParam,
)
from flink_ml_tpu.api.stage import Stage
from flink_ml_tpu.params.shared import HasMaxIter, HasSeed
from flink_ml_tpu.utils import persist


class MyStage(Stage, HasMaxIter, HasSeed):
    """Analog of StageTest.MyStage: one param of each type."""

    BOOL_PARAM = BoolParam("boolParam", "Bool param", default=True)
    INT_PARAM = IntParam("intParam", "Int param", default=1,
                         validator=ParamValidators.lt(100))
    DOUBLE_PARAM = FloatParam("doubleParam", "Double param", default=3.0,
                              validator=ParamValidators.in_range(0.0, 10.0))
    STRING_PARAM = StringParam("stringParam", "String param", default="5")
    INT_ARRAY_PARAM = IntArrayParam("intArrayParam", "IntArray param",
                                    default=(6, 7))
    DOUBLE_ARRAY_PARAM = DoubleArrayParam("doubleArrayParam",
                                          "DoubleArray param",
                                          default=(10.0, 11.0))
    STRING_ARRAY_PARAM = StringArrayParam("stringArrayParam",
                                          "StringArray param",
                                          default=("14", "15"))
    VECTOR_PARAM = VectorParam("vectorParam", "Vector param",
                               default=np.array([1.0, 2.0]))


def test_defaults():
    s = MyStage()
    assert s.get(MyStage.BOOL_PARAM) is True
    assert s.get(MyStage.INT_PARAM) == 1
    assert s.get(MyStage.DOUBLE_PARAM) == 3.0
    assert s.get(MyStage.STRING_PARAM) == "5"
    assert s.get(MyStage.INT_ARRAY_PARAM) == (6, 7)
    assert s.get("doubleArrayParam") == (10.0, 11.0)
    assert s.get(MyStage.STRING_ARRAY_PARAM) == ("14", "15")
    np.testing.assert_array_equal(s.get(MyStage.VECTOR_PARAM), [1.0, 2.0])
    # inherited mixin params are discovered too (the MRO walk is the analog
    # of the reference's interface-field reflection)
    assert s.get_max_iter() == 20
    assert s.get_seed() == 0


def test_set_get_chaining():
    s = MyStage().set(MyStage.INT_PARAM, 7).set("stringParam", "x")
    assert s.get(MyStage.INT_PARAM) == 7
    assert s.get(MyStage.STRING_PARAM) == "x"
    # descriptor read access
    assert s.INT_PARAM == 7


def test_validators_reject():
    s = MyStage()
    with pytest.raises(InvalidParamError):
        s.set(MyStage.INT_PARAM, 100)          # lt(100)
    with pytest.raises(InvalidParamError):
        s.set(MyStage.DOUBLE_PARAM, 10.5)      # in_range(0, 10)
    with pytest.raises(InvalidParamError):
        MyStage().set("noSuchParam", 1)


def test_validator_factories():
    v = ParamValidators
    assert v.gt(5)(6) and not v.gt(5)(5)
    assert v.gt_eq(5)(5) and not v.gt_eq(5)(4)
    assert v.lt(5)(4) and not v.lt(5)(5)
    assert v.lt_eq(5)(5) and not v.lt_eq(5)(6)
    assert v.in_range(0, 1)(0) and not v.in_range(0, 1, lower_inclusive=False)(0)
    assert v.in_array(["a", "b"])("a") and not v.in_array(["a"])("b")
    assert v.not_null()(0) and not v.not_null()(None)
    assert not v.gt(0)(None)


def test_type_coercion():
    s = MyStage()
    s.set(MyStage.DOUBLE_PARAM, 4)  # int -> float
    assert s.get(MyStage.DOUBLE_PARAM) == 4.0
    s.set(MyStage.INT_ARRAY_PARAM, [1.0, 2.0])
    assert s.get(MyStage.INT_ARRAY_PARAM) == (1, 2)
    with pytest.raises(InvalidParamError):
        s.set(MyStage.BOOL_PARAM, "yes")
    with pytest.raises(InvalidParamError):
        s.set(MyStage.INT_PARAM, True)  # bools are not ints here


def test_param_map_isolation():
    a, b = MyStage(), MyStage()
    a.set(MyStage.INT_PARAM, 42)
    assert b.get(MyStage.INT_PARAM) == 1


def test_json_round_trip():
    s = MyStage().set(MyStage.INT_PARAM, 9).set(
        MyStage.VECTOR_PARAM, np.array([3.0, 4.0]))
    payload = s.params_to_json()
    restored = MyStage()
    restored.params_from_json(payload)
    assert restored.get(MyStage.INT_PARAM) == 9
    np.testing.assert_array_equal(restored.get(MyStage.VECTOR_PARAM), [3.0, 4.0])
    assert restored.get(MyStage.INT_ARRAY_PARAM) == (6, 7)


def test_save_load_stage(tmp_path):
    path = str(tmp_path / "stage")
    s = MyStage().set(MyStage.INT_PARAM, 11).set(MyStage.STRING_PARAM, "hello")
    s.save(path)
    loaded = MyStage.load(path)
    assert isinstance(loaded, MyStage)
    assert loaded.get(MyStage.INT_PARAM) == 11
    assert loaded.get(MyStage.STRING_PARAM) == "hello"
    # generic reflective load (ReadWriteUtils.loadStage analog)
    loaded2 = persist.load_stage(path)
    assert isinstance(loaded2, MyStage)
    assert loaded2.get(MyStage.INT_PARAM) == 11


def test_metadata_class_check(tmp_path):
    path = str(tmp_path / "stage")
    MyStage().save(path)

    class Other(Stage):
        pass

    with pytest.raises(IOError):
        persist.load_metadata(path, Other)


def test_set_null_validated_at_set_time():
    # WithParams.java:91-95 rejects null at set() unless validator accepts it
    s = MyStage()
    with pytest.raises(InvalidParamError):
        s.set(MyStage.INT_PARAM, None)  # lt(100) rejects None


def test_array_param_rejects_bare_string():
    s = MyStage()
    with pytest.raises(InvalidParamError):
        s.set(MyStage.STRING_ARRAY_PARAM, "abc")


def test_set_foreign_param_object_rejected():
    # A same-named but differently-typed Param must not create a shadow entry
    foreign = FloatParam("intParam", "imposter")
    s = MyStage()
    with pytest.raises(InvalidParamError):
        s.set(foreign, 2.5)
    assert s.get(MyStage.INT_PARAM) == 1
    assert s.params_to_json()["intParam"] == 1
