"""Wide&Deep tests: fit/predict on a synthetic CTR-like task, save/load,
sharded multichip train step, broadcast utils."""

import numpy as np
import pytest

from flink_ml_tpu import Table
from flink_ml_tpu.models.recommendation.widedeep import (
    WideDeep,
    WideDeepModel,
    build_sharded_train_step,
)


def _ctr_table(n=512, seed=0):
    """Clicks driven by one categorical field + one dense feature."""
    rng = np.random.default_rng(seed)
    dense = rng.normal(size=(n, 4)).astype(np.float32)
    cat = np.stack([
        rng.integers(0, 10, size=n),   # field A: matters
        rng.integers(0, 7, size=n),    # field B: noise
    ], axis=1).astype(np.int32)
    logit = (cat[:, 0] - 4.5) * 1.2 + dense[:, 0] * 2.0
    label = (logit + 0.3 * rng.normal(size=n) > 0).astype(np.int64)
    return Table({"denseFeatures": dense, "catFeatures": cat,
                  "label": label})


def test_requires_vocab_sizes():
    with pytest.raises(ValueError):
        WideDeep().fit(_ctr_table())


def test_vocab_range_validated():
    t = _ctr_table()
    wd = WideDeep().set_vocab_sizes([5, 7])  # field A ids go up to 9
    with pytest.raises(ValueError):
        wd.fit(t)


def test_fit_predict():
    t = _ctr_table()
    model = (WideDeep().set_vocab_sizes([10, 7]).set_max_iter(30)
             .set_seed(0).fit(t))
    out = model.transform(t)[0]
    acc = np.mean(out["prediction"] == t["label"])
    assert acc > 0.9
    assert np.all((out["rawPrediction"] >= 0) & (out["rawPrediction"] <= 1))
    # training loss decreased
    assert model._loss_log[-1] < model._loss_log[0]


def test_save_load(tmp_path):
    t = _ctr_table(n=128)
    model = WideDeep().set_vocab_sizes([10, 7]).set_max_iter(5).fit(t)
    path = str(tmp_path / "wd")
    model.save(path)
    loaded = WideDeepModel.load(path)
    np.testing.assert_allclose(loaded.transform(t)[0]["rawPrediction"],
                               model.transform(t)[0]["rawPrediction"],
                               rtol=1e-6)


def test_sharded_train_step_dp_tp():
    # dp x tp mesh: embeddings + hidden dims sharded over 'model'
    import jax

    from flink_ml_tpu.parallel.mesh import device_mesh

    mesh = device_mesh({"data": 4, "model": 2})
    train_step, params, opt, opt_state, shard_batch = \
        build_sharded_train_step(mesh, d_dense=4, vocab_sizes=[10, 7],
                                 emb_dim=8, hidden=(16, 8))
    rng = np.random.default_rng(0)
    batch = shard_batch(
        rng.normal(size=(32, 4)).astype(np.float32),
        np.stack([rng.integers(0, 10, 32),
                  10 + rng.integers(0, 7, 32)], 1).astype(np.int32),
        rng.integers(0, 2, 32).astype(np.float32),
        np.ones((32,), np.float32))
    emb_sharding = params["emb"].sharding
    assert len(emb_sharding.device_set) == 8

    p, s, loss1 = train_step(params, opt_state, *batch)
    p, s, loss2 = train_step(p, s, *batch)
    assert np.isfinite(float(loss1))
    assert float(loss2) < float(loss1)  # two steps on same batch improve it
    # params kept their shardings through the step
    assert p["emb"].sharding.spec == emb_sharding.spec


def test_sharded_train_step_matches_single_device_oracle():
    """THE dp x tp numerical oracle (VERDICT r1 task 4): the sharded train
    step on the 8-device mesh must reproduce an unsharded single-device step
    bit-for-tolerance — loss AND updated params over several steps.  Wrong
    psum/axis placement still *converges*, which is why the loss-decreases
    assert above cannot catch it; exact equivalence can."""
    from flink_ml_tpu.models.recommendation.widedeep import (
        assert_sharded_matches_reference,
        build_reference_train_step,
    )
    from flink_ml_tpu.parallel.mesh import device_mesh

    d_dense, vocab_sizes, emb_dim, hidden, lr = 4, [10, 7], 8, (16, 8), 1e-2
    mesh = device_mesh({"data": 4, "model": 2})
    train_step, params_s, opt, opt_state_s, shard_batch = \
        build_sharded_train_step(mesh, d_dense=d_dense,
                                 vocab_sizes=vocab_sizes, emb_dim=emb_dim,
                                 hidden=hidden, lr=lr)
    step_1, params_1, opt_state_1 = build_reference_train_step(
        d_dense, vocab_sizes, emb_dim, hidden, lr)

    rng = np.random.default_rng(1)
    for step in range(3):
        dense = rng.normal(size=(32, d_dense)).astype(np.float32)
        cat = np.stack([rng.integers(0, 10, 32),
                        10 + rng.integers(0, 7, 32)], 1).astype(np.int32)
        labels = rng.integers(0, 2, 32).astype(np.float32)
        mask = np.ones((32,), np.float32)

        params_s, opt_state_s, loss_s = train_step(
            params_s, opt_state_s, *shard_batch(dense, cat, labels, mask))
        params_1, opt_state_1, loss_1 = step_1(
            params_1, opt_state_1, dense, cat, labels, mask)
        assert_sharded_matches_reference(params_s, loss_s, params_1, loss_1)


def test_broadcast_utils():
    import jax.numpy as jnp

    from flink_ml_tpu.data.broadcast import with_broadcast

    centroids = Table({"c": np.arange(6, dtype=np.float64).reshape(3, 2)})
    main = np.ones((4, 2), np.float32)

    def fn(X, ctx):
        c = ctx.get_broadcast_variable("centroids")["c"]
        assert len(c.sharding.device_set) == 8  # replicated over the mesh
        return jnp.asarray(X) @ jnp.asarray(c, jnp.float32).T

    out = with_broadcast(fn, {"centroids": centroids}, main)
    assert out.shape == (4, 3)

    def missing(X, ctx):
        ctx.get_broadcast_variable("nope")

    with pytest.raises(KeyError):
        with_broadcast(missing, {"centroids": centroids}, main)


def test_transform_validates_vocab_range():
    t = _ctr_table(n=64)
    model = WideDeep().set_vocab_sizes([10, 7]).set_max_iter(2).fit(t)
    bad = Table({"denseFeatures": np.zeros((1, 4), np.float32),
                 "catFeatures": np.array([[10, 0]], np.int32)})  # id 10 >= 10
    with pytest.raises(ValueError):
        model.transform(bad)


# ------------------------------------------------------ LazyAdam tables


def _lazy_fixture(vocab_sizes=(6, 5), emb_dim=4, hidden=(8,), batch=16,
                  seed=3):
    from flink_ml_tpu.models.recommendation.widedeep import (
        _field_offsets, build_reference_train_step)

    rng = np.random.default_rng(seed)
    n_fields = len(vocab_sizes)
    offs = _field_offsets(vocab_sizes)

    def make_batch(low, high):
        """cat ids restricted to [low, high) within each field."""
        cat = (np.stack([rng.integers(low, min(high, v), size=batch)
                         for v in vocab_sizes], 1).astype(np.int32)
               + offs[None, :])
        return (rng.normal(size=(batch, 3)).astype(np.float32), cat,
                rng.integers(0, 2, size=batch).astype(np.float32),
                np.ones((batch,), np.float32))

    dense_step, p0, s0 = build_reference_train_step(
        3, vocab_sizes, emb_dim, hidden)
    lazy_step, p1, s1 = build_reference_train_step(
        3, vocab_sizes, emb_dim, hidden, lazy_embeddings=True)
    np.testing.assert_array_equal(np.asarray(p0["emb"]),
                                  np.asarray(p1["emb"]))  # same init
    return make_batch, (dense_step, p0, s0), (lazy_step, p1, s1)


def test_lazy_adam_untouched_rows_frozen():
    """Never-touched rows keep init exactly under BOTH optimizers (zero
    grad => zero momentum), but rows touched ONCE then idle expose the
    semantic difference: dense Adam keeps moving them on later steps
    (momentum tail), LazyAdam freezes them at their post-touch value."""
    make_batch, (dense_step, p0, s0), (lazy_step, p1, s1) = _lazy_fixture()

    # step 1 touches ALL ids; steps 2-3 touch only ids < 3 per field
    first = make_batch(0, 100)
    p0, s0, _ = dense_step(p0, s0, *first)
    p1, s1, _ = lazy_step(p1, s1, *first)

    from flink_ml_tpu.models.recommendation.widedeep import _field_offsets
    offs = _field_offsets((6, 5))
    idle = np.concatenate(
        [np.arange(3, 6) + offs[0], np.arange(3, 5) + offs[1]])
    touched_once = np.asarray(first[1]).reshape(-1)
    idle = np.intersect1d(idle, touched_once)   # touched in step 1 only
    assert idle.size > 0, "fixture must touch some high ids in step 1"
    lazy_after_touch = np.asarray(p1["emb"])[idle].copy()
    dense_after_touch = np.asarray(p0["emb"])[idle].copy()

    for _ in range(3):
        b = make_batch(0, 3)
        p0, s0, _ = dense_step(p0, s0, *b)
        p1, s1, _ = lazy_step(p1, s1, *b)

    # LazyAdam: idle rows bit-frozen at their post-touch value
    np.testing.assert_array_equal(np.asarray(p1["emb"])[idle],
                                  lazy_after_touch)
    # dense Adam: nonzero momentum keeps moving them
    assert not np.array_equal(np.asarray(p0["emb"])[idle],
                              dense_after_touch)


def test_lazy_adam_matches_dense_when_all_rows_touched():
    """A row touched by EVERY step has a dense-Adam-identical history, so
    with every id in every batch the two optimizers agree allclose."""
    import jax.numpy as jnp

    make_batch, (dense_step, p0, s0), (lazy_step, p1, s1) = _lazy_fixture(
        vocab_sizes=(4, 3), batch=2)
    from flink_ml_tpu.models.recommendation.widedeep import _field_offsets

    # construct batches covering EVERY id of every field each step:
    # field A ids 0..3 and field B ids 0..2 over 12 (batch-2) rows
    rng = np.random.default_rng(9)
    offs = _field_offsets((4, 3))
    a = np.repeat(np.arange(4, dtype=np.int32), 3)
    b = np.tile(np.arange(3, dtype=np.int32), 4)
    cat_all = np.stack([a + offs[0], b + offs[1]], 1)  # (12, 2)

    for step in range(4):
        dense = rng.normal(size=(12, 3)).astype(np.float32)
        y = rng.integers(0, 2, size=12).astype(np.float32)
        w = np.ones((12,), np.float32)
        p0, s0, l0 = dense_step(p0, s0, dense, cat_all, y, w)
        p1, s1, l1 = lazy_step(p1, s1, dense, cat_all, y, w)
        np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)

    for k in ("emb", "wide_cat"):
        np.testing.assert_allclose(np.asarray(p0[k]), np.asarray(p1[k]),
                                   rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(p0["wide_dense"]),
                               np.asarray(p1["wide_dense"]),
                               rtol=1e-5, atol=1e-6)


def test_lazy_fit_converges_and_predicts():
    t = _ctr_table()
    model = (WideDeep().set_vocab_sizes([10, 7]).set_max_iter(8)
             .set(WideDeep.LAZY_EMB_OPT, True).fit(t))
    out = model.transform(t)[0]
    acc = (np.asarray(out["prediction"]) ==
           np.asarray(t["label"])).mean()
    assert acc > 0.8
    losses = model._loss_log
    assert losses[-1] < losses[0]


def test_lazy_adam_ignores_padding_rows():
    """Epoch padding rows carry cat id 0 with weight 0 — they must not
    count as 'touched': global row 0 stays bit-frozen unless a REAL row
    references it (regression: phantom momentum-tail updates at id 0)."""
    make_batch, _, (lazy_step, p1, s1) = _lazy_fixture(batch=8)

    dense, cat, y, w = make_batch(1, 100)     # real rows avoid id 0/off
    assert not np.any(cat == 0)
    # append "padding": weight-0 rows with cat id 0 (what
    # prepare_epoch_tensor produces for a ragged final batch)
    pad = 3
    dense = np.concatenate([dense, np.zeros((pad, 3), np.float32)])
    cat = np.concatenate([cat, np.zeros((pad, 2), np.int32)])
    y = np.concatenate([y, np.zeros((pad,), np.float32)])
    w = np.concatenate([w, np.zeros((pad,), np.float32)])

    from flink_ml_tpu.models.recommendation.widedeep import init_params
    init = init_params(np.random.default_rng(0), 3, (6, 5), 4, (8,))
    for _ in range(3):
        p1, s1, _ = lazy_step(p1, s1, dense, cat, y, w)

    np.testing.assert_array_equal(np.asarray(p1["emb"])[0],
                                  init["emb"][0])
    np.testing.assert_array_equal(np.asarray(s1["m"]["emb"])[0],
                                  np.zeros(4, np.float32))


# --------------------------------------------------------- out-of-core


def test_fit_outofcore_matches_inmemory_quality(tmp_path):
    """Streaming WDL fit from the data cache reaches in-memory fit
    quality on the same rows; epoch-aware factories get the epoch."""
    from flink_ml_tpu.data.datacache import DataCacheReader, DataCacheWriter

    t = _ctr_table(n=512)
    cache = str(tmp_path / "wdcache")
    w = DataCacheWriter(cache, segment_rows=256)
    w.append({"denseFeatures": np.asarray(t["denseFeatures"]),
              "catFeatures": np.asarray(t["catFeatures"]),
              "label": np.asarray(t["label"], np.float32)})
    w.finish()

    epochs_seen = []

    def make_reader(epoch):
        epochs_seen.append(epoch)
        return DataCacheReader(cache, batch_rows=128)

    est = WideDeep().set_vocab_sizes([10, 7]).set_max_iter(12).set_seed(0)
    model_stream = est.fit_outofcore(make_reader)
    model_mem = est.fit(t)

    assert epochs_seen == list(range(12))
    out_s = model_stream.transform(t)[0]
    out_m = model_mem.transform(t)[0]
    acc_s = np.mean(out_s["prediction"] == t["label"])
    acc_m = np.mean(out_m["prediction"] == t["label"])
    assert acc_s > 0.85 and acc_s >= acc_m - 0.05
    assert model_stream._loss_log[-1] < model_stream._loss_log[0]


def test_fit_outofcore_partial_batch_and_lazy(tmp_path):
    """Ragged final batch (padding rows) + lazyEmbeddingOptimizer: the
    padded rows are inert and training still converges."""
    from flink_ml_tpu.data.datacache import DataCacheReader, DataCacheWriter

    t = _ctr_table(n=500)       # 500 % 128 != 0 -> padded final batch
    cache = str(tmp_path / "wdlazy")
    w = DataCacheWriter(cache, segment_rows=256)
    w.append({"denseFeatures": np.asarray(t["denseFeatures"]),
              "catFeatures": np.asarray(t["catFeatures"]),
              "label": np.asarray(t["label"], np.float32)})
    w.finish()

    model = (WideDeep().set_vocab_sizes([10, 7]).set_max_iter(10)
             .set(WideDeep.LAZY_EMB_OPT, True)
             .fit_outofcore(
                 lambda: DataCacheReader(cache, batch_rows=128)))
    out = model.transform(t)[0]
    assert np.mean(out["prediction"] == t["label"]) > 0.8


def test_fit_outofcore_empty_reader_rejected():
    with pytest.raises(ValueError, match="empty epoch"):
        (WideDeep().set_vocab_sizes([4]).set_max_iter(2)
         .fit_outofcore(lambda: iter([])))


# ------------------------------------------------- routed table gradients


def test_routed_fit_matches_dense_scatter_fit():
    """routedEmbeddingGrad='auto' (the fit() default) must reproduce the
    autodiff-scatter fit up to f32 summation order.

    "Up to f32 summation order" is a ONE-STEP contract, not a
    trajectory one: the routed scatter sums duplicate-id gradient rows
    in segment order while autodiff's scatter-add sums them in XLA's
    order, and on the suite's 8-device virtual mesh the per-device
    partial sums reorder further — a ~1e-7-relative difference per
    step, by construction.  Adam then amplifies it multiplicatively
    (measured on this mesh: epoch-1 loss rel diff 3.7e-6 growing
    ~10-20x per epoch to ~1e-2 by epoch 8), so the old
    trajectory-level rtol=1e-5 over 8 epochs asserted something no
    reordered-sum implementation can satisfy — this was the seed
    suite's one standing failure.  The comparison is therefore split
    to match what the implementation actually guarantees:

    1. TIGHT at one epoch (8 Adam steps): loss at the repo's
       sharded-vs-reference tolerance, params at the f32
       summation-order scale.
    2. BOUNDED at 8 epochs: the trajectories stay within the measured
       chaotic-amplification envelope and converge to the same
       quality.
    """
    t = _ctr_table()

    def fit(iters, mode):
        return (WideDeep().set_vocab_sizes([10, 7]).set_max_iter(iters)
                .set_seed(0).set(WideDeep.ROUTED_EMB_GRAD, mode).fit(t))

    # 1 — the per-step contract, amplification-free horizon
    m_r1, m_d1 = fit(1, "auto"), fit(1, "off")
    np.testing.assert_allclose(m_r1._loss_log, m_d1._loss_log,
                               rtol=2e-5, atol=1e-6)
    for k in ("emb", "wide_cat", "wide_dense", "wide_b"):
        np.testing.assert_allclose(np.asarray(m_r1._params[k]),
                                   np.asarray(m_d1._params[k]),
                                   rtol=1e-3, atol=1e-3)

    # 2 — the trajectory envelope + end-quality equivalence
    m_r, m_d = fit(8, "auto"), fit(8, "off")
    np.testing.assert_allclose(m_r._loss_log, m_d._loss_log,
                               rtol=5e-2, atol=1e-4)
    for k in ("emb", "wide_cat", "wide_dense", "wide_b"):
        np.testing.assert_allclose(np.asarray(m_r._params[k]),
                                   np.asarray(m_d._params[k]),
                                   rtol=0.5, atol=5e-2)
    acc = []
    for m in (m_r, m_d):
        out = m.transform(t)[0]
        acc.append(np.mean(out["prediction"] == t["label"]))
    assert min(acc) > 0.85 and abs(acc[0] - acc[1]) < 0.02, acc


def test_routed_on_rejects_lazy():
    t = _ctr_table(n=64)
    est = (WideDeep().set_vocab_sizes([10, 7]).set_max_iter(2)
           .set(WideDeep.LAZY_EMB_OPT, True)
           .set(WideDeep.ROUTED_EMB_GRAD, "on"))
    with pytest.raises(ValueError, match="dense-Adam"):
        est.fit(t)


def test_routed_auto_defers_to_lazy():
    """'auto' + lazyEmbeddingOptimizer trains on the lazy path (no
    conflict), and still converges."""
    t = _ctr_table()
    model = (WideDeep().set_vocab_sizes([10, 7]).set_max_iter(8)
             .set_seed(0).set(WideDeep.LAZY_EMB_OPT, True).fit(t))
    out = model.transform(t)[0]
    assert np.mean(out["prediction"] == t["label"]) > 0.85


def test_routed_on_rejected_by_streaming_fit(tmp_path):
    est = (WideDeep().set_vocab_sizes([10, 7]).set_max_iter(2)
           .set(WideDeep.ROUTED_EMB_GRAD, "on"))
    with pytest.raises(ValueError, match="streaming"):
        est.fit_outofcore(lambda: iter(()))


def test_routed_fit_exact_with_padding_rows():
    """n not divisible by the global batch: the epoch layout pads rows
    with mask 0 and cat id 0 — their loss gradients are exactly zero,
    so the routed path must still match the autodiff-scatter fit."""
    t = _ctr_table(n=500)          # 500 % 32 != 0 -> padded final rows
    def fit(mode):
        return (WideDeep().set_vocab_sizes([10, 7]).set_max_iter(6)
                .set_seed(0).set(WideDeep.ROUTED_EMB_GRAD, mode).fit(t))
    m_r, m_d = fit("on"), fit("off")
    np.testing.assert_allclose(m_r._loss_log, m_d._loss_log,
                               rtol=1e-5, atol=1e-6)
    for k in ("emb", "wide_cat"):
        np.testing.assert_allclose(np.asarray(m_r._params[k]),
                                   np.asarray(m_d._params[k]),
                                   rtol=1e-4, atol=1e-5)
