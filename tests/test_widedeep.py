"""Wide&Deep tests: fit/predict on a synthetic CTR-like task, save/load,
sharded multichip train step, broadcast utils."""

import numpy as np
import pytest

from flink_ml_tpu import Table
from flink_ml_tpu.models.recommendation.widedeep import (
    WideDeep,
    WideDeepModel,
    build_sharded_train_step,
)


def _ctr_table(n=512, seed=0):
    """Clicks driven by one categorical field + one dense feature."""
    rng = np.random.default_rng(seed)
    dense = rng.normal(size=(n, 4)).astype(np.float32)
    cat = np.stack([
        rng.integers(0, 10, size=n),   # field A: matters
        rng.integers(0, 7, size=n),    # field B: noise
    ], axis=1).astype(np.int32)
    logit = (cat[:, 0] - 4.5) * 1.2 + dense[:, 0] * 2.0
    label = (logit + 0.3 * rng.normal(size=n) > 0).astype(np.int64)
    return Table({"denseFeatures": dense, "catFeatures": cat,
                  "label": label})


def test_requires_vocab_sizes():
    with pytest.raises(ValueError):
        WideDeep().fit(_ctr_table())


def test_vocab_range_validated():
    t = _ctr_table()
    wd = WideDeep().set_vocab_sizes([5, 7])  # field A ids go up to 9
    with pytest.raises(ValueError):
        wd.fit(t)


def test_fit_predict():
    t = _ctr_table()
    model = (WideDeep().set_vocab_sizes([10, 7]).set_max_iter(30)
             .set_seed(0).fit(t))
    out = model.transform(t)[0]
    acc = np.mean(out["prediction"] == t["label"])
    assert acc > 0.9
    assert np.all((out["rawPrediction"] >= 0) & (out["rawPrediction"] <= 1))
    # training loss decreased
    assert model._loss_log[-1] < model._loss_log[0]


def test_save_load(tmp_path):
    t = _ctr_table(n=128)
    model = WideDeep().set_vocab_sizes([10, 7]).set_max_iter(5).fit(t)
    path = str(tmp_path / "wd")
    model.save(path)
    loaded = WideDeepModel.load(path)
    np.testing.assert_allclose(loaded.transform(t)[0]["rawPrediction"],
                               model.transform(t)[0]["rawPrediction"],
                               rtol=1e-6)


def test_sharded_train_step_dp_tp():
    # dp x tp mesh: embeddings + hidden dims sharded over 'model'
    import jax

    from flink_ml_tpu.parallel.mesh import device_mesh

    mesh = device_mesh({"data": 4, "model": 2})
    train_step, params, opt, opt_state, shard_batch = \
        build_sharded_train_step(mesh, d_dense=4, vocab_sizes=[10, 7],
                                 emb_dim=8, hidden=(16, 8))
    rng = np.random.default_rng(0)
    batch = shard_batch(
        rng.normal(size=(32, 4)).astype(np.float32),
        np.stack([rng.integers(0, 10, 32),
                  10 + rng.integers(0, 7, 32)], 1).astype(np.int32),
        rng.integers(0, 2, 32).astype(np.float32),
        np.ones((32,), np.float32))
    emb_sharding = params["emb"].sharding
    assert len(emb_sharding.device_set) == 8

    p, s, loss1 = train_step(params, opt_state, *batch)
    p, s, loss2 = train_step(p, s, *batch)
    assert np.isfinite(float(loss1))
    assert float(loss2) < float(loss1)  # two steps on same batch improve it
    # params kept their shardings through the step
    assert p["emb"].sharding.spec == emb_sharding.spec


def test_sharded_train_step_matches_single_device_oracle():
    """THE dp x tp numerical oracle (VERDICT r1 task 4): the sharded train
    step on the 8-device mesh must reproduce an unsharded single-device step
    bit-for-tolerance — loss AND updated params over several steps.  Wrong
    psum/axis placement still *converges*, which is why the loss-decreases
    assert above cannot catch it; exact equivalence can."""
    from flink_ml_tpu.models.recommendation.widedeep import (
        assert_sharded_matches_reference,
        build_reference_train_step,
    )
    from flink_ml_tpu.parallel.mesh import device_mesh

    d_dense, vocab_sizes, emb_dim, hidden, lr = 4, [10, 7], 8, (16, 8), 1e-2
    mesh = device_mesh({"data": 4, "model": 2})
    train_step, params_s, opt, opt_state_s, shard_batch = \
        build_sharded_train_step(mesh, d_dense=d_dense,
                                 vocab_sizes=vocab_sizes, emb_dim=emb_dim,
                                 hidden=hidden, lr=lr)
    step_1, params_1, opt_state_1 = build_reference_train_step(
        d_dense, vocab_sizes, emb_dim, hidden, lr)

    rng = np.random.default_rng(1)
    for step in range(3):
        dense = rng.normal(size=(32, d_dense)).astype(np.float32)
        cat = np.stack([rng.integers(0, 10, 32),
                        10 + rng.integers(0, 7, 32)], 1).astype(np.int32)
        labels = rng.integers(0, 2, 32).astype(np.float32)
        mask = np.ones((32,), np.float32)

        params_s, opt_state_s, loss_s = train_step(
            params_s, opt_state_s, *shard_batch(dense, cat, labels, mask))
        params_1, opt_state_1, loss_1 = step_1(
            params_1, opt_state_1, dense, cat, labels, mask)
        assert_sharded_matches_reference(params_s, loss_s, params_1, loss_1)


def test_broadcast_utils():
    import jax.numpy as jnp

    from flink_ml_tpu.data.broadcast import with_broadcast

    centroids = Table({"c": np.arange(6, dtype=np.float64).reshape(3, 2)})
    main = np.ones((4, 2), np.float32)

    def fn(X, ctx):
        c = ctx.get_broadcast_variable("centroids")["c"]
        assert len(c.sharding.device_set) == 8  # replicated over the mesh
        return jnp.asarray(X) @ jnp.asarray(c, jnp.float32).T

    out = with_broadcast(fn, {"centroids": centroids}, main)
    assert out.shape == (4, 3)

    def missing(X, ctx):
        ctx.get_broadcast_variable("nope")

    with pytest.raises(KeyError):
        with_broadcast(missing, {"centroids": centroids}, main)


def test_transform_validates_vocab_range():
    t = _ctr_table(n=64)
    model = WideDeep().set_vocab_sizes([10, 7]).set_max_iter(2).fit(t)
    bad = Table({"denseFeatures": np.zeros((1, 4), np.float32),
                 "catFeatures": np.array([[10, 0]], np.int32)})  # id 10 >= 10
    with pytest.raises(ValueError):
        model.transform(bad)
