"""OnlineLogisticRegression (streaming FTRL) tests — the unbounded-iteration
capability: epoch = one stream window, model versions emitted continuously,
warm start from initial model data."""

import numpy as np
import pytest

from flink_ml_tpu import Table
from flink_ml_tpu.models.classification.online_logisticregression import (
    OnlineLogisticRegression,
    OnlineLogisticRegressionModel,
)


def _stream(n_batches=30, batch=64, d=4, seed=0):
    rng = np.random.default_rng(seed)
    w_true = rng.normal(size=(d,))
    for _ in range(n_batches):
        X = rng.normal(size=(batch, d))
        y = (X @ w_true > 0).astype(np.int64)
        yield Table({"features": X, "label": y}), w_true


def test_defaults():
    olr = OnlineLogisticRegression()
    assert olr.get_alpha() == 0.1
    assert olr.get_beta() == 0.1
    # None = auto (r4); the online trainer resolves it to DEFAULT_GLOBAL_BATCH
    assert olr.get_global_batch_size() is None
    with pytest.raises(Exception):
        olr.set_alpha(0.0)


def test_streaming_fit_learns():
    batches = []
    w_true = None
    for t, w_true in _stream(n_batches=50):
        batches.append(t)
    model = (OnlineLogisticRegression().set_alpha(0.5)
             .fit(iter(batches)))
    assert isinstance(model, OnlineLogisticRegressionModel)
    assert model.model_version == 50

    rng = np.random.default_rng(99)
    X = rng.normal(size=(512, 4))
    y = (X @ w_true > 0).astype(np.int64)
    out = model.transform(Table({"features": X, "label": y}))[0]
    assert np.mean(out["prediction"] == y) > 0.9


def test_bounded_table_windowed_by_batch_size():
    rng = np.random.default_rng(1)
    X = rng.normal(size=(100, 3))
    y = (X[:, 0] > 0).astype(np.int64)
    t = Table({"features": X, "label": y})
    model = (OnlineLogisticRegression().set_global_batch_size(32)
             .set_alpha(0.5).fit(t))
    # 100 rows / 32 -> 4 windows (last ragged)
    assert model.model_version == 4


def test_version_history_and_interval():
    batches = [t for t, _ in _stream(n_batches=10)]
    model = (OnlineLogisticRegression()
             .set(OnlineLogisticRegression.MODEL_SAVE_INTERVAL, 3)
             .fit(iter(batches)))
    # versions at batches 3, 6, 9
    assert len(model.version_history) == 3
    # versions evolve
    assert not np.allclose(model.version_history[0].coefficients,
                           model.version_history[-1].coefficients)


def test_warm_start():
    batches = [t for t, _ in _stream(n_batches=2, seed=5)]
    w0 = np.array([1.0, -1.0, 0.5, 0.0])
    olr = OnlineLogisticRegression().set_initial_model_data(
        Table({"coefficients": w0[None, :]}))
    model = olr.fit(iter(batches[:1]))
    # after only one tiny batch, weights should still be near the warm start
    assert np.linalg.norm(model._state.coefficients - w0) < 1.0


def test_l1_sparsity():
    rng = np.random.default_rng(7)
    batches = []
    for _ in range(40):
        X = rng.normal(size=(64, 10)).astype(np.float64)
        y = (X[:, 0] > 0).astype(np.int64)
        batches.append(Table({"features": X, "label": y}))
    model = (OnlineLogisticRegression().set_reg(0.2).set_elastic_net(1.0)
             .set_alpha(0.5).fit(iter(batches)))
    coef = model._state.coefficients
    assert np.sum(np.abs(coef[1:]) < 1e-8) >= 5
    assert abs(coef[0]) > 0.1


def test_empty_stream_rejected():
    with pytest.raises(ValueError):
        OnlineLogisticRegression().fit(iter([]))


def test_save_load(tmp_path):
    batches = [t for t, _ in _stream(n_batches=5)]
    model = OnlineLogisticRegression().fit(iter(batches))
    path = str(tmp_path / "olr")
    model.save(path)
    loaded = OnlineLogisticRegressionModel.load(path)
    X = np.random.default_rng(0).normal(size=(16, 4))
    t = Table({"features": X})
    np.testing.assert_array_equal(loaded.transform(t)[0]["prediction"],
                                  model.transform(t)[0]["prediction"])


class TestCheckpointedStreamingFit:
    """fit(checkpoint=..., resume=...) + WindowLog: the estimator-level
    exactly-once story for live feeds (VERDICT r2 missing #1)."""

    def _windows(self, lo, hi, rng_seed=0):
        rng = np.random.default_rng(rng_seed)
        out = []
        for i in range(lo, hi):
            X = rng.normal(size=(32, 4)).astype(np.float64)
            y = (X[:, 0] > 0).astype(np.float64)
            out.append(Table({"features": X, "label": y}))
        return out

    def test_kill_and_resume_matches_uninterrupted(self, tmp_path):
        from flink_ml_tpu.data.wal import WindowLog
        from flink_ml_tpu.iteration.checkpoint import CheckpointConfig

        windows = self._windows(0, 10)

        def est():
            return (OnlineLogisticRegression().set_num_features(4)
                    .set_global_batch_size(32))

        oracle = est().fit(iter(windows))

        class Killed(RuntimeError):
            pass

        def killing_feed(wins, die_after):
            for i, w in enumerate(wins):
                if i == die_after:
                    raise Killed()
                yield w

        wal = str(tmp_path / "wal")
        ckpt = CheckpointConfig(str(tmp_path / "ckpt"), interval=3)
        with pytest.raises(Killed):
            est().fit(WindowLog(killing_feed(windows, 7), wal),
                      checkpoint=ckpt)
        # the live feed lost windows 0..6; the last cut was epoch 6
        # (interval=3: saves at 3 and 6), so the WAL replays window 6 and
        # 7..9 come live
        resumed = est().fit(WindowLog(iter(windows[7:]), wal),
                            checkpoint=ckpt, resume=True)
        np.testing.assert_allclose(resumed._state.coefficients,
                                   oracle._state.coefficients,
                                   rtol=1e-6, atol=1e-8)
        assert resumed.model_version == oracle.model_version == 10

    def test_checkpoint_requires_num_features(self, tmp_path):
        from flink_ml_tpu.data.stream import CountWindows
        from flink_ml_tpu.iteration.checkpoint import CheckpointConfig

        src = CountWindows(iter(self._windows(0, 2)), 32)  # has a cursor
        with pytest.raises(ValueError, match="set_num_features"):
            OnlineLogisticRegression().fit(
                src, checkpoint=CheckpointConfig(str(tmp_path / "c")))

    def test_bounded_table_checkpoint_resume(self, tmp_path):
        from flink_ml_tpu.iteration.checkpoint import CheckpointConfig

        rng = np.random.default_rng(3)
        X = rng.normal(size=(320, 4))
        y = (X[:, 0] > 0).astype(np.float64)
        t = Table({"features": X, "label": y})

        def est():
            return (OnlineLogisticRegression().set_num_features(4)
                    .set_global_batch_size(32))

        oracle = est().fit(t)
        ckpt = CheckpointConfig(str(tmp_path / "ckpt"), interval=4)
        full = est().fit(t, checkpoint=ckpt)
        np.testing.assert_allclose(full._state.coefficients,
                                   oracle._state.coefficients)
        # resume from the last periodic cut (epoch 8 of 10 — stream_end
        # breaks before a final save): windows 8..9 retrain via the
        # cursor's DETERMINISTIC replay, reproducing identical weights
        resumed = est().fit(t, checkpoint=ckpt, resume=True)
        np.testing.assert_allclose(resumed._state.coefficients,
                                   oracle._state.coefficients)

    def test_checkpoint_rejects_cursorless_source(self, tmp_path):
        from flink_ml_tpu.iteration.checkpoint import CheckpointConfig

        with pytest.raises(ValueError, match="cursor"):
            (OnlineLogisticRegression().set_num_features(4)
             .fit(iter(self._windows(0, 3)),
                  checkpoint=CheckpointConfig(str(tmp_path / "c"))))

    def test_dense_width_mismatch_errors_clearly(self):
        with pytest.raises(ValueError, match="numFeatures"):
            (OnlineLogisticRegression().set_num_features(10)
             .set_global_batch_size(32)
             .fit(iter(self._windows(0, 2))))
