"""Sparse/hashed feature path through the linear family (the Criteo shape:
hashed high-dim features scored against a dense weight, VERDICT r1 task 2).

Oracle strategy: on a small dimension the sparse trainers must agree with
the dense trainers run on the densified matrix — same seed, same batching,
same update — to float tolerance.  The high-dim tests then check the 2^20
path is expressible and learns.
"""

import numpy as np
import pytest

from flink_ml_tpu import Table
from flink_ml_tpu.linalg import SparseVector, stack_sparse_vectors
from flink_ml_tpu.models.classification import (
    LogisticRegression,
    LogisticRegressionModel,
    OnlineLogisticRegression,
)
from flink_ml_tpu.models.common.sgd import (
    SGDConfig,
    sgd_fit,
    sgd_fit_sparse,
)
from flink_ml_tpu.models.common.losses import LOSSES
from flink_ml_tpu.models.feature import FeatureHasher


def _sparse_problem(rng, n=256, d=32, nnz=4):
    """Random fixed-nnz rows + separable labels; returns both forms."""
    idx = np.stack([rng.choice(d, size=nnz, replace=False)
                    for _ in range(n)]).astype(np.int32)
    vals = rng.normal(size=(n, nnz)).astype(np.float32)
    dense = np.zeros((n, d), np.float32)
    np.add.at(dense, (np.arange(n)[:, None], idx), vals)
    w_true = rng.normal(size=(d,))
    y = (dense @ w_true > 0).astype(np.float64)
    return idx, vals, dense, y


def test_stack_sparse_vectors_pads_and_derives_dim():
    vecs = [SparseVector(10, [1, 3], [1.0, 2.0]),
            SparseVector(10, [7], [5.0])]
    idx, vals, dim = stack_sparse_vectors(vecs)
    assert dim == 10
    assert idx.shape == (2, 2) and vals.shape == (2, 2)
    np.testing.assert_array_equal(idx[1], [7, 0])
    np.testing.assert_array_equal(vals[1], [5.0, 0.0])
    with pytest.raises(ValueError, match="nnz"):
        stack_sparse_vectors(vecs, nnz=1)


def test_sgd_fit_sparse_matches_dense_oracle(rng):
    idx, vals, dense, y = _sparse_problem(rng)
    cfg = SGDConfig(learning_rate=0.5, max_epochs=8, global_batch_size=64,
                    tol=0, seed=3)
    dense_state, dense_log = sgd_fit(LOSSES["logistic"], dense, y, None, cfg)
    sparse_state, sparse_log = sgd_fit_sparse(
        LOSSES["logistic"], idx, vals, y, None, dense.shape[1], cfg)
    np.testing.assert_allclose(sparse_state.coefficients,
                               dense_state.coefficients, atol=1e-5)
    np.testing.assert_allclose(sparse_state.intercept, dense_state.intercept,
                               atol=1e-5)
    np.testing.assert_allclose(sparse_log, dense_log, atol=1e-5)


def test_sgd_fit_sparse_regularized_matches_dense(rng):
    idx, vals, dense, y = _sparse_problem(rng)
    cfg = SGDConfig(learning_rate=0.3, max_epochs=5, global_batch_size=64,
                    reg=0.05, elastic_net=0.4, tol=0, seed=1)
    dense_state, _ = sgd_fit(LOSSES["logistic"], dense, y, None, cfg)
    sparse_state, _ = sgd_fit_sparse(
        LOSSES["logistic"], idx, vals, y, None, dense.shape[1], cfg)
    np.testing.assert_allclose(sparse_state.coefficients,
                               dense_state.coefficients, atol=1e-5)


def test_lr_fit_on_sparse_vector_column(rng):
    idx, vals, dense, y = _sparse_problem(rng, n=128, d=16, nnz=3)
    vecs = np.empty((128,), object)
    for i in range(128):
        vecs[i] = SparseVector(16, idx[i], vals[i])
    sparse_t = Table({"features": vecs, "label": y})
    dense_t = Table({"features": dense.astype(np.float64), "label": y})

    lr = lambda: (LogisticRegression().set_max_iter(6).set_learning_rate(0.5)
                  .set_tol(0))
    m_sparse = lr().fit(sparse_t)
    m_dense = lr().fit(dense_t)
    np.testing.assert_allclose(m_sparse._state.coefficients,
                               m_dense._state.coefficients, atol=1e-5)
    # inference accepts the sparse column too
    p_sparse = np.asarray(m_sparse.transform(sparse_t)[0]["prediction"])
    p_dense = np.asarray(m_dense.transform(dense_t)[0]["prediction"])
    np.testing.assert_array_equal(p_sparse, p_dense)


def test_lr_fit_on_hashed_pair_columns_2e20(rng):
    """The Criteo-shaped config: 2^20 hashed dims, fixed actives per row."""
    d = 1 << 20
    n, nnz = 512, 8
    idx = rng.integers(0, d, size=(n, nnz)).astype(np.int32)
    vals = np.ones((n, nnz), np.float32)
    # label depends on whether the row's first hashed slot is even
    y = (idx[:, 0] % 2 == 0).astype(np.float64)
    # make it learnable: even rows get a dedicated marker slot
    idx[y == 1, 0] = 2
    idx[y == 0, 0] = 3
    t = Table({"features_indices": idx, "features_values": vals, "label": y})

    lr = (LogisticRegression().set_max_iter(10).set_learning_rate(1.0)
          .set_tol(0).set_num_features(d).set_global_batch_size(128))
    model = lr.fit(t)
    assert model._state.coefficients.shape == (d,)
    pred = np.asarray(model.transform(t)[0]["prediction"])
    assert (pred == y).mean() > 0.95
    assert model._loss_log[-1] < model._loss_log[0]


def test_lr_requires_num_features_for_pair_columns(rng):
    t = Table({"features_indices": np.zeros((4, 2), np.int32),
               "features_values": np.ones((4, 2), np.float32),
               "label": np.asarray([0.0, 1.0, 0.0, 1.0])})
    with pytest.raises(ValueError, match="numFeatures"):
        LogisticRegression().fit(t)


def test_online_lr_sparse_matches_dense_ftrl(rng):
    idx, vals, dense, y = _sparse_problem(rng, n=200, d=24, nnz=5)
    sparse_t = Table({"features_indices": idx, "features_values": vals,
                      "label": y})
    dense_t = Table({"features": dense.astype(np.float64), "label": y})

    def online():
        return (OnlineLogisticRegression().set_global_batch_size(50)
                .set_alpha(0.5).set_beta(1.0))

    m_sparse = online().set_num_features(24).fit(sparse_t)
    m_dense = online().fit(dense_t)
    np.testing.assert_allclose(m_sparse._state.coefficients,
                               m_dense._state.coefficients, atol=1e-5)
    assert m_sparse.model_version == m_dense.model_version == 4


def test_online_lr_sparse_high_dim(rng):
    d = 1 << 20
    n, nnz = 300, 6
    idx = rng.integers(4, d, size=(n, nnz)).astype(np.int32)
    y = rng.integers(0, 2, size=n).astype(np.float64)
    idx[:, 0] = np.where(y == 1, 1, 2)  # marker slots
    vals = np.ones((n, nnz), np.float32)
    t = Table({"features_indices": idx, "features_values": vals, "label": y})
    model = (OnlineLogisticRegression().set_num_features(d)
             .set_global_batch_size(100).set_alpha(1.0).fit(t))
    w = model._state.coefficients
    assert w.shape == (d,)
    assert w[1] > 0 > w[2]  # marker weights separated
    pred = np.asarray(model.transform(t)[0]["prediction"])
    assert (pred == y).mean() > 0.95


def test_feature_hasher_sparse_output_matches_dense(rng):
    n = 64
    t = Table({
        "age": rng.normal(size=n),
        "city": rng.choice(["sf", "nyc", "la"], size=n),
        "device": rng.choice(["ios", "android"], size=n),
    })
    fh = (FeatureHasher().set_input_cols("age", "city", "device")
          .set_num_features(128).set_output_col("f"))
    dense = np.asarray(fh.transform(t)[0]["f"])
    sp = fh.set_sparse_output(True).transform(t)[0]
    idx = np.asarray(sp["f_indices"])
    vals = np.asarray(sp["f_values"])
    assert idx.shape == (n, 3) and vals.shape == (n, 3)
    rebuilt = np.zeros((n, 128))
    np.add.at(rebuilt, (np.arange(n)[:, None], idx), vals)
    np.testing.assert_allclose(rebuilt, dense, atol=1e-6)


def test_hasher_to_lr_pipeline_sparse(rng):
    """FeatureHasher(sparse) -> LogisticRegression end-to-end, the Criteo
    ingest composition."""
    n = 256
    city = rng.choice(["sf", "nyc", "la", "chi"], size=n)
    y = (city == "sf").astype(np.float64)
    t = Table({"city": city, "label": y})
    hashed = (FeatureHasher().set_input_cols("city").set_num_features(1 << 16)
              .set_output_col("features").set_sparse_output(True)
              .transform(t)[0])
    model = (LogisticRegression().set_num_features(1 << 16).set_max_iter(20)
             .set_learning_rate(2.0).set_tol(0).fit(hashed))
    pred = np.asarray(model.transform(hashed)[0]["prediction"])
    assert (pred == y).mean() > 0.98


def test_model_save_load_high_dim_roundtrip(tmp_path, rng):
    d = 1 << 18
    idx = rng.integers(0, d, size=(64, 4)).astype(np.int32)
    vals = np.ones((64, 4), np.float32)
    y = rng.integers(0, 2, size=64).astype(np.float64)
    t = Table({"features_indices": idx, "features_values": vals, "label": y})
    model = (LogisticRegression().set_num_features(d).set_max_iter(2)
             .fit(t))
    model.save(str(tmp_path / "m"))
    re = LogisticRegressionModel.load(str(tmp_path / "m"))
    np.testing.assert_allclose(re._state.coefficients,
                               model._state.coefficients)
    p1 = np.asarray(model.transform(t)[0]["prediction"])
    p2 = np.asarray(re.transform(t)[0]["prediction"])
    np.testing.assert_array_equal(p1, p2)


def test_out_of_range_indices_rejected(rng):
    from flink_ml_tpu.models.common.linear import check_sparse_indices

    with pytest.raises(ValueError, match="out of range"):
        check_sparse_indices(np.asarray([[0, 100]]), 100)
    check_sparse_indices(np.asarray([[0, 99]]), 100)  # in range: fine

    # through the estimator: hasher at 2^10 vs model at 2^8
    idx = rng.integers(0, 1 << 10, size=(32, 3)).astype(np.int32)
    idx[0, 0] = (1 << 10) - 1
    t = Table({"features_indices": idx,
               "features_values": np.ones((32, 3), np.float32),
               "label": rng.integers(0, 2, size=32).astype(np.float64)})
    with pytest.raises(ValueError, match="hash-space"):
        LogisticRegression().set_num_features(1 << 8).set_max_iter(1).fit(t)


def test_midtrain_checkpoint_resume_through_estimator(tmp_path, rng):
    """fit_outofcore exposes the full checkpoint surface (every-N-steps +
    resume) so an interrupted Criteo pass restarts without dropping to the
    sgd layer."""
    from flink_ml_tpu.data.datacache import DataCacheReader, DataCacheWriter
    from flink_ml_tpu.iteration.checkpoint import CheckpointConfig

    cache = str(tmp_path / "cache")
    w = DataCacheWriter(cache, segment_rows=256)
    X = rng.normal(size=(1024, 8)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    w.append({"features": X, "label": y})
    w.finish()

    est = (LogisticRegression().set_learning_rate(0.5).set_max_iter(3)
           .set_tol(0.0))
    ck = CheckpointConfig(str(tmp_path / "ck"))
    m1 = est.fit_outofcore(lambda: DataCacheReader(cache, batch_rows=128),
                           num_features=8, checkpoint=ck,
                           checkpoint_every_steps=2)
    # resume of a COMPLETED run returns the checkpointed answer unchanged
    m2 = est.fit_outofcore(lambda: DataCacheReader(cache, batch_rows=128),
                           num_features=8, checkpoint=ck,
                           checkpoint_every_steps=2, resume=True)
    assert np.all(np.isfinite(m2._state.coefficients))


# ---------------------------------------------------------------------------
# Blocked (128-lane) gather/scatter path + mixed dense/categorical trainer
# ---------------------------------------------------------------------------

def test_blocked_gather_scatter_bitwise_equals_elementwise(rng):
    """d % 128 == 0 switches to the row-blocked path; the arithmetic must
    be exactly the elementwise gather/scatter."""
    import jax.numpy as jnp

    from flink_ml_tpu.models.common import sgd as sgd_mod

    d = 512
    w = jnp.asarray(rng.normal(size=d), jnp.float32)
    idx = jnp.asarray(rng.integers(0, d, size=(64, 7)), jnp.int32)
    upd = jnp.asarray(rng.normal(size=64 * 7), jnp.float32)

    assert sgd_mod._use_blocked(d)
    np.testing.assert_array_equal(
        np.asarray(sgd_mod._blocked_gather(w, idx)), np.asarray(w[idx]))
    np.testing.assert_array_equal(
        np.asarray(sgd_mod._blocked_scatter_add(w, idx, upd)),
        np.asarray(w.at[idx.reshape(-1)].add(upd)))
    assert not sgd_mod._use_blocked(500)


def test_sgd_fit_sparse_blocked_dim_matches_dense_oracle(rng):
    """Same oracle as above but at d=256 so the blocked path is the one
    exercised."""
    idx, vals, dense, y = _sparse_problem(rng, n=192, d=256, nnz=5)
    cfg = SGDConfig(learning_rate=0.5, max_epochs=6, global_batch_size=64,
                    tol=0, seed=2)
    dense_state, dense_log = sgd_fit(LOSSES["logistic"], dense, y, None, cfg)
    sparse_state, sparse_log = sgd_fit_sparse(
        LOSSES["logistic"], idx, vals, y, None, 256, cfg)
    np.testing.assert_allclose(sparse_state.coefficients,
                               dense_state.coefficients, atol=1e-5)
    np.testing.assert_allclose(sparse_log, dense_log, atol=1e-5)


def _mixed_problem(rng, n=256, n_dense=5, n_cat=3, d=256):
    dense = rng.normal(size=(n, n_dense)).astype(np.float32)
    cat = rng.integers(n_dense, d, size=(n, n_cat)).astype(np.int32)
    w_true = rng.normal(size=(d,))
    margin = dense @ w_true[:n_dense] + w_true[cat].sum(axis=1)
    y = (margin > 0).astype(np.float64)
    return dense, cat, y


def test_sgd_fit_mixed_matches_sparse_encoding(rng):
    """The mixed trainer must agree with sgd_fit_sparse on the equivalent
    (indices, values) encoding: dense slot j -> (j, x_j), cat -> (idx, 1)."""
    from flink_ml_tpu.models.common.sgd import sgd_fit_mixed

    n, n_dense, n_cat, d = 256, 5, 3, 256
    dense, cat, y = _mixed_problem(rng, n, n_dense, n_cat, d)
    idx = np.concatenate(
        [np.broadcast_to(np.arange(n_dense, dtype=np.int32), (n, n_dense)),
         cat], axis=1)
    vals = np.concatenate(
        [dense, np.ones((n, n_cat), np.float32)], axis=1)

    cfg = SGDConfig(learning_rate=0.4, max_epochs=6, global_batch_size=64,
                    tol=0, seed=5)
    sparse_state, sparse_log = sgd_fit_sparse(
        LOSSES["logistic"], idx, vals, y, None, d, cfg)
    mixed_state, mixed_log = sgd_fit_mixed(
        LOSSES["logistic"], dense, cat, y, None, d, cfg)
    np.testing.assert_allclose(mixed_state.coefficients,
                               sparse_state.coefficients, atol=1e-5)
    np.testing.assert_allclose(mixed_state.intercept, sparse_state.intercept,
                               atol=1e-5)
    np.testing.assert_allclose(mixed_log, sparse_log, atol=1e-5)
    # and it learned the problem
    assert mixed_log[-1] < mixed_log[0] * 0.7


def test_sgd_fit_mixed_regularized_matches_sparse(rng):
    from flink_ml_tpu.models.common.sgd import sgd_fit_mixed

    n, n_dense, n_cat, d = 192, 4, 2, 128
    dense, cat, y = _mixed_problem(rng, n, n_dense, n_cat, d)
    idx = np.concatenate(
        [np.broadcast_to(np.arange(n_dense, dtype=np.int32), (n, n_dense)),
         cat], axis=1)
    vals = np.concatenate(
        [dense, np.ones((n, n_cat), np.float32)], axis=1)

    cfg = SGDConfig(learning_rate=0.3, max_epochs=5, global_batch_size=64,
                    reg=0.05, elastic_net=0.3, tol=0, seed=7)
    sparse_state, _ = sgd_fit_sparse(
        LOSSES["logistic"], idx, vals, y, None, d, cfg)
    mixed_state, _ = sgd_fit_mixed(
        LOSSES["logistic"], dense, cat, y, None, d, cfg)
    np.testing.assert_allclose(mixed_state.coefficients,
                               sparse_state.coefficients, atol=1e-5)


def test_sgd_fit_mixed_rejects_bad_shapes(rng):
    from flink_ml_tpu.models.common.sgd import sgd_fit_mixed

    dense = rng.normal(size=(16, 8)).astype(np.float32)
    cat = rng.integers(0, 4, size=(16, 2)).astype(np.int32)
    with pytest.raises(ValueError, match="exceeds"):
        sgd_fit_mixed(LOSSES["logistic"], dense, cat,
                      np.zeros(16), None, 4, SGDConfig())


def test_lr_fit_on_mixed_columns_matches_pair_columns(rng):
    """The estimator surface: {col}_dense + {col}_indices dispatches to the
    mixed trainer and must agree with the equivalent pair-column fit."""
    n, n_dense, n_cat, d = 256, 4, 3, 256
    dense, cat, y = _mixed_problem(rng, n, n_dense, n_cat, d)
    idx = np.concatenate(
        [np.broadcast_to(np.arange(n_dense, dtype=np.int32), (n, n_dense)),
         cat], axis=1)
    vals = np.concatenate([dense, np.ones((n, n_cat), np.float32)], axis=1)

    def make_lr():
        return (LogisticRegression().set_num_features(d).set_max_iter(6)
                .set_learning_rate(0.4).set_tol(0).set_seed(5)
                .set_global_batch_size(64))

    mixed_t = Table({"features_dense": dense, "features_indices": cat,
                     "label": y})
    pair_t = Table({"features_indices": idx, "features_values": vals,
                    "label": y})
    m_mixed = make_lr().fit(mixed_t)
    m_pair = make_lr().fit(pair_t)
    np.testing.assert_allclose(m_mixed._state.coefficients,
                               m_pair._state.coefficients, atol=1e-5)

    # transform on mixed columns scores through the mixed margins
    # (better than chance after 6 epochs; exactness is the assert above)
    out = m_mixed.transform(mixed_t)[0]
    pred = np.asarray(out["prediction"])
    assert np.mean(pred == y) > 0.65

    # out-of-range categorical at transform time is rejected
    bad = Table({"features_dense": dense[:1],
                 "features_indices": np.full((1, n_cat), d, np.int32)})
    with pytest.raises(ValueError, match="out of range"):
        m_mixed.transform(bad)


def test_lr_mixed_requires_num_features(rng):
    dense, cat, y = _mixed_problem(rng, 64, 3, 2, 128)
    t = Table({"features_dense": dense, "features_indices": cat, "label": y})
    with pytest.raises(ValueError, match="numFeatures"):
        LogisticRegression().set_max_iter(2).fit(t)


def test_outofcore_mixed_matches_manual_updates(rng):
    """sgd_fit_outofcore with dense_key+indices_key must reproduce a manual
    _mixed_update loop over the SAME batch order — true parity, not just
    'loss went down' (a swapped dense/cat wiring would fail this)."""
    import jax
    import jax.numpy as jnp

    from flink_ml_tpu.models.common.sgd import (
        SGDConfig, _mixed_update, sgd_fit_outofcore)

    n, n_dense, n_cat, d = 256, 4, 3, 256
    dense, cat, y = _mixed_problem(rng, n, n_dense, n_cat, d)
    batch = 64
    cfg = SGDConfig(learning_rate=0.4, max_epochs=3, tol=0, seed=0,
                    global_batch_size=batch)

    def make_reader():
        def gen():
            for s in range(0, n, batch):
                yield {"features_dense": dense[s:s + batch],
                       "features_indices": cat[s:s + batch],
                       "label": y[s:s + batch]}
        return gen()

    ooc_state, ooc_log = sgd_fit_outofcore(
        LOSSES["logistic"], make_reader, num_features=d, config=cfg,
        indices_key="features_indices", dense_key="features_dense")
    assert ooc_log[-1] < ooc_log[0]

    # manual twin: identical update, identical batch order
    update = jax.jit(_mixed_update(LOSSES["logistic"], cfg))
    params = {"w": jnp.zeros((d,), jnp.float32),
              "b": jnp.zeros((), jnp.float32)}
    manual_log = []
    for _ in range(cfg.max_epochs):
        losses = []
        for s in range(0, n, batch):
            params, value = update(
                params, jnp.asarray(dense[s:s + batch]),
                jnp.asarray(cat[s:s + batch]),
                jnp.asarray(y[s:s + batch], jnp.float32),
                jnp.ones((batch,), jnp.float32))
            losses.append(float(value))
        manual_log.append(float(np.mean(losses)))

    np.testing.assert_allclose(ooc_state.coefficients,
                               np.asarray(params["w"], np.float64),
                               atol=1e-6)
    np.testing.assert_allclose(ooc_log, manual_log, atol=1e-5)


def test_resolve_features_rejects_ambiguous_schema(rng):
    from flink_ml_tpu.models.common.linear import resolve_features

    t = Table({"features_dense": np.zeros((4, 2), np.float32),
               "features_indices": np.zeros((4, 3), np.int32),
               "features_values": np.ones((4, 3), np.float32)})
    with pytest.raises(ValueError, match="ambiguous"):
        resolve_features(t, "features")


def test_online_lr_accepts_mixed_columns(rng):
    """The mixed convention re-encodes into FTRL's (indices, values) form
    instead of crashing."""
    n, nd, nc, d = 256, 3, 2, 128
    dense, cat, y = _mixed_problem(rng, n, nd, nc, d)
    t = Table({"features_dense": dense, "features_indices": cat, "label": y})
    model = (OnlineLogisticRegression().set_num_features(d)
             .set_global_batch_size(64).fit(t))
    out = model.transform(Table({"features_dense": dense,
                                 "features_indices": cat}))[0]
    assert np.isfinite(np.asarray(out["rawPrediction"])).all()


import jax as _jax


class TestShardedMixedWeight:
    """dp x model mesh: the weight shards over 'model' (VERDICT r2 task 7).
    The sharded fit must reproduce the single-device oracle allclose —
    a wrong psum/axis placement still converges, so only exact
    equivalence catches it (the WideDeep oracle stance)."""

    def _data(self, d):
        rng = np.random.default_rng(5)
        n, nd, nc = 256, 3, 5
        dense = rng.normal(size=(n, nd)).astype(np.float32)
        cat = rng.integers(0, d, size=(n, nc)).astype(np.int32)
        y = rng.integers(0, 2, size=n).astype(np.float64)
        cat[:, 0] = np.where(y == 1, 40, 41)
        return dense, cat, y

    @pytest.mark.parametrize("axes", [{"data": 2, "model": 4},
                                      {"data": 1, "model": 8},
                                      {"data": 8, "model": 1}])
    def test_matches_single_device_oracle(self, axes):
        from flink_ml_tpu.models.common.losses import logistic_loss
        from flink_ml_tpu.models.common.sgd import SGDConfig, sgd_fit_mixed
        from flink_ml_tpu.parallel.mesh import device_mesh

        d = 1 << 10
        dense, cat, y = self._data(d)
        for cfg in (SGDConfig(learning_rate=0.4, global_batch_size=64,
                              max_epochs=4, tol=0),
                    SGDConfig(learning_rate=0.4, global_batch_size=64,
                              max_epochs=4, tol=0, reg=0.02,
                              elastic_net=0.25)):
            oracle, oracle_log = sgd_fit_mixed(
                logistic_loss, dense, cat, y, None, d, cfg,
                mesh=device_mesh({"data": 1},
                                 devices=_jax.devices()[:1]))
            got, got_log = sgd_fit_mixed(
                logistic_loss, dense, cat, y, None, d, cfg,
                mesh=device_mesh(axes))
            np.testing.assert_allclose(got.coefficients,
                                       oracle.coefficients,
                                       rtol=1e-4, atol=1e-5)
            np.testing.assert_allclose(got.intercept, oracle.intercept,
                                       rtol=1e-4, atol=1e-6)
            np.testing.assert_allclose(got_log, oracle_log,
                                       rtol=1e-5, atol=1e-6)

    def test_rejects_indivisible_hash_space(self):
        from flink_ml_tpu.models.common.losses import logistic_loss
        from flink_ml_tpu.models.common.sgd import SGDConfig, sgd_fit_mixed
        from flink_ml_tpu.parallel.mesh import device_mesh

        dense, cat, y = self._data(1001)
        with pytest.raises(ValueError, match="divide the model axis"):
            sgd_fit_mixed(logistic_loss, dense, cat, y, None, 1001,
                          SGDConfig(max_epochs=1),
                          mesh=device_mesh({"data": 1, "model": 8}))


def test_auto_batch_sizing_plans_ell_at_bench_scale(rng, monkeypatch):
    """VERDICT r3 task 3: the DEFAULT product path must plan the same ELL
    kernel the bench times.  At bench shape (1M rows, 2^20 hashed dims)
    the old fixed batch=32 meant 32k steps of layout (~400 GB) and a
    silent XLA fallback; auto sizing must pick a batch whose layout stack
    fits the budget so plan_mixed_impl says "ell" on one TPU chip."""
    import jax

    from flink_ml_tpu.models.common import sgd as S
    from flink_ml_tpu.parallel.mesh import device_mesh

    n, d = 1_000_000, 1 << 20
    cfg = S.SGDConfig()  # defaults: auto batch
    batch = S.resolve_global_batch_size(cfg, n, d)
    steps = -(-n // batch)
    assert steps * d * 12 <= S._ELL_LAYOUT_BUDGET_BYTES
    assert batch <= S._AUTO_BATCH_CAP

    # the planner itself would say "ell" for that layout on 1 TPU device
    mesh = device_mesh({"data": 1}, devices=jax.devices()[:1])
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    assert S.plan_mixed_impl(d, mesh, steps) == "ell"
    # ... and the r2 default would NOT have (the weak-#2 divergence)
    assert S.plan_mixed_impl(d, mesh, -(-n // 32)) == "xla"

    # explicit user choices always pass through untouched
    assert S.resolve_global_batch_size(
        S.SGDConfig(global_batch_size=17), n, d) == 17
    # dense fits keep the classic default
    assert S.resolve_global_batch_size(cfg, n) == S.DEFAULT_GLOBAL_BATCH


def test_planned_impl_surfaces_on_product_models(rng):
    """The estimator surface must expose which impl fit planned, the way
    bench.py tags lr_impl (VERDICT r3 task 3)."""
    d = 1 << 10
    X = rng.normal(size=(64, 6)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float64)
    idx = rng.integers(6, d, size=(64, 3)).astype(np.int32)
    t = Table({"features_dense": X, "features_indices": idx, "label": y})
    model = (LogisticRegression().set_num_features(d).set_max_iter(2)
             .set_tol(0).fit(t))
    # CPU backend: the planner always says "xla" for the mixed layout
    assert model.planned_impl == "xla"

    dense_model = (LogisticRegression().set_max_iter(2).set_tol(0)
                   .fit(Table({"features": X, "label": y})))
    assert dense_model.planned_impl == "dense"
    # loaded models don't carry a planned impl
    assert model.loss_log  # sanity: fit actually trained
