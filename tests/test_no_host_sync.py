"""Tier-1 wiring for scripts/check_no_host_sync.py (ISSUE 6 satellite):
step / scan-body functions in models/ and parallel/ must stay free of
host synchronization — a ``block_until_ready`` / ``jax.device_get`` /
``np.asarray`` / ``.item()`` inside the step body fences the dispatch
stream and silently destroys the comm/compute overlap the bucketed
reduction schedule builds — and the checker itself must actually catch
each violation kind (a guard that can't fail guards nothing)."""

import importlib.util
import os

_spec = importlib.util.spec_from_file_location(
    "check_no_host_sync",
    os.path.join(os.path.dirname(__file__), os.pardir, "scripts",
                 "check_no_host_sync.py"))
chs = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(chs)


def test_step_bodies_are_host_sync_free():
    problems = []
    for path in chs._module_paths():
        problems += chs.check_file(path)
    assert problems == []


def test_checker_flags_every_sync_kind(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import numpy as np\n"
        "import jax\n"
        "def batch_step(params, xb):\n"
        "    loss = (xb @ params).sum()\n"
        "    host = np.asarray(loss)\n"
        "    v = loss.item()\n"
        "    jax.device_get(params)\n"
        "    loss.block_until_ready()\n"
        "    return params, host + v\n")
    problems = chs.check_file(str(bad))
    kinds = {k for k in ("np.asarray", ".item()", "jax.device_get",
                         "block_until_ready")
             if any(k in p for p in problems)}
    assert len(problems) == 4 and len(kinds) == 4


def test_checker_covers_scanned_bodies_by_reference(tmp_path):
    """A function passed to lax.scan is a step body whatever its name."""
    bad = tmp_path / "scanned.py"
    bad.write_text(
        "import jax\n"
        "def oddly_named(carry, xs):\n"
        "    v = carry.item()\n"
        "    return carry, v\n"
        "def run(xs, carry):\n"
        "    return jax.lax.scan(oddly_named, carry, xs)\n")
    problems = chs.check_file(str(bad))
    assert len(problems) == 1 and ".item()" in problems[0]


def test_checker_covers_nested_defs_inside_step(tmp_path):
    bad = tmp_path / "nested.py"
    bad.write_text(
        "import numpy as np\n"
        "def epoch_body(state, epoch, data):\n"
        "    def inner(x):\n"
        "        return np.asarray(x)\n"
        "    return inner(state)\n")
    problems = chs.check_file(str(bad))
    assert len(problems) == 1 and "np.asarray" in problems[0]


def test_checker_ignores_non_step_functions(tmp_path):
    good = tmp_path / "good.py"
    good.write_text(
        "import numpy as np\n"
        "def decode(batch):\n"
        "    return np.asarray(batch)\n"
        "def fetch(params):\n"
        "    return params.item()\n")
    assert chs.check_file(str(good)) == []


def test_checker_covers_online_package():
    """ISSUE 7 satellite: the continuous-learning package joined the
    scanned roots — its driver feeds the same chunked dispatch stream,
    so a host sync in a step-named helper there would fence training
    under the publishes.  Assert the root is registered AND that the
    walk actually visits its modules (a registered-but-empty root would
    silently guard nothing)."""
    assert "flink_ml_tpu/online" in chs.SCAN_ROOTS
    visited = [p for p in chs._module_paths()
               if os.sep + os.path.join("flink_ml_tpu", "online") + os.sep
               in p]
    names = {os.path.basename(p) for p in visited}
    assert {"driver.py", "publish.py", "delta.py"} <= names
    for path in visited:
        assert chs.check_file(path) == []


def test_checker_covers_iteration_package():
    """ISSUE 9 satellite: the iteration runtime joined the scanned roots
    — the workset while_loop driver's whole value is zero host
    round-trips per round, so a host sync hiding in its scan/while step
    bodies would re-serialize every epoch.  Assert the root is
    registered AND that the walk actually visits its modules (a
    registered-but-empty root would silently guard nothing)."""
    assert "flink_ml_tpu/iteration" in chs.SCAN_ROOTS
    visited = [p for p in chs._module_paths()
               if os.sep + os.path.join("flink_ml_tpu", "iteration") + os.sep
               in p]
    names = {os.path.basename(p) for p in visited}
    assert {"core.py", "body.py", "checkpoint.py"} <= names
    for path in visited:
        assert chs.check_file(path) == []


def test_checker_covers_obs_package():
    """ISSUE 13 satellite: the observability package joined the scanned
    roots — the StepProbe's whole contract is zero host sync inside
    step fns (its record/record_at ride scan/while carries on every
    training hot path), so a device_get sneaking into a step-shaped
    helper there would fence every adopter's dispatch stream at once.
    Assert the root is registered AND that the walk actually visits its
    modules (a registered-but-empty root would silently guard
    nothing)."""
    assert "flink_ml_tpu/obs" in chs.SCAN_ROOTS
    visited = [p for p in chs._module_paths()
               if os.sep + os.path.join("flink_ml_tpu", "obs") + os.sep
               in p]
    names = {os.path.basename(p) for p in visited}
    assert {"probe.py", "trace.py", "tree.py"} <= names
    for path in visited:
        assert chs.check_file(path) == []


def test_checker_covers_serving_package():
    """ISSUE 14 satellite: the serving package joined the scanned roots
    — the multi-tenant scheduler's ONE serve loop multiplexes every
    tenant, so a host sync in a step-shaped helper on its dispatch path
    would stall all tenants' traffic at once (not one endpoint's), and
    the embedding cache's pool set/gather must stay async dispatches.
    Assert the root is registered AND that the walk actually visits its
    modules (a registered-but-empty root would silently guard
    nothing)."""
    assert "flink_ml_tpu/serving" in chs.SCAN_ROOTS
    visited = [p for p in chs._module_paths()
               if os.sep + os.path.join("flink_ml_tpu", "serving") + os.sep
               in p]
    names = {os.path.basename(p) for p in visited}
    assert {"scheduler.py", "embcache.py", "batcher.py", "endpoint.py",
            "executor.py", "registry.py", "metrics.py"} <= names
    for path in visited:
        assert chs.check_file(path) == []


def test_checker_covers_ops_package():
    """ISSUE 10 satellite: the ops/ kernel modules joined the scanned
    roots — the kernel registry routes every training hot path through
    them, so a host fetch in a kernel wrapper would fence EVERY
    consumer's dispatch stream at once.  Assert the root is registered
    AND that the walk actually visits its modules."""
    assert "flink_ml_tpu/ops" in chs.SCAN_ROOTS
    visited = [p for p in chs._module_paths()
               if os.sep + os.path.join("flink_ml_tpu", "ops") + os.sep
               in p]
    names = {os.path.basename(p) for p in visited}
    assert {"ell_scatter.py", "kmeans_pallas.py", "emb_grad.py",
            "emb_grad_pallas.py"} <= names
    for path in visited:
        assert chs.check_file(path) == []


def test_checker_covers_elastic_module():
    """ISSUE 15 satellite: the elastic membership runtime lives in
    ``flink_ml_tpu/parallel`` — already a scanned root — but a root
    listing is only a guard if the walk actually VISITS the new module.
    The coordinator's ``poll`` runs once per chunk boundary on the
    training hot path: a host sync in a step-shaped helper there would
    fence every elastic fit's dispatch stream at each boundary."""
    assert "flink_ml_tpu/parallel" in chs.SCAN_ROOTS
    visited = [p for p in chs._module_paths()
               if os.sep + os.path.join("flink_ml_tpu", "parallel") + os.sep
               in p]
    names = {os.path.basename(p) for p in visited}
    assert {"elastic.py", "grad_reduce.py", "mesh.py"} <= names
    for path in visited:
        assert chs.check_file(path) == []
