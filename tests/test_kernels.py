"""Unified kernel registry (``flink_ml_tpu/kernels/``, ISSUE 10).

What these tests pin down:

- registry mechanics: priority/availability/supports selection, forced
  backends (bypass availability, never supports), loud failures;
- dispatch accounting: the compile/cache-hit/latency gauges track the
  shared jit's cache keying, and serving endpoints re-export them;
- THE cross-consumer guarantee: one registry entry per (op, schema,
  backend) backs pipelines, serving, AND training — a serving warm-up
  leaves ZERO new XLA lowerings for the fused pipeline plan, the
  model's own transform, and a CV-style re-score on the same (op,
  schema, bucket), lowering-counter-asserted; the training step
  builders resolve the very same entries (fn-identity-asserted);
- the cross-backend parity matrix: every multi-backend op's alternate
  implementations agree with the XLA lowering (bit-exact where the
  kernel contract promises it), with a COVERAGE gate so registering a
  new backend without a parity harness fails this file.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from flink_ml_tpu.data.table import Table
from flink_ml_tpu.kernels import registry as kreg
from flink_ml_tpu.kernels.registry import (
    KernelEntry,
    dispatch,
    kernel_stats,
    lookup,
    register_kernel,
)


# ---------------------------------------------------------------------------
# registry mechanics
# ---------------------------------------------------------------------------

def _with_temp_op(entries):
    """Context: register throwaway entries under a test-only op name and
    drop them afterwards."""
    import contextlib

    @contextlib.contextmanager
    def cm():
        op = "_test_op_"
        for e in entries:
            register_kernel(op, **e)
        try:
            yield op
        finally:
            kreg._REGISTRY.pop(op, None)
    return cm()


def test_lookup_picks_priority_available_supported():
    with _with_temp_op([
        dict(backend="slow", fn=lambda: "slow", priority=0),
        dict(backend="fast", fn=lambda: "fast", priority=10),
        dict(backend="faster-elsewhere", fn=lambda: "x", priority=20,
             available=lambda: False),
        dict(backend="faster-elsewhen", fn=lambda: "y", priority=30,
             supports=lambda sig: False),
    ]) as op:
        assert lookup(op).backend == "fast"
        # forced backend bypasses availability...
        assert lookup(op, backend="faster-elsewhere").backend == \
            "faster-elsewhere"
        # ...but a provided sig still gates the shape contract
        with pytest.raises(ValueError, match="does not support"):
            lookup(op, sig=("some-shape",), backend="faster-elsewhen")
        # ...and with no sig the caller owns the choice entirely
        assert lookup(op, backend="faster-elsewhen").backend == \
            "faster-elsewhen"


def test_lookup_failures_are_loud():
    with pytest.raises(KeyError, match="unknown kernel op"):
        lookup("_no_such_op_")
    with _with_temp_op([
        dict(backend="narrow", fn=lambda: 0,
             supports=lambda sig: sig == ("ok",)),
    ]) as op:
        with pytest.raises(KeyError, match="no backend"):
            lookup(op, backend="missing")
        with pytest.raises(ValueError, match="no available backend"):
            lookup(op, sig=("nope",))
        assert lookup(op, sig=("ok",)).backend == "narrow"


def test_register_replaces_same_backend():
    with _with_temp_op([dict(backend="xla", fn=lambda: 1)]) as op:
        register_kernel(op, "xla", lambda: 2)
        assert len(kreg._REGISTRY[op]) == 1
        assert lookup(op, backend="xla").fn() == 2


def test_catalog_registers_every_documented_op():
    ops = kreg.ops()
    for op in ("ell_margin", "ell_scatter_apply", "gbt_level_histograms",
               "kmeans_assign", "kmeans_update_stats",
               "kmeans_workset_update", "linear_margins", "retrieve",
               "routed_table_grad", "widedeep_scores"):
        assert op in ops, f"catalog lost op {op}"
    # every op has the automatic non-TPU fallback registered
    for op in ops:
        if op.startswith("_test_"):
            continue
        assert any(e.is_available() for e in kreg._REGISTRY[op].values()), \
            f"op {op} has no available backend on this host"


# ---------------------------------------------------------------------------
# dispatch accounting
# ---------------------------------------------------------------------------

def _margin_plan(n=16, d=4, seed=0, fcol="f"):
    from flink_ml_tpu.models.common.linear import _linear_chain_kernel

    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(np.float32)
    plan = ((_linear_chain_kernel, (fcol, "m")),)
    params = ({"w": rng.normal(size=(d,)).astype(np.float32),
               "b": np.float32(0.5)},)
    return plan, params, {fcol: X}


def test_dispatch_counts_compiles_and_cache_hits():
    plan, params, cols = _margin_plan(fcol="_acct_col_a")
    before = kernel_stats.snapshot()
    out1 = dispatch(plan, params, cols, op="_acct_op")
    mid = kernel_stats.snapshot()
    assert mid["compiles"] == before["compiles"] + 1
    out2 = dispatch(plan, params, cols, op="_acct_op")
    after = kernel_stats.snapshot()
    assert after["compiles"] == mid["compiles"]          # cache hit
    assert after["cache_hits"] == mid["cache_hits"] + 1
    assert after["per_op"]["_acct_op"]["dispatches"] >= 2
    assert after["dispatch_latency_ms"] > 0.0
    np.testing.assert_array_equal(np.asarray(out1["m"]),
                                  np.asarray(out2["m"]))
    # a different shape on the same plan is a NEW compile
    plan2, params2, cols2 = _margin_plan(n=32, fcol="_acct_col_a")
    dispatch(plan2, params2, cols2, op="_acct_op")
    assert kernel_stats.snapshot()["compiles"] == after["compiles"] + 1


def test_dispatch_accounting_tracks_lowering_counter():
    """The gauge's compile/hit split mirrors the REAL jit cache: a fresh
    (plan, shapes) key lowers once, repeats lower zero times."""
    from jax._src import test_util as jtu

    plan, params, cols = _margin_plan(fcol="_lower_col_b")
    with jtu.count_jit_and_pmap_lowerings() as count:
        dispatch(plan, params, cols)
    assert count[0] == 1
    with jtu.count_jit_and_pmap_lowerings() as count:
        dispatch(plan, params, cols)
    assert count[0] == 0


def test_serving_metrics_republish_kernel_gauges():
    from flink_ml_tpu.serving.metrics import ServingMetrics

    m = ServingMetrics()
    plan, params, cols = _margin_plan(fcol="_gauge_col_c")
    dispatch(plan, params, cols)
    m.publish()
    snap = m.snapshot()
    assert snap["kernels.dispatches"] >= 1
    assert snap["kernels.compiles"] >= 1
    assert "kernels.dispatch_latency_ms" in snap


# ---------------------------------------------------------------------------
# THE cross-consumer compile-sharing guarantee
# ---------------------------------------------------------------------------

def test_one_executable_backs_serving_pipeline_and_transform():
    """Zero-new-lowerings: after a serving warm-up of the LR margins op,
    (a) the model's own transform (the training stack's predict entry —
    what fit-time evaluation and CV fold scoring call), (b) a fused
    PipelineModel plan, and (c) a hot-swapped same-shape generation all
    run on the SAME compiled executable per (op, schema, bucket)."""
    from jax._src import test_util as jtu

    from flink_ml_tpu.api.pipeline import PipelineModel
    from flink_ml_tpu.models.classification.logisticregression import (
        LogisticRegression,
    )
    from flink_ml_tpu.serving.executor import make_servable

    rng = np.random.default_rng(7)
    X = rng.normal(size=(48, 6)).astype(np.float64)
    y = (X[:, 0] > 0).astype(np.float64)
    train = Table({"features": X, "label": y})
    model = LogisticRegression().set_max_iter(2).fit(train)
    feats = Table({"features": X})

    servable = make_servable(model, Table({"features": X[:4]}),
                             max_batch_rows=64)
    servable.warm_up()        # buckets 8..64 compile HERE

    with jtu.count_jit_and_pmap_lowerings() as count:
        # (a) serving steady state
        served = servable.predict(Table({"features": X[:5]}))
        # (b) the training stack's own predict entry
        offline = model.transform(feats)[0]
        # (c) the fused pipeline plan (singleton terminal segment)
        pipe = PipelineModel([model])
        fused = pipe.transform(feats)[0]
        # (d) a same-shape new generation (CV fold / delta publish)
        import copy

        gen2 = copy.deepcopy(model)
        gen2._state.coefficients = gen2._state.coefficients * 1.5
        servable.rebind(gen2).predict(Table({"features": X[:5]}))
    assert count[0] == 0, (
        f"{count[0]} new XLA lowerings after warm-up — pipelines, "
        "serving, and the predict entry no longer share one executable")
    np.testing.assert_array_equal(offline["prediction"],
                                  fused["prediction"])
    np.testing.assert_array_equal(served["prediction"],
                                  offline["prediction"][:5])


def test_training_builders_resolve_the_same_registry_entries():
    """The training-side consumers go through the SAME registry entries
    the parity matrix exercises — fn identity, not a parallel table."""
    from flink_ml_tpu.models.common import gbt
    from flink_ml_tpu.ops import ell_scatter, emb_grad

    assert lookup("ell_margin", sig=(16,), backend="xla").fn \
        is ell_scatter.ell_margin_xla_entry
    assert lookup("ell_scatter_apply", sig=(16,), backend="xla").fn \
        is ell_scatter.ell_scatter_apply_xla_entry
    assert lookup("gbt_level_histograms", backend="xla").fn \
        is gbt._level_histograms_segsum
    assert lookup("gbt_level_histograms", backend="mxu").fn \
        is gbt._level_histograms_mxu
    assert lookup("routed_table_grad", backend="xla").fn \
        is emb_grad.routed_apply_xla
    # off TPU the automatic picks are the XLA lowerings (the fallback
    # rule), and GBT's "auto" resolves through the same lookup
    if jax.default_backend() != "tpu":
        assert lookup("ell_margin", sig=(16,)).backend == "xla"
        assert gbt.resolve_hist_impl("auto") == "segsum"


# ---------------------------------------------------------------------------
# cross-backend parity matrix
# ---------------------------------------------------------------------------

def _parity_ell_margin(backends):
    from flink_ml_tpu.ops.ell_scatter import ell_layout

    rng = np.random.default_rng(3)
    d, batch, nnz = 128 * 8, 64, 4
    cat = rng.integers(0, d, size=(1, batch, nnz)).astype(np.int32)
    lay = ell_layout(cat, d)
    w = jnp.asarray(rng.normal(size=(d,)).astype(np.float32))
    m_len = 256
    outs = {}
    for b in backends:
        entry = lookup("ell_margin", sig=(int(lay.src.shape[1]),),
                       backend=b)
        kw = {} if b == "xla" else {"interpret": True,
                                    "precision": "highest"}
        outs[b] = np.asarray(entry.fn(
            w, lay.src[0], lay.pos[0], lay.mask[0], m_len=m_len, **kw))
    ref = outs.pop("xla")
    for b, got in outs.items():
        np.testing.assert_allclose(got[:batch], ref[:batch], atol=1e-5,
                                   err_msg=f"ell_margin[{b}] vs xla")


def _parity_ell_scatter_apply(backends):
    from flink_ml_tpu.ops.ell_scatter import ell_layout

    rng = np.random.default_rng(4)
    d, batch, nnz = 128 * 8, 64, 4
    cat = rng.integers(0, d, size=(1, batch, nnz)).astype(np.int32)
    lay = ell_layout(cat, d)
    w = jnp.asarray(rng.normal(size=(d,)).astype(np.float32))
    r_ext = jnp.asarray(
        np.concatenate([rng.normal(size=batch),
                        np.zeros(256 - batch)]).astype(np.float32))
    outs = {}
    for b in backends:
        entry = lookup("ell_scatter_apply", sig=(int(lay.src.shape[1]),),
                       backend=b)
        kw = {} if b == "xla" else {"interpret": True,
                                    "precision": "highest"}
        outs[b] = np.asarray(entry.fn(
            w, r_ext, lay.src[0], lay.pos[0], lay.mask[0], lr=0.3, **kw))
    ref = outs.pop("xla")
    for b, got in outs.items():
        np.testing.assert_allclose(got, ref, atol=1e-5,
                                   err_msg=f"ell_scatter_apply[{b}] vs xla")


def _parity_gbt_hist(backends):
    rng = np.random.default_rng(5)
    n, d, bins, nodes = 512, 6, 16, 4
    binned = jnp.asarray(rng.integers(0, bins, size=(n, d)), jnp.int32)
    ids = jnp.asarray(rng.integers(-1, nodes, size=n), jnp.int32)
    g = jnp.asarray(rng.normal(size=n).astype(np.float32))
    h = jnp.asarray((rng.random(n) + 0.1).astype(np.float32))
    outs = {b: lookup("gbt_level_histograms", backend=b).fn(
        binned, ids, g, h, nodes, d, bins) for b in backends}
    gr, hr = outs.pop("xla")
    for b, (gg, hh) in outs.items():
        np.testing.assert_allclose(np.asarray(gg), np.asarray(gr),
                                   rtol=1e-4, atol=1e-5, err_msg=b)
        np.testing.assert_allclose(np.asarray(hh), np.asarray(hr),
                                   rtol=1e-4, atol=1e-5, err_msg=b)


def _parity_kmeans_update_stats(backends):
    from flink_ml_tpu.distance import DistanceMeasure
    from flink_ml_tpu.ops.kmeans_pallas import pad_correction

    rng = np.random.default_rng(6)
    n, d, k = 256, 8, 4
    pts = rng.normal(size=(n, d)).astype(np.float32)
    pts[-13:] = 0.0                       # maskless zero-pad contract
    mask = np.ones(n, np.float32)
    mask[-13:] = 0.0
    cents = pts[:k].copy()
    measure = DistanceMeasure.get_instance("euclidean")
    outs = {}
    for b in backends:
        entry = lookup("kmeans_update_stats", backend=b)
        if b == "xla":
            sums, counts = entry.fn(measure, k, jnp.asarray(pts),
                                    jnp.asarray(mask), jnp.asarray(cents))
        else:
            sums, counts = entry.fn(jnp.asarray(pts), jnp.asarray(cents),
                                    block_n=128, tie_policy="first",
                                    interpret=True)
            counts = pad_correction(counts, jnp.asarray(cents), 13,
                                    tie_policy="first")
        outs[b] = (np.asarray(sums), np.asarray(counts))
    sr, cr = outs.pop("xla")
    for b, (ss, cc) in outs.items():
        np.testing.assert_allclose(ss, sr, atol=1e-4, err_msg=b)
        np.testing.assert_allclose(cc, cr, atol=1e-5, err_msg=b)


def _parity_kmeans_workset_update(backends):
    from flink_ml_tpu.distance import DistanceMeasure

    rng = np.random.default_rng(7)
    n, d, k = 256, 8, 4
    pts = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
    pm = np.ones(n, np.float32)
    pm[-9:] = 0.0
    cents = pts[:k]
    prev = jnp.asarray(rng.integers(0, k, n).astype(np.int32))
    act = jnp.asarray((rng.random(n) < 0.5).astype(np.float32))
    measure = DistanceMeasure.get_instance("euclidean")
    outs = {}
    for b in backends:
        entry = lookup("kmeans_workset_update", backend=b)
        if b == "xla":
            outs[b] = entry.fn(measure, k, pts, cents, prev, act,
                               jnp.asarray(pm))
        else:
            outs[b] = entry.fn(pts, cents, prev, act, jnp.asarray(pm),
                               block_n=128, interpret=True)
    a_r, db_r, ds_r, s_r, c_r = [np.asarray(x) for x in outs.pop("xla")]
    for b, got in outs.items():
        a, db, ds, s, c = [np.asarray(x) for x in got]
        # per-row outputs are expression-identical -> bitwise
        np.testing.assert_array_equal(a, a_r, err_msg=b)
        np.testing.assert_array_equal(db, db_r, err_msg=b)
        np.testing.assert_array_equal(ds, ds_r, err_msg=b)
        # stats accumulate tile-sequentially -> f32-order equivalent
        np.testing.assert_allclose(s, s_r, rtol=1e-5, atol=1e-5,
                                   err_msg=b)
        np.testing.assert_allclose(c, c_r, atol=1e-5, err_msg=b)


def _parity_routed_table_grad(backends):
    from flink_ml_tpu.ops.emb_grad import emb_grad_route

    rng = np.random.default_rng(8)
    batch, fields, vocab, E = 64, 4, 40, 3
    cat = rng.integers(0, vocab, size=(1, batch, fields))
    cat[0, :40, 0] = 5                    # heavy run -> fold_passes > 0
    route = emb_grad_route(cat, vocab)
    g = jnp.asarray(rng.normal(size=(batch * fields, E)).astype(np.float32))
    outs = {}
    for b in backends:
        entry = lookup("routed_table_grad", sig=route.kernel_sig(),
                       backend=b)
        kw = {} if b == "xla" else {"interpret": True}
        outs[b] = np.asarray(entry.fn(route, g, *route.step_slice(0), **kw))
    ref = outs.pop("xla")
    for b, got in outs.items():
        # the fused fold's shift-add tree is element-identical: bitwise
        np.testing.assert_array_equal(got, ref, err_msg=b)


# -- accuracy-envelope harnesses (int8 backends, ISSUE 18) ------------------
# Int8 entries are weight-only quantized: bitwise equality with f32 is
# NOT the contract — rank-order/decision agreement within the envelope
# (>= 99% on these fixtures) is.  Each harness quantizes the f32 params
# through the publish-time recipe and forces both backends explicitly
# (the int8 entry's availability gate refuses auto-pick by design).

ENVELOPE = 0.99


def _rank_corr(a, b):
    ra = np.argsort(np.argsort(a)).astype(np.float64)
    rb = np.argsort(np.argsort(b)).astype(np.float64)
    return float(np.corrcoef(ra, rb)[0, 1])


def _parity_linear_margins(backends):
    from flink_ml_tpu.kernels.quantize import quantize_stage_params

    rng = np.random.default_rng(9)
    X = rng.normal(size=(512, 16)).astype(np.float32)
    params = {"w": rng.normal(size=(16,)).astype(np.float32),
              "b": np.float32(0.1)}
    outs = {}
    for b in backends:
        p = quantize_stage_params("linear_margins", params) \
            if b == "int8" else params
        outs[b] = np.asarray(
            lookup("linear_margins", backend=b).fn(("f", "m"), p,
                                                   {"f": X})["m"])
    ref = outs.pop("xla")
    for b, got in outs.items():
        agree = float(np.mean((got > 0) == (ref > 0)))
        assert agree >= ENVELOPE, \
            f"linear_margins[{b}] decision agreement {agree} vs xla"
        corr = _rank_corr(got, ref)
        assert corr >= ENVELOPE, \
            f"linear_margins[{b}] margin rank correlation {corr}"


def _parity_kmeans_assign(backends):
    from flink_ml_tpu.distance import DistanceMeasure
    from flink_ml_tpu.kernels.quantize import quantize_stage_params

    rng = np.random.default_rng(10)
    pts = rng.normal(size=(512, 8)).astype(np.float32)
    params = {"centroids": rng.normal(size=(7, 8)).astype(np.float32)}
    measure = DistanceMeasure.get_instance("euclidean")
    static = ("f", "a", measure)
    outs = {}
    for b in backends:
        p = quantize_stage_params("kmeans_assign", params) \
            if b == "int8" else params
        outs[b] = np.asarray(
            lookup("kmeans_assign", backend=b).fn(static, p,
                                                  {"f": pts})["a"])
    ref = outs.pop("xla")
    for b, got in outs.items():
        agree = float(np.mean(got == ref))
        assert agree >= ENVELOPE, \
            f"kmeans_assign[{b}] assignment agreement {agree} vs xla"


def _parity_widedeep_scores(backends):
    from flink_ml_tpu.kernels.quantize import quantize_stage_params
    from flink_ml_tpu.models.recommendation.widedeep import (
        _field_offsets,
        init_params,
    )

    rng = np.random.default_rng(11)
    vocab = (17, 23)
    net = init_params(rng, 4, vocab, 8, (16,))
    net["wide_cat"] = (rng.normal(size=net["wide_cat"].shape) * 0.1
                       ).astype(np.float32)
    net["wide_dense"] = (rng.normal(size=net["wide_dense"].shape) * 0.1
                         ).astype(np.float32)
    params = {"net": net, "offsets": _field_offsets(vocab)}
    dense = rng.normal(size=(512, 4)).astype(np.float32)
    cat = np.stack([rng.integers(0, v, size=512) for v in vocab],
                   axis=1).astype(np.int32)
    cols = {"d": dense, "c": cat}
    outs = {}
    for b in backends:
        p = quantize_stage_params("widedeep_scores", params) \
            if b == "int8" else params
        outs[b] = np.asarray(
            lookup("widedeep_scores", backend=b).fn(("d", "c", "s"), p,
                                                    cols)["s"])
    ref = outs.pop("xla")
    for b, got in outs.items():
        agree = float(np.mean((got > 0.5) == (ref > 0.5)))
        assert agree >= ENVELOPE, \
            f"widedeep_scores[{b}] decision agreement {agree} vs xla"
        corr = _rank_corr(got, ref)
        assert corr >= ENVELOPE, \
            f"widedeep_scores[{b}] score rank correlation {corr}"


# -- retrieve harnesses (ISSUE 19) ------------------------------------------
# The fused scan+top-k stage promises BITWISE agreement between backends:
# both run under jit (eager XLA makes different fma-contraction choices
# than the plan jit does, so the harness compares like-for-like), and the
# shared pq_lut/decode helpers carry a runtime-1.0 rounding pin so
# fusion-cluster shape cannot reorder the float graph.  Parity alone is
# NOT enough for a nearest-neighbor kernel — two backends can agree
# bit-for-bit on a wrong answer — so every retrieve backend must ALSO
# clear two quality gates of its own: exact agreement with a float64
# brute-force oracle at nprobe == nlist, and the recall envelope
# (recall@10 >= 0.95 at the reference nprobe while provably scanning
# <= 25% of the corpus).  The coverage gate below makes a backend missing
# EITHER harness fail this file by construction.

import functools

RECALL_ENVELOPE = 0.95      # recall@10 floor at the reference nprobe
SCAN_BUDGET = 0.25          # ... while scanning at most this corpus slice


@functools.lru_cache(maxsize=None)
def _retrieve_fixture(kind):
    """(index, queries) fixtures per shape class, built once per run."""
    from flink_ml_tpu.retrieval import IVFIndex, PQConfig

    rng = np.random.default_rng(19)
    if kind == "flat-small":        # continuous data: full-probe oracle
        X = rng.normal(size=(600, 32)).astype(np.float32)
        idx = IVFIndex.build(X, nlist=8, k=10, nprobe=4, seed=1)
        q = rng.normal(size=(16, 32)).astype(np.float32)
    elif kind == "pq-small":
        X = rng.normal(size=(600, 32)).astype(np.float32)
        idx = IVFIndex.build(X, nlist=8, k=10, nprobe=4, seed=1,
                             pq=PQConfig(m=8, ksub=16))
        q = rng.normal(size=(16, 32)).astype(np.float32)
    elif kind == "clustered":       # separated modes: the recall op point
        centers = rng.normal(size=(64, 16)).astype(np.float32) * 10.0
        assign = rng.integers(0, 64, size=2048)
        X = (centers[assign]
             + rng.normal(size=(2048, 16)) * 0.5).astype(np.float32)
        idx = IVFIndex.build(X, nlist=64, k=10, nprobe=8, seed=2)
        pick = rng.choice(2048, size=32, replace=False)
        q = (X[pick] + rng.normal(size=(32, 16)) * 0.05).astype(np.float32)
    else:
        raise AssertionError(kind)
    return idx, q


def _retrieve_backend_run(index, queries, backend, *, nprobe=None):
    """Run ONE backend's retrieve stage the way production runs it: under
    jit (interpret mode for the TPU backend on CPU hosts)."""
    from flink_ml_tpu.retrieval.ivf import _DIST_STAGE, _NN_STAGE

    idx = index if nprobe is None else index.with_options(nprobe=nprobe)
    entry = lookup("retrieve", sig=idx.sig(), backend=backend)
    static = idx._static()
    params = {k: jnp.asarray(v) for k, v in idx.params.items()}
    cols = {idx.query_col: jnp.asarray(queries)}
    if backend == "pallas":
        out = entry.fn(static, params, cols, interpret=True)
    else:
        out = jax.jit(lambda p, c: entry.fn(static, p, c))(params, cols)
    return np.asarray(out[_NN_STAGE]), np.asarray(out[_DIST_STAGE])


def _parity_retrieve(backends):
    for kind in ("flat-small", "pq-small"):
        idx, q = _retrieve_fixture(kind)
        outs = {b: _retrieve_backend_run(idx, q, b) for b in backends}
        nn_ref, d_ref = outs.pop("xla")
        for b, (nn, d) in outs.items():
            np.testing.assert_array_equal(
                nn, nn_ref, err_msg=f"{kind}[{b}] neighbor ids")
            # the fused contract: candidate distances never re-round
            # differently per backend — BITWISE, not approx
            np.testing.assert_array_equal(
                d.view(np.uint32), d_ref.view(np.uint32),
                err_msg=f"{kind}[{b}] distance bits")


def _retrieve_oracle(backend):
    """Brute-force oracle: at nprobe == nlist the index scans everything,
    so the neighbor sets must EQUAL the float64 exact scan's (continuous
    data — ties have measure zero)."""
    from flink_ml_tpu.retrieval import exact_neighbors

    idx, q = _retrieve_fixture("flat-small")
    ids, X = idx.stored_vectors()
    expect = exact_neighbors(q, X, ids, idx.k)
    nn, dist = _retrieve_backend_run(idx, q, backend, nprobe=idx.nlist)
    np.testing.assert_array_equal(nn, expect, err_msg=f"oracle[{backend}]")
    assert np.all(np.diff(dist, axis=1) >= 0), "distances not ascending"


def _retrieve_recall(backend):
    """Recall envelope: recall@10 >= 0.95 at the index's reference nprobe
    while the probed lists hold <= 25% of the corpus (asserted from the
    real posting-list counts, not assumed)."""
    from flink_ml_tpu.retrieval import exact_neighbors, recall_at_k

    idx, q = _retrieve_fixture("clustered")
    frac = idx.scan_fraction(q)
    assert frac <= SCAN_BUDGET, f"scan fraction {frac} over budget"
    ids, X = idx.stored_vectors()
    expect = exact_neighbors(q, X, ids, idx.k)
    nn, _ = _retrieve_backend_run(idx, q, backend)
    rec = recall_at_k(nn, expect)
    assert rec >= RECALL_ENVELOPE, (
        f"recall[{backend}] {rec} at nprobe={idx.nprobe} "
        f"(scan fraction {frac})")


#: both quality gates, keyed for the parametrized matrix below
_RETRIEVE_QUALITY = {"oracle": _retrieve_oracle, "recall": _retrieve_recall}

#: every registered retrieve backend must be listed here — the harnesses
#: above run per backend, so listing IS coverage
_RETRIEVE_BACKENDS = ("pallas", "xla")


def test_every_retrieve_backend_has_quality_harnesses():
    """ISSUE 19 coverage gate: a retrieve backend registered without BOTH
    the brute-force-oracle harness and the recall-envelope harness fails
    by construction."""
    regd = set(kreg.backends("retrieve"))
    missing = regd - set(_RETRIEVE_BACKENDS)
    assert not missing, (
        f"retrieve backend(s) {sorted(missing)} registered without "
        "oracle+recall quality harnesses — add them to "
        "_RETRIEVE_BACKENDS and make both gates pass")
    stale = set(_RETRIEVE_BACKENDS) - regd
    assert not stale, f"_RETRIEVE_BACKENDS lists unregistered {sorted(stale)}"


@pytest.mark.parametrize("backend", _RETRIEVE_BACKENDS)
@pytest.mark.parametrize("gate", sorted(_RETRIEVE_QUALITY))
def test_retrieve_quality_gates(gate, backend):
    _RETRIEVE_QUALITY[gate](backend)


_PARITY = {
    "ell_margin": _parity_ell_margin,
    "ell_scatter_apply": _parity_ell_scatter_apply,
    "gbt_level_histograms": _parity_gbt_hist,
    "kmeans_assign": _parity_kmeans_assign,
    "kmeans_update_stats": _parity_kmeans_update_stats,
    "kmeans_workset_update": _parity_kmeans_workset_update,
    "linear_margins": _parity_linear_margins,
    "retrieve": _parity_retrieve,
    "routed_table_grad": _parity_routed_table_grad,
    "widedeep_scores": _parity_widedeep_scores,
}


def test_every_multi_backend_op_has_a_parity_harness():
    """Coverage gate: registering a second backend for an op WITHOUT
    adding its parity harness here fails loudly — an unverified kernel
    must not ship behind the registry's automatic selection."""
    for op in kreg.ops():
        if op.startswith("_"):
            continue
        if len(kreg.backends(op)) > 1:
            assert op in _PARITY, (
                f"op {op} grew a second backend with no parity harness")


@pytest.mark.parametrize("op", sorted(_PARITY))
def test_parity_matrix(op):
    backends = kreg.backends(op)
    if len(backends) < 2:
        pytest.skip(f"{op} has one backend")
    assert "xla" in backends, f"{op} lost its XLA fallback"
    _PARITY[op](list(backends))


# ---------------------------------------------------------------------------
# padding contract
# ---------------------------------------------------------------------------

def test_shared_block_padding_contract():
    from flink_ml_tpu.utils.padding import (
        pad_rows_to_block,
        require_block_rows,
    )

    arrs, n = pad_rows_to_block((np.ones((10, 3)), np.arange(10)), 8)
    assert n == 10 and arrs[0].shape[0] == 16 and arrs[1].shape[0] == 16
    assert np.all(arrs[0][10:] == 0.0) and np.all(arrs[1][10:] == 0)
    require_block_rows(16, 8, op="t")                  # divisible: fine
    with pytest.raises(ValueError, match="pad_rows_to_block"):
        require_block_rows(10, 8, op="t")


def test_kmeans_pallas_raises_shared_contract_error():
    from flink_ml_tpu.ops.kmeans_pallas import kmeans_update_stats

    pts = jnp.ones((100, 4), jnp.float32)
    cents = jnp.ones((2, 4), jnp.float32)
    with pytest.raises(ValueError, match="pad_rows_to_block"):
        kmeans_update_stats(pts, cents, block_n=64, interpret=True)


# ---------------------------------------------------------------------------
# registry-resolved training paths stay value-correct end to end
# ---------------------------------------------------------------------------

def test_forced_xla_ell_builder_matches_default_on_cpu():
    """On a non-TPU host the registry's automatic pick IS the XLA
    lowering, so the default-resolved builder and the forced-"xla"
    builder must be the same computation."""
    from flink_ml_tpu.models.common.losses import logistic_loss
    from flink_ml_tpu.models.common.sgd import SGDConfig, _mixed_update_ell
    from flink_ml_tpu.ops.ell_scatter import ell_layout

    if jax.default_backend() == "tpu":
        pytest.skip("CPU-resolution test")
    rng = np.random.default_rng(11)
    d, batch, nnz = 128 * 4, 32, 3
    cat = rng.integers(0, d, size=(1, batch, nnz)).astype(np.int32)
    lay = ell_layout(cat, d)
    dense = rng.normal(size=(batch, 2)).astype(np.float32)
    y = rng.integers(0, 2, size=batch).astype(np.float32)
    wb = np.ones(batch, np.float32)
    cfg = SGDConfig(learning_rate=0.3, tol=0)
    params = {"w": jnp.zeros(d, jnp.float32), "b": jnp.zeros((), jnp.float32)}
    args = (jnp.asarray(dense), lay.src[0], lay.pos[0], lay.mask[0],
            lay.ovf_idx[0], lay.ovf_src[0], lay.heavy_idx[0],
            lay.heavy_cnt[0], jnp.asarray(y), jnp.asarray(wb))
    auto, _ = _mixed_update_ell(logistic_loss, cfg)(params, *args)
    forced, _ = _mixed_update_ell(logistic_loss, cfg, backend="xla")(
        params, *args)
    np.testing.assert_array_equal(np.asarray(auto["w"]),
                                  np.asarray(forced["w"]))


def test_workset_fused_body_matches_xla_body_in_interpret():
    """The fused workset body (what a TPU fit plans) drives the SAME
    convergence as the XLA body: same rounds, same final centroids to
    f32 summation order, same exit — interpret mode standing in for the
    chip."""
    from flink_ml_tpu.distance import DistanceMeasure
    from flink_ml_tpu.iteration import IterationConfig, iterate
    from flink_ml_tpu.models.clustering.kmeans import (
        FitPlan,
        kmeans_workset_epoch_step,
    )

    rng = np.random.default_rng(12)
    n, d, k = 256, 6, 3
    pts = rng.normal(size=(n, d)).astype(np.float32)
    pts[:n // 3] += 4.0
    pts[n // 3: 2 * n // 3] -= 4.0
    mask = jnp.ones((n,), jnp.float32)
    init = jnp.asarray(pts[:k].copy())
    measure = DistanceMeasure.get_instance("euclidean")
    plan = FitPlan("xla", None, 1, "first_row", k, d)

    results = {}
    for name, body in (
            ("xla", kmeans_workset_epoch_step(measure, k)),
            ("fused", kmeans_workset_epoch_step(measure, k, block_n=128,
                                                interpret=True))):
        results[name] = iterate(
            body, init, (jnp.asarray(pts), mask), max_epochs=40,
            workset=plan.init_workset(mask),
            workset_tol=0.0,
            config=IterationConfig(mode="fused"))
    assert results["fused"].num_epochs == results["xla"].num_epochs
    np.testing.assert_allclose(np.asarray(results["fused"].state),
                               np.asarray(results["xla"].state),
                               rtol=1e-5, atol=1e-5)
