"""Two-process jax.distributed test tier — the honest MiniCluster analog.

The reference's ITCases run multi-"node" on an in-process Flink MiniCluster
(2 TM x 2 slots, ``UnboundedStreamIterationITCase.java:155-161``); the
single-process 8-device mesh in conftest covers SPMD partitioning but leaves
``parallel/distributed.py``'s multi-process branches dead.  This test boots
TWO real OS processes, each a jax.distributed CPU participant with 2 local
devices (2 hosts x 2 slots), and runs tests/_distributed_worker.py in both:
cross-process mesh, host-local->global assembly, barrier, host-0 broadcast,
a data-parallel iterate fit and the multi-host checkpoint path.
"""

import json
import os
import socket
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_WORKER = os.path.join(_REPO, "tests", "_distributed_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_distributed_tier(tmp_path):
    # (wall-clock bounded by the 240s communicate() timeout below)
    port = _free_port()
    coord = f"127.0.0.1:{port}"
    outdir = str(tmp_path)

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    # a stray pod-launcher env var would make dist.initialize double-init
    for var in ("JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS",
                "MEGASCALE_COORDINATOR_ADDRESS", "CLOUD_TPU_TASK_ID"):
        env.pop(var, None)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")

    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, coord, "2", str(pid), outdir],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        for pid in (0, 1)
    ]
    outputs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outputs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("distributed workers timed out (possible barrier "
                    "deadlock)\n" + "\n---\n".join(outputs))

    for p, out in zip(procs, outputs):
        if p.returncode != 0 and "UNAVAILABLE" in out:
            # coordination service couldn't bind/connect in this sandbox —
            # attempted, environment forbids it (the VERDICT skip rule)
            pytest.skip(f"jax.distributed unavailable in this env:\n{out}")
        if (p.returncode != 0
                and "aren't implemented on the CPU backend" in out):
            # this jaxlib has no cross-process CPU collectives (they landed
            # later than this environment's wheel) — attempted, environment
            # forbids it (same skip rule as above)
            pytest.skip("multiprocess CPU computations unsupported by this "
                        f"jaxlib:\n{out[-500:]}")
        assert p.returncode == 0, f"worker failed:\n{out}"

    results = {}
    for pid in (0, 1):
        with open(os.path.join(outdir, f"result_{pid}.json")) as f:
            results[pid] = json.load(f)

    # both hosts observed the same global computation
    for pid in (0, 1):
        assert results[pid]["global_devices"] == 4
        # hierarchical dcn-axis gradient reduction matched its oracle
        assert results[pid]["grad_reduce_dcn_ok"] is True
        assert results[pid]["total"] == float(sum(range(8)))
        # 3 epochs x sum(0..7)=28 -> 84; resumed to 5 epochs -> 140
        assert results[pid]["final"] == 84.0
        assert results[pid]["resumed"] == 140.0
    assert results[0] != results[1]  # distinct pids really ran
