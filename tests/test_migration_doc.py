"""MIGRATION.md is the reference user's entry point — every
`flink_ml_tpu...` path it cites must keep resolving, or the doc rots
exactly where newcomers land first."""

import importlib
import os
import re

_DOC = os.path.join(os.path.dirname(__file__), "..", "MIGRATION.md")

# dotted paths inside backticks, e.g. `flink_ml_tpu.api.stage.Stage` or
# `flink_ml_tpu.api.pipeline.Pipeline/PipelineModel`
_PATTERN = re.compile(r"`(flink_ml_tpu(?:\.\w+)+(?:/[\w.]+)*)`")


def _resolve(path: str) -> None:
    parts = path.split(".")
    # walk the longest importable module prefix, then getattr the rest
    for split in range(len(parts), 0, -1):
        mod_name = ".".join(parts[:split])
        try:
            obj = importlib.import_module(mod_name)
        except ImportError:
            continue
        for attr in parts[split:]:
            obj = getattr(obj, attr)   # AttributeError = broken citation
        return
    raise ImportError(f"no importable prefix for {path}")


def test_every_cited_path_resolves():
    text = open(_DOC).read()
    cites = sorted(set(_PATTERN.findall(text)))
    assert len(cites) >= 15, "MIGRATION.md lost its citations?"
    for cite in cites:
        # `a.b.C/D` cites several names under one module
        base, *alts = cite.split("/")
        _resolve(base)
        prefix = base.rsplit(".", 1)[0]
        for alt in alts:
            _resolve(f"{prefix}.{alt}" if "." not in alt else
                     f"{base.rsplit('.', 1)[0]}.{alt}")
