"""NaiveBayes + OnlineKMeans tests."""

import numpy as np
import pytest

from flink_ml_tpu import Table
from flink_ml_tpu.models.classification.naivebayes import (
    NaiveBayes,
    NaiveBayesModel,
)
from flink_ml_tpu.models.clustering.online_kmeans import (
    OnlineKMeans,
    OnlineKMeansModel,
)


def _count_table(n=600, seed=0):
    """Two classes with distinct word distributions."""
    rng = np.random.default_rng(seed)
    y = rng.integers(0, 2, size=n)
    theta = np.array([[0.6, 0.2, 0.1, 0.1],
                      [0.1, 0.1, 0.2, 0.6]])
    X = np.stack([rng.multinomial(30, theta[c]) for c in y]).astype(np.float64)
    return Table({"features": X, "label": y})


def test_naivebayes_fit_predict():
    t = _count_table()
    model = NaiveBayes().fit(t)
    out = model.transform(t)[0]
    assert np.mean(out["prediction"] == t["label"]) > 0.95


def test_naivebayes_string_labels():
    t = _count_table(n=200)
    labels = np.where(np.asarray(t["label"]) == 0, "ham", "spam")
    t2 = Table({"features": t["features"], "label": labels})
    model = NaiveBayes().fit(t2)
    preds = model.transform(t2)[0]["prediction"]
    assert set(np.unique(preds)) <= {"ham", "spam"}
    assert np.mean(preds == labels) > 0.95


def test_naivebayes_rejects_negative_features():
    t = Table({"features": np.array([[-1.0, 2.0]]), "label": np.array([0])})
    with pytest.raises(ValueError):
        NaiveBayes().fit(t)


def test_naivebayes_save_load(tmp_path):
    t = _count_table(n=200)
    model = NaiveBayes().set_smoothing(0.5).fit(t)
    path = str(tmp_path / "nb")
    model.save(path)
    loaded = NaiveBayesModel.load(path)
    np.testing.assert_array_equal(loaded.transform(t)[0]["prediction"],
                                  model.transform(t)[0]["prediction"])
    (data,) = model.get_model_data()
    fresh = NaiveBayesModel().set_model_data(data)
    np.testing.assert_array_equal(fresh.transform(t)[0]["prediction"],
                                  model.transform(t)[0]["prediction"])


def _cluster_stream(n_batches=40, batch=128, seed=0, drift=0.0):
    rng = np.random.default_rng(seed)
    centers = np.array([[0.0, 0.0], [10.0, 10.0]])
    for i in range(n_batches):
        assign = rng.integers(0, 2, size=batch)
        pts = centers[assign] + rng.normal(scale=0.5, size=(batch, 2)) \
            + drift * i
        yield Table({"features": pts})


def test_online_kmeans_converges():
    model = (OnlineKMeans().set_k(2).set_seed(1)
             .fit(_cluster_stream()))
    assert isinstance(model, OnlineKMeansModel)
    assert model.model_version == 40
    (data,) = model.get_model_data()
    centroids = np.sort(np.asarray(data["centroids"][0]), axis=0)
    np.testing.assert_allclose(centroids, [[0, 0], [10, 10]], atol=0.5)


def test_online_kmeans_decay_tracks_drift():
    # decay < 1 follows drifting clusters; decay = 1 averages all history
    drift = 0.1
    tracking = (OnlineKMeans().set_k(2).set_decay_factor(0.2).set_seed(1)
                .fit(_cluster_stream(drift=drift)))
    averaging = (OnlineKMeans().set_k(2).set_decay_factor(1.0).set_seed(1)
                 .fit(_cluster_stream(drift=drift)))
    final_shift = drift * 39
    track_c = np.sort(np.asarray(tracking.get_model_data()[0]["centroids"][0]),
                      axis=0)
    avg_c = np.sort(np.asarray(averaging.get_model_data()[0]["centroids"][0]),
                    axis=0)
    # the tracking model's centroid is closer to the final drifted position
    track_err = np.abs(track_c[0] - final_shift).max()
    avg_err = np.abs(avg_c[0] - final_shift).max()
    assert track_err < avg_err


def test_online_kmeans_warm_start_and_predict():
    init = Table({"centroids": np.array([[[0.0, 0.0], [10.0, 10.0]]])})
    model = (OnlineKMeans().set_k(2).set_initial_model_data(init)
             .fit(_cluster_stream(n_batches=5)))
    pts = Table({"features": np.array([[0.1, 0.1], [9.9, 9.8]])})
    preds = model.transform(pts)[0]["prediction"]
    assert preds[0] != preds[1]


def test_online_kmeans_empty_stream_rejected():
    with pytest.raises(ValueError):
        OnlineKMeans().fit(iter([]))


def test_online_kmeans_version_persisted(tmp_path):
    model = OnlineKMeans().set_k(2).set_seed(1).fit(_cluster_stream(5))
    assert model.model_version == 5
    path = str(tmp_path / "okm")
    model.save(path)
    loaded = OnlineKMeansModel.load(path)
    assert loaded.model_version == 5
    (d1,), (d2,) = model.get_model_data(), loaded.get_model_data()
    np.testing.assert_allclose(d1["centroids"], d2["centroids"], rtol=1e-6)


def test_naivebayes_zero_smoothing_no_nan():
    # smoothing=0 yields -inf log-likelihoods for zero-count features; a
    # zero count in a scoring row must contribute 0, not poison the score
    # with nan (0 * -inf) and hijack argmax.
    X = np.array([[5.0, 0.0, 0.0],
                  [0.0, 5.0, 0.0],
                  [0.0, 0.0, 5.0]])
    y = np.array([0, 1, 2])
    t = Table({"features": X, "label": y})
    model = NaiveBayes().set_smoothing(0.0).fit(t)
    pred = np.asarray(model.transform(t)[0]["prediction"])
    np.testing.assert_array_equal(pred, y)


def test_naivebayes_unfitted_model_clear_errors(tmp_path):
    with pytest.raises(RuntimeError, match="no model data"):
        NaiveBayesModel().get_model_data()
    with pytest.raises(RuntimeError, match="no model data"):
        NaiveBayesModel().save(str(tmp_path / "nb"))
    assert not (tmp_path / "nb").exists()  # nothing half-written


def test_online_kmeans_initial_centroid_count_mismatch():
    init = Table({"centroids": np.zeros((2, 2), np.float32)[None]})
    est = OnlineKMeans().set_k(3).set_initial_model_data(init)
    stream = [Table({"features": np.random.default_rng(0)
                     .normal(size=(8, 2)).astype(np.float32)})]
    with pytest.raises(ValueError, match="2 centroids but k=3"):
        est.fit(stream)


class TestOnlineKMeansCheckpoint:
    def test_kill_and_resume_matches_uninterrupted(self, tmp_path):
        from flink_ml_tpu.data.wal import WindowLog
        from flink_ml_tpu.iteration.checkpoint import CheckpointConfig
        from flink_ml_tpu.models.clustering.online_kmeans import OnlineKMeans

        rng = np.random.default_rng(1)
        centers = np.array([[0.0, 0.0], [12.0, 0.0]])
        windows = []
        for i in range(9):
            pts = np.concatenate(
                [c + rng.normal(size=(40, 2)) for c in centers])
            windows.append(Table({"features": pts}))
        init = Table({"centroids": np.array([[1.0, 1.0],
                                             [10.0, 1.0]])[None]})

        def est():
            return (OnlineKMeans().set_k(2).set_decay_factor(0.8)
                    .set_initial_model_data(init))

        oracle = est().fit(iter(windows))

        class Killed(RuntimeError):
            pass

        def dying(ws, k):
            for i, w in enumerate(ws):
                if i == k:
                    raise Killed()
                yield w

        wal = str(tmp_path / "wal")
        ckpt = CheckpointConfig(str(tmp_path / "ckpt"), interval=3)
        with pytest.raises(Killed):
            est().fit(WindowLog(dying(windows, 7), wal), checkpoint=ckpt)
        resumed = est().fit(WindowLog(iter(windows[7:]), wal),
                            checkpoint=ckpt, resume=True)
        got = np.asarray(resumed.get_model_data()[0]["centroids"][0])
        want = np.asarray(oracle.get_model_data()[0]["centroids"][0])
        np.testing.assert_allclose(got, want, rtol=1e-6)
        assert resumed.model_version == oracle.model_version == 9

    def test_checkpoint_requires_warm_start_and_cursor(self, tmp_path):
        from flink_ml_tpu.iteration.checkpoint import CheckpointConfig
        from flink_ml_tpu.models.clustering.online_kmeans import OnlineKMeans

        ckpt = CheckpointConfig(str(tmp_path / "c"))
        t = Table({"features": np.zeros((8, 2))})
        with pytest.raises(ValueError, match="set_initial_model_data"):
            OnlineKMeans().set_k(2).fit(iter([t]), checkpoint=ckpt)
        init = Table({"centroids": np.zeros((1, 2, 2))})
        with pytest.raises(ValueError, match="cursor"):
            (OnlineKMeans().set_k(2).set_initial_model_data(init)
             .fit(iter([t]), checkpoint=ckpt))
