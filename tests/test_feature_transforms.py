"""Bucketizer / Binarizer / Normalizer / PolynomialExpansion / Imputer."""

import numpy as np
import pytest

from flink_ml_tpu import Table
from flink_ml_tpu.models.feature import (
    Binarizer,
    Bucketizer,
    Imputer,
    ImputerModel,
    Normalizer,
    PolynomialExpansion,
)


def _t(X):
    return Table({"features": np.asarray(X, np.float64)})


def test_binarizer():
    out = (Binarizer().set_threshold(0.5)
           .transform(_t([[0.2, 0.6], [0.5, 1.0]]))[0])
    np.testing.assert_array_equal(np.asarray(out["output"]),
                                  [[0.0, 1.0], [0.0, 1.0]])


def test_bucketizer_boundaries_and_clipping():
    b = Bucketizer().set_splits(0.0, 1.0, 2.0, 3.0).set_handle_invalid("clip")
    out = b.transform(_t([[-5.0, 0.0], [0.99, 1.0], [2.5, 99.0]]))[0]
    np.testing.assert_array_equal(np.asarray(out["output"]),
                                  [[0, 0], [0, 1], [2, 2]])


def test_bucketizer_handle_invalid_error_default():
    b = Bucketizer().set_splits(0.0, 1.0, 2.0)
    # in-range values (incl. both outer edges) are fine under the default
    out = b.transform(_t([[0.0, 1.5], [2.0, 0.5]]))[0]
    np.testing.assert_array_equal(np.asarray(out["output"]),
                                  [[0, 1], [1, 0]])
    with pytest.raises(ValueError, match="handleInvalid"):
        b.transform(_t([[-0.1]]))
    with pytest.raises(ValueError, match="handleInvalid"):
        b.transform(_t([[2.1]]))


def test_bucketizer_handle_invalid_keep_routes_to_extra_bucket():
    b = Bucketizer().set_splits(0.0, 1.0, 2.0).set_handle_invalid("keep")
    out = b.transform(_t([[-5.0, 0.5], [1.5, 99.0]]))[0]
    # 2 regular buckets -> invalids land in the dedicated bucket index 2
    np.testing.assert_array_equal(np.asarray(out["output"]),
                                  [[2, 0], [1, 2]])


def test_bucketizer_nan_is_invalid():
    with pytest.raises(ValueError, match="invalid"):
        Bucketizer().set_splits(0.0, 1.0, 2.0).transform(_t([[np.nan]]))
    # clip has no nearest bucket for NaN either
    with pytest.raises(ValueError, match="invalid"):
        (Bucketizer().set_splits(0.0, 1.0, 2.0).set_handle_invalid("clip")
         .transform(_t([[np.nan]])))
    out = (Bucketizer().set_splits(0.0, 1.0, 2.0).set_handle_invalid("keep")
           .transform(_t([[np.nan, 0.5]]))[0])
    np.testing.assert_array_equal(np.asarray(out["output"]), [[2, 0]])


def test_bucketizer_validates_splits():
    with pytest.raises(ValueError, match="increasing"):
        Bucketizer().set_splits(0.0, 2.0, 1.0).transform(_t([[0.5]]))
    with pytest.raises(ValueError, match=">= 3"):
        Bucketizer().set_splits(0.0, 1.0).transform(_t([[0.5]]))


def test_normalizer_l2_and_l1():
    X = [[3.0, 4.0]]
    out2 = Normalizer().transform(_t(X))[0]
    np.testing.assert_allclose(np.asarray(out2["output"]), [[0.6, 0.8]],
                               atol=1e-6)
    out1 = Normalizer().set_p(1.0).transform(_t(X))[0]
    np.testing.assert_allclose(np.asarray(out1["output"]),
                               [[3 / 7, 4 / 7]], atol=1e-6)


def test_normalizer_zero_row_stays_finite():
    out = Normalizer().transform(_t([[0.0, 0.0]]))[0]
    assert np.isfinite(np.asarray(out["output"])).all()


def test_polynomial_expansion_degree2_order():
    out = (PolynomialExpansion().set_degree(2)
           .transform(_t([[2.0, 3.0]]))[0])
    # depth-first by variable index: [x, x^2, xy, y, y^2]
    np.testing.assert_allclose(np.asarray(out["output"]),
                               [[2.0, 4.0, 6.0, 3.0, 9.0]], atol=1e-5)


def test_polynomial_expansion_degree1_identity():
    X = [[1.5, -2.0, 0.5]]
    out = PolynomialExpansion().set_degree(1).transform(_t(X))[0]
    np.testing.assert_allclose(np.asarray(out["output"]), X, atol=1e-6)


def test_imputer_mean_median_mode():
    X = np.asarray([[1.0, 10.0], [np.nan, 30.0], [3.0, np.nan],
                    [np.nan, 30.0]])
    mean = Imputer().fit(_t(X)).transform(_t(X))[0]
    np.testing.assert_allclose(np.asarray(mean["output"])[:, 0],
                               [1.0, 2.0, 3.0, 2.0])
    med = Imputer().set_strategy("median").fit(_t(X)).transform(_t(X))[0]
    np.testing.assert_allclose(np.asarray(med["output"])[:, 0],
                               [1.0, 2.0, 3.0, 2.0])
    mode = (Imputer().set_strategy("most_frequent").fit(_t(X))
            .transform(_t(X))[0])
    np.testing.assert_allclose(np.asarray(mode["output"])[:, 1],
                               [10.0, 30.0, 30.0, 30.0])


def test_imputer_custom_missing_value():
    X = np.asarray([[1.0], [-999.0], [3.0]])
    model = Imputer().set_missing_value(-999.0).fit(_t(X))
    out = model.transform(_t(X))[0]
    np.testing.assert_allclose(np.asarray(out["output"])[:, 0],
                               [1.0, 2.0, 3.0])


def test_imputer_save_load(tmp_path):
    X = np.asarray([[1.0], [np.nan], [3.0]])
    model = Imputer().fit(_t(X))
    model.save(str(tmp_path / "m"))
    re = ImputerModel.load(str(tmp_path / "m"))
    out = re.transform(_t(X))[0]
    np.testing.assert_allclose(np.asarray(out["output"])[:, 0],
                               [1.0, 2.0, 3.0])


def test_transformer_save_load_params(tmp_path):
    b = Bucketizer().set_splits(0.0, 1.0, 2.0)
    b.save(str(tmp_path / "b"))
    re = Bucketizer.load(str(tmp_path / "b"))
    assert tuple(re.get_splits()) == (0.0, 1.0, 2.0)
    n = Normalizer().set_p(3.0)
    n.save(str(tmp_path / "n"))
    assert Normalizer.load(str(tmp_path / "n")).get_p() == 3.0


def test_transforms_compose_in_pipeline(tmp_path):
    from flink_ml_tpu import Pipeline

    X = np.asarray([[1.0, np.nan], [2.0, 8.0], [np.nan, 4.0]])
    pipe = Pipeline([
        Imputer().set_output_col("features"),
        Normalizer().set_output_col("normed").set_features_col("features"),
    ])
    pm = pipe.fit(_t(X))
    out = pm.transform(_t(X))[0]
    normed = np.asarray(out["normed"])
    np.testing.assert_allclose(np.linalg.norm(normed, axis=1), 1.0,
                               atol=1e-5)
    pm.save(str(tmp_path / "p"))
    from flink_ml_tpu.api.pipeline import PipelineModel
    re = PipelineModel.load(str(tmp_path / "p"))
    np.testing.assert_allclose(np.asarray(re.transform(_t(X))[0]["normed"]),
                               normed, atol=1e-6)


def test_normalizer_inf_norm():
    out = Normalizer().set_p(float("inf")).transform(_t([[3.0, -4.0]]))[0]
    np.testing.assert_allclose(np.asarray(out["output"]), [[0.75, -1.0]],
                               atol=1e-6)


def test_imputer_model_without_data_errors():
    with pytest.raises(RuntimeError, match="no model data"):
        ImputerModel().transform(_t([[1.0]]))


def test_bucketizer_binarizer_float64_precision():
    # boundaries that are NOT float32-representable must still classify
    # exactly (regression: a float32 downcast merged 2^24 and 2^24+1)
    big = 16777217.0  # 2^24 + 1
    out = (Bucketizer().set_splits(0.0, big, 2 * big)
           .transform(_t([[16777216.0], [big]]))[0])
    np.testing.assert_array_equal(np.asarray(out["output"]), [[0], [1]])
    bout = (Binarizer().set_threshold(16777216.5)
            .transform(_t([[16777216.0], [big]]))[0])
    np.testing.assert_array_equal(np.asarray(bout["output"]), [[0.0], [1.0]])


def test_cross_class_load_rejected(tmp_path):
    b = Bucketizer().set_splits(0.0, 1.0, 2.0)
    b.save(str(tmp_path / "b"))
    with pytest.raises(IOError):
        Normalizer.load(str(tmp_path / "b"))
