"""Tier-1 wiring for the graftlint framework (ISSUE 8).

Three layers, mirroring the pattern ``test_no_host_sync.py``
established for single checkers:

1. **Repo gate** — ``python -m scripts.graftlint`` semantics: every pass
   over its roots, suppressions + baseline applied AND enforced, exit 0.
2. **Can't-fail self-tests** — each pass must flag its seeded bad
   fixture (a guard that can't fail guards nothing) and, for the bug
   classes this repo actually shipped fixes for, must flag the
   HISTORICAL bug when re-seeded into today's real module (the PR 1
   ``flush_lock``-across-put deadlock, the PR 7-era read-after-donate
   resume shape, the PR 3 top_k-under-auto abort).
3. **Framework mechanics** — suppressions are line-scoped and must be
   exercised (unused ones are findings), baseline entries match by
   symbol and go stale loudly, the walker skips ``__pycache__``, the
   JSON report is machine-stable, the legacy shims delegate.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from scripts.graftlint import runner  # noqa: E402
from scripts.graftlint.core import (  # noqa: E402
    EXCLUDE_DIRS,
    Finding,
    ModuleInfo,
    Project,
    iter_py_files,
)
from scripts.graftlint.passes import ALL_PASSES  # noqa: E402
from scripts.graftlint.passes.atomic_writes import AtomicWritesPass  # noqa: E402,E501
from scripts.graftlint.passes.collectives import (  # noqa: E402
    _AXIS_ARG_POS,
    _COLLECTIVES,
    CollectiveConsistencyPass,
)
from scripts.graftlint.passes.donation import DonationSafetyPass  # noqa: E402,E501
from scripts.graftlint.passes.host_sync import HostSyncPass  # noqa: E402
from scripts.graftlint.passes.locks import LockDisciplinePass  # noqa: E402


def _check(pass_obj, tmp_path, source, name="mod.py", repo=None):
    """Run one AST pass over one fixture module."""
    path = tmp_path / name
    path.write_text(textwrap.dedent(source))
    repo = repo or str(tmp_path)
    project = Project(repo=repo)
    return pass_obj.check_module(ModuleInfo(str(path), repo), project)


# ---------------------------------------------------------------------------
# 1. repo gate
# ---------------------------------------------------------------------------

def test_repo_is_clean_under_all_passes():
    """THE gate: all seven passes, suppressions + baseline enforced, no
    findings — and the accepted exceptions are really being exercised
    (they'd otherwise be unused-suppression / stale-baseline findings)."""
    report = runner.run()
    assert [f.render() for f in report.findings] == []
    assert report.exit_code == 0
    assert report.files_scanned > 100       # the walk actually walked


def test_cli_entry_point_runs_all_passes(tmp_path):
    """One command, one exit code, machine-readable findings."""
    out = tmp_path / "report.json"
    proc = subprocess.run(
        [sys.executable, "-m", "scripts.graftlint", "--json", str(out)],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "graftlint clean" in proc.stdout
    payload = json.loads(out.read_text())
    assert payload["findings"] == []
    assert payload["files_scanned"] > 100


def test_in_repo_paths_restrict_not_replace_pass_roots(tmp_path):
    """Review regression: ``graftlint flink_ml_tpu`` must intersect the
    narrowing path with each pass's own roots — running the durable-
    layer-only atomic-writes rule over the whole package produced 6
    false findings.  Out-of-repo fixture paths keep the legacy
    point-at-anything behavior."""
    report = runner.run(paths=["flink_ml_tpu"])
    assert [f.render() for f in report.findings] == []
    # and the scoping really narrows: a subdir path visits only it
    report2 = runner.run(passes=[LockDisciplinePass()],
                         paths=["flink_ml_tpu/serving"],
                         enforce_suppressions=False)
    assert report2.files_scanned <= 10
    # out-of-repo path: scanned as given even though outside the roots
    bad = tmp_path / "fixture.py"
    bad.write_text(textwrap.dedent("""\
        import queue
        import threading
        q = queue.Queue()
        lock = threading.Lock()
        def f(item):
            with lock:
                q.put(item)
        """))
    report3 = runner.run(passes=[LockDisciplinePass()], paths=[str(bad)],
                         enforce_suppressions=False)
    assert len(report3.findings) == 1


def test_json_dash_emits_parseable_stdout():
    """Review regression: with ``--json -`` the human-readable render
    moves to stderr so stdout IS the machine-readable report."""
    proc = subprocess.run(
        [sys.executable, "-m", "scripts.graftlint", "--json", "-"],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0
    payload = json.loads(proc.stdout)       # parses as pure JSON
    assert payload["findings"] == []
    assert "graftlint clean" in proc.stderr


def test_bench_schema_findings_cannot_be_baselined(tmp_path):
    """Review regression: schema drift is never grandfathered — a
    baseline entry naming a bench-schema finding must not silence it."""
    from scripts.graftlint.passes.bench_schema import BenchSchemaPass

    assert BenchSchemaPass.baseline_exempt
    drifting = BenchSchemaPass()
    fake = Finding(pass_id="bench-schema", path="bench.py", line=0,
                   message="drift", symbol="<schema>")
    drifting.run = lambda project, paths=None: [fake]
    drifting.baseline_exempt = True
    baseline = tmp_path / "baseline.txt"
    baseline.write_text("bench-schema bench.py::<schema>  # nope\n")
    report = runner.run(passes=[drifting], baseline_path=str(baseline),
                        enforce_suppressions=False)
    assert [f.pass_id for f in report.findings] == ["bench-schema"]
    assert report.baselined == []


def test_nonexistent_explicit_path_fails_loudly(tmp_path):
    """Review regression: a typo'd CI path must never pass by checking
    zero files — the runner raises (legacy-checker parity) and the CLI
    exits 2."""
    with pytest.raises(FileNotFoundError, match="no such path"):
        runner.run(paths=["flink_ml_tpu/modles"])
    proc = subprocess.run(
        [sys.executable, "-m", "scripts.graftlint", "does_not_exist.py"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 2
    assert "no such path" in proc.stderr


def test_donation_flags_same_statement_read_after_call(tmp_path):
    """Review regression: Python evaluates left-to-right, so
    ``step(state, xs) + state.sum()`` reads the donated buffer in the
    SAME statement; a read textually before the call does not."""
    problems = _check(DonationSafetyPass(), tmp_path, """\
        import jax
        step = jax.jit(lambda s, x: s + x, donate_argnums=(0,))
        def bad(state, xs):
            loss = step(state, xs) + state.sum()
            return loss
        def fine(state, xs):
            loss = state.sum() + step(state, xs)
            return loss
        """)
    assert len(problems) == 1 and "'state'" in problems[0].message
    assert problems[0].symbol == "bad"


def test_collectives_nested_switch_reports_once(tmp_path):
    """Review regression: a divergent switch inside a nested def is
    reachable from both the inner and outer function walks — one
    finding, not two."""
    problems = _check(CollectiveConsistencyPass(), tmp_path, """\
        from jax import lax
        def branch_a(x):
            return lax.psum(x, "data")
        def branch_b(x):
            return lax.all_gather(x, "data").sum()
        def outer(x, idx):
            def inner(y):
                return lax.switch(idx, [branch_a, branch_b], y)
            return inner(x)
        """)
    assert len(problems) == 1
    assert "different collective sets" in problems[0].message


def test_pass_catalog_covers_the_contract():
    ids = {cls.id for cls in ALL_PASSES}
    assert ids == {"host-sync", "atomic-writes", "donation-safety",
                   "lock-discipline", "collective-consistency",
                   "kernel-registry", "unfenced-timing", "bench-schema"}


# ---------------------------------------------------------------------------
# 2a. donation-safety
# ---------------------------------------------------------------------------

def test_donation_flags_read_after_donate(tmp_path):
    problems = _check(DonationSafetyPass(), tmp_path, """\
        import jax
        step = jax.jit(lambda s, x: s + x, donate_argnums=(0,))
        def fit(state, xs):
            out = step(state, xs)
            return state + out
        """)
    assert len(problems) == 1 and "'state' is read after" in \
        problems[0].message


def test_donation_accepts_rebind_and_copy(tmp_path):
    problems = _check(DonationSafetyPass(), tmp_path, """\
        import jax
        step = jax.jit(lambda s, x: s + x, donate_argnums=(0,))
        def fit(state, xs):
            state = step(state, xs)      # rebind consumes the donation
            return state
        def fit_copy(state, xs):
            out = step(state.copy(), xs)   # donates a private copy
            return state + out
        """)
    assert problems == []


def test_donation_covers_decorator_and_loop_back_edge(tmp_path):
    problems = _check(DonationSafetyPass(), tmp_path, """\
        import jax
        from functools import partial
        @partial(jax.jit, donate_argnums=(0,))
        def consume(buf, x):
            return buf * x
        def loop(state, chunks):
            for c in chunks:
                consume(state, c)    # donated on iter 1, read on iter 2
            return 0
        """)
    assert len(problems) == 1


def test_donation_follows_jit_factories(tmp_path):
    """The serving/executor.py shape: a helper manufactures donating
    callables; the donated positions come from the call site."""
    problems = _check(DonationSafetyPass(), tmp_path, """\
        import jax
        def serving_jit(fn, donate_argnums):
            donate = donate_argnums if True else ()
            return jax.jit(fn, donate_argnums=donate)
        def serve(X, w):
            fn = serving_jit(lambda a, b: a @ b, (0,))
            out = fn(X, w)
            return X.sum() + out         # X was donated
        """)
    assert len(problems) == 1 and "'X'" in problems[0].message


def test_donation_respects_conditional_donate_and_early_return(tmp_path):
    """Regression for the iteration/core.py false positive this PR hit:
    two mutually-exclusive arms each call the donating fn, the first
    ends in ``return`` — the second arm's call must NOT read as a
    re-read of the first arm's donation.  The conditional
    ``(0,) if cfg else ()`` form still counts as donating."""
    problems = _check(DonationSafetyPass(), tmp_path, """\
        import jax
        def build(body, cfg, initial_state, data):
            run = jax.jit(body, donate_argnums=(0,) if cfg else ())
            if cfg:
                final, outs = run(initial_state, data)
                return final, outs
            final, outs, extra = run(initial_state, data)
            return final, (outs, extra)
        """)
    assert problems == []


def test_donation_catches_reseeded_resume_hazard_in_real_core():
    """Re-seed the exact hazard ``_private_copy`` exists to prevent into
    today's ``iteration/core.py`` (read the donated state between the
    step call and the rebind): the pass must catch it, and must be
    clean on the unmodified file."""
    path = os.path.join(REPO, "flink_ml_tpu", "iteration", "core.py")
    src = open(path).read()
    marker = ("            res = step(state, jnp.asarray(epoch, jnp.int32),"
              " epoch_data)\n            state = res.feedback")
    assert marker in src, "core.py hosted-loop shape moved; update test"
    bad = src.replace(marker, marker.replace(
        "\n            state = res.feedback",
        "\n            stale = jax.tree_util.tree_leaves(state)"
        "\n            state = res.feedback"))
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        good_p = os.path.join(d, "core_good.py")
        bad_p = os.path.join(d, "core_bad.py")
        open(good_p, "w").write(src)
        open(bad_p, "w").write(bad)
        project = Project(repo=d)
        p = DonationSafetyPass()
        assert p.check_module(ModuleInfo(good_p, d), project) == []
        problems = p.check_module(ModuleInfo(bad_p, d), project)
    assert len(problems) == 1 and "'state'" in problems[0].message


# ---------------------------------------------------------------------------
# 2b. lock-discipline
# ---------------------------------------------------------------------------

def test_locks_flag_blocking_under_with_acquire_and_transitive(tmp_path):
    problems = _check(LockDisciplinePass(), tmp_path, """\
        import queue
        import threading
        import time
        q = queue.Queue(maxsize=2)
        lock = threading.Lock()
        def bad_put(item):
            with lock:
                q.put(item)
        def bad_sleep():
            lock.acquire()
            time.sleep(0.1)
            lock.release()
        def bad_transitive(item):
            with lock:
                helper(item)
        def helper(item):
            q.put(item, timeout=1.0)
        """)
    assert len(problems) == 3
    reasons = "\n".join(f.message for f in problems)
    assert "queue put()" in reasons and "time.sleep" in reasons \
        and "helper() -> queue put()" in reasons


def test_locks_accept_release_before_block_and_nonqueue_get(tmp_path):
    """The ``_flush_ready`` discipline: release, block, reacquire — in
    linear statement order the put is NOT held; and ``dict.get`` is not
    a queue get."""
    problems = _check(LockDisciplinePass(), tmp_path, """\
        import queue
        import threading
        q = queue.Queue()
        lock = threading.Lock()
        def good(items, d, k):
            lock.acquire()
            try:
                staged = list(items)
                v = d.get(k)
                lock.release()
                try:
                    for s in staged:
                        q.put(s)
                finally:
                    lock.acquire()
            finally:
                lock.release()
            return v
        """)
    assert problems == []


def test_locks_flag_device_put_join_and_wait(tmp_path):
    problems = _check(LockDisciplinePass(), tmp_path, """\
        import jax
        import threading
        lock = threading.Lock()
        def to_device(batch, sharding):
            with lock:
                return jax.device_put(batch, sharding)
        def reap(worker_thread):
            with lock:
                worker_thread.join()
        def land(manager):
            with lock:
                manager.wait()
        """)
    assert len(problems) == 3


def test_locks_catch_reseeded_flush_lock_bug_in_real_prefetch():
    """Re-seed THE PR 1 bug (blocking put moved back under flush_lock)
    into today's ``data/prefetch.py``: the pass must reconstruct the
    finding, and must be clean on the unmodified file."""
    path = os.path.join(REPO, "flink_ml_tpu", "data", "prefetch.py")
    src = open(path).read()
    marker = """\
                        flush_lock.release()
                        try:
                            for entry in ready:
                                put_or_abandon(q, entry)
                        finally:
                            flush_lock.acquire()"""
    assert marker in src, "prefetch._flush_ready shape moved; update test"
    bad = src.replace(marker, """\
                        for entry in ready:
                            put_or_abandon(q, entry)""")
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        good_p = os.path.join(d, "prefetch_good.py")
        bad_p = os.path.join(d, "prefetch_bad.py")
        open(good_p, "w").write(src)
        open(bad_p, "w").write(bad)
        project = Project(repo=d)
        p = LockDisciplinePass()
        assert p.check_module(ModuleInfo(good_p, d), project) == []
        problems = p.check_module(ModuleInfo(bad_p, d), project)
    assert len(problems) == 1
    assert "flush_lock" in problems[0].message
    assert "put_or_abandon() -> queue put()" in problems[0].message


# ---------------------------------------------------------------------------
# 2c. collective-consistency
# ---------------------------------------------------------------------------

_COLL_FIXTURE = """\
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    mesh = Mesh(jax.devices(), ("data",))

    def unbound_body(x):
        return lax.psum(x, "model")

    def run_unbound(x):
        return shard_map(unbound_body, mesh, in_specs=(P("data"),),
                         out_specs=P())(x)

    def topk_body(x):
        vals, idx = lax.top_k(x, 4)
        return lax.psum(vals, "data")

    def run_topk_auto(x):
        return shard_map(topk_body, mesh, in_specs=(P("data"),),
                         out_specs=P(), auto=frozenset({"model"}))(x)

    def branch_a(x):
        return lax.psum(x, "data")

    def branch_b(x):
        return lax.all_gather(x, "data").sum()

    def diverging(x, idx):
        return lax.switch(idx, [branch_a, branch_b], x)

    def converged(x):
        n = lax.psum(jnp.ones(()), "data")
        idx = (n > 4).astype(jnp.int32)
        return lax.switch(idx, [branch_a, branch_b], x)

    def same_sets(x, flag):
        return lax.cond(flag, branch_a, branch_a, x)
    """


def test_collectives_three_subchecks_fire_and_safe_shapes_pass(tmp_path):
    problems = _check(CollectiveConsistencyPass(), tmp_path, _COLL_FIXTURE)
    msgs = sorted(f.message for f in problems)
    assert len(problems) == 3
    assert any("axis 'model'" in m for m in msgs)            # unbound axis
    assert any("top_k" in m for m in msgs)                   # topk in auto
    assert any("different collective sets" in m for m in msgs)
    # the psum-derived switch (``converged``) and the matching-set cond
    # (``same_sets``) must NOT be flagged: exactly one branch-divergence
    # finding exists and it anchors in ``diverging``
    switch_findings = [f for f in problems
                       if "different collective sets" in f.message]
    assert [f.symbol for f in switch_findings] == ["diverging"]


def test_collectives_follow_factory_built_branch_lists(tmp_path):
    """The grad_reduce adaptive-ladder shape: branches built by a
    comprehension over a factory whose inner defs carry different
    collective sets."""
    problems = _check(CollectiveConsistencyPass(), tmp_path, """\
        from jax import lax
        def make(spec):
            if spec == "exact":
                def branch(acc):
                    return lax.psum(acc, "data")
            else:
                def branch(acc):
                    return lax.all_gather(acc, "data").sum()
            return branch
        def reduce_bucketed(acc, rung, ladder):
            branches = [make(spec) for spec in ladder]
            return lax.switch(rung, branches, acc)
        """)
    assert len(problems) == 1
    assert "different collective sets" in problems[0].message


def test_collectives_follow_cross_module_references(tmp_path):
    """sgd -> grad_reduce shape: the shard_map body reaches top_k
    through a from-import into another repo module."""
    pkg = tmp_path / "pkg"
    (pkg / "sub").mkdir(parents=True)
    (pkg / "__init__.py").write_text("")
    (pkg / "sub" / "__init__.py").write_text("")
    (pkg / "sub" / "reduce.py").write_text(textwrap.dedent("""\
        from jax import lax
        def compress(g):
            vals, idx = lax.top_k(g, 8)
            return lax.psum(vals, "data")
        """))
    (pkg / "sub" / "train.py").write_text(textwrap.dedent("""\
        from ..sub import reduce as GR
        from jax.experimental.shard_map import shard_map
        def build(mesh, auto_axes):
            def body(g):
                return GR.compress(g)
            return shard_map(body, mesh, in_specs=(), out_specs=(),
                             auto=auto_axes)
        """))
    project = Project(repo=str(tmp_path))
    mod = ModuleInfo(str(pkg / "sub" / "train.py"), str(tmp_path))
    problems = CollectiveConsistencyPass().check_module(mod, project)
    assert len(problems) == 1 and "top_k" in problems[0].message
    assert "reduce.py" in problems[0].message       # names the hop


def test_collectives_resolve_axis_through_round_loop_helpers(tmp_path):
    """ISSUE 16 seeded fixture: the recursive-doubling wire protocol
    moves its ``ppermute`` out of the shard_map body into round-loop
    helpers whose perm lists are built from ``axis_size(axis)``.  A
    typo'd LITERAL axis at the helper call site used to sail past
    sub-check 1 — the collective itself only ever sees the parameter
    name ``axis``, which is not a literal — and abort at lowering.  The
    pass now computes which helper params flow into collective axis
    arguments (transitively: ``body -> rd_round -> exchange ->
    ppermute``) and checks the literals at the call site.  The
    correctly-bound twin body must stay clean."""
    problems = _check(CollectiveConsistencyPass(), tmp_path, """\
        import jax
        from jax import lax
        from jax.experimental.shard_map import shard_map
        from jax.sharding import Mesh, PartitionSpec as P

        mesh = Mesh(jax.devices(), ("data",))

        def exchange(x, axis, perm):
            return lax.ppermute(x, axis, perm)

        def rd_round(x, r, axis):
            p = lax.axis_size(axis)
            half = p >> (r + 1)
            perm = [(i, i ^ half) for i in range(p)]
            return x + exchange(x, axis, perm)

        def body(x):
            for r in range(3):
                x = rd_round(x, r, "dcn")     # typo: mesh binds "data"
            return x

        def body_ok(x):
            for r in range(3):
                x = rd_round(x, r, "data")
            return x

        def run(x):
            return shard_map(body, mesh, in_specs=(P("data"),),
                             out_specs=P("data"))(x)

        def run_ok(x):
            return shard_map(body_ok, mesh, in_specs=(P("data"),),
                             out_specs=P("data"))(x)
        """)
    msgs = [f.message for f in problems]
    assert len(problems) == 1, msgs
    assert "axis 'dcn'" in msgs[0] and "['data']" in msgs[0]


def test_collectives_pass_visits_wire_protocol_module():
    """The recursive-doubling primitives live in parallel/collectives.py
    — assert the pass's walk genuinely VISITS that module (a roots
    listing that misses it guards nothing), that the new wrappers are
    known collectives with their axis positions registered (their axis
    rides AFTER the segment length, so the lax-default position 1 would
    misread a perm list as an axis), and that the module is clean raw:
    the one ``lax.cond`` in ``sparse_all_reduce_rd`` keeps equal branch
    collective sets by construction (both doubling branches are pure
    ppermute), so no new baseline entry was needed."""
    assert {"sparse_all_reduce_rd", "fixed_point_all_reduce"} \
        <= _COLLECTIVES
    assert _AXIS_ARG_POS["sparse_all_reduce_rd"] == 3
    assert _AXIS_ARG_POS["sparse_all_reduce"] == 3
    project = Project(repo=REPO)
    visited = {os.path.basename(m.path): m
               for m in project.iter_modules(
                   [os.path.join(REPO, "flink_ml_tpu", "parallel")])}
    assert {"collectives.py", "grad_reduce.py"} <= set(visited)
    coll_pass = CollectiveConsistencyPass()
    assert coll_pass.check_module(visited["collectives.py"], project) == []
    # grad_reduce's only raw finding stays the baselined rung switch —
    # the new wire-protocol plumbing added nothing
    raw = coll_pass.check_module(visited["grad_reduce.py"], project)
    assert {f.symbol for f in raw} <= {"_reduce_bucketed"}


def test_grad_reduce_adaptive_switch_is_baselined_not_silent():
    """The one accepted finding: the rung switch in _reduce_bucketed IS
    flagged by the raw pass (the taint is carried state, invisible
    statically) and the committed baseline is what accepts it — so the
    guard stays falsifiable."""
    project = Project(repo=REPO)
    mod = project.module(os.path.join(
        REPO, "flink_ml_tpu", "parallel", "grad_reduce.py"))
    problems = CollectiveConsistencyPass().check_module(mod, project)
    assert len(problems) == 1
    assert problems[0].symbol == "_reduce_bucketed"
    entries = runner.load_baseline(runner.BASELINE)
    assert any(e.fingerprint == problems[0].fingerprint for e in entries)


# ---------------------------------------------------------------------------
# 2d. absorbed passes keep their teeth
# ---------------------------------------------------------------------------

def test_host_sync_pass_flags_seeded_sync(tmp_path):
    problems = _check(HostSyncPass(), tmp_path, """\
        import numpy as np
        def batch_step(params, xb):
            return params, np.asarray(xb)
        """)
    assert len(problems) == 1 and "np.asarray" in problems[0].message


def test_atomic_writes_pass_flags_naked_write(tmp_path):
    problems = _check(AtomicWritesPass(), tmp_path, """\
        import os
        def save(path, data):
            with open(path, 'wb') as f:
                f.write(data)
        """)
    assert len(problems) == 1 and "half-written" in problems[0].message


def test_atomic_writes_pass_visits_aot_cache_modules():
    """The persistent executable/decision cache (ISSUE 12) joined the
    durable roots: the pass must actually VISIT both modules (parse
    them, see their open-for-write sites) and find every write riding
    the tmp-dir -> commit -> os.replace protocol — no suppressions, no
    blind spots."""
    import ast

    for rel in ("flink_ml_tpu/kernels/aot.py",
                "flink_ml_tpu/kernels/autotune.py"):
        assert rel in AtomicWritesPass.roots
    project = Project(repo=REPO)
    writes_seen = 0
    for rel in ("flink_ml_tpu/kernels/aot.py",
                "flink_ml_tpu/kernels/autotune.py"):
        mod = project.module(os.path.join(REPO, *rel.split("/")))
        problems = AtomicWritesPass().check_module(mod, project)
        assert problems == [], (
            f"{rel}: cache writes must use the commit protocol "
            f"(tmp -> os.replace): {[f.message for f in problems]}")
        # visits-the-module proof: the pass's subject matter — actual
        # open-for-write call sites — exists in the module it cleared
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) \
                    and mod.call_qualname(node) == "open":
                writes_seen += 1
    assert writes_seen >= 5, (
        "the AOT cache modules lost their write sites — the durable-root "
        "listing is guarding nothing")


def test_atomic_writes_pass_visits_obs_package():
    """flink_ml_tpu/obs joined the durable roots (ISSUE 13): trace
    exports must be tmp -> os.replace (they are the files an operator
    loads after a crash), and the one sanctioned exception — the
    sampler's line-framed JSONL append (torn tail dropped by
    read_samples, the WAL-tail stance) — must be SEEN by the raw pass
    and disarmed only by its inline suppression (suppression !=
    blindness)."""
    assert "flink_ml_tpu/obs" in AtomicWritesPass.roots
    project = Project(repo=REPO)
    visited = {os.path.relpath(m.path, REPO): m
               for m in project.iter_modules(
                   [os.path.join(REPO, "flink_ml_tpu", "obs")])}
    names = {os.path.basename(p) for p in visited}
    assert {"trace.py", "tree.py", "probe.py"} <= names
    by_file = {rel: AtomicWritesPass().check_module(mod, project)
               for rel, mod in visited.items()}
    # the atomic export writes clear the pass outright
    trace_rel = os.path.join("flink_ml_tpu", "obs", "trace.py")
    assert by_file[trace_rel] == []
    # the sampler append IS flagged raw, and the flag is suppressed
    tree_rel = os.path.join("flink_ml_tpu", "obs", "tree.py")
    raw = by_file[tree_rel]
    assert {f.symbol for f in raw} == {"ObsSampler.sample"}
    mod = visited[tree_rel]
    for f in raw:
        assert "atomic-writes" in mod.suppressions.get(f.line, set())


def test_scheduler_modules_visited_by_lock_and_host_sync_passes():
    """ISSUE 14: the multi-tenant scheduler joined the scanned surfaces.
    ``lock-discipline`` roots at the whole package — assert the walk
    genuinely VISITS the new modules (a root listing that misses them
    guards nothing) and that both are clean: the scheduler's whole
    design is compute-under-the-condvar, block outside it, and the
    embedding cache's pool faults must never run under a held lock.
    ``host-sync``'s step-tree roots grew ``flink_ml_tpu/serving`` (the
    one serve loop multiplexes EVERY tenant — a host sync in a
    step-shaped helper there stalls all of them at once)."""
    from scripts.graftlint.passes.host_sync import SCAN_ROOTS

    assert "flink_ml_tpu/serving" in SCAN_ROOTS
    assert "flink_ml_tpu" in LockDisciplinePass.roots
    project = Project(repo=REPO)
    lock_visited = {
        os.path.relpath(m.path, REPO)
        for m in project.iter_modules(
            [os.path.join(REPO, r) for r in LockDisciplinePass.roots])}
    new_modules = [os.path.join("flink_ml_tpu", "serving", name)
                   for name in ("scheduler.py", "embcache.py")]
    for rel in new_modules:
        assert rel in lock_visited, f"lock-discipline never visits {rel}"
    sync_visited = {
        os.path.relpath(m.path, REPO)
        for m in project.iter_modules(
            [os.path.join(REPO, r) for r in SCAN_ROOTS])}
    for rel in new_modules:
        assert rel in sync_visited, f"host-sync never visits {rel}"
    for rel in new_modules:
        mod = project.module(os.path.join(REPO, rel))
        assert LockDisciplinePass().check_module(mod, project) == []
        assert HostSyncPass().check_module(mod, project) == []


def test_atomic_writes_pass_guards_durability_module():
    """robustness/durability.py joined the durable roots this PR; its
    two protocol-level exceptions are inline-suppressed, so the raw pass
    must still SEE them (suppression != blindness)."""
    assert "flink_ml_tpu/robustness/durability.py" in \
        AtomicWritesPass.roots
    project = Project(repo=REPO)
    mod = project.module(os.path.join(
        REPO, "flink_ml_tpu", "robustness", "durability.py"))
    problems = AtomicWritesPass().check_module(mod, project)
    assert {f.symbol for f in problems} == \
        {"write_manifest", "write_commit_marker"}
    for f in problems:
        assert "atomic-writes" in mod.suppressions.get(f.line, set())


# ---------------------------------------------------------------------------
# 3. framework mechanics
# ---------------------------------------------------------------------------

def _mini_repo(tmp_path, body, suppress=""):
    mod = tmp_path / "m.py"
    mod.write_text(textwrap.dedent(body))
    baseline = tmp_path / "baseline.txt"
    baseline.write_text(suppress)
    return str(mod), str(baseline)


def test_suppression_drops_finding_and_is_marked_used(tmp_path):
    mod, baseline = _mini_repo(tmp_path, """\
        import queue
        import threading
        q = queue.Queue()
        lock = threading.Lock()
        def f(item):
            with lock:
                q.put(item)   # graftlint: disable=lock-discipline
        """)
    report = runner.run(repo=str(tmp_path),
                        passes=[LockDisciplinePass()], paths=[mod],
                        baseline_path=baseline,
                        enforce_suppressions=True)
    assert report.findings == [] and len(report.suppressed) == 1


def test_unused_suppression_is_itself_a_finding(tmp_path):
    mod, baseline = _mini_repo(tmp_path, """\
        def fine():   # graftlint: disable=lock-discipline
            return 1
        """)
    report = runner.run(repo=str(tmp_path),
                        passes=[LockDisciplinePass()], paths=[mod],
                        baseline_path=baseline,
                        enforce_suppressions=True)
    assert len(report.findings) == 1
    assert report.findings[0].pass_id == "unused-suppression"


def test_baseline_entry_grandfathers_by_symbol_and_goes_stale(tmp_path):
    body = """\
        import queue
        import threading
        q = queue.Queue()
        lock = threading.Lock()
        def legacy(item):
            with lock:
                q.put(item)
        """
    mod, baseline = _mini_repo(
        tmp_path, body,
        suppress="lock-discipline m.py::legacy  # grandfathered\n")
    report = runner.run(repo=str(tmp_path),
                        passes=[LockDisciplinePass()], paths=[mod],
                        baseline_path=baseline,
                        enforce_suppressions=True)
    assert report.findings == [] and len(report.baselined) == 1
    # now the hazard is fixed but the entry remains: stale-baseline
    mod2, baseline2 = _mini_repo(
        tmp_path, "def legacy():\n    return 1\n",
        suppress="lock-discipline m.py::legacy  # grandfathered\n")
    report2 = runner.run(repo=str(tmp_path),
                         passes=[LockDisciplinePass()], paths=[mod2],
                         baseline_path=baseline2,
                         enforce_suppressions=True)
    assert [f.pass_id for f in report2.findings] == ["stale-baseline"]


def test_suppression_allows_trailing_justification(tmp_path):
    """Review regression: ids stop at the comma-separated list — a
    trailing justification must neither disarm the suppression nor be
    swallowed into a garbage pass id."""
    mod, baseline = _mini_repo(tmp_path, """\
        import queue
        import threading
        q = queue.Queue()
        lock = threading.Lock()
        def f(item):
            with lock:
                q.put(item)  # graftlint: disable=lock-discipline held is protocol safe
        """)
    report = runner.run(repo=str(tmp_path),
                        passes=[LockDisciplinePass()], paths=[mod],
                        baseline_path=baseline,
                        enforce_suppressions=True)
    assert report.findings == [] and len(report.suppressed) == 1


def test_suppression_syntax_quoted_in_docstring_is_not_a_suppression(
        tmp_path):
    """Review regression: documentation QUOTING the disable syntax (a
    docstring or string literal) must not register as a suppression —
    it would fail the gate as unused."""
    mod, baseline = _mini_repo(tmp_path, '''\
        """Module doc: silence a finding with
        `# graftlint: disable=lock-discipline` on the flagged line."""
        EXAMPLE = "# graftlint: disable=host-sync"
        ''')
    report = runner.run(repo=str(tmp_path),
                        passes=[LockDisciplinePass()], paths=[mod],
                        baseline_path=baseline,
                        enforce_suppressions=True)
    assert report.findings == []


def test_shim_check_file_honors_inline_suppressions():
    """Review regression: the legacy shims and the canonical gate must
    agree on what is clean — durability.py's two suppressed sites stay
    quiet through the shim surface too."""
    caw = _load_shim("check_atomic_writes")
    path = os.path.join(REPO, "flink_ml_tpu", "robustness",
                        "durability.py")
    assert caw.check_file(path) == []


def test_json_report_shape(tmp_path):
    mod, baseline = _mini_repo(tmp_path, """\
        import queue
        import threading
        q = queue.Queue()
        lock = threading.Lock()
        def f(item):
            with lock:
                q.put(item)
        """)
    report = runner.run(repo=str(tmp_path),
                        passes=[LockDisciplinePass()], paths=[mod],
                        baseline_path=baseline,
                        enforce_suppressions=True)
    payload = report.as_dict()
    assert payload["counts"] == {"lock-discipline": 1}
    f = payload["findings"][0]
    assert {"pass", "path", "line", "symbol", "message", "hint"} <= set(f)
    json.dumps(payload)       # serializable as-is


def test_walker_skips_pycache_and_gitignore_covers_artifacts(tmp_path):
    (tmp_path / "pkg" / "__pycache__").mkdir(parents=True)
    (tmp_path / "pkg" / "real.py").write_text("x = 1\n")
    (tmp_path / "pkg" / "__pycache__" / "junk.py").write_text("x = 1\n")
    found = list(iter_py_files([str(tmp_path)]))
    assert [os.path.basename(p) for p in found] == ["real.py"]
    # every generated dir the walker special-cases must be gitignored so
    # the linter (and git) agree on what is source
    gitignore = open(os.path.join(REPO, ".gitignore")).read()
    for pattern in ("__pycache__/", ".pytest_cache/", "graftlint*.json"):
        assert pattern in gitignore, f"{pattern} missing from .gitignore"
    assert "__pycache__" in EXCLUDE_DIRS


def test_alias_resolution_sees_through_import_renames(tmp_path):
    """The shared qualified-name layer: ``import numpy as onp`` and a
    local rebinding both resolve to the same host-sync finding."""
    problems = _check(HostSyncPass(), tmp_path, """\
        import numpy as onp
        def chunk_step(carry, xs):
            return carry, onp.asarray(xs)
        """)
    assert len(problems) == 1 and "np.asarray" in problems[0].message


def test_linter_is_lint_clean():
    """Run the AST passes over the linter's own tree (plus the shims):
    the gate must hold itself to its own conventions."""
    project = Project(repo=REPO)
    passes = [AtomicWritesPass(), DonationSafetyPass(),
              LockDisciplinePass(), CollectiveConsistencyPass(),
              HostSyncPass()]
    problems = []
    for mod in project.iter_modules(["scripts"]):
        for p in passes:
            problems += p.check_module(mod, project)
    assert [f.render() for f in problems] == []


# ---------------------------------------------------------------------------
# legacy shims
# ---------------------------------------------------------------------------

def _load_shim(name):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "scripts", f"{name}.py"))
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


def test_shims_delegate_and_warn(tmp_path, capsys):
    shim = _load_shim("check_no_host_sync")
    with pytest.warns(DeprecationWarning, match="graftlint"):
        rc = shim.main([])
    assert rc == 0
    assert "clean" in capsys.readouterr().out
    caw = _load_shim("check_atomic_writes")
    with pytest.warns(DeprecationWarning, match="graftlint"):
        rc = caw.main([])
    assert rc == 0
    # the shim surface the legacy tests import is intact
    assert shim.SCAN_ROOTS and callable(shim.check_file) \
        and callable(shim._module_paths)
    assert caw.DURABLE_MODULES and callable(caw.check_file)


# ---------------------------------------------------------------------------
# 2f. kernel-registry (ISSUE 10)
# ---------------------------------------------------------------------------

def test_kernel_registry_flags_direct_pallas_call(tmp_path):
    from scripts.graftlint.passes.kernel_registry import KernelRegistryPass

    problems = _check(KernelRegistryPass(), tmp_path, """\
        from jax.experimental import pallas as pl

        def fast_scores(x):
            return pl.pallas_call(lambda i, o: None, out_shape=x)(x)
        """)
    assert len(problems) == 1
    assert "pallas_call bypasses the kernel registry" in problems[0].message
    assert problems[0].symbol == "fast_scores"


def test_kernel_registry_flags_use_pallas_branching(tmp_path):
    """The pre-PR 10 sgd.py idiom, reconstructed from a seeded fixture:
    a use_pallas parameter AND the call-site keyword both flag."""
    from scripts.graftlint.passes.kernel_registry import KernelRegistryPass

    problems = _check(KernelRegistryPass(), tmp_path, """\
        import jax

        def _update(w, use_pallas=True):
            if use_pallas:
                return w + 1
            return w - 1

        def fit(w):
            return _update(w, use_pallas=jax.default_backend() == "tpu")

        def fit_inline(w):
            use_pallas = jax.default_backend() == "tpu"
            return w + 1 if use_pallas else w - 1
        """)
    msgs = [p.message for p in problems]
    assert any("parameter forks backend dispatch" in m for m in msgs)
    assert any("backend branching at the call site" in m for m in msgs)
    assert any("binding forks backend dispatch inline" in m for m in msgs)
    assert len(problems) == 3


def test_kernel_registry_accepts_registry_lookup(tmp_path):
    from scripts.graftlint.passes.kernel_registry import KernelRegistryPass

    problems = _check(KernelRegistryPass(), tmp_path, """\
        from flink_ml_tpu.kernels.registry import lookup

        def _update(w, backend=None):
            entry = lookup("ell_margin", sig=(w.shape[0],), backend=backend)
            return entry.fn(w)
        """)
    assert problems == []


def test_kernel_registry_scope_is_models_and_retrieval_trees():
    """scope_fixed: pointing graftlint at flink_ml_tpu must not run the
    dispatch-layer rule over ops/ (where pallas_call lives by design).
    ISSUE 19 grew the scope to ``retrieval/`` — the index layer looks
    ``retrieve`` up exactly like the model families look up their ops,
    so the bypass idioms are flagged there too, and the pass must
    genuinely VISIT the new modules (a root listing that misses them
    guards nothing)."""
    from scripts.graftlint.passes.kernel_registry import KernelRegistryPass

    p = KernelRegistryPass()
    assert p.scope_fixed
    assert p.roots == ("flink_ml_tpu/models", "flink_ml_tpu/retrieval")
    project = Project(repo=REPO)
    assert p.run(project, ["flink_ml_tpu"]) == []
    visited = {
        os.path.relpath(m.path, REPO)
        for m in project.iter_modules(
            [os.path.join(REPO, r) for r in p.roots])}
    for name in ("ivf.py", "metrics.py"):
        rel = os.path.join("flink_ml_tpu", "retrieval", name)
        assert rel in visited, f"kernel-registry never visits {rel}"


# ---------------------------------------------------------------------------
# 2g. unfenced-timing (ISSUE 13)
# ---------------------------------------------------------------------------

def test_unfenced_timing_flags_bare_bracketing(tmp_path):
    """The can't-fail seeded fixture: perf_counter brackets a jitted
    call with no fence — the dispatch-enqueue-not-the-work bug bench.py
    hand-dodged per leg before fenced_call."""
    from scripts.graftlint.passes.unfenced_timing import UnfencedTimingPass

    problems = _check(UnfencedTimingPass(), tmp_path, """\
        import time
        import jax

        run = jax.jit(lambda x: x * 2)

        def measure(x):
            t0 = time.perf_counter()
            y = run(x)
            return time.perf_counter() - t0
        """)
    assert len(problems) == 1
    assert "no device fence" in problems[0].message
    assert problems[0].symbol == "measure"


def test_unfenced_timing_accepts_fenced_forms(tmp_path):
    """np.asarray probe fetch, jax.device_get, and fenced_call all
    satisfy the fence; host-only timing (no jitted call inside the
    bracket) is never flagged."""
    from scripts.graftlint.passes.unfenced_timing import UnfencedTimingPass

    problems = _check(UnfencedTimingPass(), tmp_path, """\
        import time
        import jax
        import numpy as np

        from flink_ml_tpu.utils.profiler import fenced_call

        run = jax.jit(lambda x: x * 2)

        def measure_probe(x):
            t0 = time.perf_counter()
            y = run(x)
            np.asarray(y)
            return time.perf_counter() - t0

        def measure_get(x):
            t0 = time.perf_counter()
            y = run(x)
            jax.device_get(y)
            return time.perf_counter() - t0

        def measure_fenced(x):
            t0 = time.perf_counter()
            y, s = fenced_call(run, x)
            return time.perf_counter() - t0

        def measure_host_only(rows):
            t0 = time.perf_counter()
            total = sum(range(rows))
            return time.perf_counter() - t0
        """)
    assert problems == []


def test_unfenced_timing_covers_decorator_and_direct_jit(tmp_path):
    """@jax.jit / @partial(jax.jit, ...) defs and a direct
    jax.jit(fn)(args) invocation are all jitted calls."""
    from scripts.graftlint.passes.unfenced_timing import UnfencedTimingPass

    problems = _check(UnfencedTimingPass(), tmp_path, """\
        import time
        from functools import partial

        import jax

        @jax.jit
        def step(x):
            return x + 1

        @partial(jax.jit, donate_argnums=(0,))
        def step2(x):
            return x + 2

        def measure_decorated(x):
            t0 = time.perf_counter()
            y = step(x)
            return time.perf_counter() - t0

        def measure_partial(x):
            t0 = time.perf_counter()
            y = step2(x)
            return time.perf_counter() - t0

        def measure_direct(x):
            t0 = time.perf_counter()
            y = jax.jit(lambda v: v * 3)(x)
            return time.perf_counter() - t0
        """)
    assert len(problems) == 3
    assert {p.symbol for p in problems} == {
        "measure_decorated", "measure_partial", "measure_direct"}


def test_unfenced_timing_nested_defs_are_their_own_scope(tmp_path):
    """A nested helper's bracket reports ONCE (in its own scope), and a
    jitted call inside a never-called nested def does not poison the
    enclosing function's host-only bracket."""
    from scripts.graftlint.passes.unfenced_timing import UnfencedTimingPass

    problems = _check(UnfencedTimingPass(), tmp_path, """\
        import time
        import jax

        run = jax.jit(lambda x: x * 2)

        def outer_with_bad_helper(x):
            def measure(x):
                t0 = time.perf_counter()
                y = run(x)
                return time.perf_counter() - t0

            return measure(x)

        def outer_host_bracket(x, items):
            t0 = time.perf_counter()

            def helper(v):
                return run(v)          # defined, never called in-bracket

            total = sum(items)
            return time.perf_counter() - t0
        """)
    assert len(problems) == 1
    assert problems[0].symbol == "outer_with_bad_helper.measure"


def test_unfenced_timing_scope_and_repo_clean():
    """Scope-fixed to the trees that publish measurements (bench.py +
    obs/), and both are clean — the consolidation satellite actually
    routed the hand-rolled copies through fenced_call."""
    from scripts.graftlint.passes.unfenced_timing import UnfencedTimingPass

    p = UnfencedTimingPass()
    assert p.scope_fixed
    assert set(p.roots) == {"bench.py", "flink_ml_tpu/obs"}
    project = Project(repo=REPO)
    assert [f.render() for f in p.run(project)] == []
    # the walk genuinely visited both roots
    scanned = {os.path.relpath(s, REPO) for s in project.scanned}
    assert "bench.py" in scanned
    assert any(s.startswith(os.path.join("flink_ml_tpu", "obs"))
               for s in scanned)


def test_elastic_module_visited_by_lock_and_host_sync_passes():
    """ISSUE 15: the elastic coordinator joined the scanned surfaces.
    ``lock-discipline`` roots at the whole package — assert the walk
    genuinely VISITS ``parallel/elastic.py`` (the lease table computes
    under an RLock and must never block there: an expire/poll holding
    the lock across a device_put or queue op would stall the training
    loop at every chunk boundary) and that ``host-sync`` — whose roots
    include ``flink_ml_tpu/parallel`` — sees it too; both must report
    it clean."""
    from scripts.graftlint.passes.host_sync import SCAN_ROOTS

    assert "flink_ml_tpu/parallel" in SCAN_ROOTS
    assert "flink_ml_tpu" in LockDisciplinePass.roots
    rel = os.path.join("flink_ml_tpu", "parallel", "elastic.py")
    project = Project(repo=REPO)
    lock_visited = {
        os.path.relpath(m.path, REPO)
        for m in project.iter_modules(
            [os.path.join(REPO, r) for r in LockDisciplinePass.roots])}
    assert rel in lock_visited, "lock-discipline never visits elastic.py"
    sync_visited = {
        os.path.relpath(m.path, REPO)
        for m in project.iter_modules(
            [os.path.join(REPO, r) for r in SCAN_ROOTS])}
    assert rel in sync_visited, "host-sync never visits elastic.py"
    mod = project.module(os.path.join(REPO, rel))
    assert LockDisciplinePass().check_module(mod, project) == []
    assert HostSyncPass().check_module(mod, project) == []


def test_autoscale_modules_visited_by_host_sync_and_atomic_writes():
    """ISSUE 17: ``flink_ml_tpu/autoscale/`` joined both scanned
    surfaces.  Assert host-sync's SCAN_ROOTS and atomic-writes'
    DURABLE_MODULES carry the root, that the walks genuinely VISIT all
    four control-plane modules (a root that matches nothing keeps a
    rule from ever firing — the visits-the-modules stance), and that
    every module is clean under host-sync, atomic-writes (the
    placement publish is tmp -> os.replace), and lock-discipline (the
    store writes its file OUTSIDE the lock with a generation re-check
    on re-acquire)."""
    from scripts.graftlint.passes.atomic_writes import DURABLE_MODULES
    from scripts.graftlint.passes.host_sync import SCAN_ROOTS

    assert "flink_ml_tpu/autoscale" in SCAN_ROOTS
    assert "flink_ml_tpu/autoscale" in DURABLE_MODULES
    modules = [os.path.join("flink_ml_tpu", "autoscale", f)
               for f in ("placement.py", "signals.py", "policy.py",
                         "controller.py")]
    project = Project(repo=REPO)
    sync_visited = {
        os.path.relpath(m.path, REPO)
        for m in project.iter_modules(
            [os.path.join(REPO, r) for r in SCAN_ROOTS])}
    durable_visited = {
        os.path.relpath(m.path, REPO)
        for m in project.iter_modules(
            [os.path.join(REPO, r) for r in AtomicWritesPass.roots])}
    for rel in modules:
        assert rel in sync_visited, f"host-sync never visits {rel}"
        assert rel in durable_visited, \
            f"atomic-writes never visits {rel}"
        mod = project.module(os.path.join(REPO, rel))
        assert HostSyncPass().check_module(mod, project) == []
        assert AtomicWritesPass().check_module(mod, project) == []
        assert LockDisciplinePass().check_module(mod, project) == []


def test_kernels_modules_visited_by_host_sync():
    """ISSUE 18: ``flink_ml_tpu/kernels/`` joined the host-sync scan —
    the quantize module's dequant helpers trace into every int8 serving
    program, so a host fetch in a step-shaped helper there would fence
    every consumer's dispatch stream.  Assert SCAN_ROOTS carries the
    root, the walk genuinely VISITS the kernel modules (quantize
    included — a root that matches nothing keeps the rule from ever
    firing), and every one is clean: calibration's host numpy lives at
    publish/bind time, never inside a step body."""
    from scripts.graftlint.passes.host_sync import SCAN_ROOTS

    assert "flink_ml_tpu/kernels" in SCAN_ROOTS
    modules = [os.path.join("flink_ml_tpu", "kernels", f)
               for f in ("quantize.py", "registry.py", "aot.py")]
    project = Project(repo=REPO)
    visited = {
        os.path.relpath(m.path, REPO)
        for m in project.iter_modules(
            [os.path.join(REPO, r) for r in SCAN_ROOTS])}
    for rel in modules:
        assert rel in visited, f"host-sync never visits {rel}"
        mod = project.module(os.path.join(REPO, rel))
        assert HostSyncPass().check_module(mod, project) == []


def test_failover_module_visited_by_lock_and_host_sync_passes():
    """ISSUE 20: ``serving/failover.py`` joined both scanned surfaces
    through the existing roots (``flink_ml_tpu/serving`` for host-sync,
    the whole package for lock-discipline).  Assert the walks genuinely
    VISIT the module (a root that matches nothing keeps a rule from
    ever firing — the visits-the-modules stance) and that it is clean
    under both: the failover driver's requeue + re-placement runs
    INLINE on the scheduler's one serve loop when a dispatch fault
    fires, so a host sync there would stall every tenant during the
    exact window the failover exists to keep short, and the lease
    table computes under its lock but fires tracer instants and
    recoveries outside it."""
    from scripts.graftlint.passes.host_sync import SCAN_ROOTS

    assert "flink_ml_tpu/serving" in SCAN_ROOTS
    assert "flink_ml_tpu" in LockDisciplinePass.roots
    rel = os.path.join("flink_ml_tpu", "serving", "failover.py")
    project = Project(repo=REPO)
    sync_visited = {
        os.path.relpath(m.path, REPO)
        for m in project.iter_modules(
            [os.path.join(REPO, r) for r in SCAN_ROOTS])}
    assert rel in sync_visited, "host-sync never visits failover.py"
    lock_visited = {
        os.path.relpath(m.path, REPO)
        for m in project.iter_modules(
            [os.path.join(REPO, r) for r in LockDisciplinePass.roots])}
    assert rel in lock_visited, \
        "lock-discipline never visits failover.py"
    mod = project.module(os.path.join(REPO, rel))
    assert HostSyncPass().check_module(mod, project) == []
    assert LockDisciplinePass().check_module(mod, project) == []


def test_retrieval_modules_visited_by_host_sync():
    """ISSUE 19: ``flink_ml_tpu/retrieval/`` joined the host-sync scan —
    the fused retrieve stage traces into every index tenant's serving
    program through the shared plan jit, so a host sync in a
    step-shaped helper there would stall the multiplexed serve loop
    exactly like one in ``serving/`` would.  Assert SCAN_ROOTS carries
    the root, the walk genuinely VISITS the retrieval modules (a root
    that matches nothing keeps the rule from ever firing), and every
    one is clean: index build/re-anchor is host numpy by design, but it
    runs at build time, never inside the dispatched search."""
    from scripts.graftlint.passes.host_sync import SCAN_ROOTS

    assert "flink_ml_tpu/retrieval" in SCAN_ROOTS
    modules = [os.path.join("flink_ml_tpu", "retrieval", f)
               for f in ("ivf.py", "metrics.py")]
    project = Project(repo=REPO)
    visited = {
        os.path.relpath(m.path, REPO)
        for m in project.iter_modules(
            [os.path.join(REPO, r) for r in SCAN_ROOTS])}
    for rel in modules:
        assert rel in visited, f"host-sync never visits {rel}"
        mod = project.module(os.path.join(REPO, rel))
        assert HostSyncPass().check_module(mod, project) == []
