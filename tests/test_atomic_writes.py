"""Tier-1 wiring for scripts/check_atomic_writes.py (ISSUE 5 satellite):
the durable modules' open-for-write sites must all be write-tmp ->
os.replace atomic, and the checker itself must actually catch the
violation pattern (a guard that can't fail guards nothing)."""

import importlib.util
import os

_spec = importlib.util.spec_from_file_location(
    "check_atomic_writes",
    os.path.join(os.path.dirname(__file__), os.pardir, "scripts",
                 "check_atomic_writes.py"))
caw = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(caw)


def test_durable_modules_are_atomic():
    problems = []
    for module in caw.DURABLE_MODULES:
        problems += caw.check_file(os.path.join(caw.REPO, module))
    assert problems == []


def test_checker_flags_naked_write(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import os\n"
        "def save(path, data):\n"
        "    with open(path, 'wb') as f:\n"
        "        f.write(data)\n")
    problems = caw.check_file(str(bad))
    assert len(problems) == 1 and "half-written" in problems[0]


def test_checker_accepts_tmp_then_replace(tmp_path):
    good = tmp_path / "good.py"
    good.write_text(
        "import os\n"
        "def save(path, data):\n"
        "    tmp = path + '.tmp'\n"
        "    with open(tmp, 'wb') as f:\n"
        "        f.write(data)\n"
        "    os.replace(tmp, path)\n"
        "def save_into_dir(dirpath, data):\n"
        "    tmp = dirpath + '.tmp'\n"
        "    with open(os.path.join(tmp, 'part'), 'w') as f:\n"
        "        f.write(data)\n"
        "    os.replace(tmp, dirpath)\n")
    assert caw.check_file(str(good)) == []


def test_checker_ignores_reads(tmp_path):
    src = tmp_path / "reads.py"
    src.write_text(
        "def load(path):\n"
        "    with open(path) as f:\n"
        "        a = f.read()\n"
        "    with open(path, 'rb') as f:\n"
        "        return a, f.read()\n")
    assert caw.check_file(str(src)) == []
