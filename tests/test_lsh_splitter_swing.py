"""MinHashLSH / RandomSplitter / Swing."""

import numpy as np
import pytest

from flink_ml_tpu import Table
from flink_ml_tpu.models.feature import (
    MinHashLSH,
    MinHashLSHModel,
    RandomSplitter,
)
from flink_ml_tpu.models.recommendation import Swing


# ---------------------------------------------------------------------------
# RandomSplitter
# ---------------------------------------------------------------------------

def test_random_splitter_partitions_all_rows():
    t = Table({"x": np.arange(1000), "y": np.arange(1000) * 2.0})
    parts = RandomSplitter().set_weights(0.8, 0.2).transform(t)
    assert len(parts) == 2
    assert parts[0].num_rows + parts[1].num_rows == 1000
    # rough proportions under the default seed
    assert 700 < parts[0].num_rows < 900
    # no row lost or duplicated
    merged = np.sort(np.concatenate([parts[0]["x"], parts[1]["x"]]))
    np.testing.assert_array_equal(merged, np.arange(1000))
    # y stays aligned with x
    np.testing.assert_array_equal(parts[1]["y"], parts[1]["x"] * 2.0)


def test_random_splitter_deterministic_under_seed():
    t = Table({"x": np.arange(100)})
    a = RandomSplitter().set_seed(7).set_weights(1.0, 1.0).transform(t)
    b = RandomSplitter().set_seed(7).set_weights(1.0, 1.0).transform(t)
    np.testing.assert_array_equal(a[0]["x"], b[0]["x"])


def test_random_splitter_rejects_bad_weights():
    # the validator sits on the param, so the generic set() path rejects too
    with pytest.raises(ValueError, match="invalid value"):
        RandomSplitter().set_weights(1.0, -1.0)
    with pytest.raises(ValueError, match="invalid value"):
        RandomSplitter().set_weights(1.0)
    with pytest.raises(ValueError, match="invalid value"):
        RandomSplitter().set(RandomSplitter.WEIGHTS, (1.0, -1.0))


def test_swing_rejects_negative_smoothing():
    with pytest.raises(ValueError, match="invalid value"):
        Swing().set_alpha1(-2)
    with pytest.raises(ValueError, match="invalid value"):
        Swing().set_alpha2(-1)
    with pytest.raises(ValueError, match="invalid value"):
        Swing().set_beta(-0.5)


def test_random_splitter_three_way():
    t = Table({"x": np.arange(600)})
    parts = RandomSplitter().set_weights(1.0, 1.0, 1.0).transform(t)
    assert len(parts) == 3
    assert sum(p.num_rows for p in parts) == 600


# ---------------------------------------------------------------------------
# MinHashLSH
# ---------------------------------------------------------------------------

def _binary_table(rows):
    return Table({"features": np.asarray(rows, np.float64)})


def test_minhash_identical_vectors_identical_signatures():
    t = _binary_table([[1, 0, 1, 0, 1], [1, 0, 1, 0, 1], [0, 1, 0, 1, 0]])
    model = (MinHashLSH().set_num_hash_tables(3)
             .set_num_hash_functions_per_table(2).fit(t))
    sig = np.asarray(model.transform(t)[0]["output"])
    assert sig.shape == (3, 3, 2)
    np.testing.assert_array_equal(sig[0], sig[1])
    assert not np.array_equal(sig[0], sig[2])


def test_minhash_signature_is_min_of_active_hashes():
    model = (MinHashLSH().set_num_hash_tables(1)
             .set_num_hash_functions_per_table(1).set_seed(3)
             .fit(_binary_table([[1, 1, 0]])))
    a, b = model._coeff[0]
    P = 2038074743
    t = _binary_table([[1, 1, 0]])
    sig = np.asarray(model.transform(t)[0]["output"]).ravel()[0]
    expected = min(((1 + 0) * a + b) % P, ((1 + 1) * a + b) % P)
    assert sig == expected


def test_minhash_rejects_empty_vectors():
    model = MinHashLSH().fit(_binary_table([[1, 0]]))
    with pytest.raises(ValueError, match="nonzero"):
        model.transform(_binary_table([[0, 0]]))


def test_minhash_nearest_neighbors_ranks_by_jaccard():
    rows = [
        [1, 1, 1, 1, 0, 0, 0, 0],    # jaccard dist to key: 0
        [1, 1, 1, 0, 0, 0, 0, 0],    # 0.25
        [0, 0, 0, 0, 1, 1, 1, 1],    # 1.0
    ]
    t = _binary_table(rows)
    model = (MinHashLSH().set_num_hash_tables(5).fit(t))
    key = np.asarray([1, 1, 1, 1, 0, 0, 0, 0], np.float64)
    out = model.approx_nearest_neighbors(t, key, k=2)
    dist = np.asarray(out["distCol"])
    np.testing.assert_allclose(dist, [0.0, 0.25])


def test_minhash_similarity_join():
    ta = Table({"features": np.asarray(
        [[1, 1, 1, 0, 0], [0, 0, 1, 1, 1]], np.float64),
        "id": np.asarray([10, 11])})
    tb = Table({"features": np.asarray(
        [[1, 1, 1, 0, 0], [1, 0, 0, 0, 1]], np.float64),
        "id": np.asarray([20, 21])})
    model = (MinHashLSH().set_num_hash_tables(8).fit(ta))
    joined = model.approx_similarity_join(ta, tb, threshold=0.5,
                                          id_col="id")
    pairs = set(zip(np.asarray(joined["idA"]).tolist(),
                    np.asarray(joined["idB"]).tolist()))
    assert (10, 20) in pairs            # identical rows always join
    for d in np.asarray(joined["distCol"]):
        assert d < 0.5


def test_minhash_save_load(tmp_path):
    t = _binary_table([[1, 0, 1], [0, 1, 1]])
    model = (MinHashLSH().set_num_hash_tables(2).set_seed(5).fit(t))
    path = str(tmp_path / "lsh")
    model.save(path)
    loaded = MinHashLSHModel.load(path)
    np.testing.assert_array_equal(
        np.asarray(model.transform(t)[0]["output"]),
        np.asarray(loaded.transform(t)[0]["output"]))


# ---------------------------------------------------------------------------
# Swing
# ---------------------------------------------------------------------------

def test_swing_hand_computed_two_items():
    # u0:{i0,i1} u1:{i0,i1} u2:{i0}; alpha1=0, beta=1 -> w = 1/|I_u|
    # sim(i0,i1): single user pair {u0,u1}, |I_u0 ∩ I_u1| = 2, alpha2=1
    #   -> 0.5 * 0.5 / (1 + 2) = 1/12
    t = Table({
        "user": np.asarray([0, 0, 1, 1, 2]),
        "item": np.asarray(["i0", "i1", "i0", "i1", "i0"]),
    })
    out = (Swing().set_min_user_behavior(1).set_alpha1(0).set_alpha2(1)
           .set_beta(1.0).transform(t)[0])
    items = np.asarray(out["item"])
    i0 = int(np.flatnonzero(items == "i0")[0])
    assert out["similar_items"][i0] == ["i1"]
    np.testing.assert_allclose(out["scores"][i0], [1.0 / 12.0], rtol=1e-5)


def test_swing_symmetry_and_topk():
    rng = np.random.default_rng(0)
    users = rng.integers(0, 30, size=400)
    items = rng.integers(0, 8, size=400)
    t = Table({"user": users, "item": items})
    out = (Swing().set_min_user_behavior(1).set_k(3).transform(t)[0])
    assert out.num_rows == len(np.unique(items))
    for j in range(out.num_rows):
        assert len(out["similar_items"][j]) <= 3
        scores = out["scores"][j]
        assert all(scores[i] >= scores[i + 1]
                   for i in range(len(scores) - 1))


def test_swing_min_user_behavior_filters():
    # u2 has only 1 interaction; with min=2 it contributes nothing
    t = Table({
        "user": np.asarray([0, 0, 1, 1, 2]),
        "item": np.asarray([0, 1, 0, 1, 0]),
    })
    full = (Swing().set_min_user_behavior(1).set_alpha1(0).set_alpha2(1)
            .set_beta(1.0).transform(t)[0])
    filt = (Swing().set_min_user_behavior(2).set_alpha1(0).set_alpha2(1)
            .set_beta(1.0).transform(t)[0])
    # same pair survives (u0,u1 both have 2 interactions)
    i0 = 0
    np.testing.assert_allclose(filt["scores"][i0], full["scores"][i0])


def test_swing_no_common_users_no_similarity():
    t = Table({
        "user": np.asarray([0, 1]),
        "item": np.asarray([0, 1]),
    })
    out = Swing().set_min_user_behavior(1).transform(t)[0]
    assert out["similar_items"][0] == []
    assert out["similar_items"][1] == []


def test_swing_chunked_kernel_equals_unchunked():
    """The user-chunked pair kernel must give identical scores whatever
    the chunk size (incl. non-dividing chunks that pad)."""
    import jax.numpy as jnp

    from flink_ml_tpu.models.recommendation.swing import _swing_scores

    rng = np.random.default_rng(3)
    B = jnp.asarray((rng.random((37, 6)) < 0.3).astype(np.float32))
    full = _swing_scores(B, jnp.float32(15), jnp.float32(0),
                         jnp.float32(0.3), 64)     # one chunk
    for chunk in (4, 16, 37):
        part = _swing_scores(B, jnp.float32(15), jnp.float32(0),
                             jnp.float32(0.3), chunk)
        np.testing.assert_allclose(np.asarray(part), np.asarray(full),
                                   rtol=1e-5, atol=1e-7)
