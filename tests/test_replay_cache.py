"""Decoded replay cache: multi-epoch out-of-core streams pay the host
decode once (the TPU-lifted analog of the reference's ReplayOperator
round-0 cache, ``iteration/operator/ReplayOperator.java:62-311``).

Exactness is the bar everywhere: a cached fit must produce bit-identical
parameters to the uncached fit — the cache stores the decode *outputs*,
so any divergence is a routing bug, not noise."""

import numpy as np
import pytest

from flink_ml_tpu.data.datacache import DataCacheReader, DataCacheWriter
from flink_ml_tpu.data.replay_cache import (
    DecodedReplayCache,
    default_ram_budget,
)
from flink_ml_tpu.models.common.losses import logistic_loss
from flink_ml_tpu.models.common.sgd import SGDConfig, sgd_fit_outofcore


# ------------------------------------------------------------------ unit


def test_cache_offer_finish_replay_roundtrip():
    cache = DecodedReplayCache(1 << 20)
    batches = [(np.full((4,), i, np.float32), np.full((2,), -i, np.int32))
               for i in range(5)]
    # out-of-order offers (decode workers finish in any order)
    for i in (3, 0, 4, 1, 2):
        cache.offer(i, batches[i])
    cache.finish(5)
    assert cache.ready and cache.prefix_batches == 5
    out = list(cache.replay())
    assert len(out) == 5
    for i, (a, b) in enumerate(out):
        np.testing.assert_array_equal(a, batches[i][0])
        np.testing.assert_array_equal(b, batches[i][1])
    # replay from an offset
    assert len(list(cache.replay(3))) == 2


def test_cache_budget_keeps_contiguous_prefix():
    one = np.zeros((256,), np.float32)  # 1 KiB per batch
    cache = DecodedReplayCache(3 * one.nbytes)
    for i in range(10):
        cache.offer(i, (one,))
    cache.finish(10)
    assert cache.prefix_batches == 3
    assert cache.n_batches == 10
    assert cache.cached_bytes == 3 * one.nbytes


def test_cache_gap_truncates_prefix():
    one = np.zeros((8,), np.float32)
    cache = DecodedReplayCache(1 << 20)
    for i in (0, 1, 3, 4):   # 2 never arrives under budget
        cache.offer(i, (one,))
    cache.finish(5)
    assert cache.prefix_batches == 2
    assert len(list(cache.replay())) == 2
    # freed stragglers (3, 4) must not count toward held bytes
    assert cache.cached_bytes == 2 * one.nbytes


def test_offer_materializes_disk_backed_views(tmp_path):
    """A memmap slice teed into the cache must be copied into RAM: the
    budget counts anonymous RAM, and replay must not fault to disk."""
    path = str(tmp_path / "col.bin")
    np.arange(32, dtype=np.float32).tofile(path)
    mm = np.memmap(path, dtype=np.float32, mode="r", shape=(32,))
    fresh = np.ones((4,), np.float32)
    view_of_fresh = fresh[:2]

    cache = DecodedReplayCache(1 << 20)
    cache.offer(0, (mm[4:8], np.asarray(mm[8:12]), fresh, view_of_fresh))
    cache.finish(1)
    a, b, c, d = next(iter(cache.replay()))
    for arr in (a, b):
        base = arr
        while isinstance(base, np.ndarray):
            assert not isinstance(base, np.memmap)
            base = base.base
    np.testing.assert_array_equal(a, [4, 5, 6, 7])
    np.testing.assert_array_equal(b, [8, 9, 10, 11])
    assert c is fresh                      # decode-fresh stays zero-copy
    assert d.base is fresh                 # RAM views stay views


def test_cache_guards():
    with pytest.raises(ValueError, match="ram_budget"):
        DecodedReplayCache(-1)
    cache = DecodedReplayCache(0)
    with pytest.raises(RuntimeError, match="not finished"):
        cache.prefix_batches
    with pytest.raises(RuntimeError, match="not finished"):
        next(cache.replay())
    assert default_ram_budget() > 0


# ----------------------------------------------------------- integration


def _write_cache(tmp_path, n=2048, d=16, seed=3):
    rng = np.random.default_rng(seed)
    true_w = rng.normal(size=(d,))
    cache = str(tmp_path / "cache")
    writer = DataCacheWriter(cache, segment_rows=1024)
    for start in range(0, n, 512):
        X = rng.normal(size=(512, d)).astype(np.float32)
        y = (X @ true_w > 0).astype(np.float32)
        writer.append({"features": X, "label": y})
    writer.finish()
    return cache


def _fit(cache, calls, **kw):
    def make_reader():
        calls.append(1)
        return DataCacheReader(cache, batch_rows=256)

    info = {}
    state, log = sgd_fit_outofcore(
        logistic_loss, make_reader, num_features=16,
        config=SGDConfig(learning_rate=0.5, max_epochs=4, tol=0.0),
        stream_info=info, **kw)
    return state, log, info


def test_full_replay_skips_reader_and_matches_uncached(tmp_path):
    cache = _write_cache(tmp_path)
    calls_off, calls_on = [], []
    s_off, log_off, _ = _fit(cache, calls_off, cache_decoded=False)
    s_on, log_on, info = _fit(cache, calls_on, cache_decoded="auto")

    np.testing.assert_array_equal(s_on.coefficients, s_off.coefficients)
    assert s_on.intercept == s_off.intercept
    assert log_on == log_off
    assert len(calls_off) == 4          # reader rebuilt every epoch
    # under "auto" the replay guard still builds a reader per epoch for
    # its one-batch fingerprint probe (reader-free replay is the forced
    # cache_decoded=True mode, covered below)
    assert len(calls_on) == 4
    assert info["decoded_cache_batches"] == 8   # 2048 / 256
    assert info["decoded_cache_total_batches"] == 8
    assert info["decoded_cache_bytes"] > 0
    assert len(info["epoch_seconds"]) == 4


def test_partial_prefix_replays_head_redecodes_tail(tmp_path):
    cache = _write_cache(tmp_path)
    # one decoded batch: 256 rows x (16 feat + label + weight) f32
    batch_bytes = 256 * 18 * 4
    calls, calls_off = [], []
    s_off, _, _ = _fit(cache, calls_off, cache_decoded=False)
    s_on, _, info = _fit(cache, calls, cache_decoded="auto",
                         decoded_ram_budget=3 * batch_bytes)

    np.testing.assert_array_equal(s_on.coefficients, s_off.coefficients)
    assert 0 < info["decoded_cache_batches"] < 8
    assert len(calls) == 4              # tail still needs the reader


def test_auto_stays_off_for_plain_iterators(tmp_path):
    cache = _write_cache(tmp_path)
    calls = []

    def make_reader():
        calls.append(1)
        return iter(DataCacheReader(cache, batch_rows=256))  # no protocol

    info = {}
    sgd_fit_outofcore(
        logistic_loss, make_reader, num_features=16,
        config=SGDConfig(learning_rate=0.5, max_epochs=3, tol=0.0),
        stream_info=info)
    assert len(calls) == 3
    assert info["decoded_cache_batches"] == 0


def test_forced_cache_works_for_plain_iterators(tmp_path):
    cache = _write_cache(tmp_path)
    calls, calls_off = [], []
    s_off, _, _ = _fit(cache, calls_off, cache_decoded=False)

    def make_reader():
        calls.append(1)
        return iter(DataCacheReader(cache, batch_rows=256))

    info = {}
    s_on, _ = sgd_fit_outofcore(
        logistic_loss, make_reader, num_features=16,
        config=SGDConfig(learning_rate=0.5, max_epochs=4, tol=0.0),
        cache_decoded=True, stream_info=info)
    np.testing.assert_array_equal(s_on.coefficients, s_off.coefficients)
    assert len(calls) == 1              # full replay never re-reads


def test_cache_decoded_validated(tmp_path):
    cache = _write_cache(tmp_path, n=512)
    with pytest.raises(ValueError, match="cache_decoded"):
        sgd_fit_outofcore(
            logistic_loss,
            lambda: DataCacheReader(cache, batch_rows=256),
            num_features=16, config=SGDConfig(max_epochs=2),
            cache_decoded="yes")


def test_recording_under_multiworker_decode_is_exact(tmp_path):
    """prefetch_workers=2: decode (and the cache tee) runs on two
    threads, so offers arrive out of order — replayed epochs must still
    be exact and in source order."""
    cache = _write_cache(tmp_path)
    calls_off, calls_on = [], []
    s_off, log_off, _ = _fit(cache, calls_off, cache_decoded=False,
                             prefetch_workers=2)
    s_on, log_on, info = _fit(cache, calls_on, cache_decoded="auto",
                              prefetch_workers=2)
    np.testing.assert_array_equal(s_on.coefficients, s_off.coefficients)
    assert log_on == log_off
    assert info["decoded_cache_batches"] == 8


class _EpochVaryingReader:
    """Cursor-protocol reader over a PRE-PERMUTED copy of the data —
    models readers that legitimately re-shuffle per epoch (the documented
    'vary segment order per epoch' posture)."""

    def __init__(self, X, y, batch_rows, perm):
        self.X, self.y = X[perm], y[perm]
        self.batch_rows = batch_rows
        self.total_rows = len(y)
        self._pos = 0

    def seek(self, row):
        self._pos = row

    def __iter__(self):
        while self._pos < self.total_rows:
            s = self._pos
            e = min(s + self.batch_rows, self.total_rows)
            self._pos = e
            yield {"features": self.X[s:e], "label": self.y[s:e]}


def test_guard_drops_cache_for_epoch_varying_reader():
    """A reader that reshuffles per epoch speaks the cursor protocol, so
    "auto" records epoch 0 — but the replay guard's first-batch digest
    must detect the new order each later epoch and drop the cache, so
    training sees exactly the data the reader produced (not frozen
    epoch-0 batches)."""
    rng = np.random.default_rng(9)
    true_w = rng.normal(size=8)
    X = rng.normal(size=(1024, 8)).astype(np.float32)
    y = (X @ true_w > 0).astype(np.float32)

    def run(cache_mode):
        perms = iter(np.random.default_rng(31).permuted(
            np.tile(np.arange(1024), (4, 1)), axis=1))
        info = {}
        state, log = sgd_fit_outofcore(
            logistic_loss,
            lambda: _EpochVaryingReader(X, y, 256, next(perms)),
            num_features=8,
            config=SGDConfig(learning_rate=0.5, max_epochs=4, tol=0.0),
            cache_decoded=cache_mode, stream_info=info)
        return state, log, info

    s_off, log_off, _ = run(False)
    s_auto, log_auto, info = run("auto")
    np.testing.assert_array_equal(s_auto.coefficients, s_off.coefficients)
    assert log_auto == log_off
    assert info["decoded_cache_batches"] == 0   # the replay got dropped
    assert info["decoded_cache_guard_tripped"] is True
    # one-way latch: after the first drop, recording stops (a varying
    # reader would be dropped again every epoch)
    assert info["decoded_cache_recorded_epochs"] == 1


def test_estimator_forwards_stream_kwargs(tmp_path):
    from flink_ml_tpu.models.classification.logisticregression import (
        LogisticRegression,
    )

    cache = _write_cache(tmp_path, n=1024)
    info = {}
    est = (LogisticRegression().set_learning_rate(0.5).set_max_iter(3)
           .set_tol(0.0))
    est.fit_outofcore(
        lambda: DataCacheReader(cache, batch_rows=256),
        num_features=16, cache_decoded=False, stream_info=info)
    assert info["decoded_cache_batches"] == 0

    est.fit_outofcore(
        lambda: DataCacheReader(cache, batch_rows=256),
        num_features=16, stream_info=info)
    assert info["decoded_cache_batches"] == 4   # auto engaged


def test_block_cache_shuffled_reader_exact_and_decode_once(tmp_path):
    """Block-keyed mode: a ShuffledCacheReader stream under "auto" is
    bit-identical to the uncached fit (cached decode outputs ARE the
    decode outputs), every block lands in the cache, and each epoch
    still sees its own permutation."""
    from flink_ml_tpu.data.datacache import ShuffledCacheReader

    cache = _write_cache(tmp_path)
    orders = []

    def make_reader(epoch):
        r = ShuffledCacheReader(cache, batch_rows=256, seed=3, epoch=epoch)
        orders.append(r.block_order)
        return r

    def run(mode):
        info = {}
        state, log = sgd_fit_outofcore(
            logistic_loss, make_reader, num_features=16,
            config=SGDConfig(learning_rate=0.5, max_epochs=4, tol=0.0),
            cache_decoded=mode, stream_info=info)
        return state, log, info

    s_off, log_off, _ = run(False)
    orders_off = list(orders)
    orders.clear()
    s_on, log_on, info = run("auto")
    np.testing.assert_array_equal(s_on.coefficients, s_off.coefficients)
    assert log_on == log_off
    assert orders == orders_off                 # same permutations seen
    assert len(set(orders)) == 4                # ...and they differ/epoch
    assert info["decoded_cache_mode"] == "block"
    assert info["decoded_cache_batches"] == 8   # every block cached
    assert info["decoded_cache_bytes"] > 0


def test_block_cache_respects_budget_and_stays_exact(tmp_path):
    from flink_ml_tpu.data.datacache import ShuffledCacheReader

    cache = _write_cache(tmp_path)
    batch_bytes = 256 * 18 * 4

    def run(**kw):
        info = {}
        state, _ = sgd_fit_outofcore(
            logistic_loss,
            lambda epoch: ShuffledCacheReader(cache, batch_rows=256,
                                              seed=3, epoch=epoch),
            num_features=16,
            config=SGDConfig(learning_rate=0.5, max_epochs=3, tol=0.0),
            stream_info=info, **kw)
        return state, info

    s_on, info = run(decoded_ram_budget=3 * batch_bytes)
    s_off, _ = run(cache_decoded=False)
    assert 0 < info["decoded_cache_batches"] <= 3
    # budget-limited block cache is still bit-exact
    np.testing.assert_array_equal(s_on.coefficients, s_off.coefficients)


def test_block_cache_multiworker_decode_exact(tmp_path):
    """Block mode under prefetch_workers=2: concurrent offer/get on the
    keyed cache stays bit-exact vs the uncached fit."""
    from flink_ml_tpu.data.datacache import ShuffledCacheReader

    cache = _write_cache(tmp_path)

    def run(mode):
        info = {}
        state, log = sgd_fit_outofcore(
            logistic_loss,
            lambda epoch: ShuffledCacheReader(cache, batch_rows=256,
                                              seed=6, epoch=epoch),
            num_features=16,
            config=SGDConfig(learning_rate=0.5, max_epochs=4, tol=0.0),
            cache_decoded=mode, stream_info=info, prefetch_workers=2)
        return state, log, info

    s_off, log_off, _ = run(False)
    s_on, log_on, info = run("auto")
    np.testing.assert_array_equal(s_on.coefficients, s_off.coefficients)
    assert log_on == log_off
    assert info["decoded_cache_mode"] == "block"


def test_block_cache_contract_violation_raises(tmp_path):
    """A reader that claims block-addressability but changes a block's
    content between epochs must fail loudly at the anchor check."""
    from flink_ml_tpu.data.datacache import DataCacheReader

    cache = _write_cache(tmp_path, n=1024)

    class LyingReader:
        epoch_varying = True

        def __init__(self, epoch):
            self._inner = DataCacheReader(cache, batch_rows=256)
            self._epoch = epoch
            self.batch_rows = 256
            self.total_rows = self._inner.total_rows
            self.block_order = tuple(range(4))

        def seek(self, c):
            self._inner.seek(c)

        def __iter__(self):
            for b in self._inner:
                # content drifts with the epoch — violates the contract
                yield {"features": b["features"] + self._epoch,
                       "label": b["label"]}

    with pytest.raises(ValueError, match="block_order contract"):
        sgd_fit_outofcore(
            logistic_loss, lambda epoch: LyingReader(epoch),
            num_features=16,
            config=SGDConfig(learning_rate=0.5, max_epochs=3, tol=0.0))


def test_mixed_ell_stream_cached_matches_uncached(tmp_path):
    """The ELL streaming decode (layout build) is the expensive path the
    cache exists for — exactness across cache on/off on the mixed
    layout."""
    rng = np.random.default_rng(5)
    d = 1 << 12
    cache = str(tmp_path / "mixed")
    writer = DataCacheWriter(cache, segment_rows=1024)
    for start in range(0, 2048, 512):
        dense = rng.normal(size=(512, 4)).astype(np.float32)
        idx = rng.integers(8, d, size=(512, 6)).astype(np.int32)
        y = rng.integers(0, 2, size=512).astype(np.float32)
        idx[:, 0] = np.where(y == 1, 1, 2)
        writer.append({"features_dense": dense, "features_indices": idx,
                       "label": y})
    writer.finish()

    def run(**kw):
        info = {}
        state, _ = sgd_fit_outofcore(
            logistic_loss,
            lambda: DataCacheReader(cache, batch_rows=256),
            num_features=d,
            dense_key="features_dense", indices_key="features_indices",
            config=SGDConfig(learning_rate=0.5, max_epochs=3, tol=0.0),
            stream_info=info, **kw)
        return state, info

    s_off, info_off = run(cache_decoded=False)
    s_on, info_on = run(cache_decoded="auto")
    assert info_off["decoded_cache_batches"] == 0
    assert info_on["decoded_cache_batches"] == 8
    assert info_on["impl"] == info_off["impl"]
    np.testing.assert_array_equal(s_on.coefficients, s_off.coefficients)


class _TailShuffleReader(_EpochVaryingReader):
    """Keeps batch 0 IDENTICAL every epoch but permutes the tail — the
    adversary the one-batch guard cannot see (ADVICE r4).  Seekable, so
    the guard's second mid-stream probe must catch it."""

    def __init__(self, X, y, batch_rows, perm):
        keep = np.arange(batch_rows)
        tail = batch_rows + perm
        super().__init__(X, y, batch_rows,
                         np.concatenate([keep, tail]))


def test_guard_mid_probe_catches_tail_shuffle():
    """A seekable reader whose first batch is epoch-stable but whose tail
    reshuffles: the second (mid-stream) probe drops the cache, so the fit
    equals the uncached fit instead of training on frozen epoch-0
    batches."""
    rng = np.random.default_rng(13)
    true_w = rng.normal(size=8)
    X = rng.normal(size=(1024, 8)).astype(np.float32)
    y = (X @ true_w > 0).astype(np.float32)

    def run(cache_mode):
        perms = iter(np.random.default_rng(37).permuted(
            np.tile(np.arange(1024 - 256), (4, 1)), axis=1))
        info = {}
        state, log = sgd_fit_outofcore(
            logistic_loss,
            lambda: _TailShuffleReader(X, y, 256, next(perms)),
            num_features=8,
            config=SGDConfig(learning_rate=0.5, max_epochs=4, tol=0.0),
            cache_decoded=cache_mode, stream_info=info)
        return state, log, info

    s_off, log_off, _ = run(False)
    s_auto, log_auto, info = run("auto")
    np.testing.assert_array_equal(s_auto.coefficients, s_off.coefficients)
    assert log_auto == log_off
    assert info["decoded_cache_guard_tripped"] is True


def test_offer_copies_small_views_of_large_bases():
    """A cached entry that is a small view of a big RAM buffer must not
    retain the base (the budget would count the view's bytes while real
    RAM held the whole base, ADVICE r4); exact-sized arrays stay
    zero-copy."""
    cache = DecodedReplayCache(4 << 20)
    big = np.arange(1 << 18, dtype=np.float32)     # 1 MB base
    view = big[:16]                                 # 64 B view
    fresh = np.arange(64, dtype=np.float32)         # no base
    cache.offer(0, (view, fresh))
    stored_view, stored_fresh = cache._entries[0]
    assert stored_view.base is None                 # copied off the base
    np.testing.assert_array_equal(stored_view, view)
    assert stored_fresh is fresh                    # zero-copy kept
    # a view that IS most of its base stays zero-copy (no silent 2x RAM)
    most = big[: (1 << 18) - 8]
    cache.offer(1, (most,))
    assert cache._entries[1][0].base is big


class _ShortBlockReader:
    """Declares a block_order it does not honor: yields one batch fewer —
    the silent-truncation adversary (ADVICE r4).  Seekless on purpose."""

    epoch_varying = True

    def __init__(self, X, y, batch_rows, epoch):
        self.batch_rows = batch_rows
        self.total_rows = len(y)
        order = np.random.default_rng(epoch).permutation(
            len(y) // batch_rows)
        self.block_order = tuple(int(b) for b in order)
        self.X, self.y = X, y

    def __iter__(self):
        for b in self.block_order[:-1]:             # one short
            s = b * self.batch_rows
            yield {"features": self.X[s:s + self.batch_rows],
                   "label": self.y[s:s + self.batch_rows]}


def test_block_mode_short_epoch_raises():
    rng = np.random.default_rng(17)
    X = rng.normal(size=(1024, 8)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    with pytest.raises(ValueError, match="block_order promises"):
        sgd_fit_outofcore(
            logistic_loss,
            lambda epoch: _ShortBlockReader(X, y, 256, epoch),
            num_features=8,
            config=SGDConfig(learning_rate=0.5, max_epochs=2, tol=0.0),
            cache_decoded="auto")
