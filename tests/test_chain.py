"""Operator chaining (`api/chain.py`): fused segment execution is
bit-exact with the stagewise path across every ported terminal family,
chain breaks land exactly at non-chainable stages (including the
zero-row edge), dispatch count drops to one per segment, steady state
adds zero XLA lowerings across warmed buckets, f64-vs-f32 inputs share
one compiled program, save->load round trips keep the fused path exact,
and one serving endpoint runs preprocess+score per micro-batch."""

import os

import numpy as np
import pytest

from flink_ml_tpu import PipelineModel, Table
from flink_ml_tpu.api import chain
from flink_ml_tpu.models.classification import GBTClassifier
from flink_ml_tpu.models.classification.logisticregression import (
    LogisticRegression,
)
from flink_ml_tpu.models.clustering.kmeans import KMeans
from flink_ml_tpu.models.feature.pca import PCA
from flink_ml_tpu.models.feature.randomsplitter import RandomSplitter
from flink_ml_tpu.models.feature.scalers import (
    MaxAbsScaler,
    MinMaxScaler,
    StandardScaler,
)
from flink_ml_tpu.models.feature.transforms import Binarizer, Normalizer
from flink_ml_tpu.models.recommendation.widedeep import WideDeep
from flink_ml_tpu.serving import serve_model


def _table(n=120, d=6, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d))
    y = (X[:, 0] > 0).astype(np.int64)
    return Table({"features": X, "label": y})


def _scaler_chain(table):
    """std -> minmax -> maxabs, each feeding the next column."""
    s1 = StandardScaler().set_output_col("std").fit(table)
    t1 = s1.transform(table)[0]
    s2 = (MinMaxScaler().set_features_col("std").set_output_col("mm")
          .fit(t1))
    t2 = s2.transform(t1)[0]
    s3 = (MaxAbsScaler().set_features_col("mm").set_output_col("ma")
          .fit(t2))
    return [s1, s2, s3], s3.transform(t2)[0]


def _assert_tables_equal(ref, out, cols=None):
    for name in (cols or ref.column_names):
        a, b = np.asarray(ref[name]), np.asarray(out[name])
        assert a.shape == b.shape, (name, a.shape, b.shape)
        assert np.array_equal(a, b), f"column {name!r} diverged"


def _ab(pm, *tables):
    """(stagewise, fused) outputs for the same inputs."""
    with chain.chain_disabled():
        ref = pm.transform(*tables)
    return ref, pm.transform(*tables)


# -- bit-exactness per terminal family ---------------------------------------

def test_fused_bitexact_linear_terminal():
    t = _table()
    stages, t3 = _scaler_chain(t)
    lr = (LogisticRegression().set_features_col("ma").set_max_iter(3)
          .fit(t3))
    pm = PipelineModel(stages + [lr])
    feats = t.drop("label")
    (ref,), (out,) = _ab(pm, feats)
    _assert_tables_equal(ref, out)
    plan = pm._chain_plan([feats])
    assert plan.describe() == [("segment", 4)]   # ONE fused program


def test_fused_bitexact_kmeans_terminal():
    t = _table(seed=3)
    stages, t3 = _scaler_chain(t)
    km = (KMeans().set_k(4).set_max_iter(3).set_features_col("ma")
          .fit(t3))
    pm = PipelineModel(stages + [km])
    feats = t.drop("label")
    (ref,), (out,) = _ab(pm, feats)
    _assert_tables_equal(ref, out)
    assert pm._chain_plan([feats]).describe() == [("segment", 4)]


def test_fused_bitexact_widedeep_terminal():
    rng = np.random.default_rng(6)
    n = 96
    dense = rng.normal(size=(n, 4)).astype(np.float32)
    cat = np.stack([rng.integers(0, 10, size=n),
                    rng.integers(0, 7, size=n)], axis=1).astype(np.int32)
    label = (cat[:, 0] > 4).astype(np.int64)
    t = Table({"denseFeatures": dense, "catFeatures": cat, "label": label})
    s1 = (StandardScaler().set_features_col("denseFeatures")
          .set_output_col("denseFeatures").fit(t))
    t1 = s1.transform(t)[0]
    s2 = (MaxAbsScaler().set_features_col("denseFeatures")
          .set_output_col("denseFeatures").fit(t1))
    t2 = s2.transform(t1)[0]
    s3 = (Normalizer().set_features_col("denseFeatures")
          .set_output_col("denseFeatures"))
    t3 = s3.transform(t2)[0]
    wd = WideDeep().set_vocab_sizes([10, 7]).set_max_iter(3).fit(t3)
    pm = PipelineModel([s1, s2, s3, wd])
    feats = t.drop("label")
    (ref,), (out,) = _ab(pm, feats)
    _assert_tables_equal(ref, out)
    assert pm._chain_plan([feats]).describe() == [("segment", 4)]

    # the categorical range check (WideDeep's host `pre`) still fires on
    # the fused path
    bad = Table({"denseFeatures": dense, "catFeatures": cat + 100})
    with pytest.raises(ValueError):
        pm.transform(bad)


def test_mixed_feature_chain_bitexact():
    """Longer chain through the elementwise transform kernels (Binarizer's
    f32 threshold surrogate included)."""
    t = _table(seed=9)
    s1 = StandardScaler().set_output_col("std").fit(t)
    t1 = s1.transform(t)[0]
    s2 = Binarizer().set_features_col("std").set_output_col("bin") \
        .set_threshold(0.25)
    t2 = s2.transform(t1)[0]
    s3 = Normalizer().set_features_col("std").set_output_col("norm")
    t3 = s3.transform(t2)[0]
    s4 = PCA().set_k(3).set_features_col("norm").set_output_col("pc") \
        .fit(t3)
    t4 = s4.transform(t3)[0]
    lr = (LogisticRegression().set_features_col("pc").set_max_iter(2)
          .fit(t4))
    pm = PipelineModel([s1, s2, s3, s4, lr])
    feats = t.drop("label")
    (ref,), (out,) = _ab(pm, feats)
    _assert_tables_equal(ref, out)
    assert pm._chain_plan([feats]).describe() == [("segment", 5)]


def test_encoder_chain_wide_margins_bitexact():
    """Covers the encoder kernels (StringIndexer numeric vocab, OneHot,
    VectorAssembler) AND the context-stable margin contraction: an
    8-wide assembled features column feeds the LR terminal, the width
    regime where a plain matvec would accumulate differently inside the
    fused program than in the standalone predict entry point (see
    ``linear._stable_margins``)."""
    from flink_ml_tpu.models.feature.encoders import (
        OneHotEncoder,
        OneHotEncoderParams,
        StringIndexer,
        VectorAssembler,
    )

    rng = np.random.default_rng(1)
    n = 80
    cat = rng.integers(0, 5, size=n).astype(np.int64)
    x = rng.normal(size=(n, 3))
    # f32 column: the StringIndexer lookup is a vocabulary-EQUALITY
    # decision, so its kernel declines f64 input (exact_compare) — see
    # test_exact_compare_kernels_decline_f64
    val = rng.choice([1.5, 2.5, 7.0, 9.0], size=n).astype(np.float32)
    t = Table({"cat": cat, "x": x, "val": val,
               "label": (x[:, 0] > 0).astype(np.int64)})
    si = StringIndexer().set_input_cols("val").set_output_cols("vid").fit(t)
    t0 = si.transform(t)[0]
    oh = (OneHotEncoder().set_input_cols("cat").set_output_cols("hot")
          .set(OneHotEncoderParams.HANDLE_INVALID, "keep").fit(t0))
    t1 = oh.transform(t0)[0]
    va = (VectorAssembler().set_input_cols("hot", "x", "vid")
          .set_features_col("raw"))         # 4 + 3 + 1 = 8-wide
    t2 = va.transform(t1)[0]
    sc = (StandardScaler().set_features_col("raw")
          .set_output_col("features").fit(t2))
    t3 = sc.transform(t2)[0]
    lr = LogisticRegression().set_max_iter(2).fit(t3)
    pm = PipelineModel([si, oh, va, sc, lr])
    feats = t.drop("label")
    (ref,), (out,) = _ab(pm, feats)
    assert pm._chain_plan([feats]).describe() == [("segment", 5)]
    # derived columns value-equal; dtypes follow the chain's documented
    # f32 normalization (the stagewise assembler path is host-f64)
    for name in ("vid", "hot", "features", "prediction", "rawPrediction"):
        a = np.asarray(ref[name])
        b = np.asarray(out[name])
        assert a.shape == b.shape
        assert np.array_equal(a.astype(b.dtype), b), name


def test_widedeep_wide_dense_bitexact():
    """dense width >= 8 exercises the wide tower's context-stable
    contraction (``forward_from_rows``) under fusion."""
    rng = np.random.default_rng(6)
    n = 128
    dense = rng.normal(size=(n, 8)).astype(np.float32)
    cat = np.stack([rng.integers(0, 10, size=n),
                    rng.integers(0, 7, size=n)], axis=1).astype(np.int32)
    t = Table({"denseFeatures": dense, "catFeatures": cat,
               "label": (cat[:, 0] > 4).astype(np.int64)})
    s1 = (StandardScaler().set_features_col("denseFeatures")
          .set_output_col("denseFeatures").fit(t))
    t1 = s1.transform(t)[0]
    s2 = (MaxAbsScaler().set_features_col("denseFeatures")
          .set_output_col("denseFeatures").fit(t1))
    t2 = s2.transform(t1)[0]
    wd = WideDeep().set_vocab_sizes([10, 7]).set_max_iter(2).fit(t2)
    pm = PipelineModel([s1, s2, wd])
    feats = t.drop("label")
    (ref,), (out,) = _ab(pm, feats)
    _assert_tables_equal(ref, out)


def test_gbt_breaks_chain_and_matches():
    """GBT scores host-f64 margins across trees -> deliberately NOT
    chainable; it falls back stagewise after the fused scaler segment."""
    t = _table(seed=4)
    stages, t3 = _scaler_chain(t)
    gbt = (GBTClassifier().set_max_iter(3).set_features_col("ma")
           .fit(t3))
    pm = PipelineModel(stages + [gbt])
    feats = t.drop("label")
    (ref,), (out,) = _ab(pm, feats)
    _assert_tables_equal(ref, out)
    assert pm._chain_plan([feats]).describe() == \
        [("segment", 3), ("stage", 1)]


# -- chain-break correctness --------------------------------------------------

def test_chain_break_at_splitter_bitexact():
    """scaler -> randomsplitter -> scaler -> model: segment boundaries
    land exactly at the non-chainable stage, the split fans the flow into
    two tables, and every output matches the stagewise path bit-exactly."""
    t = _table(seed=5)
    s1 = StandardScaler().set_output_col("std").fit(t)
    t1 = s1.transform(t)[0]
    s2 = (MinMaxScaler().set_features_col("std").set_output_col("mm")
          .fit(t1))
    t2 = s2.transform(t1)[0]
    lr = LogisticRegression().set_features_col("mm").set_max_iter(2) \
        .fit(t2)
    splitter = RandomSplitter().set_weights(1.0, 1.0).set_seed(7)
    pm = PipelineModel([s1, splitter, s2, lr])
    feats = t.drop("label")
    ref, out = _ab(pm, feats)
    assert len(ref) == len(out) == 2            # the split fans out
    for r, o in zip(ref, out):
        _assert_tables_equal(r, o)
    plan = pm._chain_plan([feats])
    assert plan.describe() == \
        [("segment", 1), ("stage", 1), ("segment", 2)]


def test_zero_row_table_fused():
    t = _table()
    s1 = StandardScaler().set_output_col("std").fit(t)
    t1 = s1.transform(t)[0]
    s2 = (MinMaxScaler().set_features_col("std").set_output_col("mm")
          .fit(t1))
    t2 = s2.transform(t1)[0]
    lr = LogisticRegression().set_features_col("mm").set_max_iter(2) \
        .fit(t2)
    for stages in ([s1, s2, lr],
                   [s1, RandomSplitter().set_weights(1.0, 1.0), s2, lr]):
        pm = PipelineModel(stages)
        empty = t.drop("label").take(0)
        ref, out = _ab(pm, empty)
        assert len(ref) == len(out)
        for r, o in zip(ref, out):
            assert o.num_rows == 0
            _assert_tables_equal(r, o)


def test_single_chainable_stage_stays_stagewise():
    """A plan of singleton segments is the stagewise path with extra
    bookkeeping — not worthwhile, so no plan is kept."""
    t = _table()
    s1 = StandardScaler().set_output_col("std").fit(t)
    pm = PipelineModel([s1])
    assert pm._chain_plan([t.drop("label")]) is None


def test_unsafe_int_values_fall_back_stagewise():
    """Integer batch values beyond the f32-exact range (+-2^24) cannot run
    in an f32 segment without silently diverging from the host-f64
    compare — the segment detects them per call and runs its stages
    stagewise, so the fused path still matches exactly."""
    rng = np.random.default_rng(3)
    n = 64
    big = (1 << 24) + rng.integers(0, 3, size=n).astype(np.int64)
    t = Table({"features": rng.normal(size=(n, 4)), "big": big})
    s1 = StandardScaler().set_output_col("std").fit(t)
    bz = (Binarizer().set_features_col("big").set_output_col("bin")
          .set_threshold((1 << 24) + 0.5))
    pm = PipelineModel([s1, bz])
    (ref,), (out,) = _ab(pm, t)
    _assert_tables_equal(ref, out)
    assert np.asarray(out["bin"]).any()          # the compare really fires
    # safe batches through the same plan keep the fused path
    small = Table({"features": np.asarray(t["features"]),
                   "big": big - (1 << 24)})
    (ref2,), (out2,) = _ab(pm, small)
    _assert_tables_equal(ref2, out2)

    # standalone rerouted transforms fall back to their host-f64 path too
    mm = (MinMaxScaler().set_features_col("big").set_output_col("mm")
          .fit(t))
    got = np.asarray(mm.transform(t)[0]["mm"])
    X = big.astype(np.float64).reshape(-1, 1)
    span = np.maximum(X.max() - X.min(), 1e-12)
    assert np.array_equal(got, (X - X.min()) / span)


def test_fused_onehot_negative_id_raises():
    """The stagewise keep path raises on NEGATIVE ids (only too-large
    ids zero out) — the fused segment's pre hook must raise identically,
    not silently emit a zero row."""
    from flink_ml_tpu.models.feature.encoders import (
        OneHotEncoder,
        OneHotEncoderParams,
        VectorAssembler,
    )

    rng = np.random.default_rng(9)
    n = 40
    t = Table({"cat": rng.integers(0, 4, size=n).astype(np.int64),
               "x": rng.normal(size=(n, 3))})
    oh = (OneHotEncoder().set_input_cols("cat").set_output_cols("hot")
          .set(OneHotEncoderParams.HANDLE_INVALID, "keep").fit(t))
    va = VectorAssembler().set_input_cols("hot", "x").set_features_col("f")
    pm = PipelineModel([oh, va])
    pm.transform(t)                       # warms + caches the fused plan
    assert pm._chain_plan([t]).describe() == [("segment", 2)]
    bad = Table({"cat": np.array([1, -1, 2], np.int64),
                 "x": np.zeros((3, 3))})
    with pytest.raises(ValueError, match="out of range"):
        pm.transform(bad)
    with chain.chain_disabled(), \
            pytest.raises(ValueError, match="out of range"):
        pm.transform(bad)


def test_exact_compare_kernels_decline_f64():
    """Kernels whose OUTPUT is an exact comparison decision (bucket
    index, vocabulary equality, placeholder fill) must not chain on f64
    columns: segment-entry f32 rounding could carry a value across the
    boundary the host-f64 compare respects.  They decline — stagewise
    fallback at full precision — while f32 columns keep the kernel."""
    from flink_ml_tpu.models.feature.encoders import StringIndexer
    from flink_ml_tpu.models.feature.transforms import Imputer
    from flink_ml_tpu.models.feature.vector_ops import (
        KBinsDiscretizer,
        KBinsDiscretizerModel,
        VectorIndexer,
    )

    rng = np.random.default_rng(17)
    n = 64
    Xd = rng.normal(size=(n, 2))                     # f64
    t64 = Table({"features": Xd})
    t32 = Table({"features": Xd.astype(np.float32)})
    cats = Table({"features": rng.integers(0, 3, size=(n, 2))
                  .astype(np.float64)})
    for stage in (
            KBinsDiscretizer().set_num_bins(4).fit(t64),
            VectorIndexer().set_handle_invalid("keep").fit(cats),
            Imputer().set_missing_value(0.1).fit(t64),
    ):
        assert stage.transform_kernel(t64.schema()) is None
        assert stage.transform_kernel(t32.schema()) is not None
    si = StringIndexer().set_input_cols("v").set_output_cols("vid").fit(
        Table({"v": np.array([1.0, 2.0, 1.0], np.float32)}))
    assert si.transform_kernel({"v": ((), np.dtype(np.float64))}) is None
    assert si.transform_kernel({"v": ((), np.dtype(np.float32))}) is not None

    # the divergence declining prevents: an f64 value just below a
    # non-f32-exact learned edge rounds ONTO the edge at f32 entry, so a
    # fused compare would bump it into the next bucket
    kb = KBinsDiscretizerModel().set_model_data(
        Table({"edges": np.array([[0.0, 0.3, 1.0]]),
               "n_edges": np.array([3])}))
    near = Table({"features": np.array(
        [[np.nextafter(0.3, 0.0)], [0.3], [0.75]])})
    assert np.array_equal(
        np.asarray(kb.transform(near)[0]["output"]).ravel(), [0.0, 1.0, 1.0])
    s1 = (StandardScaler().set_features_col("output")
          .set_output_col("std").fit(kb.transform(near)[0]))
    s2 = (MaxAbsScaler().set_features_col("std").set_output_col("ma")
          .fit(s1.transform(kb.transform(near)[0])[0]))
    pm = PipelineModel([kb, s1, s2])
    (ref,), (out,) = _ab(pm, near)
    _assert_tables_equal(ref, out)
    assert pm._chain_plan([near]).describe() == \
        [("stage", 1), ("segment", 2)]               # kb fell out of the chain


def test_kbins_nan_bins_last_fused():
    """NaN sorts AFTER every edge in the host searchsorted (last bin);
    the fused kernel's >=-count sees NaN compare false everywhere (bin 0)
    and must route it to the last bin explicitly."""
    from flink_ml_tpu.models.feature.vector_ops import KBinsDiscretizerModel

    kb = KBinsDiscretizerModel().set_model_data(
        Table({"edges": np.array([[0.0, 0.3, 1.0]]),
               "n_edges": np.array([3])}))
    t = Table({"features": np.array([[0.1], [np.nan], [0.8]], np.float32)})
    host = np.asarray(kb.transform(t)[0]["output"])
    fused = chain.apply_kernel(kb.transform_kernel(t.schema()), t)["output"]
    assert np.array_equal(host.astype(np.float32), np.asarray(fused))
    assert np.array_equal(np.asarray(fused).ravel(), [0.0, 1.0, 1.0])


def test_imputer_f64_placeholder_fills_exactly():
    """A non-f32-exact placeholder present EXACTLY in f64 data must fill
    via the host path — the kernel declines f64 instead of rounding the
    placeholder past the compare and passing the value through."""
    from flink_ml_tpu.models.feature.transforms import Imputer

    t = Table({"features": np.array([[0.1], [1.0], [3.0]])})
    im = Imputer().set_missing_value(0.1).set_output_col("out").fit(t)
    got = np.asarray(im.transform(t)[0]["out"]).ravel()
    assert np.array_equal(got, [2.0, 1.0, 3.0])      # 0.1 -> mean(1, 3)


def test_pre_cols_conflict_splits_segments():
    """A stage whose host ``pre`` validates a column produced mid-segment
    (OneHot on StringIndexer's ids) closes the running segment and opens
    a fresh one — fused across a segment boundary, not demoted to
    per-stage host dispatch."""
    from flink_ml_tpu.models.feature.encoders import (
        OneHotEncoder,
        OneHotEncoderParams,
        StringIndexer,
        VectorAssembler,
    )

    rng = np.random.default_rng(21)
    n = 64
    t = Table({"val": rng.choice([1.5, 2.5, 7.0], size=n)
               .astype(np.float32),
               "x": rng.normal(size=(n, 3))})
    si = StringIndexer().set_input_cols("val").set_output_cols("vid").fit(t)
    t0 = si.transform(t)[0]
    oh = (OneHotEncoder().set_input_cols("vid").set_output_cols("hot")
          .set(OneHotEncoderParams.HANDLE_INVALID, "keep").fit(t0))
    va = (VectorAssembler().set_input_cols("hot", "x")
          .set_features_col("f"))
    pm = PipelineModel([si, oh, va])
    (ref,), (out,) = _ab(pm, t)
    assert pm._chain_plan([t]).describe() == \
        [("segment", 1), ("segment", 2)]
    for name in ("vid", "hot", "f"):                 # value-equal (f32 posture)
        a, b = np.asarray(ref[name]), np.asarray(out[name])
        assert a.shape == b.shape
        assert np.array_equal(a.astype(b.dtype), b), name


def test_param_mutation_rebuilds_plan():
    """Mutating a stage param after the first fused transform must not
    serve the stale kernels the old value was baked into."""
    t = _table(seed=14)
    s1 = StandardScaler().set_output_col("std").fit(t)
    bz = (Binarizer().set_features_col("std").set_output_col("bin")
          .set_threshold(0.0))
    pm = PipelineModel([s1, bz])
    feats = t.drop("label")
    pm.transform(feats)                          # plan built at thr=0.0
    bz.set_threshold(0.75)
    (ref,), (out,) = _ab(pm, feats)
    _assert_tables_equal(ref, out)
    assert not np.array_equal(np.asarray(out["bin"]),
                              (np.asarray(out["std"]) > 0.0))


# -- dispatch accounting ------------------------------------------------------

def test_fused_dispatch_count_is_one_per_segment():
    t = _table(seed=8)
    stages, t3 = _scaler_chain(t)
    lr = (LogisticRegression().set_features_col("ma").set_max_iter(2)
          .fit(t3))
    pm = PipelineModel(stages + [lr])
    feats = t.drop("label")
    pm.transform(feats)                          # plan build + warm
    d0 = chain.dispatch_count()
    pm.transform(feats)
    assert chain.dispatch_count() - d0 == 1      # 4 stages, ONE dispatch


# -- zero recompiles ----------------------------------------------------------

def test_zero_recompile_steady_state_warmed_buckets():
    from jax._src import test_util as jtu

    t = _table(n=128, d=8, seed=2)
    stages, t3 = _scaler_chain(t)
    lr = (LogisticRegression().set_features_col("ma").set_max_iter(2)
          .fit(t3))
    pm = PipelineModel(stages + [lr])
    feats = t.drop("label")
    for n in (8, 16, 32, 64, 128):               # warm the bucket ladder
        pm.transform(feats.take(n))
    with jtu.count_jit_and_pmap_lowerings() as count:
        for n in (1, 3, 8, 9, 16, 23, 33, 64, 100, 128):
            pm.transform(feats.take(n))
    assert count[0] == 0, (
        f"{count[0]} new XLA lowerings in steady state — bucket padding "
        "or plan caching regressed")


def test_dtype_hygiene_f64_f32_share_one_compile():
    """numpy float64 input columns must NOT retrace: segment entry
    normalizes to f32 on host, so f64 and f32 views of the same data hit
    one compiled program (and produce identical derived columns)."""
    from jax._src import test_util as jtu

    t = _table(n=64, d=8, seed=11)               # f64 features
    stages, t3 = _scaler_chain(t)
    lr = (LogisticRegression().set_features_col("ma").set_max_iter(2)
          .fit(t3))
    pm = PipelineModel(stages + [lr])
    f64 = t.drop("label")
    f32 = Table({"features": np.asarray(t["features"], np.float32)})
    pm.transform(f64)                            # warm once, f64 entry
    with jtu.count_jit_and_pmap_lowerings() as count:
        (a,) = pm.transform(f64)
        (b,) = pm.transform(f32)
    assert count[0] == 0, (
        f"{count[0]} new lowerings — f64 input retraced the segment")
    # derived columns identical (the untouched passthrough input keeps
    # its caller dtype by design)
    _assert_tables_equal(
        a, b, cols=[c for c in a.column_names if c != "features"])


# -- persistence --------------------------------------------------------------

def test_persist_round_trip_fused_bitexact(tmp_path):
    t = _table(seed=12)
    stages, t3 = _scaler_chain(t)
    lr = (LogisticRegression().set_features_col("ma").set_max_iter(3)
          .fit(t3))
    pm = PipelineModel(stages + [lr])
    feats = t.drop("label")
    with chain.chain_disabled():                 # pre-save stagewise oracle
        (ref,) = pm.transform(feats)
    path = os.path.join(str(tmp_path), "pipeline")
    pm.save(path)
    loaded = PipelineModel.load(path)
    (out,) = loaded.transform(feats)             # fused path post-load
    _assert_tables_equal(ref, out)
    assert loaded._chain_plan([feats]).describe() == [("segment", 4)]


# -- serving ------------------------------------------------------------------

def test_pipeline_servable_honors_min_bucket():
    """The servable's fused plan must pad with the servable's OWN bucket
    floor: warm_up tiles buckets from min_bucket, so a plan padding to a
    different ladder would compile on the serving path after ready."""
    from jax._src import test_util as jtu

    from flink_ml_tpu.serving.executor import make_servable

    t = _table(n=128, seed=19)
    stages, t3 = _scaler_chain(t)
    lr = (LogisticRegression().set_features_col("ma").set_max_iter(2)
          .fit(t3))
    pm = PipelineModel(stages + [lr])
    feats = t.drop("label")
    servable = make_servable(pm, feats.take(2), min_bucket=64,
                             max_batch_rows=128)
    servable.warm_up()
    with jtu.count_jit_and_pmap_lowerings() as count:
        for n in (3, 40, 100):
            servable.predict(feats.take(n))
    assert count[0] == 0, (
        f"{count[0]} new lowerings post-warm-up — the fused plan pads a "
        "different bucket ladder than warm_up compiled")


def test_pipeline_serving_single_dispatch_chain():
    """One endpoint serves preprocess+score: fused per-micro-batch output
    is bit-exact with the offline stagewise transform."""
    t = _table(n=128, seed=13)
    stages, t3 = _scaler_chain(t)
    lr = (LogisticRegression().set_features_col("ma").set_max_iter(3)
          .fit(t3))
    pm = PipelineModel(stages + [lr])
    feats = t.drop("label")
    with chain.chain_disabled():
        (ref,) = pm.transform(feats)
    endpoint = serve_model(pm, feats.take(2), max_batch_rows=64,
                           max_wait_ms=0.5)
    try:
        start = 0
        for size in (1, 6, 14, 32):
            got = endpoint.predict(feats.slice(start, start + size))
            _assert_tables_equal(ref.slice(start, start + size), got)
            start += size
    finally:
        endpoint.close()
