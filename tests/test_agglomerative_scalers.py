"""AgglomerativeClustering + MaxAbs/Robust/OnlineStandard scalers."""

import numpy as np
import pytest

from flink_ml_tpu import Table
from flink_ml_tpu.models.clustering import AgglomerativeClustering
from flink_ml_tpu.models.feature import (
    MaxAbsScaler,
    MaxAbsScalerModel,
    OnlineStandardScaler,
    RobustScaler,
    RobustScalerModel,
    StandardScaler,
)


def _blobs(n_per=30, seed=0, spread=8.0):
    rng = np.random.default_rng(seed)
    centers = rng.normal(scale=spread, size=(3, 4))
    X = np.concatenate([centers[i] + rng.normal(size=(n_per, 4), scale=0.5)
                        for i in range(3)])
    y = np.repeat([0, 1, 2], n_per)
    return Table({"features": X}), y


def _cluster_sets(labels, y):
    return {frozenset(np.nonzero(labels == c)[0].tolist())
            for c in np.unique(labels)} == \
           {frozenset(np.nonzero(y == c)[0].tolist())
            for c in np.unique(y)}


@pytest.mark.parametrize("linkage", ["ward", "complete", "average", "single"])
def test_agglomerative_recovers_blobs(linkage):
    table, y = _blobs()
    out = (AgglomerativeClustering().set_num_clusters(3)
           .set_linkage(linkage).transform(table)[0])
    labels = np.asarray(out["prediction"])
    assert len(np.unique(labels)) == 3
    assert _cluster_sets(labels, y)


def test_agglomerative_k1_and_kn():
    table, _ = _blobs(n_per=4)
    one = (AgglomerativeClustering().set_num_clusters(1)
           .transform(table)[0])
    assert set(np.asarray(one["prediction"]).tolist()) == {0}
    n = len(table)
    all_sep = (AgglomerativeClustering().set_num_clusters(n)
               .transform(table)[0])
    assert len(set(np.asarray(all_sep["prediction"]).tolist())) == n


def test_agglomerative_ward_requires_euclidean():
    table, _ = _blobs(n_per=3)
    with pytest.raises(ValueError, match="euclidean"):
        (AgglomerativeClustering().set_distance_measure("manhattan")
         .transform(table))


def test_agglomerative_row_guard():
    from flink_ml_tpu.models.clustering import agglomerative as agg
    old = agg._MAX_ROWS
    agg._MAX_ROWS = 10
    try:
        table, _ = _blobs(n_per=30)
        with pytest.raises(ValueError, match="O\\(n\\^2\\)"):
            AgglomerativeClustering().transform(table)
    finally:
        agg._MAX_ROWS = old


def test_agglomerative_labels_ordered_by_first_appearance():
    X = np.asarray([[0.0], [100.0], [0.1], [100.1]])
    out = (AgglomerativeClustering().set_num_clusters(2)
           .transform(Table({"features": X}))[0])
    np.testing.assert_array_equal(np.asarray(out["prediction"]),
                                  [0, 1, 0, 1])


def test_max_abs_scaler(tmp_path):
    X = np.asarray([[2.0, -8.0], [-4.0, 4.0]])
    model = MaxAbsScaler().fit(Table({"features": X}))
    out = model.transform(Table({"features": X}))[0]
    np.testing.assert_allclose(np.asarray(out["output"]),
                               [[0.5, -1.0], [-1.0, 0.5]])
    model.save(str(tmp_path / "m"))
    re = MaxAbsScalerModel.load(str(tmp_path / "m"))
    np.testing.assert_allclose(
        np.asarray(re.transform(Table({"features": X}))[0]["output"]),
        np.asarray(out["output"]))


def test_robust_scaler_ignores_outliers(tmp_path):
    rng = np.random.default_rng(0)
    X = rng.normal(size=(500, 2))
    X[:5] = 1e6  # gross outliers
    model = RobustScaler().fit(Table({"features": X}))
    out = np.asarray(model.transform(Table({"features": X}))[0]["output"])
    # inliers stay O(1) despite the outliers
    assert np.abs(out[5:]).max() < 10.0
    model.save(str(tmp_path / "m"))
    re = RobustScalerModel.load(str(tmp_path / "m"))
    np.testing.assert_allclose(
        np.asarray(re.transform(Table({"features": X}))[0]["output"]), out)


def test_robust_scaler_validates_quantiles():
    with pytest.raises(ValueError, match="lower < upper"):
        (RobustScaler().set(RobustScaler.LOWER, 80.0)
         .set(RobustScaler.UPPER, 20.0)
         .fit(Table({"features": np.zeros((3, 1))})))


def test_online_standard_scaler_matches_batch():
    rng = np.random.default_rng(1)
    X = rng.normal(loc=3.0, scale=2.0, size=(1000, 3))
    batch_model = StandardScaler().fit(Table({"features": X}))
    windows = [Table({"features": X[i:i + 100]}) for i in range(0, 1000, 100)]
    online_model = OnlineStandardScaler().fit(iter(windows))
    t = Table({"features": X[:50]})
    np.testing.assert_allclose(
        np.asarray(online_model.transform(t)[0]["output"]),
        np.asarray(batch_model.transform(t)[0]["output"]), atol=1e-3)
    assert online_model.model_version == 10


def test_online_standard_scaler_empty_stream_rejected():
    with pytest.raises(ValueError, match="empty stream"):
        OnlineStandardScaler().fit(iter([]))


def test_online_scaler_large_mean_no_cancellation():
    # regression: f32 E[x^2]-E[x]^2 collapses std to 0 at mean 1e4
    rng = np.random.default_rng(2)
    X = rng.normal(loc=1e4, scale=1.0, size=(5000, 2))
    windows = [Table({"features": X[i:i + 500]}) for i in range(0, 5000, 500)]
    model = OnlineStandardScaler().fit(iter(windows))
    std = np.asarray(model.get_model_data()[0]["std"][0])
    np.testing.assert_allclose(std, 1.0, rtol=0.05)


def test_online_scaler_model_version_persists(tmp_path):
    from flink_ml_tpu.models.feature import OnlineStandardScalerModel

    X = np.random.default_rng(0).normal(size=(100, 2))
    windows = [Table({"features": X[i:i + 25]}) for i in range(0, 100, 25)]
    model = OnlineStandardScaler().fit(iter(windows))
    assert model.model_version == 4
    model.save(str(tmp_path / "m"))
    re = OnlineStandardScalerModel.load(str(tmp_path / "m"))
    assert re.model_version == 4
    np.testing.assert_allclose(
        np.asarray(re.transform(Table({"features": X}))[0]["output"]),
        np.asarray(model.transform(Table({"features": X}))[0]["output"]))


def test_agglomerative_k_exceeds_n_rejected():
    table, _ = _blobs(n_per=2)
    with pytest.raises(ValueError, match="exceeds"):
        AgglomerativeClustering().set_num_clusters(100).transform(table)


def test_agglomerative_matches_bruteforce_loop():
    # NN-index maintenance must agree with the O(n^3) reference merge loop
    from flink_ml_tpu.models.clustering.agglomerative import _merge_loop

    def brute(D, k, linkage):
        n = D.shape[0]
        D = D.copy(); np.fill_diagonal(D, np.inf)
        active = np.ones(n, bool); size = np.ones(n)
        parent = np.arange(n)
        for _ in range(n - k):
            masked = np.where(np.outer(active, active), D, np.inf)
            np.fill_diagonal(masked, np.inf)
            i, j = divmod(int(np.argmin(masked)), n)
            if j < i: i, j = j, i
            di, dj = D[i], D[j]
            if linkage == "single": new = np.minimum(di, dj)
            elif linkage == "complete": new = np.maximum(di, dj)
            elif linkage == "average":
                new = (size[i]*di + size[j]*dj) / (size[i]+size[j])
            else:
                sk = size; tot = size[i]+size[j]+sk
                new = ((size[i]+sk)*di + (size[j]+sk)*dj - sk*D[i,j]) / tot
            new[~active] = np.inf; new[i] = np.inf
            D[i,:] = new; D[:,i] = new; D[j,:] = np.inf; D[:,j] = np.inf
            active[j] = False; size[i] += size[j]; parent[j] = i
        def find(i):
            while parent[i] != i: i = parent[i]
            return i
        roots = np.array([find(i) for i in range(n)])
        return np.unique(roots, return_inverse=True)[1]

    rng = np.random.default_rng(3)
    X = rng.normal(size=(40, 3))
    D = ((X[:, None] - X[None, :]) ** 2).sum(-1)
    for linkage in ("single", "complete", "average", "ward"):
        got = _merge_loop(D, 5, linkage)
        exp = brute(D, 5, linkage)
        np.testing.assert_array_equal(got, exp, err_msg=linkage)


def test_agglomerative_far_from_origin_precision():
    # regression: the f32 ||x||^2 - 2xy device expansion collapsed
    # within-blob distances to 0 for data at coordinates ~1000
    rng = np.random.default_rng(7)
    centers = np.asarray([[1000.0, 1000.0], [1000.7, 1000.0],
                          [1000.0, 1000.7]])
    X = np.concatenate([c + rng.normal(scale=0.02, size=(20, 2))
                        for c in centers])
    y = np.repeat([0, 1, 2], 20)
    for linkage in ("single", "average"):
        out = (AgglomerativeClustering().set_num_clusters(3)
               .set_linkage(linkage).transform(Table({"features": X}))[0])
        assert _cluster_sets(np.asarray(out["prediction"]), y), linkage


def test_pairwise_host64_matches_device_small():
    import jax.numpy as jnp

    from flink_ml_tpu.distance import DistanceMeasure

    rng = np.random.default_rng(0)
    p = rng.normal(size=(10, 3))
    c = rng.normal(size=(4, 3))
    for name in ("euclidean", "cosine", "manhattan"):
        m = DistanceMeasure.get_instance(name)
        np.testing.assert_allclose(
            m.pairwise_host64(p, c),
            np.asarray(m.pairwise(jnp.asarray(p, jnp.float32),
                                  jnp.asarray(c, jnp.float32))),
            atol=1e-4)


class TestOnlineScalerCheckpoint:
    def test_kill_and_resume_matches_uninterrupted(self, tmp_path):
        from flink_ml_tpu.data.wal import WindowLog
        from flink_ml_tpu.iteration.checkpoint import CheckpointConfig
        from flink_ml_tpu.models.feature.online_scaler import (
            OnlineStandardScaler)

        rng = np.random.default_rng(2)
        windows = [Table({"features": 1e4 + rng.normal(size=(64, 3))})
                   for _ in range(10)]
        oracle = OnlineStandardScaler().fit(iter(windows))

        class Killed(RuntimeError):
            pass

        def dying(ws, k):
            for i, w in enumerate(ws):
                if i == k:
                    raise Killed()
                yield w

        wal = str(tmp_path / "wal")
        ckpt = CheckpointConfig(str(tmp_path / "ckpt"), interval=4)
        with pytest.raises(Killed):
            OnlineStandardScaler().fit(WindowLog(dying(windows, 7), wal),
                                       checkpoint=ckpt)
        resumed = OnlineStandardScaler().fit(
            WindowLog(iter(windows[7:]), wal), checkpoint=ckpt,
            resume=True)
        (od,), (rd,) = oracle.get_model_data(), resumed.get_model_data()
        np.testing.assert_allclose(np.asarray(rd["mean"]),
                                   np.asarray(od["mean"]), rtol=1e-12)
        np.testing.assert_allclose(np.asarray(rd["std"]),
                                   np.asarray(od["std"]), rtol=1e-9)
        assert resumed.model_version == oracle.model_version == 10

    def test_bare_table_checkpoint(self, tmp_path):
        from flink_ml_tpu.iteration.checkpoint import CheckpointConfig
        from flink_ml_tpu.models.feature.online_scaler import (
            OnlineStandardScaler)

        rng = np.random.default_rng(3)
        t = Table({"features": rng.normal(size=(10000, 2)) * 3 + 7})
        ckpt = CheckpointConfig(str(tmp_path / "c"), interval=1)
        model = OnlineStandardScaler().fit(t, checkpoint=ckpt)
        oracle = OnlineStandardScaler().fit(t)
        (md,), (od,) = model.get_model_data(), oracle.get_model_data()
        np.testing.assert_allclose(np.asarray(md["mean"]),
                                   np.asarray(od["mean"]), rtol=1e-12)
