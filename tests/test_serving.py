"""Serving runtime tests: save -> load -> serve round trips per model
family (bit-exact with offline transform), zero-recompile steady state,
atomic hot-swap under concurrent load, admission control, micro-batcher
coalescing, bucket padding helpers, prefetch metric gauges, and the
diagnosable persist load errors the registry depends on."""

import json
import os
import threading

import numpy as np
import pytest

from flink_ml_tpu import Table
from flink_ml_tpu.serving import (
    MicroBatcher,
    ModelRegistry,
    ServingEndpoint,
    ServingOverloadedError,
    make_servable,
    serve_model,
)
from flink_ml_tpu.utils.padding import (
    bucket_rows,
    bucket_sizes,
    pad_rows_to_bucket,
)


def _lr_table(n=64, d=8, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d))
    y = (X[:, 0] + 0.3 * rng.normal(size=n) > 0).astype(np.int64)
    return Table({"features": X, "label": y})


def _fit_lr(seed=0):
    from flink_ml_tpu.models.classification.logisticregression import (
        LogisticRegression)

    return LogisticRegression().set_max_iter(5).fit(_lr_table(seed=seed))


def _requests(table, sizes):
    """Non-overlapping request tables of the given row counts."""
    out, start = [], 0
    for s in sizes:
        out.append(table.slice(start, start + s))
        start += s
    return out


# -- bucket padding helpers --------------------------------------------------

def test_bucket_rows_ladder():
    assert bucket_rows(1) == 8 and bucket_rows(8) == 8
    assert bucket_rows(9) == 16
    assert bucket_rows(100) == 128
    assert bucket_rows(3, min_bucket=2) == 4
    assert bucket_sizes(64) == (8, 16, 32, 64)
    assert bucket_sizes(100) == (8, 16, 32, 64, 128)
    with pytest.raises(ValueError):
        bucket_rows(4, min_bucket=0)


def test_pad_rows_to_bucket_caps_huge_batches():
    from flink_ml_tpu.utils.padding import DEFAULT_BUCKET_CAP

    big = np.ones((DEFAULT_BUCKET_CAP + 1, 2), np.float32)
    (padded,), n = pad_rows_to_bucket((big,))
    assert padded.shape[0] == n == DEFAULT_BUCKET_CAP + 1  # exact shape kept
    (padded,), n = pad_rows_to_bucket((np.ones((9, 2), np.float32),),
                                      max_bucket_rows=None)
    assert padded.shape[0] == 16 and n == 9    # None = unlimited bucketing
    with pytest.raises(ValueError, match="bucket cap"):
        make_servable(_fit_lr(), _lr_table().drop("label").take(1),
                      max_batch_rows=DEFAULT_BUCKET_CAP * 2)


def test_pad_rows_to_bucket_zero_fill():
    a = np.arange(10, dtype=np.float32).reshape(5, 2)
    idx = np.ones((5, 3), np.int32)
    (pa, pidx), n = pad_rows_to_bucket((a, idx))
    assert n == 5 and pa.shape == (8, 2) and pidx.shape == (8, 3)
    np.testing.assert_array_equal(pa[:5], a)
    assert not pa[5:].any() and not pidx[5:].any()
    # exact bucket size: no copy path still returns the same rows
    (pb,), n = pad_rows_to_bucket((np.ones((8, 2), np.float32),))
    assert n == 8 and pb.shape == (8, 2)


# -- save -> load -> serve round trips, bit-exact with offline transform -----

def _roundtrip_serve(model, load_cls, request_tables, tmp_path,
                     example=None):
    """save -> load_stage -> deploy (warmed) -> serve each request; every
    response must be BIT-exact with the loaded model's offline
    transform."""
    from flink_ml_tpu.utils import persist

    path = str(tmp_path / "model")
    model.save(path)
    loaded = persist.load_stage(path)
    assert isinstance(loaded, load_cls)

    example = example if example is not None else request_tables[0]
    registry = ModelRegistry()
    registry.deploy("m", path, example, max_batch_rows=64)
    endpoint = ServingEndpoint(registry, "m", max_wait_ms=0.5).start()
    try:
        for req in request_tables:
            served = endpoint.predict(req)
            offline = loaded.transform(req)[0]
            assert served.column_names == offline.column_names
            for col in offline.column_names:
                np.testing.assert_array_equal(served[col], offline[col])
    finally:
        endpoint.close()


def test_roundtrip_logisticregression(tmp_path):
    from flink_ml_tpu.models.classification.logisticregression import (
        LogisticRegressionModel)

    model = _fit_lr()
    reqs = _requests(_lr_table(seed=3).drop("label"), (1, 3, 8, 13, 30))
    _roundtrip_serve(model, LogisticRegressionModel, reqs, tmp_path)


def test_roundtrip_linearregression(tmp_path):
    from flink_ml_tpu.models.regression.linearregression import (
        LinearRegression, LinearRegressionModel)

    rng = np.random.default_rng(1)
    X = rng.normal(size=(64, 6))
    t = Table({"features": X, "label": X @ rng.normal(size=6) + 0.2})
    model = LinearRegression().set_max_iter(5).fit(t)
    reqs = _requests(t.drop("label"), (2, 5, 16, 31))
    _roundtrip_serve(model, LinearRegressionModel, reqs, tmp_path)


def test_roundtrip_kmeans(tmp_path):
    from flink_ml_tpu.models.clustering.kmeans import KMeans, KMeansModel

    rng = np.random.default_rng(2)
    pts = np.concatenate([rng.normal(loc=c, size=(20, 3))
                          for c in (-4.0, 0.0, 4.0)]).astype(np.float32)
    model = KMeans().set_k(3).set_max_iter(5).fit(Table({"features": pts}))
    reqs = _requests(Table({"features": pts}), (1, 7, 20, 32))
    _roundtrip_serve(model, KMeansModel, reqs, tmp_path)


def test_roundtrip_gbt_classifier(tmp_path):
    from flink_ml_tpu.models.classification.gbtclassifier import (
        GBTClassifier, GBTClassifierModel)

    t = _lr_table(n=96, seed=4)
    model = (GBTClassifier().set_max_iter(3).set_max_depth(2)
             .set_max_bins(16).fit(t))
    reqs = _requests(t.drop("label"), (1, 5, 12, 40))
    _roundtrip_serve(model, GBTClassifierModel, reqs, tmp_path)


def test_roundtrip_gbt_regressor(tmp_path):
    from flink_ml_tpu.models.regression.gbtregressor import (
        GBTRegressor, GBTRegressorModel)

    rng = np.random.default_rng(5)
    X = rng.normal(size=(96, 5))
    t = Table({"features": X, "label": X[:, 0] * 2 + X[:, 1]})
    model = (GBTRegressor().set_max_iter(3).set_max_depth(2)
             .set_max_bins(16).fit(t))
    reqs = _requests(t.drop("label"), (2, 9, 33))
    _roundtrip_serve(model, GBTRegressorModel, reqs, tmp_path)


def test_roundtrip_widedeep(tmp_path):
    from flink_ml_tpu.models.recommendation.widedeep import (
        WideDeep, WideDeepModel)

    rng = np.random.default_rng(6)
    n = 128
    dense = rng.normal(size=(n, 4)).astype(np.float32)
    cat = np.stack([rng.integers(0, 10, size=n),
                    rng.integers(0, 7, size=n)], axis=1).astype(np.int32)
    label = (cat[:, 0] > 4).astype(np.int64)
    t = Table({"denseFeatures": dense, "catFeatures": cat, "label": label})
    model = WideDeep().set_vocab_sizes([10, 7]).set_max_iter(5).fit(t)
    reqs = _requests(t.drop("label"), (1, 6, 14, 32))
    _roundtrip_serve(model, WideDeepModel, reqs, tmp_path)


# -- zero retraces in steady state -------------------------------------------

def test_zero_recompile_steady_state():
    from jax._src import test_util as jtu

    model = _fit_lr()
    feats = _lr_table(n=128, seed=7).drop("label")
    endpoint = serve_model(model, feats.take(2), max_batch_rows=64,
                           max_wait_ms=0.5)
    try:
        # settle wave: anything lazily built outside the warm-up ladder
        # (e.g. weight device_puts) happens here
        for n in (1, 2, 64):
            endpoint.predict(feats.take(n))
        with jtu.count_jit_and_pmap_lowerings() as count:
            for n in (1, 3, 4, 7, 8, 11, 16, 23, 33, 48, 64):
                endpoint.predict(feats.take(n))
        assert count[0] == 0, (
            f"{count[0]} new XLA lowerings in steady state — the bucket "
            "warm-up did not cover the serving shapes")
    finally:
        endpoint.close()


def test_warmup_required_before_start():
    registry = ModelRegistry()
    endpoint = ServingEndpoint(registry, "missing")
    with pytest.raises(KeyError):
        endpoint.start()   # nothing deployed

    class _Factory:
        def __call__(self, model, example, **kw):
            servable = make_servable(model, example, **kw)
            servable.warm_up = lambda: servable   # deploy skips warming
            return servable

    cold = ModelRegistry(servable_factory=_Factory())
    cold.deploy("m", _fit_lr(), _lr_table().drop("label").take(1))
    with pytest.raises(RuntimeError, match="not.*warmed"):
        ServingEndpoint(cold, "m").start()


# -- micro-batcher ----------------------------------------------------------

def test_microbatcher_coalesces_and_respects_capacity():
    batcher = MicroBatcher(max_batch_rows=16, max_wait_ms=20.0,
                           queue_capacity=4)
    t = _lr_table(n=32).drop("label")
    for _ in range(3):
        batcher.submit(t.take(4))
    batch = batcher.next_batch(timeout=0.1)
    assert [r.rows for r in batch] == [4, 4, 4]   # coalesced in order

    # a request that would overflow max_batch_rows stays for the next batch
    batcher.submit(t.take(12))
    batcher.submit(t.take(8))
    batch = batcher.next_batch(timeout=0.1)
    assert [r.rows for r in batch] == [12]
    batch = batcher.next_batch(timeout=0.1)
    assert [r.rows for r in batch] == [8]

    # bounded queue: capacity 4, fifth submit sheds
    for _ in range(4):
        batcher.submit(t.take(1))
    with pytest.raises(ServingOverloadedError, match="queue full"):
        batcher.submit(t.take(1))

    with pytest.raises(ValueError, match="max_batch_rows"):
        batcher.submit(t.take(17))
    with pytest.raises(ValueError, match="empty"):
        batcher.submit(t.take(0))


def test_queue_full_requests_shed_with_documented_error():
    model = _fit_lr()
    feats = _lr_table(seed=8).drop("label")
    registry = ModelRegistry()
    registry.deploy("m", model, feats.take(1), max_batch_rows=32)
    endpoint = ServingEndpoint(registry, "m", max_batch_rows=32,
                               queue_capacity=3)
    # endpoint NOT started: submits accumulate in the bounded queue
    futures = [endpoint.submit(feats.take(1)) for _ in range(3)]
    with pytest.raises(ServingOverloadedError, match="shed"):
        endpoint.submit(feats.take(1))
    assert endpoint.metrics.shed.value == 1
    endpoint.start()   # queued requests drain once serving begins
    ref = model.transform(feats.take(1))[0]["rawPrediction"]
    for future in futures:
        np.testing.assert_array_equal(
            future.result(10)["rawPrediction"], ref)
    endpoint.close()


def test_schema_mismatch_rejected():
    endpoint = serve_model(_fit_lr(), _lr_table().drop("label").take(1),
                           max_batch_rows=32)
    try:
        with pytest.raises(ValueError, match="schema"):
            endpoint.predict(Table({"wrong": np.ones((2, 8))}))
    finally:
        endpoint.close()


# -- hot swap ----------------------------------------------------------------

def _lr_from_weights(w, b):
    from flink_ml_tpu.models.classification.logisticregression import (
        LogisticRegressionModel)

    model = LogisticRegressionModel()
    model.set_model_data(Table({"coefficients": np.asarray(w)[None, :],
                                "intercept": np.array([b])}))
    return model


def test_hot_swap_atomic_and_bitexact_under_load():
    rng = np.random.default_rng(9)
    d = 8
    model_a = _lr_from_weights(rng.normal(size=d), 0.0)
    model_b = _lr_from_weights(rng.normal(size=d) + 3.0, -1.0)
    feats = Table({"features": rng.normal(size=(256, d))})
    reqs = _requests(feats, [1 + i % 7 for i in range(40)])
    ref_a = [model_a.transform(r)[0]["rawPrediction"] for r in reqs]
    ref_b = [model_b.transform(r)[0]["rawPrediction"] for r in reqs]

    endpoint = serve_model(model_a, feats.take(1), max_batch_rows=64,
                           max_wait_ms=0.5, queue_capacity=4096)
    results = [None] * len(reqs)
    errors = []

    def client(worker, n_workers):
        try:
            for i in range(worker, len(reqs), n_workers):
                results[i] = endpoint.predict(reqs[i], timeout=30)
        except Exception as exc:  # noqa: BLE001
            errors.append(exc)

    try:
        threads = [threading.Thread(target=client, args=(w, 4))
                   for w in range(4)]
        for t in threads:
            t.start()
        # swap mid-flight: warm-up runs here, OFF the serving path
        deployed = endpoint.registry.deploy("default", model_b)
        assert deployed.generation == 2
        # a request submitted after the deploy returned must see B
        post = feats.take(5)
        np.testing.assert_array_equal(
            endpoint.predict(post)["rawPrediction"],
            model_b.transform(post)[0]["rawPrediction"])
        for t in threads:
            t.join(30)
        assert not errors
        # atomicity: every response equals EXACTLY one version's offline
        # transform — never a mix of generations within one response
        for i, out in enumerate(results):
            raw = out["rawPrediction"]
            is_a = np.array_equal(raw, ref_a[i])
            is_b = np.array_equal(raw, ref_b[i])
            assert is_a or is_b, f"request {i} matches neither version"
        assert endpoint.metrics.group.snapshot()["model_generation"] == 2
    finally:
        endpoint.close()


def test_registry_redeploy_inherits_example_and_generation():
    registry = ModelRegistry()
    feats = _lr_table().drop("label")
    gen1 = registry.deploy("m", _fit_lr(), feats.take(2), max_batch_rows=32)
    assert gen1.generation == 1 and gen1.servable.ready
    gen2 = registry.deploy("m", _fit_lr(seed=11))   # example inherited
    assert gen2.generation == 2
    assert gen2.servable.example is gen1.servable.example
    assert gen2.servable.max_batch_rows == 32
    with pytest.raises(ValueError, match="example"):
        registry.deploy("fresh", _fit_lr())


# -- persist diagnosability (the registry's load path) -----------------------

def test_load_stage_missing_class_is_clear_ioerror(tmp_path):
    from flink_ml_tpu.utils import persist

    path = str(tmp_path / "m")
    _fit_lr().save(path)
    meta_path = os.path.join(path, "metadata")
    with open(meta_path) as f:
        meta = json.load(f)

    meta["className"] = "flink_ml_tpu.models.classification." \
        "logisticregression.RenamedAway"
    with open(meta_path, "w") as f:
        json.dump(meta, f)
    with pytest.raises(IOError, match="RenamedAway") as exc_info:
        persist.load_stage(path)
    assert path in str(exc_info.value)

    meta["className"] = "no_such_module.Thing"
    with open(meta_path, "w") as f:
        json.dump(meta, f)
    with pytest.raises(IOError, match="no_such_module.Thing"):
        persist.load_stage(path)

    del meta["className"]
    with open(meta_path, "w") as f:
        json.dump(meta, f)
    with pytest.raises(IOError, match="className"):
        persist.load_stage(path)


# -- prefetch per-chunk stats as gauges --------------------------------------

def test_prefetch_chunk_stats_published_as_gauges():
    from flink_ml_tpu.data.prefetch import prefetch_to_device
    from flink_ml_tpu.utils.metrics import MetricGroup

    group = MetricGroup("prefetch")
    batches = [{"x": np.full((4, 2), i, np.float32)} for i in range(7)]
    seen = 0
    for chunk, mask, n_valid in prefetch_to_device(
            iter(batches), chunks=3, metric_group=group,
            transform=lambda b: (b["x"],)):
        seen += n_valid
    assert seen == 7
    snap = group.snapshot()
    assert snap["chunks_emitted"] == 3      # ceil(7 / 3)
    assert snap["batches"] == 7
    # final chunk padded 3 -> 1 real: 2 pad slots of 9 total
    assert snap["pad_fraction"] == pytest.approx(2 / 9, abs=1e-4)
    assert snap["put_overlap_s"] >= 0.0
    assert snap["chunk_assemble_s"] >= 0.0


# -- concurrency smoke + slow sweep ------------------------------------------

def test_concurrent_clients_coalesce_and_stay_exact():
    model = _fit_lr()
    feats = _lr_table(n=256, seed=12).drop("label")
    reqs = _requests(feats, [1 + i % 5 for i in range(48)])
    refs = [model.transform(r)[0]["rawPrediction"] for r in reqs]
    endpoint = serve_model(model, feats.take(1), max_batch_rows=64,
                           max_wait_ms=5.0, queue_capacity=4096)
    results = [None] * len(reqs)

    def client(worker, n_workers):
        for i in range(worker, len(reqs), n_workers):
            results[i] = endpoint.predict(reqs[i], timeout=30)

    try:
        threads = [threading.Thread(target=client, args=(w, 8))
                   for w in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        for out, ref in zip(results, refs):
            np.testing.assert_array_equal(out["rawPrediction"], ref)
        snap = endpoint.metrics.snapshot()
        assert snap["requests"] == len(reqs)
        # 8 concurrent clients against a 5ms wait: batches must coalesce
        assert snap["batches"] < snap["requests"]
        assert 0.0 < snap["batch_fill_ratio"] <= 1.0
        assert snap["latency_p99_ms"] >= snap["latency_p50_ms"] > 0.0
    finally:
        endpoint.close()


@pytest.mark.slow
def test_serving_concurrency_sweep():
    """The bench.py serving sweep shape (1/8/64 clients), asserted for
    correctness and shed-free completion at ample capacity."""
    model = _fit_lr()
    feats = _lr_table(n=512, seed=13).drop("label")
    endpoint = serve_model(model, feats.take(1), max_batch_rows=256,
                           max_wait_ms=1.0, queue_capacity=8192)
    ref = model.transform(feats)[0]["rawPrediction"]
    try:
        for clients in (1, 8, 64):
            per_client = 20 if clients < 64 else 5
            errors = []

            def client(worker):
                rng = np.random.default_rng(worker)
                try:
                    for _ in range(per_client):
                        start = int(rng.integers(0, 500))
                        rows = int(rng.integers(1, 9))
                        req = feats.slice(start, start + rows)
                        out = endpoint.predict(req, timeout=60)
                        np.testing.assert_array_equal(
                            out["rawPrediction"], ref[start:start + rows])
                except Exception as exc:  # noqa: BLE001
                    errors.append(exc)

            threads = [threading.Thread(target=client, args=(w,))
                       for w in range(clients)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(120)
            assert not errors
        assert endpoint.metrics.shed.value == 0
    finally:
        endpoint.close()


def test_metrics_publish_skips_quantiles_when_no_new_samples():
    """The p50/p99 recompute is an O(window) np.quantile pass under the
    ring lock — a metric tick with no new samples must skip it, and the
    pair must come from ONE quantiles() call, not two ring passes."""
    from flink_ml_tpu.serving.metrics import LatencyTracker, ServingMetrics

    m = ServingMetrics()
    calls = []
    real = LatencyTracker.quantiles
    m.latency.quantiles = lambda qs: (calls.append(tuple(qs)) or
                                      real(m.latency, qs))

    m.publish()                       # nothing recorded yet: no pass
    assert calls == []
    m.latency.record(0.010)
    m.publish()
    assert calls == [(0.50, 0.99)]    # one pass for both quantiles
    snap = m.snapshot()
    assert snap["latency_p50_ms"] == pytest.approx(10.0, abs=0.1)

    m.publish()                       # no new samples: skipped
    m.publish()
    assert len(calls) == 1

    m.latency.record(0.030)
    m.publish()                       # new sample: recomputed
    assert len(calls) == 2
