"""Parallel layer tests: mesh construction, collectives, ring attention and
Ulysses sequence parallelism vs. the dense oracle — all on the 8-device
virtual mesh (the MiniCluster analog)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from flink_ml_tpu.parallel import collectives as col
from flink_ml_tpu.parallel.mesh import device_mesh
from flink_ml_tpu.parallel.ring_attention import (
    attention_reference,
    ring_attention,
)
from flink_ml_tpu.parallel.ulysses import ulysses_attention


def _qkv(b=2, s=32, h=4, d=8, seed=0):
    rng = np.random.default_rng(seed)
    shape = (b, s, h, d)
    return (jnp.asarray(rng.normal(size=shape), jnp.float32),
            jnp.asarray(rng.normal(size=shape), jnp.float32),
            jnp.asarray(rng.normal(size=shape), jnp.float32))


def test_device_mesh_shapes():
    mesh = device_mesh({"data": 4, "model": 2})
    assert mesh.shape == {"data": 4, "model": 2}
    inferred = device_mesh({"data": -1, "model": 2})
    assert inferred.shape["data"] == 4
    with pytest.raises(ValueError):
        device_mesh({"data": 3})
    with pytest.raises(ValueError):
        device_mesh({"data": -1, "model": -1})


def test_collectives_inside_shard_map():
    mesh = device_mesh({"data": 8})

    def body(x):
        total = col.psum(jnp.sum(x), "data")
        gathered = col.all_gather(x, "data")
        rotated = col.ppermute_ring(x, "data", shift=1)
        idx = col.axis_index("data")
        return total * jnp.ones_like(x), gathered, rotated, \
            idx * jnp.ones_like(x, jnp.int32)

    x = jnp.arange(8, dtype=jnp.float32)
    fn = col.shard_map_fn(body, mesh, in_specs=P("data"),
                          out_specs=(P("data"), P("data"), P("data"),
                                     P("data")))
    total, gathered, rotated, idx = fn(x)
    np.testing.assert_array_equal(np.asarray(total), [28.0] * 8)
    # all_gather tiled: every shard sees the full vector
    assert gathered.shape == (64,)
    # ring shift by one: shard i's value moves to shard i+1
    np.testing.assert_array_equal(np.asarray(rotated),
                                  [7, 0, 1, 2, 3, 4, 5, 6])
    np.testing.assert_array_equal(np.asarray(idx), np.arange(8))


def test_reduce_scatter():
    mesh = device_mesh({"data": 8})

    def body(x):
        return col.reduce_scatter(x, "data")

    # every shard holds the full 8-vector of ones -> reduce_scatter sums the
    # 8 copies and hands each shard one element
    x = jnp.ones((64,), jnp.float32)
    fn = col.shard_map_fn(body, mesh, in_specs=P("data"), out_specs=P("data"))
    out = fn(x)
    assert out.shape == (8,)
    np.testing.assert_array_equal(np.asarray(out), [8.0] * 8)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_reference(causal):
    mesh = device_mesh({"seq": 8})
    q, k, v = _qkv()
    expected = attention_reference(q, k, v, causal=causal)
    got = ring_attention(q, k, v, mesh=mesh, axis="seq", causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_reference(causal):
    mesh = device_mesh({"seq": 4, "data": 2})
    q, k, v = _qkv(h=8)
    expected = attention_reference(q, k, v, causal=causal)
    got = ulysses_attention(q, k, v, mesh=mesh, axis="seq", causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               atol=2e-5)


def test_ring_attention_long_context_sharded_memory():
    # The point of ring attention: each device only holds seq/n of the
    # sequence; the full (s x s) score matrix never materializes.
    mesh = device_mesh({"seq": 8})
    q, k, v = _qkv(b=1, s=256, h=2, d=4)
    out = ring_attention(q, k, v, mesh=mesh, axis="seq")
    expected = attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               atol=2e-5)
    # output keeps the sequence sharding (older shard_map trims trailing
    # Nones off the spec, so compare the normalized form)
    spec = tuple(out.sharding.spec)
    assert spec[:2] == (None, "seq") and all(s is None for s in spec[2:])


def test_ring_attention_rejects_ragged_seq():
    mesh = device_mesh({"seq": 8})
    q, k, v = _qkv(s=30)
    with pytest.raises(ValueError):
        ring_attention(q, k, v, mesh=mesh, axis="seq")


def test_ulysses_rejects_bad_heads():
    mesh = device_mesh({"seq": 8})
    q, k, v = _qkv(h=4)  # 4 heads < 8 devices
    with pytest.raises(ValueError):
        ulysses_attention(q, k, v, mesh=mesh, axis="seq")


def test_distributed_single_process_degradation():
    from flink_ml_tpu.parallel import distributed as dist

    dist.initialize()
    assert dist.is_initialized()
    info = dist.process_info()
    assert info.process_count == 1 and info.is_coordinator
    assert info.global_device_count == 8

    mesh = dist.global_mesh({"data": -1})
    assert mesh.shape["data"] == 8

    local = {"x": np.arange(16, dtype=np.float32)}
    global_arr = dist.host_local_to_global(local, mesh, axis="data")
    assert len(global_arr["x"].sharding.device_set) == 8
    back = dist.global_to_host_local(global_arr, mesh, axis="data")
    np.testing.assert_array_equal(back["x"], local["x"])

    dist.barrier()  # no-op single process
    assert dist.broadcast_from_host0({"v": 3})["v"] == 3


def test_hybrid_mesh_single_host():
    from flink_ml_tpu.parallel import distributed as dist

    mesh = dist.hybrid_mesh({"data": 4, "model": 2})
    assert mesh.shape == {"dcn": 1, "data": 4, "model": 2}


# ------------------------------------------------- bare-wrapper oracles


def test_reduce_scatter_oracle_random():
    """reduce_scatter vs the numpy oracle on random data: shard i of the
    output is the i-th slice of the sum over participants."""
    mesh = device_mesh({"data": 8})
    rng = np.random.default_rng(0)
    g = rng.normal(size=(8, 64)).astype(np.float32)

    def body(x):
        return col.reduce_scatter(x[0], "data")[None]

    fn = col.shard_map_fn(body, mesh, in_specs=P("data", None),
                          out_specs=P("data", None))
    out = np.asarray(fn(jnp.asarray(g)))  # (8, 8): device i's shard
    expected = g.sum(axis=0).reshape(8, 8)
    np.testing.assert_allclose(out, expected, atol=1e-5)


def test_reduce_scatter_scatter_dimension():
    """scatter_dimension=1 splits the SECOND dim across participants."""
    mesh = device_mesh({"data": 8})
    rng = np.random.default_rng(1)
    g = rng.normal(size=(8, 4, 16)).astype(np.float32)

    def body(x):
        return col.reduce_scatter(x[0], "data", scatter_dimension=1)[None]

    fn = col.shard_map_fn(body, mesh, in_specs=P("data", None, None),
                          out_specs=P("data", None, None))
    out = np.asarray(fn(jnp.asarray(g)))  # (8, 4, 2)
    total = g.sum(axis=0)
    for i in range(8):
        np.testing.assert_allclose(out[i], total[:, 2 * i:2 * i + 2],
                                   atol=1e-5)


@pytest.mark.parametrize("shift", [1, 3, -1])
def test_ppermute_ring_shift_oracle(shift):
    """ppermute_ring(shift=s) == np.roll by s: shard i's value lands on
    shard (i + s) mod n."""
    mesh = device_mesh({"data": 8})

    def body(x):
        return col.ppermute_ring(x, "data", shift=shift)

    fn = col.shard_map_fn(body, mesh, in_specs=P("data"),
                          out_specs=P("data"))
    x = jnp.arange(8, dtype=jnp.float32)
    np.testing.assert_array_equal(np.asarray(fn(x)),
                                  np.roll(np.arange(8.0), shift))


def test_all_gather_untiled_oracle():
    """all_gather(tiled=False) stacks shards on a NEW leading axis — the
    (P, shard) layout the grad_reduce sparse exchange rides on."""
    mesh = device_mesh({"data": 8})
    rng = np.random.default_rng(2)
    g = rng.normal(size=(8, 5)).astype(np.float32)

    def body(x):
        return col.all_gather(x[0], "data", tiled=False)[None]

    fn = col.shard_map_fn(body, mesh, in_specs=P("data", None),
                          out_specs=P("data", None, None))
    out = np.asarray(fn(jnp.asarray(g)))  # (8, 8, 5): each device sees all
    for i in range(8):
        np.testing.assert_array_equal(out[i], g)


def test_pmean_pmax_axis_size_oracle():
    mesh = device_mesh({"data": 8})

    def body(x):
        return (col.pmean(x, "data") * jnp.ones_like(x),
                col.pmax(x, "data") * jnp.ones_like(x),
                col.axis_size("data") * jnp.ones_like(x, jnp.int32))

    x = jnp.asarray([3., -1., 4., 1., 5., -9., 2., 6.])
    fn = col.shard_map_fn(body, mesh, in_specs=P("data"),
                          out_specs=(P("data"), P("data"), P("data")))
    mean, mx, size = fn(x)
    np.testing.assert_allclose(np.asarray(mean), [float(np.mean(x))] * 8,
                               rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(mx), [6.0] * 8)
    np.testing.assert_array_equal(np.asarray(size), [8] * 8)


# ---------------------------------------------------------------------------
# recursive-halving/doubling sparse allreduce (the wire-protocol tier)
# ---------------------------------------------------------------------------


def _run_rd(p, idx, vals, n):
    """Run sparse_all_reduce_rd on a P-subset of the virtual mesh and
    return (dense (P, n), fill (P, FILL_VEC_LEN)) as numpy."""
    mesh = device_mesh({"data": p}, devices=jax.devices()[:p])

    def body(i, v):
        dense, fill = col.sparse_all_reduce_rd(i[0], v[0], n, "data")
        return dense[None], fill[None]

    fn = col.shard_map_fn(body, mesh, in_specs=(P("data"), P("data")),
                          out_specs=(P("data"), P("data")))
    dense, fill = fn(jnp.asarray(idx), jnp.asarray(vals))
    return np.asarray(dense), np.asarray(fill)


def _scatter_oracle(idx, vals, n):
    """The all-gather protocol's answer: every contribution scatter-added
    into a dense (n,) — duplicate indices within one contribution sum."""
    oracle = np.zeros((n,), np.float64)
    for r in range(idx.shape[0]):
        np.add.at(oracle, idx[r], vals[r].astype(np.float64))
    return oracle.astype(np.float32)


def test_rd_topology():
    """core = 2^floor(log2 P), rounds = log2(core), extras fold."""
    assert col.rd_topology(1) == (1, 0, 0)
    assert col.rd_topology(2) == (2, 1, 0)
    assert col.rd_topology(3) == (2, 1, 1)
    assert col.rd_topology(6) == (4, 2, 2)
    assert col.rd_topology(8) == (8, 3, 0)
    with pytest.raises(ValueError):
        col.rd_topology(0)


@pytest.mark.parametrize("p,n,k", [
    # every power-of-two P appears; the shape grid runs in full only at
    # P=8 (each (p, n, k) combo is its own shard_map compile — the full
    # 3x3 cross product is ~80 s of tier-1 compile time for no extra
    # code-path coverage at the smaller rounds counts)
    (2, 100, 7), (4, 64, 4), (8, 64, 4), (8, 100, 7), (8, 16, 16)])
def test_sparse_all_reduce_rd_matches_allgather_oracle(p, n, k):
    """Power-of-two P: the log2(P) halving/doubling rounds produce the
    same dense result as the all-gather oracle, elementwise, replicated
    identically on every participant."""
    rng = np.random.default_rng(p * 100 + n)
    idx = rng.integers(0, n, size=(p, k)).astype(np.int32)
    vals = np.round(rng.normal(size=(p, k)) * 8).astype(np.float32) / 8
    dense, _ = _run_rd(p, idx, vals, n)
    oracle = _scatter_oracle(idx, vals, n)
    for r in range(p):
        np.testing.assert_allclose(dense[r], oracle, atol=1e-5)
    for r in range(1, p):
        np.testing.assert_array_equal(dense[r], dense[0])


@pytest.mark.parametrize("p", [3, 6])
def test_sparse_all_reduce_rd_non_power_of_two(p):
    """P=3/6 fold the extras onto a 2^floor(log2 P) core before the
    rounds and broadcast back after — result still equals the oracle on
    ALL P participants, extras included."""
    n, k = 48, 5
    rng = np.random.default_rng(7)
    idx = rng.integers(0, n, size=(p, k)).astype(np.int32)
    vals = np.round(rng.normal(size=(p, k)) * 8).astype(np.float32) / 8
    dense, _ = _run_rd(p, idx, vals, n)
    oracle = _scatter_oracle(idx, vals, n)
    for r in range(p):
        np.testing.assert_allclose(dense[r], oracle, atol=1e-5,
                                   err_msg=f"participant {r} of {p}")


@pytest.mark.parametrize("p", [3, 8])
def test_sparse_all_reduce_rd_duplicate_indices_sum(p):
    """Duplicate indices WITHIN one contribution sum correctly (the
    merge dedup must not collapse them before scatter semantics apply).
    P=8 exercises the pure halving/doubling dedup, P=3 the pre-fold
    merge; the oracle tests' random indices cover incidental dups at
    the other extents."""
    n, k = 32, 8
    rng = np.random.default_rng(11)
    idx = rng.integers(0, n, size=(p, k)).astype(np.int32)
    idx[:, : k // 2] = idx[:, k // 2:]          # force pairwise dups
    vals = np.round(rng.normal(size=(p, k)) * 8).astype(np.float32) / 8
    dense, _ = _run_rd(p, idx, vals, n)
    oracle = _scatter_oracle(idx, vals, n)
    for r in range(p):
        np.testing.assert_allclose(dense[r], oracle, atol=1e-5)


@pytest.mark.parametrize("p", [2, 6, 8])
def test_sparse_all_reduce_rd_empty_contribution_noop(p):
    """k=0 contributions are a no-op: the result is all zeros and the
    fill vector reports nothing shipped."""
    dense, fill = _run_rd(p, np.zeros((p, 0), np.int32),
                          np.zeros((p, 0), np.float32), 50)
    np.testing.assert_array_equal(dense, np.zeros((p, 50), np.float32))
    np.testing.assert_array_equal(fill, np.zeros_like(fill))


def test_sparse_all_reduce_rd_dense_switchover():
    """Disjoint supports at k = n/2 densify the union past break-even:
    every participant flips to the dense doubling branch (switch slot
    = 1) and the result still matches the oracle."""
    p, n, k = 8, 32, 16
    rng = np.random.default_rng(3)
    idx = rng.integers(0, n, size=(p, k)).astype(np.int32)
    vals = np.round(rng.normal(size=(p, k)) * 8).astype(np.float32) / 8
    dense, fill = _run_rd(p, idx, vals, n)
    oracle = _scatter_oracle(idx, vals, n)
    for r in range(p):
        np.testing.assert_allclose(dense[r], oracle, atol=1e-5)
    np.testing.assert_array_equal(fill[:, col.FILL_SWITCH_SLOT],
                                  np.ones((p,), np.float32))


@pytest.mark.parametrize("p", [2, 3, 6, 8])
def test_fixed_point_all_reduce_is_exact(p):
    """int32 recursive doubling == the integer sum, bit-identical on
    every participant (the SwitchML pool-semantics hop)."""
    q = np.random.default_rng(0).integers(
        -127, 127, size=(p, 33)).astype(np.int32)
    mesh = device_mesh({"data": p}, devices=jax.devices()[:p])

    def body(x):
        return col.fixed_point_all_reduce(x[0], "data")[None]

    fn = col.shard_map_fn(body, mesh, in_specs=P("data"),
                          out_specs=P("data"))
    out = np.asarray(fn(jnp.asarray(q)))
    for r in range(p):
        np.testing.assert_array_equal(out[r], q.sum(0))


# ---------------------------------------------------------------- pipeline


def _mlp_stage(params, x):
    w, b = params
    return jnp.tanh(x @ w + b)


def _stacked_mlp(n_stages, d, seed=0):
    rng = np.random.default_rng(seed)
    w = jnp.asarray(rng.normal(size=(n_stages, d, d)) * 0.3, jnp.float32)
    b = jnp.asarray(rng.normal(size=(n_stages, d)) * 0.1, jnp.float32)
    return (w, b)


def _sequential(params, x):
    w, b = params
    for i in range(w.shape[0]):
        x = _mlp_stage((w[i], b[i]), x)
    return x


def test_pipeline_matches_sequential():
    from flink_ml_tpu.parallel.pipeline_parallel import build_pipeline

    mesh = device_mesh({"pipe": 8})
    d, batch = 16, 24
    params = _stacked_mlp(8, d)
    x = jnp.asarray(np.random.default_rng(1).normal(size=(batch, d)),
                    jnp.float32)
    fn = build_pipeline(_mlp_stage, mesh, n_micro=4)
    np.testing.assert_allclose(np.asarray(fn(params, x)),
                               np.asarray(_sequential(params, x)),
                               rtol=1e-5, atol=1e-5)


def test_pipeline_grad_matches_sequential():
    # jax.grad through the scan+ppermute IS the backward pipeline; it must
    # agree with the grad of the plain stacked-layer forward.
    from flink_ml_tpu.parallel.pipeline_parallel import build_pipeline

    mesh = device_mesh({"pipe": 4}, devices=jax.devices()[:4])
    d, batch = 8, 16
    params = _stacked_mlp(4, d)
    x = jnp.asarray(np.random.default_rng(2).normal(size=(batch, d)),
                    jnp.float32)
    y = jnp.asarray(np.random.default_rng(3).normal(size=(batch, d)),
                    jnp.float32)
    fn = build_pipeline(_mlp_stage, mesh, n_micro=4)

    def loss_pp(p):
        return jnp.mean((fn(p, x) - y) ** 2)

    def loss_seq(p):
        return jnp.mean((_sequential(p, x) - y) ** 2)

    g_pp = jax.grad(loss_pp)(params)
    g_seq = jax.grad(loss_seq)(params)
    for a, b in zip(jax.tree_util.tree_leaves(g_pp),
                    jax.tree_util.tree_leaves(g_seq)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_pipeline_composes_with_data_parallel():
    from flink_ml_tpu.parallel.pipeline_parallel import build_pipeline

    mesh = device_mesh({"data": 2, "pipe": 4})
    d, batch = 8, 32
    params = _stacked_mlp(4, d, seed=4)
    x = jnp.asarray(np.random.default_rng(5).normal(size=(batch, d)),
                    jnp.float32)
    fn = build_pipeline(_mlp_stage, mesh, n_micro=4, data_axis="data")
    np.testing.assert_allclose(np.asarray(fn(params, x)),
                               np.asarray(_sequential(params, x)),
                               rtol=1e-5, atol=1e-5)


def test_pipeline_validation_errors():
    from flink_ml_tpu.parallel.pipeline_parallel import build_pipeline

    mesh = device_mesh({"pipe": 4}, devices=jax.devices()[:4])
    fn = build_pipeline(_mlp_stage, mesh, n_micro=3)
    params = _stacked_mlp(4, 8)
    x = jnp.zeros((16, 8), jnp.float32)  # 16 % 3 != 0
    with pytest.raises(ValueError, match="not divisible by n_micro"):
        fn(params, x)
    bad = _stacked_mlp(3, 8)  # 3 stages on a 4-wide pipe axis
    with pytest.raises(ValueError, match="params leading dim"):
        build_pipeline(_mlp_stage, mesh, n_micro=4)(bad, jnp.zeros((8, 8)))
    with pytest.raises(ValueError, match="no axis 'pipe'"):
        build_pipeline(_mlp_stage, device_mesh({"data": 8}), n_micro=2)


# ---------------------------------------------------------------- MoE / ep


def _moe_setup(n_tokens=32, d=8, hidden=16, experts=4, seed=7):
    from flink_ml_tpu.parallel.moe import init_moe

    rng = np.random.default_rng(seed)
    params = init_moe(rng, d, hidden, experts)
    x = jnp.asarray(rng.normal(size=(n_tokens, d)), jnp.float32)
    return params, x


def _moe_oracle(params, x):
    """Per-token: run the argmax expert densely (no capacity)."""
    gates = jax.nn.softmax(x @ params.wg, axis=-1)
    top1 = np.asarray(jnp.argmax(gates, axis=-1))
    out = np.zeros_like(np.asarray(x))
    for t in range(x.shape[0]):
        e = top1[t]
        h = jax.nn.gelu(x[t] @ params.w_in[e])
        out[t] = np.asarray((h @ params.w_out[e])
                            * gates[t, e])
    return out


def test_moe_matches_per_token_oracle():
    from flink_ml_tpu.parallel.moe import moe_apply

    params, x = _moe_setup()
    # generous capacity so nothing drops
    y = moe_apply(params, x, capacity_factor=4.0, mesh=None)
    np.testing.assert_allclose(np.asarray(y), _moe_oracle(params, x),
                               rtol=1e-4, atol=1e-5)


def test_moe_sharded_matches_unsharded():
    from flink_ml_tpu.parallel.moe import moe_apply, moe_sharding

    mesh = device_mesh({"data": 2, "expert": 4})
    params, x = _moe_setup(n_tokens=64)
    shardings = moe_sharding(mesh)
    params_s = jax.device_put(params, shardings)
    x_s = jax.device_put(x, jax.sharding.NamedSharding(mesh, P("data")))

    fn = jax.jit(lambda p, x: moe_apply(
        p, x, capacity_factor=4.0, mesh=mesh, data_axis="data"))
    y_sharded = fn(params_s, x_s)
    y_local = moe_apply(params, x, capacity_factor=4.0, mesh=None)
    np.testing.assert_allclose(np.asarray(y_sharded), np.asarray(y_local),
                               rtol=1e-4, atol=1e-5)


def test_moe_capacity_drops_overflow_tokens():
    from flink_ml_tpu.parallel.moe import moe_apply

    params, x = _moe_setup(n_tokens=16)
    # capacity_factor tiny -> capacity 1 per expert: at most E tokens survive
    y = moe_apply(params, x, capacity_factor=1e-6, mesh=None)
    nonzero_rows = np.count_nonzero(
        np.any(np.abs(np.asarray(y)) > 0, axis=1))
    assert nonzero_rows <= params.wg.shape[1]
    assert np.all(np.isfinite(np.asarray(y)))


def test_moe_bf16_routing_matches_f32():
    # Routing bookkeeping must be precision-independent: bf16 inputs route
    # identically to f32 (a bf16 cumsum would collide queue positions).
    from flink_ml_tpu.parallel.moe import moe_apply

    params, x = _moe_setup(n_tokens=2048, d=8, experts=4)
    y32 = moe_apply(params, x, capacity_factor=4.0, mesh=None)
    y16 = moe_apply(params, x.astype(jnp.bfloat16), capacity_factor=4.0,
                    mesh=None)
    assert y16.dtype == jnp.bfloat16
    # A few borderline tokens may flip argmax expert under bf16 gating
    # rounding (legitimate); queue-position collisions would corrupt the
    # majority of tokens (several tokens summed into one capacity slot).
    diff = np.abs(np.asarray(y16, np.float32) - np.asarray(y32))
    frac_bad = np.mean(np.any(diff > 0.05, axis=1))
    assert frac_bad < 0.02, f"{frac_bad:.1%} tokens corrupted"


def test_moe_grouped_matches_per_group_apply():
    from flink_ml_tpu.parallel.moe import moe_apply

    params, x = _moe_setup(n_tokens=64)
    grouped = moe_apply(params, x, capacity_factor=4.0, group_size=16,
                        mesh=None)
    per_group = jnp.concatenate([
        moe_apply(params, x[i:i + 16], capacity_factor=4.0, mesh=None)
        for i in range(0, 64, 16)])
    np.testing.assert_allclose(np.asarray(grouped), np.asarray(per_group),
                               rtol=1e-5, atol=1e-6)


def test_moe_grouped_sharded_matches_local():
    from flink_ml_tpu.parallel.moe import moe_apply, moe_sharding

    mesh = device_mesh({"data": 2, "expert": 4})
    params, x = _moe_setup(n_tokens=64)
    params_s = jax.device_put(params, moe_sharding(mesh))
    x_s = jax.device_put(x, jax.sharding.NamedSharding(mesh, P("data")))
    fn = jax.jit(lambda p, t: moe_apply(
        p, t, capacity_factor=4.0, group_size=8, mesh=mesh,
        data_axis="data"))
    y = fn(params_s, x_s)
    y_local = moe_apply(params, x, capacity_factor=4.0, group_size=8,
                        mesh=None)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_local),
                               rtol=1e-4, atol=1e-5)


def test_moe_group_size_must_divide():
    from flink_ml_tpu.parallel.moe import moe_apply

    params, x = _moe_setup(n_tokens=32)
    with pytest.raises(ValueError, match="not divisible by group_size"):
        moe_apply(params, x, group_size=7, mesh=None)
