"""Parallel layer tests: mesh construction, collectives, ring attention and
Ulysses sequence parallelism vs. the dense oracle — all on the 8-device
virtual mesh (the MiniCluster analog)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from flink_ml_tpu.parallel import collectives as col
from flink_ml_tpu.parallel.mesh import device_mesh
from flink_ml_tpu.parallel.ring_attention import (
    attention_reference,
    ring_attention,
)
from flink_ml_tpu.parallel.ulysses import ulysses_attention


def _qkv(b=2, s=32, h=4, d=8, seed=0):
    rng = np.random.default_rng(seed)
    shape = (b, s, h, d)
    return (jnp.asarray(rng.normal(size=shape), jnp.float32),
            jnp.asarray(rng.normal(size=shape), jnp.float32),
            jnp.asarray(rng.normal(size=shape), jnp.float32))


def test_device_mesh_shapes():
    mesh = device_mesh({"data": 4, "model": 2})
    assert mesh.shape == {"data": 4, "model": 2}
    inferred = device_mesh({"data": -1, "model": 2})
    assert inferred.shape["data"] == 4
    with pytest.raises(ValueError):
        device_mesh({"data": 3})
    with pytest.raises(ValueError):
        device_mesh({"data": -1, "model": -1})


def test_collectives_inside_shard_map():
    mesh = device_mesh({"data": 8})

    def body(x):
        total = col.psum(jnp.sum(x), "data")
        gathered = col.all_gather(x, "data")
        rotated = col.ppermute_ring(x, "data", shift=1)
        idx = col.axis_index("data")
        return total * jnp.ones_like(x), gathered, rotated, \
            idx * jnp.ones_like(x, jnp.int32)

    x = jnp.arange(8, dtype=jnp.float32)
    fn = col.shard_map_fn(body, mesh, in_specs=P("data"),
                          out_specs=(P("data"), P("data"), P("data"),
                                     P("data")))
    total, gathered, rotated, idx = fn(x)
    np.testing.assert_array_equal(np.asarray(total), [28.0] * 8)
    # all_gather tiled: every shard sees the full vector
    assert gathered.shape == (64,)
    # ring shift by one: shard i's value moves to shard i+1
    np.testing.assert_array_equal(np.asarray(rotated),
                                  [7, 0, 1, 2, 3, 4, 5, 6])
    np.testing.assert_array_equal(np.asarray(idx), np.arange(8))


def test_reduce_scatter():
    mesh = device_mesh({"data": 8})

    def body(x):
        return col.reduce_scatter(x, "data")

    # every shard holds the full 8-vector of ones -> reduce_scatter sums the
    # 8 copies and hands each shard one element
    x = jnp.ones((64,), jnp.float32)
    fn = col.shard_map_fn(body, mesh, in_specs=P("data"), out_specs=P("data"))
    out = fn(x)
    assert out.shape == (8,)
    np.testing.assert_array_equal(np.asarray(out), [8.0] * 8)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_reference(causal):
    mesh = device_mesh({"seq": 8})
    q, k, v = _qkv()
    expected = attention_reference(q, k, v, causal=causal)
    got = ring_attention(q, k, v, mesh=mesh, axis="seq", causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_reference(causal):
    mesh = device_mesh({"seq": 4, "data": 2})
    q, k, v = _qkv(h=8)
    expected = attention_reference(q, k, v, causal=causal)
    got = ulysses_attention(q, k, v, mesh=mesh, axis="seq", causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               atol=2e-5)


def test_ring_attention_long_context_sharded_memory():
    # The point of ring attention: each device only holds seq/n of the
    # sequence; the full (s x s) score matrix never materializes.
    mesh = device_mesh({"seq": 8})
    q, k, v = _qkv(b=1, s=256, h=2, d=4)
    out = ring_attention(q, k, v, mesh=mesh, axis="seq")
    expected = attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               atol=2e-5)
    # output keeps the sequence sharding
    assert out.sharding.spec == P(None, "seq", None, None)


def test_ring_attention_rejects_ragged_seq():
    mesh = device_mesh({"seq": 8})
    q, k, v = _qkv(s=30)
    with pytest.raises(ValueError):
        ring_attention(q, k, v, mesh=mesh, axis="seq")


def test_ulysses_rejects_bad_heads():
    mesh = device_mesh({"seq": 8})
    q, k, v = _qkv(h=4)  # 4 heads < 8 devices
    with pytest.raises(ValueError):
        ulysses_attention(q, k, v, mesh=mesh, axis="seq")


def test_distributed_single_process_degradation():
    from flink_ml_tpu.parallel import distributed as dist

    dist.initialize()
    assert dist.is_initialized()
    info = dist.process_info()
    assert info.process_count == 1 and info.is_coordinator
    assert info.global_device_count == 8

    mesh = dist.global_mesh({"data": -1})
    assert mesh.shape["data"] == 8

    local = {"x": np.arange(16, dtype=np.float32)}
    global_arr = dist.host_local_to_global(local, mesh, axis="data")
    assert len(global_arr["x"].sharding.device_set) == 8
    back = dist.global_to_host_local(global_arr, mesh, axis="data")
    np.testing.assert_array_equal(back["x"], local["x"])

    dist.barrier()  # no-op single process
    assert dist.broadcast_from_host0({"v": 3})["v"] == 3


def test_hybrid_mesh_single_host():
    from flink_ml_tpu.parallel import distributed as dist

    mesh = dist.hybrid_mesh({"data": 4, "model": 2})
    assert mesh.shape == {"dcn": 1, "data": 4, "model": 2}
