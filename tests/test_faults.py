"""Chaos suite: deterministic fault injection + self-healing recovery.

The robustness PR's acceptance bar: with a seeded ``FaultPlan``,
``resilient_fit`` survives an injected mid-epoch crash PLUS a corrupted
newest checkpoint — it quarantines the bad cut, falls back to the
previous valid one, replays the source/WAL past the cursor, and
finishes with params BIT-exact vs the uninterrupted run (grad-reduce EF
residual state included); a hot-swap to a corrupt model directory rolls
back and the endpoint keeps answering bit-exact on the old generation
with zero dropped in-flight requests.
"""

import os
import threading

import numpy as np
import pytest

from flink_ml_tpu import Table
from flink_ml_tpu.data.wal import WindowLog
from flink_ml_tpu.iteration import CheckpointConfig, IterationBodyResult, \
    IterationConfig, iterate
from flink_ml_tpu.iteration.checkpoint import CheckpointManager
from flink_ml_tpu.robustness import (
    CorruptStateError,
    FaultPlan,
    InjectedCrash,
    InjectedTransientError,
    RecoveryReport,
    RetryPolicy,
    corrupt_file,
    resilient_fit,
    verify_dir,
)


# -- fault plan determinism --------------------------------------------------

def test_fault_plan_explicit_schedule_fires_in_order():
    plan = FaultPlan().inject("s", at=2, kind="transient", times=2)
    plan.inject("s", at=7, kind="crash")
    assert plan.scheduled("s") == [(2, "transient"), (3, "transient"),
                                   (7, "crash")]
    seen = []
    for i in range(9):
        try:
            plan.fire("s")
        except InjectedTransientError:
            seen.append((i, "transient"))
        except InjectedCrash:
            seen.append((i, "crash"))
    assert seen == [(2, "transient"), (3, "transient"), (7, "crash")]
    assert plan.fires == [("s", 2, "transient"), ("s", 3, "transient"),
                          ("s", 7, "crash")]


def test_fault_plan_random_schedule_is_seed_deterministic():
    a = FaultPlan(seed=5).inject_random("s", rate=0.3, horizon=50)
    b = FaultPlan(seed=5).inject_random("s", rate=0.3, horizon=50)
    c = FaultPlan(seed=6).inject_random("s", rate=0.3, horizon=50)
    assert a.scheduled("s") == b.scheduled("s")
    assert a.scheduled("s") != c.scheduled("s")
    assert 0 < len(a.scheduled("s")) < 50


def test_fault_plan_random_schedule_is_stable_across_processes():
    """The seeded schedule must not depend on Python's per-process str
    hash salt — a chaos failure found in CI has to reproduce locally.
    Pinning the literal indices locks the (seed, scope, kind) -> crc32
    key derivation."""
    plan = FaultPlan(seed=7).inject_random("source.pull", rate=0.1,
                                           horizon=100)
    assert plan.scheduled("source.pull") == [
        (3, "transient"), (57, "transient"), (70, "transient"),
        (71, "transient"), (76, "transient")]


def test_wrap_source_transient_is_lossless_on_retry():
    """The transient fault fires BEFORE the underlying pull, so the
    retried next() returns the item that would otherwise be lost — the
    contract prefetch's retry_policy rides."""
    plan = FaultPlan().inject("source.pull", at=1, kind="transient")
    src = plan.wrap_source([10, 11, 12])
    assert next(src) == 10
    with pytest.raises(InjectedTransientError):
        next(src)
    assert next(src) == 11      # nothing consumed by the failed pull
    assert next(src) == 12


def test_corrupt_file_modes(tmp_path):
    p = str(tmp_path / "f")
    payload = bytes(range(256)) * 8
    open(p, "wb").write(payload)
    corrupt_file(p, mode="flip", seed=3)
    flipped = open(p, "rb").read()
    assert len(flipped) == len(payload)
    assert sum(a != b for a, b in zip(flipped, payload)) == 1
    corrupt_file(p, mode="torn", seed=3)
    assert 0 < os.path.getsize(p) < len(payload)


# -- retry policy ------------------------------------------------------------

def test_retry_backoff_schedule_is_deterministic():
    slept = []
    p = RetryPolicy(max_attempts=5, base_delay=0.1, multiplier=2.0,
                    max_delay=0.5, sleep=slept.append)
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 5:
            raise InjectedTransientError("again")
        return "ok"

    assert p.call(flaky) == "ok"
    assert slept == [0.1, 0.2, 0.4, 0.5]   # exponential, capped
    assert p.retries == 4


def test_retry_fatal_errors_fail_fast():
    slept = []
    p = RetryPolicy(max_attempts=5, sleep=slept.append)
    with pytest.raises(ValueError):
        p.call(lambda: (_ for _ in ()).throw(ValueError("bad config")))
    assert slept == []          # not classified retryable: zero sleeps
    with pytest.raises(InjectedCrash):
        p.call(lambda: (_ for _ in ()).throw(InjectedCrash("boom")))
    assert slept == []


def test_retry_exhaustion_reraises_underlying_error():
    p = RetryPolicy(max_attempts=3, sleep=lambda s: None)
    with pytest.raises(InjectedTransientError):
        p.call(lambda: (_ for _ in ()).throw(
            InjectedTransientError("always")))
    assert p.attempts == 3


# -- validated checkpoints ---------------------------------------------------

def _save_epochs(mgr, n):
    for e in range(n):
        mgr.save(e, {"w": np.arange(4.0) * (e + 1), "b": float(e)})


def test_corrupt_newest_checkpoint_quarantined_and_falls_back(tmp_path):
    mgr = CheckpointManager(CheckpointConfig(str(tmp_path), max_to_keep=5))
    _save_epochs(mgr, 3)
    corrupt_file(str(tmp_path / "ckpt-00000002" / "leaves.npz"))
    epoch, state, _ = mgr.latest()
    assert epoch == 1
    np.testing.assert_array_equal(state["w"], np.arange(4.0) * 2)
    # the bad cut was moved aside, not deleted, and scans skip it now
    names = sorted(os.listdir(tmp_path))
    assert "ckpt-00000002.corrupt" in names
    assert mgr.list_epochs() == [0, 1]


def test_legacy_cut_missing_payload_quarantined_and_falls_back(tmp_path):
    """A pre-manifest (legacy) checkpoint dir passes verify_dir's legacy
    path, then hits FileNotFoundError on its missing payload — latest()
    must quarantine and fall back, not crash the scan."""
    from flink_ml_tpu.robustness.durability import COMMIT_MARKER, MANIFEST_NAME

    mgr = CheckpointManager(CheckpointConfig(str(tmp_path), max_to_keep=5))
    _save_epochs(mgr, 2)
    newest = tmp_path / "ckpt-00000001"
    os.remove(newest / "leaves.npz")            # partial legacy save
    for name in (MANIFEST_NAME, COMMIT_MARKER):  # strip to pre-manifest form
        os.remove(newest / name)
    epoch, state, _ = mgr.latest()
    assert epoch == 0
    assert "ckpt-00000001.corrupt" in sorted(os.listdir(tmp_path))


def test_resilient_fit_mttr_uses_injected_clock(tmp_path):
    """detect and restore stamps must come from the SAME clock: with a
    fake clock, mttr_s is fake-clock arithmetic, never a perf_counter
    delta (which would be wall-clock garbage ~1e5 s)."""
    ticks = {"t": 0.0}

    def fake_clock():
        ticks["t"] += 1.0
        return ticks["t"]

    mgr = CheckpointManager(CheckpointConfig(str(tmp_path / "ck")))
    _save_epochs(mgr, 1)
    calls = {"n": 0}

    def fit(checkpoint, resume):
        calls["n"] += 1
        if calls["n"] == 1:
            raise InjectedCrash("boom")
        checkpoint.latest()                     # the restore of a resume
        return "ok"

    report = RecoveryReport()
    assert resilient_fit(fit, checkpoint=mgr, max_restarts=1,
                         backoff=RetryPolicy(sleep=lambda s: None),
                         report=report, clock=fake_clock) == "ok"
    [event] = report.events
    assert event.mttr_s is not None and 0 < event.mttr_s < 10
    assert event.restored_step == 0


def test_torn_tail_of_every_payload_file_is_detected(tmp_path):
    for fname in ("leaves.npz", "structure.json"):
        d = tmp_path / fname.replace(".", "_")
        mgr = CheckpointManager(CheckpointConfig(str(d), max_to_keep=5))
        _save_epochs(mgr, 2)
        corrupt_file(str(d / "ckpt-00000001" / fname), mode="torn")
        with pytest.raises(CorruptStateError, match="torn|CRC|decode"):
            verify_dir(str(d / "ckpt-00000001"))
        epoch, _, _ = mgr.latest()
        assert epoch == 0


def test_crash_mid_commit_never_publishes(tmp_path):
    mgr = CheckpointManager(CheckpointConfig(str(tmp_path), max_to_keep=5))
    _save_epochs(mgr, 2)
    plan = FaultPlan().inject("checkpoint.write", at=0, kind="crash")
    with plan:
        with pytest.raises(InjectedCrash):
            mgr.save(2, {"w": np.zeros(4), "b": 0.0})
    # the half-written tmp is invisible; the previous cut restores
    assert mgr.list_epochs() == [0, 1]
    assert mgr.latest()[0] == 1


def test_torn_write_at_commit_caught_by_validation(tmp_path):
    mgr = CheckpointManager(CheckpointConfig(str(tmp_path), max_to_keep=5))
    _save_epochs(mgr, 2)
    with FaultPlan().inject("checkpoint.write", at=0, kind="torn"):
        mgr.save(2, {"w": np.zeros(4), "b": 0.0})   # commits... torn
    assert mgr.list_epochs() == [0, 1, 2]
    epoch, state, _ = mgr.latest()                  # detected + quarantined
    assert epoch == 1
    assert any(n.endswith(".corrupt") for n in os.listdir(tmp_path))


def test_enospc_at_commit_is_fatal_not_retryable(tmp_path):
    from flink_ml_tpu.robustness.retry import default_classify

    mgr = CheckpointManager(CheckpointConfig(str(tmp_path)))
    with FaultPlan().inject("checkpoint.write", at=0, kind="enospc"):
        with pytest.raises(OSError) as ei:
            mgr.save(0, {"w": np.zeros(2)})
    assert not default_classify(ei.value)


# -- WAL torn tail -----------------------------------------------------------

def _windows(lo, hi, rows=4):
    for i in range(lo, hi):
        yield Table({"x": np.full((rows,), float(i), np.float32),
                     "i": np.full((rows,), i, np.int64)})


def test_wal_torn_tail_is_truncated_and_stream_heals(tmp_path):
    d = str(tmp_path / "wal")
    log = WindowLog(_windows(0, 6), d)
    assert sum(1 for _ in log) == 6
    corrupt_file(os.path.join(d, "win-00000005.npz"), mode="torn")
    # fresh run over the dirty dir: replays 0..4, DROPS the torn tail
    # (its consumer never saw it), then continues live
    healed = WindowLog(_windows(5, 8), d)
    replayed = [int(t["i"][0]) for t in healed]
    assert replayed == [0, 1, 2, 3, 4, 5, 6, 7]
    assert not os.path.exists(os.path.join(d, "win-00000005.npz")) \
        or int(replayed[5]) == 5   # tail rewritten by the live phase


def test_wal_corrupt_non_tail_raises_diagnosable(tmp_path):
    d = str(tmp_path / "wal")
    log = WindowLog(_windows(0, 6), d)
    assert sum(1 for _ in log) == 6
    corrupt_file(os.path.join(d, "win-00000002.npz"))
    bad = WindowLog(iter(()), d)
    with pytest.raises(CorruptStateError, match="win|window 2"):
        list(bad)


def test_wal_append_retries_transient_then_lands(tmp_path):
    d = str(tmp_path / "wal")
    slept = []
    plan = FaultPlan().inject("wal.append", at=1, kind="transient", times=2)
    log = WindowLog(_windows(0, 4), d, retry_policy=RetryPolicy(
        max_attempts=4, base_delay=0.01, sleep=slept.append))
    with plan:
        n = sum(1 for _ in log)
    assert n == 4 and len(slept) == 2
    assert len([f for f in os.listdir(d) if f.endswith(".npz")]) == 4
    # and WITHOUT a retry policy the same fault kills the stream
    plan2 = FaultPlan().inject("wal.append", at=1, kind="transient")
    log2 = WindowLog(_windows(0, 4), str(tmp_path / "wal2"))
    with plan2:
        with pytest.raises(InjectedTransientError):
            list(log2)


# -- prefetch retry ----------------------------------------------------------

def test_prefetch_retries_transient_source_pulls():
    from flink_ml_tpu.data.prefetch import prefetch_to_device

    plan = FaultPlan().inject("source.pull", at=3, kind="transient",
                              times=2)
    slept = []
    policy = RetryPolicy(max_attempts=4, base_delay=0.01,
                         sleep=slept.append)
    batches = [np.full((2,), i, np.float32) for i in range(6)]
    out = list(prefetch_to_device(plan.wrap_source(batches),
                                  retry_policy=policy))
    np.testing.assert_array_equal(
        np.stack([np.asarray(b) for b in out]),
        np.stack(batches))
    assert len(slept) == 2      # two transient faults, two backoffs
    # fatal faults still propagate (in stream order)
    plan2 = FaultPlan().inject("source.pull", at=2, kind="crash")
    it = prefetch_to_device(plan2.wrap_source(batches),
                            retry_policy=policy)
    got = [np.asarray(next(it))[0], np.asarray(next(it))[0]]
    with pytest.raises(InjectedCrash):
        next(it)
    assert got == [0.0, 1.0]


def test_retrying_iterator_survives_generator_adapters():
    """The regression the reader-level wrap exists for: a generator
    above the retry layer must never see the transient — a generator
    that propagates an exception is dead forever, so retrying ABOVE it
    silently truncates the stream."""
    from flink_ml_tpu.robustness.retry import RetryingIterator

    plan = FaultPlan().inject("source.pull", at=2, kind="transient")
    policy = RetryPolicy(max_attempts=3, base_delay=0.0,
                         sleep=lambda s: None)
    wrapped = RetryingIterator(plan.wrap_source(range(5)), policy)
    chain = (x * 10 for x in wrapped)        # the sgd-style adapter
    assert list(chain) == [0, 10, 20, 30, 40]
    assert policy.retries == 1


# -- self-healing training (THE acceptance test) ----------------------------

def _lr_cache(tmp_path, name, n=1536, d=8, seed=7):
    from flink_ml_tpu.data.datacache import DataCacheWriter

    rng = np.random.default_rng(seed)
    true_w = rng.normal(size=(d,))
    cache = str(tmp_path / name)
    writer = DataCacheWriter(cache, segment_rows=512)
    for _ in range(n // 512):
        X = rng.normal(size=(512, d)).astype(np.float32)
        writer.append({"features": X,
                       "label": (X @ true_w > 0).astype(np.float32)})
    writer.finish()
    return cache


def test_resilient_fit_survives_crash_plus_corrupt_newest_checkpoint(
        tmp_path):
    """Mid-epoch crash AND a torn newest checkpoint: resilient_fit
    quarantines the bad cut, restores the previous valid one, replays
    the reader past the cursor, and lands BIT-exact on the uninterrupted
    run — with topk-EF gradient compression, so the reducer residual
    state provably rides the recovery too."""
    from flink_ml_tpu.data.datacache import DataCacheReader
    from flink_ml_tpu.models.common.losses import logistic_loss
    from flink_ml_tpu.models.common.sgd import SGDConfig, sgd_fit_outofcore
    from flink_ml_tpu.parallel.grad_reduce import GradReduceConfig

    cache = _lr_cache(tmp_path, "c1")
    cfg = SGDConfig(learning_rate=0.4, max_epochs=4, tol=0.0,
                    grad_reduce=GradReduceConfig(mode="topk", density=0.25))
    kw = dict(num_features=8, config=cfg, cache_decoded=False,
              steps_per_dispatch=2)
    # 1536 rows / 256 = 6 batches per epoch; cuts every 2 steps

    def reader():
        return DataCacheReader(cache, batch_rows=256)

    ref_state, ref_log = sgd_fit_outofcore(logistic_loss, reader, **kw)

    # fault plan: the cut at epoch-2 step-2 (the 9th checkpoint.write:
    # 4 per epoch — three mid-epoch + one boundary) commits TORN bytes;
    # the crash then fires at source pull 17 (7 pulls/epoch — 6 batches
    # + the end-of-stream probe — so 17 = epoch 2, batch 4).  Recovery
    # must detect the torn newest cut, quarantine it, and fall back to
    # the epoch-2 boundary cut — replaying MORE steps, still bit-exact.
    plan = (FaultPlan(seed=3)
            .inject("checkpoint.write", at=8, kind="torn")
            .inject("source.pull", at=17, kind="crash"))

    report = RecoveryReport()
    slept = []
    with plan:
        state, log = resilient_fit(
            sgd_fit_outofcore, logistic_loss,
            lambda: plan.wrap_source(reader()),
            checkpoint=CheckpointConfig(str(tmp_path / "ck"),
                                        max_to_keep=4),
            checkpoint_every_steps=2, max_restarts=2,
            backoff=RetryPolicy(base_delay=0.01, sleep=slept.append),
            report=report, **kw)

    # both faults fired (wall-clock order varies: prefetch pulls run
    # ahead of compute, so the crash can fire before the torn write)
    assert sorted(f[0] for f in plan.fires) == ["checkpoint.write",
                                                "source.pull"]
    assert report.restarts == 1 and report.recovered
    assert report.events[0].mttr_s is not None
    assert slept == [0.01]
    # the torn cut was quarantined during recovery
    assert any(n.endswith(".corrupt")
               for n in os.listdir(tmp_path / "ck"))
    np.testing.assert_array_equal(state.coefficients, ref_state.coefficients)
    assert state.intercept == ref_state.intercept
    np.testing.assert_array_equal(log, ref_log)


def test_resilient_fit_adaptive_overlap_crash_resumes_bitexact(tmp_path):
    """ISSUE 6 acceptance extension of the pair above: the crash now
    lands MID adaptive-window with bucketed one-step-stale overlap — so
    recovery must round-trip the pending gradient buffer, the per-leaf
    rung/EMA policy state, and the EF residual (all riding the params
    carry under GR_STATE_KEY), and the fit-end drain must apply the same
    mass either way.  Any dropped or re-zeroed piece of the schedule
    state breaks bit-exactness."""
    from flink_ml_tpu.data.datacache import DataCacheReader
    from flink_ml_tpu.models.common.losses import logistic_loss
    from flink_ml_tpu.models.common.sgd import SGDConfig, sgd_fit_outofcore
    from flink_ml_tpu.parallel.grad_reduce import GradReduceConfig

    cache = _lr_cache(tmp_path, "c_adaptive")
    cfg = SGDConfig(learning_rate=0.4, max_epochs=4, tol=0.0,
                    grad_reduce=GradReduceConfig(
                        mode="topk", density=0.25, bucket_count=3,
                        overlap=True, adaptive=True, adaptive_window=3))
    kw = dict(num_features=8, config=cfg, cache_decoded=False,
              steps_per_dispatch=2)
    # 6 batches/epoch, cuts every 2 steps, window 3: global step 17 (the
    # crash) sits mid-window — tick 16 of window [15, 18)

    def reader():
        return DataCacheReader(cache, batch_rows=256)

    ref_state, ref_log = sgd_fit_outofcore(logistic_loss, reader, **kw)

    plan = (FaultPlan(seed=5)
            .inject("checkpoint.write", at=8, kind="torn")
            .inject("source.pull", at=17, kind="crash"))
    report = RecoveryReport()
    with plan:
        state, log = resilient_fit(
            sgd_fit_outofcore, logistic_loss,
            lambda: plan.wrap_source(reader()),
            checkpoint=CheckpointConfig(str(tmp_path / "ck_a"),
                                        max_to_keep=4),
            checkpoint_every_steps=2, max_restarts=2,
            backoff=RetryPolicy(base_delay=0.01, sleep=lambda s: None),
            report=report, **kw)

    assert report.restarts == 1 and report.recovered
    assert any(n.endswith(".corrupt")
               for n in os.listdir(tmp_path / "ck_a"))
    np.testing.assert_array_equal(state.coefficients, ref_state.coefficients)
    assert state.intercept == ref_state.intercept
    np.testing.assert_array_equal(log, ref_log)


def test_outofcore_reader_retry_heals_transient_exactly(tmp_path):
    """sgd_fit_outofcore(retry_policy=): a transient reader failure
    mid-epoch costs a backoff, not the fit — and the healed run's params
    are bit-exact vs the fault-free run (nothing skipped, nothing
    doubled)."""
    from flink_ml_tpu.data.datacache import DataCacheReader
    from flink_ml_tpu.models.common.losses import logistic_loss
    from flink_ml_tpu.models.common.sgd import SGDConfig, sgd_fit_outofcore

    cache = _lr_cache(tmp_path, "cretry")
    cfg = SGDConfig(learning_rate=0.4, max_epochs=3, tol=0.0)
    kw = dict(num_features=8, config=cfg, cache_decoded=False)

    def reader():
        return DataCacheReader(cache, batch_rows=256)

    ref_state, ref_log = sgd_fit_outofcore(logistic_loss, reader, **kw)

    plan = FaultPlan().inject("source.pull", at=9, kind="transient",
                              times=2)
    slept = []
    policy = RetryPolicy(max_attempts=4, base_delay=0.01,
                         sleep=slept.append)
    state, log = sgd_fit_outofcore(
        logistic_loss, lambda: plan.wrap_source(reader()),
        retry_policy=policy, **kw)
    assert len(slept) == 2
    np.testing.assert_array_equal(state.coefficients, ref_state.coefficients)
    np.testing.assert_array_equal(log, ref_log)
    # and WITHOUT the policy, the same transient kills the fit
    plan2 = FaultPlan().inject("source.pull", at=9, kind="transient")
    with pytest.raises(InjectedTransientError):
        sgd_fit_outofcore(logistic_loss,
                          lambda: plan2.wrap_source(reader()), **kw)


def test_resilient_fit_exhausted_restarts_reraises(tmp_path):
    from flink_ml_tpu.data.datacache import DataCacheReader
    from flink_ml_tpu.models.common.losses import logistic_loss
    from flink_ml_tpu.models.common.sgd import SGDConfig, sgd_fit_outofcore

    cache = _lr_cache(tmp_path, "c2", n=512)
    cfg = SGDConfig(max_epochs=2, tol=0.0)
    plan = FaultPlan().inject("source.pull", at=0, kind="crash", times=99)
    report = RecoveryReport()
    with plan:
        with pytest.raises(InjectedCrash):
            resilient_fit(
                sgd_fit_outofcore, logistic_loss,
                lambda: plan.wrap_source(
                    DataCacheReader(cache, batch_rows=256)),
                num_features=8, config=cfg, cache_decoded=False,
                checkpoint=CheckpointConfig(str(tmp_path / "ck2")),
                checkpoint_every_steps=2, max_restarts=2,
                backoff=RetryPolicy(base_delay=0.0, sleep=lambda s: None),
                report=report)
    assert report.restarts == 2         # tried, twice, then gave up


def test_resilient_fit_fatal_error_not_retried(tmp_path):
    calls = {"n": 0}

    def fit(checkpoint, resume):
        calls["n"] += 1
        raise ValueError("deterministic logic bug")

    with pytest.raises(ValueError):
        resilient_fit(fit, checkpoint=CheckpointConfig(str(tmp_path)),
                      max_restarts=3,
                      backoff=RetryPolicy(sleep=lambda s: None))
    assert calls["n"] == 1


def test_resilient_iterate_replays_wal_past_cursor_bitexact(tmp_path):
    """Supervised hosted iteration over a NON-replayable live feed: the
    crash loses the source's consumed windows forever, recovery restores
    the checkpoint cut and replays the WAL windows past the cursor —
    final state bit-exact vs the uninterrupted run."""
    import jax.numpy as jnp

    def body(state, epoch, window):
        x = jnp.asarray(np.asarray(window["x"], np.float32))
        return IterationBodyResult(state * 0.9 + jnp.sum(x) * (epoch + 1))

    oracle = iterate(
        body, jnp.asarray(0.0),
        WindowLog(_windows(0, 12), str(tmp_path / "wal-oracle")),
        config=IterationConfig(mode="hosted", jit=False))
    assert oracle.num_epochs == 12

    feed = _windows(0, 12)      # ONE generator: consumed windows are gone
    plan = FaultPlan().inject("source.pull", at=7, kind="crash")
    wal_dir = str(tmp_path / "wal-chaos")

    def fit(checkpoint, resume):
        # a fresh WindowLog per attempt over the SAME live feed — the
        # crash-heal path replays the logged-but-unacknowledged windows
        return iterate(
            body, jnp.asarray(0.0),
            WindowLog(plan.wrap_source(feed), wal_dir),
            config=IterationConfig(mode="hosted", jit=False),
            checkpoint=checkpoint, resume=resume)

    report = RecoveryReport()
    with plan:
        result = resilient_fit(
            fit, checkpoint=CheckpointConfig(str(tmp_path / "ck"),
                                             interval=4),
            max_restarts=1, report=report,
            backoff=RetryPolicy(base_delay=0.0, sleep=lambda s: None))

    assert report.restarts == 1
    assert result.num_epochs == 12
    np.testing.assert_array_equal(np.asarray(result.state),
                                  np.asarray(oracle.state))


# -- serving self-healing ----------------------------------------------------

def _lr_table(n=64, d=8, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d))
    y = (X[:, 0] + 0.3 * rng.normal(size=n) > 0).astype(np.int64)
    return Table({"features": X, "label": y})


def _fit_lr(seed=0):
    from flink_ml_tpu.models.classification.logisticregression import (
        LogisticRegression)

    return LogisticRegression().set_max_iter(5).fit(_lr_table(seed=seed))


def test_hot_swap_corrupt_model_dir_rolls_back_and_keeps_serving(tmp_path):
    """The serving acceptance bar: a hot-swap to a corrupt model
    directory rolls back (health SERVING->DEGRADED, rollback counter),
    the endpoint keeps answering BIT-exact on the old generation, and
    requests in flight across the failed swap are all answered — zero
    drops.  A later good deploy heals back to SERVING."""
    from flink_ml_tpu.serving import serve_model
    from flink_ml_tpu.serving.metrics import (HEALTH_DEGRADED,
                                              HEALTH_SERVING)

    model_a = _fit_lr(seed=0)
    feats = _lr_table(seed=5).drop("label")
    endpoint = serve_model(model_a, feats.take(2), max_batch_rows=32,
                           max_wait_ms=0.5)
    try:
        before = endpoint.predict(feats.take(8))

        # a saved-then-corrupted candidate version
        bad_path = str(tmp_path / "bad")
        _fit_lr(seed=1).save(bad_path)
        corrupt_file(os.path.join(bad_path, "data", "model.npz"))

        # concurrent traffic riding across the failed swap
        results, errors = [], []

        def client():
            try:
                for _ in range(10):
                    results.append(endpoint.predict(feats.take(4)))
            except Exception as exc:  # noqa: BLE001
                errors.append(exc)

        threads = [threading.Thread(target=client) for _ in range(3)]
        for t in threads:
            t.start()
        deployed = endpoint.hot_swap(bad_path)
        for t in threads:
            t.join()

        assert errors == []                      # zero dropped requests
        assert len(results) == 30
        assert deployed.generation == 1          # rolled back to the live gen
        assert endpoint.metrics.health == HEALTH_DEGRADED
        assert endpoint.metrics.rollbacks.value == 1
        after = endpoint.predict(feats.take(8))
        for col in before.column_names:          # bit-exact on old gen
            np.testing.assert_array_equal(after[col], before[col])

        # a good deploy heals the endpoint
        good_path = str(tmp_path / "good")
        _fit_lr(seed=2).save(good_path)
        healed = endpoint.hot_swap(good_path)
        assert healed.generation == 2
        assert endpoint.metrics.health == HEALTH_SERVING
    finally:
        endpoint.close()


def test_first_deploy_failure_still_raises_with_rollback(tmp_path):
    from flink_ml_tpu.serving import ModelRegistry

    bad_path = str(tmp_path / "bad")
    _fit_lr().save(bad_path)
    corrupt_file(os.path.join(bad_path, "data", "model.npz"))
    registry = ModelRegistry()
    with pytest.raises(IOError, match="truncated or corrupted"):
        registry.deploy("m", bad_path,
                        _lr_table().drop("label").take(1), rollback=True)


def test_registry_load_retries_transient_failures(tmp_path):
    from flink_ml_tpu.serving import ModelRegistry, ServingEndpoint

    path = str(tmp_path / "m")
    _fit_lr().save(path)
    feats = _lr_table().drop("label")
    plan = FaultPlan().inject("serving.load", at=0, kind="transient",
                              times=2)
    slept = []
    registry = ModelRegistry(retry_policy=RetryPolicy(
        max_attempts=4, base_delay=0.01, sleep=slept.append))
    with plan:
        deployed = registry.deploy("m", path, feats.take(2),
                                   max_batch_rows=32)
    assert deployed.generation == 1 and len(slept) == 2
    endpoint = ServingEndpoint(registry, "m", max_wait_ms=0.5).start()
    try:
        assert endpoint.predict(feats.take(4)).num_rows == 4
    finally:
        endpoint.close()


def test_warmup_fault_rolls_back_via_endpoint(tmp_path):
    """An injected warm-up crash (not even a corrupt dir) takes the same
    rollback path: nothing publishes, incumbent keeps serving."""
    from flink_ml_tpu.serving import serve_model
    from flink_ml_tpu.serving.metrics import HEALTH_DEGRADED

    feats = _lr_table().drop("label")
    endpoint = serve_model(_fit_lr(seed=0), feats.take(2),
                           max_batch_rows=32, max_wait_ms=0.5)
    try:
        before = endpoint.predict(feats.take(4))
        # serve_model's own warm-up already consumed index 0 of nothing:
        # the plan activates only for the swap, so at=0 is ITS warm-up
        plan = FaultPlan().inject("serving.warm_up", at=0, kind="crash")
        with plan:
            deployed = endpoint.hot_swap(_fit_lr(seed=9))
        assert deployed.generation == 1
        assert endpoint.metrics.health == HEALTH_DEGRADED
        after = endpoint.predict(feats.take(4))
        for col in before.column_names:
            np.testing.assert_array_equal(after[col], before[col])
    finally:
        endpoint.close()


def test_rollback_metrics_attach_per_endpoint_on_shared_registry():
    """Two endpoints over ONE registry: a failed hot-swap must flip the
    health gauge of the endpoint that asked for the swap — never the
    sibling that merely shares the registry."""
    from flink_ml_tpu.serving import ModelRegistry, ServingEndpoint
    from flink_ml_tpu.serving.metrics import HEALTH_DEGRADED, HEALTH_SERVING

    feats = _lr_table().drop("label")
    registry = ModelRegistry()
    registry.deploy("a", _fit_lr(seed=0), feats.take(2), max_batch_rows=32)
    registry.deploy("b", _fit_lr(seed=1), feats.take(2), max_batch_rows=32)
    ep_a = ServingEndpoint(registry, "a", max_batch_rows=32)
    ep_b = ServingEndpoint(registry, "b", max_batch_rows=32)
    ep_a.hot_swap(_fit_lr(seed=2))          # claims nothing registry-wide
    plan = FaultPlan().inject("serving.warm_up", at=0, kind="crash")
    with plan:
        deployed = ep_b.hot_swap(_fit_lr(seed=3))
    assert deployed.generation == 1         # b rolled back to incumbent
    assert ep_b.metrics.health == HEALTH_DEGRADED
    assert ep_b.metrics.rollbacks.value == 1
    assert ep_a.metrics.health == HEALTH_SERVING
    assert ep_a.metrics.rollbacks.value == 0


# -- continuous learning: train-while-serve chaos (ISSUE 7) ------------------

def _ctl_windows(lo, hi, rows=16, d=4):
    for i in range(lo, hi):
        rng = np.random.default_rng(2000 + i)
        X = rng.normal(size=(rows, d)).astype(np.float32)
        yield Table({"features": X,
                     "label": (X[:, 0] > 0).astype(np.float32)})


def _ctl_offline_w(n_windows, every=4):
    from flink_ml_tpu.models.common.losses import logistic_loss
    from flink_ml_tpu.models.common.sgd import SGDConfig, sgd_fit_outofcore

    def make_reader():
        for w in _ctl_windows(0, n_windows):
            yield w.to_dict()

    state, _ = sgd_fit_outofcore(
        logistic_loss, make_reader, num_features=4,
        config=SGDConfig(max_epochs=1, tol=0.0), steps_per_dispatch=every)
    return np.asarray(state.coefficients, np.float32)


def _ctl_endpoint():
    from flink_ml_tpu.models.classification.logisticregression import (
        LogisticRegression)
    from flink_ml_tpu.serving import serve_model

    boot_window = next(_ctl_windows(0, 1))
    boot = LogisticRegression().set_max_iter(1).fit(boot_window)
    return serve_model(boot, boot_window.drop("label").take(2),
                       max_batch_rows=32, max_wait_ms=0.5)


def _ctl_learner(endpoint, source, tmp_path, **kw):
    from flink_ml_tpu.models.common.losses import logistic_loss
    from flink_ml_tpu.online import ContinuousLearner

    return ContinuousLearner(
        loss_fn=logistic_loss, num_features=4, source=source,
        wal_dir=str(tmp_path / "wal"), endpoint=endpoint, batch_rows=16,
        checkpoint=CheckpointConfig(str(tmp_path / "ck")),
        publish_every_steps=4,
        backoff=RetryPolicy(base_delay=0.0, sleep=lambda s: None), **kw)


def test_continuous_crash_mid_delta_publish_resumes_served_bitexact(
        tmp_path):
    """THE ISSUE 7 chaos acceptance, half one: an injected crash inside
    the chunk-boundary publish (AFTER the checkpoint cut landed) is
    healed by the supervised loop — restore, WAL replay, deterministic
    re-train — and the final served model is bit-exact with the
    uninterrupted offline fit over every window.  The replayed cut
    republishes idempotently (digest-verified), so serving never
    observes divergent bits."""
    endpoint = _ctl_endpoint()
    try:
        plan = FaultPlan().inject("serving.publish", at=1, kind="crash")
        learner = _ctl_learner(endpoint, _ctl_windows(0, 24), tmp_path)
        report = RecoveryReport()
        with plan:
            learner.run(max_windows=24, report=report)
        assert report.restarts == 1
        live = endpoint.registry.current("default")
        w_served = np.asarray(live.servable.model._state.coefficients,
                              np.float32)
        assert w_served.tobytes() == _ctl_offline_w(24).tobytes()
        # publishes resumed past the crashed cut and reached the end
        assert learner.publish_log[-1].step == 24
    finally:
        endpoint.close()


def test_continuous_torn_wal_tail_resumes_served_bitexact(tmp_path):
    """Half two: the process dies AND its newest WAL append is torn
    (the crash-mid-append shape).  The restarted driver truncates the
    torn tail — that window never reached the trainer, so the live
    source re-delivers it — and converges to the same served bits as
    the uninterrupted run."""
    endpoint = _ctl_endpoint()
    try:
        # phase 1: hard crash at the pull of window 10 (no supervision:
        # the process is gone)
        plan = FaultPlan().inject("source.pull", at=10, kind="crash")
        learner1 = _ctl_learner(
            endpoint, plan.wrap_source(_ctl_windows(0, 24)), tmp_path,
            max_restarts=0)
        with plan, pytest.raises(InjectedCrash):
            learner1.run(max_windows=24)
        # windows 0..9 were logged write-ahead; tear the newest entry
        # (its append never committed cleanly in this failure story)
        wal_dir = str(tmp_path / "wal")
        logged = sorted(f for f in os.listdir(wal_dir)
                        if f.startswith("win-"))
        assert logged[-1] == "win-00000009.npz"
        corrupt_file(os.path.join(wal_dir, logged[-1]), mode="torn")
        # phase 2: a fresh driver process over the live source — which
        # still holds window 9 (a torn append means the consumer never
        # saw it)
        learner2 = _ctl_learner(endpoint, _ctl_windows(9, 24), tmp_path)
        learner2.run(max_windows=24)
        live = endpoint.registry.current("default")
        w_served = np.asarray(live.servable.model._state.coefficients,
                              np.float32)
        assert w_served.tobytes() == _ctl_offline_w(24).tobytes()
    finally:
        endpoint.close()


def test_zero_dropped_requests_during_continuous_publishes():
    """Serving continuity: a barrage of concurrent requests across a
    stream of delta publishes — every future resolves (zero drops), and
    the generation advances mid-flight (requests really did span
    publishes)."""
    from flink_ml_tpu.online import DeltaEncoder, params_of_model

    endpoint = _ctl_endpoint()
    try:
        feats = next(_ctl_windows(5, 6)).drop("label")
        pub = endpoint.delta_publisher()
        enc = DeltaEncoder()
        p = params_of_model(
            endpoint.registry.current("default").servable.model)
        pub.apply(enc.encode(1, p, pub.stats))
        enc.ack()
        gen0 = endpoint.registry.current("default").generation
        results, errors = [], []

        def client(worker):
            try:
                for i in range(20):
                    out = endpoint.predict(feats.take(1 + (i % 8)),
                                           timeout=30.0)
                    results.append(out.num_rows)
            except BaseException as exc:  # noqa: BLE001
                errors.append(exc)

        clients = [threading.Thread(target=client, args=(i,))
                   for i in range(4)]
        for t in clients:
            t.start()
        for step in range(2, 30):
            p = {"w": p["w"] + np.float32(0.01), "b": p["b"]}
            pub.apply(enc.encode(step, p, pub.stats))
            enc.ack()
        for t in clients:
            t.join(30.0)
        assert not errors, f"dropped/failed requests: {errors[:3]}"
        assert len(results) == 4 * 20
        assert endpoint.registry.current("default").generation >= gen0 + 20
        assert endpoint.metrics.shed.value == 0
    finally:
        endpoint.close()


def test_workset_iterate_crash_mid_run_resumes_bitexact(tmp_path):
    """ISSUE 9 acceptance: a crash injected mid-iteration while the
    active-set mask AND the Hamerly bound pytree ride the carry —
    recovery restores the checkpoint cut (mask, bounds, cached
    assignments, epoch counter together) and lands bit-exact on the
    uninterrupted run: same centroids AND the same rounds-run count, so
    the convergence-driven exit fires at the identical epoch."""
    import jax.numpy as jnp

    from flink_ml_tpu.distance import DistanceMeasure
    from flink_ml_tpu.iteration import Workset
    from flink_ml_tpu.models.clustering.kmeans import (
        _fit_plan,
        kmeans_workset_epoch_step,
    )
    from flink_ml_tpu.parallel.mesh import default_mesh

    rng = np.random.default_rng(0)
    k, n, d = 5, 512, 8
    centers = rng.normal(size=(k, d)) * 8.0
    X = (centers[rng.integers(0, k, n)]
         + rng.normal(size=(n, d)) * 0.4).astype(np.float32)
    points = jnp.asarray(X)
    pad_mask = jnp.ones((n,), jnp.float32)
    init = jnp.asarray(X[:k])

    measure = DistanceMeasure.get_instance("euclidean")
    body = kmeans_workset_epoch_step(measure, k)
    plan = _fit_plan(n, d, k, measure, default_mesh(), workset=True)

    def run(checkpoint=None, resume=False):
        return iterate(
            body, init, (points, pad_mask), max_epochs=60,
            workset=plan.init_workset(pad_mask),
            config=IterationConfig(mode="hosted"),
            checkpoint=checkpoint, resume=resume)

    oracle = run()
    assert oracle.num_epochs < 60       # converges mid-run
    assert oracle.num_epochs > 8        # the crash lands before the exit

    plan_f = FaultPlan().inject("iterate.epoch", at=6, kind="crash")
    report = RecoveryReport()
    with plan_f:
        result = resilient_fit(
            run, checkpoint=CheckpointConfig(str(tmp_path / "ck"),
                                             interval=4),
            max_restarts=1, report=report,
            backoff=RetryPolicy(base_delay=0.0, sleep=lambda s: None))

    assert report.restarts == 1 and report.recovered
    # rounds-run count resumes exactly — the while-exit epoch matches
    assert result.num_epochs == oracle.num_epochs
    np.testing.assert_array_equal(np.asarray(result.state),
                                  np.asarray(oracle.state))
    # the recovered workset drained exactly like the uninterrupted one
    np.testing.assert_array_equal(np.asarray(result.workset.mask),
                                  np.asarray(oracle.workset.mask))
    for key in ("assign", "upper", "lower"):
        np.testing.assert_array_equal(
            np.asarray(result.workset.bounds[key]),
            np.asarray(oracle.workset.bounds[key]))


# -- elastic data-parallel training (ISSUE 15) -------------------------------
#
# The elastic contract, asserted at the FIT level: a resize at a chunk
# boundary is bit-exact vs a fixed fleet of the new size restoring the
# same cut (same reduce order), EF residuals and pending overlap
# buffers included; a worker death mid-chunk degrades to the crash path
# and resumes onto the surviving fleet; kill+rejoin churn stays within
# the PR 6 adaptive tolerance of the fixed-fleet loss trajectory.

def _elastic_coord(workers, chips=2):
    from flink_ml_tpu.parallel.elastic import ElasticCoordinator

    return ElasticCoordinator(chips_per_worker=chips,
                              initial_workers=workers)


def _elastic_gr():
    from flink_ml_tpu.parallel.grad_reduce import GradReduceConfig

    # topk + buckets + overlap + hierarchical: the richest carry — EF
    # residual, pending buffer, rounding-free policy state — all of
    # which must survive the resize re-shard
    return GradReduceConfig(mode="topk", density=0.25, bucket_count=2,
                            overlap=True, axis="data", dcn_axis="dcn")


def _elastic_cache(tmp_path, name):
    # 1440 rows / 240 = 6 batches per epoch; W=2 -> 3 chunk boundaries
    # per epoch; 240 is divisible by every fleet extent used here
    # (2x2=4, 3x2=6, 4x2=8, 1x2=2)
    from flink_ml_tpu.data.datacache import DataCacheWriter

    rng = np.random.default_rng(13)
    true_w = rng.normal(size=(8,))
    cache = str(tmp_path / name)
    writer = DataCacheWriter(cache, segment_rows=480)
    for _ in range(3):
        X = rng.normal(size=(480, 8)).astype(np.float32)
        writer.append({"features": X,
                       "label": (X @ true_w > 0).astype(np.float32)})
    writer.finish()
    return cache


def _copy_cut(src_dir, dst_dir, step):
    import shutil

    name = f"ckpt-{step:08d}"
    os.makedirs(dst_dir, exist_ok=True)
    shutil.copytree(os.path.join(src_dir, name),
                    os.path.join(dst_dir, name))


def test_elastic_resize_at_boundary_bitexact_vs_fixed_fleet(tmp_path):
    """THE elastic acceptance: a join at a chunk boundary (fleet 2 -> 3
    over the dcn axis) under topk+overlap+hierarchical grad_reduce is
    bit-exact — final params AND loss log — vs a fixed fleet of the new
    size restoring the exact same cut.  EF residual and pending overlap
    buffer both ride the re-shard (they are nonzero at the boundary by
    construction of the config)."""
    from flink_ml_tpu.data.datacache import DataCacheReader
    from flink_ml_tpu.iteration.checkpoint import CheckpointManager
    from flink_ml_tpu.models.common.losses import logistic_loss
    from flink_ml_tpu.models.common.sgd import SGDConfig, sgd_fit_outofcore

    cache = _elastic_cache(tmp_path, "c_el")
    cfg = SGDConfig(learning_rate=0.4, max_epochs=3, tol=0.0,
                    grad_reduce=_elastic_gr())
    kw = dict(num_features=8, config=cfg, cache_decoded=False,
              steps_per_dispatch=2, checkpoint_every_steps=2)

    def reader():
        return DataCacheReader(cache, batch_rows=240)

    # elastic run: join fires at chunk boundary 2 (global step 6 — the
    # end-of-epoch-0 boundary), so epochs 1-2 train on the grown fleet
    coord = _elastic_coord(2)
    plan = FaultPlan().inject(coord.SCOPE, at=2, kind="join")
    report = RecoveryReport()
    with plan:
        state_e, log_e = resilient_fit(
            sgd_fit_outofcore, logistic_loss,
            lambda: plan.wrap_source(reader()),
            checkpoint=CheckpointConfig(str(tmp_path / "ck_e"),
                                        max_to_keep=99),
            elastic=coord,
            backoff=RetryPolicy(base_delay=0.0, sleep=lambda s: None),
            report=report, **kw)

    assert report.resizes == 1 and report.restarts == 0
    assert report.events[0].kind == "resize"
    assert report.events[0].fleet_size == 3
    assert report.events[0].mttr_s is not None   # the resize pause
    assert report.events[0].restored_step == 6
    assert coord.fleet_size == 3

    # fixed fleet 2 with cuts kept: its step-6 cut is byte-identical to
    # the elastic run's (same program up to the boundary)
    c2 = _elastic_coord(2)
    state_a, log_a = sgd_fit_outofcore(
        logistic_loss, reader, mesh=c2.mesh(), membership=c2,
        checkpoint=CheckpointConfig(str(tmp_path / "ck_a"),
                                    max_to_keep=99), **kw)
    # the cut records what fleet wrote it (the satellite contract)
    mgr = CheckpointManager(CheckpointConfig(str(tmp_path / "ck_a")))
    _, _, meta = mgr.latest()
    assert meta["mesh_shape"] == {"dcn": 2, "data": 2}
    assert meta["participant_count"] == 4

    # fixed fleet of the NEW size restoring the same cut
    _copy_cut(str(tmp_path / "ck_a"), str(tmp_path / "ck_b"), 6)
    c3 = _elastic_coord(3)
    state_b, log_b = sgd_fit_outofcore(
        logistic_loss, reader, mesh=c3.mesh(), membership=c3,
        checkpoint=CheckpointManager(CheckpointConfig(
            str(tmp_path / "ck_b"), max_to_keep=99)),
        resume=True, **kw)

    np.testing.assert_array_equal(state_e.coefficients,
                                  state_b.coefficients)
    assert state_e.intercept == state_b.intercept
    np.testing.assert_array_equal(log_e, log_b)
    # and the resized run genuinely diverged from the fixed-2 run (the
    # comparison is not vacuous)
    assert not np.array_equal(state_e.coefficients, state_a.coefficients)


def test_controller_preemption_at_boundary_bitexact_on_shrunken_fleet(
        tmp_path):
    """ISSUE 17: a CONTROLLER-initiated preemption rides the exact same
    chunk-boundary seam as injected churn — ``request_resize(1,
    at_boundary=2)`` shrinks the fleet 2 -> 1 at the same boundary a
    seeded ``"preempt"`` fault would, the transition lands in the audit
    log as a plain ``preempt``, and the shrunken run restores BIT-EXACT
    (params + loss log) vs a fixed fleet of the new size restoring the
    same step-6 cut.  This is what makes autoscale preemption lossless
    by construction: the PR 15 chaos matrix covers it for free."""
    from flink_ml_tpu.data.datacache import DataCacheReader
    from flink_ml_tpu.iteration.checkpoint import CheckpointManager
    from flink_ml_tpu.models.common.losses import logistic_loss
    from flink_ml_tpu.models.common.sgd import SGDConfig, sgd_fit_outofcore

    cache = _elastic_cache(tmp_path, "c_ctrl")
    cfg = SGDConfig(learning_rate=0.4, max_epochs=3, tol=0.0,
                    grad_reduce=_elastic_gr())
    kw = dict(num_features=8, config=cfg, cache_decoded=False,
              steps_per_dispatch=2, checkpoint_every_steps=2)

    def reader():
        return DataCacheReader(cache, batch_rows=240)

    # controller-driven run: the resize request is pinned to chunk
    # boundary 2 (global step 6) — the FaultPlan index space — with NO
    # FaultPlan active at all
    coord = _elastic_coord(2)
    coord.request_resize(1, at_boundary=2, reason="p99 over target")
    report = RecoveryReport()
    state_e, log_e = resilient_fit(
        sgd_fit_outofcore, logistic_loss, reader,
        checkpoint=CheckpointConfig(str(tmp_path / "ck_ce"),
                                    max_to_keep=99),
        elastic=coord,
        backoff=RetryPolicy(base_delay=0.0, sleep=lambda s: None),
        report=report, **kw)

    assert report.resizes == 1 and report.restarts == 0
    assert report.events[0].kind == "resize"
    assert report.events[0].fleet_size == 1
    assert report.events[0].restored_step == 6
    assert coord.fleet_size == 1
    # the SAME seam as injected churn: an ordinary preempt transition
    # in the audit log, counted like any chaos-schedule preemption
    assert [t[0] for t in coord.transitions] == ["preempt"]
    assert coord.counters["preemptions"] == 1
    assert coord.counters["controller_requests"] == 1

    # fixed fleet 2 with cuts kept: donor of the step-6 cut
    c2 = _elastic_coord(2)
    sgd_fit_outofcore(
        logistic_loss, reader, mesh=c2.mesh(), membership=c2,
        checkpoint=CheckpointConfig(str(tmp_path / "ck_ca"),
                                    max_to_keep=99), **kw)

    # fixed fleet of the SHRUNKEN size restoring the same cut
    _copy_cut(str(tmp_path / "ck_ca"), str(tmp_path / "ck_cb"), 6)
    c1 = _elastic_coord(1)
    state_b, log_b = sgd_fit_outofcore(
        logistic_loss, reader, mesh=c1.mesh(), membership=c1,
        checkpoint=CheckpointManager(CheckpointConfig(
            str(tmp_path / "ck_cb"), max_to_keep=99)),
        resume=True, **kw)

    np.testing.assert_array_equal(state_e.coefficients,
                                  state_b.coefficients)
    assert state_e.intercept == state_b.intercept
    np.testing.assert_array_equal(log_e, log_b)


def test_elastic_kill_and_rejoin_matches_fixed_fleet_trajectory(tmp_path):
    """Chaos churn: a worker is killed at one boundary and a fresh one
    joins a few chunks later.  The churned run's final loss must stay
    within the PR 6 adaptive tolerance (1e-3) of the fixed-fleet run —
    elasticity perturbs the compression schedule, never the
    optimization."""
    from flink_ml_tpu.data.datacache import DataCacheReader
    from flink_ml_tpu.models.common.losses import logistic_loss
    from flink_ml_tpu.models.common.sgd import SGDConfig, sgd_fit_outofcore

    cache = _elastic_cache(tmp_path, "c_churn")
    cfg = SGDConfig(learning_rate=0.4, max_epochs=4, tol=0.0,
                    grad_reduce=_elastic_gr())
    kw = dict(num_features=8, config=cfg, cache_decoded=False,
              steps_per_dispatch=2, checkpoint_every_steps=2)

    def reader():
        return DataCacheReader(cache, batch_rows=240)

    # fixed-fleet reference: 2 workers throughout
    c_ref = _elastic_coord(2)
    _, log_ref = sgd_fit_outofcore(
        logistic_loss, reader, mesh=c_ref.mesh(), membership=c_ref,
        checkpoint=CheckpointConfig(str(tmp_path / "ck_ref")), **kw)

    # kill a worker every ~4 boundaries, add one back in between —
    # periodic churn through the whole run (boundary indices count
    # across supervised attempts, so the schedule is deterministic)
    coord = _elastic_coord(2)
    plan = (FaultPlan(seed=4)
            .inject(coord.SCOPE, at=2, kind="preempt")
            .inject(coord.SCOPE, at=4, kind="join")
            .inject(coord.SCOPE, at=6, kind="preempt")
            .inject(coord.SCOPE, at=8, kind="join"))
    report = RecoveryReport()
    with plan:
        _, log_e = resilient_fit(
            sgd_fit_outofcore, logistic_loss,
            lambda: plan.wrap_source(reader()),
            checkpoint=CheckpointConfig(str(tmp_path / "ck_ch"),
                                        max_to_keep=99),
            elastic=coord,
            backoff=RetryPolicy(base_delay=0.0, sleep=lambda s: None),
            report=report, **kw)

    assert report.resizes == 4
    assert coord.counters["preemptions"] == 2
    assert coord.counters["joins"] == 2
    assert coord.fleet_size == 2
    assert len(log_e) == len(log_ref)
    assert abs(log_e[-1] - log_ref[-1]) < 1e-3, (
        "kill+rejoin churn drifted past the adaptive tolerance: "
        f"{log_e[-1]} vs fixed-fleet {log_ref[-1]}")


def test_elastic_torn_checkpoint_during_resize_resumes_bitexact(tmp_path):
    """The resize's own boundary cut commits TORN bytes: the restore
    onto the new fleet must quarantine it, fall back to the previous
    valid cut, and replay the gap on the NEW fleet — landing bit-exact
    on a fixed fleet of the new size restoring that same earlier cut."""
    from flink_ml_tpu.data.datacache import DataCacheReader
    from flink_ml_tpu.iteration.checkpoint import CheckpointManager
    from flink_ml_tpu.models.common.losses import logistic_loss
    from flink_ml_tpu.models.common.sgd import SGDConfig, sgd_fit_outofcore

    cache = _elastic_cache(tmp_path, "c_torn")
    cfg = SGDConfig(learning_rate=0.4, max_epochs=3, tol=0.0,
                    grad_reduce=_elastic_gr())
    kw = dict(num_features=8, config=cfg, cache_decoded=False,
              steps_per_dispatch=2, checkpoint_every_steps=2)

    def reader():
        return DataCacheReader(cache, batch_rows=240)

    # cuts land at steps 2, 4, 6 (writes 0, 1, 2 in epoch 0); the join
    # fires at boundary 2 — whose cut (write 2, step 6) commits torn
    coord = _elastic_coord(2)
    plan = (FaultPlan(seed=6)
            .inject("checkpoint.write", at=2, kind="torn")
            .inject(coord.SCOPE, at=2, kind="join"))
    report = RecoveryReport()
    manager_e = CheckpointManager(CheckpointConfig(
        str(tmp_path / "ck_e"), max_to_keep=99))
    with plan:
        state_e, log_e = resilient_fit(
            sgd_fit_outofcore, logistic_loss,
            lambda: plan.wrap_source(reader()),
            checkpoint=manager_e, elastic=coord,
            backoff=RetryPolicy(base_delay=0.0, sleep=lambda s: None),
            report=report, **kw)

    assert report.resizes == 1
    # the torn step-6 cut was quarantined; the resize fell back to the
    # step-4 cut and replayed batches 5-6 on the grown fleet
    assert any(n.endswith(".corrupt")
               for n in os.listdir(tmp_path / "ck_e"))
    assert manager_e.last_restored_step == 4

    # baseline: fixed 2 to get a clean step-4 cut, then fixed 3 from it
    c2 = _elastic_coord(2)
    sgd_fit_outofcore(
        logistic_loss, reader, mesh=c2.mesh(), membership=c2,
        checkpoint=CheckpointConfig(str(tmp_path / "ck_a"),
                                    max_to_keep=99), **kw)
    _copy_cut(str(tmp_path / "ck_a"), str(tmp_path / "ck_b"), 4)
    c3 = _elastic_coord(3)
    state_b, log_b = sgd_fit_outofcore(
        logistic_loss, reader, mesh=c3.mesh(), membership=c3,
        checkpoint=CheckpointManager(CheckpointConfig(
            str(tmp_path / "ck_b"), max_to_keep=99)),
        resume=True, **kw)

    np.testing.assert_array_equal(state_e.coefficients,
                                  state_b.coefficients)
    assert state_e.intercept == state_b.intercept
    np.testing.assert_array_equal(log_e, log_b)


def test_elastic_ef_and_pending_survive_two_consecutive_resizes(tmp_path):
    """Grow then shrink (2 -> 3 -> 2) with EF residual + pending overlap
    buffer live across BOTH re-shards: the double-resized run must be
    bit-exact vs a run that freshly restores the first boundary's cut
    onto the grown fleet and then takes the second resize itself —
    i.e. the carry that crossed resize #1 is indistinguishable from a
    fresh restore of the same cut."""
    from flink_ml_tpu.data.datacache import DataCacheReader
    from flink_ml_tpu.iteration.checkpoint import CheckpointManager
    from flink_ml_tpu.models.common.losses import logistic_loss
    from flink_ml_tpu.models.common.sgd import SGDConfig, sgd_fit_outofcore

    cache = _elastic_cache(tmp_path, "c_two")
    cfg = SGDConfig(learning_rate=0.4, max_epochs=4, tol=0.0,
                    grad_reduce=_elastic_gr())
    kw = dict(num_features=8, config=cfg, cache_decoded=False,
              steps_per_dispatch=2, checkpoint_every_steps=2)

    def reader():
        return DataCacheReader(cache, batch_rows=240)

    # elastic run: join at boundary 2 (step 6), preempt at boundary 5
    # (step 12 — polls 3/4/5 land at epoch-1 boundaries 8/10/12 because
    # the post-resize attempt replays zero chunks in epoch 0)
    coord = _elastic_coord(2)
    plan = (FaultPlan(seed=8)
            .inject(coord.SCOPE, at=2, kind="join")
            .inject(coord.SCOPE, at=5, kind="preempt"))
    report = RecoveryReport()
    with plan:
        state_e, log_e = resilient_fit(
            sgd_fit_outofcore, logistic_loss,
            lambda: plan.wrap_source(reader()),
            checkpoint=CheckpointConfig(str(tmp_path / "ck_e"),
                                        max_to_keep=99),
            elastic=coord,
            backoff=RetryPolicy(base_delay=0.0, sleep=lambda s: None),
            report=report, **kw)
    assert report.resizes == 2
    assert [e.fleet_size for e in report.events] == [3, 2]

    # chained baseline: fixed 2 to step 6, fresh restore onto 3, which
    # then takes the SECOND resize (preempt at its boundary 5) itself
    c2 = _elastic_coord(2)
    sgd_fit_outofcore(
        logistic_loss, reader, mesh=c2.mesh(), membership=c2,
        checkpoint=CheckpointConfig(str(tmp_path / "ck_a"),
                                    max_to_keep=99), **kw)
    _copy_cut(str(tmp_path / "ck_a"), str(tmp_path / "ck_b"), 6)
    c3 = _elastic_coord(3)
    # the resumed run's boundary counter restarts at 0: its epoch-1
    # boundaries poll at indices 0/1/2, so index 2 IS step 12 — the
    # same boundary the double-resized run's index 5 landed on
    plan_b = FaultPlan(seed=8).inject(c3.SCOPE, at=2, kind="preempt")
    report_b = RecoveryReport()
    with plan_b:
        state_b, log_b = resilient_fit(
            sgd_fit_outofcore, logistic_loss,
            lambda: plan_b.wrap_source(reader()),
            checkpoint=CheckpointManager(CheckpointConfig(
                str(tmp_path / "ck_b"), max_to_keep=99)),
            elastic=c3, resume=True,
            backoff=RetryPolicy(base_delay=0.0, sleep=lambda s: None),
            report=report_b, **kw)
    assert report_b.resizes == 1

    np.testing.assert_array_equal(state_e.coefficients,
                                  state_b.coefficients)
    assert state_e.intercept == state_b.intercept
    np.testing.assert_array_equal(log_e, log_b)


def test_elastic_wire_accounting_leaves_survive_two_resizes(tmp_path):
    """ISSUE 16 chaos test: the wire-protocol tier's accounting leaves
    (per-round ``fill``, union-density ``union``) ride the reducer state
    through TWO elastic re-shards (2 -> 3 -> 2 workers).  The routing
    rule under test: ``union`` re-seeds as broadcast participant 0 (a
    smoothed statistic, psum-uniform within each dcn hop group),
    ``fill`` is per-round counts specific to the OLD fleet's round
    structure (re-seeded to zeros at the new extent) — both must
    re-seat at every fleet size, never refuse the resize."""
    from flink_ml_tpu.data.datacache import DataCacheReader
    from flink_ml_tpu.iteration.checkpoint import load_pytree
    from flink_ml_tpu.models.common.losses import logistic_loss
    from flink_ml_tpu.models.common.sgd import SGDConfig, sgd_fit_outofcore
    from flink_ml_tpu.parallel.grad_reduce import FILL_VEC_LEN

    cache = _elastic_cache(tmp_path, "c_wire")
    cfg = SGDConfig(learning_rate=0.4, max_epochs=4, tol=0.0,
                    grad_reduce=_elastic_gr())
    kw = dict(num_features=8, config=cfg, cache_decoded=False,
              steps_per_dispatch=2, checkpoint_every_steps=2)

    def reader():
        return DataCacheReader(cache, batch_rows=240)

    coord = _elastic_coord(2)
    plan = (FaultPlan(seed=8)
            .inject(coord.SCOPE, at=2, kind="join")
            .inject(coord.SCOPE, at=5, kind="preempt"))
    report = RecoveryReport()
    with plan:
        resilient_fit(
            sgd_fit_outofcore, logistic_loss,
            lambda: plan.wrap_source(reader()),
            checkpoint=CheckpointConfig(str(tmp_path / "ck_w"),
                                        max_to_keep=99),
            elastic=coord,
            backoff=RetryPolicy(base_delay=0.0, sleep=lambda s: None),
            report=report, **kw)
    assert report.resizes == 2
    assert [e.fleet_size for e in report.events] == [3, 2]

    def find_wire_leaves(tree):
        """The (fill, union) pair wherever the reducer state landed."""
        if isinstance(tree, dict):
            if "fill" in tree and "union" in tree:
                return tree["fill"], tree["union"]
            for v in tree.values():
                hit = find_wire_leaves(v)
                if hit is not None:
                    return hit
        elif isinstance(tree, (list, tuple)):
            for v in tree:
                hit = find_wire_leaves(v)
                if hit is not None:
                    return hit
        return None

    ck = tmp_path / "ck_w"
    cuts = sorted(name for name in os.listdir(ck)
                  if name.startswith("ckpt-")
                  and not name.endswith((".corrupt", ".old", ".tmp")))
    assert cuts
    extents = set()
    for name in cuts:
        tree, _meta = load_pytree(str(ck / name))
        hit = find_wire_leaves(tree)
        assert hit is not None, f"{name} lost the wire accounting leaves"
        fill, union = np.asarray(hit[0]), np.asarray(hit[1])
        # fill: (participants, units, FILL_VEC_LEN); union: (p, units)
        assert fill.shape[0] == union.shape[0]
        assert fill.shape[-1] == FILL_VEC_LEN
        assert np.isfinite(fill).all() and np.isfinite(union).all()
        # union is psum'd over the dcn hop axis, hence identical across
        # workers WITHIN each ICI column (participants stack in
        # (dcn, data) order) — each column compresses a different
        # gradient shard, so columns legitimately differ; the resize
        # routing (broadcast participant 0) re-seeds the EMA from one
        # column, which the next steps re-diverge
        u3 = union.reshape(union.shape[0] // 2, 2, *union.shape[1:])
        np.testing.assert_allclose(
            u3, np.broadcast_to(u3[:1], u3.shape), atol=0)
        extents.add(fill.shape[0])
    # cuts exist at BOTH fleet extents (2 workers x 2 chips, 3 x 2):
    # the leaves re-seated across grow AND shrink
    assert extents == {4, 6}
    # the last cut (post-shrink) measured real traffic again: the
    # re-seeded fill repopulated after the second resize
    tree, _meta = load_pytree(str(ck / cuts[-1]))
    fill, union = find_wire_leaves(tree)
    assert np.asarray(fill).any()
    assert np.asarray(union).any()


def test_elastic_worker_death_mid_chunk_degrades_to_crash_path(tmp_path):
    """A worker dies MID-chunk (crash at a source pull, not at a
    boundary): the supervisor revokes the victim's lease and recovery
    restores the newest pre-crash cut onto the SURVIVING fleet —
    bit-exact vs a fixed fleet of the surviving size restoring that
    same cut.  Crash-elasticity and planned-elasticity share the code
    path; this exercises the crash side."""
    from flink_ml_tpu.data.datacache import DataCacheReader
    from flink_ml_tpu.iteration.checkpoint import CheckpointManager
    from flink_ml_tpu.models.common.losses import logistic_loss
    from flink_ml_tpu.models.common.sgd import SGDConfig, sgd_fit_outofcore

    cache = _elastic_cache(tmp_path, "c_death")
    cfg = SGDConfig(learning_rate=0.4, max_epochs=3, tol=0.0,
                    grad_reduce=_elastic_gr())
    kw = dict(num_features=8, config=cfg, cache_decoded=False,
              steps_per_dispatch=2, checkpoint_every_steps=2)

    def reader():
        return DataCacheReader(cache, batch_rows=240)

    # 7 pulls/epoch (6 batches + end-of-stream probe); pull 9 = epoch 1
    # batch 2 — mid-chunk, after the step-8 boundary cut
    coord = _elastic_coord(3)
    plan = FaultPlan(seed=2).inject("source.pull", at=9, kind="crash")
    report = RecoveryReport()
    manager_e = CheckpointManager(CheckpointConfig(
        str(tmp_path / "ck_e"), max_to_keep=99))
    with plan:
        state_e, log_e = resilient_fit(
            sgd_fit_outofcore, logistic_loss,
            lambda: plan.wrap_source(reader()),
            checkpoint=manager_e, elastic=coord, max_restarts=2,
            backoff=RetryPolicy(base_delay=0.0, sleep=lambda s: None),
            report=report, **kw)

    assert report.restarts == 1 and report.resizes == 0
    assert report.recovered
    assert report.events[0].kind == "crash"
    assert report.events[0].fleet_size == 2      # surviving fleet
    assert coord.fleet_size == 2
    assert coord.counters["deaths"] == 1
    restored = manager_e.last_restored_step
    assert restored is not None and restored >= 6

    # baseline: fixed 3 (no faults) writes byte-identical pre-crash
    # cuts; fixed 2 restores the same cut the recovery used
    c3 = _elastic_coord(3)
    sgd_fit_outofcore(
        logistic_loss, reader, mesh=c3.mesh(), membership=c3,
        checkpoint=CheckpointConfig(str(tmp_path / "ck_a"),
                                    max_to_keep=99), **kw)
    _copy_cut(str(tmp_path / "ck_a"), str(tmp_path / "ck_b"), restored)
    c2 = _elastic_coord(2)
    state_b, log_b = sgd_fit_outofcore(
        logistic_loss, reader, mesh=c2.mesh(), membership=c2,
        checkpoint=CheckpointManager(CheckpointConfig(
            str(tmp_path / "ck_b"), max_to_keep=99)),
        resume=True, **kw)

    np.testing.assert_array_equal(state_e.coefficients,
                                  state_b.coefficients)
    assert state_e.intercept == state_b.intercept
    np.testing.assert_array_equal(log_e, log_b)


def test_elastic_legacy_cut_onto_different_fleet_raises(tmp_path):
    """A cut whose meta predates mesh-shape metadata (the pre-elastic
    layout) restored onto a DIFFERENT fleet must fail with a
    diagnosable CorruptStateError — never a silent wrong-shape
    restore.  (Same-fleet restores of legacy cuts keep working; that
    path is every pre-elastic resume test in this file.)"""
    import json

    from flink_ml_tpu.data.datacache import DataCacheReader
    from flink_ml_tpu.iteration.checkpoint import CheckpointManager
    from flink_ml_tpu.models.common.losses import logistic_loss
    from flink_ml_tpu.models.common.sgd import SGDConfig, sgd_fit_outofcore

    cache = _elastic_cache(tmp_path, "c_leg")
    cfg = SGDConfig(learning_rate=0.4, max_epochs=2, tol=0.0,
                    grad_reduce=_elastic_gr())
    kw = dict(num_features=8, config=cfg, cache_decoded=False,
              steps_per_dispatch=2, checkpoint_every_steps=2)

    def reader():
        return DataCacheReader(cache, batch_rows=240)

    c2 = _elastic_coord(2)
    sgd_fit_outofcore(
        logistic_loss, reader, mesh=c2.mesh(), membership=c2,
        checkpoint=CheckpointConfig(str(tmp_path / "ck"),
                                    max_to_keep=99), **kw)

    # strip the fleet metadata from EVERY cut — legacy saves — and
    # rewrite the CRC manifests so validation still passes
    from flink_ml_tpu.robustness.durability import write_manifest

    ck = tmp_path / "ck"
    for name in os.listdir(ck):
        if not name.startswith("ckpt-") or name.endswith(".corrupt"):
            continue
        sj = ck / name / "structure.json"
        doc = json.loads(sj.read_text())
        for key in ("mesh_shape", "participant_count"):
            doc["meta"].pop(key, None)
        sj.write_text(json.dumps(doc))
        write_manifest(str(ck / name))

    c3 = _elastic_coord(3)
    with pytest.raises(CorruptStateError, match="mesh-shape metadata"):
        sgd_fit_outofcore(
            logistic_loss, reader, mesh=c3.mesh(), membership=c3,
            checkpoint=CheckpointManager(CheckpointConfig(
                str(tmp_path / "ck"), max_to_keep=99)),
            resume=True, **kw)


def test_widedeep_elastic_resize_bitexact_vs_fixed_fleet(tmp_path):
    """The second elastic adopter: WideDeep's streaming fit consumes
    membership at chunk boundaries; a join resize (params + Adam state
    replicated onto the grown mesh) is bit-exact vs a fixed fleet of
    the new size restoring the same cut."""
    import jax.tree_util as jtu

    from flink_ml_tpu.iteration.checkpoint import CheckpointManager
    from flink_ml_tpu.models.recommendation.widedeep import WideDeep

    rng = np.random.default_rng(11)
    n, d, batch = 1440, 4, 240
    vocab = (7, 5, 3)
    dense = rng.normal(size=(n, d)).astype(np.float32)
    cat = np.stack([rng.integers(0, v, size=n) for v in vocab],
                   1).astype(np.int32)
    y = (rng.random(n) > 0.5).astype(np.float32)

    def reader():
        for i in range(0, n, batch):
            yield {"denseFeatures": dense[i:i + batch],
                   "catFeatures": cat[i:i + batch],
                   "label": y[i:i + batch]}

    wd = WideDeep().set_vocab_sizes(list(vocab)).set_max_iter(3)

    def fit(**kw):
        return wd.fit_outofcore(lambda: reader(), steps_per_dispatch=2,
                                checkpoint_every_steps=2, **kw)

    coord = _elastic_coord(2)
    plan = FaultPlan().inject(coord.SCOPE, at=2, kind="join")
    report = RecoveryReport()
    with plan:
        model_e = resilient_fit(
            fit, checkpoint=CheckpointConfig(str(tmp_path / "ck_e"),
                                             max_to_keep=99),
            elastic=coord,
            backoff=RetryPolicy(base_delay=0.0, sleep=lambda s: None),
            report=report)
    assert report.resizes == 1

    c2 = _elastic_coord(2)
    fit(mesh=c2.mesh(), membership=c2,
        checkpoint=CheckpointConfig(str(tmp_path / "ck_a"),
                                    max_to_keep=99))
    _copy_cut(str(tmp_path / "ck_a"), str(tmp_path / "ck_b"), 6)
    c3 = _elastic_coord(3)
    model_b = fit(mesh=c3.mesh(), membership=c3,
                  checkpoint=CheckpointManager(CheckpointConfig(
                      str(tmp_path / "ck_b"), max_to_keep=99)),
                  resume=True)

    for a, b in zip(jtu.tree_leaves(model_e._params),
                    jtu.tree_leaves(model_b._params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(model_e._loss_log, model_b._loss_log)


def test_elastic_exact_mode_resize_bitexact(tmp_path):
    """Elastic without grad_reduce: the batch shards over every mesh
    axis jointly (dcn x data) and the implicit-psum path resizes
    through the same restore — there is no reducer state, so the
    re-shard is pure placement, and the contract still holds bit-exact
    vs the fixed fleet of the new size."""
    from flink_ml_tpu.data.datacache import DataCacheReader
    from flink_ml_tpu.iteration.checkpoint import CheckpointManager
    from flink_ml_tpu.models.common.losses import logistic_loss
    from flink_ml_tpu.models.common.sgd import SGDConfig, sgd_fit_outofcore

    cache = _elastic_cache(tmp_path, "c_exact")
    cfg = SGDConfig(learning_rate=0.4, max_epochs=2, tol=0.0)
    kw = dict(num_features=8, config=cfg, cache_decoded=False,
              steps_per_dispatch=2, checkpoint_every_steps=2)

    def reader():
        return DataCacheReader(cache, batch_rows=240)

    coord = _elastic_coord(2)
    plan = FaultPlan().inject(coord.SCOPE, at=1, kind="join")
    report = RecoveryReport()
    with plan:
        state_e, log_e = resilient_fit(
            sgd_fit_outofcore, logistic_loss,
            lambda: plan.wrap_source(reader()),
            checkpoint=CheckpointConfig(str(tmp_path / "ck_e"),
                                        max_to_keep=99),
            elastic=coord,
            backoff=RetryPolicy(base_delay=0.0, sleep=lambda s: None),
            report=report, **kw)
    assert report.resizes == 1

    c2 = _elastic_coord(2)
    sgd_fit_outofcore(
        logistic_loss, reader, mesh=c2.mesh(), membership=c2,
        checkpoint=CheckpointConfig(str(tmp_path / "ck_a"),
                                    max_to_keep=99), **kw)
    _copy_cut(str(tmp_path / "ck_a"), str(tmp_path / "ck_b"), 4)
    c3 = _elastic_coord(3)
    state_b, log_b = sgd_fit_outofcore(
        logistic_loss, reader, mesh=c3.mesh(), membership=c3,
        checkpoint=CheckpointManager(CheckpointConfig(
            str(tmp_path / "ck_b"), max_to_keep=99)),
        resume=True, **kw)

    np.testing.assert_array_equal(state_e.coefficients,
                                  state_b.coefficients)
    assert state_e.intercept == state_b.intercept
    np.testing.assert_array_equal(log_e, log_b)


# -- int8 serving chaos (ISSUE 18) -------------------------------------------
# the quantized path's two failure stories: a delta publish must
# re-derive scales for the new generation (stale scales never serve,
# in-flight requests finish on the old ones), and a corrupt quantized
# AOT entry must quarantine + recompile to the exact same codes.

def _int8_endpoint():
    from flink_ml_tpu.models.classification.logisticregression import (
        LogisticRegression)
    from flink_ml_tpu.serving import serve_model

    boot_window = next(_ctl_windows(0, 1))
    boot = LogisticRegression().set_max_iter(1).fit(boot_window)
    return serve_model(boot, boot_window.drop("label").take(2),
                       max_batch_rows=32, max_wait_ms=0.5,
                       precision="int8")


def test_delta_publish_to_int8_tenant_recalibrates_and_swaps_atomically():
    """A delta publish to an int8 tenant re-runs per-channel max-abs
    calibration on the NEW generation's params (rebind re-derives the
    scales, so stale scales never serve) and swaps via the registry
    CAS: the old servable object keeps answering bit-exact on the old
    codes+scales — the in-flight story — while each generation is
    bit-stable across repeat predicts."""
    from flink_ml_tpu.online import DeltaEncoder, params_of_model

    endpoint = _int8_endpoint()
    try:
        feats = next(_ctl_windows(5, 6)).drop("label")
        live0 = endpoint.registry.current("default")
        old_servable = live0.servable
        assert old_servable.precision == "int8"
        scales0 = np.asarray(old_servable._kernel.params["w"]["s"])
        old_a = np.asarray(endpoint.predict(feats)["rawPrediction"])
        old_b = np.asarray(endpoint.predict(feats)["rawPrediction"])
        np.testing.assert_array_equal(old_a, old_b)  # bit-stable gen 0

        pub = endpoint.delta_publisher()
        enc = DeltaEncoder()
        p = params_of_model(old_servable.model)
        p2 = {"w": (p["w"] * np.float32(1.5)).astype(np.float32),
              "b": p["b"]}
        pub.apply(enc.encode(1, p2, pub.stats))
        enc.ack()

        live1 = endpoint.registry.current("default")
        assert live1.generation > live0.generation
        assert live1.servable.precision == "int8"
        scales1 = np.asarray(live1.servable._kernel.params["w"]["s"])
        # re-calibration really happened: the new generation's scales
        # came from the NEW params, not the stale gen-0 calibration
        assert scales1.tobytes() != scales0.tobytes()
        from flink_ml_tpu.kernels.quantize import quantize_channelwise
        exp_q, exp_s = quantize_channelwise(p2["w"])
        np.testing.assert_array_equal(
            np.asarray(live1.servable._kernel.params["w"]["q"]), exp_q)
        np.testing.assert_array_equal(scales1, exp_s)

        new_a = np.asarray(endpoint.predict(feats)["rawPrediction"])
        new_b = np.asarray(endpoint.predict(feats)["rawPrediction"])
        np.testing.assert_array_equal(new_a, new_b)  # bit-stable gen 1
        assert new_a.tobytes() != old_a.tobytes()
        # the pre-swap servable still serves the OLD generation's bits:
        # an in-flight request that grabbed it finishes on the old
        # scales, never a half-swapped mix
        inflight = np.asarray(
            old_servable.predict(feats)["rawPrediction"])
        np.testing.assert_array_equal(inflight, old_a)
    finally:
        endpoint.close()


def test_corrupt_int8_aot_entry_quarantines_and_recompiles_same_codes(
        tmp_path):
    """Flip a byte in a persisted int8 executable, restart the cache
    (fresh ``ExecutableCache`` over the same root): warm-up quarantines
    the entry, recompiles transparently, and — because calibration is
    deterministic host numpy — the rebuilt program serves the exact
    same bits as the pre-corruption reference."""
    from flink_ml_tpu.kernels import aot
    from flink_ml_tpu.kernels.registry import kernel_stats
    from flink_ml_tpu.models.classification.logisticregression import (
        LogisticRegression)
    from flink_ml_tpu.serving import make_servable

    window = next(_ctl_windows(0, 1))
    model = LogisticRegression().set_max_iter(1).fit(window)
    feats = window.drop("label").take(8)
    root = str(tmp_path / "aotcache")
    aot.set_cache(aot.ExecutableCache(root))
    try:
        sv = make_servable(model, feats.take(2), max_batch_rows=8,
                           min_bucket=8, precision="int8").warm_up()
        ref = np.asarray(sv.predict(feats)["rawPrediction"])
        exec_root = os.path.join(root, "exec")
        entries = [os.path.join(exec_root, n)
                   for n in sorted(os.listdir(exec_root))
                   if ".corrupt" not in n and ".tmp." not in n]
        assert entries, "int8 warm-up persisted no AOT entries"
        for entry in entries:
            corrupt_file(os.path.join(entry, "executable.bin"),
                         mode="flip")
        # restarted process: fresh cache object, same directory
        aot.set_cache(aot.ExecutableCache(root))
        before = kernel_stats.snapshot()["aot"]
        sv2 = make_servable(model, feats.take(2), max_batch_rows=8,
                            min_bucket=8, precision="int8").warm_up()
        out = np.asarray(sv2.predict(feats)["rawPrediction"])
        after = kernel_stats.snapshot()["aot"]
        np.testing.assert_array_equal(out, ref)  # same codes, same bits
        assert after["quarantined"] >= before["quarantined"] + 1
        assert any(".corrupt" in n for n in os.listdir(exec_root))
    finally:
        aot.set_cache(None)


# -- retrieval chaos (ISSUE 19) ----------------------------------------------

def _retrieve_endpoint():
    from flink_ml_tpu.retrieval import IVFIndex
    from flink_ml_tpu.serving import serve_model

    rng = np.random.default_rng(190)
    X = rng.normal(size=(240, 16)).astype(np.float32)
    idx = IVFIndex.build(X, nlist=8, k=5, nprobe=8, seed=1,
                         drift_threshold=None)
    q = Table({"query": rng.normal(size=(8, 16)).astype(np.float32)})
    endpoint = serve_model(idx, q.take(2), max_batch_rows=32,
                           max_wait_ms=0.5)
    return endpoint, idx, q


def test_crash_mid_index_delta_publish_heals_idempotently():
    """ISSUE 19 chaos half one: a crash injected INSIDE the index delta
    publish (before the registry swap) leaves the old generation
    serving bit-stable, and the replayed publish of the SAME cut lands
    idempotently — the digest-verified codec never acknowledged the
    crashed cut, so re-encoding step 1 reproduces it exactly — after
    which the new generation serves the inserted vectors.  Each
    generation's neighbor sets are bit-stable across repeat predicts."""
    from flink_ml_tpu.online import DeltaEncoder

    endpoint, idx, q = _retrieve_endpoint()
    try:
        old_a = np.asarray(endpoint.predict(q)["neighbors"])
        old_b = np.asarray(endpoint.predict(q)["neighbors"])
        np.testing.assert_array_equal(old_a, old_b)   # bit-stable gen 1
        gen0 = endpoint.registry.current("default").generation

        mode, nxt = idx.updated(inserts=np.asarray(q["query"]))
        assert mode == "delta"
        pub = endpoint.delta_publisher()
        enc = DeltaEncoder()
        plan = FaultPlan().inject("serving.publish", at=0, kind="crash")
        with plan, pytest.raises(InjectedCrash):
            pub.apply(enc.encode(1, nxt.params, pub.stats))
        # crash BEFORE the swap: the old generation keeps serving the
        # old lists, and the cut stays unacknowledged
        assert endpoint.registry.current("default").generation == gen0
        np.testing.assert_array_equal(
            np.asarray(endpoint.predict(q)["neighbors"]), old_a)

        # the supervised replay: re-encode the same step, republish
        res = pub.apply(enc.encode(1, nxt.params, pub.stats))
        enc.ack()
        assert res.generation == gen0 + 1
        new_a = np.asarray(endpoint.predict(q)["neighbors"])
        new_b = np.asarray(endpoint.predict(q)["neighbors"])
        np.testing.assert_array_equal(new_a, new_b)   # bit-stable gen 2
        # the queries themselves were inserted: each is now its own NN
        np.testing.assert_array_equal(new_a[:, 0], np.arange(240, 248))
    finally:
        endpoint.close()


def test_corrupt_retrieve_aot_entry_quarantines_and_recompiles_same_neighbors(
        tmp_path):
    """ISSUE 19 chaos half two: flip a byte in a persisted retrieve-plan
    executable, restart the cache (fresh ``ExecutableCache`` over the
    same root): warm-up quarantines the entry, recompiles
    transparently, and — the search plan being a pure function of the
    index params — the rebuilt program serves the exact same neighbor
    sets as the pre-corruption reference."""
    from flink_ml_tpu.kernels import aot
    from flink_ml_tpu.kernels.registry import kernel_stats
    from flink_ml_tpu.retrieval import IVFIndex
    from flink_ml_tpu.serving import make_servable

    rng = np.random.default_rng(191)
    X = rng.normal(size=(200, 16)).astype(np.float32)
    idx = IVFIndex.build(X, nlist=8, k=5, nprobe=4, seed=2)
    q = Table({"query": rng.normal(size=(8, 16)).astype(np.float32)})
    root = str(tmp_path / "aotcache")
    aot.set_cache(aot.ExecutableCache(root))
    try:
        sv = make_servable(idx, q.take(2), max_batch_rows=8,
                           min_bucket=8).warm_up()
        ref = np.asarray(sv.predict(q)["neighbors"])
        exec_root = os.path.join(root, "exec")
        entries = [os.path.join(exec_root, n)
                   for n in sorted(os.listdir(exec_root))
                   if ".corrupt" not in n and ".tmp." not in n]
        assert entries, "retrieve warm-up persisted no AOT entries"
        for entry in entries:
            corrupt_file(os.path.join(entry, "executable.bin"),
                         mode="flip")
        # restarted process: fresh cache object, same directory
        aot.set_cache(aot.ExecutableCache(root))
        before = kernel_stats.snapshot()["aot"]
        sv2 = make_servable(idx, q.take(2), max_batch_rows=8,
                            min_bucket=8).warm_up()
        out = np.asarray(sv2.predict(q)["neighbors"])
        after = kernel_stats.snapshot()["aot"]
        np.testing.assert_array_equal(out, ref)
        assert after["quarantined"] >= before["quarantined"] + 1
        assert any(".corrupt" in n for n in os.listdir(exec_root))
    finally:
        aot.set_cache(None)


# -- serving fleet failover chaos (ISSUE 20) ---------------------------------
# the failover PR's chaos acceptance, all on seeded FaultPlans: a chip
# death at the dispatch boundary mid-sweep drops ZERO requests and the
# retried answers are bit-identical with an unfailed sweep; a chip
# death while a delta cut is in flight heals through the publisher's
# drift re-anchor; an autoscale publish racing the failover's CAS
# resolves through exactly one PlacementConflict retry.

def _fo_drain(scheduler, max_batches=10_000):
    """Inline serve loop: form + dispatch until the queues are empty
    (deterministic — no background thread, no wall clock)."""
    batches = 0
    while batches < max_batches:
        formed = scheduler._next_batch(timeout=0.0)
        if formed is None:
            return batches
        scheduler._dispatch(*formed)
        batches += 1
    raise AssertionError("drain did not converge")


def test_fault_plan_chip_kinds_fire_and_randomize_deterministically():
    """chip_down/chip_flap are first-class schedulable kinds: explicit
    schedules raise their own exception types, and ``inject_random``'s
    seeded schedule for a chip kind replays identically (same seed,
    same deaths) while keying on the KIND — a chip_down plan is not a
    transient plan wearing a different label."""
    from flink_ml_tpu.robustness.faults import (InjectedChipDown,
                                                InjectedChipFlap)
    from flink_ml_tpu.serving import CHIP_SCOPE

    plan = (FaultPlan().inject(CHIP_SCOPE, at=0, kind="chip_down")
            .inject(CHIP_SCOPE, at=1, kind="chip_flap"))
    with pytest.raises(InjectedChipDown):
        plan.fire(CHIP_SCOPE)
    with pytest.raises(InjectedChipFlap):
        plan.fire(CHIP_SCOPE)
    assert plan.fires == [(CHIP_SCOPE, 0, "chip_down"),
                          (CHIP_SCOPE, 1, "chip_flap")]

    def deaths(seed):
        return FaultPlan(seed=seed).inject_random(
            CHIP_SCOPE, rate=0.15, horizon=60,
            kind="chip_down").scheduled(CHIP_SCOPE)

    assert deaths(11) == deaths(11)
    assert deaths(11) != deaths(12)
    assert 0 < len(deaths(11)) < 60
    assert all(kind == "chip_down" for _, kind in deaths(11))
    # the kind participates in the schedule derivation: the same seed's
    # transient schedule lands on different indices
    transients = FaultPlan(seed=11).inject_random(
        CHIP_SCOPE, rate=0.15, horizon=60).scheduled(CHIP_SCOPE)
    assert [i for i, _ in transients] != [i for i, _ in deaths(11)]


def test_chip_death_mid_sweep_drops_nothing_and_answers_bitexact():
    """THE ISSUE 20 chaos acceptance, half one: a seeded chip_down at
    the dispatch boundary mid-sweep.  Every in-flight request is
    requeued with its future intact and re-served by the survivor —
    zero drops, and every answer is bit-identical with the unfailed
    sweep (the requeue replays the same rows through the same compiled
    programs; a chip move never perturbs the math)."""
    from flink_ml_tpu.autoscale.placement import PlacementStore
    from flink_ml_tpu.serving import (DISPATCH_SCOPE, SLO_INTERACTIVE,
                                      SLO_STANDARD, FailoverDriver,
                                      SharedScheduler)

    model_rt, model_batch = _fit_lr(seed=0), _fit_lr(seed=1)
    feats = _lr_table(n=96, seed=7).drop("label")
    requests = [feats.slice(8 * i, 8 * i + 8) for i in range(12)]

    def sweep(plan=None):
        s = SharedScheduler(max_batch_rows=16, max_wait_ms=0.0,
                            queue_capacity=4096)
        s.add_tenant("rt", model_rt, feats.take(2), slo=SLO_INTERACTIVE)
        s.add_tenant("batch", model_batch, feats.take(2),
                     slo=SLO_STANDARD)
        store = PlacementStore(2)
        store.publish({"rt": [0], "batch": [1]}, 0)
        driver = FailoverDriver(s, store, chips=[0, 1])
        futures = [s.submit("rt" if i % 2 == 0 else "batch", req)
                   for i, req in enumerate(requests)]
        if plan is None:
            _fo_drain(s)
        else:
            with plan:
                _fo_drain(s)
        return s, store, driver, [f.result(timeout=0) for f in futures]

    _, _, _, ref = sweep()

    plan = FaultPlan(seed=20).inject(DISPATCH_SCOPE, at=1,
                                     kind="chip_down")
    s, store, driver, outs = sweep(plan)
    assert plan.fires == [(DISPATCH_SCOPE, 1, "chip_down")]
    assert len(driver.reports) == 1
    rep = driver.reports[0]
    assert rep.dead_chips == (1,)       # LIFO victim: the newest lease
    assert rep.cause == "dispatch"
    assert rep.requeued > 0
    assert s._requeued.value == rep.requeued
    assert s._deadline_shed.value == 0  # nothing aged out of its SLO
    assert rep.moved == ("batch",)
    assert store.current().chips_for("batch") == (0,)
    # zero drops, bit-identical: every future answered with the exact
    # bits the unfailed sweep produced
    assert len(outs) == len(ref) == len(requests)
    for got, want in zip(outs, ref):
        assert got.column_names == want.column_names
        for col in got.column_names:
            np.testing.assert_array_equal(np.asarray(got[col]),
                                          np.asarray(want[col]))


def test_chip_death_between_delta_cut_and_publish_reanchors():
    """THE ISSUE 20 chaos acceptance, half two: a chip dies while a
    delta cut is in flight (encoded, not yet published).  The failover
    re-admits the moved tenant under a fresh registry generation; the
    publisher's next apply() sees the drift, re-anchors its base on the
    re-admitted generation, and the pending delta lands cleanly on top
    — no divergent bits, no stuck publisher, idempotent by the same
    digest discipline every other heal in this file rides."""
    from flink_ml_tpu.autoscale.placement import PlacementStore
    from flink_ml_tpu.online import DeltaEncoder, params_of_model
    from flink_ml_tpu.robustness.faults import InjectedChipDown
    from flink_ml_tpu.serving import (SLO_INTERACTIVE, FailoverDriver,
                                      SharedScheduler)

    model = _fit_lr(seed=0)
    feats = _lr_table(seed=5).drop("label")
    s = SharedScheduler(max_batch_rows=32, max_wait_ms=0.0)
    s.add_tenant("t", model, feats.take(2), slo=SLO_INTERACTIVE)
    store = PlacementStore(2)
    store.publish({"t": [1]}, 0)
    driver = FailoverDriver(s, store, chips=[0, 1])

    pub = s.delta_publisher("t")
    enc = DeltaEncoder()
    p0 = params_of_model(model)
    p1 = {"w": (p0["w"] * np.float32(1.25)).astype(np.float32),
          "b": p0["b"]}
    res1 = pub.apply(enc.encode(1, p1, pub.stats))
    enc.ack()
    assert res1.mode == "full"
    gen1 = s.registry.current("t").generation
    assert gen1 == res1.generation

    # the step-2 cut is encoded — in flight — when its chip dies.
    # Sparse on purpose: one touched coefficient keeps the payload
    # under the staleness policy's full_ratio, so the cut IS a delta
    w2 = p1["w"].copy()
    w2[0] += np.float32(0.5)
    p2 = {"w": w2, "b": p1["b"]}
    update2 = enc.encode(2, p2, pub.stats)
    rep = driver.on_chip_fault(InjectedChipDown("died mid-publish"))
    assert rep is not None and rep.dead_chips == (1,)
    assert rep.moved == ("t",)
    assert store.current().chips_for("t") == (0,)
    gen_readmit = s.registry.current("t").generation
    assert gen_readmit == gen1 + 1      # re-admission stamped the move

    res2 = pub.apply(update2)
    enc.ack()
    assert res2.mode == "delta"         # the delta survived the move
    assert res2.generation == gen_readmit + 1
    served = params_of_model(s.registry.current("t").servable.model)
    np.testing.assert_array_equal(served["w"], p2["w"])
    np.testing.assert_array_equal(served["b"], p2["b"])
    # and the tenant still answers on the healed generation
    fut = s.submit("t", feats.take(4))
    _fo_drain(s)
    assert fut.result(timeout=0).num_rows == 4


def test_autoscale_publish_racing_failover_resolves_in_one_retry():
    """THE ISSUE 20 chaos acceptance, half three: an autoscale tick's
    placement publish lands between the failover's read of the current
    map and its conditional publish.  The shared generation stream
    turns that into exactly one PlacementConflict retry — the driver
    re-derives the eviction against the racer's map and the second
    publish wins; neither writer clobbers, the dead chip's tenant still
    moves, and the racer's own edit survives."""
    from flink_ml_tpu.autoscale.placement import PlacementStore
    from flink_ml_tpu.robustness.faults import InjectedChipDown
    from flink_ml_tpu.serving import (SLO_INTERACTIVE, FailoverDriver,
                                      SharedScheduler)

    class RacingStore(PlacementStore):
        """Injects ONE out-of-band publish (the autoscale tick re-deriving
        the learner extent) between a CAS caller's read and its
        conditional publish — the deterministic rendering of the race."""

        raced = 0

        def publish(self, servables, learner_workers, *,
                    expected_generation=None):
            if expected_generation is not None and not self.raced:
                self.raced += 1
                cur = self.current()
                PlacementStore.publish(self, dict(cur.servables),
                                       cur.learner_workers + 1)
            return PlacementStore.publish(
                self, servables, learner_workers,
                expected_generation=expected_generation)

    model = _fit_lr(seed=0)
    feats = _lr_table(seed=5).drop("label")
    s = SharedScheduler(max_batch_rows=32, max_wait_ms=0.0)
    s.add_tenant("x", model, feats.take(2), slo=SLO_INTERACTIVE)
    store = RacingStore(3)
    # "y" is placed but never admitted (another process's tenant): the
    # re-placement must carry it anyway — placement is fleet state, not
    # this scheduler's private view
    store.publish({"x": [2], "y": [0]}, 0)
    gen0 = store.generation
    driver = FailoverDriver(s, store, chips=[0, 1, 2])

    rep = driver.on_chip_fault(InjectedChipDown("death under the tick"))
    assert rep is not None
    assert store.raced == 1
    assert rep.conflicts == 1 and driver.conflicts == 1
    pmap = store.current()
    assert pmap.generation == gen0 + 2  # racer's publish + the retry
    assert rep.generation == pmap.generation
    assert pmap.chips_for("x") == (1,)  # least-loaded live survivor
    assert pmap.chips_for("y") == (0,)  # the racer's view preserved...
    assert pmap.learner_workers == 1    # ...including its own edit
    assert s.brownout_level == 1        # capacity loss still accounted
