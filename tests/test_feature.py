"""Feature transformers + evaluator tests."""

import numpy as np
import pytest

from flink_ml_tpu import Pipeline, Table
from flink_ml_tpu.models.evaluation import BinaryClassificationEvaluator
from flink_ml_tpu.models.feature import (
    MinMaxScaler,
    MinMaxScalerModel,
    OneHotEncoder,
    StandardScaler,
    StandardScalerModel,
    StringIndexer,
    StringIndexerModel,
    VectorAssembler,
)


def test_standard_scaler(tmp_path):
    X = np.array([[1.0, 10.0], [3.0, 20.0], [5.0, 30.0]])
    t = Table({"features": X})
    model = StandardScaler().set_output_col("scaled").fit(t)
    out = model.transform(t)[0]["scaled"]
    np.testing.assert_allclose(out.mean(0), 0, atol=1e-6)
    np.testing.assert_allclose(out.std(0), 1, atol=1e-5)
    # persistence
    path = str(tmp_path / "ss")
    model.save(path)
    loaded = StandardScalerModel.load(path)
    np.testing.assert_allclose(loaded.transform(t)[0]["scaled"], out,
                               atol=1e-6)


def test_standard_scaler_flags():
    X = np.array([[1.0], [3.0]])
    t = Table({"features": X})
    no_mean = (StandardScaler().set("withMean", False).fit(t)
               .transform(t)[0]["output"])
    assert no_mean.min() > 0  # not centered


def test_minmax_scaler(tmp_path):
    X = np.array([[0.0, -5.0], [10.0, 5.0]])
    t = Table({"features": X})
    model = MinMaxScaler().fit(t)
    out = model.transform(t)[0]["output"]
    np.testing.assert_allclose(out, [[0, 0], [1, 1]], atol=1e-9)
    model.set("min", -1.0).set("max", 1.0)
    out = model.transform(t)[0]["output"]
    np.testing.assert_allclose(out, [[-1, -1], [1, 1]], atol=1e-9)
    path = str(tmp_path / "mm")
    model.save(path)
    loaded = MinMaxScalerModel.load(path)
    np.testing.assert_allclose(loaded.transform(t)[0]["output"], out)
    with pytest.raises(ValueError):
        model.set("min", 2.0).set("max", 1.0).transform(t)


def test_string_indexer(tmp_path):
    t = Table.from_rows(
        [("a",), ("b",), ("b",), ("c",), ("b",)], ["city"])
    model = (StringIndexer().set_input_cols("city").set_output_cols("city_id")
             .fit(t))
    out = model.transform(t)[0]
    # vocabulary by descending frequency: b(3), then a/c by value
    assert model._vocab["city"] == ["b", "a", "c"]
    np.testing.assert_array_equal(out["city_id"], [1, 0, 0, 2, 0])
    # unseen value policy
    t2 = Table.from_rows([("z",)], ["city"])
    assert model.transform(t2)[0]["city_id"][0] == 3  # keep -> len(vocab)
    with pytest.raises(ValueError):
        model.set("handleInvalid", "error").transform(t2)
    path = str(tmp_path / "si")
    model.save(path)
    loaded = StringIndexerModel.load(path)
    assert loaded._vocab["city"] == ["b", "a", "c"]


def test_one_hot_encoder():
    t = Table({"id": np.array([0, 1, 2, 1])})
    model = OneHotEncoder().set_input_cols("id").set_output_cols("hot").fit(t)
    out = model.transform(t)[0]["hot"]
    assert out.shape == (4, 2)  # dropLast: 3 categories -> 2 cols
    np.testing.assert_array_equal(out[0], [1, 0])
    np.testing.assert_array_equal(out[2], [0, 0])  # last category dropped
    full = (OneHotEncoder().set_input_cols("id").set_output_cols("hot")
            .set("dropLast", False).fit(t).transform(t)[0]["hot"])
    assert full.shape == (4, 3)
    with pytest.raises(ValueError):
        model.transform(Table({"id": np.array([5])}))


def test_vector_assembler():
    t = Table({"a": np.array([1.0, 2.0]),
               "b": np.array([[10.0, 20.0], [30.0, 40.0]])})
    out = (VectorAssembler().set_input_cols("a", "b")
           .transform(t)[0]["features"])
    np.testing.assert_array_equal(out, [[1, 10, 20], [2, 30, 40]])
    with pytest.raises(ValueError):
        VectorAssembler().transform(t)


def test_feature_pipeline_end_to_end(tmp_path):
    # assemble -> scale -> logistic regression, all through Pipeline
    from flink_ml_tpu.models.classification import LogisticRegression

    rng = np.random.default_rng(0)
    a = rng.normal(size=128)
    b = rng.normal(size=(128, 2)) * 100
    y = ((a + b[:, 0] / 100) > 0).astype(np.int64)
    t = Table({"a": a, "b": b, "label": y})

    pipeline = Pipeline([
        VectorAssembler().set_input_cols("a", "b").set_features_col("raw"),
        StandardScaler().set_features_col("raw").set_output_col("features"),
        LogisticRegression().set_max_iter(30).set_learning_rate(0.5),
    ])
    model = pipeline.fit(t)
    out = model.transform(t)[0]
    assert np.mean(out["prediction"] == y) > 0.9
    path = str(tmp_path / "pm")
    model.save(path)
    from flink_ml_tpu import PipelineModel
    np.testing.assert_array_equal(
        PipelineModel.load(path).transform(t)[0]["prediction"],
        out["prediction"])


def test_binary_evaluator():
    labels = np.array([1, 1, 0, 0, 1, 0], np.float64)
    perfect = np.array([0.9, 0.8, 0.2, 0.1, 0.95, 0.3])
    t = Table({"label": labels, "rawPrediction": perfect})
    ev = BinaryClassificationEvaluator().set_metrics(
        "areaUnderROC", "areaUnderPR", "accuracy")
    out = ev.transform(t)[0]
    assert out["areaUnderROC"][0] == pytest.approx(1.0)
    assert out["areaUnderPR"][0] == pytest.approx(1.0, abs=1e-6)
    assert out["accuracy"][0] == pytest.approx(1.0)

    random_scores = np.array([0.5, 0.4, 0.6, 0.5, 0.45, 0.55])
    t2 = Table({"label": labels, "rawPrediction": random_scores})
    auc = ev.transform(t2)[0]["areaUnderROC"][0]
    assert 0.0 <= auc <= 1.0
    with pytest.raises(Exception):
        ev.set_metrics("nope")


def test_evaluator_against_sklearn_formula():
    # cross-check AUC on a non-trivial case against the rank-statistic formula
    rng = np.random.default_rng(3)
    scores = rng.uniform(size=200)
    labels = (rng.uniform(size=200) < scores).astype(np.float64)  # correlated
    t = Table({"label": labels, "rawPrediction": scores})
    auc = BinaryClassificationEvaluator().transform(t)[0]["areaUnderROC"][0]
    # Mann-Whitney U formulation
    pos = scores[labels == 1]
    neg = scores[labels == 0]
    u = np.mean([(p > neg).mean() + 0.5 * (p == neg).mean() for p in pos])
    assert auc == pytest.approx(u, abs=1e-3)


def test_indexer_to_onehot_keep_pipeline():
    # StringIndexer(keep) -> OneHotEncoder(keep): unseen category becomes an
    # all-zeros row instead of crashing the serving pipeline.
    train = Table.from_rows([("a",), ("b",), ("a",)], ["city"])
    idx = (StringIndexer().set_input_cols("city").set_output_cols("id")
           .fit(train))
    indexed = idx.transform(train)[0]
    enc = (OneHotEncoder().set_input_cols("id").set_output_cols("hot")
           .set("dropLast", False).set("handleInvalid", "keep").fit(indexed))
    serve = Table.from_rows([("a",), ("z",)], ["city"])
    out = enc.transform(idx.transform(serve)[0])[0]["hot"]
    np.testing.assert_array_equal(out[0], [1, 0])
    np.testing.assert_array_equal(out[1], [0, 0])  # unseen -> zeros


def test_string_indexer_vectorized_large():
    rng = np.random.default_rng(0)
    values = rng.choice(["x", "y", "z", "w"], size=100_000)
    t = Table({"c": values})
    model = StringIndexer().set_input_cols("c").set_output_cols("id").fit(t)
    import time
    t0 = time.perf_counter()
    ids = model.transform(t)[0]["id"]
    assert time.perf_counter() - t0 < 1.0  # vectorized, not a python loop
    # ids faithfully invert through the vocab
    vocab = np.asarray(model._vocab["c"])
    np.testing.assert_array_equal(vocab[ids], values)


def test_auc_tie_handling():
    # Fully tied scores must give AUC 0.5 regardless of row order.
    for labels in ([1, 0], [0, 1]):
        t = Table({"label": np.asarray(labels, np.float64),
                   "rawPrediction": np.array([0.7, 0.7])})
        auc = BinaryClassificationEvaluator().transform(t)[0]["areaUnderROC"][0]
        assert auc == pytest.approx(0.5)
    # quantized scores vs the tie-aware Mann-Whitney formula
    rng = np.random.default_rng(1)
    scores = np.round(rng.uniform(size=300), 1)  # heavy ties
    labels = (rng.uniform(size=300) < scores).astype(np.float64)
    t = Table({"label": labels, "rawPrediction": scores})
    auc = BinaryClassificationEvaluator().transform(t)[0]["areaUnderROC"][0]
    pos, neg = scores[labels == 1], scores[labels == 0]
    u = np.mean([(p > neg).mean() + 0.5 * (p == neg).mean() for p in pos])
    assert auc == pytest.approx(u, abs=1e-6)


def test_string_indexer_no_truncation():
    # An unseen value longer than the fitted dtype width must not be
    # truncated onto a vocab prefix.
    train = Table.from_rows([("cat",), ("dog",)], ["w"])
    model = (StringIndexer().set_input_cols("w").set_output_cols("id")
             .fit(train))
    out = model.transform(Table.from_rows([("cats",)], ["w"]))[0]["id"]
    assert out[0] == 2  # unseen -> len(vocab), NOT id of 'cat'
    with pytest.raises(ValueError):
        (model.set("handleInvalid", "error")
         .transform(Table.from_rows([("cats",)], ["w"])))


def test_string_indexer_order_types():
    """The four stringOrderType orderings (the Flink ML param)."""
    t = Table({"c": np.asarray(["b", "a", "b", "c", "b", "a"], dtype=object)})

    def vocab(order):
        m = (StringIndexer().set_input_cols("c").set_output_cols("i")
             .set_string_order_type(order).fit(t))
        return m._vocab["c"]

    assert vocab("frequencyDesc") == ["b", "a", "c"]   # 3, 2, 1
    assert vocab("frequencyAsc") == ["c", "a", "b"]
    assert vocab("alphabetAsc") == ["a", "b", "c"]
    assert vocab("alphabetDesc") == ["c", "b", "a"]

    with pytest.raises(ValueError):
        StringIndexer().set_string_order_type("nope")
