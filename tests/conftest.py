"""Test harness configuration.

The reference exercises multi-"node" behavior with an in-process Flink
MiniCluster (2 TM x 2 slots, ``UnboundedStreamIterationITCase.java:155-161``).
The TPU-native analog is a virtual 8-device CPU mesh: we force the host
platform to expose 8 XLA devices *before* jax is imported anywhere, so every
sharding/collective test runs real SPMD partitioning in one process.
"""

import os

# Must happen before any jax import.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(42)
