"""Test harness configuration.

The reference exercises multi-"node" behavior with an in-process Flink
MiniCluster (2 TM x 2 slots, ``UnboundedStreamIterationITCase.java:155-161``).
The TPU-native analog is a virtual 8-device CPU mesh: every sharding /
collective test runs real SPMD partitioning in one process.

The environment's sitecustomize imports jax at interpreter startup (to
register the axon TPU backend), so JAX_PLATFORMS in os.environ is already
consumed before this file runs — we must update the live jax config instead.
The unit/IT suite always runs on the virtual CPU mesh; real-TPU execution is
exercised by bench.py and __graft_entry__.py.
"""

import os

# XLA_FLAGS is read lazily at CPU-client creation, so this still works even
# though jax itself is already imported.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(42)


@pytest.fixture
def cpu_mesh_8():
    """All 8 virtual CPU devices on one ``data`` axis (the MiniCluster
    analog)."""
    from flink_ml_tpu.parallel.mesh import device_mesh

    return device_mesh({"data": 8})
