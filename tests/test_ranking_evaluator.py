"""RankingEvaluator — hand-computed top-k metric fixtures."""

import numpy as np
import pytest

from flink_ml_tpu import Table
from flink_ml_tpu.models.evaluation import RankingEvaluator


def _lists_table(preds, labels):
    p = np.empty(len(preds), object)
    r = np.empty(len(labels), object)
    for i, (a, b) in enumerate(zip(preds, labels)):
        p[i], r[i] = list(a), list(b)
    return Table({"prediction": p, "label": r})


def test_hand_computed_single_row():
    # ranked [a, b, c, d], relevant {a, c, x}; k = 4
    t = _lists_table([["a", "b", "c", "d"]], [["a", "c", "x"]])
    out = RankingEvaluator().set_k(4).transform(t)[0]
    assert out["precisionAtK"][0] == pytest.approx(2 / 4)
    assert out["recallAtK"][0] == pytest.approx(2 / 3)
    assert out["hitRateAtK"][0] == 1.0
    # DCG = 1/log2(2) + 1/log2(4) = 1.5; IDCG(3 relevant, k=4) =
    # 1/log2(2)+1/log2(3)+1/log2(4) = 2.1309
    expected_ndcg = 1.5 / (1 + 1 / np.log2(3) + 0.5)
    assert out["ndcgAtK"][0] == pytest.approx(expected_ndcg, rel=1e-6)
    # AP@4 = (1/1 + 2/3) / min(3, 4)
    assert out["mapAtK"][0] == pytest.approx((1 + 2 / 3) / 3, rel=1e-6)


def test_perfect_and_worthless_rankings_average():
    t = _lists_table(
        [["a", "b"], ["x", "y"]],      # row 1 perfect, row 2 all misses
        [["a", "b"], ["a", "b"]])
    out = RankingEvaluator().set_k(2).transform(t)[0]
    assert out["precisionAtK"][0] == pytest.approx(0.5)
    assert out["recallAtK"][0] == pytest.approx(0.5)
    assert out["hitRateAtK"][0] == pytest.approx(0.5)
    assert out["ndcgAtK"][0] == pytest.approx(0.5)
    assert out["mapAtK"][0] == pytest.approx(0.5)


def test_k_truncates_predictions():
    t = _lists_table([["x", "y", "a"]], [["a"]])
    out2 = RankingEvaluator().set_k(2).transform(t)[0]
    assert out2["hitRateAtK"][0] == 0.0         # a is ranked third
    out3 = RankingEvaluator().set_k(3).transform(t)[0]
    assert out3["hitRateAtK"][0] == 1.0


def test_rows_without_relevant_items_skipped():
    t = _lists_table([["a"], ["b"]], [["a"], []])
    out = RankingEvaluator().set_k(1).transform(t)[0]
    assert out["precisionAtK"][0] == 1.0        # only row 1 counted
    with pytest.raises(ValueError, match="no rows"):
        RankingEvaluator().transform(_lists_table([["a"]], [[]]))


def test_metric_subset_and_validation():
    t = _lists_table([["a"]], [["a"]])
    out = (RankingEvaluator().set_metrics("ndcgAtK", "mapAtK")
           .set_k(1).transform(t)[0])
    assert out.column_names == ["ndcgAtK", "mapAtK"]
    with pytest.raises(ValueError, match="invalid value"):
        RankingEvaluator().set_metrics("nope")


def test_integer_item_ids():
    t = _lists_table([[3, 1, 2]], [[2, 9]])
    out = RankingEvaluator().set_k(3).transform(t)[0]
    assert out["recallAtK"][0] == pytest.approx(0.5)


def test_duplicate_predictions_count_once():
    t = _lists_table([["a", "a"]], [["a"]])
    out = RankingEvaluator().set_k(2).transform(t)[0]
    assert out["recallAtK"][0] == pytest.approx(1.0)
    assert out["mapAtK"][0] == pytest.approx(1.0)
    assert out["ndcgAtK"][0] <= 1.0


def test_none_label_cell_skipped():
    p = np.empty(2, object)
    p[0], p[1] = ["a"], ["b"]
    r = np.empty(2, object)
    r[0], r[1] = ["a"], None
    out = (RankingEvaluator().set_k(1)
           .transform(Table({"prediction": p, "label": r}))[0])
    assert out["precisionAtK"][0] == pytest.approx(1.0)
