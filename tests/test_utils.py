"""Aux subsystem tests: metrics, config, profiler timer."""

import os

import jax.numpy as jnp
import numpy as np

from flink_ml_tpu.iteration import IterationBodyResult, IterationConfig, iterate
from flink_ml_tpu.utils.config import (
    FrameworkConfig,
    get_config,
    resolve_cache_dir,
    set_config,
)
from flink_ml_tpu.utils.metrics import (
    IterationMetricsListener,
    MetricGroup,
)
from flink_ml_tpu.utils.profiler import StepTimer


def test_metric_group_hierarchy():
    root = MetricGroup()
    root.counter("a").inc(3)
    sub = root.add_group("epoch")
    sub.counter("records").inc(100)
    sub.gauge("rate").set(5.5)
    snap = root.snapshot()
    assert snap == {"a": 3, "epoch.records": 100, "epoch.rate": 5.5}
    # idempotent registration
    root.counter("a").inc()
    assert root.snapshot()["a"] == 4


def test_iteration_metrics_listener():
    listener = IterationMetricsListener(records_per_epoch=1000)

    def body(x, e):
        return IterationBodyResult(x + 1, outputs=x * 2.0)

    res = iterate(body, jnp.asarray(0.0), max_epochs=4,
                  config=IterationConfig(mode="hosted"),
                  listeners=[listener])
    assert len(listener.epoch_seconds) == 4
    assert listener.epoch_metrics == [0.0, 2.0, 4.0, 6.0]
    snap = listener.group.snapshot()
    assert snap["epochs"] == 4
    assert snap["records"] == 4000
    assert snap["records_per_sec"] > 0
    assert snap["total_seconds"] > 0


def test_config_env_override(monkeypatch):
    monkeypatch.setenv("FLINK_ML_TPU_DATA_CACHE_PATH", "/tmp/fmt_cache_test")
    monkeypatch.setenv("FLINK_ML_TPU_LOG_EVERY_EPOCHS", "7")
    cfg = FrameworkConfig.from_env()
    assert cfg.data_cache_path == "/tmp/fmt_cache_test"
    assert cfg.log_every_epochs == 7


def test_resolve_cache_dir(tmp_path, monkeypatch):
    old = get_config()
    try:
        set_config(FrameworkConfig(data_cache_path=str(tmp_path / "c")))
        path = resolve_cache_dir()
        assert path == str(tmp_path / "c")
        assert os.path.isdir(path)

        set_config(FrameworkConfig())  # fallback: fresh tmp dir
        p1, p2 = resolve_cache_dir(), resolve_cache_dir()
        assert p1 != p2 and os.path.isdir(p1)
    finally:
        set_config(old)


def test_step_timer_fences_device_work():
    t = StepTimer().start()
    x = jnp.ones((64, 64)) @ jnp.ones((64, 64))
    elapsed = t.stop(probe=x)
    assert elapsed > 0
    assert t.laps == [elapsed]


def test_fixed_row_batcher_pin_pad_grow():
    import pytest

    from flink_ml_tpu.utils.padding import FixedRowBatcher

    b = FixedRowBatcher(4)
    assert b.rows is None
    out = b.pad((np.ones((6, 2), np.float32), np.ones((6,), np.int32)))
    assert b.rows == 8                      # 6 rounded up to multiple 4
    assert out[0].shape == (8, 2) and out[1].shape == (8,)
    assert out[0][6:].sum() == 0            # zero padding
    # later short batch pads to the pinned rows
    out2 = b.pad((np.ones((3, 2), np.float32), np.ones((3,), np.int32)))
    assert out2[0].shape == (8, 2)
    # growing batch fails loudly
    with pytest.raises(ValueError, match="growing batch"):
        b.pad((np.ones((9, 2), np.float32), np.ones((9,), np.int32)))
    # explicit pin is a no-op once pinned
    b.pin(100)
    assert b.rows == 8
    with pytest.raises(ValueError, match="multiple"):
        FixedRowBatcher(0)


def test_fixed_row_batcher_concurrent_first_batch():
    """Two decode workers racing the first batch: exactly ONE pin wins
    (a lost pin would append twice — observable in _rows)."""
    import threading

    from flink_ml_tpu.utils.padding import FixedRowBatcher

    for _ in range(20):
        b = FixedRowBatcher(1)
        results = []
        barrier = threading.Barrier(2)

        def worker(rows):
            barrier.wait()
            out = b.pad((np.ones((rows, 1), np.float32),))
            results.append(out[0].shape[0])

        ts = [threading.Thread(target=worker, args=(64,)) for _ in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert results == [64, 64]
        assert len(b._rows) == 1            # a raced pin appends twice
