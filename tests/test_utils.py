"""Aux subsystem tests: metrics, config, profiler timer."""

import os

import jax.numpy as jnp
import numpy as np

from flink_ml_tpu.iteration import IterationBodyResult, IterationConfig, iterate
from flink_ml_tpu.utils.config import (
    FrameworkConfig,
    get_config,
    resolve_cache_dir,
    set_config,
)
from flink_ml_tpu.utils.metrics import (
    IterationMetricsListener,
    MetricGroup,
)
from flink_ml_tpu.utils.profiler import StepTimer


def test_metric_group_hierarchy():
    root = MetricGroup()
    root.counter("a").inc(3)
    sub = root.add_group("epoch")
    sub.counter("records").inc(100)
    sub.gauge("rate").set(5.5)
    snap = root.snapshot()
    assert snap == {"a": 3, "epoch.records": 100, "epoch.rate": 5.5}
    # idempotent registration
    root.counter("a").inc()
    assert root.snapshot()["a"] == 4


def test_iteration_metrics_listener():
    listener = IterationMetricsListener(records_per_epoch=1000)

    def body(x, e):
        return IterationBodyResult(x + 1, outputs=x * 2.0)

    res = iterate(body, jnp.asarray(0.0), max_epochs=4,
                  config=IterationConfig(mode="hosted"),
                  listeners=[listener])
    assert len(listener.epoch_seconds) == 4
    assert listener.epoch_metrics == [0.0, 2.0, 4.0, 6.0]
    snap = listener.group.snapshot()
    assert snap["epochs"] == 4
    assert snap["records"] == 4000
    assert snap["records_per_sec"] > 0
    assert snap["total_seconds"] > 0


def test_config_env_override(monkeypatch):
    monkeypatch.setenv("FLINK_ML_TPU_DATA_CACHE_PATH", "/tmp/fmt_cache_test")
    monkeypatch.setenv("FLINK_ML_TPU_LOG_EVERY_EPOCHS", "7")
    cfg = FrameworkConfig.from_env()
    assert cfg.data_cache_path == "/tmp/fmt_cache_test"
    assert cfg.log_every_epochs == 7


def test_resolve_cache_dir(tmp_path, monkeypatch):
    old = get_config()
    try:
        set_config(FrameworkConfig(data_cache_path=str(tmp_path / "c")))
        path = resolve_cache_dir()
        assert path == str(tmp_path / "c")
        assert os.path.isdir(path)

        set_config(FrameworkConfig())  # fallback: fresh tmp dir
        p1, p2 = resolve_cache_dir(), resolve_cache_dir()
        assert p1 != p2 and os.path.isdir(p1)
    finally:
        set_config(old)


def test_step_timer_fences_device_work():
    t = StepTimer().start()
    x = jnp.ones((64, 64)) @ jnp.ones((64, 64))
    elapsed = t.stop(probe=x)
    assert elapsed > 0
    assert t.laps == [elapsed]
