"""Multi-tenant serving fabric tests (ISSUE 14): WFQ fairness shares,
the shed-order contract (bulk before interactive, property-tested at the
boundary), compilation-free admission (zero new lowerings for tenant
N+1 of a served schema), cross-tenant coalescing on a shared servable,
publish-chaos isolation (a delta push to tenant A leaves tenant B's
served bits and latency ring untouched), the embedding-row cache
(exact under eviction churn, LRU order, bypass fallback, bit-exact
cached WideDeep serving incl. across rebind), the lock-free batcher
shed fast path, and the generation-stamped shed events."""

import threading

import numpy as np
import pytest

from flink_ml_tpu import Table
from flink_ml_tpu.serving import (
    SLO_BULK,
    SLO_CLASSES,
    SLO_INTERACTIVE,
    SLO_STANDARD,
    EmbeddingRowCache,
    MicroBatcher,
    ModelRegistry,
    ServingEndpoint,
    ServingOverloadedError,
    SharedScheduler,
    make_servable,
)
from flink_ml_tpu.serving.metrics import HEALTH_DEGRADED, HEALTH_SERVING


# -- fixtures ----------------------------------------------------------------

def _lr_table(n=64, d=8, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d))
    y = (X[:, 0] + 0.3 * rng.normal(size=n) > 0).astype(np.int64)
    return Table({"features": X, "label": y})


def _fit_lr(seed=0):
    from flink_ml_tpu.models.classification.logisticregression import (
        LogisticRegression)

    return LogisticRegression().set_max_iter(3).fit(_lr_table(seed=seed))


def _lr_from_weights(w, b):
    from flink_ml_tpu.models.classification.logisticregression import (
        LogisticRegressionModel)

    model = LogisticRegressionModel()
    model.set_model_data(Table({"coefficients": np.asarray(w)[None, :],
                                "intercept": np.array([b])}))
    return model


class _StubServable:
    """Queue-mechanics stub: echoes its input, always ready — lets the
    WFQ/shed tests exercise pure admission + placement without model
    fits or compiles."""

    ready = True
    warmup_report = None

    def __init__(self, model, example, **kwargs):
        self.model = model
        self.example = example
        self.max_batch_rows = kwargs.get("max_batch_rows", 256)
        self.min_bucket = kwargs.get("min_bucket", 8)
        self.output_cols = None

    def warm_up(self):
        return self

    def check_schema(self, table):
        pass

    def bucket_for(self, rows):
        return max(8, rows)

    def predict(self, table):
        return table


def _stub_scheduler(**kwargs):
    return SharedScheduler(ModelRegistry(servable_factory=_StubServable),
                           **kwargs)


def _feats(n=256, seed=1):
    return _lr_table(n=n, seed=seed).drop("label")


def _drain(scheduler, max_batches=10_000):
    """Run the scheduler's pick->dispatch loop inline (no thread) until
    the queue is empty; returns the number of batches formed."""
    batches = 0
    while True:
        formed = scheduler._next_batch(timeout=0.0)
        if formed is None:
            return batches
        scheduler._dispatch(*formed)
        batches += 1


# -- WFQ fairness ------------------------------------------------------------

def test_wfq_weighted_shares_within_class():
    """Backlogged same-class tenants share served rows in proportion to
    their weights; a serving prefix of the saturated queues shows the
    3:1:1 split within one batch of tolerance."""
    s = _stub_scheduler(max_batch_rows=4, max_wait_ms=0.0,
                        queue_capacity=4096)
    feats = _feats()
    for name, weight in (("heavy", 3.0), ("light1", 1.0),
                         ("light2", 1.0)):
        s.add_tenant(name, object(), feats.take(2), slo=SLO_STANDARD,
                     weight=weight)
        for _ in range(60):
            s.submit(name, feats.take(4))
    for _ in range(30):                 # a strict prefix: queues stay hot
        formed = s._next_batch(timeout=0.0)
        assert formed is not None
        s._dispatch(*formed)
    served = {name: s.tenant(name).rows_served
              for name in ("heavy", "light1", "light2")}
    total = sum(served.values())
    assert total == 30 * 4
    # weighted shares: 3/5, 1/5, 1/5 of rows, within one 4-row batch
    assert abs(served["heavy"] - total * 3 / 5) <= 4
    assert abs(served["light1"] - total / 5) <= 4
    assert abs(served["light2"] - total / 5) <= 4
    _drain(s)


def test_wfq_idle_tenant_reenters_at_class_virtual_time():
    """An idle tenant does not bank credit: when it goes backlogged it
    re-enters at the class virtual time instead of monopolizing the
    device to 'catch up'."""
    s = _stub_scheduler(max_batch_rows=4, max_wait_ms=0.0,
                        queue_capacity=4096)
    feats = _feats()
    s.add_tenant("busy", object(), feats.take(2), slo=SLO_STANDARD)
    s.add_tenant("idle", object(), feats.take(2), slo=SLO_STANDARD)
    for _ in range(20):
        s.submit("busy", feats.take(4))
    _drain(s)
    vclass = s._vclass[SLO_STANDARD]
    assert vclass > 0.0
    s.submit("idle", feats.take(4))
    assert s.tenant("idle").vft >= vclass
    _drain(s)


# -- shed order (priority shedding) ------------------------------------------

def test_shed_order_bulk_before_standard_before_interactive():
    """Under a monotone load ramp, bulk sheds strictly first, then
    standard, and interactive only when the queue is FULL."""
    s = _stub_scheduler(queue_capacity=10)   # limits: bulk 5, std 8, int 10
    feats = _feats()
    for name, slo in (("i", SLO_INTERACTIVE), ("s", SLO_STANDARD),
                      ("b", SLO_BULK)):
        s.add_tenant(name, object(), feats.take(2), slo=slo)
    assert s.admit_limits == {SLO_INTERACTIVE: 10, SLO_STANDARD: 8,
                              SLO_BULK: 5}
    # fill to the bulk threshold with interactive traffic
    for _ in range(5):
        s.submit("i", feats.take(1))
    with pytest.raises(ServingOverloadedError, match="bulk"):
        s.submit("b", feats.take(1))
    # standard still admits up to ITS threshold
    for _ in range(3):
        s.submit("s", feats.take(1))
    with pytest.raises(ServingOverloadedError, match="standard"):
        s.submit("s", feats.take(1))
    # interactive admits to full capacity, then sheds last
    for _ in range(2):
        s.submit("i", feats.take(1))
    with pytest.raises(ServingOverloadedError, match="interactive"):
        s.submit("i", feats.take(1))
    assert s.shed_counts() == {SLO_INTERACTIVE: 1, SLO_STANDARD: 1,
                               SLO_BULK: 1}
    _drain(s)


def test_shed_order_property_at_the_boundary():
    """Property check over seeded random submit interleavings: whenever
    a request of a class is shed, the queue depth was at (or above) the
    class threshold, an interactive shed implies a FULL queue — and in
    every run, the first interactive shed happens only after at least
    one bulk shed (bulk is 100% shed before interactive ever is)."""
    rng = np.random.default_rng(14)
    feats = _feats()
    for trial in range(8):
        s = _stub_scheduler(queue_capacity=int(rng.integers(4, 16)))
        tenants = {}
        for slo in SLO_CLASSES:
            s.add_tenant(slo, object(), feats.take(2), slo=slo)
            tenants[slo] = s.tenant(slo)
        shed_events = []
        for _ in range(200):
            slo = SLO_CLASSES[int(rng.integers(0, 3))]
            depth_before = s._depth
            if rng.random() < 0.25 and s._depth:
                formed = s._next_batch(timeout=0.0)
                if formed is not None:
                    s._dispatch(*formed)
                continue
            try:
                s.submit(slo, feats.take(1))
            except ServingOverloadedError:
                shed_events.append(slo)
                assert depth_before >= s.admit_limits[slo]
                if slo == SLO_INTERACTIVE:
                    assert depth_before >= s.queue_capacity
                    assert SLO_BULK in shed_events, (
                        "interactive shed before any bulk shed")
        _drain(s)


def test_admit_fractions_must_respect_priority_order():
    with pytest.raises(ValueError, match="non-increasing"):
        _stub_scheduler(queue_capacity=10,
                        admit_fractions={SLO_INTERACTIVE: 1.0,
                                         SLO_STANDARD: 0.5,
                                         SLO_BULK: 0.9})
    with pytest.raises(ValueError, match="admit fraction"):
        _stub_scheduler(queue_capacity=10,
                        admit_fractions={SLO_INTERACTIVE: 1.0,
                                         SLO_STANDARD: 0.5,
                                         SLO_BULK: 0.0})


def test_scheduler_health_degrades_on_shed_and_heals_after_drain():
    s = _stub_scheduler(queue_capacity=4)    # bulk limit: 2
    feats = _feats()
    s.add_tenant("b", object(), feats.take(2), slo=SLO_BULK)
    assert s.health == HEALTH_SERVING
    for _ in range(2):
        s.submit("b", feats.take(1))
    with pytest.raises(ServingOverloadedError):
        s.submit("b", feats.take(1))
    assert s.health == HEALTH_DEGRADED
    _drain(s)
    assert s.health == HEALTH_SERVING       # queue receded: healed


# -- dispatch priority + coalescing ------------------------------------------

def test_interactive_dispatches_before_bulk_backlog():
    s = _stub_scheduler(max_batch_rows=8, max_wait_ms=0.0,
                        queue_capacity=4096)
    feats = _feats()
    s.add_tenant("inter", object(), feats.take(2), slo=SLO_INTERACTIVE)
    s.add_tenant("bulk", object(), feats.take(2), slo=SLO_BULK)
    for _ in range(20):
        s.submit("bulk", feats.take(8))
    s.submit("inter", feats.take(1))
    serve_name, picked = s._next_batch(timeout=0.0)
    assert serve_name == "inter"
    assert [t.name for t, _ in picked] == ["inter"]
    s._dispatch(serve_name, picked)
    _drain(s)


def test_cross_tenant_coalescing_on_shared_servable():
    """Two tenants mapped to ONE servable (traffic multi-tenancy): their
    same-class requests coalesce into one batch, and each future
    resolves to exactly its own rows."""
    model = _fit_lr()
    feats = _feats(seed=3)
    registry = ModelRegistry()
    s = SharedScheduler(registry, max_batch_rows=64, max_wait_ms=5.0,
                        queue_capacity=1024)
    s.add_tenant("owner", model, feats.take(2), slo=SLO_STANDARD)
    s.add_tenant("guest", servable_of="owner", slo=SLO_STANDARD)
    reqs = [("owner", feats.slice(0, 3)), ("guest", feats.slice(3, 8)),
            ("owner", feats.slice(8, 9))]
    futures = [(name, req, s.submit(name, req)) for name, req in reqs]
    serve_name, picked = s._next_batch(timeout=0.0)
    assert serve_name == "owner"
    assert {t.name for t, _ in picked} == {"owner", "guest"}
    assert len(picked) == 3                  # ONE batch for all three
    s._dispatch(serve_name, picked)
    for name, req, future in futures:
        out = future.result(10)
        np.testing.assert_array_equal(
            out["rawPrediction"],
            model.transform(req)[0]["rawPrediction"])
    assert s.tenant("guest").admission_report is None
    assert s.tenant("guest").rows_served == 5


# -- compilation-free admission ----------------------------------------------

def test_second_tenant_of_served_schema_admits_with_zero_new_lowerings():
    """THE registry dividend (ISSUE 14 acceptance): tenant N+1 whose
    model shares an already-served schema warms entirely out of the
    shared jit cache — zero new XLA lowerings, and the admission report
    says so."""
    from jax._src import test_util as jtu

    feats = _feats(seed=7)
    s = SharedScheduler(max_batch_rows=64, max_wait_ms=0.5,
                        queue_capacity=1024)
    s.add_tenant("t1", _fit_lr(seed=1), feats.take(2),
                 slo=SLO_INTERACTIVE)
    s.start()
    try:
        # settle wave: lazy one-time work outside the warm-up ladder
        for n in (1, 2, 64):
            s.predict("t1", feats.take(n))
        model2 = _fit_lr(seed=2)     # the FIT is training-side work;
        ref2 = model2.transform(      # admission is what must be free
            feats.take(5))[0]["rawPrediction"]
        with jtu.count_jit_and_pmap_lowerings() as count:
            tenant = s.add_tenant("t2", model2, feats.take(2),
                                  slo=SLO_BULK)
            out = s.predict("t2", feats.take(5))
        assert count[0] == 0, (
            f"{count[0]} new lowerings admitting a same-schema tenant — "
            "the scheduler must be purely admission + placement")
        report = tenant.admission_report
        assert report is not None and report["compiled"] == 0
        assert report["aot_loaded"] + report["cache_hits"] \
            + sum(1 for b in report["buckets"].values()
                  if b["source"] == "untracked") == len(report["buckets"])
        np.testing.assert_array_equal(out["rawPrediction"], ref2)
    finally:
        s.close()


# -- publish chaos: tenant isolation -----------------------------------------

def test_delta_publish_to_one_tenant_leaves_others_untouched():
    """Continuous publishes to tenant A must not move tenant B: B's
    served bits stay bit-exact with B's (never-republished) model, B's
    generation gauge stays 1, and B's latency ring records exactly B's
    requests."""
    rng = np.random.default_rng(21)
    d = 8
    a1 = _lr_from_weights(rng.normal(size=d), 0.0)
    a2 = _lr_from_weights(rng.normal(size=d) + 2.0, -0.5)
    model_b = _lr_from_weights(rng.normal(size=d) - 1.0, 0.3)
    feats = Table({"features": rng.normal(size=(256, d))})
    s = SharedScheduler(max_batch_rows=64, max_wait_ms=0.5,
                        queue_capacity=8192)
    s.add_tenant("a", a1, feats.take(2), slo=SLO_STANDARD)
    s.add_tenant("b", model_b, feats.take(2), slo=SLO_STANDARD)
    s.start()

    ref_b = model_b.transform(feats)[0]["rawPrediction"]
    ref_a = {0: a1.transform(feats)[0]["rawPrediction"],
             1: a2.transform(feats)[0]["rawPrediction"]}
    stop = threading.Event()
    publishes = [0]
    errors = []

    def publisher():
        import time

        models = (a1, a2)
        try:
            while not stop.is_set():
                live = s.registry.current("a")
                nxt = models[(publishes[0] + 1) % 2]
                s.registry.publish_servable(
                    "a", live.servable.rebind(nxt),
                    metrics=s.tenant("a").metrics, mode="delta")
                publishes[0] += 1
                time.sleep(0.002)
        except Exception as exc:  # noqa: BLE001
            errors.append(exc)

    def client(name, refs, worker):
        crng = np.random.default_rng(worker)
        try:
            for _ in range(30):
                start = int(crng.integers(0, 200))
                rows = int(crng.integers(1, 6))
                req = feats.slice(start, start + rows)
                out = s.predict(name, req, timeout=30)
                raw = out["rawPrediction"]
                if isinstance(refs, dict):       # tenant a: any published gen
                    assert any(
                        np.array_equal(raw, r[start:start + rows])
                        for r in refs.values()), "mixed-generation response"
                else:
                    np.testing.assert_array_equal(
                        raw, refs[start:start + rows])
        except Exception as exc:  # noqa: BLE001
            errors.append(exc)

    try:
        pub = threading.Thread(target=publisher)
        clients = [threading.Thread(target=client,
                                    args=("b", ref_b, w)) for w in range(3)]
        clients += [threading.Thread(target=client,
                                     args=("a", ref_a, 10 + w))
                    for w in range(2)]
        pub.start()
        for t in clients:
            t.start()
        for t in clients:
            t.join(60)
        stop.set()
        pub.join(10)
        assert not errors, errors[:3]
        assert publishes[0] > 0
        b_metrics = s.tenant("b").metrics
        snap = b_metrics.group.snapshot()
        # B's generation never moved and its ring holds exactly B's
        # requests — A's publishes did not leak into B's accounting
        assert snap["model_generation"] == 1
        assert b_metrics.latency.count == snap["requests"] == 90
        assert snap["publishes_delta"] == 0 and snap["publishes_full"] == 0
        assert s.registry.generation("a") == publishes[0] + 1
    finally:
        stop.set()
        s.close()


# -- embedding-row cache -----------------------------------------------------

def _widedeep(seed=6, vocab=(50, 30), n=128):
    from flink_ml_tpu.models.recommendation.widedeep import WideDeep

    rng = np.random.default_rng(seed)
    dense = rng.normal(size=(n, 4)).astype(np.float32)
    cat = np.stack([rng.integers(0, v, size=n) for v in vocab],
                   axis=1).astype(np.int32)
    label = (cat[:, 0] > vocab[0] // 2).astype(np.int64)
    t = Table({"denseFeatures": dense, "catFeatures": cat, "label": label})
    return WideDeep().set_vocab_sizes(list(vocab)).set_max_iter(2).fit(t), t


def test_embcache_exact_under_eviction_churn():
    rng = np.random.default_rng(2)
    V, E = 80, 6
    emb = rng.normal(size=(V, E)).astype(np.float32)
    wc = rng.normal(size=(V,)).astype(np.float32)
    cache = EmbeddingRowCache({"emb": emb, "wide_cat": wc},
                              block_rows=8, capacity_blocks=4)
    for _ in range(100):
        ids = rng.integers(0, V, size=(int(rng.integers(1, 9)), 2))
        out = cache.lookup(ids)
        np.testing.assert_array_equal(np.asarray(out["emb"]), emb[ids])
        np.testing.assert_array_equal(np.asarray(out["wide_cat"]),
                                      wc[ids])
    snap = cache.snapshot()
    assert snap["hits"] > 0 and snap["misses"] > 0
    assert snap["resident_blocks"] <= snap["capacity_blocks"] == 4
    assert snap["evictions"] > 0


def test_embcache_lru_evicts_least_recently_touched():
    V, E = 32, 2
    emb = np.arange(V * E, dtype=np.float32).reshape(V, E)
    cache = EmbeddingRowCache({"emb": emb}, block_rows=8,
                              capacity_blocks=2)
    cache.lookup(np.array([0]))        # block 0
    cache.lookup(np.array([8]))        # block 1
    cache.lookup(np.array([1]))        # touch block 0 -> block 1 is LRU
    cache.lookup(np.array([16]))       # block 2 evicts block 1
    assert set(cache._slot_of) == {0, 2}
    assert cache.evictions == 1
    out = cache.lookup(np.array([9]))  # block 1 re-faults, still exact
    np.testing.assert_array_equal(np.asarray(out["emb"]), emb[[9]])
    assert cache.block_faults == 4


def test_embcache_bypasses_batches_larger_than_the_cache():
    """A batch whose working set exceeds the whole cache serves uncached
    (exact host rows), leaves the resident set untouched, and counts a
    bypass — never a wrong answer."""
    V, E = 64, 3
    rng = np.random.default_rng(3)
    emb = rng.normal(size=(V, E)).astype(np.float32)
    cache = EmbeddingRowCache({"emb": emb}, block_rows=8,
                              capacity_blocks=2)
    cache.lookup(np.array([0, 8]))     # two resident blocks
    resident = dict(cache._slot_of)
    ids = np.array([0, 8, 16, 24, 32])  # 5 unique blocks > capacity 2
    out = cache.lookup(ids)
    np.testing.assert_array_equal(np.asarray(out["emb"]), emb[ids])
    assert cache.bypasses == 1
    assert cache._slot_of == resident   # resident set untouched


def test_embcache_validation():
    with pytest.raises(ValueError, match="vocab dim"):
        EmbeddingRowCache({"a": np.zeros((4, 2)), "b": np.zeros((5,))})
    with pytest.raises(ValueError, match="block_rows"):
        EmbeddingRowCache({"a": np.zeros((4, 2))}, block_rows=0)
    cache = EmbeddingRowCache({"a": np.arange(10.0)}, block_rows=4,
                              capacity_blocks=99)
    assert cache.capacity_blocks == cache.n_blocks == 3   # capped
    with pytest.raises(ValueError, match="out of range"):
        cache.lookup(np.array([10]))
    with pytest.raises(ValueError, match="out of range"):
        cache.lookup(np.array([-1]))


def test_cached_widedeep_bitexact_with_offline_transform():
    model, t = _widedeep()
    feats = t.drop("label")
    servable = make_servable(model, feats.take(2), emb_cache=True,
                             cache_block_rows=8, cache_capacity_blocks=6,
                             max_batch_rows=64)
    servable.warm_up()
    for sz in (1, 7, 10, 33):
        req = feats.slice(0, sz)
        served = servable.predict(req)
        offline = model.transform(req)[0]
        for col in ("rawPrediction", "prediction"):
            np.testing.assert_array_equal(served[col], offline[col])
    snap = servable.cache.snapshot()
    assert snap["hits"] > 0 and snap["lookups"] > 0


def test_cached_widedeep_rebind_gets_fresh_cache():
    """A delta publish (rebind) must not serve the OLD generation's
    cached rows: the clone carries a fresh cache over the new tables
    and scores bit-exactly as the new model."""
    model, t = _widedeep(seed=8)
    feats = t.drop("label")
    servable = make_servable(model, feats.take(2), emb_cache=True,
                             cache_block_rows=8, cache_capacity_blocks=8,
                             max_batch_rows=64)
    servable.warm_up()
    servable.predict(feats.take(10))    # populate the old cache

    from flink_ml_tpu.models.recommendation.widedeep import WideDeepModel

    new_model = WideDeepModel()
    new_model._params = {
        **{k: model._params[k] for k in ("wide_dense", "wide_b", "mlp")},
        "emb": np.asarray(model._params["emb"]) * 2.0 + 1.0,
        "wide_cat": np.asarray(model._params["wide_cat"]) - 3.0,
    }
    new_model._vocab_sizes = model._vocab_sizes
    clone = servable.rebind(new_model)
    assert clone.ready and clone.cache is not servable.cache
    req = feats.take(10)
    np.testing.assert_array_equal(
        clone.predict(req)["rawPrediction"],
        new_model.transform(req)[0]["rawPrediction"])
    # the incumbent keeps serving the OLD params bit-exactly
    np.testing.assert_array_equal(
        servable.predict(req)["rawPrediction"],
        model.transform(req)[0]["rawPrediction"])


def test_embcache_rejects_non_widedeep():
    with pytest.raises(TypeError, match="WideDeepModel"):
        make_servable(_fit_lr(), _feats().take(1), emb_cache=True)


def test_cached_widedeep_zero_retraces_after_warmup():
    from jax._src import test_util as jtu

    model, t = _widedeep(seed=9)
    feats = t.drop("label")
    servable = make_servable(model, feats.take(2), emb_cache=True,
                             cache_block_rows=8,
                             cache_capacity_blocks=10, max_batch_rows=64)
    servable.warm_up()
    for n in (1, 2, 64):
        servable.predict(feats.take(n))         # settle wave
    with jtu.count_jit_and_pmap_lowerings() as count:
        for n in (1, 3, 7, 8, 11, 16, 33, 64):
            servable.predict(feats.take(n))
    assert count[0] == 0, (
        f"{count[0]} new lowerings in cached-WideDeep steady state — "
        "pool shapes must stay constant under residency churn")


# -- satellites: batcher fast path + shed generation stamping ----------------

class _PoisonedLock:
    """Context manager that fails the test if the fast path touches the
    queue lock."""

    def __init__(self):
        self.entered = 0

    def __enter__(self):
        self.entered += 1
        raise AssertionError("queue lock acquired on the shed fast path")

    def __exit__(self, *exc):
        return False


def test_microbatcher_fast_shed_never_touches_the_lock():
    batcher = MicroBatcher(max_batch_rows=8, queue_capacity=2)
    t = _feats()
    for _ in range(2):
        batcher.submit(t.take(1))
    batcher._cond = _PoisonedLock()             # saturation reached
    with pytest.raises(ServingOverloadedError, match="queue full"):
        batcher.submit(t.take(1))               # lock-free shed
    batcher.fast_shed = False                   # the bench A/B toggle
    with pytest.raises(AssertionError, match="fast path"):
        batcher.submit(t.take(1))               # legacy path locks


def test_scheduler_fast_shed_never_touches_the_lock():
    s = _stub_scheduler(queue_capacity=4)
    feats = _feats()
    s.add_tenant("b", object(), feats.take(2), slo=SLO_BULK)
    for _ in range(2):                          # bulk limit = 2
        s.submit("b", feats.take(1))
    s._cond = _PoisonedLock()
    with pytest.raises(ServingOverloadedError, match="shed"):
        s.submit("b", feats.take(1))


def test_endpoint_shed_stamps_live_generation():
    from flink_ml_tpu.obs.trace import tracer

    model = _fit_lr()
    feats = _feats(seed=8)
    registry = ModelRegistry()
    registry.deploy("m", model, feats.take(1), max_batch_rows=32)
    endpoint = ServingEndpoint(registry, "m", max_batch_rows=32,
                               queue_capacity=1)
    # endpoint NOT started: the queue fills and the next submit sheds
    endpoint.submit(feats.take(1))
    tracer.enable()
    try:
        with pytest.raises(ServingOverloadedError):
            endpoint.submit(feats.take(1))
    finally:
        tracer.disable()
    snap = endpoint.metrics.group.snapshot()
    assert snap["last_shed_generation"] == 1
    sheds = list(tracer.find("shed"))
    assert sheds and sheds[0].ids["generation"] == 1
    tracer.clear()
    endpoint.start()
    endpoint.close()


# -- observability wiring ----------------------------------------------------

def test_scheduler_spans_carry_tenant_correlation_key():
    from flink_ml_tpu.obs.trace import CORRELATION_KEYS, tracer

    assert "tenant" in CORRELATION_KEYS
    s = _stub_scheduler(max_batch_rows=8, max_wait_ms=0.0,
                        queue_capacity=64)
    feats = _feats()
    s.add_tenant("acme", object(), feats.take(2), slo=SLO_INTERACTIVE)
    tracer.enable()
    try:
        future = s.submit("acme", feats.take(2))
        formed = s._next_batch(timeout=0.0)
        s._dispatch(*formed)
        future.result(10)
        spans = {sp.name: sp for sp in tracer.spans()}
        assert spans["request"].ids["tenant"] == "acme"
        assert spans["queue_wait"].ids["tenant"] == "acme"
        assert spans["serve_batch"].ids["tenant"] == "acme"
    finally:
        tracer.disable()
        tracer.clear()


def test_default_tree_registers_scheduler_subtree():
    from flink_ml_tpu.obs.tree import default_tree, prometheus_text

    s = _stub_scheduler(queue_capacity=16)
    feats = _feats()
    s.add_tenant("t0", object(), feats.take(2), slo=SLO_INTERACTIVE)
    s.submit("t0", feats.take(1))
    _drain(s)
    snap = default_tree(scheduler=s).snapshot()
    assert snap["scheduler"]["batches"] == 1
    assert snap["scheduler"]["tenants.t0.requests"] == 1
    text = prometheus_text(snap)
    assert "flink_ml_tpu_scheduler_tenants_t0_requests 1" in text


def test_add_tenant_validation_and_lifecycle():
    s = _stub_scheduler(queue_capacity=16)
    feats = _feats()
    s.add_tenant("a", object(), feats.take(2))
    with pytest.raises(ValueError, match="already admitted"):
        s.add_tenant("a", object(), feats.take(2))
    with pytest.raises(ValueError, match="SLO class"):
        s.add_tenant("x", object(), feats.take(2), slo="gold")
    with pytest.raises(ValueError, match="weight"):
        s.add_tenant("x", object(), feats.take(2), weight=0.0)
    with pytest.raises(ValueError, match="servable_of"):
        s.add_tenant("x", object(), servable_of="a")
    with pytest.raises(KeyError, match="not an admitted tenant"):
        s.add_tenant("x", servable_of="ghost")
    with pytest.raises(ValueError, match="needs a model"):
        s.add_tenant("x")
    with pytest.raises(KeyError, match="unknown tenant"):
        s.submit("ghost", feats.take(1))
    with pytest.raises(ValueError, match="empty"):
        s.submit("a", feats.take(0))
    with pytest.raises(ValueError, match="split it client-side"):
        s.submit("a", feats.take(16).concat(
            _feats(n=512, seed=5).take(241)))
    s.start()
    with pytest.raises(RuntimeError, match="already started"):
        s.start()
    s.close()
    with pytest.raises(RuntimeError, match="closed"):
        s.submit("a", feats.take(1))


def test_dispatch_failure_fails_futures_and_loop_survives():
    """A batch the loop cannot serve (tenant undeployed mid-flight)
    delivers its failure TO the waiting futures — callers never hang —
    and the one shared loop keeps serving every other tenant."""
    s = _stub_scheduler(queue_capacity=16, max_wait_ms=0.0)
    feats = _feats()
    s.add_tenant("a", object(), feats.take(2))
    s.add_tenant("b", object(), feats.take(2))
    s.start()
    try:
        s.registry.undeploy("a")
        future = s.submit("a", feats.take(1))
        with pytest.raises(KeyError, match="no model deployed"):
            future.result(10)
        out = s.predict("b", feats.take(2), timeout=10)
        assert out.num_rows == 2
    finally:
        s.close()


def test_scheduler_end_to_end_under_concurrent_clients():
    """Smoke: the real serve thread, three tenants, concurrent clients,
    every response bit-exact with the tenant's own model."""
    models = {name: _fit_lr(seed=i)
              for i, name in enumerate(("red", "green", "blue"))}
    feats = _feats(seed=4)
    refs = {name: m.transform(feats)[0]["rawPrediction"]
            for name, m in models.items()}
    s = SharedScheduler(max_batch_rows=64, max_wait_ms=1.0,
                        queue_capacity=8192)
    for i, (name, model) in enumerate(models.items()):
        s.add_tenant(name, model, feats.take(2),
                     slo=SLO_CLASSES[i % 3], weight=1.0 + i)
    s.start()
    errors = []

    def client(name, worker):
        crng = np.random.default_rng(worker)
        try:
            for _ in range(25):
                start = int(crng.integers(0, 200))
                rows = int(crng.integers(1, 7))
                out = s.predict(name, feats.slice(start, start + rows),
                                timeout=30)
                np.testing.assert_array_equal(
                    out["rawPrediction"],
                    refs[name][start:start + rows])
        except Exception as exc:  # noqa: BLE001
            errors.append(exc)

    try:
        threads = [threading.Thread(target=client, args=(name, 7 * i + 1))
                   for i, name in enumerate(models)
                   for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        assert not errors, errors[:3]
        snap = s.snapshot()
        assert snap["requests"] == 150
        assert s.shed_counts() == {slo: 0 for slo in SLO_CLASSES}
    finally:
        s.close()
