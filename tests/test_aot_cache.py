"""AOT executable cache + registry autotuning (ISSUE 12).

Covers the acceptance contract end to end:

- cached-executable outputs bit-identical to a fresh compile of the
  same key (in-process A/B and cross-process via subprocess)
- a second process warming from a populated cache performs ZERO XLA
  lowerings for cached keys (lowering-counter asserted in a subprocess)
- the corruption sweep: truncated payload / flipped payload byte /
  stale-fingerprint meta / missing manifest all quarantine and fall
  back to a live compile — never a crash, never wrong bits — with the
  event accounted in ``kernel_stats``
- autotune winners are measured, persisted, and reloaded by a later
  process (fresh cache instance) without re-search; ``registry.lookup``
  honors a recorded backend decision
- serving warm-up reports readiness wall + per-bucket source, and the
  deploy path logs the one-line summary
"""

import json
import logging
import os
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

import jax.numpy as jnp

from flink_ml_tpu import Table
from flink_ml_tpu.kernels import aot, autotune
from flink_ml_tpu.kernels import registry as kreg
from flink_ml_tpu.kernels.registry import kernel_stats

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), os.pardir))


@pytest.fixture
def cache(tmp_path):
    c = aot.ExecutableCache(str(tmp_path / "aotcache"))
    aot.set_cache(c)
    try:
        yield c
    finally:
        aot.set_cache(None)


def _lr_plan(d=6, rows=16, seed=3):
    from flink_ml_tpu.models.common.linear import _linear_chain_kernel

    rng = np.random.default_rng(seed)
    plan = ((_linear_chain_kernel, ("f", "m")),)
    params = ({"w": jnp.asarray(rng.normal(size=(d,)).astype(np.float32)),
               "b": np.float32(0.25)},)
    cols = {"f": rng.normal(size=(rows, d)).astype(np.float32)}
    return plan, params, cols


def _dispatch(plan, params, cols):
    return np.asarray(kreg.dispatch(plan, params, dict(cols), op="aot_t")["m"])


# ---------------------------------------------------------------------------
# bit-exactness + cache-source accounting
# ---------------------------------------------------------------------------

def test_aot_roundtrip_bit_identical_and_accounted(cache):
    plan, params, cols = _lr_plan()
    snap0 = kernel_stats.snapshot()["aot"]

    out_compile = _dispatch(plan, params, cols)      # miss: compile + store
    snap1 = kernel_stats.snapshot()["aot"]
    assert snap1["misses"] == snap0["misses"] + 1
    assert snap1["stores"] == snap0["stores"] + 1
    assert snap1["compile_ms"] > snap0["compile_ms"]

    # a fresh cache instance over the same root = a restarted process:
    # the executable must come back from disk, not a compile
    aot.set_cache(aot.ExecutableCache(cache.root))
    out_loaded = _dispatch(plan, params, cols)
    snap2 = kernel_stats.snapshot()["aot"]
    assert snap2["hits"] == snap1["hits"] + 1
    assert snap2["misses"] == snap1["misses"]
    assert snap2["load_ms"] > snap1["load_ms"]

    # and the plain-jit path (cache disabled) agrees bit for bit
    aot.set_cache(None)
    out_jit = _dispatch(plan, params, cols)
    assert np.array_equal(out_compile, out_loaded)
    assert np.array_equal(out_compile, out_jit)

    # per-op ledger carries the split the satellite asks for
    rec = kernel_stats.snapshot()["per_op"]["aot_t"]
    assert rec["aot_hits"] >= 1 and rec["aot_misses"] >= 1
    assert rec["compile_ms"] > 0 and rec["aot_load_ms"] > 0


def test_memory_memo_skips_disk_after_first_load(cache):
    plan, params, cols = _lr_plan(seed=4)
    _dispatch(plan, params, cols)
    snap1 = kernel_stats.snapshot()["aot"]
    _dispatch(plan, params, cols)                    # steady state
    snap2 = kernel_stats.snapshot()["aot"]
    assert (snap2["hits"], snap2["misses"]) == (snap1["hits"],
                                                snap1["misses"])


# ---------------------------------------------------------------------------
# corruption sweep: quarantine + transparent recompile, never a crash
# ---------------------------------------------------------------------------

def _entry_dirs(cache):
    root = os.path.join(cache.root, "exec")
    return [os.path.join(root, n) for n in sorted(os.listdir(root))
            if ".corrupt" not in n and ".tmp." not in n]


def _corrupt_truncate(entry):
    path = os.path.join(entry, "executable.bin")
    with open(path, "r+b") as f:
        f.truncate(os.path.getsize(path) // 2)


def _corrupt_flip(entry):
    path = os.path.join(entry, "executable.bin")
    with open(path, "r+b") as f:
        f.seek(os.path.getsize(path) // 2)
        byte = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([byte[0] ^ 0xFF]))


def _corrupt_stale_fingerprint(entry):
    # a version-SKEWED entry whose CRCs are perfectly valid: meta claims
    # another jaxlib, manifest + marker re-committed over the edit
    from flink_ml_tpu.robustness.durability import (write_commit_marker,
                                                    write_manifest)

    meta_path = os.path.join(entry, "meta.json")
    with open(meta_path) as f:
        meta = json.load(f)
    meta["fingerprint"]["jaxlib"] = "0.0.0-stale"
    with open(meta_path, "w") as f:       # graftlint: disable=atomic-writes
        json.dump(meta, f)                # — test helper forging damage
    write_manifest(entry)
    write_commit_marker(entry)


def _corrupt_drop_manifest(entry):
    os.remove(os.path.join(entry, "manifest.json"))


@pytest.mark.parametrize("damage", [
    _corrupt_truncate, _corrupt_flip, _corrupt_stale_fingerprint,
    _corrupt_drop_manifest,
], ids=["truncated", "flipped-byte", "stale-jaxlib", "missing-manifest"])
def test_corruption_quarantines_and_recompiles(cache, damage):
    plan, params, cols = _lr_plan(seed=5)
    reference = _dispatch(plan, params, cols)
    entries = _entry_dirs(cache)
    assert len(entries) == 1
    damage(entries[0])

    aot.set_cache(aot.ExecutableCache(cache.root))   # restarted process
    before = kernel_stats.snapshot()["aot"]
    out = _dispatch(plan, params, cols)              # must NOT raise
    after = kernel_stats.snapshot()["aot"]

    assert np.array_equal(out, reference)            # never wrong bits
    assert after["quarantined"] == before["quarantined"] + 1
    assert after["misses"] == before["misses"] + 1   # transparent recompile
    corrupt = [n for n in os.listdir(os.path.join(cache.root, "exec"))
               if ".corrupt" in n]
    assert len(corrupt) == 1
    # the recompile re-stored a valid entry: the NEXT restart loads it
    aot.set_cache(aot.ExecutableCache(cache.root))
    assert np.array_equal(_dispatch(plan, params, cols), reference)
    assert kernel_stats.snapshot()["aot"]["hits"] == after["hits"] + 1


def test_uncommitted_tmp_entry_is_invisible(cache):
    """A crash mid-store (tmp dir never renamed) must read as a plain
    miss — the commit point is the os.replace, so no quarantine and no
    crash."""
    plan, params, cols = _lr_plan(seed=6)
    reference = _dispatch(plan, params, cols)
    entry = _entry_dirs(cache)[0]
    os.rename(entry, entry + ".tmp.999")             # un-commit it
    aot.set_cache(aot.ExecutableCache(cache.root))
    before = kernel_stats.snapshot()["aot"]
    assert np.array_equal(_dispatch(plan, params, cols), reference)
    after = kernel_stats.snapshot()["aot"]
    assert after["quarantined"] == before["quarantined"]
    assert after["misses"] == before["misses"] + 1


def test_store_failure_degrades_to_in_process_serving(cache, monkeypatch):
    """A broken cache VOLUME (ENOSPC, permissions) must never take down
    dispatch: the freshly-compiled executable serves in-process and the
    failure is accounted, not raised."""
    from flink_ml_tpu.robustness import durability

    def broken_commit(dirpath, **kw):
        raise OSError(28, "No space left on device")

    monkeypatch.setattr(durability, "commit_dir", broken_commit)
    plan, params, cols = _lr_plan(seed=8)
    before = kernel_stats.snapshot()["aot"]
    out = _dispatch(plan, params, cols)              # must NOT raise
    after = kernel_stats.snapshot()["aot"]
    assert out.shape == (16,)
    assert after["store_failed"] == before["store_failed"] + 1
    assert after["stores"] == before["stores"]
    # steady state keeps serving from the in-process copy
    assert np.array_equal(_dispatch(plan, params, cols), out)


def test_foreign_device_decision_is_skipped_not_quarantined(cache):
    """A valid decision recorded by another backend/chip sharing the
    fleet cache root is not ours to use — and not ours to destroy."""
    cache.record_decision({
        "format": 1, "op": "aot_foreign_op", "sig": "()",
        "kind": "backend", "choice": "x", "timings_ms": {},
        "search_ms": 1.0, "probe": "",
        "device": {"backend": "notthisbackend", "device_kind": "mythical"},
    })
    aot.set_cache(aot.ExecutableCache(cache.root))   # fresh scan
    assert autotune.get_decision("aot_foreign_op", ()) is None
    tune_root = os.path.join(cache.root, "autotune")
    assert not any(".corrupt" in n for n in os.listdir(tune_root))
    assert len(os.listdir(tune_root)) == 1           # entry survived


def test_code_fingerprint_is_transitive_over_helpers():
    """Editing a helper a kernel reaches by global name (directly or
    through a dispatch-table dict) must change the kernel's fingerprint
    — a restarted process must never load an executable built from the
    old helper."""
    from flink_ml_tpu.kernels.aot import _code_fingerprint

    src = "def top(x):\n    return helper(x)\n"
    src_tab = "def top(x):\n    return table['a'](x)\n"

    def make(source, **globs):
        g = dict(globs)
        exec(source, g)
        return g["top"]

    h1 = lambda x: x + 1      # noqa: E731
    h2 = lambda x: x + 2      # noqa: E731 — same co_code, different const
    assert _code_fingerprint(make(src, helper=h1)) \
        == _code_fingerprint(make(src, helper=h1))
    assert _code_fingerprint(make(src, helper=h1)) \
        != _code_fingerprint(make(src, helper=h2))
    assert _code_fingerprint(make(src_tab, table={"a": h1})) \
        != _code_fingerprint(make(src_tab, table={"a": h2}))


# ---------------------------------------------------------------------------
# cross-process: zero lowerings from a populated cache
# ---------------------------------------------------------------------------

_CHILD = textwrap.dedent("""\
    import json, os, sys
    import numpy as np
    from jax._src import test_util as jtu
    from flink_ml_tpu import Table
    from flink_ml_tpu.models.classification.logisticregression import (
        LogisticRegressionModel)
    from flink_ml_tpu.serving.executor import make_servable
    from flink_ml_tpu.kernels.registry import kernel_stats

    rng = np.random.default_rng(11)
    model = LogisticRegressionModel()
    model.set_model_data(Table({
        "coefficients": rng.normal(size=(1, 12)),
        "intercept": np.array([0.4])}))
    feats = Table({"features": rng.normal(size=(64, 12))
                   .astype(np.float32)})
    servable = make_servable(model, feats.take(2), max_batch_rows=32)
    with jtu.count_jit_and_pmap_lowerings() as count:
        servable.warm_up()
        out = servable.predict(feats.take(5))
    print(json.dumps({
        "lowerings": count[0],
        "aot": kernel_stats.snapshot()["aot"],
        "warmup": servable.warmup_report,
        "out": {n: np.asarray(out[n]).tolist()
                for n in sorted(out.column_names)},
    }))
""")


def _run_child(script_path, cache_root):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["FLINK_ML_TPU_AOT_CACHE_PATH"] = cache_root
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run([sys.executable, script_path], env=env,
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]
    return json.loads(proc.stdout.strip().splitlines()[-1])


def test_second_process_warms_with_zero_compiles(cache, tmp_path):
    script = tmp_path / "child.py"
    script.write_text(_CHILD)

    cold = _run_child(str(script), cache.root)
    warm = _run_child(str(script), cache.root)

    # cold process compiled and stored; the warm one must not lower a
    # single program for the cached keys — the acceptance criterion
    assert cold["lowerings"] > 0
    assert cold["aot"]["misses"] > 0 and cold["aot"]["stores"] > 0
    assert warm["lowerings"] == 0, (
        f"warm process lowered {warm['lowerings']} programs — the "
        "executable cache did not cover its warm-up")
    assert warm["aot"]["hits"] >= len(warm["warmup"]["buckets"])
    assert warm["aot"]["misses"] == 0

    # served bits are identical across the two processes
    assert cold["out"] == warm["out"]

    # the warm-up report attributes every bucket to the cache
    assert all(b["source"] == "aot"
               for b in warm["warmup"]["buckets"].values())
    assert all(b["source"] == "compile"
               for b in cold["warmup"]["buckets"].values())
    # and the measured number the bench leg headlines: warm-up wall
    # collapses when compiles become deserializes
    assert warm["warmup"]["wall_s"] < cold["warmup"]["wall_s"]


# ---------------------------------------------------------------------------
# autotune: measured, persisted, reloaded without re-search
# ---------------------------------------------------------------------------

def test_autotune_winner_persistence_roundtrip(cache):
    calls = {"slow": 0, "fast": 0}

    def mk(name, delay):
        def thunk():
            calls[name] += 1
            time.sleep(delay)
            return np.zeros(1)
        return thunk

    choice, decision = autotune.choose(
        "aot_test_op", (16, 4),
        {"slow": mk("slow", 0.003), "fast": mk("fast", 0.0)})
    assert choice == "fast" and decision["search_ms"] > 0
    assert calls["slow"] > 0 and calls["fast"] > 0
    key = "aot_test_op|(16, 4)"
    assert kernel_stats.tuned_ops[key]["source"] == "measured"

    # a later process (fresh cache instance): recorded winner, no search
    aot.set_cache(aot.ExecutableCache(cache.root))
    calls["slow"] = calls["fast"] = 0
    choice2, decision2 = autotune.choose(
        "aot_test_op", (16, 4),
        {"slow": mk("slow", 0.003), "fast": mk("fast", 0.0)})
    assert choice2 == "fast"
    assert calls == {"slow": 0, "fast": 0}           # zero re-search
    assert kernel_stats.tuned_ops[key]["source"] == "cache"


def test_autotune_disabled_measures_but_does_not_persist(tmp_path):
    aot.set_cache(None)
    try:
        assert not autotune.enabled()
        choice, dec = autotune.choose(
            "aot_nopersist_op", (),
            {"a": lambda: np.zeros(1),
             "b": lambda: (time.sleep(0.003), np.zeros(1))[1]})
        assert choice == "a" and dec["device"] is None
    finally:
        aot.set_cache(None)


def test_corrupt_decision_quarantines_and_researches(cache):
    autotune.choose("aot_decay_op", (),
                    {"x": lambda: np.zeros(1), "y": lambda: np.zeros(1)})
    tune_root = os.path.join(cache.root, "autotune")
    entry = [os.path.join(tune_root, n) for n in os.listdir(tune_root)][0]
    os.remove(os.path.join(entry, "manifest.json"))
    aot.set_cache(aot.ExecutableCache(cache.root))
    assert autotune.get_decision("aot_decay_op", ()) is None
    assert any(".corrupt" in n for n in os.listdir(tune_root))


def test_lookup_honors_tuned_backend(cache):
    kreg.register_kernel("aot_lookup_op", "alpha", lambda: None,
                         priority=10)
    kreg.register_kernel("aot_lookup_op", "beta", lambda: None,
                         priority=0)
    try:
        assert kreg.lookup("aot_lookup_op").backend == "alpha"
        choice, _ = autotune.choose(
            "aot_lookup_op", (),
            {"alpha": lambda: (time.sleep(0.003), np.zeros(1))[1],
             "beta": lambda: np.zeros(1)})
        assert choice == "beta"
        # the measured winner beats static priority, here and in every
        # later process that shares the cache root
        assert kreg.lookup("aot_lookup_op").backend == "beta"
        aot.set_cache(aot.ExecutableCache(cache.root))
        assert kreg.lookup("aot_lookup_op").backend == "beta"
        # forced lookups stay forced
        assert kreg.lookup("aot_lookup_op",
                           backend="alpha").backend == "alpha"
    finally:
        with kreg._REG_LOCK:
            kreg._REGISTRY.pop("aot_lookup_op", None)


def test_kmeans_block_pick_measured_and_persisted(cache):
    from flink_ml_tpu.ops import kmeans_pallas as kp

    bn = kp.pick_block_n_measured(8, 4, interpret=True,
                                  candidates=[128, 256])
    assert bn in (128, 256)
    key = "kmeans_update_stats|('block_n', 8, 4)"
    assert kernel_stats.tuned_ops[key]["source"] == "measured"
    assert set(kernel_stats.tuned_ops[key]["timings_ms"]) == \
        {"128", "256"}

    aot.set_cache(aot.ExecutableCache(cache.root))   # later process
    bn2 = kp.pick_block_n_measured(8, 4, interpret=True,
                                   candidates=[128, 256])
    assert bn2 == bn
    assert kernel_stats.tuned_ops[key]["source"] == "cache"


def test_kmeans_block_pick_analytic_when_disabled():
    from flink_ml_tpu.ops import kmeans_pallas as kp

    aot.set_cache(None)
    try:
        assert kp.pick_block_n_measured(64, 256) == \
            kp.pick_block_n(None, 64, 256)
        assert kp.pick_block_n_workset_measured(64, 256) == \
            kp.pick_block_n_workset(None, 64, 256)
    finally:
        aot.set_cache(None)


# ---------------------------------------------------------------------------
# aot_jit: the training step builders' pre-warm path (GBT)
# ---------------------------------------------------------------------------

def _gbt_fixture():
    from flink_ml_tpu.models.common.gbt import GBTConfig

    rng = np.random.default_rng(23)
    X = rng.normal(size=(512, 6)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)

    def grad_hess(y, pred):
        p = 1.0 / (1.0 + np.exp(-pred))
        return (p - y), np.maximum(p * (1.0 - p), 1e-16)

    cfg = GBTConfig(num_trees=2, max_depth=3, max_bins=16,
                    learning_rate=0.3)
    return X, y, grad_hess, cfg


def test_gbt_train_forest_through_aot_cache(cache):
    from flink_ml_tpu.models.common.gbt import train_forest
    from jax._src import test_util as jtu

    X, y, grad_hess, cfg = _gbt_fixture()
    aot.set_cache(None)
    baseline = train_forest(X, y, grad_hess, 0.0, cfg)

    aot.set_cache(cache)
    first = train_forest(X, y, grad_hess, 0.0, cfg)   # compile + store

    aot.set_cache(aot.ExecutableCache(cache.root))    # restarted process
    with jtu.count_jit_and_pmap_lowerings() as count:
        second = train_forest(X, y, grad_hess, 0.0, cfg)
    assert count[0] == 0, (
        f"{count[0]} lowerings on the warm-cache GBT run — the aot_jit "
        "wrapper did not cover the training step builders")

    for a, b in ((baseline, first), (baseline, second)):
        assert np.array_equal(a.feature, b.feature)
        assert np.array_equal(a.threshold, b.threshold)
        assert np.array_equal(a.value, b.value)


def test_aot_jit_falls_back_under_tracing(cache):
    """aot_jit-wrapped fns called with tracers (inside an enclosing jit
    or scan, e.g. the out-of-core chunk paths) must inline as plain
    nested jits — an executable cannot run mid-trace."""
    import jax

    from flink_ml_tpu.kernels.aot import aot_jit

    @aot_jit
    def double(x):
        return x * 2

    @jax.jit
    def outer(x):
        return double(x) + 1

    x = jnp.arange(4, dtype=jnp.float32)
    assert np.array_equal(np.asarray(outer(x)),
                          np.asarray(x) * 2 + 1)
    assert np.array_equal(np.asarray(double(x)), np.asarray(x) * 2)


# ---------------------------------------------------------------------------
# serving warm-up readiness report + deploy summary
# ---------------------------------------------------------------------------

def _lr_model(d=8, seed=7):
    from flink_ml_tpu.models.classification.logisticregression import (
        LogisticRegressionModel)

    rng = np.random.default_rng(seed)
    model = LogisticRegressionModel()
    model.set_model_data(Table({
        "coefficients": rng.normal(size=(1, d)),
        "intercept": np.array([0.1])}))
    feats = Table({"features": rng.normal(size=(32, d))
                   .astype(np.float32)})
    return model, feats


def test_warmup_report_and_deploy_summary(cache, caplog):
    from flink_ml_tpu.serving import ModelRegistry, ServingEndpoint

    model, feats = _lr_model()
    registry = ModelRegistry()
    with caplog.at_level(logging.INFO, logger="flink_ml_tpu.robustness"):
        dep = registry.deploy("m", model, feats.take(1),
                              max_batch_rows=32)
    rep = dep.servable.warmup_report
    assert rep["wall_s"] > 0
    assert set(rep["buckets"]) == set(dep.servable.buckets)
    assert rep["compiled"] == len(dep.servable.buckets)
    assert any("warm-up of 'm'" in r.message and "compiled" in r.message
               for r in caplog.records)

    # a redeploy of the same generation: every bucket rides the compile
    # cache (or the aot loads) — zero fresh compiles, says the report
    dep2 = registry.deploy("m", model)
    rep2 = dep2.servable.warmup_report
    assert rep2["compiled"] == 0
    assert all(b["source"] in ("cache", "aot")
               for b in rep2["buckets"].values())

    endpoint = ServingEndpoint(registry, "m")
    assert endpoint.warmup_report == rep2


def test_warmup_report_without_cache():
    """The report (and the deploy summary) must not depend on the AOT
    cache being configured — sources just never say 'aot'."""
    from flink_ml_tpu.serving import ModelRegistry

    aot.set_cache(None)
    try:
        model, feats = _lr_model(seed=9)
        dep = ModelRegistry().deploy("m", model, feats.take(1),
                                     max_batch_rows=16)
        rep = dep.servable.warmup_report
        assert rep["wall_s"] > 0 and len(rep["buckets"]) > 0
        assert all(b["source"] != "aot"
                   for b in rep["buckets"].values())
    finally:
        aot.set_cache(None)


# ---------------------------------------------------------------------------
# stats surface: the kernels.* re-export carries the new gauges
# ---------------------------------------------------------------------------

def test_thread_counts_isolated_from_other_threads():
    """Warm-up source attribution diffs the deploy thread's OWN
    counters: dispatches recorded by a concurrently-serving thread (the
    hot-swap shape) must not move this thread's view."""
    import threading

    base = kernel_stats.thread_counts()
    t = threading.Thread(target=lambda: kernel_stats.record(
        "other_thread_op", compiled=True, seconds=0.0))
    t.start()
    t.join()
    assert kernel_stats.thread_counts() == base
    kernel_stats.record("this_thread_op", compiled=False, seconds=0.0)
    assert kernel_stats.thread_counts()[2] == base[2] + 1


def test_kernel_stats_publish_carries_aot_and_tuning_gauges():
    from flink_ml_tpu.utils.metrics import MetricGroup

    group = MetricGroup("t_aot")
    kernel_stats.publish(group)
    snap = group.snapshot()
    for gauge in ("aot_hits", "aot_misses", "aot_quarantined",
                  "aot_load_ms", "aot_compile_ms", "tuned_ops"):
        assert any(k.endswith(gauge) for k in snap), (gauge, snap.keys())
