"""ALS matrix factorization tests (CPU mesh; fused iterate path)."""

import numpy as np
import pytest

from flink_ml_tpu import Table
from flink_ml_tpu.models.recommendation import ALS, ALSModel


def _synthetic(n_users=40, n_items=30, rank=4, density=0.5, seed=0,
               noise=0.0):
    rng = np.random.default_rng(seed)
    U = rng.normal(size=(n_users, rank)) / np.sqrt(rank)
    V = rng.normal(size=(n_items, rank)) / np.sqrt(rank)
    full = U @ V.T
    mask = rng.random((n_users, n_items)) < density
    u, i = np.nonzero(mask)
    r = full[u, i] + noise * rng.normal(size=len(u))
    return Table({"user": u.astype(np.int64), "item": i.astype(np.int64),
                  "rating": r.astype(np.float64)}), full


def test_param_defaults():
    als = ALS()
    assert als.get_rank() == 10
    assert als.get_reg_param() == pytest.approx(0.1)
    assert not als.get_implicit_prefs()
    assert als.get_user_col() == "user"
    assert als.get_rating_col() == "rating"


def test_explicit_recovers_low_rank_matrix():
    table, full = _synthetic()
    # rank 6 > true rank 4: at the exact rank ALS can stall in an
    # init-dependent local minimum (rmse ~0.06 for some seeds); mild
    # overparameterization makes recovery seed-robust (verified over seeds).
    als = (ALS().set_rank(6).set_max_iter(20).set_reg_param(1e-3)
           .set_seed(1))
    model = als.fit(table)
    out = model.transform(table)[0]
    pred = np.asarray(out["prediction"])
    rmse = np.sqrt(np.mean((pred - np.asarray(table["rating"])) ** 2))
    assert rmse < 0.02, rmse
    # held-out entries of the low-rank matrix are recovered too
    uh, ih = np.meshgrid(np.arange(full.shape[0]), np.arange(full.shape[1]),
                         indexing="ij")
    held = model.transform(Table({
        "user": uh.ravel().astype(np.int64),
        "item": ih.ravel().astype(np.int64)}))[0]
    rmse_all = np.sqrt(np.nanmean(
        (np.asarray(held["prediction"]).reshape(full.shape) - full) ** 2))
    assert rmse_all < 0.15, rmse_all


def test_rmse_decreases_with_iterations():
    table, _ = _synthetic(noise=0.01, seed=3)
    truth = np.asarray(table["rating"])

    def rmse_after(iters):
        m = (ALS().set_rank(4).set_max_iter(iters).set_reg_param(0.01)
             .set_seed(2).fit(table))
        p = np.asarray(m.transform(table)[0]["prediction"])
        return np.sqrt(np.mean((p - truth) ** 2))

    assert rmse_after(10) < rmse_after(1)


def test_implicit_prefs_ranks_observed_above_unobserved():
    rng = np.random.default_rng(7)
    n_users, n_items = 30, 20
    # two taste groups: users prefer even or odd items
    u, i, r = [], [], []
    for user in range(n_users):
        group = user % 2
        for item in range(group, n_items, 2):
            if rng.random() < 0.7:
                u.append(user); i.append(item); r.append(1.0 + rng.random())
    table = Table({"user": np.asarray(u, np.int64),
                   "item": np.asarray(i, np.int64),
                   "rating": np.asarray(r, np.float64)})
    model = (ALS().set_implicit_prefs(True).set_alpha(10.0).set_rank(4)
             .set_reg_param(0.05).set_max_iter(10).set_seed(0).fit(table))
    users = np.repeat(np.arange(n_users, dtype=np.int64), n_items)
    items = np.tile(np.arange(n_items, dtype=np.int64), n_users)
    scores = np.asarray(model.transform(Table({
        "user": users, "item": items}))[0]["prediction"])
    scores = scores.reshape(n_users, n_items)
    same = np.array([[1.0 if (it % 2) == (us % 2) else 0.0
                      for it in range(n_items)] for us in range(n_users)])
    # mean score for in-group items must clearly beat out-of-group
    assert (scores * same).sum() / same.sum() > \
        (scores * (1 - same)).sum() / (1 - same).sum() + 0.2


def test_cold_start_predicts_nan():
    table, _ = _synthetic()
    model = ALS().set_rank(3).set_max_iter(3).fit(table)
    out = model.transform(Table({
        "user": np.asarray([0, 10**6], np.int64),
        "item": np.asarray([0, 0], np.int64)}))[0]
    pred = np.asarray(out["prediction"])
    assert np.isfinite(pred[0])
    assert np.isnan(pred[1])


def test_save_load_round_trip(tmp_path):
    table, _ = _synthetic(n_users=12, n_items=9)
    model = ALS().set_rank(3).set_max_iter(5).set_seed(4).fit(table)
    p1 = np.asarray(model.transform(table)[0]["prediction"])
    model.save(str(tmp_path / "m"))
    re = ALSModel.load(str(tmp_path / "m"))
    p2 = np.asarray(re.transform(table)[0]["prediction"])
    np.testing.assert_allclose(p1, p2)
    assert re.get_prediction_col() == model.get_prediction_col()


def test_estimator_save_load_round_trip(tmp_path):
    als = ALS().set_rank(7).set_implicit_prefs(True).set_alpha(2.5)
    als.save(str(tmp_path / "e"))
    re = ALS.load(str(tmp_path / "e"))
    assert re.get_rank() == 7
    assert re.get_implicit_prefs()
    assert re.get_alpha() == pytest.approx(2.5)


def test_negative_ratings_rejected_for_implicit():
    table = Table({"user": np.asarray([0], np.int64),
                   "item": np.asarray([0], np.int64),
                   "rating": np.asarray([-1.0])})
    with pytest.raises(ValueError):
        ALS().set_implicit_prefs(True).fit(table)


def test_unobserved_users_keep_factors_finite():
    # user ids with gaps: all factor rows must stay finite (singular normal
    # equations guarded)
    table = Table({"user": np.asarray([0, 0, 5, 5], np.int64),
                   "item": np.asarray([0, 1, 0, 1], np.int64),
                   "rating": np.asarray([1.0, 2.0, 3.0, 4.0])})
    model = ALS().set_rank(2).set_max_iter(4).fit(table)
    data = model.get_model_data()[0]
    assert np.isfinite(np.asarray(data["userFactors"][0])).all()
    assert np.isfinite(np.asarray(data["itemFactors"][0])).all()


def test_zero_reg_singular_solve_keeps_finite_factors():
    # regParam=0 with fewer ratings than rank: the singular solve must not
    # poison the factors with NaN (regression).
    table = Table({"user": np.asarray([0, 0, 1], np.int64),
                   "item": np.asarray([0, 1, 0], np.int64),
                   "rating": np.asarray([1.0, 2.0, 3.0])})
    model = ALS().set_rank(4).set_reg_param(0.0).set_max_iter(3).fit(table)
    pred = np.asarray(model.transform(table)[0]["prediction"])
    assert np.isfinite(pred).all()


def test_empty_ratings_rejected():
    table = Table({"user": np.asarray([], np.int64),
                   "item": np.asarray([], np.int64),
                   "rating": np.asarray([], np.float64)})
    with pytest.raises(ValueError, match="at least one rating"):
        ALS().fit(table)


def test_implicit_fractional_weights_consistent():
    # The implicit normal equations must weight A and b consistently:
    # duplicating a rating must equal doubling its weight.
    import jax.numpy as jnp

    from flink_ml_tpu.models.recommendation.als import _solve_side

    rng = np.random.default_rng(0)
    V = jnp.asarray(rng.normal(size=(3, 2)).astype(np.float32))
    prev = jnp.asarray(np.zeros((2, 2), np.float32))
    u = jnp.asarray([0, 0, 1], jnp.int32)
    i = jnp.asarray([0, 1, 2], jnp.int32)
    r = jnp.asarray([1.0, 2.0, 1.5], jnp.float32)
    dup = _solve_side(prev, V, jnp.concatenate([u, u[:1]]),
                      jnp.concatenate([i, i[:1]]),
                      jnp.concatenate([r, r[:1]]),
                      jnp.ones(4, jnp.float32), 2, 0.1, True, 2.0)
    wt = _solve_side(prev, V, u, i, r,
                     jnp.asarray([2.0, 1.0, 1.0], jnp.float32), 2, 0.1,
                     True, 2.0)
    np.testing.assert_allclose(np.asarray(dup), np.asarray(wt), atol=1e-5)


def test_recommend_for_users_topk_and_exclude():
    """recommend_for_users: matmul top-k, train-pair exclusion, and the
    RankingEvaluator-consumable output shape."""
    users = np.repeat(np.arange(8), 5)
    items = np.tile(np.arange(5), 8)
    # user u loves item u % 5 (rating 5), others 1
    ratings = np.where(items == (users % 5), 5.0, 1.0)
    t = Table({"user": users, "item": items, "rating": ratings})
    model = (ALS().set_rank(4).set_max_iter(10).set_reg_param(0.05)
             .fit(t))

    recs = model.recommend_for_users(np.arange(8), k=2)
    assert recs.num_rows == 8
    for u in range(8):
        top = recs["recommendations"][u]
        assert len(top) == 2
        # rank-4 factorization is approximate: the loved item must at
        # least make the top 2, and scores come back ranked
        assert (u % 5) in top
        scores = recs["scores"][u]
        assert scores[0] >= scores[1]

    # every user rated ALL 5 items, so excluding the training
    # interactions leaves nothing to recommend: lists come back EMPTY
    # (excluded items are removed, never padded back in)
    excl = model.recommend_for_users(np.arange(8), k=5, exclude=t)
    for u in range(8):
        assert excl["recommendations"][u] == []
        assert excl["scores"][u] == []

    # partial exclusion: drop only item (u % 5); it must vanish from the
    # list while the rest stay ranked
    part = model.recommend_for_users(
        np.arange(8), k=5,
        exclude=Table({"user": np.arange(8), "item": np.arange(8) % 5}))
    for u in range(8):
        got = part["recommendations"][u]
        assert len(got) == 4 and (u % 5) not in got
        s = part["scores"][u]
        assert all(s[i] >= s[i + 1] for i in range(len(s) - 1))

    with pytest.raises(ValueError, match="unknown user"):
        model.recommend_for_users([999], k=1)
    with pytest.raises(ValueError, match="positive"):
        model.recommend_for_users([0], k=0)


def test_sorted_normal_equations_match_scatter():
    """The sorted MXU normal equations must equal the scatter-add form
    (f32 summation order aside) for explicit AND implicit modes,
    including heavy groups whose runs cross chunk boundaries and
    zero-weight (padding) ratings."""
    import jax.numpy as jnp

    from flink_ml_tpu.models.recommendation.als import (
        NeqPlan, _normal_equations, _normal_equations_sorted)

    rng = np.random.default_rng(41)
    n_groups, n_other, nnz, rank = 12, 9, 700, 5
    g = rng.integers(0, n_groups, size=nnz)
    g[:300] = 3                      # heavy group spanning chunks
    o = rng.integers(0, n_other, size=nnz).astype(np.int32)
    r = rng.normal(size=nnz).astype(np.float32)
    w = np.where(rng.random(nnz) < 0.1, 0.0, 1.0).astype(np.float32)
    factors = rng.normal(size=(n_other, rank)).astype(np.float32)

    for implicit in (False, True):
        rr = np.abs(r) if implicit else r
        A0, b0, c0 = _normal_equations(
            jnp.asarray(factors), jnp.asarray(g, jnp.int32),
            jnp.asarray(o), jnp.asarray(rr), jnp.asarray(w),
            n_groups, implicit, 0.7)
        plan = NeqPlan(g, chunk=128)   # force many chunk crossings
        A1, b1, c1 = _normal_equations_sorted(
            jnp.asarray(factors),
            jnp.asarray(plan.sort_pad(o)),
            jnp.asarray(plan.sort_pad(rr)),
            jnp.asarray(plan.sort_pad(w)),
            jnp.asarray(plan.local_rank), jnp.asarray(plan.g_lo),
            n_groups, plan.span, plan.chunk, implicit, 0.7)
        np.testing.assert_allclose(np.asarray(A1), np.asarray(A0),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(np.asarray(b1), np.asarray(b0),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(np.asarray(c1), np.asarray(c0),
                                   rtol=1e-5, atol=1e-5)


def test_sorted_fit_matches_scatter_fit():
    """End-to-end: the fit() default (sorted) reproduces the scatter
    fit's factors allclose, explicit and implicit."""
    rng = np.random.default_rng(42)
    n = 1500
    users = rng.integers(0, 40, n).astype(np.int64)
    items = rng.integers(0, 25, n).astype(np.int64)
    ratings = (np.sin(users * 0.3) + np.cos(items * 0.5)
               + 0.05 * rng.normal(size=n)).astype(np.float32)
    t = Table({"user": users, "item": items, "rating": ratings})

    for implicit in (False, True):
        r_col = np.abs(ratings) if implicit else ratings
        ti = Table({"user": users, "item": items, "rating": r_col})

        def fit(impl):
            est = (ALS().set_user_col("user").set_item_col("item")
                   .set_rating_col("rating").set_rank(6).set_max_iter(4)
                   .set_seed(0).set_implicit_prefs(implicit)
                   .set(ALS.NEQ_IMPL, impl))
            return est.fit(ti if implicit else t)

        m_sorted, m_scatter = fit("sorted"), fit("scatter")
        for a, b in zip(m_sorted.get_model_data(),
                        m_scatter.get_model_data()):
            np.testing.assert_allclose(
                np.asarray(a["userFactors"]), np.asarray(b["userFactors"]),
                rtol=5e-3, atol=5e-3)


def test_auto_falls_back_to_scatter_on_long_tail():
    """'auto' must not pick the sorted path when the per-chunk group
    band degenerates (long-tail data: most groups have 1-2 ratings) —
    span is host-known at plan time, so the fallback is free."""
    from flink_ml_tpu.models.recommendation import als as als_mod

    rng = np.random.default_rng(43)
    n = 600
    users = np.arange(n).astype(np.int64)       # every user one rating
    items = rng.integers(0, 20, n).astype(np.int64)
    ratings = rng.normal(size=n).astype(np.float32)
    t = Table({"user": users, "item": items, "rating": ratings})

    # span_u == chunk-wide band here; force a tiny cap to trigger
    old = als_mod._NEQ_AUTO_SPAN_CAP
    als_mod._NEQ_AUTO_SPAN_CAP = 8
    try:
        model = (ALS().set_user_col("user").set_item_col("item")
                 .set_rating_col("rating").set_rank(4).set_max_iter(2)
                 .set_seed(0).fit(t))
    finally:
        als_mod._NEQ_AUTO_SPAN_CAP = old
    assert model.get_model_data()  # fit completed on the scatter path


def test_neq_plan_span_matches_full_plan():
    """The bincount-based span bound 'auto' consults BEFORE building a
    NeqPlan must equal the plan's own span exactly — it is the same
    sorted-sequence arithmetic without the O(nnz log nnz) argsort."""
    from flink_ml_tpu.models.recommendation.als import (NeqPlan,
                                                        _neq_plan_span)

    rng = np.random.default_rng(0)
    cases = []
    for _ in range(8):
        n_groups = int(rng.integers(1, 500))
        nnz = int(rng.integers(1, 5000))
        cases.append(rng.integers(0, n_groups, nnz))       # uniform
        cases.append((rng.pareto(0.5, nnz) * 10).astype(np.int64)
                     % n_groups)                           # long tail
    cases.append(np.zeros(300, np.int64))                  # single group
    cases.append(np.arange(300))                           # all singletons
    for g in cases:
        for chunk in (7, 64, 8192):
            assert _neq_plan_span(g, chunk) == NeqPlan(g, chunk).span


# -- workset (delta-iteration) fit, ISSUE 9 ----------------------------------

def test_workset_fit_converges_early_and_tracks_bsp():
    """worksetTol > 0: users/items whose neighborhoods settled skip their
    solves, the fused while_loop exits as soon as every movement falls
    below the threshold (strictly before maxIter), and the factors stay
    within threshold-scale distance of the BSP fit."""
    table, _ = _synthetic(noise=0.01, seed=2)
    kw = dict(rank=4, max_iter=60, reg=1e-2, seed=5)

    def build(**extra):
        est = (ALS().set_rank(kw["rank"]).set_max_iter(kw["max_iter"])
               .set_reg_param(kw["reg"]).set_seed(kw["seed"]))
        for name, v in extra.items():
            getattr(est, f"set_{name}")(v)
        return est

    base = build().fit(table)
    est = build(workset_tol=1e-4)
    model = est.fit(table)

    rep = est.last_workset_report
    assert rep["rounds"] < kw["max_iter"]        # convergence-driven exit
    assert rep["rounds"] == len(rep["active_fraction"])
    assert rep["active_fraction"][-1] == 0.0     # both masks drained
    # the skip rule shrinks the workset before it drains (some round
    # solved strictly fewer than all groups)
    assert rep["active_fraction"].min() == 0.0
    assert np.any((rep["active_fraction"] > 0)
                  & (rep["active_fraction"] < 1))

    pb = base.transform(table)[0]["prediction"]
    pw = model.transform(table)[0]["prediction"]
    np.testing.assert_allclose(pw, pb, atol=5e-3)


def test_workset_tol_param_defaults_and_validation():
    assert ALS().get_workset_tol() == 0.0
    assert ALS().set_workset_tol(1e-3).get_workset_tol() == 1e-3
    with pytest.raises(Exception):
        ALS().set_workset_tol(-1.0)


def test_workset_zero_tol_is_plain_bsp_fit():
    """worksetTol=0 (the default) must take the classic path — bitwise
    identical to a fit that never heard of worksets."""
    table, _ = _synthetic(seed=4)
    a = (ALS().set_rank(4).set_max_iter(8).set_seed(3)).fit(table)
    b = (ALS().set_rank(4).set_max_iter(8).set_seed(3)
         .set_workset_tol(0.0)).fit(table)
    np.testing.assert_array_equal(
        a.get_model_data()[0]["userFactors"][0],
        b.get_model_data()[0]["userFactors"][0])
