"""ELL static-routing scatter (`ops/ell_scatter.py`) — the Pallas hot
path behind the mixed-layout LR trainer.

Tier-1 (CPU) coverage: layout construction (host + device builders must
agree, overflow and heavy-hitter routing must be exact), the csum/pick
math against a plain numpy scatter, and the full `_mixed_update_ell`
step against the `_mixed_update` oracle.  The Mosaic kernel itself is
compiled and parity-checked on real TPU by bench.py before anything is
timed (same stance as the KMeans kernel, bench.py)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from flink_ml_tpu.ops.ell_scatter import (
    ELL_WIDTH,
    ell_layout,
    ell_layout_device,
    ell_scatter_apply_xla,
    supported,
)


def _scatter_reference(d, layout, r_ext, lr, step=0):
    """Dense scatter the ELL + overflow routing back to a flat weight."""
    w = np.zeros(d, np.float64)
    src = np.asarray(layout.src[step])
    pos = np.asarray(layout.pos[step])
    mask = np.asarray(layout.mask[step])
    rows = src.shape[0]
    # reconstruct per-slot updates the kernel would apply
    u = -lr * r_ext[src]
    csum = np.cumsum(u, axis=1)
    G = np.take_along_axis(csum, pos, axis=1) * mask
    delta = G - np.concatenate([np.zeros((rows, 1)), G[:, :-1]], axis=1)
    w += delta.reshape(-1)
    np.add.at(w, np.asarray(layout.ovf_idx[step]),
              -lr * r_ext[np.asarray(layout.ovf_src[step])])
    np.add.at(w, np.asarray(layout.heavy_idx[step]),
              -lr * (np.asarray(layout.heavy_cnt[step], np.float64)
                     @ r_ext[:layout.batch]))
    return w


def _direct_scatter(d, cat, r, lr):
    w = np.zeros(d, np.float64)
    np.add.at(w, cat.reshape(-1),
              np.repeat(-lr * r, cat.shape[-1]))
    return w


class TestLayout:
    def test_supported(self):
        assert supported(1 << 20)
        assert supported(128 * 128)
        assert not supported(1000)       # not lane-divisible
        assert not supported(128 * 64)   # too few rows

    def test_routing_matches_direct_scatter(self):
        rng = np.random.default_rng(0)
        d, batch, nnz = 128 * 128, 64, 7
        cat = rng.integers(0, d, size=(2, batch, nnz)).astype(np.int32)
        r = rng.normal(size=batch).astype(np.float32)
        layout = ell_layout(cat, d)
        r_ext = np.concatenate([r, np.zeros(1, np.float32)])
        for step in range(2):
            got = _scatter_reference(d, layout, r_ext, 0.3, step)
            want = _direct_scatter(d, cat[step], r, 0.3)
            np.testing.assert_allclose(got, want, atol=1e-5)

    def test_heavy_hitter_overflows(self):
        # one index receives every slot: below the heavy threshold it
        # splits ELL (128) + overflow (the rest)
        d, batch, nnz = 128 * 128, 300, 2
        cat = np.full((1, batch, nnz), 777, np.int32)
        r = np.ones(batch, np.float32)
        layout = ell_layout(cat, d, heavy_threshold=10_000)
        n_ovf = int((np.asarray(layout.ovf_src[0]) != batch).sum())
        assert n_ovf == batch * nnz - ELL_WIDTH
        r_ext = np.concatenate([r, np.zeros(1, np.float32)])
        got = _scatter_reference(d, layout, r_ext, 1.0)
        want = _direct_scatter(d, cat[0], r, 1.0)
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_heavy_hitter_dense_path(self):
        # above the threshold the whole run routes to the count matrix
        rng = np.random.default_rng(8)
        d, batch, nnz = 128 * 128, 400, 4
        cat = rng.integers(0, d, size=(1, batch, nnz)).astype(np.int32)
        cat[:, :, 0] = 777          # 400 slots > threshold 300
        cat[:, ::2, 1] = 778        # 200 slots < threshold: stays per-slot
        r = rng.normal(size=batch).astype(np.float32)
        layout = ell_layout(cat, d, heavy_threshold=300)
        h_idx = np.asarray(layout.heavy_idx[0])
        assert 777 in h_idx and 778 not in h_idx
        # heavy slots left the ELL grid and the overflow list
        assert int((np.asarray(layout.ovf_src[0])
                    != batch).sum()) < batch * nnz
        r_ext = np.concatenate([r, np.zeros(1, np.float32)])
        got = _scatter_reference(d, layout, r_ext, 0.7)
        want = _direct_scatter(d, cat[0], r, 0.7)
        np.testing.assert_allclose(got, want, atol=1e-4)

    def test_device_builder_agrees_with_host(self):
        rng = np.random.default_rng(1)
        d, batch, nnz = 128 * 128, 96, 5
        cat = rng.integers(0, d, size=(3, batch, nnz)).astype(np.int32)
        # include a heavy hitter to exercise the device overflow path
        cat[:, :, 0] = 12345
        host = ell_layout(cat, d)
        dev = ell_layout_device(jnp.asarray(cat), d, ovf_cap=1024)
        r = rng.normal(size=batch).astype(np.float32)
        r_ext = np.concatenate([r, np.zeros(1, np.float32)])
        for step in range(3):
            got_h = _scatter_reference(d, host, r_ext, 0.5, step)
            got_d = _scatter_reference(d, dev, r_ext, 0.5, step)
            np.testing.assert_allclose(got_h, got_d, atol=1e-5)

    def test_device_builder_capacity_check(self):
        """ADVICE r3: the device builder drops slots beyond the static
        caps — need_ovf/need_heavy + assert_capacities make that loud."""
        rng = np.random.default_rng(2)
        d, batch, nnz = 128 * 128, 64, 4
        cat = rng.integers(0, d, size=(2, batch, nnz)).astype(np.int32)
        ok = ell_layout_device(jnp.asarray(cat), d, ovf_cap=1024)
        assert ok.need_ovf is not None and ok.need_heavy is not None
        assert ok.assert_capacities() is ok

        # force an overflow flood: all 256 slots/step land in table row 0
        # (indices < 128) with ~2 repeats each — light runs, but the row
        # keeps only ELL_WIDTH slots, so ~128 must spill per step
        cat2 = rng.integers(0, 128, size=(2, batch, nnz)).astype(np.int32)
        need = ell_layout_device(jnp.asarray(cat2), d, ovf_cap=4096)
        worst = int(jnp.max(need.need_ovf))
        assert worst >= batch * nnz - 128
        starved = ell_layout_device(jnp.asarray(cat2), d, ovf_cap=worst - 1)
        with pytest.raises(ValueError, match="raise ovf_cap"):
            starved.assert_capacities()

        # heavy starvation: two distinct heavy indices, cap of one
        cat3 = np.zeros((1, 600, 2), np.int32)
        cat3[..., 1] = 777
        starved_h = ell_layout_device(jnp.asarray(cat3), d, heavy_cap=1)
        with pytest.raises(ValueError, match="raise heavy_cap"):
            starved_h.assert_capacities()


class TestApplyXla:
    def test_matches_numpy(self):
        rng = np.random.default_rng(2)
        d, batch, nnz = 128 * 128, 128, 9
        cat = rng.integers(0, d, size=(1, batch, nnz)).astype(np.int32)
        layout = ell_layout(cat, d)
        r = rng.normal(size=batch).astype(np.float32)
        r_ext = jnp.concatenate([jnp.asarray(r), jnp.zeros(1)])
        u = -0.2 * np.asarray(r_ext)[np.asarray(layout.src[0])]
        w0 = rng.normal(size=d).astype(np.float32)
        got = np.asarray(ell_scatter_apply_xla(
            jnp.asarray(w0), jnp.asarray(u), layout.pos[0],
            layout.mask[0]))
        want = w0.astype(np.float64) + _scatter_reference(
            d, layout, np.asarray(r_ext), 0.2)
        np.testing.assert_allclose(got, want, atol=1e-4)


class TestMixedUpdateEll:
    def test_step_matches_xla_oracle(self):
        from flink_ml_tpu.models.common.losses import logistic_loss
        from flink_ml_tpu.models.common.sgd import (
            SGDConfig, _mixed_update, _mixed_update_ell)

        rng = np.random.default_rng(3)
        d, batch, nnz, nd = 128 * 128, 64, 6, 4
        dense = rng.normal(size=(batch, nd)).astype(np.float32)
        cat = rng.integers(0, d, size=(1, batch, nnz)).astype(np.int32)
        y = rng.integers(0, 2, size=batch).astype(np.float32)
        wb = np.ones(batch, np.float32)
        layout = ell_layout(cat, d)

        for cfg in (SGDConfig(learning_rate=0.4, tol=0),
                    SGDConfig(learning_rate=0.4, reg=0.05,
                              elastic_net=0.3, tol=0)):
            params = {"w": jnp.asarray(rng.normal(size=d), jnp.float32),
                      "b": jnp.asarray(0.1, jnp.float32)}
            oracle = _mixed_update(logistic_loss, cfg)
            want, want_loss = oracle(params, jnp.asarray(dense),
                                     jnp.asarray(cat[0]), jnp.asarray(y),
                                     jnp.asarray(wb))
            ell = _mixed_update_ell(logistic_loss, cfg, backend="xla")
            got, got_loss = ell(params, jnp.asarray(dense),
                                layout.src[0],
                                layout.pos[0], layout.mask[0],
                                layout.ovf_idx[0], layout.ovf_src[0],
                                layout.heavy_idx[0], layout.heavy_cnt[0],
                                jnp.asarray(y), jnp.asarray(wb))
            np.testing.assert_allclose(np.asarray(got_loss),
                                       np.asarray(want_loss), rtol=1e-6)
            np.testing.assert_allclose(np.asarray(got["w"]),
                                       np.asarray(want["w"]), atol=1e-5)
            np.testing.assert_allclose(np.asarray(got["b"]),
                                       np.asarray(want["b"]), rtol=1e-5)

    def test_sgd_fit_mixed_plans_xla_on_cpu(self):
        from flink_ml_tpu.models.common.sgd import plan_mixed_impl
        from flink_ml_tpu.parallel.mesh import default_mesh

        assert plan_mixed_impl(1 << 20, default_mesh()) == "xla"


@pytest.mark.skipif(jax.default_backend() != "tpu",
                    reason="Mosaic kernel needs TPU")
class TestApplyPallas:
    def test_kernel_matches_xla_twin(self):
        from flink_ml_tpu.ops.ell_scatter import ell_scatter_apply

        rng = np.random.default_rng(4)
        d = 128 * 128
        rows = d // 128
        u = rng.normal(size=(rows, 128)).astype(np.float32)
        cat = rng.integers(0, d, size=(1, 64, 8)).astype(np.int32)
        layout = ell_layout(cat, d)
        w0 = rng.normal(size=d).astype(np.float32)
        got = np.asarray(ell_scatter_apply(
            jnp.asarray(w0), jnp.asarray(u), layout.pos[0],
            layout.mask[0]))
        want = np.asarray(ell_scatter_apply_xla(
            jnp.asarray(w0), jnp.asarray(u), layout.pos[0],
            layout.mask[0]))
        np.testing.assert_allclose(got, want, atol=1e-4)


def test_heavy_threshold_floor_enforced():
    # threshold < ELL_WIDTH would silently drop kept-slot updates after a
    # heavy run (pos inflated past rank); both builders must refuse it
    cat = np.zeros((1, 8, 2), np.int32)
    with pytest.raises(ValueError, match="heavy_threshold"):
        ell_layout(cat, 128 * 128, heavy_threshold=64)
    with pytest.raises(ValueError, match="heavy_threshold"):
        ell_layout_device(jnp.asarray(cat), 128 * 128, heavy_threshold=64)


class TestSparseUpdateEll:
    def test_step_matches_xla_oracle(self):
        from flink_ml_tpu.models.common.losses import logistic_loss
        from flink_ml_tpu.models.common.sgd import (
            SGDConfig, _sparse_update, _sparse_update_ell)

        rng = np.random.default_rng(6)
        d, batch, nnz = 128 * 128, 96, 7
        idx = rng.integers(0, d, size=(1, batch, nnz)).astype(np.int32)
        idx[:, ::3, 0] = 505           # duplicate hot-ish index w/ values
        vals = rng.normal(size=(1, batch, nnz)).astype(np.float32)
        y = rng.integers(0, 2, size=batch).astype(np.float32)
        wb = np.ones(batch, np.float32)
        layout = ell_layout(idx, d, values=vals)
        assert layout.val is not None and layout.ovf_val is not None
        assert layout.heavy_cnt.dtype == jnp.float32

        for cfg in (SGDConfig(learning_rate=0.3, tol=0),
                    SGDConfig(learning_rate=0.3, reg=0.04,
                              elastic_net=0.5, tol=0)):
            params = {"w": jnp.asarray(rng.normal(size=d), jnp.float32),
                      "b": jnp.asarray(-0.2, jnp.float32)}
            want, want_loss = _sparse_update(logistic_loss, cfg)(
                params, jnp.asarray(idx[0]), jnp.asarray(vals[0]),
                jnp.asarray(y), jnp.asarray(wb))
            got, got_loss = _sparse_update_ell(
                logistic_loss, cfg, backend="xla")(
                params,
                layout.src[0], layout.pos[0], layout.mask[0],
                layout.val[0], layout.ovf_idx[0], layout.ovf_src[0],
                layout.ovf_val[0], layout.heavy_idx[0],
                layout.heavy_cnt[0], jnp.asarray(y), jnp.asarray(wb))
            np.testing.assert_allclose(np.asarray(got_loss),
                                       np.asarray(want_loss), rtol=1e-6)
            np.testing.assert_allclose(np.asarray(got["w"]),
                                       np.asarray(want["w"]), atol=1e-5)

    def test_heavy_values_route_dense(self):
        from flink_ml_tpu.models.common.losses import logistic_loss
        from flink_ml_tpu.models.common.sgd import (
            SGDConfig, _sparse_update, _sparse_update_ell)

        rng = np.random.default_rng(7)
        d, batch, nnz = 128 * 128, 300, 3
        idx = rng.integers(0, d, size=(1, batch, nnz)).astype(np.int32)
        idx[:, :, 0] = 999             # 300 slots > threshold
        vals = rng.normal(size=(1, batch, nnz)).astype(np.float32)
        y = rng.integers(0, 2, size=batch).astype(np.float32)
        wb = np.ones(batch, np.float32)
        layout = ell_layout(idx, d, values=vals, heavy_threshold=256)
        assert 999 in np.asarray(layout.heavy_idx[0])
        cfg = SGDConfig(learning_rate=0.5, tol=0)
        params = {"w": jnp.zeros(d, jnp.float32),
                  "b": jnp.zeros((), jnp.float32)}
        want, _ = _sparse_update(logistic_loss, cfg)(
            params, jnp.asarray(idx[0]), jnp.asarray(vals[0]),
            jnp.asarray(y), jnp.asarray(wb))
        got, _ = _sparse_update_ell(logistic_loss, cfg, backend="xla")(
            params,
            layout.src[0], layout.pos[0], layout.mask[0], layout.val[0],
            layout.ovf_idx[0], layout.ovf_src[0], layout.ovf_val[0],
            layout.heavy_idx[0], layout.heavy_cnt[0],
            jnp.asarray(y), jnp.asarray(wb))
        np.testing.assert_allclose(np.asarray(got["w"]),
                                   np.asarray(want["w"]), atol=1e-5)

    def test_device_builder_values_agree_with_host(self):
        from flink_ml_tpu.models.common.losses import logistic_loss
        from flink_ml_tpu.models.common.sgd import (
            SGDConfig, _sparse_update_ell)

        rng = np.random.default_rng(9)
        d, batch, nnz = 128 * 128, 400, 5
        idx = rng.integers(0, d, size=(2, batch, nnz)).astype(np.int32)
        # 400 occurrences of idx 31: > threshold 128 -> HEAVY value sums;
        # 200 of idx 33 (same table row as 31): not heavy, > ELL_WIDTH
        # entries in row 0 -> real OVERFLOW values
        idx[:, :, 0] = 31
        idx[:, ::2, 1] = 33
        vals = rng.normal(size=(2, batch, nnz)).astype(np.float32)
        host = ell_layout(idx, d, values=vals, heavy_threshold=256)
        dev = ell_layout_device(jnp.asarray(idx), d, ovf_cap=512,
                                values=jnp.asarray(vals),
                                heavy_threshold=256)
        assert 31 in np.asarray(host.heavy_idx[0])
        assert float(np.abs(np.asarray(host.ovf_val)).sum()) > 0
        # grid fields match exactly; overflow/heavy capacities differ by
        # construction, so compare the applied UPDATE instead
        for f in ("src", "pos", "mask", "val"):
            np.testing.assert_allclose(
                np.asarray(getattr(host, f)),
                np.asarray(getattr(dev, f)), atol=1e-6, err_msg=f)
        y = rng.integers(0, 2, size=batch).astype(np.float32)
        wb = np.ones(batch, np.float32)
        cfg = SGDConfig(learning_rate=0.4, tol=0)
        upd = _sparse_update_ell(logistic_loss, cfg, backend="xla")
        outs = []
        for L in (host, dev):
            params = {"w": jnp.zeros(d, jnp.float32),
                      "b": jnp.zeros((), jnp.float32)}
            got, _ = upd(params,
                         L.src[0], L.pos[0], L.mask[0], L.val[0],
                         L.ovf_idx[0], L.ovf_src[0], L.ovf_val[0],
                         L.heavy_idx[0], L.heavy_cnt[0],
                         jnp.asarray(y), jnp.asarray(wb))
            outs.append(np.asarray(got["w"]))
        np.testing.assert_allclose(outs[0], outs[1], atol=1e-6)


def test_sharded_ell_fit_matches_single_device_oracle(monkeypatch):
    """VERDICT r3 task 4: the data-axis-sharded ELL path (device-local
    grids + psum) must reproduce the single-device fit exactly (up to f32
    partial-sum order) on the virtual 8-device CPU mesh."""
    from flink_ml_tpu.models.common import sgd as S
    from flink_ml_tpu.models.common.losses import LOSSES
    from flink_ml_tpu.parallel.mesh import device_mesh

    rng = np.random.default_rng(7)
    n_dev = 8
    batch = 4 * n_dev
    n, nd, nc, d = 8 * batch, 3, 2, 128 * 128
    dense = rng.normal(size=(n, nd)).astype(np.float32)
    cat = rng.integers(nd, d, size=(n, nc)).astype(np.int32)
    y = (dense[:, 0] + 0.3 > 0).astype(np.float64)
    cfg = S.SGDConfig(learning_rate=0.3, max_epochs=3,
                      global_batch_size=batch, tol=0, seed=0,
                      reg=0.01, elastic_net=0.5)

    # force the ELL plan on CPU (the planner itself requires TPU); the
    # XLA twin of the kernel runs under shard_map
    monkeypatch.setattr(S, "plan_mixed_impl", lambda *a, **k: "ell")
    mesh8 = device_mesh({"data": n_dev})
    state_s, log_s = S.sgd_fit_mixed(LOSSES["logistic"], dense, cat, y,
                                     None, d, cfg, mesh=mesh8)
    assert state_s.planned_impl == "ell"

    monkeypatch.setattr(S, "plan_mixed_impl", lambda *a, **k: "xla")
    mesh1 = device_mesh({"data": 1}, devices=jax.devices()[:1])
    state_1, log_1 = S.sgd_fit_mixed(LOSSES["logistic"], dense, cat, y,
                                     None, d, cfg, mesh=mesh1)
    np.testing.assert_allclose(state_s.coefficients, state_1.coefficients,
                               atol=1e-5)
    np.testing.assert_allclose(log_s, log_1, atol=1e-6)
    assert log_s[-1] < log_s[0]


def test_plan_mixed_impl_admits_data_axis_meshes(monkeypatch):
    """plan_mixed_impl returns "ell" for a single-process data-axis mesh
    when the caller opts in (sgd_fit_mixed), and keeps the XLA fallback
    for single-device-shaped ELL wirings (the streaming fit)."""
    from flink_ml_tpu.models.common import sgd as S
    from flink_ml_tpu.parallel.mesh import device_mesh

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    d = 1 << 20
    mesh8 = device_mesh({"data": 8})
    assert S.plan_mixed_impl(d, mesh8, 32, allow_sharded=True) == "ell"
    assert S.plan_mixed_impl(d, mesh8, 32) == "xla"
    # model-axis meshes never take the data-sharded ELL route
    mesh_mp = device_mesh({"data": 4, "model": 2})
    assert S.plan_mixed_impl(d, mesh_mp, 32, allow_sharded=True) == "xla"
    # budget still enforced per device
    assert S.plan_mixed_impl(d, mesh8, 1 << 15, allow_sharded=True) == "xla"


def test_sharded_ell_sparse_fit_matches_single_device_oracle(monkeypatch):
    """Values-aware (indices, values) twin of the sharded-ELL oracle:
    device-local grids + psum must reproduce the single-device sparse fit
    on the 8-device CPU mesh."""
    from flink_ml_tpu.models.common import sgd as S
    from flink_ml_tpu.models.common.losses import LOSSES
    from flink_ml_tpu.parallel.mesh import device_mesh

    rng = np.random.default_rng(9)
    n_dev = 8
    batch = 4 * n_dev
    n, nnz, d = 8 * batch, 4, 128 * 128
    idx = rng.integers(0, d, size=(n, nnz)).astype(np.int32)
    vals = rng.normal(size=(n, nnz)).astype(np.float32)
    y = (vals[:, 0] > 0).astype(np.float64)
    cfg = S.SGDConfig(learning_rate=0.3, max_epochs=3,
                      global_batch_size=batch, tol=0, seed=0, reg=0.01)

    monkeypatch.setattr(S, "plan_mixed_impl", lambda *a, **k: "ell")
    mesh8 = device_mesh({"data": n_dev})
    state_s, log_s = S.sgd_fit_sparse(LOSSES["logistic"], idx, vals, y,
                                      None, d, cfg, mesh=mesh8)
    assert state_s.planned_impl == "ell"

    monkeypatch.setattr(S, "plan_mixed_impl", lambda *a, **k: "xla")
    mesh1 = device_mesh({"data": 1}, devices=jax.devices()[:1])
    state_1, log_1 = S.sgd_fit_sparse(LOSSES["logistic"], idx, vals, y,
                                      None, d, cfg, mesh=mesh1)
    np.testing.assert_allclose(state_s.coefficients, state_1.coefficients,
                               atol=1e-5)
    np.testing.assert_allclose(log_s, log_1, atol=1e-6)
    assert log_s[-1] < log_s[0]


def test_native_layout_builder_matches_numpy():
    """native/ell_layout.cpp (counting-sort, ~13x the numpy builder at
    product shape) must reproduce the numpy builder exactly: grids,
    overflow order, heavy routing, sentinel handling, forced-cap raises.
    Heavy f32 VALUE sums may differ in summation order only."""
    import flink_ml_tpu.ops.ell_scatter as E

    lib = E._native_ell()
    if lib is None:
        pytest.skip("native ell_layout unavailable (no toolchain)")

    def both(cat, d, values=None, **kw):
        nat = E.ell_layout(cat, d, values=values, device=False, **kw)
        E._ELL_NATIVE, E._ELL_NATIVE_TRIED = None, True   # force numpy
        try:
            ref = E.ell_layout(cat, d, values=values, device=False, **kw)
        finally:
            E._ELL_NATIVE_TRIED = False
        return nat, ref

    def check(cat, d, values=None, **kw):
        nat, ref = both(cat, d, values=values, **kw)
        for f in ("src", "pos", "mask", "ovf_idx", "ovf_src", "heavy_idx",
                  "need_ovf", "need_heavy"):
            np.testing.assert_array_equal(
                np.asarray(getattr(nat, f)), np.asarray(getattr(ref, f)),
                err_msg=f)
        if values is None:
            np.testing.assert_array_equal(np.asarray(nat.heavy_cnt),
                                          np.asarray(ref.heavy_cnt))
        else:
            np.testing.assert_allclose(np.asarray(nat.heavy_cnt),
                                       np.asarray(ref.heavy_cnt), atol=1e-4)
            np.testing.assert_array_equal(np.asarray(nat.val),
                                          np.asarray(ref.val))
            np.testing.assert_array_equal(np.asarray(nat.ovf_val),
                                          np.asarray(ref.ovf_val))

    rng = np.random.default_rng(0)
    d = 128 * 128
    check(rng.integers(0, d, size=(3, 96, 5)).astype(np.int32), d)

    # heavy + overflow flood + a second light index sharing the row
    cat2 = rng.integers(0, d, size=(2, 700, 4)).astype(np.int32)
    cat2[:, :, 0] = 777
    cat2[:, ::2, 1] = 778
    check(cat2, d)

    # sentinel padding rows drop out of the layout
    cat3 = rng.integers(0, d, size=(2, 64, 4)).astype(np.int32)
    cat3[:, 50:, :] = d
    check(cat3, d)

    # values variant (sgd_fit_sparse's layout)
    check(cat2, d, values=rng.normal(size=cat2.shape).astype(np.float32))

    # forced caps raise identically on both paths
    cat4 = cat2.copy()
    cat4[:, :, 1] = 9999   # two heavy indices
    for forced in ({"pad_heavy_cap": 1}, {"pad_ovf_cap": 8}):
        with pytest.raises(ValueError, match="forced cap"):
            E.ell_layout(cat2 if "pad_ovf_cap" in forced else cat4, d,
                         device=False, **forced)
        E._ELL_NATIVE, E._ELL_NATIVE_TRIED = None, True
        try:
            with pytest.raises(ValueError, match="forced cap"):
                E.ell_layout(cat2 if "pad_ovf_cap" in forced else cat4, d,
                             device=False, **forced)
        finally:
            E._ELL_NATIVE_TRIED = False

    # forced caps that fit produce exact forced shapes
    nat, ref = both(cat2, d, pad_ovf_cap=2048, pad_heavy_cap=4)
    assert nat.ovf_idx.shape == ref.ovf_idx.shape == (2, 2048)
    assert nat.heavy_idx.shape == ref.heavy_idx.shape == (2, 4)


def test_fused_gather_kernel_matches_twin_interpret():
    """ell_scatter_apply_fused (EXPERIMENTAL r4: u-gather inside the
    kernel via one-hot MXU contraction) must equal gather-then-apply in
    interpret mode, including pad slots (src == batch -> r_ext zero pad)."""
    from flink_ml_tpu.ops.ell_scatter import ell_scatter_apply_fused

    rng = np.random.default_rng(3)
    d, batch, nnz = 128 * 128, 96, 7
    cat = rng.integers(0, d, size=(1, batch, nnz)).astype(np.int32)
    lay = ell_layout(cat, d, device=False)
    r = rng.normal(size=batch).astype(np.float32)
    r_ext = np.concatenate([r, np.zeros(256 - batch % 256, np.float32)])
    w0 = rng.normal(size=d).astype(np.float32)
    lr = 0.35
    got = np.asarray(ell_scatter_apply_fused(
        jnp.asarray(w0), jnp.asarray(r_ext), jnp.asarray(lay.src[0]),
        jnp.asarray(lay.pos[0]), jnp.asarray(lay.mask[0]), lr=lr,
        interpret=True))
    u = (-lr) * r_ext[np.asarray(lay.src[0])]
    want = np.asarray(ell_scatter_apply_xla(
        jnp.asarray(w0), jnp.asarray(u), lay.pos[0], lay.mask[0]))
    np.testing.assert_allclose(got, want, atol=1e-5)

def test_margin_kernel_matches_direct_gather_interpret():
    """ell_margin_xla / ell_margin_fused (r4: forward half of the ELL
    plan) must reproduce sum_j v_j * w[idx_j] exactly when the whole
    batch fits the grid, for both the implicit-1.0 mixed layout and the
    values-aware sparse layout; the pad region (slot/ovf pads carry
    src == batch) is discarded by the [:batch] slice."""
    from flink_ml_tpu.ops.ell_scatter import ell_margin_fused, ell_margin_xla

    rng = np.random.default_rng(11)
    d, batch, nnz, m_len = 128 * 128, 96, 7, 256
    cat = rng.integers(0, d, size=(1, batch, nnz)).astype(np.int32)
    w = rng.normal(size=d).astype(np.float32)
    lay = ell_layout(cat, d, device=False)
    want = w[cat[0]].sum(axis=1)
    got = np.asarray(ell_margin_xla(
        jnp.asarray(w), jnp.asarray(lay.src[0]), jnp.asarray(lay.pos[0]),
        jnp.asarray(lay.mask[0]), m_len))
    np.testing.assert_allclose(got[:batch], want, atol=1e-4)
    got_f = np.asarray(ell_margin_fused(
        jnp.asarray(w), jnp.asarray(lay.src[0]), jnp.asarray(lay.pos[0]),
        jnp.asarray(lay.mask[0]), m_len=m_len, interpret=True))
    np.testing.assert_allclose(got_f[:batch], want, atol=1e-4)

    vals = rng.normal(size=(1, batch, nnz)).astype(np.float32)
    layv = ell_layout(cat, d, values=vals, device=False)
    wantv = (vals[0] * w[cat[0]]).sum(axis=1)
    gotv = np.asarray(ell_margin_fused(
        jnp.asarray(w), jnp.asarray(layv.src[0]), jnp.asarray(layv.pos[0]),
        jnp.asarray(layv.mask[0]), m_len=m_len,
        val=jnp.asarray(layv.val[0]), interpret=True))
    np.testing.assert_allclose(gotv[:batch], wantv, atol=1e-4)


def test_margin_decomposition_with_overflow_and_heavy():
    """The three-way margin decomposition (grid + overflow + heavy) must
    be exact when slots spill and a heavy index exists — the sgd helper's
    algebra, driven directly: a skewed batch where one index repeats past
    HEAVY_THRESHOLD and one row overflows its 128 slots."""
    from flink_ml_tpu.ops.ell_scatter import ell_margin_xla

    rng = np.random.default_rng(12)
    d, batch, nnz = 128 * 128, 1024, 8
    cat = rng.integers(0, d, size=(1, batch, nnz)).astype(np.int32)
    cat[0, :, 0] = 777            # heavy: 1024 > HEAVY_THRESHOLD slots
    cat[0, :200, 1] = 128 * 5 + np.arange(200) % 3  # row 5 overflows
    w = rng.normal(size=d).astype(np.float32)
    lay = ell_layout(cat, d, device=False)
    assert int(np.asarray(lay.need_heavy).max()) >= 1
    assert int(np.asarray(lay.need_ovf).max()) >= 1
    m_len = 1024 + 256
    mext = np.asarray(ell_margin_xla(
        jnp.asarray(w), jnp.asarray(lay.src[0]), jnp.asarray(lay.pos[0]),
        jnp.asarray(lay.mask[0]), m_len))
    ovf = np.zeros(m_len, np.float32)
    np.add.at(ovf, np.asarray(lay.ovf_src[0]),
              w[np.asarray(lay.ovf_idx[0])])
    margin = (mext + ovf)[:batch] + (
        w[np.asarray(lay.heavy_idx[0])]
        @ np.asarray(lay.heavy_cnt[0]).astype(np.float32))
    want = w[cat[0]].sum(axis=1)
    np.testing.assert_allclose(margin, want, rtol=1e-5, atol=1e-4)


def test_trim_overflow_preserves_update_exactly():
    """trim_overflow slices the overflow arrays to measured need; its
    exactness rests on every builder front-compacting real entries, so
    assert the trimmed layout yields the IDENTICAL update as the full
    one (any dropped real slot would move the overflow scatter), for
    both the host and device builders."""
    from flink_ml_tpu.models.common.losses import logistic_loss
    from flink_ml_tpu.models.common.sgd import SGDConfig, _mixed_update_ell
    from flink_ml_tpu.ops.ell_scatter import ell_layout_device

    rng = np.random.default_rng(17)
    d, batch, nnz = 128 * 128, 1024, 8
    cat = rng.integers(0, d, size=(1, batch, nnz)).astype(np.int32)
    cat[0, :300, 1] = 128 * 7 + np.arange(300) % 5   # row 7 spills
    y = rng.integers(0, 2, size=batch).astype(np.float32)
    wb = np.ones(batch, np.float32)
    dense = rng.normal(size=(batch, 3)).astype(np.float32)
    upd = _mixed_update_ell(logistic_loss,
                            SGDConfig(learning_rate=0.4, tol=0),
                            backend="xla")
    for builder in ("host", "device"):
        lay = (ell_layout(cat, d, pad_ovf_cap=2048)
               if builder == "host"
               else ell_layout_device(jnp.asarray(cat), d, ovf_cap=2048))
        trimmed = lay.assert_capacities().trim_overflow()
        need = int(np.asarray(lay.need_ovf).max())
        assert need > 0, "test data must actually spill"
        assert trimmed.ovf_idx.shape[1] < lay.ovf_idx.shape[1]
        assert trimmed.ovf_idx.shape[1] >= need
        outs = []
        for L in (lay, trimmed):
            params = {"w": jnp.zeros((d,), jnp.float32),
                      "b": jnp.zeros((), jnp.float32)}
            got, _ = upd(params, jnp.asarray(dense),
                         jnp.asarray(L.src[0]), jnp.asarray(L.pos[0]),
                         jnp.asarray(L.mask[0]), jnp.asarray(L.ovf_idx[0]),
                         jnp.asarray(L.ovf_src[0]),
                         jnp.asarray(L.heavy_idx[0]),
                         jnp.asarray(L.heavy_cnt[0]),
                         jnp.asarray(y), jnp.asarray(wb))
            outs.append(np.asarray(got["w"]))
        np.testing.assert_array_equal(outs[0], outs[1], err_msg=builder)
