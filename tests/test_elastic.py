"""Elastic membership runtime — unit coverage.

The chunk-boundary / fit-level contracts (bit-exact resize, kill+rejoin
trajectory, torn-cut-during-resize, EF/pending across two resizes,
death-mid-chunk) live in tests/test_faults.py next to the rest of the
chaos suite; this file covers the machinery underneath: the heartbeat
lease table under an injected clock, the FaultPlan membership kinds,
the reducer-state reshard mapping, and the legacy-cut fleet gate.
"""

import numpy as np
import pytest

import jax

from flink_ml_tpu.iteration.checkpoint import (
    CorruptStateError,
    mesh_shape_meta,
    require_fleet_compat,
)
from flink_ml_tpu.parallel import grad_reduce as GR
from flink_ml_tpu.parallel.elastic import (
    ElasticCoordinator,
    ResizeRequested,
)
from flink_ml_tpu.robustness import (
    FaultPlan,
    InjectedJoin,
    InjectedPreemption,
)


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# -- lease table -------------------------------------------------------------

def test_lease_expiry_under_injected_clock():
    """Missed heartbeats past the lease timeout reap the worker — the
    real-deployment death signal, fully deterministic under the
    injected clock."""
    clock = FakeClock()
    c = ElasticCoordinator(chips_per_worker=1, initial_workers=3,
                           lease_timeout_s=5.0, clock=clock)
    assert c.fleet_size == 3
    clock.advance(4.0)
    c.heartbeat("w0")
    c.heartbeat("w1")          # w2 goes silent
    clock.advance(2.0)         # w2's lease lapsed (6.0 > 5.0)
    assert c.expire() == ["w2"]
    assert c.live_workers() == ("w0", "w1")
    assert c.counters["expirations"] == 1
    # heartbeats renewed w0/w1 to 9.0 — still alive
    assert c.expire() == []


def test_heartbeat_unknown_worker_raises():
    c = ElasticCoordinator(chips_per_worker=1, initial_workers=1)
    with pytest.raises(KeyError, match="nope"):
        c.heartbeat("nope")


def test_membership_epoch_bumps_per_transition_and_floor_suppresses():
    c = ElasticCoordinator(chips_per_worker=1, initial_workers=2,
                           min_workers=2, max_workers=3)
    assert c.membership_epoch == 0
    assert c.register() == "w2"
    assert c.membership_epoch == 1
    # at max: join suppressed, epoch unchanged
    assert c.register() is None
    assert c.membership_epoch == 1
    assert c.counters["suppressed"] == 1
    assert c.leave("w2")
    assert c.membership_epoch == 2
    # at the min floor: preempt suppressed — a chaos schedule cannot
    # shrink the fleet past min_workers and kill the run
    assert c.preempt() is None
    assert c.fleet_size == 2
    assert c.counters["suppressed"] == 2


def test_mesh_follows_join_order_and_marks_fleet_consumed():
    devs = jax.devices()
    c = ElasticCoordinator(chips_per_worker=2, initial_workers=2,
                           devices=devs)
    m = c.mesh()
    assert dict(m.shape) == {"dcn": 2, "data": 2}
    assert list(m.devices.flat) == devs[:4]
    assert c.poll() is False
    c.register()
    assert c.poll() is True           # changed since the mesh was built
    m2 = c.mesh()
    assert dict(m2.shape) == {"dcn": 3, "data": 2}
    assert list(m2.devices.flat) == devs[:6]
    assert c.poll() is False          # consumed
    # LIFO preempt frees the newest worker's devices
    c.preempt()
    assert list(c.mesh().devices.flat) == devs[:4]


def test_on_failure_prefers_lapsed_lease_then_lifo_victim():
    clock = FakeClock()
    c = ElasticCoordinator(chips_per_worker=1, initial_workers=3,
                           lease_timeout_s=5.0, clock=clock)
    from flink_ml_tpu.robustness import InjectedCrash, \
        InjectedDiskFullError

    clock.advance(6.0)
    c.heartbeat("w1")
    c.heartbeat("w2")          # w0 silent -> its lease lapsed
    assert c.on_failure(RuntimeError("boom")) == "w0"
    assert c.counters["expirations"] == 1
    # a failure that is not worker-loss-shaped (disk full, logic bug)
    # never evicts a healthy seat — plain crash recovery instead
    assert c.on_failure(InjectedDiskFullError("disk full")) is None
    assert c.fleet_size == 2
    # no lapsed lease + a crash: deterministic LIFO victim
    assert c.on_failure(InjectedCrash("boom")) == "w2"
    assert c.counters["deaths"] == 1
    # min_workers floor: the fleet stays put, plain crash recovery
    assert c.on_failure(InjectedCrash("boom")) is None
    assert c.fleet_size == 1


def test_snapshot_and_metric_group_publish():
    from flink_ml_tpu.obs.tree import default_tree
    from flink_ml_tpu.utils.metrics import MetricGroup

    c = ElasticCoordinator(chips_per_worker=1, initial_workers=2)
    c.register()
    c.preempt()
    snap = default_tree(elastic=c).snapshot()["elastic"]
    assert snap["fleet_size"] == 2
    assert snap["joins"] == 1 and snap["preemptions"] == 1
    assert snap["membership_epoch"] == 2
    g = MetricGroup("root")
    c.publish(g)
    flat = g.snapshot()
    assert flat["elastic.fleet_size"] == 2
    assert flat["elastic.preemptions"] == 1


# -- FaultPlan membership kinds ---------------------------------------------

def test_fault_plan_membership_kinds_raise_and_are_seedable():
    plan = (FaultPlan().inject("s", at=0, kind="preempt")
            .inject("s", at=1, kind="join"))
    with pytest.raises(InjectedPreemption):
        plan.fire("s")
    with pytest.raises(InjectedJoin):
        plan.fire("s")
    assert plan.fires == [("s", 0, "preempt"), ("s", 1, "join")]
    # seeded random schedules work for the membership kinds unchanged
    a = FaultPlan(seed=9).inject_random("s", rate=0.2, horizon=40,
                                        kind="preempt")
    b = FaultPlan(seed=9).inject_random("s", rate=0.2, horizon=40,
                                        kind="preempt")
    assert a.scheduled("s") == b.scheduled("s") != []


def test_wrap_source_membership_fault_is_lossless():
    """A membership fault fires BEFORE the pull — the retried next()
    still sees every item, so wrappers stay lossless across a resize
    (the satellite contract)."""
    plan = FaultPlan().inject("source.pull", at=1, kind="preempt")
    src = plan.wrap_source([10, 11, 12])
    assert next(src) == 10
    with pytest.raises(InjectedPreemption):
        next(src)
    assert next(src) == 11
    assert next(src) == 12


def test_poll_translates_injected_churn_deterministically():
    c = ElasticCoordinator(chips_per_worker=1, initial_workers=3)
    c.mesh()
    plan = (FaultPlan().inject(c.SCOPE, at=1, kind="preempt")
            .inject(c.SCOPE, at=3, kind="join"))
    with plan:
        assert c.poll(0) is False
        assert c.poll(1) is True      # preempt: newest worker left
        assert c.live_workers() == ("w0", "w1")
        c.mesh()
        assert c.poll(2) is False
        assert c.poll(3) is True      # join: a fresh seat
    assert [t[0] for t in c.transitions] == ["preempt", "join"]
    # a non-membership kind at the seam propagates like any crash
    c.mesh()
    plan2 = FaultPlan().inject(c.SCOPE, at=0, kind="crash")
    from flink_ml_tpu.robustness import InjectedCrash

    with plan2, pytest.raises(InjectedCrash):
        c.poll(4)


# -- reducer-state reshard ---------------------------------------------------

def _topk_state(n, shape=(6,), overlap=True):
    cfg = GR.GradReduceConfig(mode="topk", density=0.5, overlap=overlap)
    like = {"w": np.zeros(shape, np.float32)}
    st = jax.device_get(GR.init_state(cfg, like, n))
    return cfg, st


def test_reshard_state_preserves_total_mass_and_layout():
    cfg, st = _topk_state(4)
    rng = np.random.default_rng(0)
    st["ef"]["w"] = rng.normal(size=(4, 6)).astype(np.float32)
    st["pending"]["w"] = rng.normal(size=(4, 6)).astype(np.float32)
    out = GR.reshard_state(st, 6)
    assert out["ef"]["w"].shape == (6, 6)
    # totals preserved exactly (the applied-mass invariant the drain
    # and the EF recursion both ride)
    np.testing.assert_array_equal(out["ef"]["w"].sum(0),
                                  st["ef"]["w"].sum(0))
    np.testing.assert_array_equal(out["pending"]["w"].sum(0),
                                  st["pending"]["w"].sum(0))
    # collapsed onto the first participant, rest zero
    assert np.all(out["ef"]["w"][1:] == 0)


def test_reshard_state_hier_keeps_slice_structure():
    """Hierarchical EF residuals live embedded at each participant's ICI
    slice; the resize must keep slice i's mass in slice-i rows so the
    next reduce-scatter routes it home (the PR 3 re-embedding rule)."""
    cfg = GR.GradReduceConfig(mode="topk", density=0.5, axis="data",
                              dcn_axis="dcn")
    like = {"w": np.zeros((8,), np.float32)}
    st = jax.device_get(GR.init_state(cfg, like, 4))   # dcn=2 x ici=2
    ef = np.zeros((4, 8), np.float32)
    # participant (d, i) holds residual only in ICI slice i (4 elems)
    for d in range(2):
        for i in range(2):
            ef[d * 2 + i, i * 4:(i + 1) * 4] = (d + 1) * (i + 1)
    st["ef"] = {"w": ef}
    out = GR.reshard_state(st, 6, ici_size=2)          # dcn 2 -> 3
    w = out["ef"]["w"]
    assert w.shape == (6, 8)
    # dcn group 0 carries the per-slice totals, groups 1..2 are zero
    np.testing.assert_array_equal(w[0], [3, 3, 3, 3, 0, 0, 0, 0])
    np.testing.assert_array_equal(w[1], [0, 0, 0, 0, 6, 6, 6, 6])
    assert np.all(w[2:] == 0)


def test_reshard_state_policy_and_keys_deterministic():
    cfg = GR.GradReduceConfig(mode="topk", density=0.25, adaptive=True,
                              density_ladder=(0.1, 0.25, "int8", "exact"))
    like = {"w": np.zeros((6,), np.float32)}
    st = jax.device_get(GR.init_state(cfg, like, 2))
    st["ema"] = np.asarray([[0.3], [0.3]], np.float32)
    st["rung"] = np.asarray([[2], [2]], np.int32)
    st["tick"] = np.asarray([5, 5], np.int32)
    a = GR.reshard_state(st, 4)
    b = GR.reshard_state(st, 4)
    # policy state broadcasts (it is replicated content by construction)
    np.testing.assert_array_equal(a["ema"],
                                  np.full((4, 1), np.float32(0.3)))
    np.testing.assert_array_equal(a["rung"], np.full((4, 1), 2))
    np.testing.assert_array_equal(a["tick"], np.full((4,), 5))
    # rounding keys re-derive deterministically and stay distinct
    np.testing.assert_array_equal(a["key"], b["key"])
    assert len({tuple(np.asarray(k).tolist()) for k in a["key"]}) == 4


def test_reshard_state_same_size_is_identity_and_validates():
    cfg, st = _topk_state(4)
    assert GR.reshard_state(st, 4) is st
    with pytest.raises(ValueError, match="ICI"):
        GR.reshard_state(st, 6, ici_size=4)
    cfg2, st2 = _topk_state(2)
    st2["mystery"] = np.zeros((2, 3), np.float32)
    with pytest.raises(ValueError, match="mystery"):
        GR.reshard_state(st2, 4)
    assert GR.state_participants(st) == 4
    assert GR.state_participants({}) is None
    assert GR.state_participants(None) is None


# -- fleet-compat gate -------------------------------------------------------

def test_require_fleet_compat_legacy_cut_raises_diagnosable():
    with pytest.raises(CorruptStateError, match="mesh-shape metadata"):
        require_fleet_compat({"epoch": 4}, saved_participants=4,
                             current_participants=6, path="/ck/ckpt-4")
    # same fleet: legacy cuts keep restoring fine
    require_fleet_compat({"epoch": 4}, saved_participants=4,
                         current_participants=4)
    # a cut that says which fleet wrote it passes the gate (the caller
    # then reshards)
    mesh = ElasticCoordinator(chips_per_worker=2,
                              initial_workers=2).mesh()
    meta = mesh_shape_meta(mesh, participant_count=4)
    assert meta["mesh_shape"] == {"dcn": 2, "data": 2}
    assert meta["participant_count"] == 4
    require_fleet_compat(meta, saved_participants=4,
                         current_participants=6)


def test_resize_requested_carries_fleet_identity():
    exc = ResizeRequested(step=12, fleet_size=3, membership_epoch=2)
    assert exc.step == 12 and exc.fleet_size == 3
    assert "3 worker" in str(exc)


def test_membership_without_checkpoint_or_supervisor_fails_loudly(tmp_path):
    """The two misuse modes: an elastic fit without durable cuts has
    nothing to resize from (ValueError at the fit), and a
    ResizeRequested with no elastic supervisor must propagate, not be
    swallowed as a crash."""
    from flink_ml_tpu.models.common.losses import logistic_loss
    from flink_ml_tpu.models.common.sgd import SGDConfig, sgd_fit_outofcore

    c = ElasticCoordinator(chips_per_worker=1, initial_workers=2)
    with pytest.raises(ValueError, match="checkpoint"):
        sgd_fit_outofcore(
            logistic_loss, lambda: iter([]), num_features=4,
            config=SGDConfig(max_epochs=1), mesh=c.mesh(), membership=c)

    from flink_ml_tpu.iteration import CheckpointConfig
    from flink_ml_tpu.robustness import resilient_fit

    def fake_fit(*, checkpoint, resume):
        raise ResizeRequested(step=0, fleet_size=2, membership_epoch=1)

    with pytest.raises(ResizeRequested):
        resilient_fit(fake_fit,
                      checkpoint=CheckpointConfig(str(tmp_path / "ck")))


def test_membership_flat_compressed_config_rejected(tmp_path):
    """A flat (non-hierarchical) compressed grad_reduce on an elastic
    (dcn, data) mesh would silently replicate the batch over the
    resizable axis — refused with sizing guidance instead."""
    from flink_ml_tpu.iteration import CheckpointConfig
    from flink_ml_tpu.models.common.losses import logistic_loss
    from flink_ml_tpu.models.common.sgd import SGDConfig, sgd_fit_outofcore
    from flink_ml_tpu.parallel.grad_reduce import GradReduceConfig

    c = ElasticCoordinator(chips_per_worker=2, initial_workers=2)
    cfg = SGDConfig(max_epochs=1, grad_reduce=GradReduceConfig(
        mode="topk", density=0.25))
    with pytest.raises(ValueError, match="dcn_axis"):
        sgd_fit_outofcore(
            logistic_loss, lambda: iter([]), num_features=4, config=cfg,
            mesh=c.mesh(), membership=c,
            checkpoint=CheckpointConfig(str(tmp_path / "ck")))


def test_request_resize_applies_at_boundary_through_the_churn_path():
    """ISSUE 17: a controller resize request is deferred to its pinned
    chunk boundary (the FaultPlan index space: poll invocations), then
    applied through the SAME register/preempt transitions injected
    churn uses — the audit log shows plain preempt/join kinds, and the
    clamp + last-writer-wins semantics hold."""
    c = ElasticCoordinator(chips_per_worker=1, initial_workers=3,
                           min_workers=1, max_workers=5)
    # clamp: target outside [min, max] lands on the bound
    assert c.request_resize(99) == 5
    # last-writer-wins: the newest intent replaces the pending one
    assert c.request_resize(1, at_boundary=2) == 1
    assert c.counters["controller_requests"] == 2
    assert c.snapshot()["pending_resize_target"] == 1
    assert not c.poll() and c.fleet_size == 3      # boundary 0: pending
    assert not c.poll() and c.fleet_size == 3      # boundary 1: pending
    assert c.poll() and c.fleet_size == 1          # boundary 2: applied
    # the same path as injected churn: ordinary preempt transitions
    assert [t[0] for t in c.transitions] == ["preempt", "preempt"]
    assert c.counters["preemptions"] == 2
    assert c.snapshot()["pending_resize_target"] == -1
    # mesh absorbs the new fleet; the NEXT request grows through joins
    c.mesh()
    assert not c.poll()
    c.request_resize(2)
    assert c.poll() and c.fleet_size == 2          # next boundary, join
    assert c.transitions[-1][0] == "join"
    assert c.snapshot()["boundary_polls"] == 5


def test_request_resize_composes_with_injected_churn():
    """A seeded fault and a pending controller request landing on the
    SAME boundary compose: the injected transition fires first (the
    seam), then the request converges the fleet to its target — one
    boundary, one consistent final extent."""
    c = ElasticCoordinator(chips_per_worker=1, initial_workers=2,
                           min_workers=1, max_workers=4)
    plan = FaultPlan().inject(c.SCOPE, at=0, kind="join")
    c.request_resize(4)
    with plan:
        assert c.poll()
    # join fired (2 -> 3), then the request topped up to 4
    assert c.fleet_size == 4
    assert [t[0] for t in c.transitions] == ["join", "join"]
