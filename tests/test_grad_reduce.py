"""Gradient-reduction subsystem tests (parallel/grad_reduce.py): every
mode against a numpy single-program oracle on the 8-device CPU mesh, the
EF residual recursion, the hierarchical ICI x DCN composition, and the
bytes-on-wire accounting the bench comm leg reports."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from flink_ml_tpu.parallel import grad_reduce as GR
from flink_ml_tpu.parallel.collectives import shard_map_fn
from flink_ml_tpu.parallel.grad_reduce import GradReduceConfig
from flink_ml_tpu.parallel.mesh import device_mesh


def _abstract(tree):
    """Hashable (structure, shapes, dtypes) signature of a pytree — what
    the compiled program actually depends on, given fixed config/mesh."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return treedef, tuple(
        (np.shape(l), np.result_type(l).name) for l in leaves)


# GradReduceConfig is a frozen dataclass and the compiled reducer is a
# pure function of (config, mesh, arg structure/shapes), so identical
# keys reuse one executable instead of re-tracing a fresh closure per
# call — results are bit-identical either way.
_JIT_CACHE = {}


def _run_reduce(grads_stack, config, axis_sizes, state=None):
    """Apply reduce_gradients once over a mesh of ``axis_sizes``;
    ``grads_stack`` leaves carry a leading participant dim covering every
    reduction axis.  Returns (reduced, new_state, per_device_reduced)."""
    mesh = device_mesh(axis_sizes)
    n_dev = int(np.prod(list(axis_sizes.values())))
    if state is None:
        grads_like = jax.tree_util.tree_map(lambda a: a[0], grads_stack)
        state = GR.init_state(config, grads_like, n_dev)
    dev_spec = P(tuple(axis_sizes.keys()))

    key = (config, tuple(sorted(axis_sizes.items())),
           _abstract(grads_stack), _abstract(state))
    fn = _JIT_CACHE.get(key)
    if fn is None:
        def body(g, st):
            g_l = jax.tree_util.tree_map(lambda a: a[0], g)
            red, new_st = GR.reduce_gradients(
                g_l, GR.squeeze_state(st), config)
            return (jax.tree_util.tree_map(lambda a: a[None], red),
                    GR.unsqueeze_state(new_st))

        fn = jax.jit(shard_map_fn(body, mesh, in_specs=(dev_spec, dev_spec),
                                  out_specs=(dev_spec, dev_spec)))
        _JIT_CACHE[key] = fn
    red, new_state = fn(grads_stack, state)
    red = jax.tree_util.tree_map(np.asarray, red)
    # the reduced gradient must come back replicated: every participant
    # holds the identical sum
    for leaf in jax.tree_util.tree_leaves(red):
        np.testing.assert_array_equal(leaf, np.broadcast_to(leaf[:1],
                                                            leaf.shape))
    return (jax.tree_util.tree_map(lambda a: a[0], red), new_state, red)


def _grads(n_dev=8, d=64, seed=0):
    rng = np.random.default_rng(seed)
    return {"w": jnp.asarray(rng.normal(size=(n_dev, d)).astype(np.float32)),
            "b": jnp.asarray(rng.normal(size=(n_dev,)).astype(np.float32))}


def _np_topk_contrib(acc, k):
    """One participant's EF top-k contribution: (sent dense, unsent)."""
    order = np.argsort(-np.abs(acc), kind="stable")[:k]
    sent = np.zeros_like(acc)
    sent[order] = acc[order]
    return sent, acc - sent


def test_config_validation():
    with pytest.raises(ValueError, match="mode"):
        GradReduceConfig(mode="fp4")
    with pytest.raises(ValueError, match="density"):
        GradReduceConfig(mode="topk", density=0.0)
    with pytest.raises(ValueError, match="block_size"):
        GradReduceConfig(mode="int8", block_size=0)
    with pytest.raises(ValueError, match="single ICI axis"):
        GradReduceConfig(axis=("a", "b"), dcn_axis="dcn")
    assert GR.reduction_axes(
        GradReduceConfig(axis="data", dcn_axis="dcn")) == ("dcn", "data")
    assert not GR.needs_state(GradReduceConfig())
    assert GR.needs_state(GradReduceConfig(mode="topk"))


def test_exact_matches_sum():
    g = _grads()
    red, state, _ = _run_reduce(g, GradReduceConfig(mode="exact"),
                                {"data": 8})
    assert state == {}
    np.testing.assert_allclose(red["w"], np.asarray(g["w"]).sum(0),
                               atol=1e-5)
    np.testing.assert_allclose(red["b"], np.asarray(g["b"]).sum(),
                               atol=1e-5)


def test_topk_matches_ef_oracle_over_steps():
    """Two reduction steps against a numpy EF-SGD oracle: step 1 sends each
    participant's top-k, step 2's accumulated gradient includes step 1's
    unsent residual."""
    cfg = GradReduceConfig(mode="topk", density=0.125)  # k = 8 of 64
    g1, g2 = _grads(seed=1), _grads(seed=2)
    n_dev, d = 8, 64
    k = GR._topk_k(d, cfg.density)

    red1, state1, _ = _run_reduce(g1, cfg, {"data": 8})
    res_np = np.zeros((n_dev, d), np.float32)
    exp1 = np.zeros(d, np.float32)
    for p in range(n_dev):
        sent, res_np[p] = _np_topk_contrib(np.asarray(g1["w"])[p], k)
        exp1 += sent
    np.testing.assert_allclose(red1["w"], exp1, atol=1e-5)
    np.testing.assert_allclose(np.asarray(state1["ef"]["w"]), res_np,
                               atol=1e-6)
    # scalar leaf: k=1 means the bias is effectively exact every step
    np.testing.assert_allclose(red1["b"], np.asarray(g1["b"]).sum(),
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(state1["ef"]["b"]), 0.0,
                               atol=1e-7)

    red2, state2, _ = _run_reduce(g2, cfg, {"data": 8}, state=state1)
    exp2 = np.zeros(d, np.float32)
    for p in range(n_dev):
        sent, res_np[p] = _np_topk_contrib(
            np.asarray(g2["w"])[p] + res_np[p], k)
        exp2 += sent
    np.testing.assert_allclose(red2["w"], exp2, atol=1e-5)
    np.testing.assert_allclose(np.asarray(state2["ef"]["w"]), res_np,
                               atol=1e-6)


def test_topk_sent_plus_residual_is_lossless():
    """EF bookkeeping invariant: per participant, sent + unsent == the
    accumulated gradient exactly (nothing is dropped, only deferred)."""
    cfg = GradReduceConfig(mode="topk", density=0.1)
    g = _grads(seed=3)
    _, state, per_dev = _run_reduce(g, cfg, {"data": 8})
    # reconstruct each participant's sent part from the oracle and check
    # acc == sent + residual
    k = GR._topk_k(64, cfg.density)
    for p in range(8):
        acc = np.asarray(g["w"])[p]
        sent, _ = _np_topk_contrib(acc, k)
        np.testing.assert_allclose(
            sent + np.asarray(state["ef"]["w"])[p], acc, atol=1e-6)


def test_int8_bounded_error_and_determinism():
    cfg = GradReduceConfig(mode="int8", block_size=16, seed=7)
    g = _grads(seed=4)
    red, state, _ = _run_reduce(g, cfg, {"data": 8})
    exact = np.asarray(g["w"]).sum(0)
    # per participant the stochastic round is off by < 1 quantum
    # (scale = blockmax/127); the summed error is bounded by the sum of
    # the participants' block scales
    scales = (np.abs(np.asarray(g["w"]).reshape(8, -1, 16)).max(axis=2)
              / 127.0)
    bound = np.repeat(scales.sum(0), 16) * (1.0 + 1e-6)
    assert np.all(np.abs(red["w"] - exact) <= bound)
    # key advanced, and the same inputs + same state reproduce bit-identical
    red_again, _, _ = _run_reduce(g, cfg, {"data": 8})
    np.testing.assert_array_equal(red["w"], red_again["w"])
    assert not np.array_equal(np.asarray(state["key"]),
                              np.asarray(GR.init_state(cfg, None, 8)["key"]))


def test_hierarchical_exact_matches_flat():
    cfg = GradReduceConfig(mode="exact", axis="data", dcn_axis="dcn")
    g = _grads(seed=5, d=60)  # 60 does not divide the 4-wide ICI axis: pad
    red, _, _ = _run_reduce(g, cfg, {"dcn": 2, "data": 4})
    np.testing.assert_allclose(red["w"], np.asarray(g["w"]).sum(0),
                               atol=1e-5)


def test_hierarchical_topk_matches_shard_oracle():
    """Hierarchical EF top-k: the DCN hop compresses the ICI-summed shard;
    the oracle reduces each dcn member's 4-device ICI group exactly, then
    applies per-member top-k with shard-domain residuals."""
    cfg = GradReduceConfig(mode="topk", density=0.25, axis="data",
                           dcn_axis="dcn")
    D, I, d = 2, 4, 64
    shard_len = d // I
    k = GR._topk_k(shard_len, cfg.density)
    g1, g2 = _grads(n_dev=D * I, seed=6), _grads(n_dev=D * I, seed=7)

    res = np.zeros((D, d), np.float32)  # per-dcn-member shard residuals

    def oracle(g_np):
        out = np.zeros(d, np.float32)
        for m in range(D):
            ici_sum = g_np[m * I:(m + 1) * I].sum(0)
            for i in range(I):
                sl = slice(i * shard_len, (i + 1) * shard_len)
                acc = ici_sum[sl] + res[m, sl]
                sent, unsent = _np_topk_contrib(acc, k)
                out[sl] += sent
                res[m, sl] = unsent
        return out

    red1, state1, _ = _run_reduce(g1, cfg, {"dcn": 2, "data": 4})
    np.testing.assert_allclose(red1["w"], oracle(np.asarray(g1["w"])),
                               atol=1e-5)
    # the carried residual embeds each device's shard at its own slice
    ef = np.asarray(state1["ef"]["w"]).reshape(D, I, d)
    for m in range(D):
        for i in range(I):
            sl = slice(i * shard_len, (i + 1) * shard_len)
            np.testing.assert_allclose(ef[m, i][sl], res[m, sl], atol=1e-6)
            outside = np.delete(ef[m, i], np.r_[sl])
            np.testing.assert_allclose(outside, 0.0, atol=1e-7)

    red2, _, _ = _run_reduce(g2, cfg, {"dcn": 2, "data": 4}, state=state1)
    np.testing.assert_allclose(red2["w"], oracle(np.asarray(g2["w"])),
                               atol=1e-5)


def test_hierarchical_int8_bounded_error():
    cfg = GradReduceConfig(mode="int8", block_size=8, axis="data",
                           dcn_axis="dcn")
    g = _grads(seed=8)
    red, _, _ = _run_reduce(g, cfg, {"dcn": 2, "data": 4})
    exact = np.asarray(g["w"]).sum(0)
    # only the 2-member DCN hop quantizes (the ICI reduce is exact), so
    # the error is bounded by 2 quanta of the shard block scales; bound
    # loosely by 2 * max|exact ici sum| / 127 per element
    ici = np.asarray(g["w"]).reshape(2, 4, -1).sum(1)
    bound = 2 * np.abs(ici).max() / 127.0 + 1e-6
    assert np.abs(red["w"] - exact).max() <= bound


def test_payload_bytes_accounting():
    like = {"w": np.zeros((1 << 20,), np.float32),
            "b": np.zeros((), np.float32)}
    exact = GR.payload_bytes(like, GradReduceConfig())
    assert exact["dense_bytes"] == exact["compressed_bytes"] == \
        4 * ((1 << 20) + 1)
    topk = GR.payload_bytes(like, GradReduceConfig(mode="topk", density=0.1))
    # floor(k) makes 5x the LOWER bound at density 0.1 (idx + val = 8 B)
    assert topk["compression_ratio"] >= 5.0
    assert topk["compressed_bytes"] == 8 * ((1 << 20) // 10 + 1)
    q = GR.payload_bytes(like, GradReduceConfig(mode="int8", block_size=256))
    assert 3.5 <= q["compression_ratio"] <= 4.0
    hier = GR.payload_bytes(
        like, GradReduceConfig(mode="topk", density=0.1, dcn_axis="dcn"),
        ici_size=4)
    # the compressed hop is the 1/4-sized ICI shard; the exact ICI bytes
    # ride separately
    assert hier["dense_bytes"] == 4 * ((1 << 20) // 4 + 1)
    assert hier["compression_ratio"] >= 5.0
    assert hier["ici_bytes"] > 0


# ------------------------------------------------------------- sgd adoption


def _lr_problem(n=512, d=64, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = (X @ rng.normal(size=d) > 0).astype(np.float64)
    return X, y


def test_sgd_exact_mode_bit_identical():
    """Acceptance: mode='exact' (and config=None) keep the pre-reducer
    lax.psum path bit-for-bit — no behavior change unless opted in."""
    from flink_ml_tpu.models.common.losses import LOSSES
    from flink_ml_tpu.models.common.sgd import SGDConfig, sgd_fit

    X, y = _lr_problem()
    mesh = device_mesh({"data": 8})
    kw = dict(learning_rate=0.5, max_epochs=20, tol=0, global_batch_size=64)
    s0, log0 = sgd_fit(LOSSES["logistic"], X, y, None, SGDConfig(**kw), mesh)
    s1, log1 = sgd_fit(LOSSES["logistic"], X, y, None,
                       SGDConfig(**kw, grad_reduce=GradReduceConfig()), mesh)
    np.testing.assert_array_equal(s0.coefficients, s1.coefficients)
    assert s0.intercept == s1.intercept
    np.testing.assert_array_equal(log0, log1)


def test_sgd_topk_ef_density01_converges_to_dense():
    """Acceptance: EF top-k at density 0.1 lands within 1e-3 of the dense
    loss on a convex logistic problem over the 8-device mesh."""
    from flink_ml_tpu.models.common.losses import LOSSES
    from flink_ml_tpu.models.common.sgd import SGDConfig, sgd_fit

    X, y = _lr_problem()
    mesh = device_mesh({"data": 8})
    kw = dict(learning_rate=0.2, max_epochs=200, tol=0,
              global_batch_size=64)
    _, log_dense = sgd_fit(LOSSES["logistic"], X, y, None, SGDConfig(**kw),
                           mesh)
    state, log_topk = sgd_fit(
        LOSSES["logistic"], X, y, None,
        SGDConfig(**kw, grad_reduce=GradReduceConfig(mode="topk",
                                                     density=0.1)), mesh)
    assert abs(log_dense[-1] - log_topk[-1]) < 1e-3, (
        f"dense {log_dense[-1]} vs topk {log_topk[-1]}")
    assert np.isfinite(state.coefficients).all()


def test_sgd_int8_close_to_dense():
    from flink_ml_tpu.models.common.losses import LOSSES
    from flink_ml_tpu.models.common.sgd import SGDConfig, sgd_fit

    X, y = _lr_problem()
    mesh = device_mesh({"data": 8})
    kw = dict(learning_rate=0.5, max_epochs=40, tol=0, global_batch_size=64)
    _, log_dense = sgd_fit(LOSSES["logistic"], X, y, None, SGDConfig(**kw),
                           mesh)
    _, log_q = sgd_fit(
        LOSSES["logistic"], X, y, None,
        SGDConfig(**kw, grad_reduce=GradReduceConfig(mode="int8",
                                                     block_size=32)), mesh)
    assert abs(log_dense[-1] - log_q[-1]) < 1e-3


def test_sgd_hierarchical_on_hybrid_mesh():
    """The fused fit runs the two-tier reduce on a hybrid mesh: batch
    sharded over dcn x data, compression only on the dcn hop."""
    from flink_ml_tpu.models.common.losses import LOSSES
    from flink_ml_tpu.models.common.sgd import SGDConfig, sgd_fit
    from flink_ml_tpu.parallel import distributed as dist

    X, y = _lr_problem()
    hmesh = dist.hybrid_mesh({"data": 8})
    kw = dict(learning_rate=0.5, max_epochs=40, tol=0, global_batch_size=64)
    _, log_dense = sgd_fit(LOSSES["logistic"], X, y, None, SGDConfig(**kw))
    state, log_h = sgd_fit(
        LOSSES["logistic"], X, y, None,
        SGDConfig(**kw, grad_reduce=GradReduceConfig(
            mode="topk", density=0.1, axis="data", dcn_axis="dcn")), hmesh)
    assert np.isfinite(state.coefficients).all()
    assert log_h[-1] < log_h[0]
    assert abs(log_dense[-1] - log_h[-1]) < 5e-2


def test_sgd_params_matrix_weight_compressed():
    """sgd_fit_params with a (d, C) weight (the softmax family's shape)
    routes through the same compressed update."""
    import jax.numpy as jnp

    from flink_ml_tpu.models.common.sgd import SGDConfig, sgd_fit_params

    rng = np.random.default_rng(3)
    n, d, C = 256, 16, 3
    X = rng.normal(size=(n, d)).astype(np.float32)
    labels = rng.integers(0, C, size=n).astype(np.float64)

    def softmax_loss(scores, yb, wb):
        y = jax.nn.one_hot(yb.astype(jnp.int32), C)
        logp = jax.nn.log_softmax(scores, axis=-1)
        per_row = -jnp.sum(y * logp, axis=-1)
        return jnp.sum(per_row * wb) / jnp.maximum(jnp.sum(wb), 1e-12)

    mesh = device_mesh({"data": 8})
    init = {"w": jnp.zeros((d, C), jnp.float32),
            "b": jnp.zeros((C,), jnp.float32)}
    kw = dict(learning_rate=0.5, max_epochs=30, tol=0, global_batch_size=64)
    p_dense, log_dense = sgd_fit_params(
        softmax_loss, X, labels, None, SGDConfig(**kw), mesh,
        init_params=dict(init))
    p_topk, log_topk = sgd_fit_params(
        softmax_loss, X, labels, None,
        SGDConfig(**kw, grad_reduce=GradReduceConfig(mode="topk",
                                                     density=0.25)),
        mesh, init_params=dict(init))
    assert "_gr" not in p_topk
    assert log_topk[-1] < log_topk[0]
    assert abs(log_dense[-1] - log_topk[-1]) < 5e-2


# -------------------------------------------------------- out-of-core + EF


def _stream_cache(tmp_path, n_seg=3, d=8, seed=7):
    from flink_ml_tpu.data.datacache import DataCacheWriter

    rng = np.random.default_rng(seed)
    true_w = rng.normal(size=(d,))
    cache = str(tmp_path / "cache")
    writer = DataCacheWriter(cache, segment_rows=512)
    for _ in range(n_seg):
        X = rng.normal(size=(512, d)).astype(np.float32)
        writer.append({"features": X,
                       "label": (X @ true_w > 0).astype(np.float32)})
    writer.finish()
    return cache


class _FailAfter:
    """Reader wrapper that dies after N read_batch calls across the run."""

    counter = 0

    def __init__(self, inner, fail_after):
        self._inner = inner
        self._fail_after = fail_after

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def __iter__(self):
        while True:
            _FailAfter.counter += 1
            if _FailAfter.counter > self._fail_after:
                raise RuntimeError("injected mid-epoch failure")
            b = self._inner.read_batch()
            if b is None:
                return
            yield b


def test_outofcore_ef_residual_checkpoint_roundtrip_exact(tmp_path):
    """Acceptance: the EF residual rides the donated scan carry AND the
    mid-epoch checkpoint — crash + resume reproduces the uninterrupted
    compressed run bit-for-bit (impossible if the residual were dropped
    or re-zeroed on restore)."""
    from flink_ml_tpu.data.datacache import DataCacheReader
    from flink_ml_tpu.iteration.checkpoint import CheckpointConfig
    from flink_ml_tpu.models.common.losses import logistic_loss
    from flink_ml_tpu.models.common.sgd import SGDConfig, sgd_fit_outofcore

    cache = _stream_cache(tmp_path)
    cfg = SGDConfig(learning_rate=0.4, max_epochs=4, tol=0.0,
                    grad_reduce=GradReduceConfig(mode="topk", density=0.1))

    def reader():
        return DataCacheReader(cache, batch_rows=256)

    ref_state, ref_log = sgd_fit_outofcore(
        logistic_loss, reader, num_features=8, config=cfg)
    assert ref_state.planned_impl == "dense-stream-reduced"

    ck = CheckpointConfig(str(tmp_path / "ck"), max_to_keep=3)
    _FailAfter.counter = 0
    with pytest.raises(RuntimeError, match="injected"):
        sgd_fit_outofcore(
            logistic_loss, lambda: _FailAfter(reader(), 15),
            num_features=8, config=cfg, cache_decoded=False,
            checkpoint=ck, checkpoint_every_steps=2)
    resumed_state, resumed_log = sgd_fit_outofcore(
        logistic_loss, reader, num_features=8, config=cfg,
        checkpoint=ck, checkpoint_every_steps=2, resume=True)
    np.testing.assert_array_equal(resumed_state.coefficients,
                                  ref_state.coefficients)
    assert resumed_state.intercept == ref_state.intercept
    np.testing.assert_array_equal(resumed_log, ref_log)


def test_outofcore_reduced_chunked_bit_exact_vs_w1(tmp_path):
    """steps_per_dispatch W=1 vs W=8 stay bit-exact with the reducer state
    in the carry (the masked dead steps must freeze the residual too)."""
    from flink_ml_tpu.data.datacache import DataCacheReader
    from flink_ml_tpu.models.common.losses import logistic_loss
    from flink_ml_tpu.models.common.sgd import SGDConfig, sgd_fit_outofcore

    cache = _stream_cache(tmp_path)
    cfg = SGDConfig(learning_rate=0.4, max_epochs=2, tol=0.0,
                    grad_reduce=GradReduceConfig(mode="topk", density=0.1))

    def reader():
        return DataCacheReader(cache, batch_rows=256)

    s1, log1 = sgd_fit_outofcore(logistic_loss, reader, num_features=8,
                                 config=cfg, steps_per_dispatch=1)
    s8, log8 = sgd_fit_outofcore(logistic_loss, reader, num_features=8,
                                 config=cfg, steps_per_dispatch=8)
    np.testing.assert_array_equal(s1.coefficients, s8.coefficients)
    np.testing.assert_array_equal(log1, log8)


def test_outofcore_rejects_compressed_sparse_layouts(tmp_path):
    from flink_ml_tpu.models.common.losses import logistic_loss
    from flink_ml_tpu.models.common.sgd import SGDConfig, sgd_fit_outofcore

    cfg = SGDConfig(grad_reduce=GradReduceConfig(mode="topk"))
    with pytest.raises(ValueError, match="sparse by construction"):
        sgd_fit_outofcore(
            logistic_loss, lambda: iter([]), num_features=8, config=cfg,
            dense_key="fd", indices_key="fi")


# ------------------------------------------------------- widedeep adoption


def test_widedeep_sharded_compressed_matches_exact_at_density_1():
    """density=1.0 sends every entry, so the compressed dp x tp step must
    reproduce the implicit-GSPMD step allclose — a full-model oracle for
    the manual data axis + auto model axis wiring."""
    from flink_ml_tpu.models.recommendation.widedeep import (
        build_sharded_train_step)

    mesh = device_mesh({"data": 4, "model": 2})
    vocab = [16, 12]
    rng = np.random.default_rng(0)
    B = 32
    dense = rng.normal(size=(B, 3)).astype(np.float32)
    cat = (np.stack([rng.integers(0, v, size=B) for v in vocab], 1)
           + np.asarray([0, 16])).astype(np.int32)
    labels = rng.integers(0, 2, size=B).astype(np.float32)
    mask = np.ones(B, np.float32)

    step_e, p_e, _, os_e, shard_e = build_sharded_train_step(
        mesh, 3, vocab, 8, (16, 8))
    batch = shard_e(dense, cat, labels, mask)
    for _ in range(3):
        p_e, os_e, loss_e = step_e(p_e, os_e, *batch)

    step_c, p_c, _, os_c, shard_c, grs = build_sharded_train_step(
        mesh, 3, vocab, 8, (16, 8),
        grad_reduce=GradReduceConfig(mode="topk", density=1.0))
    batch_c = shard_c(dense, cat, labels, mask)
    for _ in range(3):
        p_c, os_c, grs, loss_c = step_c(p_c, os_c, grs, *batch_c)
    np.testing.assert_allclose(float(loss_e), float(loss_c), rtol=1e-5,
                               atol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(p_e)),
                    jax.tree_util.tree_leaves(jax.device_get(p_c))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_widedeep_sharded_topk_trains():
    from flink_ml_tpu.models.recommendation.widedeep import (
        build_sharded_train_step)

    mesh = device_mesh({"data": 4, "model": 2})
    vocab = [16, 12]
    rng = np.random.default_rng(1)
    B = 32
    dense = rng.normal(size=(B, 3)).astype(np.float32)
    cat = (np.stack([rng.integers(0, v, size=B) for v in vocab], 1)
           + np.asarray([0, 16])).astype(np.int32)
    labels = rng.integers(0, 2, size=B).astype(np.float32)
    mask = np.ones(B, np.float32)

    step, p, _, os_, shard, grs = build_sharded_train_step(
        mesh, 3, vocab, 8, (16, 8),
        grad_reduce=GradReduceConfig(mode="topk", density=0.1))
    batch = shard(dense, cat, labels, mask)
    losses = []
    for _ in range(10):
        p, os_, grs, loss = step(p, os_, grs, *batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    # the EF residual is live: after a compressed step some mass is
    # carried instead of applied
    assert any(float(np.abs(np.asarray(leaf)).max()) > 0
               for leaf in jax.tree_util.tree_leaves(
                   jax.device_get(grs)["ef"]))


# ------------------------------------------ r11: buckets / adaptive / overlap


def test_r11_config_validation():
    with pytest.raises(ValueError, match="bucket_count"):
        GradReduceConfig(bucket_count=-1)
    with pytest.raises(ValueError, match="topk-family"):
        GradReduceConfig(mode="int8", adaptive=True)
    with pytest.raises(ValueError, match="ladder rungs"):
        GradReduceConfig(mode="topk", adaptive=True,
                         density_ladder=(0.1, "fp4"))
    with pytest.raises(ValueError, match="not in"):
        GradReduceConfig(mode="topk", adaptive=True,
                         density_ladder=(0.1, 1.5))
    with pytest.raises(ValueError, match="requires adaptive"):
        GradReduceConfig(mode="topk", density_ladder=(0.1,))
    # the exact-mode fence: overlap is ignored, not an error
    assert not GR.wants_overlap(GradReduceConfig(mode="exact", overlap=True))
    assert GR.wants_overlap(GradReduceConfig(mode="topk", overlap=True))
    assert not GR.wants_overlap(None)
    assert GR.effective_ladder(
        GradReduceConfig(mode="topk", density=0.2, adaptive=True)) == \
        (0.05, 0.2, "exact")


def test_bucket_plan_balanced_and_covering():
    like = {"w": np.zeros((1000,), np.float32),
            "b": np.zeros((), np.float32),
            "v": np.zeros((7, 3), np.float32)}
    cfg = GradReduceConfig(mode="topk", bucket_count=8)
    plan = GR.plan_buckets(like, cfg)
    sizes = plan.bucket_sizes
    assert len(sizes) == 8 and sum(sizes) == plan.total == 1022
    assert max(sizes) - min(sizes) <= 1          # size-balanced
    # ranges tile [0, total) exactly, in order
    pos = 0
    for lo, hi in plan.ranges:
        assert lo == pos and hi > lo
        pos = hi
    assert pos == plan.total
    # every bucket knows exactly the leaves it overlaps
    for (lo, hi), leaves in zip(plan.ranges, plan.bucket_leaves):
        for li in leaves:
            assert plan.leaf_offsets[li] < hi and \
                plan.leaf_offsets[li + 1] > lo
    # bucket_count=0 (adaptive-only) degrades to one bucket per leaf
    # (sorted-dict-key leaf order: b (1), v (21), w (1000))
    per_leaf = GR.plan_buckets(like, GradReduceConfig(
        mode="topk", adaptive=True))
    assert per_leaf.ranges == ((0, 1), (1, 22), (22, 1022))


def test_exact_bucketed_bit_identical():
    """Acceptance: exact mode with bucketing enabled is bit-identical to
    the legacy blocking psum path (psum is elementwise — the transport
    cut cannot change a single bit)."""
    g = _grads(seed=9, d=100)
    red0, _, _ = _run_reduce(g, GradReduceConfig(mode="exact"), {"data": 8})
    red1, _, _ = _run_reduce(g, GradReduceConfig(mode="exact",
                                                 bucket_count=4),
                             {"data": 8})
    np.testing.assert_array_equal(red0["w"], red1["w"])
    np.testing.assert_array_equal(red0["b"], red1["b"])


def test_sgd_exact_bucketed_fit_bit_identical():
    """The full-fit A/B of the same fence: an exact bucketed fit equals
    the no-config legacy fit bit-for-bit."""
    from flink_ml_tpu.models.common.losses import LOSSES
    from flink_ml_tpu.models.common.sgd import SGDConfig, sgd_fit

    X, y = _lr_problem()
    mesh = device_mesh({"data": 8})
    kw = dict(learning_rate=0.5, max_epochs=20, tol=0, global_batch_size=64)
    s0, log0 = sgd_fit(LOSSES["logistic"], X, y, None, SGDConfig(**kw), mesh)
    s1, log1 = sgd_fit(
        LOSSES["logistic"], X, y, None,
        SGDConfig(**kw, grad_reduce=GradReduceConfig(
            mode="exact", bucket_count=8, overlap=True)), mesh)
    np.testing.assert_array_equal(s0.coefficients, s1.coefficients)
    assert s0.intercept == s1.intercept
    np.testing.assert_array_equal(log0, log1)


def test_topk_bucketed_ef_lossless():
    """EF bookkeeping invariant survives the bucket transport: summed
    over participants, gradient mass == reduced + carried residual
    (nothing dropped at bucket boundaries, only deferred)."""
    cfg = GradReduceConfig(mode="topk", density=0.1, bucket_count=4)
    g = _grads(seed=10, d=100)
    red, state, _ = _run_reduce(g, cfg, {"data": 8})
    total_grad = np.asarray(g["w"]).sum(0)
    total_res = np.asarray(state["ef"]["w"]).sum(0)
    np.testing.assert_allclose(red["w"] + total_res, total_grad, atol=1e-5)


def test_topk_bucketed_selects_per_bucket():
    """Bucketed top-k selects k per BUCKET: gradient mass concentrated in
    one bucket's span still leaves every other bucket sending its own
    top-k (the SparCML variable-rate posture the planner feeds)."""
    cfg = GradReduceConfig(mode="topk", density=0.25, bucket_count=2)
    w = np.zeros((8, 64), np.float32)
    w[:, :32] = 100.0        # bucket 0 span dominates
    w[:, 32:] = 0.001        # per-leaf topk would never send these
    g = {"w": jnp.asarray(w)}
    red, _, _ = _run_reduce(g, cfg, {"data": 8})
    # bucket 1 (elements 32:64) sent its own top-k despite the tiny values
    assert np.abs(red["w"][32:]).max() > 0


def test_adaptive_rung_follows_residual_ratio():
    """The policy loop: diffuse gradients (top-k residual dominates)
    climb the ladder toward exact; spiky gradients (residual ~ 0)
    descend toward the cheap rung.  Selection only moves at window
    boundaries."""
    cfg = GradReduceConfig(mode="topk", density=0.1, adaptive=True,
                           adaptive_window=2)
    rung0 = GR._initial_rung(cfg)

    # diffuse: random normal at density 0.1 keeps ~90% of the mass unsent
    state = None
    for seed in range(6):
        gi = _grads(seed=100 + seed, d=256)
        _, state, _ = _run_reduce(gi, cfg, {"data": 8}, state=state)
    rung = np.asarray(state["rung"])[0]
    assert rung[1] > rung0            # the dense leaf climbed
    assert int(np.asarray(state["tick"])[0]) == 6

    # spiky: one huge coordinate per participant — top-k captures
    # essentially everything, ratio ~ 0, the leaf descends
    spiky = np.full((8, 256), 1e-6, np.float32)
    spiky[:, 3] = 1e3
    g = {"w": jnp.asarray(spiky), "b": jnp.asarray(np.ones(8, np.float32))}
    state = None
    for _ in range(6):
        _, state, _ = _run_reduce(g, cfg, {"data": 8}, state=state)
    rung = np.asarray(state["rung"])[0]
    assert rung[1] < rung0


def test_adaptive_exact_rung_clears_residual():
    """A leaf pinned at the exact rung reduces exactly AND consumes the
    whole accumulated residual (unsent == 0)."""
    cfg = GradReduceConfig(mode="topk", density=0.1, adaptive=True,
                           density_ladder=("exact",))
    g = _grads(seed=12)
    red, state, _ = _run_reduce(g, cfg, {"data": 8})
    np.testing.assert_allclose(red["w"], np.asarray(g["w"]).sum(0),
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(state["ef"]["w"]), 0.0, atol=1e-7)


def test_adaptive_int8_rung_runs():
    cfg = GradReduceConfig(mode="topk", density=0.1, adaptive=True,
                           density_ladder=("int8",), block_size=16)
    g = _grads(seed=13)
    red, state, _ = _run_reduce(g, cfg, {"data": 8})
    exact = np.asarray(g["w"]).sum(0)
    scales = (np.abs(np.asarray(g["w"]).reshape(8, -1, 16)).max(axis=2)
              / 127.0)
    bound = np.repeat(scales.sum(0), 16) * (1.0 + 1e-6)
    assert np.all(np.abs(red["w"] - exact) <= bound)
    np.testing.assert_allclose(np.asarray(state["ef"]["w"]), 0.0, atol=1e-7)


def test_pipelined_reduce_is_one_step_stale():
    """pipelined_reduce returns the reduction of the PREVIOUS call's
    gradient: call 1 reduces the zeros-initialized pending (a no-op),
    call 2 reduces call 1's gradient."""
    cfg = GradReduceConfig(mode="topk", density=1.0, overlap=True)
    mesh = device_mesh({"data": 8})
    g1, g2 = _grads(seed=14), _grads(seed=15)
    state = GR.init_state(cfg, jax.tree_util.tree_map(lambda a: a[0], g1), 8)
    dev_spec = P("data")

    def body(g, st):
        g_l = jax.tree_util.tree_map(lambda a: a[0], g)
        red, new_st = GR.pipelined_reduce(g_l, GR.squeeze_state(st), cfg)
        return (jax.tree_util.tree_map(lambda a: a[None], red),
                GR.unsqueeze_state(new_st))

    fn = jax.jit(shard_map_fn(body, mesh, in_specs=(dev_spec, dev_spec),
                              out_specs=(dev_spec, dev_spec)))
    red1, state = fn(g1, state)
    np.testing.assert_allclose(np.asarray(red1["w"])[0], 0.0, atol=1e-7)
    red2, state = fn(g2, state)
    np.testing.assert_allclose(np.asarray(red2["w"])[0],
                               np.asarray(g1["w"]).sum(0), atol=1e-5)
    # the pending buffer now carries g2, and drain_pending recovers it
    # (+ the empty residual) exactly
    drain = GR.drain_pending(jax.device_get(state))
    np.testing.assert_allclose(drain["w"], np.asarray(g2["w"]).sum(0),
                               atol=1e-5)


def test_sgd_overlap_topk_converges_to_dense():
    """Acceptance: one-step-stale bucketed EF top-k at density 0.1 lands
    within 1e-3 of the dense loss (the PR 3 tolerance) — the residual
    absorbs the staleness like it absorbs the sparsification."""
    from flink_ml_tpu.models.common.losses import LOSSES
    from flink_ml_tpu.models.common.sgd import SGDConfig, sgd_fit

    X, y = _lr_problem()
    mesh = device_mesh({"data": 8})
    kw = dict(learning_rate=0.2, max_epochs=200, tol=0,
              global_batch_size=64)
    _, log_dense = sgd_fit(LOSSES["logistic"], X, y, None, SGDConfig(**kw),
                           mesh)
    state, log_ov = sgd_fit(
        LOSSES["logistic"], X, y, None,
        SGDConfig(**kw, grad_reduce=GradReduceConfig(
            mode="topk", density=0.1, bucket_count=4, overlap=True)), mesh)
    assert abs(log_dense[-1] - log_ov[-1]) < 1e-3, (
        f"dense {log_dense[-1]} vs overlapped {log_ov[-1]}")
    assert np.isfinite(state.coefficients).all()


def test_outofcore_overlap_adaptive_chunked_bit_exact_vs_w1(tmp_path):
    """W=1 vs W=8 stay bit-exact with the whole r11 state — pending
    buffer, rung/EMA/tick, EF residual — riding the donated carry (the
    masked dead steps must freeze ALL of it)."""
    from flink_ml_tpu.data.datacache import DataCacheReader
    from flink_ml_tpu.models.common.losses import logistic_loss
    from flink_ml_tpu.models.common.sgd import SGDConfig, sgd_fit_outofcore

    cache = _stream_cache(tmp_path)
    cfg = SGDConfig(learning_rate=0.4, max_epochs=2, tol=0.0,
                    grad_reduce=GradReduceConfig(
                        mode="topk", density=0.25, bucket_count=3,
                        overlap=True, adaptive=True, adaptive_window=2))

    def reader():
        return DataCacheReader(cache, batch_rows=256)

    s1, log1 = sgd_fit_outofcore(logistic_loss, reader, num_features=8,
                                 config=cfg, steps_per_dispatch=1)
    s8, log8 = sgd_fit_outofcore(logistic_loss, reader, num_features=8,
                                 config=cfg, steps_per_dispatch=8)
    assert s1.planned_impl == "dense-stream-reduced"
    np.testing.assert_array_equal(s1.coefficients, s8.coefficients)
    np.testing.assert_array_equal(log1, log8)


def test_outofcore_overlap_checkpoint_roundtrip_exact(tmp_path):
    """Crash + resume with overlap + adaptive + buckets reproduces the
    uninterrupted run bit-for-bit: the pending gradient and the policy
    state ride the checkpoint cut, and the fit-end drain applies the
    same mass either way."""
    from flink_ml_tpu.data.datacache import DataCacheReader
    from flink_ml_tpu.iteration.checkpoint import CheckpointConfig
    from flink_ml_tpu.models.common.losses import logistic_loss
    from flink_ml_tpu.models.common.sgd import SGDConfig, sgd_fit_outofcore

    cache = _stream_cache(tmp_path)
    cfg = SGDConfig(learning_rate=0.4, max_epochs=4, tol=0.0,
                    grad_reduce=GradReduceConfig(
                        mode="topk", density=0.25, bucket_count=3,
                        overlap=True, adaptive=True, adaptive_window=3))

    def reader():
        return DataCacheReader(cache, batch_rows=256)

    ref_state, ref_log = sgd_fit_outofcore(
        logistic_loss, reader, num_features=8, config=cfg)

    ck = CheckpointConfig(str(tmp_path / "ck"), max_to_keep=3)
    _FailAfter.counter = 0
    with pytest.raises(RuntimeError, match="injected"):
        sgd_fit_outofcore(
            logistic_loss, lambda: _FailAfter(reader(), 15),
            num_features=8, config=cfg, cache_decoded=False,
            checkpoint=ck, checkpoint_every_steps=2)
    resumed_state, resumed_log = sgd_fit_outofcore(
        logistic_loss, reader, num_features=8, config=cfg,
        checkpoint=ck, checkpoint_every_steps=2, resume=True)
    np.testing.assert_array_equal(resumed_state.coefficients,
                                  ref_state.coefficients)
    assert resumed_state.intercept == ref_state.intercept
    np.testing.assert_array_equal(resumed_log, ref_log)


def test_widedeep_bucketed_density1_matches_exact():
    """Bucketed density-1.0 top-k sends every entry, so the bucket
    transport must still reproduce the implicit-GSPMD step allclose."""
    from flink_ml_tpu.models.recommendation.widedeep import (
        build_sharded_train_step)

    mesh = device_mesh({"data": 4, "model": 2})
    vocab = [16, 12]
    rng = np.random.default_rng(2)
    B = 32
    dense = rng.normal(size=(B, 3)).astype(np.float32)
    cat = (np.stack([rng.integers(0, v, size=B) for v in vocab], 1)
           + np.asarray([0, 16])).astype(np.int32)
    labels = rng.integers(0, 2, size=B).astype(np.float32)
    mask = np.ones(B, np.float32)

    step_e, p_e, _, os_e, shard_e = build_sharded_train_step(
        mesh, 3, vocab, 8, (16, 8))
    batch = shard_e(dense, cat, labels, mask)
    for _ in range(3):
        p_e, os_e, loss_e = step_e(p_e, os_e, *batch)

    step_c, p_c, _, os_c, shard_c, grs = build_sharded_train_step(
        mesh, 3, vocab, 8, (16, 8),
        grad_reduce=GradReduceConfig(mode="topk", density=1.0,
                                     bucket_count=3))
    batch_c = shard_c(dense, cat, labels, mask)
    for _ in range(3):
        p_c, os_c, grs, loss_c = step_c(p_c, os_c, grs, *batch_c)
    np.testing.assert_allclose(float(loss_e), float(loss_c), rtol=1e-5,
                               atol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(p_e)),
                    jax.tree_util.tree_leaves(jax.device_get(p_c))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_widedeep_overlap_adaptive_trains():
    from flink_ml_tpu.models.recommendation.widedeep import (
        build_sharded_train_step)

    mesh = device_mesh({"data": 4, "model": 2})
    vocab = [16, 12]
    rng = np.random.default_rng(3)
    B = 32
    dense = rng.normal(size=(B, 3)).astype(np.float32)
    cat = (np.stack([rng.integers(0, v, size=B) for v in vocab], 1)
           + np.asarray([0, 16])).astype(np.int32)
    labels = rng.integers(0, 2, size=B).astype(np.float32)
    mask = np.ones(B, np.float32)

    step, p, _, os_, shard, grs = build_sharded_train_step(
        mesh, 3, vocab, 8, (16, 8),
        grad_reduce=GradReduceConfig(mode="topk", density=0.1,
                                     bucket_count=2, overlap=True,
                                     adaptive=True, adaptive_window=3))
    batch = shard(dense, cat, labels, mask)
    losses = []
    for _ in range(10):
        p, os_, grs, loss = step(p, os_, grs, *batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert int(np.asarray(jax.device_get(grs)["tick"])[0]) == 10


def test_payload_bytes_fabric_split_and_buckets():
    like = {"w": np.zeros((1 << 20,), np.float32),
            "b": np.zeros((), np.float32)}
    # flat: total == compressed (one fabric)
    flat = GR.payload_bytes(like, GradReduceConfig(mode="topk",
                                                   density=0.1))
    assert flat["total_wire_bytes"] == flat["compressed_bytes"]
    # hierarchical: the two fabrics report separately and total sums them
    hier = GR.payload_bytes(
        like, GradReduceConfig(mode="topk", density=0.1, dcn_axis="dcn"),
        ici_size=4)
    assert hier["dcn_compressed_bytes"] == hier["compressed_bytes"]
    assert hier["dcn_dense_bytes"] == hier["dense_bytes"]
    assert hier["total_wire_bytes"] == \
        hier["ici_bytes"] + hier["dcn_compressed_bytes"]
    assert hier["dcn_compression_ratio"] >= 5.0
    # bucketed accounting follows the transport's per-bucket k
    bucketed = GR.payload_bytes(like, GradReduceConfig(
        mode="topk", density=0.1, bucket_count=8))
    assert bucketed["bucket_count"] == 8
    assert bucketed["compression_ratio"] >= 5.0
    # adaptive with realized rungs: exact rung pays dense bytes
    cfg = GradReduceConfig(mode="topk", density=0.1, adaptive=True)
    cheap = GR.payload_bytes(like, cfg, rungs=[0, 0])
    dear = GR.payload_bytes(like, cfg, rungs=[2, 2])   # "exact" rung
    assert cheap["compressed_bytes"] < dear["compressed_bytes"]
    assert dear["compressed_bytes"] == dear["dense_bytes"]
    rep = GR.bucket_report(like, cfg, rungs=[2, 0])
    per_leaf = {e["leaf"]: e for e in rep["per_leaf"]}
    assert per_leaf[0]["mode"] == "exact"
    assert per_leaf[1]["density"] == 0.025


# ------------------------------------------- wire-protocol tier (ISSUE 16)


def test_wire_protocol_config_and_resolution():
    with pytest.raises(ValueError, match="wire_protocol"):
        GradReduceConfig(wire_protocol="ring")
    with pytest.raises(ValueError, match="int8_accum"):
        GradReduceConfig(int8_accum="fp8")
    with pytest.raises(ValueError, match="dcn_schedule"):
        GradReduceConfig(dcn_schedule="latest")
    # rd / fixed need one hop axis to run the rounds on
    with pytest.raises(ValueError, match="ONE named axis"):
        GradReduceConfig(mode="topk", axis=("a", "b"), wire_protocol="rd")
    with pytest.raises(ValueError, match="ONE named axis"):
        GradReduceConfig(mode="int8", axis=("a", "b"), int8_accum="fixed")
    # auto resolves to rd on a single hop, falls back on multi-axis
    assert GR.resolved_wire_protocol(
        GradReduceConfig(mode="topk", axis="data")) == "rd"
    assert GR.resolved_wire_protocol(
        GradReduceConfig(mode="topk", axis="data", dcn_axis="dcn")) == "rd"
    assert GR.resolved_wire_protocol(
        GradReduceConfig(mode="topk", axis=("a", "b"))) == "allgather"
    assert GR.resolved_wire_protocol(
        GradReduceConfig(mode="topk", wire_protocol="allgather")) \
        == "allgather"
    assert GR.hop_axis(GradReduceConfig(axis="data", dcn_axis="dcn")) \
        == "dcn"
    assert GR.hop_axis(GradReduceConfig(axis="data")) == "data"
    assert GR.hop_axis(GradReduceConfig(axis=("a", "b"))) is None


def test_topk_rd_matches_allgather_protocol():
    """The rd wire protocol changes BYTES, not math: same reduced
    gradient as the legacy all-gather protocol from the same state, and
    only rd carries the fill/union accounting leaves."""
    g = _grads(seed=21)
    cfg_rd = GradReduceConfig(mode="topk", density=0.25)
    cfg_ag = GradReduceConfig(mode="topk", density=0.25,
                              wire_protocol="allgather")
    red_rd, st_rd, _ = _run_reduce(g, cfg_rd, {"data": 8})
    red_ag, st_ag, _ = _run_reduce(g, cfg_ag, {"data": 8})
    np.testing.assert_allclose(red_rd["w"], red_ag["w"], atol=1e-5)
    np.testing.assert_allclose(red_rd["b"], red_ag["b"], atol=1e-5)
    np.testing.assert_allclose(np.asarray(st_rd["ef"]["w"]),
                               np.asarray(st_ag["ef"]["w"]), atol=1e-5)
    assert "fill" in st_rd and "union" in st_rd
    assert "fill" not in st_ag and "union" not in st_ag
    assert st_rd["fill"].shape == (8, 2, GR.FILL_VEC_LEN)


def test_dcn_schedule_earliest_vs_free_bit_identical():
    """The earliest-needed-bucket-first schedule is pure ORDERING — the
    chained run is bit-identical to the unconstrained one, and
    bucket_report exposes which policy a config resolves to."""
    g = _grads(seed=22, d=96)
    kw = dict(mode="topk", density=0.2, bucket_count=3, axis="data",
              dcn_axis="dcn")
    red_e, st_e, _ = _run_reduce(
        g, GradReduceConfig(**kw, dcn_schedule="earliest"),
        {"dcn": 2, "data": 4})
    red_f, st_f, _ = _run_reduce(
        g, GradReduceConfig(**kw, dcn_schedule="free"),
        {"dcn": 2, "data": 4})
    np.testing.assert_array_equal(red_e["w"], red_f["w"])
    np.testing.assert_array_equal(np.asarray(st_e["ef"]["w"]),
                                  np.asarray(st_f["ef"]["w"]))
    like = {"w": np.zeros((96,), np.float32)}
    rep = GR.bucket_report(like, GradReduceConfig(**kw))
    assert rep["schedule"]["policy"] == "earliest"
    assert rep["schedule"]["order"] == [0, 1, 2]
    flat = GR.bucket_report(like, GradReduceConfig(
        mode="topk", density=0.2, bucket_count=3))
    assert flat["schedule"]["policy"] is None
    assert flat["schedule"]["order"] is None


def test_wire_bytes_reduction_acceptance():
    """Acceptance: bytes-on-wire per participant drops >= P/4 (= 2x at
    P=8) vs the all-gather protocol at density 0.01 — analytically AND
    measured from a real run's fill accounting (~P/2 = 4x expected)."""
    like = {"g": np.zeros((4096,), np.float32)}
    cfg = GradReduceConfig(mode="topk", density=0.01, axis="data")
    rep = GR.payload_bytes(like, cfg, hop_size=8)
    w = rep["wire"]
    assert rep["wire_protocol"] == "rd"
    assert w["hop_participants"] == 8 and w["rounds"] == 3
    assert w["allgather_bytes"] == 8 * 40 * 7       # 8B/entry * k * (P-1)
    assert w["reduction_vs_allgather_best"] >= 2.0  # the P/4 floor
    assert w["reduction_vs_allgather_best"] >= 3.9  # ~P/2 expected
    # measured: run the real reducer, feed its fill state back in
    rng = np.random.default_rng(23)
    g = {"g": jnp.asarray(np.tile(
        rng.normal(size=(1, 4096)).astype(np.float32), (8, 1)))}
    _, state, _ = _run_reduce(g, cfg, {"data": 8})
    rep_m = GR.payload_bytes(like, cfg, hop_size=8, fill=state["fill"])
    wm = rep_m["wire"]
    assert wm["rd_bytes_measured"] is not None
    assert wm["reduction_vs_allgather_measured"] >= 2.0
    assert wm["switch_rate_measured"] == 0.0        # stayed sparse
    # fill-in monotone: later rounds carry >= earlier unions
    rounds = wm["fill_rounds_measured"]
    assert len(rounds) == 3 and all(r > 0 for r in rounds)
    # without a fill observation the measured fields are null, never faked
    assert rep["wire"]["rd_bytes_measured"] is None
    assert rep["wire"]["reduction_vs_allgather_measured"] is None


def test_reshard_carries_wire_state_leaves():
    """PR 15 elastic resize routing for the new leaves: ``union`` (a
    replicated statistic) broadcasts participant 0, ``fill`` (per-round
    counts specific to the OLD fleet's round structure) re-seeds to
    zeros at the new size — never refused, never averaged across
    incompatible topologies."""
    g = _grads(seed=24)
    cfg = GradReduceConfig(mode="topk", density=0.25)
    _, state, _ = _run_reduce(g, cfg, {"data": 8})
    assert np.asarray(state["fill"]).any()
    for n_new in (4, 6):
        rs = GR.reshard_state(state, n_new)
        assert rs["fill"].shape == (n_new,) + state["fill"].shape[1:]
        assert not np.asarray(rs["fill"]).any()
        np.testing.assert_array_equal(
            np.asarray(rs["union"]),
            np.broadcast_to(np.asarray(state["union"])[:1],
                            (n_new,) + state["union"].shape[1:]))


def test_int8_fixed_hop_matches_legacy_dequant_envelope():
    """Satellite: quantized_all_reduce's dequantize-then-sum is the
    LEGACY accumulation; the int32-hop mode must agree within the
    quantization envelope (sum of per-participant block quanta) — an
    agreement envelope, NOT bit-equality: the two orders round
    differently by design."""
    g = _grads(seed=25)
    legacy = GradReduceConfig(mode="int8", block_size=16, seed=7)
    fixed = GradReduceConfig(mode="int8", block_size=16, seed=7,
                             int8_accum="fixed")
    red_l, _, _ = _run_reduce(g, legacy, {"data": 8})
    red_f, _, per_dev = _run_reduce(g, fixed, {"data": 8})
    exact = np.asarray(g["w"]).sum(0)
    # fixed-point accumulates in int32 against ONE shared scale, so its
    # error bound is P quanta of the shared (pmax) scale
    shared = np.abs(np.asarray(g["w"]).reshape(8, -1, 16)).max(
        axis=(0, 2)) / 127.0
    bound = np.repeat(shared, 16) * 8 * (1.0 + 1e-6)
    assert np.all(np.abs(red_f["w"] - exact) <= bound)
    assert np.all(np.abs(red_f["w"] - red_l["w"]) <= 2 * bound)
    # the int32 hop is deterministic across participants: bit-identical
    # replicas even before the harness's replication assert
    np.testing.assert_array_equal(per_dev["w"],
                                  np.broadcast_to(per_dev["w"][:1],
                                                  per_dev["w"].shape))


def test_exact_mode_bit_identical_to_legacy_reduce():
    """Tentpole guardrail: exact mode never routes through the wire
    protocol — bit-identical to a raw lax.psum whatever wire_protocol
    says, and it carries no accounting state."""
    from jax import lax

    g = _grads(seed=26)
    mesh = device_mesh({"data": 8})

    def raw(x):
        return lax.psum(x[0], "data")[None]

    fn = shard_map_fn(raw, mesh, in_specs=P("data"), out_specs=P("data"))
    oracle = np.asarray(fn(g["w"]))[0]
    for proto in ("auto", "rd", "allgather"):
        cfg = GradReduceConfig(mode="exact", wire_protocol=proto)
        red, state, _ = _run_reduce(g, cfg, {"data": 8})
        np.testing.assert_array_equal(red["w"], oracle)
        assert state == {}


# ---------------------------------------------------------- hosted iterate


def test_hosted_iterate_carries_reducer_state(tmp_path):
    """A hosted-iterate body using reduce_gradients keeps its reducer
    state in the iterate state pytree: per-epoch checkpoints round-trip
    the residual, so crash + resume equals the uninterrupted run exactly."""
    from flink_ml_tpu.iteration import (
        IterationBodyResult,
        IterationConfig,
        iterate,
    )
    from flink_ml_tpu.iteration.checkpoint import CheckpointConfig

    mesh = device_mesh({"data": 8})
    cfg = GradReduceConfig(mode="topk", density=0.25)
    d = 32
    rng = np.random.default_rng(5)
    data = jnp.asarray(rng.normal(size=(8, d)).astype(np.float32))
    target = jnp.asarray(rng.normal(size=(d,)).astype(np.float32))
    dev_spec = P("data")

    def reduce_fn(w, st, x):
        def body(w, st, x):
            g = {"w": x[0] * (w - target)}
            red, new_st = GR.reduce_gradients(g, GR.squeeze_state(st), cfg)
            return red["w"], GR.unsqueeze_state(new_st)

        return shard_map_fn(body, mesh,
                            in_specs=(P(), dev_spec, P("data", None)),
                            out_specs=(P(), dev_spec))(w, st, x)

    def epoch_body(state, epoch, x):
        w, st = state["w"], state["gr"]
        g, st = reduce_fn(w, st, x)
        return IterationBodyResult({"w": w - 0.05 * g, "gr": st})

    init = {"w": jnp.zeros((d,), jnp.float32),
            "gr": GR.init_state(cfg, {"w": jnp.zeros((d,))}, 8)}
    ck = str(tmp_path / "ck")
    full = iterate(epoch_body, init, data, max_epochs=8,
                   config=IterationConfig(mode="hosted"),
                   checkpoint=CheckpointConfig(ck))
    # resume from the epoch-5 cut and run to 8: must equal the full run
    resumed = iterate(epoch_body, init, data, max_epochs=8,
                      config=IterationConfig(mode="hosted"),
                      checkpoint=CheckpointConfig(ck), resume=True)
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(full.state["w"])),
        np.asarray(jax.device_get(resumed.state["w"])))


def test_hosted_iterate_carries_r11_schedule_state(tmp_path):
    """The r11 reducer state — pending overlap buffer, adaptive
    rung/EMA/tick — is just more pytree leaves in the iterate state:
    per-epoch checkpoints round-trip the whole schedule, so crash +
    resume equals the uninterrupted run exactly (including which rung
    each leaf sits on)."""
    from flink_ml_tpu.iteration import (
        IterationBodyResult,
        IterationConfig,
        iterate,
    )
    from flink_ml_tpu.iteration.checkpoint import CheckpointConfig

    mesh = device_mesh({"data": 8})
    cfg = GradReduceConfig(mode="topk", density=0.25, bucket_count=2,
                           overlap=True, adaptive=True, adaptive_window=3)
    d = 32
    rng = np.random.default_rng(6)
    data = jnp.asarray(rng.normal(size=(8, d)).astype(np.float32))
    target = jnp.asarray(rng.normal(size=(d,)).astype(np.float32))
    dev_spec = P("data")

    def reduce_fn(w, st, x):
        def body(w, st, x):
            g = {"w": x[0] * (w - target)}
            red, new_st = GR.pipelined_reduce(g, GR.squeeze_state(st), cfg)
            return red["w"], GR.unsqueeze_state(new_st)

        return shard_map_fn(body, mesh,
                            in_specs=(P(), dev_spec, P("data", None)),
                            out_specs=(P(), dev_spec))(w, st, x)

    def epoch_body(state, epoch, x):
        w, st = state["w"], state["gr"]
        g, st = reduce_fn(w, st, x)
        return IterationBodyResult({"w": w - 0.05 * g, "gr": st})

    init = {"w": jnp.zeros((d,), jnp.float32),
            "gr": GR.init_state(cfg, {"w": jnp.zeros((d,))}, 8)}
    ck = str(tmp_path / "ck")
    full = iterate(epoch_body, init, data, max_epochs=8,
                   config=IterationConfig(mode="hosted"),
                   checkpoint=CheckpointConfig(ck))
    resumed = iterate(epoch_body, init, data, max_epochs=8,
                      config=IterationConfig(mode="hosted"),
                      checkpoint=CheckpointConfig(ck), resume=True)
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(full.state["w"])),
        np.asarray(jax.device_get(resumed.state["w"])))
    for a, b in zip(
            jax.tree_util.tree_leaves(jax.device_get(full.state["gr"])),
            jax.tree_util.tree_leaves(jax.device_get(resumed.state["gr"]))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert int(np.asarray(jax.device_get(full.state["gr"]["tick"]))[0]) == 8
