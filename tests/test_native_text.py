"""Native batch hasher (native/texthash.cpp) vs the pure-Python FNV-1a."""



import numpy as np
import pytest

from flink_ml_tpu.models.feature.text import _fnv1a
from flink_ml_tpu.utils import native_text


pytestmark = pytest.mark.skipif(not native_text.native_available(),
                                reason="native toolchain unavailable")


def test_fnv1a_batch_bit_identical_to_python():
    strings = ["", "a", "some token", "café ☕", "colname=value",
               "x" * 1000]
    native = native_text.fnv1a_batch(strings)
    expected = np.asarray([_fnv1a(s) for s in strings], np.uint64)
    np.testing.assert_array_equal(native, expected)


def test_hashing_tf_native_matches_python_loop():
    rng = np.random.default_rng(0)
    vocab = [f"tok{i}" for i in range(50)]
    docs = np.empty(20, object)
    for i in range(20):
        docs[i] = list(rng.choice(vocab, size=rng.integers(0, 30)))
    m = 64
    native = native_text.hashing_tf(docs, m, binary=False)
    expected = np.zeros((20, m), np.float64)
    for i, doc in enumerate(docs):
        for tok in doc:
            expected[i, _fnv1a(tok) % m] += 1.0
    np.testing.assert_array_equal(native, expected)

    nb = native_text.hashing_tf(docs, m, binary=True)
    np.testing.assert_array_equal(nb, (expected > 0).astype(np.float64))


def test_hashing_tf_through_stage_uses_native():
    """HashingTF output is identical whichever path runs (the stage picks
    native when available — this asserts the integrated result)."""
    from flink_ml_tpu import Table
    from flink_ml_tpu.models.feature import HashingTF

    docs = np.empty(3, object)
    docs[0] = ["a", "b", "a"]
    docs[1] = []
    docs[2] = ["café", "b"]
    out = (HashingTF().set_num_features(32)
           .transform(Table({"features": docs}))[0])
    mat = np.asarray(out["output"])
    assert mat[0, _fnv1a("a") % 32] == 2.0
    assert mat[1].sum() == 0.0
    assert mat[2, _fnv1a("café") % 32] == 1.0


def test_native_path_engaged_not_fallback():
    """Regression guard against the binding silently falling back: the lib
    loads, the batch entry points return real arrays (None IS the fallback
    signal), and a corpus-scale fill matches the Python loop on a sample.
    Deliberately not a wall-clock gate — timing assertions flake on loaded
    hosts; non-None return is the property that guards the regression."""
    assert native_text.native_available()
    rng = np.random.default_rng(1)
    vocab = [f"token_{i:05d}" for i in range(1000)]
    docs = np.empty(500, object)
    for i in range(500):
        docs[i] = list(rng.choice(vocab, size=100))

    native = native_text.hashing_tf(docs, 1 << 12, binary=False)
    assert native is not None and native.shape == (500, 1 << 12)

    sub = 50
    expected = np.zeros((sub, 1 << 12), np.float64)
    for i in range(sub):
        for tok in docs[i]:
            expected[i, _fnv1a(tok) % (1 << 12)] += 1.0
    np.testing.assert_array_equal(native[:sub], expected)

    hashes = native_text.fnv1a_batch(vocab)
    assert hashes is not None and len(hashes) == len(vocab)
