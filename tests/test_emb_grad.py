"""Statically-routed table-gradient scatter (ops/emb_grad.py) vs the
XLA scatter-add oracle, over both placements (gather inverse-map and
sorted-unique scatter)."""

import numpy as np
import pytest

import jax.numpy as jnp

from flink_ml_tpu.ops.emb_grad import emb_grad_route, routed_table_grad


PLACEMENTS = ("gather", "scatter")


def _oracle(ids, g, num_rows):
    out = np.zeros((num_rows, g.shape[-1]), np.float64)
    np.add.at(out, ids.reshape(-1), g.reshape(-1, g.shape[-1]))
    return out.astype(np.float32)


def _routed(route, s, g_flat):
    arrays = tuple(jnp.asarray(np.asarray(a))
                   for a in route.step_slice(s))
    return np.asarray(route.apply(jnp.asarray(g_flat), *arrays))


@pytest.mark.parametrize("placement", PLACEMENTS)
@pytest.mark.parametrize("emb_dim", [1, 8])
def test_matches_scatter_add_oracle(placement, emb_dim):
    rng = np.random.default_rng(0)
    steps, batch, fields, vocab = 3, 64, 5, 200
    cat = rng.integers(0, vocab, size=(steps, batch, fields), dtype=np.int64)
    route = emb_grad_route(cat, vocab, placement=placement)
    for s in range(steps):
        g = rng.normal(size=(batch * fields, emb_dim)).astype(np.float32)
        got = _routed(route, s, g)
        np.testing.assert_allclose(got, _oracle(cat[s], g, vocab),
                                   rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("placement", PLACEMENTS)
def test_scalar_payload_squeezes(placement):
    rng = np.random.default_rng(1)
    cat = rng.integers(0, 50, size=(1, 32, 4), dtype=np.int64)
    route = emb_grad_route(cat, 50, placement=placement)
    g = rng.normal(size=(32 * 4,)).astype(np.float32)
    got = _routed(route, 0, g)
    assert got.shape == (50,)
    np.testing.assert_allclose(got, _oracle(cat[0], g[:, None], 50)[:, 0],
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("placement", PLACEMENTS)
def test_heavy_run_and_all_unique_edges(placement):
    rng = np.random.default_rng(2)
    batch, fields, vocab = 128, 4, 4096
    # step 0: one id floods half the slots (deep fold); step 1: all
    # distinct ids (the fold must still pass with runs of length 1)
    heavy = rng.integers(0, vocab, size=(batch, fields), dtype=np.int64)
    heavy.reshape(-1)[: batch * fields // 2] = 7
    uniq = np.arange(batch * fields, dtype=np.int64).reshape(batch, fields)
    cat = np.stack([heavy, uniq])
    route = emb_grad_route(cat, vocab, placement=placement)
    assert route.fold_passes >= 8
    for s in range(2):
        g = rng.normal(size=(batch * fields, 3)).astype(np.float32)
        np.testing.assert_allclose(_routed(route, s, g),
                                   _oracle(cat[s], g, vocab),
                                   rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("placement", PLACEMENTS)
def test_all_same_id(placement):
    cat = np.zeros((1, 16, 2), np.int64)
    route = emb_grad_route(cat, 10, placement=placement)
    g = np.ones((32, 2), np.float32)
    got = _routed(route, 0, g)
    expected = np.zeros((10, 2), np.float32)
    expected[0] = 32.0
    np.testing.assert_allclose(got, expected, rtol=1e-6)


def test_placements_agree():
    rng = np.random.default_rng(5)
    cat = rng.integers(0, 300, size=(2, 64, 4), dtype=np.int64)
    g = rng.normal(size=(256, 6)).astype(np.float32)
    outs = [_routed(emb_grad_route(cat, 300, placement=p), 1, g)
            for p in PLACEMENTS]
    np.testing.assert_allclose(outs[0], outs[1], rtol=1e-6, atol=1e-7)


def test_u_cap_pads_and_rejects():
    rng = np.random.default_rng(3)
    cat = rng.integers(0, 30, size=(2, 16, 2), dtype=np.int64)
    need = max(len(np.unique(cat[s])) for s in range(2))
    route = emb_grad_route(cat, 30, u_cap=need + 5, placement="scatter")
    assert route.out_ids.shape[1] == need + 5
    # padded sentinel ids are unique and ascending (the scatter's
    # indices_are_sorted + unique_indices claims must stay true)
    oi = np.asarray(route.out_ids)
    assert all(np.all(np.diff(oi[s]) > 0) for s in range(2))
    g = rng.normal(size=(32, 4)).astype(np.float32)
    np.testing.assert_allclose(_routed(route, 0, g),
                               _oracle(cat[0], g, 30), rtol=1e-5, atol=1e-5)
    with pytest.raises(ValueError, match="u_cap"):
        emb_grad_route(cat, 30, u_cap=need - 1, placement="scatter")


def test_unknown_placement_rejected():
    with pytest.raises(ValueError, match="placement"):
        emb_grad_route(np.zeros((1, 2, 2), np.int64), 10,
                       placement="banana")


@pytest.mark.parametrize("placement", PLACEMENTS)
def test_route_shapes_shared_across_steps(placement):
    rng = np.random.default_rng(4)
    # step 0 has many fewer unique ids than step 1 — shapes must match
    cat0 = rng.integers(0, 4, size=(16, 3), dtype=np.int64)
    cat1 = rng.integers(0, 1000, size=(16, 3), dtype=np.int64)
    route = emb_grad_route(np.stack([cat0, cat1]), 1000,
                           placement=placement)
    a0, a1 = route.step_slice(0), route.step_slice(1)
    assert all(x.shape == y.shape for x, y in zip(a0, a1))
    for s, c in enumerate([cat0, cat1]):
        g = rng.normal(size=(48, 2)).astype(np.float32)
        np.testing.assert_allclose(_routed(route, s, g),
                                   _oracle(c, g, 1000),
                                   rtol=1e-5, atol=1e-5)


def test_direct_scatter_fn_callable():
    """routed_table_grad stays usable standalone (callers outside the
    route object, e.g. future streaming integrations)."""
    rng = np.random.default_rng(6)
    cat = rng.integers(0, 40, size=(1, 8, 3), dtype=np.int64)
    route = emb_grad_route(cat, 40, placement="scatter", device=False)
    g = rng.normal(size=(24, 2)).astype(np.float32)
    out = routed_table_grad(
        jnp.asarray(g), jnp.asarray(route.order[0]),
        jnp.asarray(route.sorted_ids[0]), jnp.asarray(route.out_pos[0]),
        jnp.asarray(route.out_ids[0]), num_rows=40,
        fold_passes=route.fold_passes)
    np.testing.assert_allclose(np.asarray(out), _oracle(cat[0], g, 40),
                               rtol=1e-5, atol=1e-5)


def test_auto_placement_budget():
    """'auto' picks gather until the inverse map outgrows its budget,
    then scatter — and honors u_cap in both."""
    from flink_ml_tpu.ops import emb_grad as eg

    cat = np.random.default_rng(8).integers(
        0, 100, size=(2, 8, 2), dtype=np.int64)
    assert emb_grad_route(cat, 100, placement="auto").placement == "gather"
    old = eg._POS_MAP_BUDGET_BYTES
    eg._POS_MAP_BUDGET_BYTES = 4   # force the fallback
    try:
        r = emb_grad_route(cat, 100, placement="auto")
        assert r.placement == "scatter" and r.pos_map is None
    finally:
        eg._POS_MAP_BUDGET_BYTES = old
    with pytest.raises(ValueError, match="u_cap"):
        emb_grad_route(cat, 100, u_cap=1, placement="gather")
