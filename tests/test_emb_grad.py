"""Statically-routed table-gradient scatter (ops/emb_grad.py) vs the
XLA scatter-add oracle."""

import numpy as np
import pytest

import jax.numpy as jnp

from flink_ml_tpu.ops.emb_grad import emb_grad_route, routed_table_grad


def _oracle(ids, g, num_rows):
    out = np.zeros((num_rows, g.shape[-1]), np.float64)
    np.add.at(out, ids.reshape(-1), g.reshape(-1, g.shape[-1]))
    return out.astype(np.float32)


def _routed(route, s, g_flat):
    o, sid, op, oi = (np.asarray(a) for a in route.step_slice(s))
    return np.asarray(routed_table_grad(
        jnp.asarray(g_flat), jnp.asarray(o), jnp.asarray(sid),
        jnp.asarray(op), jnp.asarray(oi), num_rows=route.num_rows,
        fold_passes=route.fold_passes))


@pytest.mark.parametrize("emb_dim", [1, 8])
def test_matches_scatter_add_oracle(emb_dim):
    rng = np.random.default_rng(0)
    steps, batch, fields, vocab = 3, 64, 5, 200
    cat = rng.integers(0, vocab, size=(steps, batch, fields), dtype=np.int64)
    route = emb_grad_route(cat, vocab)
    for s in range(steps):
        g = rng.normal(size=(batch * fields, emb_dim)).astype(np.float32)
        got = _routed(route, s, g)
        np.testing.assert_allclose(got, _oracle(cat[s], g, vocab),
                                   rtol=1e-5, atol=1e-5)


def test_scalar_payload_squeezes():
    rng = np.random.default_rng(1)
    cat = rng.integers(0, 50, size=(1, 32, 4), dtype=np.int64)
    route = emb_grad_route(cat, 50)
    g = rng.normal(size=(32 * 4,)).astype(np.float32)
    got = _routed(route, 0, g)
    assert got.shape == (50,)
    np.testing.assert_allclose(got, _oracle(cat[0], g[:, None], 50)[:, 0],
                               rtol=1e-5, atol=1e-5)


def test_heavy_run_and_all_unique_edges():
    rng = np.random.default_rng(2)
    batch, fields, vocab = 128, 4, 4096
    # step 0: one id floods half the slots (deep fold); step 1: all
    # distinct ids (the fold must still pass with runs of length 1)
    heavy = rng.integers(0, vocab, size=(batch, fields), dtype=np.int64)
    heavy.reshape(-1)[: batch * fields // 2] = 7
    uniq = np.arange(batch * fields, dtype=np.int64).reshape(batch, fields)
    cat = np.stack([heavy, uniq])
    route = emb_grad_route(cat, vocab)
    assert route.fold_passes >= 8
    for s in range(2):
        g = rng.normal(size=(batch * fields, 3)).astype(np.float32)
        np.testing.assert_allclose(_routed(route, s, g),
                                   _oracle(cat[s], g, vocab),
                                   rtol=1e-4, atol=1e-4)


def test_all_same_id():
    cat = np.zeros((1, 16, 2), np.int64)
    route = emb_grad_route(cat, 10)
    g = np.ones((32, 2), np.float32)
    got = _routed(route, 0, g)
    expected = np.zeros((10, 2), np.float32)
    expected[0] = 32.0
    np.testing.assert_allclose(got, expected, rtol=1e-6)


def test_u_cap_pads_and_rejects():
    rng = np.random.default_rng(3)
    cat = rng.integers(0, 30, size=(2, 16, 2), dtype=np.int64)
    need = max(len(np.unique(cat[s])) for s in range(2))
    route = emb_grad_route(cat, 30, u_cap=need + 5)
    assert route.out_ids.shape[1] == need + 5
    # padded sentinel ids are unique and ascending (the scatter's
    # indices_are_sorted + unique_indices claims must stay true)
    oi = np.asarray(route.out_ids)
    assert all(np.all(np.diff(oi[s]) > 0) for s in range(2))
    g = rng.normal(size=(32, 4)).astype(np.float32)
    np.testing.assert_allclose(_routed(route, 0, g),
                               _oracle(cat[0], g, 30), rtol=1e-5, atol=1e-5)
    with pytest.raises(ValueError, match="u_cap"):
        emb_grad_route(cat, 30, u_cap=need - 1)


def test_route_shapes_shared_across_steps():
    rng = np.random.default_rng(4)
    # step 0 has many fewer unique ids than step 1 — shapes must match
    cat0 = rng.integers(0, 4, size=(16, 3), dtype=np.int64)
    cat1 = rng.integers(0, 1000, size=(16, 3), dtype=np.int64)
    route = emb_grad_route(np.stack([cat0, cat1]), 1000)
    assert route.out_pos.shape == route.out_ids.shape
    for s, c in enumerate([cat0, cat1]):
        g = rng.normal(size=(48, 2)).astype(np.float32)
        np.testing.assert_allclose(_routed(route, s, g),
                                   _oracle(c, g, 1000),
                                   rtol=1e-5, atol=1e-5)
