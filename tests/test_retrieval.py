"""Vector retrieval serving (ISSUE 19): IVF / IVF-PQ index build,
the registry-dispatched fused scan+top-k search, incremental updates
over the delta codec, and the servable/scheduler integration.

What these tests pin down:

- build invariants: padded posting-list row blocks honor the shared
  ELL padding contract, CSR offsets account every live row, loud
  validation errors;
- search correctness: full-probe search EQUALS the float64 brute-force
  oracle; the acceptance operating point (recall@10 >= 0.95 while
  analytically scanning <= 25% of the corpus); pad slots surface as
  neighbor -1 at +inf, never a fake id;
- PQ: the kernel's ADC distances exactly match explicit
  reconstructed-vector distances (encode and LUT agree), and PQ recall
  is high when the corpus is PQ-representable;
- incremental updates: delta insert/delete with swap-remove semantics,
  the old generation untouched (in-flight queries finish on old
  lists), overflow and centroid drift re-anchor, publish adapters
  round-trip params;
- serving: IVFIndex is the first NON-model servable — admission of a
  second same-schema index tenant costs ZERO new lowerings, delta
  publishes swap generations atomically, and the RecallProbe gauge
  rides the tenant's ServingMetrics subtree.
"""

import numpy as np
import pytest

from flink_ml_tpu import Table
from flink_ml_tpu.retrieval import (
    IVFIndex,
    PQConfig,
    RecallProbe,
    exact_neighbors,
    recall_at_k,
)
from flink_ml_tpu.serving import SLO_INTERACTIVE, SharedScheduler

# the ISSUE 19 acceptance operating point
RECALL_FLOOR = 0.95
SCAN_BUDGET = 0.25


# -- fixtures ----------------------------------------------------------------

def _gaussian(n=600, d=32, seed=3):
    return np.random.default_rng(seed).normal(size=(n, d)).astype(np.float32)


def _clustered(n=2048, d=16, nclusters=64, seed=4, spread=0.5):
    """Well-separated modes — the regime IVF's scan budget pays off in."""
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(nclusters, d)).astype(np.float32) * 10.0
    assign = rng.integers(0, nclusters, size=n)
    X = (centers[assign] + rng.normal(size=(n, d)) * spread
         ).astype(np.float32)
    return X


def _queries_near(X, count, seed=5, jitter=0.05):
    rng = np.random.default_rng(seed)
    pick = rng.choice(X.shape[0], size=count, replace=False)
    return (X[pick] + rng.normal(size=(count, X.shape[1])) * jitter
            ).astype(np.float32)


def _pq_friendly(nclusters=16, d=16, seed=6):
    """Core/halo corpus: each cluster holds a TIGHT core of 10 (the true
    top-10 of a near-center query, at ~zero distance) and a wide halo.
    The distance gap dwarfs the PQ quantization distortion, so recall
    measures the kernel, not codebook luck."""
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(nclusters, d)).astype(np.float32) * 10.0
    core = (np.repeat(centers, 10, axis=0)
            + rng.normal(size=(nclusters * 10, d)) * 0.05)
    halo = (np.repeat(centers, 30, axis=0)
            + rng.normal(size=(nclusters * 30, d)) * 1.0)
    X = np.concatenate([core, halo]).astype(np.float32)
    q = (centers[rng.integers(0, nclusters, size=32)]
         + rng.normal(size=(32, d)) * 0.02).astype(np.float32)
    return X, q


# -- build invariants --------------------------------------------------------

def test_build_validation_is_loud():
    X = _gaussian(n=64, d=8)
    with pytest.raises(ValueError, match="nlist"):
        IVFIndex.build(X, nlist=65)
    with pytest.raises(ValueError, match="non-empty"):
        IVFIndex.build(np.zeros((0, 8), np.float32), nlist=1)
    with pytest.raises(ValueError, match="unique"):
        IVFIndex.build(X, nlist=4, ids=np.zeros(64, np.int32))
    with pytest.raises(ValueError, match="non-negative"):
        IVFIndex.build(X, nlist=4, ids=np.arange(64) - 1)
    with pytest.raises(ValueError, match="must divide"):
        IVFIndex.build(X, nlist=4, pq=PQConfig(m=3))
    with pytest.raises(ValueError, match="block"):
        IVFIndex.build(X, nlist=2, block=8)   # a list must overflow 8


def test_build_posting_lists_honor_padding_contract():
    X = _gaussian(n=300, d=16, seed=7)
    idx = IVFIndex.build(X, nlist=8, k=5, seed=1)
    ids2, counts = idx.params["ids"], idx.params["counts"]
    assert idx.block % 8 == 0
    assert ids2.shape == (8, idx.block)
    assert idx.num_vectors == 300 and counts.sum() == 300
    # CSR offsets account every live row; pad slots are -1 with
    # exact-zero vector rows (the maskless pad_rows_to_block contract)
    assert idx.offsets[-1] == 300
    vecs = idx.params["vecs"].reshape(8, idx.block, 16)
    for lst in range(8):
        c = int(counts[lst])
        assert np.all(ids2[lst, :c] >= 0) and np.all(ids2[lst, c:] == -1)
        assert np.all(vecs[lst, c:] == 0.0)
    # every stored id is addressable and round-trips its vector
    sids, svecs = idx.stored_vectors()
    np.testing.assert_array_equal(sids, np.arange(300))
    np.testing.assert_array_equal(svecs, X)


# -- search correctness ------------------------------------------------------

def test_full_probe_search_equals_float64_oracle():
    X = _gaussian(n=500, d=24, seed=8)
    idx = IVFIndex.build(X, nlist=8, k=10, seed=2)
    q = _gaussian(n=20, d=24, seed=9)
    nn, dist = idx.search(q, nprobe=idx.nlist)
    expect = exact_neighbors(q, X, np.arange(500), 10)
    np.testing.assert_array_equal(nn, expect)
    assert nn.dtype == np.int64 and dist.dtype == np.float32
    assert np.all(np.diff(dist, axis=1) >= 0), "distances not ascending"
    # the reported distances ARE squared L2 (f32 expression)
    d2 = np.sum((q[:, None, :] - X[nn]) ** 2, axis=-1)
    np.testing.assert_allclose(dist, d2, rtol=1e-4, atol=1e-3)


def test_acceptance_recall_at_bounded_scan():
    """THE acceptance point: recall@10 >= 0.95 at the reference nprobe
    while the probed lists provably hold <= 25% of the corpus (analytic
    accounting from the CSR counts, not timing)."""
    X = _clustered()
    idx = IVFIndex.build(X, nlist=64, k=10, nprobe=8, seed=3)
    q = _queries_near(X, 48)
    frac = idx.scan_fraction(q)
    assert 0.0 < frac <= SCAN_BUDGET, f"scan fraction {frac}"
    nn, _ = idx.search(q)
    rec = recall_at_k(nn, exact_neighbors(q, X, np.arange(X.shape[0]), 10))
    assert rec >= RECALL_FLOOR, f"recall {rec} at scan fraction {frac}"
    # full probe scans everything, by the same accounting
    assert idx.scan_fraction(q, nprobe=idx.nlist) == pytest.approx(1.0)


def test_short_lists_pad_with_minus_one_never_fake_ids():
    X = _gaussian(n=12, d=8, seed=10)
    idx = IVFIndex.build(X, nlist=4, k=10, nprobe=1, seed=4)
    q = _gaussian(n=6, d=8, seed=11)
    nn, dist = idx.search(q)
    counts = idx.params["counts"]
    assert int(counts.max()) < 10   # every probe sees fewer than k rows
    for row_nn, row_d in zip(nn, dist):
        real = row_nn >= 0
        assert np.all(np.isfinite(row_d[real]))
        assert np.all(np.isinf(row_d[~real]))
        # a -1 slot never precedes a real id (top-k keeps real firsts)
        assert not np.any(np.diff(real.astype(int)) > 0)


def test_pq_adc_distances_match_explicit_reconstruction():
    """The ADC lookup-table scan must equal distances to the explicitly
    reconstructed vectors (centroid + decoded codewords) — encode and
    LUT disagree only through bugs, not quantization."""
    X = _gaussian(n=400, d=32, seed=12)
    idx = IVFIndex.build(X, nlist=4, k=8, pq=PQConfig(m=8, ksub=16),
                         seed=5)
    q = _gaussian(n=10, d=32, seed=13)
    nn, dist = idx.search(q, nprobe=idx.nlist)

    cb_q, cb_s = idx.params["cb_q"], idx.params["cb_s"]
    decoded = cb_q.astype(np.float32) * cb_s[..., None]     # (m, ksub, dsub)
    codes = idx.params["codes"].reshape(idx.nlist, idx.block, -1)
    ids2 = idx.params["ids"]
    recon = {}
    for lst in range(idx.nlist):
        for j in range(int(idx.params["counts"][lst])):
            vid = int(ids2[lst, j])
            parts = [decoded[s, int(codes[lst, j, s])]
                     for s in range(cb_q.shape[0])]
            recon[vid] = (idx.params["centroids"][lst]
                          + np.concatenate(parts))
    for qi in range(q.shape[0]):
        for slot in range(nn.shape[1]):
            vid = int(nn[qi, slot])
            d2 = float(np.sum((q[qi] - recon[vid]) ** 2,
                              dtype=np.float64))
            assert dist[qi, slot] == pytest.approx(d2, rel=1e-4, abs=1e-3)


def test_pq_recall_on_representable_corpus():
    """On the core/halo corpus the true top-10 gap dwarfs quantization
    distortion — the PQ index must clear the same recall floor."""
    X, q = _pq_friendly()
    idx = IVFIndex.build(X, nlist=16, k=10, nprobe=4,
                         pq=PQConfig(m=8, ksub=16), seed=6)
    nn, _ = idx.search(q)
    rec = recall_at_k(nn, exact_neighbors(q, X, np.arange(X.shape[0]), 10))
    assert rec >= RECALL_FLOOR, f"PQ recall {rec}"


def test_search_plan_and_option_views():
    X = _gaussian(n=200, d=16, seed=15)
    idx = IVFIndex.build(X, nlist=8, k=5, seed=7)
    plan = idx.search_plan()
    assert plan.sig == idx.sig() and plan.backend == "xla"  # CPU host
    view = idx.with_options(nprobe=8, k=3)
    assert (view.nprobe, view.k) == (8, 3)
    assert view.params is idx.params           # same lists, new schema
    assert (idx.nprobe, idx.k) != (8, 3)       # the view never mutates
    with pytest.raises(ValueError, match="nprobe"):
        idx.with_options(nprobe=9)
    with pytest.raises(TypeError, match="query"):
        idx.transform(Table({"wrong": X}))


# -- incremental updates -----------------------------------------------------

def test_updated_delta_insert_and_delete_with_swap_remove():
    X = _gaussian(n=160, d=8, seed=16)
    idx = IVFIndex.build(X, nlist=4, k=5, seed=8, drift_threshold=None)
    before = {k: v.copy() for k, v in idx.params.items()}

    new_vecs = _gaussian(n=3, d=8, seed=17) * 0.5
    mode, nxt = idx.updated(inserts=new_vecs, delete_ids=[0, 7])
    assert mode == "delta"
    # the OLD index is untouched — in-flight queries finish on old lists
    for name, arr in before.items():
        np.testing.assert_array_equal(idx.params[name], arr)
    assert nxt.num_vectors == 160 + 3 - 2
    # deleted ids are gone, inserted ids resolve to their vectors
    sids, svecs = nxt.stored_vectors()
    assert 0 not in sids and 7 not in sids
    for off, vid in enumerate(range(160, 163)):
        assert vid in sids
        np.testing.assert_array_equal(
            svecs[np.searchsorted(sids, vid)], new_vecs[off])
    # swap-remove kept lists dense: every live slot < count, pads -1
    ids2, counts = nxt.params["ids"], nxt.params["counts"]
    for lst in range(nxt.nlist):
        c = int(counts[lst])
        assert np.all(ids2[lst, :c] >= 0) and np.all(ids2[lst, c:] == -1)
    # full-probe search over the new index matches the oracle of the
    # surviving corpus (the moved rows' vectors moved with their ids)
    q = _gaussian(n=8, d=8, seed=18)
    nn, _ = nxt.search(q, nprobe=nxt.nlist)
    np.testing.assert_array_equal(
        nn, exact_neighbors(q, svecs, sids, nxt.k))
    with pytest.raises(KeyError, match="delete id"):
        nxt.updated(delete_ids=[0])
    with pytest.raises(ValueError, match="already live"):
        nxt.updated(inserts=new_vecs[:1], insert_ids=[161])


def test_updated_overflow_reanchors_with_full_corpus():
    X = _gaussian(n=40, d=8, seed=19)
    idx = IVFIndex.build(X, nlist=4, k=5, seed=9, list_slack=0,
                         drift_threshold=None)
    # flood one region until some list overflows its block
    target = X[int(np.argmax(np.bincount(
        np.argmin(np.sum((X[:, None, :] - idx.params["centroids"]) ** 2,
                         axis=-1), axis=1))))]
    flood = (target[None, :]
             + _gaussian(n=idx.block + 4, d=8, seed=20) * 0.01)
    mode, nxt = idx.updated(inserts=flood)
    assert mode == "reanchor"
    assert nxt.num_vectors == 40 + idx.block + 4
    sids, svecs = nxt.stored_vectors()
    q = _gaussian(n=4, d=8, seed=21)
    nn, _ = nxt.search(q, nprobe=nxt.nlist)
    np.testing.assert_array_equal(
        nn, exact_neighbors(q, svecs, sids, nxt.k))


def test_updated_drift_reanchors():
    X = _gaussian(n=120, d=8, seed=22)
    idx = IVFIndex.build(X, nlist=4, k=5, seed=10, drift_threshold=1e-6)
    assert idx.centroid_drift() >= 0.0
    shifted = _gaussian(n=6, d=8, seed=23) + 4.0   # off-distribution mass
    mode, nxt = idx.updated(inserts=shifted)
    assert mode == "reanchor"
    assert nxt.num_vectors == 126


def test_publish_adapters_round_trip_index_params():
    from flink_ml_tpu.online.publish import (
        model_with_params,
        params_of_model,
    )

    X = _gaussian(n=120, d=8, seed=24)
    idx = IVFIndex.build(X, nlist=4, k=5, seed=11, drift_threshold=None)
    params = params_of_model(idx)
    assert set(params) == set(idx.params)
    _, nxt = idx.updated(inserts=_gaussian(n=2, d=8, seed=25))
    rebound = model_with_params(idx, params_of_model(nxt))
    assert isinstance(rebound, IVFIndex)
    q = _gaussian(n=6, d=8, seed=26)
    np.testing.assert_array_equal(rebound.search(q)[0], nxt.search(q)[0])
    # the rebound clone serves the new lists; the source is untouched
    assert rebound.params is not idx.params


# -- serving integration -----------------------------------------------------

def _built_pair(seed=27):
    """Two same-shape indexes (block pinned) — the zero-lowerings
    admission fixture."""
    X1, X2 = _gaussian(n=240, d=16, seed=seed), \
        _gaussian(n=240, d=16, seed=seed + 1)
    a = IVFIndex.build(X1, nlist=8, k=5, nprobe=2, seed=1, block=80)
    b = IVFIndex.build(X2, nlist=8, k=5, nprobe=2, seed=2, block=80)
    assert a.sig() == b.sig()
    return a, b


def test_index_tenant_admits_with_zero_new_lowerings():
    """The registry dividend extends to the first NON-model servable:
    index tenant N+1 of a served (nprobe, k, dim, pq) schema warms
    entirely out of the shared jit cache."""
    from jax._src import test_util as jtu

    a, b = _built_pair()
    q = Table({"query": _gaussian(n=16, d=16, seed=29)})
    s = SharedScheduler(max_batch_rows=64, max_wait_ms=0.5,
                        queue_capacity=1024)
    s.add_tenant("idx-a", a, q.take(2), slo=SLO_INTERACTIVE)
    s.start()
    try:
        for n in (1, 2, 16):        # settle lazy one-time work
            s.predict("idx-a", q.take(n))
        ref_b = b.transform(q.take(5))[0]["neighbors"]
        with jtu.count_jit_and_pmap_lowerings() as count:
            s.add_tenant("idx-b", b, q.take(2), slo=SLO_INTERACTIVE)
            out = s.predict("idx-b", q.take(5))
        assert count[0] == 0, (
            f"{count[0]} new lowerings admitting a same-schema index "
            "tenant")
        np.testing.assert_array_equal(out["neighbors"], ref_b)
    finally:
        s.close()


def test_delta_publish_swaps_generations_atomically():
    """Insert-as-delta through the PR 7 codec: the generation advances,
    the swapped lists serve the inserted vector, and the PREVIOUS
    generation's servable still answers with the old lists bit-for-bit
    (in-flight queries finish on what they started on)."""
    from flink_ml_tpu.online import DeltaEncoder

    X = _gaussian(n=240, d=16, seed=30)
    idx = IVFIndex.build(X, nlist=8, k=5, nprobe=8, seed=3,
                         drift_threshold=None)
    q = Table({"query": _gaussian(n=8, d=16, seed=31)})
    s = SharedScheduler(max_batch_rows=64, max_wait_ms=0.5,
                        queue_capacity=1024)
    s.add_tenant("retr", idx, q.take(2), slo=SLO_INTERACTIVE)
    s.start()
    try:
        ref_old = s.predict("retr", q)["neighbors"]
        live0 = s.registry.current("retr")
        old_servable = live0.servable

        # insert the queries themselves: generation 2 MUST return them
        mode, nxt = idx.updated(inserts=np.asarray(q["query"]))
        assert mode == "delta"
        pub = s.delta_publisher("retr")
        enc = DeltaEncoder()
        res1 = pub.apply(enc.encode(1, nxt.params, pub.stats))
        enc.ack()
        assert res1.generation == 2

        got = s.predict("retr", q)["neighbors"]
        np.testing.assert_array_equal(
            np.asarray(got)[:, 0], np.arange(240, 248))
        # the old generation's servable object still serves old bits
        np.testing.assert_array_equal(
            old_servable.predict(q)["neighbors"], ref_old)
        live1 = s.registry.current("retr")
        assert live1.generation > live0.generation
        assert live1.servable is not old_servable
    finally:
        s.close()


def test_recall_probe_rides_tenant_serving_metrics():
    X = _clustered(n=1024, d=16, nclusters=32, seed=32)
    idx = IVFIndex.build(X, nlist=32, k=10, nprobe=32, seed=4)
    q = Table({"query": _queries_near(X, 16, seed=33)})
    s = SharedScheduler(max_batch_rows=64, max_wait_ms=0.5,
                        queue_capacity=1024)
    tenant = s.add_tenant("retr", idx, q.take(2), slo=SLO_INTERACTIVE)
    s.start()
    try:
        out = s.predict("retr", q)
        probe = RecallProbe(idx, sample=1.0)
        assert np.isnan(probe.value)             # absent until sampled
        batch = probe.observe(np.asarray(q["query"]),
                              neighbors=np.asarray(out["neighbors"]))
        # full probe + exact scan of the same corpus: perfect recall
        assert batch == 1.0 and probe.value == 1.0
        assert probe.publish(tenant.metrics) == 1.0
        assert tenant.metrics.recall_probe == 1.0
        snap = tenant.metrics.snapshot()
        key = [k for k in snap if k.endswith("recall_probe")]
        assert key and snap[key[0]] == 1.0
        mean, count = probe.reset()
        assert mean == 1.0 and count == 160 and np.isnan(probe.value)
    finally:
        s.close()


def test_recall_probe_validates_sample():
    X = _gaussian(n=64, d=8, seed=34)
    idx = IVFIndex.build(X, nlist=4, k=5, seed=5)
    with pytest.raises(ValueError, match="sample"):
        RecallProbe(idx, sample=0.0)
    probe = RecallProbe(idx, sample=1e-12, seed=1)
    assert probe.observe(X[:4]) is None          # kept no rows: no score
    assert np.isnan(probe.value)


def test_recall_at_k_scoring_rules():
    found = np.array([[1, 2, -1], [9, 9, 9]])
    expected = np.array([[1, 2, 3], [7, 8, 9]])
    # -1 never counts; duplicates in found count the intersection once
    assert recall_at_k(found, expected) == pytest.approx((2 + 1) / 6)
    assert recall_at_k(np.zeros((0, 3)), np.zeros((0, 3))) == 1.0
    with pytest.raises(ValueError, match="matching n"):
        recall_at_k(found, expected[:1])
    # exact_neighbors pads beyond the corpus with -1
    out = exact_neighbors(np.zeros((2, 4)), np.zeros((1, 4)),
                          np.array([5]), k=3)
    np.testing.assert_array_equal(out, [[5, -1, -1], [5, -1, -1]])
