"""Hand-written example stages for pipeline tests.

Analog of the reference's ``ExampleStages.java`` (SumEstimator / SumModel
used by ``PipelineTest.java``): a trivial estimator that sums an input column
into model data, and a model that adds that sum to every row.
"""

from typing import List

import numpy as np

from flink_ml_tpu import Estimator, Model, Table, Transformer
from flink_ml_tpu.params.param import IntParam
from flink_ml_tpu.utils import persist


class SumModel(Model):
    """Adds the learned (or provided) delta to column 'x'."""

    DELTA = IntParam("delta", "Value added to inputs", default=0)

    def __init__(self):
        super().__init__()

    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        delta = self.get(SumModel.DELTA)
        return [table.with_column("x", table["x"] + delta)]

    def set_model_data(self, *inputs) -> "SumModel":
        (table,) = inputs
        self.set(SumModel.DELTA, int(table["delta"][0]))
        return self

    def get_model_data(self) -> List[Table]:
        return [Table({"delta": np.array([self.get(SumModel.DELTA)])})]

    def save(self, path: str) -> None:
        persist.save_metadata(self, path)
        persist.save_model_arrays(
            path, "model", {"delta": np.array([self.get(SumModel.DELTA)])})

    @classmethod
    def load(cls, path: str) -> "SumModel":
        model = persist.load_stage_param(path)
        data = persist.load_model_arrays(path, "model")
        model.set(SumModel.DELTA, int(data["delta"][0]))
        return model


class SumEstimator(Estimator[SumModel]):
    """fit() sums column 'x' over all rows into the model delta."""

    def fit(self, *inputs) -> SumModel:
        (table,) = inputs
        model = SumModel()
        model.set(SumModel.DELTA, int(np.sum(table["x"])))
        return model


class PlusOne(Transformer):
    def transform(self, *inputs) -> List[Table]:
        (table,) = inputs
        return [table.with_column("x", table["x"] + 1)]
