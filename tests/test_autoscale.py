"""Autoscaling control plane tests (ISSUE 17): the versioned placement
store (atomic tmp -> os.replace publish, generation CAS, restart
reconciliation, capacity validation), typed signal frames over the
metrics tree (per-class depths, windowed shed rates, NaN-neutral
degradation), the hysteresis unit matrix (deadband holds under
oscillating p99; a publish storm of 30 generations causes ZERO
placement churn; min-dwell bounds decisions/minute), the
injectable-clock regression (dwell timers + decision latency all on one
fake clock), controller actuation into the scheduler + elastic
coordinator, and the compressed 24h diurnal replay acceptance test —
interactive p99 holds while learner staleness stays bounded, every
decision a tracer instant."""

import json
import math
import os

import numpy as np
import pytest

from flink_ml_tpu import Table
from flink_ml_tpu.autoscale import (
    DECISION_HOLD,
    DECISION_SCALE_SERVING,
    DECISION_YIELD_TO_TRAINING,
    AutoscaleController,
    AutoscalePolicy,
    PlacementConflict,
    PlacementMap,
    PlacementStore,
    PolicyConfig,
    SignalFrame,
    SignalSource,
)
from flink_ml_tpu.obs.tree import MetricsTree, default_tree, prometheus_text
from flink_ml_tpu.parallel.elastic import ElasticCoordinator
from flink_ml_tpu.serving import ModelRegistry, SharedScheduler
from flink_ml_tpu.serving.scheduler import (
    SLO_BULK,
    SLO_CLASSES,
    SLO_INTERACTIVE,
)


# -- fixtures ----------------------------------------------------------------

class FakeClock:
    """One injectable clock for sampler + policy + controller + store +
    scheduler busy accounting — advancing it moves every timer
    coherently (the clock-domain satellite)."""

    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


def _frame(p99=float("nan"), idle=float("nan"), at=0.0, qd_inter=0.0,
           shed_inter=0.0, staleness=float("nan")):
    return SignalFrame(
        at=at, tenants={}, interactive_p99_ms=p99,
        queue_depth={"interactive": qd_inter, "standard": 0.0,
                     "bulk": 0.0},
        shed_rate={"interactive": shed_inter, "standard": 0.0,
                   "bulk": 0.0},
        chip_idle_fraction=idle, staleness_s=staleness,
        learner_staleness_s=staleness, fleet_size=0, membership_epoch=0,
        max_generation=float("nan"))


def _config(**kw):
    kw.setdefault("p99_target_ms", 50.0)
    kw.setdefault("total_chips", 8)
    kw.setdefault("chips_per_worker", 1)
    kw.setdefault("min_dwell_s", 10.0)
    kw.setdefault("min_serving_chips", 1)
    return PolicyConfig(**kw)


class _StubServable:
    """Queue-mechanics stub (the test_scheduler idiom): echoes input,
    always ready.  ``busy_s_per_row`` advances an injected clock inside
    predict, so device-busy time — and therefore the scheduler's
    chip_idle_fraction — is a deterministic function of served rows."""

    busy_clock = None
    busy_s_per_row = 0.0
    ready = True
    warmup_report = None

    def __init__(self, model, example, **kwargs):
        self.max_batch_rows = kwargs.get("max_batch_rows", 256)
        self.output_cols = None

    def warm_up(self):
        return self

    def check_schema(self, table):
        pass

    def bucket_for(self, rows):
        return max(8, rows)

    def predict(self, table):
        if _StubServable.busy_clock is not None:
            _StubServable.busy_clock.advance(
                _StubServable.busy_s_per_row * table.num_rows)
        return table


@pytest.fixture
def stub_busy():
    yield
    _StubServable.busy_clock = None
    _StubServable.busy_s_per_row = 0.0


def _stub_scheduler(**kwargs):
    return SharedScheduler(ModelRegistry(servable_factory=_StubServable),
                           **kwargs)


def _feats(n=8, seed=1):
    rng = np.random.default_rng(seed)
    return Table({"features": rng.normal(size=(n, 4))})


def _drain(scheduler):
    """Inline pick->dispatch until empty (deterministic, no thread)."""
    batches = 0
    while True:
        formed = scheduler._next_batch(timeout=0.0)
        if formed is None:
            return batches
        scheduler._dispatch(*formed)
        batches += 1


# -- placement store ---------------------------------------------------------

def test_placement_publish_bumps_generation_and_is_durable(tmp_path):
    path = str(tmp_path / "placement.json")
    store = PlacementStore(8, chips_per_worker=2, path=path,
                           clock=FakeClock(5.0))
    assert store.generation == 0
    pmap = store.publish({"a": [0, 1], "b": [1, 2, 3]}, 2)
    assert pmap.generation == 1
    assert pmap.serving_chips() == (0, 1, 2, 3)
    assert pmap.chips_for("a") == (0, 1)
    assert pmap.published_at == 5.0
    # durable through the commit protocol: real file, no tmp debris
    assert os.path.exists(path)
    assert not os.path.exists(path + ".tmp")
    on_disk = PlacementMap.from_dict(json.loads(
        open(path).read()))
    assert on_disk == pmap
    # reads are the live reference
    assert store.current() is pmap


def test_placement_validation_rejects_bad_maps():
    store = PlacementStore(4, chips_per_worker=1)
    with pytest.raises(ValueError, match="outside the pool"):
        store.publish({"a": [0, 4]}, 0)
    with pytest.raises(ValueError, match="repeats a chip"):
        store.publish({"a": [1, 1]}, 0)
    with pytest.raises(ValueError, match="overcommits"):
        store.publish({"a": [0, 1, 2]}, 2)   # 3 serving + 2 learner > 4
    with pytest.raises(ValueError, match="learner_workers"):
        store.publish({}, -1)
    # tenants MAY overlap each other (PR 14 shared-device posture)
    pmap = store.publish({"a": [0, 1], "b": [0, 1]}, 2)
    assert pmap.serving_chips() == (0, 1)


def test_placement_conditional_publish_conflicts():
    store = PlacementStore(4)
    store.publish({"a": [0]}, 1)
    with pytest.raises(PlacementConflict):
        store.publish({"a": [0, 1]}, 1, expected_generation=0)
    # un-conditional publish still wins
    assert store.publish({"a": [0, 1]}, 1).generation == 2


def test_placement_load_reconciles_newer_disk_map(tmp_path):
    """The crash-between-write-and-swap window: disk ahead of memory is
    adopted at restart; disk behind is ignored."""
    path = str(tmp_path / "placement.json")
    writer = PlacementStore(8, path=path)
    writer.publish({"a": [0, 1]}, 3)
    writer.publish({"a": [0, 1, 2]}, 2)
    fresh = PlacementStore(8, path=path)
    adopted = fresh.load()
    assert adopted is not None and adopted.generation == 2
    assert fresh.current().learner_workers == 2
    assert fresh.load() is None          # nothing newer now
    assert PlacementStore(8).load() is None   # no path configured


# -- signals -----------------------------------------------------------------

def _fake_tree(sched=None, elastic=None):
    tree = MetricsTree()
    if sched is not None:
        tree.register("scheduler", sched)     # dict: captured by ref
    if elastic is not None:
        tree.register("elastic", elastic)
    return tree


def test_signals_frame_from_tree_with_windowed_shed_rates():
    clock = FakeClock()
    sched = {
        "tenants.inter.slo": "interactive",
        "tenants.inter.latency_p99_ms": 12.5,
        "tenants.inter.queue_depth": 3,
        "tenants.inter.shed": 0,
        "tenants.inter.model_staleness_seconds": float("nan"),
        "tenants.inter.model_generation": 4,
        "tenants.bulk.slo": "bulk",
        "tenants.bulk.latency_p99_ms": 80.0,
        "tenants.bulk.shed": 10,
        "tenants.bulk.model_staleness_seconds": 7.5,
        "queue_depth_interactive": 3,
        "queue_depth_standard": 0,
        "queue_depth_bulk": 9,
        "shed_interactive": 0,
        "shed_standard": 0,
        "shed_bulk": 10,
        "chip_idle_fraction": 0.25,
    }
    source = SignalSource(_fake_tree(sched, {"fleet_size": 3,
                                             "membership_epoch": 7}),
                          clock=clock)
    f1 = source.sample()
    assert f1.interactive_p99_ms == 12.5     # bulk's 80ms is NOT the slo p99
    assert f1.queue_depth["bulk"] == 9
    assert f1.chip_idle_fraction == 0.25
    assert f1.fleet_size == 3 and f1.membership_epoch == 7
    assert f1.staleness_s == 7.5
    assert f1.max_generation == 4
    assert f1.tenants["inter"].slo == "interactive"
    # first sample has no window: rates are 0, never garbage
    assert f1.shed_rate["bulk"] == 0.0
    # 20 more bulk sheds over 10 fake seconds -> 2/s, windowed
    sched["shed_bulk"] = 30
    sched["tenants.bulk.shed"] = 30
    clock.advance(10.0)
    f2 = source.sample()
    assert f2.at == 10.0
    assert f2.shed_rate["bulk"] == pytest.approx(2.0)
    assert f2.tenants["bulk"].shed_rate_per_s == pytest.approx(2.0)
    assert f2.shed_rate["interactive"] == 0.0


def test_signals_missing_surfaces_degrade_to_neutral():
    source = SignalSource(_fake_tree(), clock=FakeClock())
    frame = source.sample()
    assert frame.tenants == {}
    assert math.isnan(frame.interactive_p99_ms)
    assert math.isnan(frame.chip_idle_fraction)
    assert math.isnan(frame.staleness_s)
    assert frame.fleet_size == 0
    assert all(frame.queue_depth[slo] == 0.0 for slo in SLO_CLASSES)


# -- hysteresis unit matrix --------------------------------------------------

def test_deadband_holds_under_oscillating_p99():
    """p99 bouncing anywhere inside (low, high) watermarks — noisy
    quantiles, GC hiccups — produces ZERO actuations."""
    clock = FakeClock()
    policy = AutoscalePolicy(_config(high_frac=0.9, low_frac=0.5),
                             clock=clock)
    rng = np.random.default_rng(0)
    for i in range(200):
        # oscillate across (25, 45) ms, strictly inside the deadband
        p99 = 25.1 + 19.8 * rng.random()
        d = policy.decide(_frame(p99=p99, idle=0.9, at=float(i)),
                          learner_workers=2)
        assert d.kind == DECISION_HOLD, (i, p99, d.reason)
    assert policy.actuations == 0
    assert policy.holds == 200


def test_min_dwell_bounds_decisions_per_minute():
    """Signals demand movement EVERY second; min_dwell_s=10 caps
    actuations at ceil(60/10 + 1) over a minute — the decisions/minute
    bound is structural, not probabilistic."""
    policy = AutoscalePolicy(_config(min_dwell_s=10.0, total_chips=64),
                             clock=FakeClock())
    actuated = []
    workers = 32
    for second in range(60):
        d = policy.decide(_frame(p99=200.0, at=float(second)),
                          learner_workers=workers)
        if d.actuates:
            workers = d.learner_workers
            actuated.append(second)
    assert len(actuated) <= 7            # 60s / 10s dwell (+ the t=0 one)
    assert actuated[:2] == [0, 10]       # dwell gates exactly
    for a, b in zip(actuated, actuated[1:]):
        assert b - a >= 10


def test_publish_storm_of_30_generations_causes_zero_placement_churn():
    """30 back-to-back model generations land between controller ticks
    while every pressure signal sits in the deadband: the placement map
    must not move — decisions are a function of load, never of publish
    counters (the policy cannot even see the generation except as a
    trace-correlation field)."""
    clock = FakeClock()
    sched = {
        "tenants.svc.slo": "interactive",
        "tenants.svc.latency_p99_ms": 30.0,      # mid-deadband
        "tenants.svc.model_generation": 0,
        "queue_depth_interactive": 0,
        "chip_idle_fraction": 0.2,               # below idle_high too
    }
    store = PlacementStore(8)
    store.publish({"svc": [0, 1, 2, 3]}, 4)
    base_generation = store.generation
    controller = AutoscaleController.build(
        _fake_tree(sched), store=store, policy_config=_config(),
        clock=clock)
    for generation in range(1, 31):
        sched["tenants.svc.model_generation"] = generation
        clock.advance(1.0)
        d = controller.tick()
        assert d.kind == DECISION_HOLD
    assert store.generation == base_generation   # ZERO churn
    assert controller.actuations == 0
    assert controller.policy.actuations == 0


def test_policy_respects_floors_and_ceilings():
    policy = AutoscalePolicy(
        _config(min_learner_workers=1, min_serving_chips=4,
                total_chips=8), clock=FakeClock())
    # pressure, but the learner is at its floor: hold, say why
    d = policy.decide(_frame(p99=200.0, at=0.0), learner_workers=1)
    assert d.kind == DECISION_HOLD and "floor" in d.reason
    # trough, but the learner is at its ceiling (serving floor): hold
    d = policy.decide(_frame(p99=1.0, idle=0.95, at=100.0),
                      learner_workers=4)
    assert d.kind == DECISION_HOLD and "ceiling" in d.reason
    # NaN everything: cold control plane holds
    d = policy.decide(_frame(at=200.0), learner_workers=2)
    assert d.kind == DECISION_HOLD
    assert policy.actuations == 0


def test_policy_config_validation():
    with pytest.raises(ValueError, match="deadband"):
        _config(high_frac=0.4, low_frac=0.5)
    with pytest.raises(ValueError, match="p99_target_ms"):
        _config(p99_target_ms=0.0)
    with pytest.raises(ValueError, match="overcommit"):
        _config(total_chips=4, min_serving_chips=3,
                min_learner_workers=2)


# -- clock injection ---------------------------------------------------------

def test_controller_clock_injectable_end_to_end():
    """The PR 5 CheckpointManager pattern, regression-tested: ONE fake
    clock drives sampler stamps, policy dwell, and the decision-latency
    gauge — wall time never leaks in.  Dwell expiry is visible purely
    by advancing the fake clock."""
    clock = FakeClock()
    sched = {"tenants.a.slo": "interactive",
             "tenants.a.latency_p99_ms": 500.0,
             "queue_depth_interactive": 0,
             "chip_idle_fraction": 0.0}
    store = PlacementStore(8)
    store.publish({"a": [0, 1, 2, 3]}, 4)
    controller = AutoscaleController.build(
        _fake_tree(sched), store=store,
        policy_config=_config(min_dwell_s=10.0), clock=clock)
    d1 = controller.tick()
    assert d1.kind == DECISION_SCALE_SERVING
    assert d1.at == 0.0                       # frame stamped by the fake
    # latency measured on the SAME clock: no advance -> exactly zero
    # (a wall-clock leak would read > 0 here)
    assert controller.last_decision_latency_s == 0.0
    clock.advance(5.0)
    assert controller.tick().kind == DECISION_HOLD       # inside dwell
    assert "min-dwell" in controller.policy.last_reason
    clock.advance(6.0)                                   # t=11 > dwell
    assert controller.tick().kind == DECISION_SCALE_SERVING
    assert store.current().learner_workers == 2


# -- controller actuation ----------------------------------------------------

def test_controller_actuates_scheduler_and_elastic(stub_busy):
    """An actuating decision publishes the next placement generation and
    moves BOTH actuators: scheduler WFQ weights rescale to chip counts
    (placement_generation gauge tracks), and the elastic coordinator
    applies the resize at its next chunk boundary through the same
    register/preempt path as injected churn.  Every decision is a
    tracer instant."""
    from flink_ml_tpu.obs import trace as trace_mod

    clock = FakeClock()
    scheduler = _stub_scheduler()
    feats = _feats()
    scheduler.add_tenant("inter", object(), feats.take(2),
                         slo=SLO_INTERACTIVE, weight=2.0)
    scheduler.add_tenant("bulk", object(), feats.take(2), slo=SLO_BULK)
    coord = ElasticCoordinator(chips_per_worker=1, initial_workers=4,
                               min_workers=1, clock=clock)
    sched_signals = {"tenants.inter.slo": "interactive",
                     "tenants.inter.latency_p99_ms": 500.0,
                     "chip_idle_fraction": 0.0}
    store = PlacementStore(8)
    store.publish({"inter": [0, 1, 2, 3], "bulk": [0, 1, 2, 3]}, 4)
    controller = AutoscaleController.build(
        _fake_tree(sched_signals), store=store, scheduler=scheduler,
        elastic=coord, policy_config=_config(), clock=clock)
    trace_mod.tracer.enable()
    try:
        d = controller.tick()
    finally:
        instants = [s for s in trace_mod.tracer.spans()
                    if s.name == "autoscale_decision"]
        trace_mod.tracer.disable()
        trace_mod.tracer.clear()
    assert d.kind == DECISION_SCALE_SERVING
    assert store.generation == 2
    pmap = store.current()
    assert pmap.learner_workers == 3
    assert pmap.serving_chips() == (0, 1, 2, 3, 4)
    # scheduler actuation: weight = base * chips, generation gauge set
    assert scheduler.tenant("inter").weight == 2.0 * 5
    assert scheduler.tenant("bulk").weight == 1.0 * 5
    snap = scheduler.snapshot()
    assert snap["placement_generation"] == 2
    # elastic actuation: applied at the NEXT boundary, same seam
    assert coord.fleet_size == 4
    coord.poll()
    assert coord.fleet_size == 3
    assert coord.counters["preemptions"] == 1
    assert coord.counters["controller_requests"] == 1
    # the decision is visible as a tracer instant with its reason
    assert len(instants) == 1
    assert instants[0].ids["x_kind"] == DECISION_SCALE_SERVING
    assert "p99" in instants[0].ids["x_reason"]


def test_controller_conflict_skips_actuation():
    """A racing placement writer between sample and publish: the tick
    counts a conflict and does NOT actuate a stale edit."""
    clock = FakeClock()
    sched = {"tenants.a.slo": "interactive",
             "tenants.a.latency_p99_ms": 500.0}
    store = PlacementStore(8)
    store.publish({"a": [0, 1, 2, 3]}, 4)

    class RacingPolicy(AutoscalePolicy):
        # the race lands AFTER the tick captured its base generation
        # (sample + capture are done by the time decide runs)
        def decide(self, frame, *, learner_workers):
            store.publish({"a": [0, 1, 2, 3]}, 4)
            return super().decide(frame, learner_workers=learner_workers)

    controller = AutoscaleController(
        store=store, policy=RacingPolicy(_config(), clock=clock),
        signals=SignalSource(_fake_tree(sched), clock=clock),
        clock=clock)
    generation = store.generation
    controller.tick()
    assert controller.conflicts == 1
    assert controller.actuations == 0
    assert store.generation == generation + 1   # only the racer's write


# -- obs round-trip ----------------------------------------------------------

def test_scheduler_class_depth_and_idle_gauges_round_trip(stub_busy):
    """The ISSUE 17 obs satellite: per-SLO-class queue depth gauges and
    chip_idle_fraction survive snapshot -> prometheus round-trip; idle
    is NaN (absent in prometheus) before the first window, then a real
    windowed fraction on the injected busy clock."""
    clock = FakeClock()
    _StubServable.busy_clock = clock
    _StubServable.busy_s_per_row = 0.1
    scheduler = _stub_scheduler(max_batch_rows=8, max_wait_ms=0.0,
                                busy_clock=clock)
    feats = _feats()
    scheduler.add_tenant("inter", object(), feats.take(2),
                         slo=SLO_INTERACTIVE)
    scheduler.add_tenant("bulk", object(), feats.take(2), slo=SLO_BULK)
    snap = scheduler.snapshot()
    assert math.isnan(snap["chip_idle_fraction"])   # no window yet
    text = prometheus_text({"scheduler": snap})
    assert "chip_idle_fraction" not in text          # NaN = absent
    assert "queue_depth_interactive 0" in text
    # queue 3 interactive + 1 bulk requests, sample while queued
    for _ in range(3):
        scheduler.submit("inter", feats.take(4))
    scheduler.submit("bulk", feats.take(4))
    snap = scheduler.snapshot()
    assert snap["queue_depth_interactive"] == 3
    assert snap["queue_depth_bulk"] == 1
    assert snap["tenants.inter.slo"] == "interactive"
    # serve 16 rows (1.6 busy s) inside a 10 s window -> idle 0.84
    _drain(scheduler)
    clock.advance(10.0 - 1.6)
    snap = scheduler.snapshot()
    assert snap["chip_idle_fraction"] == pytest.approx(0.84)
    assert snap["queue_depth_interactive"] == 0
    text = prometheus_text({"scheduler": snap})
    assert "flink_ml_tpu_scheduler_chip_idle_fraction 0.84" in text
    assert "flink_ml_tpu_scheduler_queue_depth_bulk 0" in text
    # the signal layer reads the same names back out
    frame = SignalSource(_fake_tree(scheduler.snapshot()),
                         clock=FakeClock()).sample()
    assert frame.chip_idle_fraction == pytest.approx(0.84)


# -- the acceptance replay ---------------------------------------------------

def test_compressed_diurnal_replay_holds_p99_and_bounds_staleness(
        stub_busy):
    """The ISSUE 17 acceptance scenario at CPU smoke scale: a compressed
    24h diurnal day against a REAL SharedScheduler + ElasticCoordinator
    + PlacementStore wired through one controller on one fake clock.
    Peak traffic preempts the learner down to serving's benefit;
    the trough hands chips back.  Asserts: interactive p99 holds inside
    the PR 14 envelope with ZERO interactive sheds, the learner's
    staleness stays bounded (it keeps capacity often enough to publish),
    the coordinator's fleet converges to every published placement, and
    EVERY tick is a tracer instant."""
    from flink_ml_tpu.obs import trace as trace_mod

    clock = FakeClock()
    dt = 900.0                       # one tick per compressed 15 min
    _StubServable.busy_clock = clock
    _StubServable.busy_s_per_row = 0.9
    scheduler = _stub_scheduler(max_batch_rows=64, max_wait_ms=0.0,
                                busy_clock=clock)
    feats = _feats(64)
    scheduler.add_tenant("inter", object(), feats.take(2),
                         slo=SLO_INTERACTIVE)
    scheduler.add_tenant("bulk", object(), feats.take(2), slo=SLO_BULK)
    coord = ElasticCoordinator(chips_per_worker=1, initial_workers=4,
                               min_workers=1, clock=clock)
    store = PlacementStore(8, chips_per_worker=1)
    store.publish({"inter": [0, 1, 2, 3], "bulk": [0, 1, 2, 3]}, 4)
    tree = default_tree(scheduler=scheduler, elastic=coord)
    controller = AutoscaleController.build(
        tree, store=store, scheduler=scheduler, elastic=coord,
        clock=clock,
        policy_config=_config(
            p99_target_ms=250.0,     # the PR 14 interactive envelope
            queue_high=24, idle_high=0.6, min_dwell_s=1800.0,
            min_serving_chips=4, min_learner_workers=1))

    learner_last_publish = 0.0
    max_staleness = 0.0
    kinds = set()
    trace_mod.tracer.enable(capacity=4096)
    try:
        for tick in range(96):               # 24h x 4 ticks/hour
            hour = (tick * dt / 3600.0) % 24.0
            # diurnal interactive load: heavy 9h-21h, near-zero at night
            peak = hour >= 9.0 and hour < 21.0
            n_requests = 30 if peak else 1
            for i in range(n_requests):
                scheduler.submit("inter", feats.take(8))
            if not peak:
                scheduler.submit("bulk", feats.take(16))
            decision = controller.tick()     # samples the queued state
            kinds.add(decision.kind)
            _drain(scheduler)
            # chunk boundaries: pending resizes apply through the seam
            coord.poll()
            assert coord.fleet_size == store.current().learner_workers
            # the learner "publishes" whenever it holds capacity
            if coord.fleet_size >= 1:
                learner_last_publish = clock.t
            max_staleness = max(max_staleness,
                                clock.t - learner_last_publish)
            clock.advance(dt)
    finally:
        instants = [s for s in trace_mod.tracer.spans()
                    if s.name == "autoscale_decision"]
        trace_mod.tracer.disable()
        trace_mod.tracer.clear()

    # every decision visible as a tracer instant, reasons included
    assert len(instants) == 96
    assert all(s.ids["x_reason"] for s in instants)
    # the controller MOVED the fleet both ways across the day
    assert DECISION_SCALE_SERVING in kinds
    assert DECISION_YIELD_TO_TRAINING in kinds
    assert controller.actuations >= 2
    # interactive p99 held the envelope: zero interactive sheds, real
    # latency (inline drain) far inside 250ms
    assert scheduler.shed_counts()[SLO_INTERACTIVE] == 0
    p99 = scheduler.snapshot()["tenants.inter.latency_p99_ms"]
    assert p99 < 250.0
    # learner staleness bounded: never starved longer than 2 ticks
    assert max_staleness <= 2 * dt
    # placement generations advanced monotonically and durably
    assert store.generation >= 1 + controller.actuations
