"""SQLTransformer — restricted SELECT dialect over Table."""

import numpy as np
import pytest

from flink_ml_tpu import Table
from flink_ml_tpu.models.feature import SQLTransformer


def _t():
    return Table({
        "a": np.array([1.0, 2.0, 3.0, 4.0]),
        "b": np.array([10.0, 20.0, 30.0, 40.0]),
        "label": np.array([0, 1, 0, 1]),
    })


def _run(stmt, table=None):
    return SQLTransformer().set_statement(stmt).transform(table or _t())[0]


def test_select_star_passthrough():
    out = _run("SELECT * FROM __THIS__")
    assert out.column_names == ["a", "b", "label"]
    np.testing.assert_array_equal(out["a"], [1, 2, 3, 4])


def test_select_expressions_with_aliases():
    out = _run("SELECT a, a + b AS s, a * 2 AS twice, "
               "SQRT(b) AS root FROM __THIS__")
    np.testing.assert_array_equal(out["s"], [11, 22, 33, 44])
    np.testing.assert_array_equal(out["twice"], [2, 4, 6, 8])
    np.testing.assert_allclose(out["root"], np.sqrt([10, 20, 30, 40]))


def test_where_filters_rows():
    out = _run("SELECT *, a + 1 AS a1 FROM __THIS__ WHERE a > 2")
    np.testing.assert_array_equal(out["a"], [3, 4])
    np.testing.assert_array_equal(out["a1"], [4, 5])


def test_where_sql_equality_and_boolean_ops():
    out = _run("SELECT a FROM __THIS__ WHERE label = 1 AND b >= 20")
    np.testing.assert_array_equal(out["a"], [2, 4])
    out = _run("SELECT a FROM __THIS__ WHERE NOT (label = 1) OR a = 4")
    np.testing.assert_array_equal(out["a"], [1, 3, 4])


def test_functions_min_max_pow():
    out = _run("SELECT MIN(a, 2.5) AS lo, POW(a, 2) AS sq FROM __THIS__")
    np.testing.assert_array_equal(out["lo"], [1, 2, 2.5, 2.5])
    np.testing.assert_array_equal(out["sq"], [1, 4, 9, 16])


def test_scalar_literal_broadcasts():
    out = _run("SELECT a, 1 AS one FROM __THIS__")
    np.testing.assert_array_equal(out["one"], [1, 1, 1, 1])


def test_vector_columns_flow_through_arithmetic():
    t = Table({"v": np.arange(8.0).reshape(4, 2), "a": np.arange(4.0)})
    out = _run("SELECT v * 2 AS v2 FROM __THIS__ WHERE a > 0", t)
    np.testing.assert_array_equal(out["v2"], np.arange(8.0).reshape(4, 2)[1:] * 2)


def test_rejects_malformed_statement():
    with pytest.raises(ValueError, match="FROM __THIS__"):
        _run("DELETE FROM __THIS__")


def test_rejects_unknown_column_and_function():
    with pytest.raises(ValueError, match="unknown column"):
        _run("SELECT missing FROM __THIS__")
    with pytest.raises(ValueError, match="unknown function"):
        _run("SELECT open('/etc/passwd') FROM __THIS__")


def test_rejects_attribute_access_and_subscripts():
    with pytest.raises(ValueError, match="unsupported syntax"):
        _run("SELECT a.dtype FROM __THIS__")
    with pytest.raises(ValueError, match="unsupported syntax"):
        _run("SELECT a[0] FROM __THIS__")


def test_statement_param_required():
    with pytest.raises(ValueError, match="not be null"):
        SQLTransformer().transform(_t())


def test_save_load_roundtrip(tmp_path):
    st = SQLTransformer().set_statement("SELECT a + b AS s FROM __THIS__")
    path = str(tmp_path / "sqlt")
    st.save(path)
    loaded = SQLTransformer.load(path)
    out = loaded.transform(_t())[0]
    np.testing.assert_array_equal(out["s"], [11, 22, 33, 44])


def test_chained_comparison():
    out = _run("SELECT a FROM __THIS__ WHERE 1 < a <= 3")
    np.testing.assert_array_equal(out["a"], [2, 3])


def test_string_literals_survive_rewrites():
    t = Table({"s": np.asarray(["x=y", "a and b", "plain"], dtype=object),
               "n": np.array([1.0, 2.0, 3.0])})
    out = _run("SELECT n FROM __THIS__ WHERE s = 'x=y'", t)
    np.testing.assert_array_equal(out["n"], [1.0])
    out = _run("SELECT n FROM __THIS__ WHERE s = 'a and b'", t)
    np.testing.assert_array_equal(out["n"], [2.0])
    out = _run("SELECT 'a,b' AS c, n FROM __THIS__", t)
    assert list(out["c"]) == ["a,b"] * 3


def test_malformed_expression_raises_value_error():
    with pytest.raises(ValueError, match="could not parse"):
        _run("SELECT a + FROM __THIS__")
    with pytest.raises(ValueError, match="could not parse"):
        _run("SELECT a FROM __THIS__ WHERE a = 'unterminated")
