"""Wide&Deep step-variant shootout on the real chip.

Times one training step at the bench stretch config (26 x 40k vocab,
emb 64, mlp (1024, 512, 256), batch 8192) for every candidate
table-gradient implementation, so round 5's default-placement decision
is a measurement, not a guess:

- ``dense``          — autodiff scatter (the r4 baseline, 18.8 ms).
- ``routed_gather``  — static route, scatter-free inverse-map placement
                       (the r5 fit() default).
- ``routed_scatter`` — static route, sorted-unique scatter placement.
- ``routed_gather_sorted_fwd`` — EXPERIMENT: the forward reads the
  embedding table at ``sorted_ids`` (ascending rows — DMA-friendly)
  and un-permutes within the small (slots, emb) array, so ALL
  big-table access (forward read, backward dense write) is ascending;
  the random permutes touch only 54 MB arrays.  Not in the product
  path until this script proves it.
- ``lazy``           — LazyAdam (context: the r4 honest negative).

Run (relay up):  python scripts/wdl_step_experiments.py
Writes one JSON line; paste into R5_TPU_STATUS.md.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))


def main() -> None:
    import jax
    import jax.numpy as jnp
    import optax

    from flink_ml_tpu.models.common.losses import logistic_loss
    from flink_ml_tpu.models.recommendation.widedeep import (
        _field_offsets,
        build_reference_train_step,
        forward_from_rows,
        init_params,
    )
    from flink_ml_tpu.ops.emb_grad import (
        emb_grad_route,
        routed_table_grad_gather,
    )

    smoke = jax.default_backend() != "tpu"
    n_fields, d_dense = 26, 13
    vocab_each = (1 << 20) // n_fields if not smoke else 64
    vocab_sizes = (vocab_each,) * n_fields
    emb_dim = 64 if not smoke else 8
    hidden = (1024, 512, 256) if not smoke else (32, 16)
    batch = (1 << 13) if not smoke else (1 << 8)
    # 32 scanned steps per dispatch: the fixed ~69 ms tunnel round-trip
    # then biases each step by ~2 ms (identically across variants)
    # instead of ~9 ms at 8 steps
    steps = 32 if not smoke else 2
    total_vocab = int(np.sum(vocab_sizes))
    lr = 1e-2

    rng = np.random.default_rng(17)
    offs = _field_offsets(vocab_sizes)
    cat_host = (rng.integers(0, vocab_each,
                             size=(steps, batch, n_fields)).astype(np.int32)
                + offs[None, None, :].astype(np.int32))
    dense = jnp.asarray(
        rng.normal(size=(steps, batch, d_dense)).astype(np.float32))
    cat = jnp.asarray(cat_host)
    y = jnp.asarray(
        rng.integers(0, 2, size=(steps, batch)).astype(np.float32))
    mask = jnp.ones((steps, batch), jnp.float32)

    route_g = emb_grad_route(cat_host, total_vocab, placement="gather")
    route_s = emb_grad_route(cat_host, total_vocab, placement="scatter")
    # inverse permutation for the sorted-forward experiment:
    # inv[order[i]] = i, so rows_sorted[inv] restores batch order
    inv_host = np.empty_like(np.asarray(route_g.order))
    for s in range(steps):
        inv_host[s][np.asarray(route_g.order[s])] = np.arange(
            inv_host.shape[1], dtype=np.int32)
    inv = jnp.asarray(inv_host)

    def sorted_fwd_step():
        """Custom step: ascending-row table reads + small-array permutes,
        gather-placement backward.  Matches dense Adam up to f32 order."""
        params = jax.tree_util.tree_map(
            jnp.asarray,
            init_params(np.random.default_rng(0), d_dense, vocab_sizes,
                        emb_dim, hidden))
        opt = optax.adam(lr)

        def batch_step(params, opt_state, dense_b, labels, mask_b,
                       r_order, r_sid, r_pos_map, r_inv):
            rest = {k: v for k, v in params.items()
                    if k not in ("emb", "wide_cat")}
            # forward table reads at ASCENDING rows, then un-permute
            # inside the small (slots, emb) array (jax.lax.stop_gradient
            # is not needed: the rows enter the diff'd fn as inputs, so
            # the backward below is ours, not autodiff's)
            emb_rows = params["emb"][r_sid][r_inv].reshape(
                batch, n_fields, emb_dim)
            wide_rows = params["wide_cat"][r_sid][r_inv].reshape(
                batch, n_fields)

            def loss_rows(rest, emb_rows, wide_rows):
                return logistic_loss(
                    forward_from_rows(rest, dense_b, wide_rows, emb_rows),
                    labels, mask_b)

            loss, (g_rest, g_emb, g_wide) = jax.value_and_grad(
                loss_rows, argnums=(0, 1, 2))(rest, emb_rows, wide_rows)
            # backward identical to the gather placement (the route's own
            # permute gather runs on the small grad arrays)
            grads = {
                **g_rest,
                "emb": routed_table_grad_gather(
                    g_emb.reshape(-1, emb_dim), r_order, r_sid,
                    r_pos_map, fold_passes=route_g.fold_passes),
                "wide_cat": routed_table_grad_gather(
                    g_wide.reshape(-1), r_order, r_sid, r_pos_map,
                    fold_passes=route_g.fold_passes),
            }
            updates, opt_state = opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        return jax.jit(batch_step), params, opt.init(params)

    def measure(kind: str) -> float:
        if kind == "sorted_fwd":
            step, params, opt_state = sorted_fwd_step()
            rt = (route_g.order, route_g.sorted_ids, route_g.pos_map, inv)

            def call(p, o, i):
                return step(p, o, dense[i], y[i], mask[i],
                            *(a[i] for a in rt))
        else:
            route = {"gather": route_g, "scatter": route_s}.get(kind)
            step, params, opt_state = build_reference_train_step(
                d_dense, vocab_sizes, emb_dim, hidden, lr=lr,
                lazy_embeddings=(kind == "lazy"), route=route)
            rt = route.stacked_arrays() if route is not None else ()

            def call(p, o, i):
                return step(p, o, dense[i], cat[i], y[i], mask[i],
                            *(a[i] for a in rt))

        @jax.jit
        def run(params, opt_state):
            def body(carry, i):
                p, o = carry
                p, o, loss = call(p, o, i)
                return (p, o), loss

            (params, opt_state), losses = jax.lax.scan(
                body, (params, opt_state),
                jnp.arange(steps, dtype=jnp.int32))
            return params, opt_state, losses

        p, o, losses = run(params, opt_state)
        losses0 = np.asarray(losses)
        assert np.all(np.isfinite(losses0)), kind
        if kind != "lazy":
            # every dense-Adam variant must trace the same trajectory
            # (differences are f32 summation order only) — a wrong route
            # fails here, before any number is recorded
            if "dense" in loss_ref:
                np.testing.assert_allclose(losses0, loss_ref["dense"],
                                           rtol=1e-4, atol=1e-5,
                                           err_msg=kind)
            else:
                loss_ref["dense"] = losses0
        trials = []
        for _ in range(3):
            t0 = time.perf_counter()
            p, o, losses = run(p, o)
            np.asarray(losses)
            trials.append(time.perf_counter() - t0)
        return min(trials) / steps

    loss_ref: dict = {}
    out = {"backend": jax.default_backend(),
           "config": (f"{n_fields}x{vocab_each} vocab, emb {emb_dim}, "
                      f"mlp {hidden}, batch {batch}"),
           "fold_passes": route_g.fold_passes,
           "variants_allclose": True}
    for kind in ("dense", "gather", "scatter", "sorted_fwd", "lazy"):
        out[f"{kind}_step_ms"] = round(1000 * measure(kind), 3)

    # stage ablation of the routed-gather table gradient alone (no MLP,
    # no Adam): attributes any gap to permute / folds / placement so a
    # miss against the <=12 ms step target names its next lever.  The
    # fold-less and stage-only timings compute WRONG values on purpose —
    # they exist to time the remaining stages.
    from flink_ml_tpu.ops.emb_grad import _folded_ext

    S = batch * n_fields
    g_keys = jax.random.split(jax.random.PRNGKey(5), 2)
    g_flat = jax.random.normal(g_keys[0], (S, emb_dim), jnp.float32)
    rt = route_g.stacked_arrays()

    def timed(fn):
        @jax.jit
        def run(g_flat, mul):
            def body(carry, i):
                r = fn(g_flat * mul, rt[0][i], rt[1][i], rt[2][i])
                return carry, jnp.sum(r[:1])

            return jax.lax.scan(body, 0.0,
                                jnp.arange(steps, dtype=jnp.int32))

        run(g_flat, 1.0)
        trials = []
        for t in range(1, 4):
            t0 = time.perf_counter()
            _, s = run(g_flat, 1.0 + t * 1e-6)
            np.asarray(s)
            trials.append(time.perf_counter() - t0)
        return round(1000 * min(trials) / steps, 3)

    out["ablate_grad_full_ms"] = timed(
        lambda g, o, sid, pm: routed_table_grad_gather(
            g, o, sid, pm, fold_passes=route_g.fold_passes))
    out["ablate_grad_nofold_ms"] = timed(
        lambda g, o, sid, pm: routed_table_grad_gather(
            g, o, sid, pm, fold_passes=0))
    out["ablate_permute_only_ms"] = timed(
        lambda g, o, sid, pm: jnp.take(g, o, axis=0, unique_indices=True))
    out["ablate_fold_only_ms"] = timed(
        lambda g, o, sid, pm: _folded_ext(
            g, jnp.arange(S, dtype=jnp.int32), sid,
            route_g.fold_passes)[0])
    print(json.dumps(out))


if __name__ == "__main__":
    main()
