"""kernel-registry — models/ must dispatch device kernels through
``flink_ml_tpu.kernels``, not hand-rolled backend branches.

ISSUE 10 collapsed three kernel notions (chain StageKernels, serving
executors, ops/ Pallas kernels) into one per-backend registry: a Pallas
implementation registered once accelerates pipelines, serving, AND
training, with the XLA lowering as the automatic fallback.  That only
holds while the model layer actually goes THROUGH the registry — the
two bypass idioms this pass flags are exactly what PRs 1-9 accumulated
and PR 10 removed by hand:

- a direct ``pl.pallas_call`` (or ``pallas_call``) in ``models/``: a
  kernel invoked where only one consumer can see it.  Kernels live in
  ``ops/`` and register; models look them up.
- ``use_pallas``-style backend branching: a function parameter, keyword
  argument, or variable named ``use_pallas`` (the pre-PR 10 sgd.py
  idiom ``use_pallas=jax.default_backend() == "tpu"``), which silently
  forks dispatch policy per call site instead of resolving it once in
  the registry's availability/supports predicates.

Scope-fixed to ``flink_ml_tpu/models`` plus (ISSUE 19)
``flink_ml_tpu/retrieval`` — the index layer looks ``retrieve`` up
exactly like a model family looks up its op, so the same two bypass
idioms apply; ``ops/`` is where pallas_call belongs, and the registry
itself obviously names backends.
"""

from __future__ import annotations

import ast

from typing import List

from ..core import ModuleInfo, Project
from .base import LintPass

#: the flagged branching identifier (the historical idiom, verbatim)
_BRANCH_NAME = "use_pallas"


class KernelRegistryPass(LintPass):
    id = "kernel-registry"
    describes = ("models/ must dispatch kernels through the kernel "
                 "registry (no direct pallas_call, no use_pallas-style "
                 "backend branching)")
    roots = ("flink_ml_tpu/models", "flink_ml_tpu/retrieval")
    scope_fixed = True
    hint = ("register the implementation in kernels/registry.py (op, "
            "backend, supports, available) and resolve it with "
            "lookup(op, sig) — see ARCHITECTURE.md 'Kernel registry'")

    def check_module(self, mod: ModuleInfo,
                     project: Project) -> List:
        findings: List = []
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                qn = mod.call_qualname(node) or ""
                if qn.endswith("pallas_call"):
                    findings.append(mod.finding(
                        self.id, node,
                        "direct pallas_call bypasses the kernel registry "
                        "— move the kernel to flink_ml_tpu/ops/ and "
                        "register it",
                        hint=self.hint))
                for kw in node.keywords:
                    if kw.arg == _BRANCH_NAME:
                        findings.append(mod.finding(
                            self.id, kw.value,
                            f"'{_BRANCH_NAME}=' backend branching at the "
                            "call site bypasses the kernel registry",
                            hint=self.hint))
            elif isinstance(node, ast.arg) and node.arg == _BRANCH_NAME:
                findings.append(mod.finding(
                    self.id, node,
                    f"'{_BRANCH_NAME}' parameter forks backend dispatch "
                    "per function instead of a registry lookup",
                    hint=self.hint))
            elif isinstance(node, ast.Name) and node.id == _BRANCH_NAME \
                    and isinstance(node.ctx, ast.Store):
                # the inline form: `use_pallas = default_backend() == ...`
                # binds the fork without any parameter or keyword
                findings.append(mod.finding(
                    self.id, node,
                    f"'{_BRANCH_NAME}' binding forks backend dispatch "
                    "inline instead of a registry lookup",
                    hint=self.hint))
        return findings
