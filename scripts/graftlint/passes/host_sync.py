"""host-sync — no host synchronization inside step/scan bodies.

Absorbed from ``scripts/check_no_host_sync.py`` (ISSUE 6 satellite; that
script is now a delegating shim).  The communication-overlap schedule
(``grad_reduce.pipelined_reduce``) only buys anything if the device
queue stays full: a ``block_until_ready`` / ``jax.device_get`` /
``np.asarray`` / ``.item()`` inside a step body fences the dispatch
stream and silently destroys the overlap (and PR 1's chunked-dispatch
amortization with it).

A function is a step body if (a) it is named like one (``update``,
``*_step``, ``*_body``, ...) or (b) it is passed by reference as the
scanned body to ``lax.scan`` / ``masked_chunk_scan`` / ``while_loop`` /
``fori_loop`` anywhere in the module; nested helper defs inside a step
body are covered by the AST walk.  Heuristic by design (AST names, not
tracing) — step bodies are pure device math in this repo, so ANY of the
four calls is a finding.
"""

from __future__ import annotations

import ast

from typing import List

from ..core import ModuleInfo, Project
from .base import LintPass

#: function names that ARE step/scan bodies in this repo's idiom
STEP_NAMES = {
    "update", "batch_step", "scan_step", "chunk_step", "device_fn",
    "train_step", "epoch_body", "body", "step",
}

STEP_SUFFIXES = ("_step", "_body", "_update")

#: callables whose argument is a scanned/stepped body function
SCAN_CALLEES = {"scan", "masked_chunk_scan", "while_loop", "fori_loop"}

#: every step/scan body in these trees must stay host-sync-free
#: (``online/`` joined with ISSUE 7: its driver feeds the same chunked
#: scan, so a host sync in a step-named helper there would fence the
#: training dispatch stream the publishes ride on; ``iteration/`` joined
#: with ISSUE 9: the workset while_loop driver's whole value is zero host
#: round-trips per round — a ``block_until_ready``/``.item()`` hiding in
#: its scan/while bodies would re-serialize every epoch; ``ops/`` joined
#: with ISSUE 10: the kernel registry routes every training hot path
#: through these modules, so a host fetch in a kernel wrapper would
#: fence EVERY consumer's dispatch stream at once)
#: (``obs/`` joined with ISSUE 13: the StepProbe's whole contract is
#: zero host sync inside step fns — its ``record``/``record_at`` ride
#: scan/while carries on every training hot path, so a device_get
#: sneaking into a step-shaped helper there would fence every adopter's
#: dispatch stream at once)
#: (``serving/`` joined with ISSUE 14: the multi-tenant scheduler's one
#: serve loop multiplexes EVERY tenant — a host sync in a step-shaped
#: helper on its dispatch path would stall every tenant's traffic at
#: once, not one endpoint's, and the embedding-cache pool ops must stay
#: async for the miss path to overlap with serving)
#: (``autoscale/`` joined with ISSUE 17: the controller reads the same
#: metrics tree the serving/training hot paths publish into — a host
#: sync in a step-shaped helper here would fence the very dispatch
#: streams the control plane exists to keep busy, turning every
#: decision tick into a fleet-wide stall)
#: (``kernels/`` joined with ISSUE 18: the quantize module's dequant
#: helpers trace into every int8 serving program and the registry's
#: dispatch wrapper fronts every kernel consumer — a host fetch in a
#: step-shaped helper here would fence training AND serving dispatch
#: streams at once; calibration is host-side numpy by design, but it
#: runs at publish/bind time, never inside a step body)
#: (``retrieval/`` joined with ISSUE 19: the fused retrieve stage traces
#: into every index tenant's serving program through the shared plan
#: jit — a host sync in a step-shaped helper there would fence the
#: multiplexed serve loop exactly like one in ``serving/`` would; index
#: BUILD is host-side numpy by design, but it runs at build/re-anchor
#: time, never inside the dispatched search)
#: (``serving/failover.py`` rides the existing ``serving/`` root with
#: ISSUE 20: the failover driver's requeue + re-placement runs INLINE
#: on the scheduler's one serve loop when a dispatch-boundary fault
#: fires — a host sync in a step-shaped helper there would stall every
#: tenant's traffic during the exact window the failover exists to keep
#: short, and the lease table's poll shares the loop's cadence; the
#: visits self-test in tests/test_graftlint.py pins the module into
#: both this pass's and lock-discipline's walks)
SCAN_ROOTS = (
    "flink_ml_tpu/autoscale",
    "flink_ml_tpu/iteration",
    "flink_ml_tpu/kernels",
    "flink_ml_tpu/models",
    "flink_ml_tpu/obs",
    "flink_ml_tpu/online",
    "flink_ml_tpu/ops",
    "flink_ml_tpu/parallel",
    "flink_ml_tpu/retrieval",
    "flink_ml_tpu/serving",
)


def is_step_name(name: str) -> bool:
    return name in STEP_NAMES or name.endswith(STEP_SUFFIXES)


def scanned_body_names(tree: ast.AST) -> set:
    """Names passed as the body argument to scan-family calls anywhere in
    the module — step bodies regardless of their name."""
    out = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        name = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None)
        if name not in SCAN_CALLEES or not node.args:
            continue
        args = node.args
        cands = [args[2]] if name == "fori_loop" and len(args) >= 3 \
            else args[:2] if name == "while_loop" else [args[0]]
        for cand in cands:
            if isinstance(cand, ast.Name):
                out.add(cand.id)
    return out


def sync_kind(mod: ModuleInfo, call: ast.Call):
    """The host-sync kind of a call, or None.  ``np.asarray`` matches
    through import aliasing (``import numpy as onp`` included)."""
    f = call.func
    if isinstance(f, ast.Attribute):
        if f.attr == "block_until_ready":
            return "block_until_ready"
        if f.attr == "item":
            return ".item()"
        if f.attr == "device_get":
            return "jax.device_get"
        if f.attr == "asarray":
            root = mod.qualname(f.value)
            if root in ("numpy", "np", "onp"):
                return "np.asarray"
    elif isinstance(f, ast.Name) and f.id == "device_get":
        return "device_get"
    return None


class HostSyncPass(LintPass):
    id = "host-sync"
    describes = ("no host synchronization (block_until_ready/device_get/"
                 "np.asarray/.item) inside step or scan-body functions")
    roots = SCAN_ROOTS
    scope_fixed = True      # the convention applies to the step trees
    hint = ("keep step bodies pure device math; fetch on the host side of "
            "the dispatch boundary (see ARCHITECTURE.md 'Gradient "
            "reduction')")

    def check_module(self, mod: ModuleInfo,
                     project: Project) -> List:
        scanned = scanned_body_names(mod.tree)
        findings, seen = [], set()
        for fn in ast.walk(mod.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not (is_step_name(fn.name) or fn.name in scanned):
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                kind = sync_kind(mod, node)
                if kind and node.lineno not in seen:
                    seen.add(node.lineno)
                    findings.append(mod.finding(
                        self.id, node,
                        f"{kind} inside step body {fn.name}() — a host "
                        "sync here fences the dispatch stream and "
                        "destroys comm/compute overlap", hint=self.hint))
        return findings
