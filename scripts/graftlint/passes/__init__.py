"""The pass catalog.  Order is the report order; ids are the names
``# graftlint: disable=<id>`` and the baseline file key on."""

from .atomic_writes import AtomicWritesPass
from .bench_schema import BenchSchemaPass
from .collectives import CollectiveConsistencyPass
from .donation import DonationSafetyPass
from .host_sync import HostSyncPass
from .kernel_registry import KernelRegistryPass
from .locks import LockDisciplinePass
from .unfenced_timing import UnfencedTimingPass

ALL_PASSES = (
    HostSyncPass,
    AtomicWritesPass,
    DonationSafetyPass,
    LockDisciplinePass,
    CollectiveConsistencyPass,
    KernelRegistryPass,
    UnfencedTimingPass,
    BenchSchemaPass,
)

__all__ = ["ALL_PASSES", "AtomicWritesPass", "BenchSchemaPass",
           "CollectiveConsistencyPass", "DonationSafetyPass",
           "HostSyncPass", "KernelRegistryPass", "LockDisciplinePass",
           "UnfencedTimingPass"]
