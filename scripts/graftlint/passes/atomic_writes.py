"""atomic-writes — durable-layer writes must be tmp -> ``os.replace``.

Absorbed from ``scripts/check_atomic_writes.py`` (ISSUE 5 satellite; the
script is now a delegating shim).  The durability contract of
``utils/persist.py`` / ``iteration/checkpoint.py`` / ``data/wal.py`` is
*write tmp -> os.replace*: a crash mid-write must never leave a
half-written file at a path a loader trusts.  Flags any
``open(path, "w"/"wb"/"a"...)`` whose enclosing function never
``os.replace``'s a path sharing a variable with the opened expression
(writing INTO a tmp dir that is itself renamed counts: the shared
variable is the tmp dir name).
"""

from __future__ import annotations

import ast

from typing import List

from ..core import ModuleInfo, Project
from .base import LintPass

#: the durable layer: every open-for-write here must be atomic
#: (``robustness/durability.py`` joined in PR 8 — the manifest/marker
#: commit protocol lives there and must obey its own rule;
#: ``kernels/aot.py`` + ``kernels/autotune.py`` joined in ISSUE 12 —
#: the persistent executable/decision cache writes through the same
#: commit protocol and must be tmp -> os.replace like everything else
#: a loader trusts; ``flink_ml_tpu/obs/`` joined in ISSUE 13 — trace
#: exports and metrics time-series are exactly the files an operator
#: loads after a crash, so a half-written trace JSON must never sit at
#: a trusted path.  The one sanctioned exception — the sampler's
#: line-framed JSONL append, whose torn tail the reader truncates (the
#: WAL-tail stance) — carries an inline suppression with its
#: justification, which this root existing keeps EXERCISED.
#: ``flink_ml_tpu/autoscale/`` joined in ISSUE 17 — the placement map
#: is the file a restarting control plane trusts to know who owns
#: which chips; a torn placement would mis-route an entire fleet, so
#: every publish must be tmp -> os.replace.)
DURABLE_MODULES = (
    "flink_ml_tpu/utils/persist.py",
    "flink_ml_tpu/iteration/checkpoint.py",
    "flink_ml_tpu/data/wal.py",
    "flink_ml_tpu/robustness/durability.py",
    "flink_ml_tpu/kernels/aot.py",
    "flink_ml_tpu/kernels/autotune.py",
    "flink_ml_tpu/obs",
    "flink_ml_tpu/autoscale",
)

_WRITE_MODES = {"w", "wb", "w+", "wb+", "a", "ab"}


def _names(node: ast.AST) -> set:
    """Variable names referenced by an expression, skipping the ``os``
    module root used in ``os.path.join(tmp, ...)``."""
    out = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            out.add(sub.id)
    out.discard("os")
    return out


def _open_mode(call: ast.Call):
    if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant) \
            and isinstance(call.args[1].value, str):
        return call.args[1].value
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
            return kw.value.value
    return None


class AtomicWritesPass(LintPass):
    id = "atomic-writes"
    describes = ("durable-module open-for-write sites follow the "
                 "write-tmp -> os.replace commit pattern")
    roots = DURABLE_MODULES
    scope_fixed = True      # the convention applies to the durable layer
    hint = ("write to '<path>.tmp' then os.replace(tmp, path) — or write "
            "into a tmp dir that is itself renamed")

    def check_module(self, mod: ModuleInfo,
                     project: Project) -> List:
        findings = []
        for fn in ast.walk(mod.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            writes = []       # (node, path-variable names)
            replaced = set()  # names appearing as os.replace source args
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                qual = mod.call_qualname(node)
                if qual == "open" and node.args:
                    mode = _open_mode(node)
                    if mode and mode.strip("b+") in ("w", "a") \
                            and mode in _WRITE_MODES:
                        writes.append((node, _names(node.args[0])))
                elif qual == "os.replace" and node.args:
                    replaced |= _names(node.args[0])
            for node, names in writes:
                if not names:
                    findings.append(mod.finding(
                        self.id, node,
                        "open-for-write on a literal path with no "
                        "os.replace — not crash-atomic", hint=self.hint))
                elif not names & replaced:
                    findings.append(mod.finding(
                        self.id, node,
                        f"open-for-write on {sorted(names)} but "
                        f"{fn.name}() never os.replace's a path sharing "
                        "those names — a crash can leave a half-written "
                        "file", hint=self.hint))
        return findings
