"""donation-safety — a donated buffer is never read again.

The hazard ``iteration/core.py::_private_copy`` exists to prevent: a
value passed at a ``donate_argnums`` position of a jitted function is
*consumed* — XLA may reuse its buffer for the output, so any later read
of the same Python name observes garbage (or trips the deleted-buffer
check, backend-dependent and often only on TPU).  PR 1's donated-carry
chunk scan and PR 7's resume paths both had to get this right by hand;
this pass checks it everywhere.

What counts as a donating callable:

- ``name = jax.jit(fn, donate_argnums=...)`` with a non-empty literal
  (or conditional ``(0,) if cfg else ()`` — treated as donating, since
  the read-after-donate is a bug whenever the condition holds);
- a def decorated ``@partial(jax.jit, donate_argnums=...)``;
- a *factory*: a local function whose returned value flows from a
  ``jax.jit(..., donate_argnums=<param>)`` call (``serving/executor.py::
  _serving_jit``) — call sites with a literal at that parameter bind a
  donating callable;
- a direct ``jax.jit(fn, donate_argnums=...)(args...)`` call.

The check is a small path-sensitive walk over each function body: a
bare name passed at a donated position becomes *donated*; a later Load
of it on any path is a finding; a Store (including the common
``state = step(state, ...)`` rebind) clears it.  Loop bodies run twice
so a donation on iteration N is seen by a read at the loop head on
iteration N+1 — the resume-path shape of the bug.
"""

from __future__ import annotations

import ast

from typing import Dict, List, Optional, Set

from ..core import ModuleInfo, Project
from .base import LintPass

_PARTIAL = {"functools.partial", "partial"}


def _jit_call(mod: ModuleInfo, node) -> bool:
    return isinstance(node, ast.Call) and \
        mod.call_qualname(node) in ("jax.jit", "jit")


def _donate_kwarg(call: ast.Call):
    for kw in call.keywords:
        if kw.arg in ("donate_argnums", "donate_argnames"):
            return kw.value
    return None


def _positions(expr) -> Optional[Set[int]]:
    """Donated positions from a donate_argnums expression: int / tuple
    literal, or the union over a conditional's arms.  None = statically
    unknown (the pass then skips — it cannot name positions)."""
    if expr is None:
        return set()
    if isinstance(expr, ast.Constant) and isinstance(expr.value, int):
        return {expr.value}
    if isinstance(expr, (ast.Tuple, ast.List)):
        out: Set[int] = set()
        for el in expr.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, int):
                out.add(el.value)
            else:
                return None
        return out
    if isinstance(expr, ast.IfExp):
        a, b = _positions(expr.body), _positions(expr.orelse)
        if a is None or b is None:
            return None
        return a | b
    return None


def _jit_donation_positions(mod: ModuleInfo, call: ast.Call,
                            ) -> Optional[Set[int]]:
    """Donated positions of a ``jax.jit(...)`` call (empty set = not
    donating)."""
    expr = _donate_kwarg(call)
    if expr is None:
        return set()
    return _positions(expr)


class _Factory:
    """A local function that manufactures donating callables: calls get
    their positions from the argument bound to ``param`` (name or
    index)."""

    def __init__(self, param_name: str, param_index: int):
        self.param_name = param_name
        self.param_index = param_index

    def positions_at_call(self, call: ast.Call) -> Optional[Set[int]]:
        if self.param_index < len(call.args):
            return _positions(call.args[self.param_index])
        for kw in call.keywords:
            if kw.arg == self.param_name:
                return _positions(kw.value)
        return set()        # param defaulted — assume non-donating


def _find_factories(mod: ModuleInfo) -> Dict[str, _Factory]:
    """Local functions whose body jits with ``donate_argnums`` flowing
    from one of their parameters."""
    out: Dict[str, _Factory] = {}
    for fns in mod.functions.values():
        for fn in fns:
            params = [a.arg for a in fn.args.args]
            for node in ast.walk(fn):
                if not _jit_call(mod, node):
                    continue
                expr = _donate_kwarg(node)
                if expr is None:
                    continue
                # names feeding the donate expr, one assignment hop deep
                feed = {n.id for n in ast.walk(expr)
                        if isinstance(n, ast.Name)}
                for stmt in ast.walk(fn):
                    if isinstance(stmt, ast.Assign) and \
                            len(stmt.targets) == 1 and \
                            isinstance(stmt.targets[0], ast.Name) and \
                            stmt.targets[0].id in feed:
                        feed |= {n.id for n in ast.walk(stmt.value)
                                 if isinstance(n, ast.Name)}
                for p in params:
                    if p in feed:
                        out[fn.name] = _Factory(p, params.index(p))
                        break
    return out


class DonationSafetyPass(LintPass):
    id = "donation-safety"
    describes = ("a value passed at a donate_argnums position of a "
                 "jitted function is never read again on any path")
    roots = ("flink_ml_tpu", "scripts")
    hint = ("rebind the result over the donated name "
            "(state = step(state, ...)) or donate a private copy "
            "(iteration/core.py::_private_copy)")

    def check_module(self, mod: ModuleInfo,
                     project: Project) -> List:
        factories = _find_factories(mod)
        # module-level donating callables: name -> positions
        module_donating: Dict[str, Set[int]] = {}
        for stmt in mod.tree.body:
            self._collect_bindings(mod, stmt, factories, module_donating)

        findings: List = []
        for fns in mod.functions.values():
            for fn in fns:
                self._check_function(mod, fn, factories,
                                     dict(module_donating), findings)
        # unique per (line, name)
        seen, out = set(), []
        for f in findings:
            key = (f.line, f.message)
            if key not in seen:
                seen.add(key)
                out.append(f)
        return out

    # -- binding collection --------------------------------------------------
    def _collect_bindings(self, mod, stmt, factories,
                          donating: Dict[str, Set[int]]) -> None:
        """Record ``name = <donating callable>`` bindings from one
        statement (module- or function-level)."""
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                isinstance(stmt.targets[0], ast.Name) and \
                isinstance(stmt.value, ast.Call):
            name = stmt.targets[0].id
            pos = self._call_positions(mod, stmt.value, factories)
            if pos:
                donating[name] = pos
            elif name in donating:
                del donating[name]      # rebound to something else
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in stmt.decorator_list:
                if isinstance(dec, ast.Call) and \
                        mod.call_qualname(dec) in _PARTIAL and dec.args and \
                        mod.qualname(dec.args[0]) in ("jax.jit", "jit"):
                    pos = _positions(_donate_kwarg(dec))
                    if pos:
                        donating[stmt.name] = pos

    def _call_positions(self, mod, call: ast.Call, factories,
                        ) -> Optional[Set[int]]:
        """Donated positions of the callable a Call produces (jit call or
        factory call), or empty/None."""
        qual = mod.call_qualname(call)
        if qual in ("jax.jit", "jit"):
            return _jit_donation_positions(mod, call)
        fname = call.func.id if isinstance(call.func, ast.Name) else None
        if fname in factories:
            return factories[fname].positions_at_call(call)
        return set()

    # -- per-function walk ---------------------------------------------------
    def _check_function(self, mod, fn, factories, donating, findings):
        donated: Dict[str, ast.Call] = {}    # name -> the donating call

        def handle_loads(expr, fresh=()):
            """Flag Loads of donated names in ``expr``.  ``fresh`` names
            were donated by a call inside THIS expression: only a read
            textually AFTER that call's end is a read-after-donate —
            Python evaluates left-to-right, so ``f(state) + state.sum()``
            reads the donated buffer but ``state.sum() + f(state)`` does
            not (and the donated argument itself sits inside the call
            span)."""
            for node in ast.walk(expr):
                if isinstance(node, ast.Name) and \
                        isinstance(node.ctx, ast.Load) and \
                        node.id in donated:
                    call = donated[node.id]
                    if node.id in fresh:
                        call_end = (getattr(call, "end_lineno",
                                            call.lineno),
                                    getattr(call, "end_col_offset", 1 << 30))
                        if (node.lineno, node.col_offset) <= call_end:
                            continue
                    callee = (mod.qualname(call.func)
                              or getattr(call.func, "id", "<jitted>"))
                    findings.append(mod.finding(
                        self.id, node,
                        f"'{node.id}' is read after being passed at a "
                        f"donated position of {callee}() at line "
                        f"{call.lineno} — the donated buffer may have "
                        "been reused by XLA", hint=self.hint))
                    del donated[node.id]     # report once per donation

        def handle_calls(expr):
            """Mark names donated by donating calls inside ``expr``."""
            for node in ast.walk(expr):
                if not isinstance(node, ast.Call):
                    continue
                pos: Optional[Set[int]] = None
                if isinstance(node.func, ast.Name):
                    name = node.func.id
                    if name in donating:
                        pos = donating[name]
                elif _jit_call(mod, node.func):
                    # jax.jit(f, donate_argnums=...)(args)
                    pos = _jit_donation_positions(mod, node.func)
                if not pos:
                    continue
                for p in pos:
                    if p < len(node.args) and \
                            isinstance(node.args[p], ast.Name):
                        donated[node.args[p].id] = node

        def process_expr(expr):
            """One expression, evaluation-order-aware: record donations
            made by calls inside it, THEN check loads — names donated by
            this very expression only flag when read after the call's
            span (``f(state) + state.sum()``)."""
            prior = set(donated)
            handle_calls(expr)
            handle_loads(expr, fresh=set(donated) - prior)

        def kill_targets(target):
            for node in ast.walk(target):
                if isinstance(node, ast.Name):
                    donated.pop(node.id, None)

        def exec_stmt(stmt) -> bool:
            """Process one statement; True = control never falls through
            (return/raise/break/continue) — later statements in the
            block, and sibling-branch merges, must not see this path's
            donations."""
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                # nested defs: bindings only (their bodies are checked as
                # their own functions via mod.functions)
                self._collect_bindings(mod, stmt, factories, donating)
                return False
            if isinstance(stmt, ast.Assign):
                process_expr(stmt.value)
                for t in stmt.targets:
                    kill_targets(t)
                self._collect_bindings(mod, stmt, factories, donating)
                return False
            if isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                if stmt.value is not None:
                    process_expr(stmt.value)
                if isinstance(stmt, ast.AugAssign):
                    handle_loads(stmt.target)
                kill_targets(stmt.target)
                return False
            if isinstance(stmt, ast.Expr):
                process_expr(stmt.value)
                return False
            if isinstance(stmt, ast.Return):
                if stmt.value is not None:
                    process_expr(stmt.value)
                return True
            if isinstance(stmt, ast.Raise):
                for part in (stmt.exc, stmt.cause):
                    if part is not None:
                        process_expr(part)
                return True
            if isinstance(stmt, (ast.Break, ast.Continue)):
                return True
            if isinstance(stmt, ast.If):
                process_expr(stmt.test)
                snap = dict(donated)
                t_body = exec_block(stmt.body)
                after_body = dict(donated)
                donated.clear()
                donated.update(snap)
                t_else = exec_block(stmt.orelse)
                # a name donated on ANY path that REACHES here stays
                # donated; an arm that returned/raised contributes
                # nothing to the fall-through state
                if t_body and t_else:
                    return True
                if t_else:
                    donated.clear()
                if not t_body:
                    donated.update(after_body)
                return False
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                process_expr(stmt.iter)
                for _ in range(2):       # back-edge: donations reach the head
                    kill_targets(stmt.target)
                    exec_block(stmt.body)
                exec_block(stmt.orelse)
                return False
            if isinstance(stmt, ast.While):
                for _ in range(2):
                    process_expr(stmt.test)
                    exec_block(stmt.body)
                exec_block(stmt.orelse)
                return False
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    process_expr(item.context_expr)
                    if item.optional_vars is not None:
                        kill_targets(item.optional_vars)
                return exec_block(stmt.body)
            if isinstance(stmt, ast.Try):
                exec_block(stmt.body)
                for h in stmt.handlers:
                    exec_block(h.body)
                exec_block(stmt.orelse)
                exec_block(stmt.finalbody)
                return False
            # default: inspect all expressions in the statement
            for node in ast.iter_child_nodes(stmt):
                if isinstance(node, ast.expr):
                    process_expr(node)
            return False

        def exec_block(stmts) -> bool:
            for s in stmts:
                if exec_stmt(s):
                    return True      # later statements are unreachable
            return False

        exec_block(fn.body)
