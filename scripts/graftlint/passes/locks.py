"""lock-discipline — no Lock/RLock held across a blocking call.

THE bug class of PR 1's ``flush_lock``-across-``put`` deadlock: a lock
held while parking on a bounded queue (or a thread join, a sleep, a
``device_put``, file/socket I/O) serializes the pipeline at best and
deadlocks it at worst — every lock site in ``data/prefetch.py``,
``serving/``, ``online/publish.py`` and ``utils/padding.py`` follows
the convention *compute under the lock, block outside it*.

Mechanics:

- **Lock identification** — names/attributes assigned from
  ``threading.Lock()`` / ``RLock()`` (including ``self._x = Lock()``
  and dataclass ``field(default_factory=threading.Lock)``), plus the
  naming convention: any ``with``/``acquire`` target whose trailing
  name contains "lock" or "mutex".
- **Held regions** — ``with lock:`` bodies, and linear
  ``lock.acquire()`` ... ``lock.release()`` spans in statement order
  (which correctly models the release-before-put / reacquire pattern
  ``prefetch._flush_ready`` uses).
- **Blocking calls** — ``queue.put/get`` on queue-typed or queue-named
  receivers, ``Thread.join``, ``time.sleep``, ``jax.device_put``,
  ``block_until_ready``, ``open``, socket send/recv, ``Event/\
  Condition.wait``, ``Future.result`` — and any call to a local
  function whose body (transitively, depth-capped) contains one:
  the follow-by-reference analysis that caught the original
  ``_flush_ready`` shape.
"""

from __future__ import annotations

import ast
import re

from typing import Dict, List, Optional, Tuple

from ..core import ModuleInfo, Project
from .base import LintPass

_LOCK_CTORS = {"threading.Lock", "threading.RLock", "Lock", "RLock"}
_QUEUE_CTORS = {"queue.Queue", "queue.SimpleQueue", "queue.LifoQueue",
                "queue.PriorityQueue", "Queue", "SimpleQueue"}
_LOCKISH_RE = re.compile(r"(lock|mutex)", re.IGNORECASE)
_QUEUEISH_RE = re.compile(r"(^|_)(q|fq|queue)$|queue", re.IGNORECASE)
_THREADISH_RE = re.compile(r"(thread|worker|proc|pool)|^te?$",
                           re.IGNORECASE)
_FUTISH_RE = re.compile(r"fut", re.IGNORECASE)
_SOCKET_ATTRS = {"recv", "recv_into", "send", "sendall", "accept",
                 "connect"}
_ALWAYS_BLOCKING_QUALS = {
    "time.sleep", "jax.device_put", "device_put",
    "jax.block_until_ready", "futures.wait",
    "concurrent.futures.wait", "select.select",
}

_MAX_DEPTH = 4


def _trailing_name(node) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _expr_key(node) -> Optional[str]:
    """Stable textual identity for a lock expression ("self._lock",
    "flush_lock")."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class _ModuleFacts:
    """Per-module name classification: which names are locks, queues,
    threads (constructor-tracked, annotation-tracked, plus the naming
    conventions)."""

    def __init__(self, mod: ModuleInfo):
        self.mod = mod
        self.lock_keys: set = set()
        self.queue_names: set = set()
        self.thread_names: set = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                value = node.value
                ctor = self._ctor_qual(value)
                ann = getattr(node, "annotation", None)
                ann_qual = mod.qualname(ann) if ann is not None else None
                for t in targets:
                    key = _expr_key(t)
                    name = _trailing_name(t)
                    if key is None or name is None:
                        continue
                    if ctor in _LOCK_CTORS or self._lock_factory(value):
                        self.lock_keys.add(key)
                    if ctor in _QUEUE_CTORS or (
                            ann_qual and "Queue" in ann_qual):
                        self.queue_names.add(name)
                    if ctor in ("threading.Thread", "Thread"):
                        self.thread_names.add(name)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for a in node.args.args + node.args.kwonlyargs:
                    if a.annotation is not None:
                        q = mod.qualname(a.annotation)
                        if q and "Queue" in q:
                            self.queue_names.add(a.arg)

    def _ctor_qual(self, value) -> Optional[str]:
        if isinstance(value, ast.Call):
            return self.mod.call_qualname(value)
        return None

    def _lock_factory(self, value) -> bool:
        """``field(default_factory=threading.Lock)``."""
        if not (isinstance(value, ast.Call)
                and self.mod.call_qualname(value) in
                ("dataclasses.field", "field")):
            return False
        for kw in value.keywords:
            if kw.arg == "default_factory" and \
                    self.mod.qualname(kw.value) in _LOCK_CTORS:
                return True
        return False

    def is_lock(self, expr) -> bool:
        key = _expr_key(expr)
        name = _trailing_name(expr)
        if key in self.lock_keys:
            return True
        return bool(name and _LOCKISH_RE.search(name))

    def is_queueish(self, expr) -> bool:
        name = _trailing_name(expr)
        return bool(name and (name in self.queue_names
                              or _QUEUEISH_RE.search(name)))

    def is_threadish(self, expr) -> bool:
        name = _trailing_name(expr)
        return bool(name and (name in self.thread_names
                              or _THREADISH_RE.search(name)))


def _blocking_reason(mod: ModuleInfo, facts: _ModuleFacts,
                     call: ast.Call) -> Optional[str]:
    """Why a single call is blocking, or None.  Local-function
    transitivity is layered on top by ``_fn_blocking``."""
    qual = mod.call_qualname(call)
    if qual in _ALWAYS_BLOCKING_QUALS:
        return qual
    if qual == "open":
        return "open() file I/O"
    f = call.func
    if not isinstance(f, ast.Attribute):
        return None
    attr = f.attr
    if attr == "block_until_ready":
        return "block_until_ready"
    if attr == "sleep" and qual and qual.endswith("time.sleep"):
        return "time.sleep"
    if attr in ("put", "get", "put_nowait", "get_nowait"):
        if attr.endswith("_nowait"):
            return None
        if facts.is_queueish(f.value):
            return f"queue {attr}()"
        return None
    if attr == "join":
        if isinstance(f.value, ast.Constant):
            return None                       # "sep".join(...)
        if mod.qualname(f.value) in ("os.path", "posixpath", "ntpath"):
            return None
        if facts.is_threadish(f.value):
            return "Thread.join"
        return None
    if attr in _SOCKET_ATTRS:
        return f"socket .{attr}()"
    if attr == "wait":
        return ".wait()"
    if attr == "result":
        name = _trailing_name(f.value)
        if name and _FUTISH_RE.search(name):
            return "Future.result()"
        return None
    return None


def _fn_blocking(mod: ModuleInfo, facts: _ModuleFacts, fn,
                 memo: Dict[str, Optional[str]], depth: int = 0,
                 ) -> Optional[str]:
    """First blocking reason anywhere in ``fn`` (transitive through
    bare-name calls to local functions, depth-capped), or None.
    Ignores the callee's own lock regions — a callee that blocks while
    NOT holding our lock still blocks us."""
    if fn.name in memo:
        return memo[fn.name]
    memo[fn.name] = None          # cycle guard
    reason = None
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        reason = _blocking_reason(mod, facts, node)
        if reason:
            break
        if depth < _MAX_DEPTH and isinstance(node.func, ast.Name) and \
                node.func.id in mod.functions and \
                node.func.id != fn.name:
            inner = _fn_blocking(mod, facts,
                                 mod.functions[node.func.id][-1],
                                 memo, depth + 1)
            if inner:
                reason = f"{node.func.id}() -> {inner}"
                break
    memo[fn.name] = reason
    return reason


class LockDisciplinePass(LintPass):
    id = "lock-discipline"
    describes = ("no threading.Lock/RLock held across a blocking call "
                 "(queue put/get, join, sleep, device_put, "
                 "block_until_ready, file/socket I/O)")
    roots = ("flink_ml_tpu", "scripts")
    hint = ("compute under the lock, block outside it — snapshot what "
            "you need, release, then block (prefetch._flush_ready is "
            "the worked example)")

    def check_module(self, mod: ModuleInfo,
                     project: Project) -> List:
        facts = _ModuleFacts(mod)
        memo: Dict[str, Optional[str]] = {}
        findings: List = []
        for fns in mod.functions.values():
            for fn in fns:
                self._check_fn(mod, facts, fn, memo, findings)
        return findings

    def _check_fn(self, mod, facts, fn, memo, findings):
        held: List[Tuple[str, int]] = []      # (lock key, acquire line)

        def check_call(node: ast.Call):
            if not held:
                return
            # acquire/release themselves are region markers, not
            # blocking events (nested-lock ordering is out of scope)
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr in ("acquire", "release") and \
                    facts.is_lock(node.func.value):
                return
            reason = _blocking_reason(mod, facts, node)
            if reason is None and isinstance(node.func, ast.Name) and \
                    node.func.id in mod.functions:
                inner = _fn_blocking(mod, facts,
                                     mod.functions[node.func.id][-1],
                                     memo, 1)
                if inner:
                    reason = f"{node.func.id}() -> {inner}"
            if reason:
                lock, line = held[-1]
                findings.append(mod.finding(
                    self.id, node,
                    f"{lock} (held since line {line}) is held across a "
                    f"blocking call: {reason} — blocking under a lock "
                    "stalls every other thread at best and deadlocks "
                    "under backpressure at worst", hint=self.hint))

        def scan_expr(expr):
            for node in ast.walk(expr):
                if isinstance(node, ast.Call):
                    check_call(node)
                    # acquire()/release() toggle the held set even when
                    # embedded in a larger statement
                    if isinstance(node.func, ast.Attribute) and \
                            facts.is_lock(node.func.value):
                        key = _expr_key(node.func.value) or "<lock>"
                        if node.func.attr == "acquire":
                            held.append((key, node.lineno))
                        elif node.func.attr == "release":
                            for i in range(len(held) - 1, -1, -1):
                                if held[i][0] == key:
                                    del held[i]
                                    break
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef, ast.Lambda)):
                    return    # nested callables checked on their own

        def exec_stmt(stmt):
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                return
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                lock_items = []
                for item in stmt.items:
                    scan_expr(item.context_expr)
                    if isinstance(item.context_expr, ast.Call):
                        continue      # ``with pool:`` etc
                    if facts.is_lock(item.context_expr):
                        key = _expr_key(item.context_expr) or "<lock>"
                        held.append((key, stmt.lineno))
                        lock_items.append(key)
                exec_block(stmt.body)
                for _ in lock_items:
                    held.pop()
                return
            for node in ast.iter_child_nodes(stmt):
                if isinstance(node, ast.expr):
                    scan_expr(node)
            for attr in ("body", "orelse", "finalbody"):
                for sub in getattr(stmt, attr, []) or []:
                    exec_stmt(sub)
            for h in getattr(stmt, "handlers", []) or []:
                exec_block(h.body)

        def exec_block(stmts):
            for s in stmts:
                exec_stmt(s)

        exec_block(fn.body)
