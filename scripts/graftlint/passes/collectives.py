"""collective-consistency — collectives inside manual regions stay
well-formed.

Three sub-checks, each a bug class this repo has actually shipped a fix
for (this jax/jaxlib 0.4.37 — see the compat shims in
``parallel/collectives.py``):

1. **Unbound axis** — a collective inside a ``shard_map`` body naming a
   mesh axis the mapping doesn't bind hard-aborts at lowering with an
   unhelpful message.  Checked when the mesh's axis names resolve
   statically (a ``Mesh(..., ("a", "b"))`` literal or an
   ``axis_names=`` kwarg); axis sets that live in runtime config are
   skipped, not guessed.
2. **top_k inside a manual-subgroup region** — ``lax.top_k`` inside a
   ``shard_map`` body that leaves other axes to GSPMD ``auto`` aborts
   XLA's partitioner (the PR 3 WideDeep finding; its fix runs the
   compressed reduction fully manual in a second shard_map).
3. **Branch collective divergence** — ``lax.cond`` / ``lax.switch``
   branches whose collective *sets* differ are only legal when every
   participant provably takes the same branch, i.e. the branch index
   derives from a ``psum``-family reduction (the rule PR 6's adaptive
   rung ladder depends on — all participants psum the same norms, so
   all switch together).  A divergent-branch switch on an unproven
   index is flagged; branch lists built by factories
   (``[make(spec) for spec in ladder]``) resolve through the factory's
   inner defs.

Follow-by-reference: branch bodies and shard_map bodies are walked
transitively through bare-name calls, across modules when the callee
resolves through a from-import into the repo (the
``sgd -> grad_reduce`` shape).

Sub-check 1 also resolves axes THROUGH helper calls: a body that hands
a literal axis to a round-loop helper (``_rd_round(x, "dcn")`` whose
``lax.ppermute`` perm list is built from ``axis_size(axis)`` — the
recursive-doubling wire protocol's shape) is checked at the call site
by computing which of the callee's parameters flow into collective
axis arguments (:meth:`_Resolver.axis_params`, transitive).  The repo
wrappers whose axis is not the lax API's second positional
(``sparse_all_reduce(_rd)``, ``quantized_all_reduce``) carry their
positions in ``_AXIS_ARG_POS``.
"""

from __future__ import annotations

import ast

from typing import Dict, List, Optional, Set, Tuple

from ..core import ModuleInfo, Project
from .base import LintPass

#: collective primitives by trailing name (jax.lax.* or the repo's
#: ``parallel.collectives`` wrappers)
_COLLECTIVES = {
    "psum", "pmean", "pmax", "pmin", "psum_scatter", "all_gather",
    "all_to_all", "ppermute", "ppermute_ring", "reduce_scatter",
    "sparse_all_reduce", "sparse_all_reduce_rd", "quantized_all_reduce",
    "fixed_point_all_reduce", "axis_index", "axis_size", "pbroadcast",
}

#: positional index of the axis argument when it is not the lax-API
#: default of 1 — the repo's sparse wrappers put the segment length
#: before the axis
_AXIS_ARG_POS = {
    "sparse_all_reduce": 3,
    "sparse_all_reduce_rd": 3,
    "quantized_all_reduce": 2,
}

#: reductions whose result is identical on every participant — deriving
#: a branch index from one keeps collective control flow converged
_UNIFORM_REDUCTIONS = {"psum", "pmean", "pmax", "pmin", "axis_size"}

_SHARD_MAP_NAMES = {"shard_map", "shard_map_fn"}

_MAX_DEPTH = 5


def _is_collective_call(mod: ModuleInfo, call: ast.Call) -> Optional[str]:
    f = call.func
    name = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else None)
    if name not in _COLLECTIVES:
        return None
    qual = mod.call_qualname(call) or name
    # accept jax.lax.*, bare from-imports, and the repo's wrappers; any
    # other receiver spelling still trails with the collective name,
    # which is unambiguous enough in this codebase
    return name if (qual.endswith(name)) else None


def _axis_strings(expr) -> Optional[Set[str]]:
    """Literal axis names in an axis_name argument, or None if runtime."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return {expr.value}
    if isinstance(expr, (ast.Tuple, ast.List)):
        out: Set[str] = set()
        for el in expr.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, str):
                out.add(el.value)
            else:
                return None
        return out
    return None


def _axis_arg(call: ast.Call):
    """The axis_name argument of a collective call (second positional in
    the lax API — :data:`_AXIS_ARG_POS` overrides for the repo wrappers
    whose axis rides later — or the kwarg)."""
    for kw in call.keywords:
        if kw.arg in ("axis_name", "axis"):
            return kw.value
    f = call.func
    name = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else None)
    pos = _AXIS_ARG_POS.get(name, 1)
    if len(call.args) > pos:
        return call.args[pos]
    return None


def _bind_args(fn, call: ast.Call):
    """(param_name, caller_expr) pairs of a call against a resolved
    callee's positional signature (keywords included; *args and
    defaults-by-omission simply don't pair, which is the safe no-check
    direction)."""
    names = [a.arg for a in fn.args.args]
    out = []
    for i, a in enumerate(call.args):
        if i < len(names):
            out.append((names[i], a))
    for kw in call.keywords:
        if kw.arg:
            out.append((kw.arg, kw.value))
    return out


class _Resolver:
    """Transitive function-body walker with cross-module following."""

    def __init__(self, project: Project):
        self.project = project
        self._memo: Dict[Tuple[str, str], Set[str]] = {}
        self._axis_memo: Dict[Tuple[str, str], Set[str]] = {}

    def axis_params(self, mod: ModuleInfo, fn, depth: int = 0) -> Set[str]:
        """Parameter names of ``fn`` that flow into a collective's axis
        argument — directly (``lax.ppermute(x, axis, perm)`` inside a
        round-loop helper whose ``axis`` is a parameter), or through a
        further resolved callee's axis params.  This is what lets
        :meth:`CollectiveConsistencyPass._check_axis_binding` resolve a
        LITERAL axis at the call site of a helper (the recursive-
        doubling round loops) instead of only at the collective itself
        (memoized, cycle-safe, depth-capped)."""
        key = (mod.path, f"{fn.name}:{fn.lineno}")
        if key in self._axis_memo:
            return self._axis_memo[key]
        self._axis_memo[key] = set()     # cycle guard
        try:
            arg_names = {a.arg for a in fn.args.args}
        except AttributeError:
            arg_names = set()
        params: Set[str] = set()
        for node in ast.walk(getattr(fn, "_node", fn)):
            if not isinstance(node, ast.Call):
                continue
            if _is_collective_call(mod, node):
                ax = _axis_arg(node)
                if isinstance(ax, ast.Name) and ax.id in arg_names:
                    params.add(ax.id)
            elif depth < _MAX_DEPTH:
                resolved = self.resolve_callee(mod, node)
                if resolved is None:
                    continue
                inner = self.axis_params(resolved[0], resolved[1],
                                         depth + 1)
                if not inner:
                    continue
                for pname, expr in _bind_args(resolved[1], node):
                    if pname in inner and isinstance(expr, ast.Name) \
                            and expr.id in arg_names:
                        params.add(expr.id)
        self._axis_memo[key] = params
        return params

    def resolve_callee(self, mod: ModuleInfo, call: ast.Call,
                       ) -> Optional[Tuple[ModuleInfo, ast.AST]]:
        """(module, FunctionDef) of a called name — local def, from-import
        into the repo, or ``pkgmod.fn`` attribute into the repo."""
        f = call.func
        if isinstance(f, ast.Name):
            return self.project.resolve_function(mod, f.id)
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
            base = mod.aliases.get(f.value.id)
            if base:
                target = self.project.resolve_module(base)
                if target is not None and f.attr in target.functions:
                    return target, target.functions[f.attr][-1]
        return None

    def collectives_of(self, mod: ModuleInfo, fn, depth: int = 0,
                       ) -> Set[str]:
        """Trailing names of every collective called from ``fn``,
        transitively (memoized, cycle-safe, depth-capped)."""
        key = (mod.path, f"{fn.name}:{fn.lineno}")
        if key in self._memo:
            return self._memo[key]
        self._memo[key] = set()       # cycle guard
        out: Set[str] = set()
        for node in ast.walk(getattr(fn, "_node", fn)):
            if not isinstance(node, ast.Call):
                continue
            name = _is_collective_call(mod, node)
            if name:
                out.add(name)
            elif depth < _MAX_DEPTH:
                resolved = self.resolve_callee(mod, node)
                if resolved is not None:
                    out |= self.collectives_of(resolved[0], resolved[1],
                                               depth + 1)
        self._memo[key] = out
        return out

    def find_call(self, mod: ModuleInfo, fn, trailing: str,
                  depth: int = 0, _seen=None) -> Optional[Tuple]:
        """First call whose trailing name is ``trailing`` reachable from
        ``fn`` — returns (module, node) for the finding location."""
        _seen = _seen if _seen is not None else set()
        key = (mod.path, f"{fn.name}:{fn.lineno}")
        if key in _seen:
            return None
        _seen.add(key)
        for node in ast.walk(getattr(fn, "_node", fn)):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            name = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None)
            if name == trailing:
                return mod, node
            if depth < _MAX_DEPTH:
                resolved = self.resolve_callee(mod, node)
                if resolved is not None:
                    hit = self.find_call(resolved[0], resolved[1],
                                         trailing, depth + 1, _seen)
                    if hit is not None:
                        return hit
        return None


def _mesh_axes(mod: ModuleInfo, call: ast.Call) -> Optional[Set[str]]:
    """Statically-known axis universe of a shard_map call: an
    ``axis_names=`` kwarg, or a ``mesh=`` name assigned from a literal
    ``Mesh(...)`` in the same module."""
    mesh_expr = None
    for kw in call.keywords:
        if kw.arg == "axis_names":
            return _axis_strings(kw.value)
        if kw.arg == "mesh":
            mesh_expr = kw.value
    if mesh_expr is None and len(call.args) >= 2:
        mesh_expr = call.args[1]
    if not isinstance(mesh_expr, ast.Name):
        return None
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id == mesh_expr.id and \
                isinstance(node.value, ast.Call) and \
                (mod.call_qualname(node.value) or "").endswith("Mesh"):
            ctor = node.value
            for kw in ctor.keywords:
                if kw.arg == "axis_names":
                    return _axis_strings(kw.value)
            if len(ctor.args) >= 2:
                return _axis_strings(ctor.args[1])
    return None


def _body_fn(mod: ModuleInfo, call: ast.Call):
    """The body function of a shard_map(_fn) call, resolved locally."""
    if not call.args:
        return None
    arg = call.args[0]
    if isinstance(arg, ast.Name) and arg.id in mod.functions:
        return mod.functions[arg.id][-1]
    if isinstance(arg, ast.Lambda):
        return None          # lambdas: no def to walk transitively
    return None


def _decorated_bodies(mod: ModuleInfo):
    """(shard_map_call, body_fn) pairs from the
    ``@partial(shard_map_fn, ...)`` decorator form."""
    for fns in mod.functions.values():
        for fn in fns:
            for dec in fn.decorator_list:
                if isinstance(dec, ast.Call) and dec.args and \
                        getattr(dec.args[0], "id", None) in \
                        _SHARD_MAP_NAMES:
                    yield dec, fn


class CollectiveConsistencyPass(LintPass):
    id = "collective-consistency"
    describes = ("collectives in shard_map bodies name bound axes; no "
                 "top_k under manual-subgroup (auto=) regions; cond/"
                 "switch branches keep matching collective sets unless "
                 "the index is psum-derived")
    roots = ("flink_ml_tpu", "scripts")
    hint = ""

    def check_module(self, mod: ModuleInfo,
                     project: Project) -> List:
        findings: List = []
        resolver = _Resolver(project)
        sites = []
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                f = node.func
                name = f.attr if isinstance(f, ast.Attribute) else (
                    f.id if isinstance(f, ast.Name) else None)
                if name in _SHARD_MAP_NAMES:
                    body = _body_fn(mod, node)
                    if body is not None:
                        sites.append((node, body))
        sites.extend(_decorated_bodies(mod))

        for call, body in sites:
            self._check_axis_binding(mod, resolver, call, body, findings)
            self._check_topk_in_auto(mod, resolver, call, body, findings)
        self._check_branches(mod, resolver, findings)
        # a switch inside a nested def is walked from BOTH the inner and
        # the enclosing function — report each site once
        seen, out = set(), []
        for f in findings:
            key = (f.line, f.message)
            if key not in seen:
                seen.add(key)
                out.append(f)
        return out

    # -- sub-check 1: unbound axis -------------------------------------------
    def _check_axis_binding(self, mod, resolver, call, body, findings):
        bound = _mesh_axes(mod, call)
        if bound is None:
            return                    # runtime mesh: skip, don't guess
        for node in ast.walk(body):
            if not isinstance(node, ast.Call):
                continue
            if _is_collective_call(mod, node) is not None:
                self._flag_unbound(mod, call, node,
                                   _axis_strings(_axis_arg(node)),
                                   bound, findings)
                continue
            # helper call whose literal args feed a collective's axis
            # deeper down (the recursive-doubling round-loop shape:
            # body -> _rd_round(x, "dcn") -> lax.ppermute(x, axis, perm)
            # with the perm built from axis_size(axis)) — resolve the
            # callee's axis-bearing params and check the literals here.
            resolved = resolver.resolve_callee(mod, node)
            if resolved is None:
                continue
            inner = resolver.axis_params(resolved[0], resolved[1])
            if not inner:
                continue
            for pname, expr in _bind_args(resolved[1], node):
                if pname in inner:
                    self._flag_unbound(mod, call, node,
                                       _axis_strings(expr), bound,
                                       findings)

    def _flag_unbound(self, mod, call, node, axes, bound, findings):
        if axes is None:
            return
        for ax in sorted(axes - bound):
            findings.append(mod.finding(
                self.id, node,
                f"collective names axis {ax!r} but the enclosing "
                f"shard_map (line {call.lineno}) only binds "
                f"{sorted(bound)} — this aborts at lowering",
                hint="bind the axis in the mesh/specs or reduce "
                     "over a bound axis"))

    # -- sub-check 2: top_k under auto ---------------------------------------
    def _check_topk_in_auto(self, mod, resolver, call, body, findings):
        has_auto = any(kw.arg == "auto" and not (
            isinstance(kw.value, (ast.Tuple, ast.List, ast.Set))
            and not kw.value.elts) for kw in call.keywords)
        if not has_auto:
            return
        hit = resolver.find_call(mod, body, "top_k")
        if hit is not None:
            hit_mod, node = hit
            where = f" (reached via {hit_mod.rel}:{node.lineno})" \
                if hit_mod is not mod else ""
            findings.append(mod.finding(
                self.id, call,
                "lax.top_k is reachable inside a shard_map body that "
                "leaves axes to GSPMD auto partitioning — this XLA "
                "aborts on top_k in manual-subgroup regions (the PR 3 "
                f"WideDeep finding){where}",
                hint="run the top_k-bearing reduction in a second, "
                     "fully-manual shard_map (widedeep._build_reduced_"
                     "sharded_step is the worked example)"))

    # -- sub-check 3: branch divergence --------------------------------------
    def _check_branches(self, mod, resolver, findings):
        for fns in mod.functions.values():
            for fn in fns:
                tainted = self._psum_tainted_names(mod, fn)
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    f = node.func
                    name = f.attr if isinstance(f, ast.Attribute) else (
                        f.id if isinstance(f, ast.Name) else None)
                    if name == "switch" and len(node.args) >= 2:
                        branches = self._resolve_branch_list(
                            mod, fn, node.args[1])
                        index = node.args[0]
                    elif name == "cond" and len(node.args) >= 3:
                        branches = [self._branch_body(mod, a)
                                    for a in node.args[1:3]]
                        index = node.args[0]
                    else:
                        continue
                    if not branches or any(b is None for b in branches) \
                            or len(branches) < 2:
                        continue
                    sets = [frozenset(resolver.collectives_of(m, b))
                            for (m, b) in branches]
                    if len(set(sets)) <= 1:
                        continue
                    if self._index_is_uniform(mod, index, tainted):
                        continue
                    diff = sorted(set.union(*map(set, sets))
                                  - set.intersection(*map(set, sets)))
                    findings.append(mod.finding(
                        self.id, node,
                        f"lax.{name} branches have different collective "
                        f"sets (differing: {diff}) and the branch index "
                        "is not provably psum-derived — participants can "
                        "branch apart and the collectives deadlock/abort",
                        hint="derive the index from a psum/pmean/pmax of "
                             "participant-local values (grad_reduce's "
                             "adaptive rung ladder is the worked "
                             "example), or give every branch the same "
                             "collective set"))
        return findings

    def _branch_body(self, mod, expr):
        """(module, fn) for one branch expression, or None."""
        if isinstance(expr, ast.Lambda):
            return (mod, _LambdaFn(expr))
        if isinstance(expr, ast.Name) and expr.id in mod.functions:
            return (mod, mod.functions[expr.id][-1])
        return None

    def _resolve_branch_list(self, mod, fn, expr):
        """Branch bodies of a lax.switch branches argument: a literal
        list/tuple, a name assigned one, or a name assigned a
        comprehension over a factory call (grad_reduce's
        ``[_segment_reducer(spec, cfg) for spec in ladder]``) — the
        factory's inner defs are the branch universe."""
        if isinstance(expr, ast.Name):
            assigned = None
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) and \
                        len(node.targets) == 1 and \
                        isinstance(node.targets[0], ast.Name) and \
                        node.targets[0].id == expr.id:
                    assigned = node.value
            expr = assigned
        if expr is None:
            return None
        if isinstance(expr, (ast.List, ast.Tuple)):
            return [self._branch_body(mod, el) for el in expr.elts]
        if isinstance(expr, ast.ListComp) and \
                isinstance(expr.elt, ast.Call) and \
                isinstance(expr.elt.func, ast.Name) and \
                expr.elt.func.id in mod.functions:
            factory = mod.functions[expr.elt.func.id][-1]
            inner = [n for n in ast.walk(factory)
                     if isinstance(n, (ast.FunctionDef,
                                       ast.AsyncFunctionDef))
                     and n is not factory]
            if len(inner) >= 2:
                return [(mod, f) for f in inner]
        return None

    def _psum_tainted_names(self, mod, fn) -> Set[str]:
        """Names in ``fn`` whose value derives from a uniform reduction
        (psum/pmean/pmax/pmin) — two propagation rounds."""
        tainted: Set[str] = set()

        def expr_tainted(expr) -> bool:
            for node in ast.walk(expr):
                if isinstance(node, ast.Call):
                    f = node.func
                    nm = f.attr if isinstance(f, ast.Attribute) else (
                        f.id if isinstance(f, ast.Name) else None)
                    if nm in _UNIFORM_REDUCTIONS:
                        return True
                if isinstance(node, ast.Name) and \
                        isinstance(node.ctx, ast.Load) and \
                        node.id in tainted:
                    return True
            return False

        for _ in range(2):
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) and \
                        expr_tainted(node.value):
                    for t in node.targets:
                        for sub in ast.walk(t):
                            if isinstance(sub, ast.Name):
                                tainted.add(sub.id)
        return tainted

    def _index_is_uniform(self, mod, index, tainted: Set[str]) -> bool:
        for node in ast.walk(index):
            if isinstance(node, ast.Call):
                f = node.func
                nm = f.attr if isinstance(f, ast.Attribute) else (
                    f.id if isinstance(f, ast.Name) else None)
                if nm in _UNIFORM_REDUCTIONS:
                    return True
            if isinstance(node, ast.Name) and node.id in tainted:
                return True
        return False


class _LambdaFn:
    """Adapter so a Lambda walks like a FunctionDef in the resolver."""

    def __init__(self, node: ast.Lambda):
        self.name = f"<lambda:{node.lineno}>"
        self.lineno = node.lineno
        self.body = node.body
        self._node = node

    def __getattr__(self, item):
        return getattr(self._node, item)
