"""bench-schema — bench.py <-> BENCH_SCHEMA.md drift (non-AST pass).

Delegates to ``scripts/check_bench_schema.py`` (still the canonical
implementation — its logic is regex-over-docs, not AST, and
``tests/test_bench_schema.py`` exercises it directly); this pass folds
it into the single ``python -m scripts.graftlint`` entry point so CI
and humans run ONE command.  Each drift line becomes a Finding;
``baseline_exempt`` keeps the runner from ever grandfathering one —
schema drift is fixed, not accepted.
"""

from __future__ import annotations

import importlib.util
import os

from typing import List, Optional, Sequence

from ..core import Finding, Project
from .base import LintPass

_SCRIPT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "check_bench_schema.py")


def _load_checker():
    spec = importlib.util.spec_from_file_location("check_bench_schema",
                                                  _SCRIPT)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


class BenchSchemaPass(LintPass):
    id = "bench-schema"
    describes = ("bench.py metric-version literals match BENCH_SCHEMA.md "
                 "and every emitted BENCH_*.json key is documented")
    roots = ()
    baseline_exempt = True
    hint = ("bump bench.py and BENCH_SCHEMA.md together; document new "
            "keys in the schema doc (scripts/check_bench_schema.py "
            "--help for details)")

    def run(self, project: Project,
            paths: Optional[Sequence[str]] = None) -> List[Finding]:
        if paths:
            return []        # an explicit AST-path narrowing is active
        checker = _load_checker()
        problems = checker.check_versions()
        import glob

        documented = checker.schema_documented_keys(
            open(checker.SCHEMA).read())
        for path in sorted(glob.glob(os.path.join(project.repo,
                                                  "BENCH_*.json"))):
            problems += checker.check_json(path, documented)
        return [Finding(pass_id=self.id, path="bench.py", line=0,
                        message=p, symbol="<schema>", hint=self.hint)
                for p in problems]
