"""Pass protocol: a pass declares an ``id``, the repo-relative ``roots``
it scans, and implements ``check_module``.  Whole-repo (non-AST) passes
override ``run`` instead."""

from __future__ import annotations

import os

from typing import List, Optional, Sequence

from ..core import Finding, ModuleInfo, Project


class LintPass:
    #: the name suppressions and the baseline refer to
    id: str = ""
    #: one-line description for --list-passes / the pass catalog doc
    describes: str = ""
    #: repo-relative directories/files scanned by default
    roots: Sequence[str] = ()
    #: True = findings can never be grandfathered via the baseline file
    baseline_exempt: bool = False
    #: True = ``roots`` define WHERE THE CONVENTION APPLIES (the
    #: durable layer, the step trees) and explicit paths can only
    #: narrow them; False = ``roots`` are just the default scan surface
    #: and an explicit path substitutes for them (lint any tree)
    scope_fixed: bool = False

    def run(self, project: Project,
            paths: Optional[Sequence[str]] = None) -> List[Finding]:
        """Findings over the pass's roots, optionally narrowed by
        explicit ``paths``.

        For a ``scope_fixed`` pass an in-repo path RESTRICTS the pass to
        the intersection of the path and the pass's own roots —
        ``graftlint flink_ml_tpu`` must not run the durable-layer-only
        atomic-writes rule over the whole package.  Generic passes scan
        whatever tree they are pointed at; a path OUTSIDE the repo is
        always scanned as given — the point-the-tool-at-a-fixture
        behavior the legacy checkers had."""
        findings: List[Finding] = []
        for mod in project.iter_modules(
                self._scoped(project, paths) if paths else self.roots):
            findings += self.check_module(mod, project)
        return findings

    def _scoped(self, project: Project,
                paths: Sequence[str]) -> List[str]:
        def _norm(p: str) -> str:
            return os.path.abspath(p if os.path.isabs(p)
                                   else os.path.join(project.repo, p))

        def _under(child: str, parent: str) -> bool:
            return child == parent or \
                child.startswith(parent.rstrip(os.sep) + os.sep)

        repo = os.path.abspath(project.repo)
        # roots absent from THIS project (a fixture repo, typically)
        # cannot scope anything — explicit paths then scan as given
        abs_roots = [r for r in (_norm(r) for r in self.roots)
                     if os.path.exists(r)]
        scoped: List[str] = []
        for p in paths:
            ap = _norm(p)
            if not _under(ap, repo) or not abs_roots \
                    or not self.scope_fixed:
                scoped.append(ap)               # scan as given
                continue
            for root in abs_roots:
                if _under(ap, root):
                    scoped.append(ap)           # path narrows the root
                    break
                if _under(root, ap):
                    scoped.append(root)         # path contains the root
        return list(dict.fromkeys(scoped))

    def check_module(self, mod: ModuleInfo,
                     project: Project) -> List[Finding]:
        raise NotImplementedError
