"""unfenced-timing — wall-clock timing of jitted work needs a device
fence (ISSUE 13 satellite).

JAX dispatch is asynchronous: ``t0 = perf_counter(); jitted(...);
perf_counter() - t0`` measures the *enqueue*, not the work — and on the
axon tunnel even ``block_until_ready`` does not reliably block, so the
repo's one honest idiom is a ``device_get`` of a probe value between
the jitted call and the clock read (``utils/profiler.StepTimer`` /
``fenced_call``).  bench.py hand-rolled that idiom in half a dozen
places before ISSUE 13 consolidated them onto ``fenced_call``; this
pass keeps the hand-rolled-without-the-fence form from coming back.

Detection (per function, events in source order):

- **start** — ``t = time.perf_counter()`` (the bare assignment form);
- **jitted call** — a call of a name bound to ``jax.jit(...)`` in this
  module (assignment or decorator, ``partial(jax.jit, ...)``
  included), or a direct ``jax.jit(...)(...)`` invocation;
- **fence** — ``np.asarray`` / ``jax.device_get`` /
  ``.block_until_ready()`` / ``.item()`` / ``StepTimer.stop`` /
  ``fenced_call`` (which fences internally);
- **read** — any other ``time.perf_counter()`` call (the
  ``perf_counter() - t0`` form).

A read while a start is armed and the latest jitted call since then
has no fence after it is a finding.  Heuristic by design (the
host-sync stance): timing code in this repo is straight-line
start/call/fence/read, so positional order is the control flow that
matters.  Scope-fixed to the trees that TIME device work as their
product — ``bench.py`` and ``flink_ml_tpu/obs`` — where an unfenced
number would be published as a measurement.
"""

from __future__ import annotations

import ast

from typing import List, Optional, Set

from ..core import ModuleInfo, Project
from .base import LintPass

_PARTIAL = {"functools.partial", "partial"}

#: call qualnames / attribute names that fence the dispatch stream
_FENCE_QUALS = {"numpy.asarray", "jax.device_get", "device_get",
                "fenced_call", "flink_ml_tpu.utils.profiler.fenced_call"}
_FENCE_ATTRS = {"block_until_ready", "item", "stop", "fetch"}

_PERF_QUALS = {"time.perf_counter", "perf_counter"}


def _is_jit_expr(mod: ModuleInfo, node) -> bool:
    """``jax.jit(...)`` or ``partial(jax.jit, ...)``."""
    if not isinstance(node, ast.Call):
        return False
    qual = mod.call_qualname(node)
    if qual in ("jax.jit", "jit"):
        return True
    if qual in _PARTIAL and node.args:
        inner = mod.qualname(node.args[0])
        return inner in ("jax.jit", "jit")
    return False


def _jitted_names(mod: ModuleInfo) -> Set[str]:
    """Names bound to jitted callables anywhere in the module:
    ``x = jax.jit(...)`` (conditional arms included) and defs decorated
    ``@jax.jit`` / ``@partial(jax.jit, ...)``."""
    out: Set[str] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            value = node.value
            cands = ([value.body, value.orelse]
                     if isinstance(value, ast.IfExp) else [value])
            if any(_is_jit_expr(mod, c) for c in cands):
                out.add(node.targets[0].id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if _is_jit_expr(mod, dec) or mod.qualname(dec) in (
                        "jax.jit", "jit"):
                    out.add(node.name)
    return out


def _own_nodes(fn: ast.AST):
    """The nodes of ``fn``'s OWN body, nested def subtrees pruned — a
    nested helper's timing bracket is its own scope (it would otherwise
    be reported twice, and a jitted call inside a never-called nested
    def would poison the enclosing function's bracket).  Lambdas stay:
    ``jax.jit(lambda ...)(x)`` executes inline."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _event(mod: ModuleInfo, node: ast.AST, jitted: Set[str]
           ) -> Optional[str]:
    if not isinstance(node, ast.Call):
        return None
    qual = mod.call_qualname(node)
    if qual in _PERF_QUALS:
        parent = mod.parent(node)
        if isinstance(parent, ast.Assign) and parent.value is node:
            return "start"
        return "read"
    if qual in _FENCE_QUALS:
        return "fence"
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr in _FENCE_ATTRS:
        return "fence"
    if isinstance(f, ast.Name) and f.id in jitted:
        return "jit"
    if _is_jit_expr(mod, f):        # direct jax.jit(fn)(args)
        return "jit"
    return None


class UnfencedTimingPass(LintPass):
    id = "unfenced-timing"
    describes = ("perf_counter timing that brackets a jitted call needs "
                 "a device fence (device_get/np.asarray/fenced_call) "
                 "before the clock is read")
    roots = ("bench.py", "flink_ml_tpu/obs")
    scope_fixed = True      # the convention applies to the timing trees
    hint = ("route the timing through utils/profiler.fenced_call (or "
            "fetch a probe of the result with np.asarray/jax.device_get "
            "before reading the clock)")

    def check_module(self, mod: ModuleInfo,
                     project: Project) -> List:
        jitted = _jitted_names(mod)
        findings = []
        for fn in ast.walk(mod.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            events = []
            for node in _own_nodes(fn):
                kind = _event(mod, node, jitted)
                if kind is not None:
                    events.append((node.lineno, node.col_offset,
                                   kind, node))
            events.sort(key=lambda e: (e[0], e[1]))
            armed = False
            unfenced_jit = False
            for _, _, kind, node in events:
                if kind == "start":
                    armed, unfenced_jit = True, False
                elif kind == "jit":
                    if armed:
                        unfenced_jit = True
                elif kind == "fence":
                    unfenced_jit = False
                elif kind == "read" and armed and unfenced_jit:
                    findings.append(mod.finding(
                        self.id, node,
                        "perf_counter read after a jitted call with no "
                        "device fence in between — this times the "
                        "dispatch enqueue, not the device work",
                        hint=self.hint))
                    unfenced_jit = False   # report once per interval
        return findings
