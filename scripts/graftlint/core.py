"""Shared analysis infrastructure every graftlint pass builds on.

One parse per module (cached on :class:`Project`), with:

- **Qualified-name resolution** — ``ModuleInfo.qualname`` resolves an
  expression through ``import x as y`` / ``from a.b import c as d``
  aliases AND module-level local rebinding (``sleep2 = time.sleep``), so
  a pass matches ``jax.lax.psum`` however the module spells it.
- **Suppressions** — a ``# graftlint: disable=<pass>[,<pass>]`` comment
  on the flagged line drops that line's findings for those passes; the
  runner enforces that every suppression is *exercised* (an unused one is
  itself a finding — a suppression that guards nothing rots silently).
- **Function index** — every ``def`` in the module (nested included) by
  name, for the follow-functions-passed-by-reference analyses.
- **The shared walker** — :func:`iter_py_files` with one exclusion set
  (``__pycache__`` et al.) instead of each checker re-implementing
  directory filtering.
"""

from __future__ import annotations

import ast
import os
import re

from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence, Set

#: directories the shared walker never descends into (generated or
#: vendored artifacts — each must be .gitignore'd, see test_graftlint)
EXCLUDE_DIRS = {"__pycache__", ".git", ".pytest_cache", ".mypy_cache",
                ".ruff_cache", ".ipynb_checkpoints", ".venv", "node_modules",
                "build", "dist"}

#: ids are a comma-separated list right after ``disable=``; anything
#: after the list (a justification) is free text, not part of the ids
_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable=([\w-]+(?:\s*,\s*[\w-]+)*)")


def iter_py_files(roots: Sequence[str]) -> Iterator[str]:
    """Every ``.py`` under ``roots`` (files pass through verbatim), in
    sorted order, skipping :data:`EXCLUDE_DIRS` — THE directory-filter
    shared by all passes and both legacy checker shims."""
    for root in roots:
        if os.path.isfile(root):
            yield root
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in EXCLUDE_DIRS
                                 and not d.endswith(".egg-info"))
            for f in sorted(filenames):
                if f.endswith(".py"):
                    yield os.path.join(dirpath, f)


@dataclass
class Finding:
    """One diagnostic: ``file:line``, the pass that raised it, the claim,
    and a fix hint.  ``symbol`` (the enclosing function) keys the
    baseline — line numbers drift with every edit, symbols rarely do."""

    pass_id: str
    path: str            # repo-relative
    line: int
    message: str
    symbol: str = ""     # enclosing function ("outer.inner" when nested)
    hint: str = ""

    @property
    def fingerprint(self) -> str:
        return f"{self.pass_id} {self.path}::{self.symbol or '<module>'}"

    def render(self) -> str:
        text = f"{self.path}:{self.line}: [{self.pass_id}] {self.message}"
        if self.hint:
            text += f"\n    fix: {self.hint}"
        return text

    def as_dict(self) -> dict:
        return {"pass": self.pass_id, "path": self.path, "line": self.line,
                "symbol": self.symbol, "message": self.message,
                "hint": self.hint}


class ModuleInfo:
    """One parsed module + the resolution tables passes share."""

    def __init__(self, path: str, repo: str):
        self.path = os.path.abspath(path)
        try:
            self.rel = os.path.relpath(self.path, repo)
        except ValueError:          # different drive (windows) — keep abs
            self.rel = self.path
        with open(self.path) as f:
            self.src = f.read()
        self.tree = ast.parse(self.src, filename=self.path)
        self.lines = self.src.splitlines()
        #: dotted package of this module ("flink_ml_tpu.data.prefetch")
        #: for resolving relative imports; "" when outside the repo
        self.package = ""
        if not self.rel.startswith(("..", os.sep)):
            self.package = self.rel[:-3].replace(os.sep, ".") \
                if self.rel.endswith(".py") else ""
        self.aliases: Dict[str, str] = {}
        self.functions: Dict[str, List[ast.AST]] = {}
        self._parents: Dict[ast.AST, ast.AST] = {}
        self._index()
        #: line -> set of pass ids disabled on that line.  Parsed from
        #: COMMENT tokens only — a docstring QUOTING the syntax is
        #: documentation, not a suppression
        self.suppressions: Dict[int, Set[str]] = {}
        for line_no, comment in self._comments():
            m = _SUPPRESS_RE.search(comment)
            if m:
                self.suppressions.setdefault(line_no, set()).update(
                    p.strip() for p in m.group(1).split(",") if p.strip())

    def _comments(self):
        import io
        import tokenize

        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(self.src).readline):
                if tok.type == tokenize.COMMENT:
                    yield tok.start[0], tok.string
        except (tokenize.TokenError, IndentationError):
            return

    # -- indexing -----------------------------------------------------------
    def _index(self) -> None:
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self._parents[child] = node
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                self._record_import(node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions.setdefault(node.name, []).append(node)
            elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, (ast.Name, ast.Attribute)) \
                    and self._parents.get(node) is self.tree:
                # module-level rebinding: ``sleep2 = time.sleep``
                dotted = self._dotted(node.value)
                if dotted:
                    self.aliases[node.targets[0].id] = \
                        self.aliases.get(dotted, dotted)

    def _record_import(self, node) -> None:
        if isinstance(node, ast.Import):
            for a in node.names:
                self.aliases[(a.asname or a.name).split(".")[0]] = \
                    a.name if a.asname else a.name.split(".")[0]
                if a.asname:
                    self.aliases[a.asname] = a.name
            return
        base = node.module or ""
        if node.level:                      # relative import
            parts = self.package.split(".") if self.package else []
            parts = parts[:len(parts) - node.level]
            base = ".".join(parts + ([node.module] if node.module else []))
        for a in node.names:
            if a.name == "*":
                continue
            self.aliases[a.asname or a.name] = \
                f"{base}.{a.name}" if base else a.name

    @staticmethod
    def _dotted(node) -> Optional[str]:
        parts = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if isinstance(node, ast.Name):
            parts.append(node.id)
            return ".".join(reversed(parts))
        return None

    # -- resolution ---------------------------------------------------------
    def qualname(self, node) -> Optional[str]:
        """Alias-resolved dotted name of an expression, or None when it
        is not a plain name/attribute chain.  ``np.asarray`` ->
        ``numpy.asarray``; ``lax.psum`` -> ``jax.lax.psum`` (given
        ``from jax import lax``)."""
        dotted = self._dotted(node)
        if dotted is None:
            return None
        root, _, rest = dotted.partition(".")
        resolved = self.aliases.get(root, root)
        return f"{resolved}.{rest}" if rest else resolved

    def call_qualname(self, call: ast.Call) -> Optional[str]:
        return self.qualname(call.func)

    def enclosing_function(self, node) -> str:
        """Dotted enclosing-def chain of ``node`` ("" at module level)."""
        chain: List[str] = []
        cur = self._parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                chain.append(cur.name)
            cur = self._parents.get(cur)
        return ".".join(reversed(chain))

    def parent(self, node) -> Optional[ast.AST]:
        return self._parents.get(node)

    def finding(self, pass_id: str, node, message: str,
                hint: str = "") -> Finding:
        return Finding(pass_id=pass_id, path=self.rel,
                       line=getattr(node, "lineno", 0), message=message,
                       symbol=self.enclosing_function(node), hint=hint)


@dataclass
class Project:
    """Module cache + repo layout shared across passes (each file parses
    once no matter how many passes read it)."""

    repo: str
    _cache: Dict[str, ModuleInfo] = field(default_factory=dict)
    #: modules any pass actually visited — the universe for
    #: unused-suppression enforcement
    scanned: Set[str] = field(default_factory=set)

    def module(self, path: str) -> ModuleInfo:
        path = os.path.abspath(path)
        if path not in self._cache:
            self._cache[path] = ModuleInfo(path, self.repo)
        return self._cache[path]

    def iter_modules(self, roots: Sequence[str]) -> Iterator[ModuleInfo]:
        """ModuleInfos under repo-relative ``roots``; remembers what was
        visited for suppression enforcement."""
        abs_roots = [r if os.path.isabs(r) else os.path.join(self.repo, r)
                     for r in roots]
        for path in iter_py_files(abs_roots):
            mod = self.module(path)
            self.scanned.add(mod.path)
            yield mod

    def resolve_module(self, dotted: str) -> Optional[ModuleInfo]:
        """The ModuleInfo for a dotted module path inside the repo
        (``flink_ml_tpu.parallel.grad_reduce``), or None."""
        rel = dotted.replace(".", os.sep)
        for cand in (rel + ".py", os.path.join(rel, "__init__.py")):
            path = os.path.join(self.repo, cand)
            if os.path.isfile(path):
                return self.module(path)
        return None

    def resolve_function(self, mod: ModuleInfo, name: str,
                         ) -> Optional[tuple]:
        """Resolve a bare callee name to ``(ModuleInfo, FunctionDef)`` —
        a def in ``mod`` itself, or followed through a from-import into
        another repo module (one hop; deeper chains resolve recursively
        at the caller's discretion)."""
        if name in mod.functions:
            return mod, mod.functions[name][-1]
        dotted = mod.aliases.get(name)
        if not dotted or "." not in dotted:
            return None
        mod_path, _, fn_name = dotted.rpartition(".")
        target = self.resolve_module(mod_path)
        if target is not None and fn_name in target.functions:
            return target, target.functions[fn_name][-1]
        # ``from ..parallel import grad_reduce`` + ``grad_reduce.foo``
        # resolves at the call site via qualname instead
        return None
